"""Real-mesh SPMD ablation: the shard_map backend vs the vmap simulator.

ROADMAP open item 1's acceptance capture (ISSUE 14): every headline
number used to run 8 "ranks" vmapped onto one chip, so the event
exchange was an XLA-scheduling claim, not real inter-device traffic.
This tool runs the SAME op-point the arena/bucketed ablations use
(LeNetCifar, Ring(8), synthetic CIFAR prototypes) on a real 8-device
mesh (`--xla_force_host_platform_device_count=8` on CPU — one rank per
device, `ppermute` as an actual collective) and commits
artifacts/mesh_ablation_<platform>.json (MESH_ABLATION_SCHEMA in
tools/validate_artifacts.py) with:

  * the REAL-COLLECTIVE EventGraD-vs-D-PSGD step ratio (median paired
    per-round over scanned steady-state runs — the bucketed-ablation
    protocol) on the shard_map backend, next to the vmap twin;
  * the mesh-vs-vmap cost of the SAME eventgrad step (what moving from
    the single-chip simulator to a real mesh costs at this op-point);
  * bitwise_state: the shard_map leg's final scanned TrainState ==
    the vmap leg's, leaf for leaf (the tests/test_mesh_parity.py
    contract re-proven at production geometry);
  * the mesh-program audit flags at production geometry:
    `audit_shard_lift` clean on the LeNetCifar and ResNet18 arena
    cells (only declared-offset ppermutes + axis_index, zero
    callbacks) and the seeded mesh oracle CAUGHT
    (analysis/audit.MESH_ORACLES);
  * a 64-rank scale leg (tests/mesh64_worker.py in a subprocess — the
    device count is fixed at client startup): per-neighbor wire bytes
    proven EXACTLY equal to `collectives.wire_real_bytes_per_neighbor`
    on all 64 ranks, plus its steady step_ms.

tools/perf_ledger.py ingests the mesh rows (backend="shard_map") into
the trajectory; the `backend` field in the comparability-group key
keeps them from ever gating against vmap rows.

Usage: python tools/mesh_ablation.py [n_rounds]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the mesh needs its devices before the first backend use
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from eventgrad_tpu.utils import compile_cache  # noqa: E402

compile_cache.honor_cpu_pin()

from eventgrad_tpu.analysis import audit  # noqa: E402
from eventgrad_tpu.data.datasets import load_or_synthesize  # noqa: E402
from eventgrad_tpu.data.sharding import batched_epoch  # noqa: E402
from eventgrad_tpu.models.cnn import LeNetCifar  # noqa: E402
from eventgrad_tpu.parallel.events import EventConfig  # noqa: E402
from eventgrad_tpu.parallel.spmd import (  # noqa: E402
    build_mesh, shard_map_available, spmd,
)
from eventgrad_tpu.parallel.topology import Ring  # noqa: E402
from eventgrad_tpu.train.state import init_train_state  # noqa: E402
from eventgrad_tpu.train.steps import make_train_step  # noqa: E402
from eventgrad_tpu.utils.metrics import median as _median  # noqa: E402

K_SCAN = 8


def _scale64_leg() -> dict:
    """Run the 64-rank worker in its own interpreter (the device count
    is fixed at client startup) and distill its record."""
    worker = os.path.join(REPO, "tests", "mesh64_worker.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, worker, "--timed"], env=env,
        capture_output=True, text=True, timeout=600,
    )
    if out.returncode != 0:
        raise RuntimeError(f"mesh64 worker failed: {out.stderr[-2000:]}")
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    per_nb = rec["per_neighbor_bytes_formula"]
    edge = np.asarray(rec["edge_bytes"])
    metric = np.asarray(rec["sent_bytes_wire_real"])
    wire_exact = bool(
        (edge == rec["steps"] * per_nb).all()
        and (metric == rec["n_neighbors"] * per_nb).all()
    )
    return {
        "n_ranks": rec["n_ranks"],
        "n_devices": rec["n_devices"],
        "model": "MLP",
        "wire_bytes_exact": wire_exact,
        "per_neighbor_bytes": per_nb,
        "exchange_offsets": rec["exchange_offsets"],
        "declared_offsets": rec["declared_offsets"],
        "offsets_ok": rec["exchange_offsets"] == rec["declared_offsets"],
        "step_ms": rec.get("step_ms"),
    }


def main(n_rounds: int = 12) -> int:
    if not shard_map_available():
        print("shard_map unavailable in this jax; nothing to ablate",
              file=sys.stderr)
        return 1
    if len(jax.devices()) < 8:
        print(f"need 8 devices, have {len(jax.devices())}",
              file=sys.stderr)
        return 1

    topo = Ring(8)
    model = LeNetCifar()
    lr, mom = 1e-2, 0.9
    tx = optax.sgd(lr, momentum=mom)
    per_rank = 8
    x, y = load_or_synthesize("cifar10", None, "train", n_synth=1024)
    xb, yb = batched_epoch(x, y, topo.n_ranks, per_rank)
    xs = jnp.asarray(np.stack(
        [xb[:, s % xb.shape[1]] for s in range(K_SCAN)], 0))
    ys = jnp.asarray(np.stack(
        [yb[:, s % yb.shape[1]] for s in range(K_SCAN)], 0))
    cfg = EventConfig(
        adaptive=True, horizon=1.05, warmup_passes=10, max_silence=50
    )
    mesh = build_mesh(topo)

    # one scanned program per (algo, backend); interleaved rounds with
    # median PAIRED per-round ratios — the arena/bucketed protocol
    variants = {}
    finals = {}
    for algo, c in (("dpsgd", None), ("eventgrad", cfg)):
        for backend in ("vmap", "shard_map"):
            state = init_train_state(
                model, x.shape[1:], tx, topo, algo, c, arena=True
            )
            lifted = spmd(
                make_train_step(
                    model, tx, topo, algo, event_cfg=c, arena=True,
                ),
                topo, mesh=mesh if backend == "shard_map" else None,
            )

            def run(s, xs, ys, _l=lifted):
                return jax.lax.scan(lambda s, b: _l(s, b), s, (xs, ys))

            run = jax.jit(run)
            t0 = time.perf_counter()
            out, _ = run(state, xs, ys)
            jax.block_until_ready(jax.tree.leaves(out.params)[0])
            compile_s = time.perf_counter() - t0
            variants[(algo, backend)] = (state, run, compile_s)
            finals[(algo, backend)] = out

    # bitwise: the scanned eventgrad final state must be IDENTICAL
    # across the lifts, every leaf of the TrainState
    bitwise = True
    for algo in ("dpsgd", "eventgrad"):
        lv = jax.tree.leaves(finals[(algo, "vmap")])
        ls = jax.tree.leaves(finals[(algo, "shard_map")])
        for a, b in zip(lv, ls):
            if not bool((np.asarray(a) == np.asarray(b)).all()):
                bitwise = False

    times = {k: [] for k in variants}
    for _ in range(n_rounds):
        for k, (state, run, _c) in variants.items():
            t0 = time.perf_counter()
            out, _ = run(state, xs, ys)
            jax.block_until_ready(jax.tree.leaves(out.params)[0])
            times[k].append((time.perf_counter() - t0) / K_SCAN * 1000)

    results = {}
    ratios = {}
    for backend in ("vmap", "shard_map"):
        leg = {}
        for algo in ("dpsgd", "eventgrad"):
            v = times[(algo, backend)]
            leg[algo] = {
                "compile_s": round(variants[(algo, backend)][2], 4),
                "step_ms_min": round(min(v), 4),
                "step_ms_p50": round(_median(v), 4),
            }
        paired = [
            e / d
            for e, d in zip(times[("eventgrad", backend)],
                            times[("dpsgd", backend)])
        ]
        leg["step_overhead_ratio"] = round(_median(paired), 4)
        ratios[backend] = leg["step_overhead_ratio"]
        results[backend] = leg
        print(json.dumps({backend: leg}), flush=True)
    mesh_cost = [
        s / v
        for s, v in zip(times[("eventgrad", "shard_map")],
                        times[("eventgrad", "vmap")])
    ]

    # mesh-program audit at production geometry + the seeded oracle
    lenet = audit.audit_shard_lift(
        audit.config_by_name("lenet_masked_f32_arena")
    )
    resnet = audit.audit_shard_lift(
        audit.config_by_name("resnet18_masked_f32_arena")
    )
    oracles = audit.run_mesh_oracles()

    rec = {
        "bench": "mesh_ablation",
        "platform": jax.devices()[0].platform,
        "n_devices": len(jax.devices()),
        "op_point": {
            "model": "LeNetCifar", "topology": "ring:8",
            "per_rank_batch": per_rank, "scan_steps": K_SCAN,
            "rounds": n_rounds, "momentum": mom, "arena": True,
            "data": "synthetic-prototype",
        },
        "results": results,
        # the headline: EventGraD vs D-PSGD with REAL collectives
        "step_overhead_ratio_mesh": ratios["shard_map"],
        "step_overhead_ratio_vmap": ratios["vmap"],
        # what the mesh costs over the simulator for the same step
        "mesh_vs_vmap_ratio": round(_median(mesh_cost), 4),
        "bitwise_state": bitwise,
        "audit": {
            "lenet_clean": audit.shard_lift_clean(lenet),
            "resnet18_clean": audit.shard_lift_clean(resnet),
            "lenet_offsets": lenet["exchange_offsets"],
            "resnet18_offsets": resnet["exchange_offsets"],
            "mesh_oracles": oracles,
            "mesh_oracle_caught": all(o["detected"] for o in oracles),
        },
        "scale64": _scale64_leg(),
        "protocol": (
            "ratios are median paired per-round (eventgrad/dpsgd "
            "back-to-back under the same load) over scanned "
            "steady-state runs; one rank per device on the shard_map "
            "legs, all ranks on device 0 on the vmap legs"
        ),
    }
    out_path = os.path.join(
        REPO, "artifacts", f"mesh_ablation_{jax.default_backend()}.json"
    )
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    print(f"wrote {out_path}", file=sys.stderr)
    ok = (
        bitwise
        and rec["audit"]["lenet_clean"]
        and rec["audit"]["resnet18_clean"]
        and rec["audit"]["mesh_oracle_caught"]
        and rec["scale64"]["wire_bytes_exact"]
    )
    return 0 if ok else 1


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 12
    sys.exit(main(n))
