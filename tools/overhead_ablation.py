"""Attribute the EventGraD-vs-D-PSGD wall overhead (round-3 verdict item 2).

BENCH_r03 recorded wall_s_eventgrad/wall_s_dpsgd = 80.1/60.5 (1.32x) at the
reduced tier — but wall_s wraps the whole train() call, jit compile
included, so the ratio conflates one-time compile cost with per-step cost.
This tool separates them at the same op-point (LeNetCifar, Ring(8), global
batch 64, synthetic CIFAR prototypes), then microbenches each candidate
component of the event step in isolation:

  full steps   compile_s + steady-state step_ms for
                 dpsgd            dense exchange, no trigger
                 event_adaptive   the bench trigger (horizon 1.05 + guard)
                 event_constant   constant threshold — drops the adaptive
                                  slope/history machinery
  micro (ms)   jit'd alone on the same shapes/topology:
                 decide           the trigger state machine
                                  (events.decide_and_update: per-leaf norms
                                  + [L]-vector threshold update)
                 exchange_dense   collectives.neighbor_vals (dpsgd's path)
                 exchange_masked  collectives.masked_neighbor_vals
                                  (mask + fire-bit ppermute + where-select)
                 mix_sgd_tail     mix + optax SGD tail (shared)

Derived: per-step overhead %, compile-time delta, and the projected wall
attribution at the bench's 640-pass op-point. Reference point for scale:
the reference's trigger is ~8 scalar norms/step (dmnist/event/event.cpp:
316-343) — near-free; the TPU rebuild's should be too.

Writes artifacts/overhead_ablation_r4_<platform>.json.

Usage:
  python tools/overhead_ablation.py [n_timed_steps]   micro attribution
  python tools/overhead_ablation.py order <ed|de>     in-loop order twin:
      runs the bench op-point's two train() legs in the given order
      (ed = eventgrad first, the bench's order; de = dpsgd first) inside
      THIS process and appends one JSON line per leg to
      artifacts/overhead_order_r4_<platform>.jsonl. Run each order in a
      fresh process: the experiment exists to expose what the FIRST
      train() call of a process absorbs (jit/backend warmup) — the
      round-3 bench's 1.32x wall ratio, measured with eventgrad always
      first, turned out to be exactly that.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from eventgrad_tpu.utils import compile_cache  # noqa: E402

compile_cache.honor_cpu_pin()

from eventgrad_tpu.data.datasets import load_or_synthesize  # noqa: E402
from eventgrad_tpu.data.sharding import batched_epoch  # noqa: E402
from eventgrad_tpu.models import LeNetCifar  # noqa: E402
from eventgrad_tpu.parallel import collectives  # noqa: E402
from eventgrad_tpu.parallel.events import (  # noqa: E402
    EventConfig, decide_and_update,
)
from eventgrad_tpu.parallel.spmd import spmd  # noqa: E402
from eventgrad_tpu.parallel.topology import Ring  # noqa: E402
from eventgrad_tpu.train.state import init_train_state  # noqa: E402
from eventgrad_tpu.train.steps import make_train_step  # noqa: E402
from eventgrad_tpu.utils.profiling import timed_steps  # noqa: E402


def _micro(fn, *args, iters: int = 30):
    """(compile_s, steady ms/call) of jit'd fn on fixed args."""
    t0 = time.perf_counter()
    jax.block_until_ready(fn(*args))
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return compile_s, 1000 * (time.perf_counter() - t0) / iters


def order_experiment(order: str) -> None:
    """Time the reduced-tier train() twins in the given order, one JSON
    line per leg (see module docstring)."""
    import numpy as np

    from eventgrad_tpu.train.loop import train

    topo = Ring(8)
    x, y = load_or_synthesize("cifar10", None, "train", n_synth=1024)
    cfg = EventConfig(
        adaptive=True, horizon=1.05, warmup_passes=10, max_silence=50
    )
    common = dict(
        epochs=40, batch_size=8, learning_rate=1e-2, momentum=0.9,
        random_sampler=True, log_every_epoch=False,
    )
    d = jax.devices()[0]
    out_path = os.path.join(
        REPO, "artifacts", f"overhead_order_r4_{d.platform}.jsonl"
    )
    algos = ("eventgrad", "dpsgd") if order == "ed" else ("dpsgd", "eventgrad")
    for pos, algo in enumerate(algos):
        t0 = time.perf_counter()
        _, hist = train(
            LeNetCifar(), topo, x, y, algo=algo,
            event_cfg=cfg if algo == "eventgrad" else None, **common,
        )
        wall = time.perf_counter() - t0
        steady = hist[1:] or hist
        rec = {
            "order": order, "position": pos, "algo": algo,
            "wall_s": round(wall, 2),
            "epoch0_s": round(hist[0]["wall_s"], 2),
            "steady_step_ms": round(1000 * float(
                np.mean([h["wall_s"] / h["steps"] for h in steady])
            ), 2),
            "passes": common["epochs"] * hist[0]["steps"],
            "platform": d.platform,
            "captured_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
        }
        with open(out_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        print(json.dumps(rec), flush=True)


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "order":
        order_experiment(sys.argv[2] if len(sys.argv) > 2 else "ed")
        return
    n_steps = int(sys.argv[1]) if len(sys.argv) > 1 else 24
    topo = Ring(8)
    model = LeNetCifar()
    tx = optax.sgd(1e-2, momentum=0.9)
    per_rank = 8  # global batch 64 over 8 ranks — the reduced-tier op-point

    x, y = load_or_synthesize("cifar10", None, "train", n_synth=1024)
    xb, yb = batched_epoch(x, y, topo.n_ranks, per_rank)
    steps_avail = xb.shape[1]
    batches = [
        (jnp.asarray(xb[:, s % steps_avail]), jnp.asarray(yb[:, s % steps_avail]))
        for s in range(n_steps)
    ]

    cfg_adapt = EventConfig(
        adaptive=True, horizon=1.05, warmup_passes=10, max_silence=50
    )
    cfg_const = EventConfig(adaptive=False, constant=0.05, warmup_passes=10)

    full = {}
    for name, algo, cfg in (
        ("dpsgd", "dpsgd", None),
        ("event_adaptive", "eventgrad", cfg_adapt),
        ("event_constant", "eventgrad", cfg_const),
    ):
        state = init_train_state(model, x.shape[1:], tx, topo, algo, cfg)
        step = jax.jit(
            spmd(make_train_step(model, tx, topo, algo, event_cfg=cfg), topo)
        )
        out = timed_steps(step, state, batches, warmup=2)
        out.pop("state")
        full[name] = {k: round(v, 4) for k, v in out.items()}

    # ---- micro benches on the same stacked shapes -----------------------
    st = init_train_state(model, x.shape[1:], tx, topo, "eventgrad", cfg_adapt)
    params, ev = st.params, st.event

    decide = jax.jit(spmd(
        lambda p, s: decide_and_update(
            p, s, jnp.int32(100), cfg_adapt, topo.n_neighbors
        ),
        topo,
    ))
    ex_dense = jax.jit(spmd(
        lambda p: collectives.neighbor_vals(p, topo), topo
    ))
    ex_masked = jax.jit(spmd(
        lambda p, f, b: collectives.masked_neighbor_vals(p, f, b, topo)[0],
        topo,
    ))

    def _tail(p, bufs, g, o):
        mixed = collectives.mix(p, bufs, topo)
        updates, o2 = tx.update(g, o, mixed)
        return optax.apply_updates(mixed, updates), o2

    tail = jax.jit(spmd(_tail, topo))

    fire, ev2 = decide(params, ev)
    jax.block_until_ready(fire)
    grads = jax.tree.map(jnp.ones_like, params)

    micro = {}
    for name, fn, args in (
        ("decide", decide, (params, ev)),
        ("exchange_dense", ex_dense, (params,)),
        ("exchange_masked", ex_masked, (params, fire, ev.bufs)),
        ("mix_sgd_tail", tail, (params, ev.bufs, grads, st.opt_state)),
    ):
        compile_s, ms = _micro(fn, *args)
        micro[name] = {"compile_s": round(compile_s, 4), "ms": round(ms, 4)}

    dp, ea = full["dpsgd"], full["event_adaptive"]
    passes = 640  # the reduced tier's captured op-point
    step_delta_ms = ea["step_ms_mean"] - dp["step_ms_mean"]
    compile_delta_s = ea["compile_s"] - dp["compile_s"]
    derived = {
        "step_overhead_pct": round(
            100 * (ea["step_ms_mean"] / dp["step_ms_mean"] - 1), 2
        ),
        "compile_delta_s": round(compile_delta_s, 2),
        "projected_wall_delta_s_at_640_passes": round(
            compile_delta_s + passes * step_delta_ms / 1000, 2
        ),
        "micro_trigger_share_of_step_pct": round(
            100 * micro["decide"]["ms"] / ea["step_ms_mean"], 2
        ),
        "micro_masked_minus_dense_ms": round(
            micro["exchange_masked"]["ms"] - micro["exchange_dense"]["ms"], 4
        ),
    }

    d = jax.devices()[0]
    rec = {
        "op_point": {
            "model": "LeNetCifar", "topology": "ring8",
            "global_batch": topo.n_ranks * per_rank,
            "n_timed_steps": n_steps,
            "trigger": {"horizon": 1.05, "max_silence": 50, "warmup": 10},
        },
        "full_steps": full,
        "micro": micro,
        "derived": derived,
        "platform": d.platform,
        "device_kind": d.device_kind,
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    out_path = os.path.join(
        REPO, "artifacts", f"overhead_ablation_r4_{d.platform}.json"
    )
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec, indent=1))


if __name__ == "__main__":
    main()
