"""EventGraD sender state machine vs a hand-computed trace of
/root/reference/dmnist/event/event.cpp:324-391 semantics."""

import jax.numpy as jnp
import numpy as np

from eventgrad_tpu.parallel.events import EventConfig, EventState, decide_and_update
from eventgrad_tpu.parallel.topology import Ring


def _state(params, topo, cfg):
    return EventState.init(params, topo, cfg)


def test_adaptive_trace_single_param():
    topo = Ring(4)
    cfg = EventConfig(adaptive=True, horizon=0.5, warmup_passes=0, history=2)
    params = {"w": jnp.array([3.0, 4.0])}  # norm 5
    st = _state(params, topo, cfg)

    # pass 1: vd = |5-0| = 5 >= thres 0*0.5 -> fire
    fire, st = decide_and_update(params, st, jnp.int32(1), cfg, topo.n_neighbors)
    assert bool(fire["w"])
    np.testing.assert_allclose(st.slopes[0], [0.0, 5.0])  # slope = 5/1
    np.testing.assert_allclose(st.thres[0], 2.5)  # mean of history
    np.testing.assert_allclose(st.last_sent_norm[0], 5.0)
    np.testing.assert_allclose(st.last_sent_iter[0], 1.0)
    assert int(st.num_events) == 2  # ring: counts both neighbors (event.cpp:344)

    # pass 2: norm 5.5 -> vd 0.5 < thres 2.5*0.5=1.25 -> no fire, decay only
    params2 = {"w": jnp.array([3.3, 4.4])}  # norm 5.5
    fire, st = decide_and_update(params2, st, jnp.int32(2), cfg, topo.n_neighbors)
    assert not bool(fire["w"])
    np.testing.assert_allclose(st.thres[0], 1.25)
    np.testing.assert_allclose(st.last_sent_norm[0], 5.0)
    assert int(st.num_events) == 2

    # pass 3: norm 7 -> vd 2 >= thres 0.625 -> fire; slope = 2/(3-1) = 1
    params3 = {"w": jnp.array([jnp.sqrt(49.0), 0.0])}
    fire, st = decide_and_update(params3, st, jnp.int32(3), cfg, topo.n_neighbors)
    assert bool(fire["w"])
    np.testing.assert_allclose(st.slopes[0], [5.0, 1.0])
    np.testing.assert_allclose(st.thres[0], 3.0)
    np.testing.assert_allclose(st.last_sent_iter[0], 3.0)
    assert int(st.num_events) == 4


def test_constant_threshold_mode():
    topo = Ring(4)
    cfg = EventConfig(adaptive=False, constant=10.0, warmup_passes=0)
    params = {"w": jnp.array([3.0, 4.0])}
    st = _state(params, topo, cfg)

    fire, st = decide_and_update(params, st, jnp.int32(1), cfg, topo.n_neighbors)
    assert not bool(fire["w"])  # vd 5 < 10
    np.testing.assert_allclose(st.thres[0], 10.0)

    cfg0 = EventConfig(adaptive=False, constant=0.0, warmup_passes=0)
    st0 = _state(params, topo, cfg0)
    fire, _ = decide_and_update(params, st0, jnp.int32(1), cfg0, topo.n_neighbors)
    assert bool(fire["w"])  # threshold 0 always fires -> exact D-PSGD


def test_warmup_always_fires():
    topo = Ring(4)
    cfg = EventConfig(adaptive=False, constant=1e9, warmup_passes=30)
    params = {"w": jnp.zeros(3)}  # vd = 0 every pass
    st = _state(params, topo, cfg)
    for p in range(1, 30):  # pass_num < 30 fires (event.cpp:343 strict <)
        fire, st = decide_and_update(params, st, jnp.int32(p), cfg, topo.n_neighbors)
        assert bool(fire["w"]), p
    fire, st = decide_and_update(params, st, jnp.int32(30), cfg, topo.n_neighbors)
    assert not bool(fire["w"])


def test_multi_param_independent_state():
    topo = Ring(4)
    cfg = EventConfig(adaptive=False, constant=4.0, warmup_passes=0)
    params = {"a": jnp.array([3.0, 4.0]), "b": jnp.array([1.0, 0.0])}
    st = _state(params, topo, cfg)
    fire, st = decide_and_update(params, st, jnp.int32(1), cfg, topo.n_neighbors)
    assert bool(fire["a"]) and not bool(fire["b"])
    assert int(st.num_events) == 2


def test_max_silence_bounds_gap_between_fires():
    """Beyond-reference: with max_silence=K a parameter never stays silent
    K passes in a row, even under an impossibly high constant threshold."""
    topo = Ring(4)
    cfg = EventConfig(adaptive=False, constant=1e9, warmup_passes=0,
                      max_silence=3)
    params = {"w": jnp.array([3.0, 4.0])}
    st = _state(params, topo, cfg)
    fires = []
    for p in range(1, 10):
        fire, st = decide_and_update(params, st, jnp.int32(p), cfg,
                                     topo.n_neighbors)
        fires.append(bool(fire["w"]))
    # last_sent_iter starts at 0: fires exactly when (pass - last) >= 3
    assert fires == [False, False, True, False, False, True, False, False,
                     True]


def test_max_silence_one_is_dpsgd():
    """max_silence=1 fires every pass — the D-PSGD equivalence knob."""
    topo = Ring(4)
    cfg = EventConfig(adaptive=False, constant=1e9, warmup_passes=0,
                      max_silence=1)
    params = {"w": jnp.array([1.0])}
    st = _state(params, topo, cfg)
    for p in range(1, 5):
        fire, st = decide_and_update(params, st, jnp.int32(p), cfg,
                                     topo.n_neighbors)
        assert bool(fire["w"])


def test_max_silence_zero_is_reference_behavior():
    """max_silence=0 (default) leaves the reference trigger untouched."""
    topo = Ring(4)
    cfg0 = EventConfig(adaptive=True, horizon=0.5, warmup_passes=0)
    cfgs = EventConfig(adaptive=True, horizon=0.5, warmup_passes=0,
                       max_silence=0)
    params = {"w": jnp.array([3.0, 4.0])}
    s0, ss = _state(params, topo, cfg0), _state(params, topo, cfgs)
    for p in range(1, 6):
        f0, s0 = decide_and_update(params, s0, jnp.int32(p), cfg0,
                                   topo.n_neighbors)
        fs, ss = decide_and_update(params, ss, jnp.int32(p), cfgs,
                                   topo.n_neighbors)
        assert bool(f0["w"]) == bool(fs["w"])
    np.testing.assert_allclose(s0.thres[0], ss.thres[0])


def test_pick_mnist_rung_ladder():
    """Budget-adaptive reduced-tier MNIST ladder (round-4): rung choice
    is a pure function of remaining budget + the reference-pure flag."""
    from eventgrad_tpu.parallel.events import pick_mnist_rung

    # generous budget: the >= 1.0 vs-baseline rung, stabilized trigger
    assert pick_mnist_rung(float("inf"), refpure=False) == (4096, 68, 1.025, 50)
    assert pick_mnist_rung(400.0, refpure=False) == (4096, 68, 1.025, 50)
    # mid budget: the 380-pass rung
    assert pick_mnist_rung(300.0, refpure=False) == (2048, 95, 1.025, 50)
    # tight budget: keep the tier's 160-pass floor
    assert pick_mnist_rung(200.0, refpure=False) is None
    # reference-pure request: pass budget upgrades, trigger stays pure
    assert pick_mnist_rung(400.0, refpure=True) == (4096, 68, 1.0, 0)
    assert pick_mnist_rung(300.0, refpure=True) == (2048, 95, 1.0, 0)


def test_pick_cifar_epochs_ladder():
    from eventgrad_tpu.parallel.events import pick_cifar_epochs

    assert pick_cifar_epochs(float("inf")) == 60   # direct run: 960 passes
    assert pick_cifar_epochs(660.0) == 60
    assert pick_cifar_epochs(600.0) == 40          # MNIST top rung keeps priority
    assert pick_cifar_epochs(200.0) == 40


def test_pick_full_epochs_ladder():
    from eventgrad_tpu.parallel.events import pick_full_epochs

    # ladder recalibrated from the round-4 live capture (~19.3 s per
    # epoch pair + ~320 s cold fixed costs, tpu_flagship_quick.json)
    assert pick_full_epochs(None) == 61      # direct run: reference scale
    assert pick_full_epochs(1800.0) == 61
    assert pick_full_epochs("1100") == 30    # env strings accepted
    assert pick_full_epochs(700.0) == 12
    assert pick_full_epochs(520.0) == 8      # warm-cache sizing
    assert pick_full_epochs(250.0) == 5      # minimum chip evidence
