"""Top-k payload selection and scatter semantics (spevent.cpp:339-542)."""

import jax.numpy as jnp
import numpy as np

from eventgrad_tpu.parallel.sparsify import (
    SparseConfig,
    SparseState,
    scatter_into,
    topk_payload,
)
from eventgrad_tpu.parallel.topology import Ring


def test_k_for_ceil_rule():
    cfg = SparseConfig(topk_percent=10.0)
    assert cfg.k_for(100) == 10
    assert cfg.k_for(101) == 11  # ceil (spevent.cpp:148)
    assert cfg.k_for(5) == 1
    cfg_all = SparseConfig(topk_percent=100.0)
    assert cfg_all.k_for(7) == 7


def test_topk_selects_largest_drift():
    cfg = SparseConfig(topk_percent=50.0)
    params = {"w": jnp.array([1.0, 5.0, 2.0, 9.0])}
    prev = {"w": jnp.array([1.0, 0.0, 2.5, 0.0])}  # |diff| = [0, 5, .5, 9]
    vals, idxs = topk_payload(params, prev, cfg)
    assert sorted(np.asarray(idxs["w"]).tolist()) == [1, 3]
    # values are the *current* params at those indices, not the diffs
    got = dict(zip(np.asarray(idxs["w"]).tolist(), np.asarray(vals["w"]).tolist()))
    assert got == {1: 5.0, 3: 9.0}


def test_scatter_respects_gate():
    full = {"w": jnp.zeros((2, 2))}
    vals = {"w": jnp.array([7.0])}
    idxs = {"w": jnp.array([3], jnp.int32)}
    out = scatter_into(full, vals, idxs, {"w": jnp.array(True)})
    np.testing.assert_allclose(out["w"], [[0, 0], [0, 7.0]])
    out = scatter_into(full, vals, idxs, {"w": jnp.array(False)})
    np.testing.assert_allclose(out["w"], np.zeros((2, 2)))


def test_state_init_copies_params():
    topo = Ring(4)
    params = {"w": jnp.arange(4.0)}
    st = SparseState.init(params, topo)
    np.testing.assert_allclose(st.prev_sent["w"], params["w"])
    assert len(st.replicas) == 2
    np.testing.assert_allclose(st.replicas[0]["w"], params["w"])
