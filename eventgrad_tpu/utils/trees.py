"""Small pytree helpers shared across the framework."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def tree_norm(tree: Any) -> Any:
    """Per-leaf L2 norm of the flattened leaf — the event metric
    `torch::norm(flatten(param))` (/root/reference/dmnist/event/event.cpp:325),
    returned as a pytree of scalars."""
    return jax.tree.map(lambda x: jnp.linalg.norm(x.reshape(-1)), tree)


def tree_zeros_like(tree: Any) -> Any:
    return jax.tree.map(jnp.zeros_like, tree)


def tree_count_params(tree: Any) -> int:
    """Total element count (reference prints this at startup, event.cpp:158-165)."""
    return sum(x.size for x in jax.tree.leaves(tree))


def tree_num_leaves(tree: Any) -> int:
    return len(jax.tree.leaves(tree))


def tree_cast(tree: Any, dtype) -> Any:
    return jax.tree.map(lambda x: x.astype(dtype), tree)
