"""On-device telemetry: a JIT-safe accumulator pytree in the train scan.

The design rule is ZERO added host syncs: every counter lives in the
scan-carried `TrainState.telemetry` and is CUMULATIVE, so the host reads
it at most once per jit-dispatch block (train/loop.py flushes at block
ends and diffs consecutive snapshots — no device-side reset write
either). Per-pass cost is a handful of fused vector ops on [L] (leaf
count) and [n_edges] arrays — measured < 3% of a CPU micro-bench step
(docs/OBSERVABILITY.md).

Field semantics: obs.schema.TELEMETRY_FIELDS (the one versioned
definition).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from flax import struct

from eventgrad_tpu.obs import ledger as obs_ledger
from eventgrad_tpu.obs.schema import SILENCE_BUCKETS


class TelemetryState(struct.PyTreeNode):
    """Per-rank cumulative telemetry counters (see schema.TELEMETRY_FIELDS
    for units). Stacked over ranks like every other TrainState leaf."""

    steps: jnp.ndarray            # i32 []
    fire_count: jnp.ndarray       # i32 [L]
    defer_count: jnp.ndarray      # i32 [L]
    thres_sum: jnp.ndarray        # f32 [L]
    drift_sum: jnp.ndarray        # f32 [L]
    silence_hist: jnp.ndarray     # i32 [SILENCE_BUCKETS]
    fired_elems_sum: jnp.ndarray  # f32 []
    fired_elems_peak: jnp.ndarray # f32 []
    edge_bytes: jnp.ndarray       # f32 [n_edges]
    # integrity counters (chaos/integrity.py): per-edge wire rejections
    # (checksum mismatch / non-finite payload) and quarantined passes.
    # Defaulted so pre-integrity snapshots restore via the known-added
    # migration path (train/loop.py restore_with_fill).
    wire_reject: jnp.ndarray = None    # type: ignore[assignment]  # i32 [n_edges]
    quarantined: jnp.ndarray = None    # type: ignore[assignment]  # i32 []
    # per-bucket wire-real bytes of the bucketed gossip schedule
    # (train/steps.py bucketed=); [1] on the monolithic path, so the
    # sum always reconciles with edge_bytes' total. Defaulted like the
    # integrity counters so pre-bucket snapshots restore via the
    # known-added migration path.
    bucket_bytes: jnp.ndarray = None   # type: ignore[assignment]  # f32 [n_buckets]
    # bounded-async counters (train(staleness=D >= 2)): per-edge
    # staleness sums (the gauge accumulates per pass; mean = /steps),
    # a log2 histogram of the per-edge-pass staleness, and the
    # late-commit count. Defaulted like the integrity counters so
    # pre-bounded-async snapshots restore via the known-added path.
    edge_staleness: jnp.ndarray = None  # type: ignore[assignment]  # f32 [n_edges]
    staleness_hist: jnp.ndarray = None  # type: ignore[assignment]  # i32 [SILENCE_BUCKETS]
    late_commits: jnp.ndarray = None    # type: ignore[assignment]  # i32 []
    # the message-lifecycle ledger (obs/ledger.py): cumulative per-edge
    # disposition counters + the bounded-async in-flight queue, mutated
    # only through obs.ledger.ledger_update (the `telemetry-counter-
    # ledgered` lint rule). Defaulted like the other known-added fields
    # so pre-ledger snapshots restore via restore_with_fill.
    ledger: obs_ledger.MessageLedger = None  # type: ignore[assignment]

    @classmethod
    def init(
        cls, n_leaves: int, n_edges: int, n_buckets: int = 1,
        queue_depth: int = 0,
    ) -> "TelemetryState":
        zl = jnp.zeros((n_leaves,), jnp.float32)
        return cls(
            steps=jnp.zeros((), jnp.int32),
            fire_count=jnp.zeros((n_leaves,), jnp.int32),
            defer_count=jnp.zeros((n_leaves,), jnp.int32),
            thres_sum=zl,
            drift_sum=zl,
            silence_hist=jnp.zeros((SILENCE_BUCKETS,), jnp.int32),
            fired_elems_sum=jnp.zeros((), jnp.float32),
            fired_elems_peak=jnp.zeros((), jnp.float32),
            edge_bytes=jnp.zeros((n_edges,), jnp.float32),
            wire_reject=jnp.zeros((n_edges,), jnp.int32),
            quarantined=jnp.zeros((), jnp.int32),
            bucket_bytes=jnp.zeros((max(1, n_buckets),), jnp.float32),
            edge_staleness=jnp.zeros((n_edges,), jnp.float32),
            staleness_hist=jnp.zeros((SILENCE_BUCKETS,), jnp.int32),
            late_commits=jnp.zeros((), jnp.int32),
            ledger=obs_ledger.MessageLedger.init(
                n_edges, queue_depth=queue_depth
            ),
        )


def silence_bucket(silence: jnp.ndarray) -> jnp.ndarray:
    """[L] silence (passes since last send) -> log2 bucket index: bucket k
    counts silence in [2^k, 2^(k+1)); the last bucket absorbs the tail.
    Silence < 1 (warmup pass 0 edge) clamps into bucket 0."""
    s = jnp.maximum(silence.astype(jnp.float32), 1.0)
    return jnp.clip(
        jnp.floor(jnp.log2(s)).astype(jnp.int32), 0, SILENCE_BUCKETS - 1
    )


def accumulate(
    tel: TelemetryState,
    *,
    fire_vec: Optional[jnp.ndarray] = None,      # bool [L] effective fires
    defer_vec: Optional[jnp.ndarray] = None,     # bool [L] gated-out fires
    thres: Optional[jnp.ndarray] = None,         # f32 [L] post-decay
    drift: Optional[jnp.ndarray] = None,         # f32 [L] |norm - last_sent|
    silence: Optional[jnp.ndarray] = None,       # f32/i32 [L] passes quiet
    fired_elems: Optional[jnp.ndarray] = None,   # f32 [] admitted elements
    edge_bytes: Optional[jnp.ndarray] = None,    # f32 [n_edges] this pass
    wire_reject: Optional[jnp.ndarray] = None,   # bool/i32 [n_edges]
    quarantined: Optional[jnp.ndarray] = None,   # bool/i32 []
    bucket_bytes: Optional[jnp.ndarray] = None,  # f32 [n_buckets] this pass
    edge_staleness: Optional[jnp.ndarray] = None,  # i32/f32 [n_edges]
    late_commits: Optional[jnp.ndarray] = None,    # i32 [] this pass
    ledger_inputs: Optional[dict] = None,  # kwargs for ledger_update
) -> TelemetryState:
    """One pass of counter updates; omitted (None) quantities leave their
    counters untouched (the non-event algorithms pass only edge_bytes).
    Pure elementwise/scatter-add vector ops — fuses into the step under
    jit with no extra HBM round trips."""
    upd = {"steps": tel.steps + 1}
    if fire_vec is not None:
        upd["fire_count"] = tel.fire_count + fire_vec.astype(jnp.int32)
    if defer_vec is not None:
        upd["defer_count"] = tel.defer_count + defer_vec.astype(jnp.int32)
    if thres is not None:
        upd["thres_sum"] = tel.thres_sum + thres
    if drift is not None:
        upd["drift_sum"] = tel.drift_sum + drift
    if silence is not None:
        upd["silence_hist"] = tel.silence_hist.at[
            silence_bucket(silence)
        ].add(1)
    if fired_elems is not None:
        fe = jnp.asarray(fired_elems, jnp.float32)
        upd["fired_elems_sum"] = tel.fired_elems_sum + fe
        upd["fired_elems_peak"] = jnp.maximum(tel.fired_elems_peak, fe)
    if edge_bytes is not None:
        upd["edge_bytes"] = tel.edge_bytes + edge_bytes
    if wire_reject is not None:
        upd["wire_reject"] = tel.wire_reject + wire_reject.astype(jnp.int32)
    if quarantined is not None:
        upd["quarantined"] = tel.quarantined + quarantined.astype(jnp.int32)
    if bucket_bytes is not None:
        upd["bucket_bytes"] = tel.bucket_bytes + bucket_bytes
    if edge_staleness is not None:
        upd["edge_staleness"] = (
            tel.edge_staleness + edge_staleness.astype(jnp.float32)
        )
        upd["staleness_hist"] = tel.staleness_hist.at[
            silence_bucket(edge_staleness)
        ].add(1)
    if late_commits is not None:
        upd["late_commits"] = tel.late_commits + late_commits.astype(
            jnp.int32
        )
    if ledger_inputs is not None and tel.ledger is not None:
        # the message-lifecycle ledger: ALL disposition math lives in
        # obs.ledger.ledger_update — the step only hands over the
        # branch's raw observables (obs/schema.py DISPOSITIONS)
        upd["ledger"] = obs_ledger.ledger_update(
            tel.ledger, **ledger_inputs
        )
    return tel.replace(**upd)


def window_record(cur, prev=None):
    """Host-side flush: diff two cumulative stacked snapshots (leading
    axis = ranks, numpy or device arrays) into one flush-window `obs`
    dict — the schema.RECORD_FIELDS shape the history records carry.
    `prev=None` means "since init" (the first flush). Counts sum over
    ranks; means average over ranks; the fired-elements peak is the max
    over ranks of the CUMULATIVE running peak (a running max cannot be
    windowed)."""
    import numpy as np

    from eventgrad_tpu.obs.schema import OBS_SCHEMA_VERSION

    def d(field):
        c = np.asarray(getattr(cur, field), np.float64)
        if prev is None:
            return c
        return c - np.asarray(getattr(prev, field), np.float64)

    steps = int(d("steps").reshape(-1)[0])
    denom = max(1, steps)
    rec = {
        "schema": OBS_SCHEMA_VERSION,
        "steps": steps,
        "fire_count": [int(v) for v in d("fire_count").sum(axis=0)],
        "defer_count": [int(v) for v in d("defer_count").sum(axis=0)],
        "thres_mean": [
            round(float(v), 6) for v in d("thres_sum").mean(axis=0) / denom
        ],
        "drift_mean": [
            round(float(v), 6) for v in d("drift_sum").mean(axis=0) / denom
        ],
        "silence_hist": [int(v) for v in d("silence_hist").sum(axis=0)],
        "fired_elems_mean": round(
            float(d("fired_elems_sum").mean()) / denom, 2
        ),
        "fired_elems_peak": float(
            np.asarray(cur.fired_elems_peak, np.float64).max()
        ),
        "edge_bytes_per_step": [
            round(float(v), 2) for v in d("edge_bytes").mean(axis=0) / denom
        ],
    }
    if cur.wire_reject is not None:
        # integrity counters were known-added: a pre-integrity snapshot
        # (or a hand-built test state) carries None — omit the keys
        # instead of fabricating zeros for a run that never counted
        rec["wire_reject_count"] = [
            int(v) for v in d("wire_reject").sum(axis=0)
        ]
        rec["quarantined_steps"] = int(d("quarantined").sum())
    if cur.bucket_bytes is not None:
        # bucketed-schedule rider (known-added like the integrity
        # counters): per-bucket wire-real bytes per pass, rank mean
        rec["bucket_bytes_per_step"] = [
            round(float(v), 2)
            for v in d("bucket_bytes").mean(axis=0) / denom
        ]
    if cur.edge_staleness is not None:
        # bounded-async riders (known-added): the per-edge staleness
        # gauge (rank-mean per pass), its histogram, and late commits
        rec["edge_staleness_per_step"] = [
            round(float(v), 4)
            for v in d("edge_staleness").mean(axis=0) / denom
        ]
        rec["staleness_hist"] = [
            int(v) for v in d("staleness_hist").sum(axis=0)
        ]
        rec["late_commit_count"] = int(d("late_commits").sum())
    if cur.ledger is not None:
        # message-lifecycle ledger (known-added like the riders above):
        # per-disposition per-edge window deltas summed over ranks +
        # the in-flight gauge at the window end (obs/ledger.py)
        rec["message_ledger"] = obs_ledger.window_block(
            cur.ledger, None if prev is None else prev.ledger
        )
    return rec
