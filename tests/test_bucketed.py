"""Bucketed gossip schedule: bucket geometry, capacity splits, bitwise
parity with the monolithic step, the jaxpr interleaving gate, and the
cross-layout resume guard.

The bucketed path's contract (docs/ARCHITECTURE.md "Bucketed gossip
schedule"): segmenting the flat arena into K leaf-aligned buckets and
pipelining each bucket's gate/pack/exchange/commit/mix changes the
SCHEDULE, never the values — training is bitwise the monolithic path
across algorithms, wires, dtypes, staleness, chaos delivery masks, and
telemetry. Deferral under the compact wire becomes BUCKET-LOCAL (each
bucket has its own split of the capacity), which is semantics, not
drift: the parity matrix runs at non-binding capacity, and the
bucket-local behavior has its own units here.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from _spmd import requires_shard_map

from eventgrad_tpu.analysis import walker
from eventgrad_tpu.chaos import monitor as chaos_monitor
from eventgrad_tpu.chaos.schedule import ChaosSchedule
from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP
from eventgrad_tpu.obs import device as obs_device
from eventgrad_tpu.parallel import arena, collectives
from eventgrad_tpu.parallel.events import EventConfig, capacity_gate
from eventgrad_tpu.parallel.spmd import build_mesh, spmd, stack_for_ranks
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.state import init_train_state
from eventgrad_tpu.train.steps import make_train_step

N_RANKS = 4
IN_SHAPE = (8, 8, 1)
PER_RANK = 4
MODEL = dict(hidden=16)
CFG = EventConfig(adaptive=True, horizon=0.95, warmup_passes=2,
                  max_silence=4)
#: the audit MLP's element count — a NON-BINDING compact capacity, so
#: the per-bucket split admits exactly what the monolithic gate admits
#: and the parity claim is exact (binding budgets are bucket-local by
#: design and unit-tested separately below)
FULL_CAPACITY = 1210


def _batches(n_steps, seed=0):
    x, y = synthetic_dataset(
        N_RANKS * PER_RANK * n_steps, IN_SHAPE, seed=seed
    )
    xb = jnp.asarray(x.reshape((n_steps, N_RANKS, PER_RANK) + IN_SHAPE))
    yb = jnp.asarray(y.reshape((n_steps, N_RANKS, PER_RANK)))
    return [(xb[i], yb[i]) for i in range(n_steps)]


def _build(algo, bucketed, *, wire=None, gossip_wire="dense",
           capacity=None, staleness=0, obs=False, chaos=None,
           momentum=0.0, backend="vmap"):
    topo = Ring(N_RANKS)
    model = MLP(**MODEL)
    tx = optax.sgd(0.05, momentum=momentum if momentum else None)
    arena_on = algo == "eventgrad"
    state = init_train_state(
        model, IN_SHAPE, tx, topo, algo, CFG, seed=0, arena=arena_on,
        bucketed=bucketed or 1,
    )
    if chaos is not None:
        state = state.replace(
            chaos=stack_for_ranks(chaos_monitor.PeerHealth.init(topo), topo)
        )
    if obs:
        n_leaves = len(jax.tree.leaves(state.params))
        state = state.replace(
            telemetry=stack_for_ranks(
                obs_device.TelemetryState.init(
                    n_leaves, topo.n_neighbors,
                    n_buckets=min(bucketed or 1, n_leaves),
                ),
                topo,
            )
        )
    step = make_train_step(
        model, tx, topo, algo, event_cfg=CFG, wire=wire,
        gossip_wire=gossip_wire, compact_capacity=capacity,
        staleness=staleness, obs=obs, chaos=chaos, arena=arena_on,
        bucketed=bucketed,
    )
    mesh = build_mesh(topo) if backend == "shard_map" else None
    return state, jax.jit(spmd(step, topo, mesh=mesh))


def _run(state, lifted, batches):
    m = None
    for b in batches:
        state, m = lifted(state, b)
    return state, m


def _flat_bufs(bufs):
    """Per-neighbor flat view of either layout (monolithic [n] array or
    the bucketed tuple of per-bucket arrays)."""
    out = []
    for buf in bufs:
        if isinstance(buf, tuple):
            out.append(np.concatenate(
                [np.asarray(x) for x in buf], axis=-1
            ))
        else:
            out.append(np.asarray(buf))
    return out


def _assert_parity(s_m, s_b, m_m, m_b, algo):
    for name in ("params", "opt_state", "batch_stats"):
        for x, y in zip(jax.tree.leaves(getattr(s_m, name)),
                        jax.tree.leaves(getattr(s_b, name))):
            np.testing.assert_array_equal(
                np.asarray(x), np.asarray(y), err_msg=name
            )
    for f in ("thres", "last_sent_norm", "last_sent_iter", "slopes",
              "num_events", "num_deferred"):
        np.testing.assert_array_equal(
            np.asarray(getattr(s_m.event, f)),
            np.asarray(getattr(s_b.event, f)), err_msg=f,
        )
    if algo == "eventgrad":
        for i, (bm, bb) in enumerate(
            zip(_flat_bufs(s_m.event.bufs), _flat_bufs(s_b.event.bufs))
        ):
            np.testing.assert_array_equal(bm, bb, err_msg=f"bufs[{i}]")
    if s_m.chaos is not None:
        for x, y in zip(jax.tree.leaves(s_m.chaos),
                        jax.tree.leaves(s_b.chaos)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                          err_msg="chaos")
    if s_m.telemetry is not None:
        # every field bitwise except bucket_bytes, whose SHAPE is the
        # schedule ([1] vs [K]) — its total must still reconcile
        for f in ("steps", "fire_count", "defer_count", "thres_sum",
                  "drift_sum", "silence_hist", "fired_elems_sum",
                  "fired_elems_peak", "edge_bytes"):
            np.testing.assert_array_equal(
                np.asarray(getattr(s_m.telemetry, f)),
                np.asarray(getattr(s_b.telemetry, f)), err_msg=f,
            )
        np.testing.assert_allclose(
            np.asarray(s_b.telemetry.bucket_bytes).sum(-1),
            np.asarray(s_m.telemetry.bucket_bytes).sum(-1),
        )
    # metrics: shared keys bitwise; the per-bucket vector (bucketed
    # only) must sum to the wire-real total exactly
    for k in m_m:
        np.testing.assert_array_equal(
            np.asarray(m_m[k]), np.asarray(m_b[k]), err_msg=k
        )
    extra = set(m_b) - set(m_m)
    assert extra <= {"sent_bytes_wire_real_per_bucket"}
    if extra:
        np.testing.assert_allclose(
            np.asarray(m_b["sent_bytes_wire_real_per_bucket"]).sum(-1),
            np.asarray(m_b["sent_bytes_wire_real"]),
        )


#: the required parity matrix: algos x wires x gossip wires x staleness
#: x obs x chaos, each dimension exercised against at least one other
#: (the test_arena.py CASES rule), crossed with K in {2, 4}
CASES = {
    "event_masked_f32": dict(algo="eventgrad"),
    "event_masked_int8": dict(algo="eventgrad", wire="int8"),
    "event_masked_bf16_stale": dict(algo="eventgrad", wire="bf16",
                                    staleness=1),
    "event_masked_obs": dict(algo="eventgrad", obs=True),
    "event_masked_chaos": dict(algo="eventgrad",
                               chaos=ChaosSchedule(seed=3, drop_p=0.4)),
    "event_masked_mom": dict(algo="eventgrad", momentum=0.9),
    "event_compact_f32": dict(algo="eventgrad", gossip_wire="compact",
                              capacity=FULL_CAPACITY),
    "event_compact_int8_obs": dict(algo="eventgrad",
                                   gossip_wire="compact",
                                   capacity=FULL_CAPACITY, wire="int8",
                                   obs=True),
    "event_compact_stale": dict(algo="eventgrad", gossip_wire="compact",
                                capacity=FULL_CAPACITY, staleness=1),
    "sp_f32": dict(algo="sp_eventgrad"),
    "sp_int8_stale": dict(algo="sp_eventgrad", wire="int8", staleness=1),
}


@pytest.mark.parametrize("name", sorted(CASES))
def test_bucketed_bitwise_matches_monolithic(name):
    """K in {2, 4} reproduce the monolithic step bitwise: full state AND
    step metrics after several steps (warmup crossing, real fire
    patterns)."""
    kw = dict(CASES[name])
    algo = kw.pop("algo")
    batches = _batches(5)
    s_m, lift_m = _build(algo, None, **kw)
    s_m, m_m = _run(s_m, lift_m, batches)
    for K in (2, 4):
        s_b, lift_b = _build(algo, K, **kw)
        s_b, m_b = _run(s_b, lift_b, batches)
        _assert_parity(s_m, s_b, m_m, m_b, algo)


@requires_shard_map
def test_bucketed_bitwise_matches_monolithic_shard_map():
    """Same contract under the real-mesh lift (one device per rank)."""
    if len(jax.devices()) < N_RANKS:
        pytest.skip(f"needs {N_RANKS} devices")
    batches = _batches(3)
    s_m, lift_m = _build("eventgrad", None, backend="shard_map")
    s_b, lift_b = _build("eventgrad", 2, backend="shard_map")
    s_m, m_m = _run(s_m, lift_m, batches)
    s_b, m_b = _run(s_b, lift_b, batches)
    _assert_parity(s_m, s_b, m_m, m_b, "eventgrad")


# ---------------------------------------------------------------------------
# bucket geometry units


def _tree(sizes):
    return {f"l{i:02d}": jnp.zeros((s,), jnp.float32)
            for i, s in enumerate(sizes)}


def test_buckets_leaf_aligned_partition():
    """Buckets tile the arena exactly: contiguous, leaf-aligned (no leaf
    straddles a boundary), element-balanced cuts, k clamped to the leaf
    count, and every bucket's local layout re-derives the parent's."""
    spec = arena.arena_spec(_tree((1024, 16, 160, 10, 300, 7)))
    for k in (1, 2, 3, 4, 6, 9):
        bs = spec.buckets(k)
        assert len(bs) == min(k, spec.n_leaves)
        assert bs[0].lo == 0 and bs[-1].hi == spec.n_leaves
        assert sum(b.size for b in bs) == spec.n_total
        for b1, b2 in zip(bs, bs[1:]):
            assert b1.hi == b2.lo                  # contiguous
            assert b1.start + b1.size == b2.start  # element-contiguous
        for b in bs:
            assert b.sizes == spec.sizes[b.lo:b.hi]
            assert b.starts_rel[0] == 0
            assert b.floor == max(b.sizes)
            assert b.size == sum(b.sizes)
            # the bucket-local segment map re-bases the parent's
            seg = np.asarray(b.seg_expand())
            assert seg.shape == (b.size,)
            assert seg.max() == b.n_leaves - 1
    # cached like every other piece of leaf metadata
    assert spec.buckets(3) is spec.buckets(3)


def test_split_capacity_floors_and_exact_sum():
    spec = arena.arena_spec(_tree((1024, 16, 160, 10)))
    bs = spec.buckets(2)
    # full capacity splits to the bucket sizes exactly
    assert collectives.split_capacity(spec.n_total, bs) == tuple(
        b.size for b in bs
    )
    # a binding capacity still sums exactly and respects every floor
    floor_total = collectives.bucketed_capacity_floor(bs)
    for cap in (floor_total, floor_total + 37, spec.n_total - 1):
        caps = collectives.split_capacity(cap, bs)
        assert sum(caps) == cap
        for c, b in zip(caps, bs):
            assert c >= b.floor
    # below the bucketed floor: loud, names the bound
    with pytest.raises(ValueError, match="bucketed floor"):
        collectives.split_capacity(floor_total - 1, bs)


def test_deferral_stays_bucket_local():
    """A bucket that overflows its split defers ONLY its own leaves:
    other buckets' admissions are unaffected — where the monolithic
    greedy gate would have let bucket 0's overflow starve later leaves
    in line."""
    spec = arena.arena_spec(_tree((100, 100, 50, 60)))
    bs = spec.buckets(2)
    assert [b.lo for b in bs] == [0, 2]
    fire = jnp.asarray([True, True, True, True])
    caps = collectives.split_capacity(210, bs)  # (100+100, 50+60) -> binding
    gated = []
    for b in bs:
        gated.append(capacity_gate(
            fire[b.lo:b.hi], b.sizes, caps[b.index]
        ))
    eff = np.concatenate([np.asarray(g) for g in gated])
    # bucket 0 (200 elems) into its ~120-elem split: one leaf defers;
    # bucket 1's admission is untouched by bucket 0's overflow
    assert eff[:2].sum() == 1
    assert caps[1] >= bs[1].floor
    # monolithic greedy at the same total admits strictly differently
    mono = np.asarray(capacity_gate(fire, spec.sizes, 210))
    assert not np.array_equal(eff, mono)


def test_bucketed_wire_bytes_sum_to_monolithic():
    spec = arena.arena_spec(_tree((1024, 16, 160, 10)))
    for wire in (None, "bf16", "int8"):
        for k in (2, 4):
            bs = spec.buckets(k)
            per = collectives.bucketed_wire_real_bytes_per_neighbor(
                bs, wire
            )
            assert len(per) == k
            assert sum(per) == collectives.wire_real_bytes_per_neighbor(
                spec.n_total, spec.n_leaves, wire, fire_bits=True
            )
            caps = collectives.split_capacity(spec.n_total, bs)
            per_c = collectives.bucketed_wire_real_bytes_per_neighbor(
                bs, wire, caps
            )
            assert sum(per_c) == collectives.wire_real_bytes_per_neighbor(
                spec.n_total, spec.n_leaves, wire,
                compact_capacity=spec.n_total, fire_bits=True,
            )


# ---------------------------------------------------------------------------
# the jaxpr interleaving gate (ISSUE 10 acceptance)


def test_jaxpr_interleaving_gate():
    """In the bucketed step's jaxpr, at least one exchange-side op of
    bucket k appears between update-side ops of buckets k-1 and k+1
    (machine-checked via analysis/walker.bucket_schedule) — the
    exchanges interleave with update work instead of forming one
    prefix block like the monolithic schedule."""
    K = 4
    topo = Ring(N_RANKS)
    model = MLP(**MODEL)
    tx = optax.sgd(0.05)
    state = init_train_state(
        model, IN_SHAPE, tx, topo, "eventgrad", CFG, seed=0, arena=True,
        bucketed=K,
    )
    params0 = jax.tree.map(lambda l: l[0], state.params)
    dims = [b.size for b in arena.arena_spec(params0).buckets(K)]
    assert len(set(dims)) == K, "gate geometry needs distinct buckets"
    step = make_train_step(
        model, tx, topo, "eventgrad", event_cfg=CFG, arena=True,
        bucketed=K,
    )
    batch = _batches(1)[0]
    closed = jax.make_jaxpr(spmd(step, topo))(state, batch)
    sched = walker.bucket_schedule(closed.jaxpr, dims, dims)
    # every bucket's exchange and commit were found...
    for b in range(K):
        assert sched["exchange"][b], f"bucket {b}: no exchange ops found"
        assert sched["update"][b], f"bucket {b}: no update ops found"
    # ...and the schedule interleaves
    assert sched["interleaved"], (
        "bucketed step's exchanges form a prefix block: "
        f"{sched['exchange']} vs {sched['update']}"
    )

    # the monolithic step must NOT pass the same gate (its one exchange
    # precedes every commit — nothing to interleave)
    state_m = init_train_state(
        model, IN_SHAPE, tx, topo, "eventgrad", CFG, seed=0, arena=True
    )
    step_m = make_train_step(
        model, tx, topo, "eventgrad", event_cfg=CFG, arena=True
    )
    closed_m = jax.make_jaxpr(spmd(step_m, topo))(state_m, batch)
    n_total = sum(dims)
    sched_m = walker.bucket_schedule(
        closed_m.jaxpr, [n_total], [n_total]
    )
    assert not sched_m["interleaved"]


# ---------------------------------------------------------------------------
# validation + resume


def test_bucketed_validation():
    topo = Ring(N_RANKS)
    model = MLP(**MODEL)
    tx = optax.sgd(0.05)
    with pytest.raises(ValueError, match="eventgrad"):
        make_train_step(model, tx, topo, "dpsgd", bucketed=2)
    with pytest.raises(ValueError, match="arena"):
        make_train_step(
            model, tx, topo, "eventgrad", event_cfg=CFG, bucketed=2
        )
    from eventgrad_tpu.chaos.integrity import IntegrityConfig

    with pytest.raises(ValueError, match="integrity"):
        make_train_step(
            model, tx, topo, "eventgrad", event_cfg=CFG, arena=True,
            bucketed=2, integrity=IntegrityConfig(),
        )
    # the per-bucket fused tail is measured-gated: without a
    # bucketed_tail_speedup entry the step refuses (the loop demotes
    # to the monolithic fused path with a warning instead)
    from eventgrad_tpu.ops import arena_tuning

    if not arena_tuning.bucketed_tail_ok(2):
        with pytest.raises(ValueError, match="bucketed_tail_speedup"):
            make_train_step(
                model, tx, topo, "eventgrad", event_cfg=CFG, arena=True,
                bucketed=2, fused_sgd=(0.05, 0.9),
            )


def test_bucketed_fused_tail_parity(monkeypatch):
    """With the measured gate forced open, the per-bucket fused tail
    (one fused_mix_commit per bucket) reproduces the monolithic fused
    tail bitwise — the decomposition is positionwise."""
    from eventgrad_tpu.ops import arena_tuning

    monkeypatch.setattr(
        arena_tuning, "bucketed_tail_ok", lambda *a, **kw: True
    )
    batches = _batches(4)
    kw = dict(momentum=0.9)
    topo = Ring(N_RANKS)
    model = MLP(**MODEL)
    tx = optax.sgd(0.05, momentum=0.9)

    def build(bucketed):
        state = init_train_state(
            model, IN_SHAPE, tx, topo, "eventgrad", CFG, seed=0,
            arena=True, bucketed=bucketed or 1,
        )
        step = make_train_step(
            model, tx, topo, "eventgrad", event_cfg=CFG, arena=True,
            fused_sgd=(0.05, 0.9), bucketed=bucketed,
        )
        return state, jax.jit(spmd(step, topo))

    s_m, lift_m = build(None)
    s_b, lift_b = build(2)
    s_m, _ = _run(s_m, lift_m, batches)
    s_b, _ = _run(s_b, lift_b, batches)
    for x, y in zip(jax.tree.leaves(s_m.params),
                    jax.tree.leaves(s_b.params)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    for bm, bb in zip(_flat_bufs(s_m.event.bufs),
                      _flat_bufs(s_b.event.bufs)):
        np.testing.assert_array_equal(bm, bb)


def test_resume_across_layout_change_fails_loudly(tmp_path):
    """EventState buffers are carried per-bucket under the bucketed
    schedule: resuming a monolithic snapshot with --bucketed (or a
    bucketed snapshot monolithically) must fail LOUDLY, never corrupt
    state."""
    from eventgrad_tpu.train.loop import train

    x, y = synthetic_dataset(64, IN_SHAPE, seed=3)
    common = dict(
        algo="eventgrad", epochs=1, batch_size=4, event_cfg=CFG, seed=0,
        log_every_epoch=False, save_every=1,
    )
    d1 = str(tmp_path / "mono")
    train(MLP(**MODEL), Ring(N_RANKS), x, y, checkpoint_dir=d1, **common)
    with pytest.raises(RuntimeError, match="bucketed"):
        train(MLP(**MODEL), Ring(N_RANKS), x, y, checkpoint_dir=d1,
              resume=True, bucketed=2, **{**common, "epochs": 2})
    d2 = str(tmp_path / "bucketed")
    train(MLP(**MODEL), Ring(N_RANKS), x, y, checkpoint_dir=d2,
          bucketed=2, **common)
    with pytest.raises(Exception):
        train(MLP(**MODEL), Ring(N_RANKS), x, y, checkpoint_dir=d2,
              resume=True, **{**common, "epochs": 2})


def test_train_level_bucketed_history_parity():
    """train(bucketed=K) reproduces the monolithic run's history on
    every shared numeric field, carries `buckets` and the per-bucket
    wire split, and a same-K resume round-trips."""
    from eventgrad_tpu.train.loop import train

    x, y = synthetic_dataset(64, IN_SHAPE, seed=1)
    common = dict(
        algo="eventgrad", epochs=2, batch_size=4, event_cfg=CFG, seed=0,
        log_every_epoch=False,
    )
    s_m, h_m = train(MLP(**MODEL), Ring(N_RANKS), x, y, **common)
    s_b, h_b = train(MLP(**MODEL), Ring(N_RANKS), x, y, bucketed=2,
                     **common)
    for x_, y_ in zip(jax.tree.leaves(s_m.params),
                      jax.tree.leaves(s_b.params)):
        np.testing.assert_array_equal(np.asarray(x_), np.asarray(y_))
    for rm, rb in zip(h_m, h_b):
        assert rb["buckets"] == 2
        split = rb["sent_bytes_wire_real_per_bucket"]
        assert len(split) == 2
        assert sum(split) == pytest.approx(
            rb["sent_bytes_wire_real_per_step_per_chip"]
        )
        for k in ("loss", "train_acc", "num_events", "num_deferred",
                  "msgs_saved_pct", "fired_frac",
                  "sent_bytes_per_step_per_chip",
                  "sent_bytes_wire_real_per_step_per_chip"):
            assert rm[k] == rb[k], k
