"""The declared-kernel registry: rank-dim signatures for opaque kernels.

`pallas_call` (and any future custom-call) is an opaque boundary to the
rank-isolation dataflow (analysis/rankflow.py): the abstract interpreter
cannot look through the kernel body's ref semantics to prove the rank
axis is treated pointwise.  Soundness therefore demands an EXPLICIT
trust declaration: a kernel may appear in the audited step only if it
is registered here with a rank-dim signature, and rankflow checks every
call site against that signature —

  * every rank-carrying operand must carry the rank axis at the
    signature's `lifted_dim` (the grid dim vmap prepends when it
    batches a `pallas_call`), un-merged (no blocked/folded layout);
  * every output inherits the rank axis at `lifted_dim` and must be
    shaped `n_ranks` there;
  * an UNREGISTERED kernel is a violation, always — even on
    rank-invariant operands.  A new kernel must be reviewed for
    rank-pointwise semantics and declared, not waved through.

Registering a kernel is a reviewed claim, not a formality: by adding an
entry you assert the kernel body never indexes across the lifted grid
dim (its BlockSpec index maps pass the batch grid index straight
through).  docs/ANALYSIS.md "Registering a kernel" has the checklist.

The registry is also the source of truth for the
`pallas-kernel-registered` AST lint (analysis/lint.py): every
`pl.pallas_call` site in the package must reference a registered kernel
function, and every entry must still name a real call site (stale
entries flag).  Entries are keyed by the KERNEL FUNCTION's name — the
name `pallas_call` carries in the traced jaxpr (`name_and_src_info`),
modulo the `_batched` suffixes vmap appends.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

#: suffix vmap's pallas batching rule appends to the traced kernel name
#: (once per nested vmap level)
_BATCH_SUFFIX = "_batched"


@dataclasses.dataclass(frozen=True)
class KernelSig:
    """Rank-dim signature of one declared kernel.

    `name` — the kernel function's name (jaxpr `name_and_src_info`).
    `module` — where the kernel lives (docs + the lint's cross-check).
    `lifted_dim` — the array dim every rank-carrying operand/output
    must carry the rank coordinate at under the vmap lift (the
    prepended batch-grid dim; 0 for every kernel we ship).
    `reviewed` — one line recording WHY the kernel is rank-pointwise.
    """

    name: str
    module: str
    lifted_dim: int = 0
    reviewed: str = ""


#: the declared kernels.  First entries (ISSUE 12): the FlashAttention
#: family (ops/attention.py — also the kernels parallel/ring_attention.py
#: runs per hop under use_flash=True) and the arena/event engines.
REGISTRY: Dict[str, KernelSig] = {}


def register(sig: KernelSig) -> KernelSig:
    if sig.name in REGISTRY:
        raise ValueError(f"kernel {sig.name!r} already registered")
    REGISTRY[sig.name] = sig
    return sig


for _sig in (
    KernelSig(
        "_fwd_kernel", "eventgrad_tpu/ops/attention.py",
        reviewed="flash fwd: grid (B,H,nQ,nK); B carries the lifted batch "
        "straight through every BlockSpec index map — no cross-batch read "
        "(ring_attention's use_flash hop runs this same kernel per hop)",
    ),
    KernelSig(
        "_dq_kernel", "eventgrad_tpu/ops/attention.py",
        reviewed="flash bwd dQ: same (B,H,·,·) grid discipline as _fwd_kernel",
    ),
    KernelSig(
        "_dkv_kernel", "eventgrad_tpu/ops/attention.py",
        reviewed="flash bwd dK/dV: same (B,H,·,·) grid discipline as "
        "_fwd_kernel",
    ),
    KernelSig(
        "_kernel", "eventgrad_tpu/ops/fused_update.py",
        reviewed="fused mix+SGD: 1-D row grid over the padded flat arena; "
        "index map i -> (i, 0) never crosses rows of the lifted dim",
    ),
    KernelSig(
        "_commit_kernel", "eventgrad_tpu/ops/arena_update.py",
        reviewed="bucketed commit+mix+SGD tail: 1-D row grid, pointwise "
        "row blocks",
    ),
    KernelSig(
        "_carrier_commit_kernel", "eventgrad_tpu/ops/arena_update.py",
        reviewed="carrier-resident commit+mix+SGD tail: same 1-D row "
        "grid and index map i -> (i, 0) as _commit_kernel; the in-"
        "kernel dequant (carrier select * committed scale) is strictly "
        "elementwise within a row block",
    ),
    KernelSig(
        "_mask_kernel", "eventgrad_tpu/ops/event_engine.py",
        reviewed="masked-wire build: 1-D row grid, per-row select",
    ),
    KernelSig(
        "_mask_quant_kernel", "eventgrad_tpu/ops/event_engine.py",
        reviewed="masked-wire build + int8 quantize: 1-D row grid, "
        "per-row select/scale",
    ),
):
    register(_sig)


def base_name(traced_name: str) -> str:
    """Strip the `_batched` suffix(es) vmap's pallas batching rule
    appends, recovering the registry key."""
    while traced_name.endswith(_BATCH_SUFFIX):
        traced_name = traced_name[: -len(_BATCH_SUFFIX)]
    return traced_name


def lookup(traced_name: str) -> Optional[KernelSig]:
    """Signature for a jaxpr-traced kernel name, or None if undeclared."""
    return REGISTRY.get(base_name(traced_name))


def registered_names() -> Tuple[str, ...]:
    return tuple(sorted(REGISTRY))
