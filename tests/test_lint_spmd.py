"""Tier-1 lint: the shard_map skip-pattern must not spread.

Some CPU-only environments run a jax without `jax.shard_map`, where the
SEED's shard_map tests fail outright (the known pre-existing tier-1
failures). Every test added SINCE skips instead — through the ONE
`requires_shard_map` marker in tests/_spmd.py, so the condition and the
reason string live in a single place while ROADMAP Open item 1
(real-mesh SPMD: retire the single-chip vmap lift) is pending. This
lint walks the test tree and enforces it:

  * a test file that touches `shard_map` must import the shared marker
    (no hand-rolled `pytest.mark.skipif(not hasattr(jax, "shard_map"))`
    copies — ~10 of those accumulated across PRs 2-6 before the
    consolidation);
  * the three SEED files are exempt BY NAME: their shard_map tests
    predate the helper and intentionally FAIL (not skip) in
    shard_map-less environments — they are the recorded tier-1
    baseline, and converting them would silently move it.
"""

import os
import re

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))

#: the seed's shard_map test files: the pre-existing tier-1 baseline
#: failures in shard_map-less environments. Frozen — new entries mean
#: new un-skipped debt, which is exactly what this lint exists to stop.
SEED_EXEMPT = {
    "test_collectives.py",
    "test_ring_attention.py",
    "test_train_equivalence.py",
}

_IMPORT_RE = re.compile(
    r"^\s*from\s+_spmd\s+import\s+.*\brequires_shard_map\b", re.MULTILINE
)
# a hand-rolled respelling: a skipif whose condition mentions shard_map
# (the helper file itself holds the one allowed instance)
_RESPELL_RE = re.compile(r"skipif\s*\([^)]*shard_map", re.DOTALL)


def _test_files():
    this = os.path.basename(__file__)
    for name in sorted(os.listdir(TESTS_DIR)):
        if name == this:  # the lint's own docstrings quote the patterns
            continue
        if name.startswith("test_") and name.endswith(".py"):
            with open(os.path.join(TESTS_DIR, name)) as f:
                yield name, f.read()


def test_shard_map_tests_use_shared_marker():
    """Any non-seed test file touching shard_map imports the single
    `requires_shard_map` definition from tests/_spmd.py."""
    offenders = [
        name
        for name, src in _test_files()
        if "shard_map" in src
        and name not in SEED_EXEMPT
        and not _IMPORT_RE.search(src)
    ]
    assert not offenders, (
        f"{offenders} touch shard_map without importing the shared "
        "`requires_shard_map` marker from tests/_spmd.py (ROADMAP Open "
        "item 1); add `from _spmd import requires_shard_map` instead of "
        "re-spelling the skipif"
    )


def test_no_respelled_shard_map_skipif():
    """Nobody — seed files included — re-spells the skipif condition:
    the definition lives in tests/_spmd.py and nowhere else."""
    offenders = [
        name for name, src in _test_files() if _RESPELL_RE.search(src)
    ]
    assert not offenders, (
        f"{offenders} re-spell the shard_map skipif; use "
        "`requires_shard_map` from tests/_spmd.py (single definition, "
        "single reason string)"
    )


def test_seed_exemption_list_matches_reality():
    """The exemption list stays honest: every exempt file still exists
    and still touches shard_map (a renamed/retired file must leave the
    list, or the lint silently covers nothing)."""
    for name in sorted(SEED_EXEMPT):
        path = os.path.join(TESTS_DIR, name)
        assert os.path.exists(path), f"exempt file {name} no longer exists"
        with open(path) as f:
            assert "shard_map" in f.read(), (
                f"exempt file {name} no longer touches shard_map — drop "
                "it from SEED_EXEMPT"
            )
