"""Epoch driver: scan-compiled training, consensus, and evaluation.

Mirrors the reference's shared skeleton (epoch loop -> batch loop -> comm ->
step -> accuracy, e.g. /root/reference/dmnist/event/event.cpp:269-500) but
compiles the *entire epoch* as one `lax.scan` over steps, so the TPU runs
back-to-back fused steps with no host round-trips; per-epoch metrics come
back as stacked arrays. Host batch assembly for epoch E+1 overlaps epoch
E's device compute via `data.prefetch.EpochPrefetcher` (native shard-plan
+ memcpy gathers on a background thread).

End-of-training consensus: the reference allreduce-averages parameters and
lets rank 0 evaluate (event.cpp:517-525). Here `consensus_params` means over
the stacked rank axis — numerically the same reduction.
"""

from __future__ import annotations

import contextlib
import functools
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from eventgrad_tpu.chaos import crashpoint
from eventgrad_tpu.chaos import integrity as chaos_integrity
from eventgrad_tpu.chaos import membership as chaos_membership
from eventgrad_tpu.chaos import monitor as chaos_monitor
from eventgrad_tpu.chaos import schedule as chaos_schedule
from eventgrad_tpu.chaos.policy import RecoveryPolicy
from eventgrad_tpu.obs import OBS_MODES
from eventgrad_tpu.obs import device as obs_device
from eventgrad_tpu.obs import ledger as obs_ledger
from eventgrad_tpu.data.prefetch import EpochPrefetcher
from eventgrad_tpu.data.sharding import epoch_index_plan, epoch_steps
from eventgrad_tpu.ops import arena_tuning
from eventgrad_tpu.parallel import arena as arena_lib
from eventgrad_tpu.parallel import collectives, multihost
from eventgrad_tpu.parallel import policy as policy_lib
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.sparsify import SparseConfig
from eventgrad_tpu.parallel.spmd import resolve_backend, spmd, stack_for_ranks
from eventgrad_tpu.parallel.topology import Topology
from eventgrad_tpu.data.sharding import expand_to_mesh
from eventgrad_tpu.train.state import init_train_state, init_train_state_spmd
from eventgrad_tpu.train.steps import make_train_step
from eventgrad_tpu.utils import checkpoint, trees
from eventgrad_tpu.utils.metrics import msgs_saved_pct


@jax.jit
def consensus_params(stacked_params: Any) -> Any:
    """Average the per-rank models into the final consensus model.

    jit: one dispatch for the whole tree — eagerly this is one tunnel
    round-trip per leaf (86 for the ResNet, ~0.4 s each over axon).
    """
    return jax.tree.map(lambda x: jnp.mean(x, axis=0), stacked_params)


@jax.jit
def rank0_slice(tree: Any) -> Any:
    """Rank 0's slice of a stacked pytree, as one compiled dispatch (the
    eager per-leaf `x[0]` costs a tunnel round-trip per leaf)."""
    return jax.tree.map(lambda x: x[0], tree)


@jax.jit
def _device_copy(tree: Any) -> Any:
    """On-device copy into FRESH buffers (one dispatch). The pipelined
    loop uses it to capture post-block state (telemetry counters) before
    the next `run_epoch` dispatch donates the originals — an explicit
    HLO copy, because a jitted identity may alias the input buffers."""
    return jax.tree.map(jnp.copy, tree)


def _snapshot_async_depth(raw: Any) -> int:
    """The bounded-async queue depth D a peeked snapshot was written
    with (0 = no per-edge delivery queues, i.e. staleness <= 1) —
    inferred from the leaf paths, so it works on the template-free
    orbax restore regardless of container kinds. The slot index is the
    2nd path component under pending/ for BOTH queue owners:
    eventgrad's EventState.pending and sp_eventgrad's
    SparseState.pending payload queues."""
    import re as _re

    from eventgrad_tpu.utils.checkpoint import _path_name

    slots = set()
    for kp, _ in jax.tree_util.tree_flatten_with_path(raw)[0]:
        m = _re.match(
            r"state/(?:event|sparse)/pending/\d+/(\d+)/", _path_name(kp)
        )
        if m:
            slots.add(int(m.group(1)))
    return max(slots) + 1 if slots else 0


def _snapshot_bucket_count(raw: Any) -> int:
    """The bucket count K a peeked snapshot's EventState receive
    buffers were written with (1 = monolithic flat arena or tree
    layout) — inferred from the leaf paths: per-bucket buffers are
    per-neighbor TUPLES, so the 2nd component under bufs/ is a numeric
    bucket index; monolithic flat bufs are leaves at bufs/{i} (no 2nd
    component) and tree-layout bufs have non-numeric module names
    there. Lets a cross-K resume fail with the cause named BEFORE the
    structural restore produces an unhelpful treedef mismatch."""
    import re as _re

    from eventgrad_tpu.utils.checkpoint import _path_name

    buckets = set()
    for kp, _ in jax.tree_util.tree_flatten_with_path(raw)[0]:
        m = _re.match(r"state/event/bufs/\d+/(\d+)(?:/|$)", _path_name(kp))
        if m:
            buckets.add(int(m.group(1)))
    return max(buckets) + 1 if buckets else 1


def _snapshot_resident_wire(raw: Any) -> Optional[str]:
    """The carrier dtype a peeked snapshot's EventState receive buffers
    were written in ('bf16' | 'int8'; None = f32-resident / no event
    buffers) — read from the bufs leaf dtypes on the template-free
    orbax restore, because a cross-resident restore would otherwise be
    structurally legal: the buffer SHAPES match, and the path graft
    silently casts same-shape leaves (utils/checkpoint.py)."""
    import re as _re

    from eventgrad_tpu.utils.checkpoint import _path_name

    for kp, leaf in jax.tree_util.tree_flatten_with_path(raw)[0]:
        if _re.match(r"state/event/bufs/", _path_name(kp)):
            dt = str(getattr(leaf, "dtype", ""))
            return {"int8": "int8", "bfloat16": "bf16"}.get(dt)
    return None


def _loss_record(pass_base: int, s_i: int, r: int,
                 loss_all: np.ndarray) -> Dict[str, Any]:
    """Per-(pass, rank) loss record — the shared schema of the send trace's
    train{r}.txt rider and the non-event values{r}.txt stream."""
    return {
        "pass": pass_base + s_i + 1,
        "rank": r,
        "loss": round(float(loss_all[s_i, r]), 6),
    }


def _write_trace(path: str, m: Dict[str, np.ndarray], pass_base: int,
                 topo: Topology, state, carry: Dict[str, np.ndarray]) -> None:
    """Append the reference's file_write=1 instrumentation as JSONL.

    Send side (send{r}.txt, event.cpp:337-339,385-391): one record per
    (pass, rank) with per-parameter norm/thres/fired vectors in leaf-major
    order, plus the step's train loss (= train{r}.txt, the per-step loss
    file of dcifar10/event/event.cpp:271-273). Receive side (recv{r}.txt, event.cpp:418-425,446-461): one record
    per (pass, rank, neighbor) with the received-buffer norm and a changed
    bit — here derived deterministically from the sender's fire bit, with
    `carry` holding the stale norm between messages (the buffers start as
    zeros, like the reference's window, event.cpp:177-179). A header record
    names the parameter leaves and neighbor directions on first write."""
    n_ranks = topo.n_ranks
    fired_all = np.asarray(m["trace_fired"])
    norm_all = np.asarray(m["trace_norm"])
    thres_all = np.asarray(m["trace_thres"])
    loss_all = np.asarray(m["loss"])
    specs = topo.neighbors
    last = carry["recv_norm"]
    srcs = [
        [topo.neighbor_source(r, nb) for r in range(n_ranks)] for nb in specs
    ]
    first = not os.path.exists(path) or os.path.getsize(path) == 0
    with open(path, "a") as tf:
        if first:
            names = [
                "/".join(str(getattr(p, "key", p)) for p in kp)
                for kp, _ in jax.tree_util.tree_flatten_with_path(state.params)[0]
            ]
            tf.write(json.dumps({
                "trace_params": names,
                "trace_neighbors": [nb.name for nb in specs],
            }) + "\n")
        steps = fired_all.shape[0]
        for s_i in range(steps):
            for r in range(n_ranks):
                rec = _loss_record(pass_base, s_i, r, loss_all)
                rec.update(
                    norm=[round(float(v), 6) for v in norm_all[s_i, r]],
                    thres=[round(float(v), 6) for v in thres_all[s_i, r]],
                    fired=[int(v) for v in fired_all[s_i, r]],
                )
                tf.write(json.dumps(rec) + "\n")
            for k, nb in enumerate(specs):
                for r in range(n_ranks):
                    src = srcs[k][r]
                    ch = fired_all[s_i, src]
                    last[k, r] = np.where(ch, norm_all[s_i, src], last[k, r])
                    tf.write(
                        json.dumps(
                            {
                                "pass": pass_base + s_i + 1,
                                "rank": r,
                                "recv": nb.name,
                                "changed": [int(v) for v in ch],
                                "norm": [round(float(v), 6) for v in last[k, r]],
                            }
                        )
                        + "\n"
                    )


class DeviceEvaluator:
    """Rank-0-style test pass (event.cpp:535-586) as ONE jitted device scan.

    The legacy `evaluate` ran a host loop of per-batch forward dispatches
    with numpy reductions — dozens of dispatch round-trips and a blocking
    readback per batch, all sitting on the training loop's critical path
    at block ends. Here the whole test set lives on device (uploaded
    once) and the pass is a single `lax.scan` over batches returning two
    scalars (correct count, summed NLL), so the loop can DISPATCH the
    eval at a block end and read the two scalars back a block later (the
    dispatch pipeline, docs/ARCHITECTURE.md "The dispatch pipeline").
    `dispatch()` enqueues and returns futures; `result()` blocks and
    renders the {"accuracy", "loss"} dict. Serial and pipelined callers
    share this one implementation, so eval numbers are mode-independent.
    """

    def __init__(self, model, x, y, batch_size: int = 1000):
        x = np.asarray(x)
        y = np.asarray(y)
        # legacy truncation rule: whole batches only, unless the set is
        # smaller than one batch (then a single short batch)
        bs = batch_size if len(x) >= batch_size else len(x)
        n = (len(x) // bs) * bs
        s = n // bs
        self._x = jnp.asarray(
            np.ascontiguousarray(x[:n]).reshape((s, bs) + x.shape[1:])
        )
        self._y = jnp.asarray(
            np.ascontiguousarray(y[:n]).reshape((s, bs) + y.shape[1:]),
            dtype=jnp.int32,
        )
        # targets: batch elements, or batch x tokens for LM label grids
        self._n_targets = int(
            n * int(np.prod(y.shape[1:], dtype=np.int64) or 1)
        )

        def run(variables, xs, ys):
            def body(carry, batch):
                xb, yb = batch
                out = model.apply(variables, xb, train=False)
                if out.ndim == 3:  # LM logits [B, T, V]: score per token
                    out = out.reshape(-1, out.shape[-1])
                    yb = yb.reshape(-1)
                logp = jax.nn.log_softmax(out.astype(jnp.float32), axis=-1)
                nll = -jnp.take_along_axis(
                    logp, yb[:, None], axis=-1
                ).sum()
                correct = (out.argmax(-1) == yb).sum().astype(jnp.int32)
                return (carry[0] + correct, carry[1] + nll), None

            init = (jnp.zeros((), jnp.int32), jnp.zeros((), jnp.float32))
            (correct, nll), _ = jax.lax.scan(body, init, (xs, ys))
            return correct, nll

        self._run = jax.jit(run)

    def dispatch(self, params, batch_stats):
        """Enqueue the test pass; returns (correct, nll) device futures."""
        variables = {"params": params}
        if batch_stats is not None and jax.tree.leaves(batch_stats):
            variables["batch_stats"] = batch_stats
        return self._run(variables, self._x, self._y)

    def result(self, fut) -> Dict[str, float]:
        """Blocking readback of a `dispatch` future -> metrics dict."""
        correct, nll = fut
        return {
            "accuracy": 100.0 * int(correct) / self._n_targets,
            "loss": float(nll) / self._n_targets,
        }


def evaluate(model, params, batch_stats, x, y, batch_size: int = 1000) -> Dict[str, float]:
    """One-shot test pass — builds a `DeviceEvaluator` and runs it
    synchronously (callers that eval repeatedly should hold the
    evaluator: the jit and the device-resident test set are reused)."""
    ev = DeviceEvaluator(model, x, y, batch_size)
    return ev.result(ev.dispatch(params, batch_stats))


def train(
    model,
    topo: Topology,
    x_train: np.ndarray,
    y_train: np.ndarray,
    *,
    algo: str = "dpsgd",
    epochs: int = 2,
    batch_size: int = 64,
    learning_rate: float = 0.05,
    momentum: float = 0.0,
    event_cfg: Optional[EventConfig] = None,
    sparse_cfg: Optional[SparseConfig] = None,
    augment: bool = False,
    random_sampler: bool = False,
    sync_bn: bool = False,
    mesh=None,
    backend: Optional[str] = None,
    seed: int = 0,
    x_test: Optional[np.ndarray] = None,
    y_test: Optional[np.ndarray] = None,
    log_every_epoch: bool = True,
    checkpoint_dir: Optional[str] = None,
    save_every: int = 0,
    resume: bool = False,
    trace_file: Optional[str] = None,
    fused_update: bool = False,
    wire_bf16: bool = False,
    wire: "Optional[str]" = None,
    gossip_wire: str = "dense",
    compact_frac: Optional[float] = None,
    staleness: int = 0,
    fault_inject: Optional[str] = None,
    chaos: Optional[Any] = None,
    chaos_policy: Optional[RecoveryPolicy] = None,
    membership: Optional[Any] = None,
    integrity: Optional[Any] = None,
    on_epoch: Optional[Any] = None,
    device_data: Optional[bool] = None,
    epochs_per_dispatch: int = 1,
    obs: str = "off",
    registry: Optional[Any] = None,
    arena: Optional[bool] = None,
    bucketed: Optional[int] = None,
    pipeline: Optional[bool] = None,
    trigger_policy: Optional[str] = None,
    carrier_resident: Optional[bool] = None,
) -> Tuple[Any, List[Dict[str, Any]]]:
    """Run the full training job; returns (final_state, per-epoch history).

    backend (None | "vmap" | "shard_map" | "auto") picks the SPMD lift
    (docs/ARCHITECTURE.md "Mesh backends"): "vmap" is the single-chip
    simulator (all ranks batched onto one device), "shard_map" the real
    device mesh — one rank per device, the gossip exchange runs as
    actual `ppermute` collectives over ICI/DCN (ROADMAP open item 1);
    "auto" takes the mesh whenever the shard_map transform and enough
    devices exist and falls back to vmap otherwise. None (the default)
    defers to the explicit `mesh` argument (parallel/spmd.build_mesh) —
    legacy wiring; `backend="shard_map"` with mesh=None builds the mesh
    itself. Training is BITWISE identical across the lifts on full
    state, metrics, and history (tests/test_mesh_parity.py,
    tests/test_cli.py::test_mesh_backend_matches_sim); every history
    record carries `rec["backend"]` so downstream consumers
    (tools/perf_ledger.py) never compare mesh rows against vmap rows.

    arena (None = auto) routes the gossip hot path through the flat
    parameter arena (parallel/arena.py + ops/event_engine.py): params,
    event wire buffers, and the mix/SGD tail run over one contiguous
    per-rank buffer with cached leaf metadata, bitwise-identical to the
    tree path (tests/test_arena.py) but without its per-step tree
    traversals. Auto enables it for dpsgd/eventgrad (the algos whose
    step consumes it) on plain data-parallel topologies with a single
    parameter dtype; `False`
    forces the legacy tree path (the A/B knob of
    tools/overhead_ablation.py). The EventState receive buffers are
    carried flat under the arena, so checkpoint layouts differ by mode:
    in auto mode a resume that hits a tree-layout (pre-arena) snapshot
    falls back to `arena=False` with a warning — old checkpoints keep
    working; with an explicit `arena=True` the cross-layout restore
    raises an actionable error instead of corrupting state. History
    records carry `rec["arena"]`.

    bucketed (None|K, auto-off for K=1) runs the event-exchange hot
    path under the BUCKETED gossip schedule (train/steps.py): the flat
    arena splits into K contiguous leaf-aligned buckets
    (parallel/arena.py ArenaSpec.buckets) and each bucket's
    gate->pack->exchange->commit->mix chain is emitted
    software-pipelined so the scheduler can overlap one bucket's
    ppermute with another's update work — bitwise-identical training
    to the monolithic path (tests/test_bucketed.py), proven the same
    way the arena was (equivalence matrix + trace audit + scanned
    median-paired A/B, tools/overhead_ablation.py bucketed). eventgrad
    (needs the arena; EventState buffers are then carried per-bucket —
    a resume across a bucketed/monolithic layout change fails loudly)
    and sp_eventgrad (per-leaf exchange grouped by bucket, state
    unchanged) only; the compact wire's capacity splits per bucket
    with bucket-local deferral (collectives.split_capacity,
    docs/compaction.md); not combinable with the integrity engine or
    chaos bitflips; with fused_update the per-bucket kernel tail needs
    a measured ops/arena_tuning entry (bench_kernels.py bucketed) —
    unmeasured backends demote to the monolithic fused path with a
    warning. History records carry `buckets` and
    `sent_bytes_wire_real_per_bucket`.

    carrier_resident (None = off) keeps the EventState receive buffers
    CARRIER-RESIDENT: stored in the wire dtype (wire='bf16'/'int8')
    with per-leaf int8 dequant scales in EventState.buf_scales, the
    dequant fused into the commit/mix reads (train/steps.py) — bitwise
    the f32-resident run (the f32 buffers only ever held exactly
    dequant(carrier)) at 1-2 B/elem of buffer traffic instead of 4.
    eventgrad + arena + bf16/int8 wire + staleness <= 1 only; not
    combinable with the integrity engine or chaos bitflip=/nanstep=
    faults; sp_eventgrad accepts True as a documented no-op. The
    resident dtype is CHECKPOINT layout: resuming a carrier snapshot
    into an f32-resident run (or vice versa, or across wire dtypes)
    fails loudly, both directions. History records carry
    `rec["resident_dtype"]`; tools/overhead_ablation.py resident is
    the A/B proof instrument.

    staleness (0 | 1 | D >= 2) picks the exchange's asynchrony model
    (train/steps.py): 0 mixes this pass's exchange, 1 the previous
    pass's (the deterministic RMA model), and D >= 2 runs the
    BOUNDED-ASYNC gossip engine — per-edge delivery queues carried in
    EventState (depth D), chaos `lag=`/`slow=` clauses delivering
    messages late with commit-on-arrival, a rank running up to D
    passes ahead of a late neighbor (docs/chaos.md "Bounded-async
    gossip & stragglers"). eventgrad + arena only; not combinable with
    bucketed/fused_update/trace_file. The queue depth is part of the
    checkpoint layout: resuming a D-clock snapshot into a run with a
    different D fails loudly, both directions. History records gain
    `staleness`, `edge_staleness_max`, and `late_commits`;
    tools/straggler_ablation.py is the proof instrument.

    With `checkpoint_dir`, the full gossip TrainState (+ epoch counter) is
    snapshotted every `save_every` epochs (always at the end); `resume=True`
    restores the latest snapshot and continues from its epoch — the elastic
    story the reference lacks entirely (a dead MPI rank just hangs it,
    decent.cpp:200-205).

    fused_update=True routes the gossip-mix + SGD tail of each step through
    the Pallas fused kernel (ops/fused_update.py) — one HBM read/write per
    parameter element. Gossip algorithms only (allreduce keeps optax).

    fault_inject ("crash:N" or "hang:N") kills or wedges the process right
    after epoch N's work (post-snapshot) — the fault-injection half of the
    elastic-recovery story (eventgrad_tpu/supervise.py); the reference has neither
    (a dead rank just hangs its peers' MPI_Recv, decent.cpp:200-205).

    chaos (a chaos.ChaosSchedule, spec string like "drop=0.2,seed=7", or
    serialized dict) injects deterministic message loss INSIDE the gossip
    step — the network-fault counterpart of fault_inject's process faults;
    chaos_policy (chaos.RecoveryPolicy) adds receiver-side forced-sync /
    edge-freeze recovery. History records gain per-edge silence maxima,
    injected-drop counts, and a consensus-error probe at dispatch-block
    ends; the first record carries the serialized schedule so the run is
    replayable from its log alone. See docs/chaos.md.

    Preemption & crash drills (chaos/crashpoint.py, docs/chaos.md
    "Preemption & crash consistency"): with a checkpoint_dir on the
    single-process path, SIGTERM/SIGINT request a GRACEFUL drain — at
    the next dispatch-block boundary the loop drains the pipeline,
    joins the async writer, force-snapshots, writes a PREEMPTED marker,
    and raises chaos.GracefulPreemption (the CLI exits
    exitcodes.PREEMPTED_EXIT; the supervisor relaunches immediately
    without charging its restart budget) — so a preemption replays at
    most the one block that was in flight. The chaos clause
    `preempt=EPOCH@STEP` is the deterministic, replayable twin of the
    signal. Independently, EG_CRASHPOINT=site[:hit_n] arms a seeded
    HARD kill at one named state-mutating seam (checkpoint swap, writer
    thread, block boundaries, bootstrap stream, rollback-restore);
    tools/crash_matrix.py kills at every site under every configuration
    and proves bitwise resume. With nothing armed and no signal
    delivered, the traced step and history are bit-identical to a
    drill-free build (the armed run's first record carries a
    `crashpoint` rider).

    membership (a chaos.MembershipSchedule, spec string like
    "leave=1@3,join=1@5", or serialized dict — also liftable from a
    chaos spec's join=/leave= clauses) runs the run under the ELASTIC
    membership engine (chaos/membership.py): at the end of each named
    epoch (a dispatch-block boundary — membership pins one-epoch blocks,
    so the fused step never sees a dynamic shape) a rank leaves (ring
    heal generalized to a clean N->N-1 re-slice) or a newcomer joins
    (N->N+1: its full gossip TrainState row bootstraps from a neighbor's
    snapshot streamed through utils/checkpoint.host_snapshot +
    AsyncWriter — on disk under `<checkpoint_dir>/bootstrap` when a
    checkpoint_dir exists, in host memory otherwise; bitwise either
    way), and the next pass force-fires every exchange so stale buffers
    refresh in one cycle. The data shards, step program, and prefetcher
    rebuild for the new rank count (one extra jit compile per
    transition). Deterministic and replayable: the schedule rides the
    first history record (like chaos), and replaying it reproduces the
    final state bitwise. Every record carries `active_ranks`; the record
    after a transition carries `membership_transitions`. Single-process
    plain-ring gossip runs only (dpsgd/eventgrad, mesh=None, no
    device_data/trace_file; pipeline forced off — transitions mutate
    state between blocks). See docs/chaos.md "Membership & elasticity".

    integrity (a chaos.IntegrityConfig, "on"/"off", or serialized dict)
    arms the integrity engine (chaos/integrity.py, docs/chaos.md
    "Integrity & rollback"). The IN-STEP defenses — wire checksums on
    every gossip payload (a failed check is an event that did not fire)
    and non-finite quarantine (a rank whose grads go NaN/Inf skips its
    update and suppresses its sends) — ride the fused step
    (algo="eventgrad"; they compose with the pipeline). The HOST-SIDE
    engine — the `DivergenceSentinel` judging every dispatch block's
    mean loss and consensus-error probe, and the rollback that restores
    all ranks from the retained last-known-good snapshot on a trip —
    rides the block drain and forces the serial schedule (like
    membership: a tripped block must not cascade into an already-
    dispatched successor). Rollback: the loop retains a host-memory
    last-known-good snapshot after every HEALTHY block (plus validated
    on-disk rolling retention under `<checkpoint_dir>/good` via
    utils/checkpoint.RollingRetention when a checkpoint_dir exists); on
    trip it restores that snapshot, re-arms every event buffer through
    the membership engine's force_refresh (all wires rewire in one fire
    cycle), HARDENS the step (escalate=True: checksums + quarantine on,
    one recompile) and replays — deterministically, so the whole run
    (faults, trip, rollback, replay) is bitwise-reproducible from the
    seed. A trip beyond max_rollbacks (or with rollback disabled)
    raises chaos.IntegrityEscalation — the CLI exits
    INTEGRITY_ABORT_EXIT and the supervisor gives up WITHOUT a restart.
    History records gain wire_rejects / quarantined_steps /
    integrity_rollbacks; the first record carries the serialized config
    (replayability, like `chaos`), and the first record after a
    rollback carries `integrity_rollback` (reason, epochs, hardened).

    gossip_wire="compact" (eventgrad only) switches the exchange to the
    budgeted compacted wire (collectives.compact_neighbor_vals) once
    warmup is over: the loop runs the dense masked path through the
    warmup passes (fire-everything would blow any budget), observes the
    post-warmup fired sizes, picks a STATIC capacity with
    collectives.choose_capacity (or honors an explicit `compact_frac` of
    the parameter count), and rebuilds the step once — capacity never
    changes again, so there is exactly one extra jit compile and zero
    recompile churn. History records carry `gossip_wire`,
    `compact_capacity`, and `sent_bytes_wire_real_per_step_per_chip` (the
    bytes the collective actually moves — see docs/compaction.md). If the
    observed fire rate leaves nothing to compact (capacity would reach
    the full model), the run stays dense and says so in the record.
    With a capacity-FREE compact wire (sp_eventgrad: the top-k lanes
    are already statically sized) "compact" is accepted as a no-op
    alias of the native wire — no warmup phase, no autotune, no
    rebuild; records still carry `gossip_wire: "compact"`.

    trigger_policy names a registered TriggerPolicy
    (parallel/policy.py): "norm_delta" (eventgrad's default — the
    EventGraD trigger, bitwise the pre-policy path), "topk"
    (sp_eventgrad's default), "micro" (rotating owned-partition sends,
    index-free — MiCRO arXiv:2310.00967 adapted to gossip), or
    "hybrid" (norm-delta gate x owned partition). None runs the algo's
    default. Event-algo history records carry `rec["policy"]`; the
    compact-wire guards above consult the policy's WireSpec. See
    docs/ARCHITECTURE.md "Trigger policies".

    device_data=True uploads the full (cast) dataset to the device ONCE and
    ships only the per-epoch permutation index plan ([n_ranks, steps, batch]
    int32, ~KBs) per dispatch; batches are gathered on-device inside the
    scan. One epoch's stacked batch tensor is the same bytes as the whole
    dataset (an epoch is one full pass), so this removes ~all recurring H2D
    traffic — the opposite end of the spectrum from the reference's
    per-element item() marshalling (decent.cpp:183-189). None = auto:
    enabled on TPU for single-process non-hybrid runs with datasets under
    ~1.5 GB. Identical trajectories to the host path (same index plans,
    same gather — tests/test_dispatch_modes.py).

    obs ("off" | "block" | "epoch") turns on the on-device telemetry
    accumulators (obs.device.TelemetryState: per-leaf fire/deferral
    counts, threshold and drift-norm trajectories, silence histograms,
    per-edge wire-real bytes). Counters are cumulative in the scan-carried
    state and flushed to host at most ONCE per jit-dispatch block (the
    host diffs consecutive snapshots — zero added per-step host syncs and
    no device-side reset); the flush-window summary rides the block-end
    history record as `rec["obs"]` (schema: docs/OBSERVABILITY.md).
    "epoch" additionally pins epochs_per_dispatch to 1 so every epoch IS
    a block end — per-epoch telemetry at the cost of per-epoch dispatch.
    "off" is the default and leaves the traced step bit-identical to a
    telemetry-free build. Block ends also probe the consensus error
    (single-process, non-hybrid runs), chaos-style.

    registry (an obs.Registry) additionally records host span traces of
    the loop's own phases — dispatch blocks, eval, checkpoint, telemetry
    flush — exportable as Chrome-trace/Perfetto JSON
    (Registry.write_chrome_trace). The loop never closes the registry;
    the caller owns its lifecycle (cli.py wires --obs-dir).

    pipeline (None = auto) software-pipelines the block loop: block B+1's
    scan is dispatched IMMEDIATELY after block B's, and block B's host
    work — telemetry flush, history records, eval readback, checkpoint
    serialization — runs while the device computes B+1, instead of the
    serial block_until_ready -> flush -> eval -> checkpoint chain. The
    eval is dispatched on-device at block end (DeviceEvaluator) with its
    two-scalar readback deferred one block; checkpoints snapshot
    device->host eagerly and serialize on a background writer thread
    (utils/checkpoint.AsyncWriter, join barrier before the next save and
    on exit). Training state and history metrics are BITWISE-identical
    with the pipeline on or off (tests/test_dispatch_pipeline.py) — the
    dispatch order of the training scans is unchanged; only the host
    schedule moves. wall_s stays meaningful: it measures dispatch (or
    previous-block readiness) to this block's observed readiness, i.e.
    back-to-back device time when the pipe is full. Auto enables it for
    single-process runs without fault_inject (a fault must land at an
    exact post-snapshot epoch boundary, which requires the serial
    schedule; multi-process keeps serial collective/checkpoint
    ordering); explicit True raises on those. During a compact-wire
    run's dense autotune phase the loop drains eagerly (the capacity
    decision gates the next dispatch) and pipelining starts once the
    capacity is fixed. See docs/ARCHITECTURE.md "The dispatch pipeline".

    epochs_per_dispatch=K fuses K consecutive epochs into ONE jit dispatch
    (the scan simply runs K*steps steps), amortizing the per-dispatch host
    and tunnel latency by K. Metrics come back stacked and are split into
    per-epoch history records on the host; consensus/eval runs at block
    ends (every K epochs), checkpoints still land exactly on `save_every`
    boundaries (blocks are split there). fault_inject forces K=1 (the
    fault must land at an exact epoch boundary).
    """
    # mesh-backend resolution (parallel/spmd.resolve_backend): an
    # explicit mesh wins ("auto"/"shard_map" just confirm it); a
    # backend request with no mesh builds one — "vmap" pins the
    # simulator and contradicts an explicit mesh loudly
    if backend is not None:
        if mesh is not None and backend == "vmap":
            raise ValueError(
                "backend='vmap' contradicts an explicit mesh= argument; "
                "drop one of them"
            )
        if mesh is None:
            mesh = resolve_backend(backend, topo)
    backend_name = "shard_map" if mesh is not None else "vmap"
    if gossip_wire not in ("dense", "compact"):
        raise ValueError(
            f"gossip_wire must be 'dense' or 'compact', got {gossip_wire!r}"
        )
    # trigger-policy resolution (parallel/policy.py): validates the
    # name/algo pairing up front and supplies the WireSpec every compact
    # decision below consults — the guard is registry-driven, not an
    # algo-name match (sp_eventgrad's statically-sized top-k wire takes
    # compact as a capacity-free no-op alias)
    pol = None
    if algo in policy_lib.DEFAULT_FOR_ALGO or trigger_policy is not None:
        pol = policy_lib.resolve(trigger_policy, algo)
    if gossip_wire == "compact":
        if pol is None or "compact" not in pol.wire_spec().gossip_wires:
            raise ValueError(
                "gossip_wire='compact' rides the statically-sized wire "
                "of an event trigger policy (algos: eventgrad, "
                f"sp_eventgrad); algo={algo!r} with policy "
                f"{pol.name if pol else 'none'!r} declares no compact "
                "wire (parallel/policy.py WireSpec)"
            )
    # compact needs the capacity autotune machinery only when the
    # policy's wire says so
    compact_needs_cap = (
        gossip_wire == "compact"
        and pol is not None and pol.wire_spec().compact_needs_capacity
    )
    # a capacity-free compact wire (sp_eventgrad's top-k lanes) is
    # statically sized from step 0: no dense warmup, no autotune, no
    # runner rebuild — the wire mode is "compact" for the whole run
    compact_static = gossip_wire == "compact" and not compact_needs_cap
    if compact_frac is not None and compact_static:
        raise ValueError(
            f"compact_frac sizes the capacity autotune; the "
            f"{pol.name!r} policy's compact wire is capacity-free "
            "(its top-k lanes are already statically sized)"
        )
    if compact_frac is not None:
        if gossip_wire != "compact":
            raise ValueError("compact_frac needs gossip_wire='compact'")
        if not (0.0 < float(compact_frac) <= 1.0):
            raise ValueError(
                f"compact_frac must be in (0, 1], got {compact_frac}"
            )
    if obs not in OBS_MODES:
        raise ValueError(f"obs must be one of {OBS_MODES}, got {obs!r}")
    obs_on = obs != "off"
    # span recording is a no-op without a registry (nullcontext) — the
    # loop's control flow is identical either way
    def _span(name: str, **args):
        if registry is None:
            return contextlib.nullcontext()
        return registry.span(name, **args)

    chaos_sched = chaos_schedule.resolve(chaos) if chaos is not None else None
    # --- integrity-engine resolution (chaos/integrity.py) --------------
    integ_cfg = chaos_integrity.resolve(integrity)
    # the host-side engine: sentinel judges blocks; rollback needs it
    # (a rollback can only be *requested* by a trip)
    integ_engine_on = integ_cfg is not None and integ_cfg.sentinel
    integ_rollback_on = integ_engine_on and integ_cfg.rollback
    if integ_cfg is not None:
        if (integ_cfg.checksum or integ_cfg.quarantine) and algo != "eventgrad":
            raise ValueError(
                "integrity checksums/quarantine ride the event exchange "
                f"(algo='eventgrad'); got algo={algo!r} — for the host-"
                "side sentinel alone pass IntegrityConfig(checksum="
                "False, quarantine=False)"
            )
        if integ_rollback_on and integ_cfg.escalate and algo != "eventgrad":
            raise ValueError(
                "integrity escalate=True hardens the event exchange "
                "after a rollback (checksums + quarantine on), which "
                f"needs algo='eventgrad'; got algo={algo!r} — pass "
                "escalate=False"
            )
    if integ_engine_on and (mesh is not None or multihost.is_multiprocess()):
        raise ValueError(
            "the integrity sentinel/rollback engine needs the single-"
            "process path (a rollback restores host-retained state "
            "between blocks); in-step defenses alone "
            "(IntegrityConfig(sentinel=False, rollback=False)) compose "
            "with any backend"
        )
    fault_mode, fault_epoch = None, -1
    if fault_inject:
        fault_mode, _, n = fault_inject.partition(":")
        if fault_mode not in ("crash", "hang") or not n.isdigit():
            raise ValueError(f"bad fault_inject spec {fault_inject!r}")
        fault_epoch = int(n)
    ckpt_path = os.path.join(checkpoint_dir, "ckpt") if checkpoint_dir else None
    if checkpoint_dir:
        # a PREEMPTED marker left by a drained predecessor is consumed
        # here: this incarnation supersedes it (chaos/crashpoint.py)
        crashpoint.consume_marker(checkpoint_dir)
    # armed-crashpoint rider (chaos/crashpoint.py): stamped on the run's
    # first record like the chaos schedule, so a crash-drill log names
    # the kill it survived; None (the normal case) stamps nothing
    crash_armed = crashpoint.armed()

    # --- elastic membership resolution (chaos/membership.py) -----------
    memb_sched = (
        chaos_membership.resolve(membership) if membership is not None
        else None
    )
    if chaos_sched is not None and chaos_sched.membership:
        inline = chaos_sched.membership_schedule()
        if memb_sched is not None and not memb_sched.is_noop:
            # identical events are NOT a conflict: a chaos-inline run
            # stamps both riders (rec["membership"] and the chaos dict's
            # embedded join=/leave= clauses), and a replay from its own
            # log feeds both back — tools/soak.py's replay leg does
            if memb_sched.events != inline.events:
                raise ValueError(
                    "membership events arrived both via membership= and "
                    "the chaos spec's join=/leave= clauses, and they "
                    "disagree; pass one schedule"
                )
        memb_sched = inline
    memb_on = memb_sched is not None and not memb_sched.is_noop
    memb_engine = None
    memb_raw = None  # peeked snapshot: reused by the resume restore below
    if memb_on:
        if algo not in ("dpsgd", "eventgrad"):
            raise ValueError(
                "membership transitions ride the gossip exchange "
                f"(dpsgd, eventgrad); got algo={algo!r}"
            )
        if len(topo.axes) != 1 or topo.gossip_axes != topo.axes:
            raise ValueError(
                "membership transitions handle single-axis gossip rings; "
                f"got axes {topo.axes}"
            )
        if mesh is not None or multihost.is_multiprocess():
            raise ValueError(
                "membership needs the single-process vmap path (a "
                "transition re-shapes the stacked state between blocks)"
            )
        if trace_file:
            raise ValueError(
                "trace_file carries rank-shaped recv staleness; not "
                "available under membership transitions"
            )
        if chaos_sched is not None and chaos_sched.death:
            # die= is rank-indexed INSIDE the traced step; a transition
            # re-slices the stacked rows, silently retargeting the death
            # to a different worker — use a membership leave instead
            raise ValueError(
                "chaos die= events are rank-indexed in the traced step "
                "and do not compose with membership re-indexing; script "
                "the removal as a membership leave= event"
            )
        # fail fast on a schedule that ever shrinks the ring below 2 or
        # names an index/src outside the ring it will meet
        memb_sched.validate(topo.n_ranks)
        beyond = [e for e in memb_sched.events if e.epoch > epochs]
        if beyond:
            # legal (the interrupted first leg of a longer schedule runs
            # exactly this way, then a resume completes it) but worth a
            # flag: these events will not apply in THIS run
            import warnings
            warnings.warn(
                f"{len(beyond)} membership event(s) land beyond "
                f"epochs={epochs} (first: {beyond[0].kind}@"
                f"{beyond[0].epoch}) and will not apply in this run",
                RuntimeWarning,
            )
        memb_base_n = topo.n_ranks  # pre-schedule ring size
        # resume: the snapshot's rank count follows from the membership
        # log at its saved epoch — peek the epoch, then build state (and
        # everything downstream) at that topology
        if ckpt_path and resume:
            found0 = checkpoint.latest(ckpt_path)
            if found0:
                # one deserialization serves both the epoch peek and the
                # full restore below (raw= short-circuits the disk read)
                memb_raw = checkpoint.peek(found0)
                ep0 = int(np.asarray(memb_raw["epoch"]))
                topo = memb_sched.topology_at(topo, ep0)
        memb_engine = chaos_membership.MembershipEngine(
            memb_sched, event_cfg=event_cfg, bootstrap_dir=checkpoint_dir,
        )
    if integ_rollback_on and memb_on:
        raise ValueError(
            "integrity rollback does not compose with membership "
            "transitions (a retained snapshot's rank count can disagree "
            "with the post-transition ring); run the sentinel without "
            "rollback (IntegrityConfig(rollback=False)) or drop the "
            "membership schedule"
        )
    tx = optax.sgd(learning_rate, momentum=momentum if momentum else None)

    # data shards across the data axes (gossip + any declared ddp
    # allreduce subgroups); sp ranks hold sequence chunks, sharded/
    # replicated aux ranks (tp/pp/ep) see the same batch (the model, not
    # the data, differs across them)
    n_data = topo.n_data_ranks
    hybrid = topo.is_hybrid
    input_shape = tuple(x_train.shape[1:])
    input_dtype = (
        jnp.int32
        if np.issubdtype(np.asarray(x_train).dtype, np.integer)
        else jnp.float32
    )
    if "sp" in topo.axes and topo.axis_size("sp") > 1:
        n_sp = topo.axis_size("sp")
        if input_dtype != jnp.int32:
            raise ValueError(
                "sequence parallelism (sp axis) chunks the TRAILING input "
                f"dimension (here size {input_shape[-1]} of shape "
                f"{input_shape}) as a token sequence, but the inputs are "
                f"{np.asarray(x_train).dtype} — for image data that "
                "dimension is channels and must not be sliced; use an "
                "integer token dataset with sp"
            )
        if input_shape[-1] % n_sp:
            raise ValueError(
                f"sequence length {input_shape[-1]} not divisible by sp={n_sp}"
            )
        input_shape = input_shape[:-1] + (input_shape[-1] // n_sp,)
    # sharded layers (tp/ep) and sp-offset attention read lax.axis_index at
    # init time, so any non-gossip axis needs the SPMD-context initializer
    init_fn = (
        init_train_state_spmd
        if (topo.sharded_axes or topo.aux_axes)
        else init_train_state
    )
    # flat-arena resolution BEFORE state init: the EventState buffer
    # layout must match the step that will consume it. Auto: gossip
    # algorithms on plain data-parallel topologies; the single-dtype
    # requirement is probed shape-only (no device work).
    # the arena serves the algos whose step consumes it: dpsgd and
    # eventgrad. allreduce has no gossip hot path, and sp_eventgrad's
    # top-k replicas are tree state (its trigger already reads leaves
    # leaf-parallel) — flattening its unused receive buffers would only
    # break existing checkpoints for zero win.
    _arena_algos = ("dpsgd", "eventgrad")
    if arena is None:
        arena_on = algo in _arena_algos
    else:
        arena_on = bool(arena)
        if arena_on and algo not in _arena_algos:
            raise ValueError(
                f"arena=True is a no-op for algo={algo!r} — only "
                f"{_arena_algos} route through the flat arena; use "
                "arena=None (auto) or False"
            )
    if arena_on and (topo.sharded_axes or topo.aux_axes):
        if arena:
            raise ValueError(
                "arena=True is not supported on sharded/aux-axis "
                "topologies (their initializers need the SPMD context); "
                "use arena=None/False"
            )
        arena_on = False
    if arena_on:
        try:
            _vs = jax.eval_shape(
                model.init,
                jax.random.PRNGKey(0),
                jnp.zeros((1,) + tuple(input_shape), input_dtype),
            )
            _homog = len({
                str(l.dtype) for l in jax.tree.leaves(_vs["params"])
            }) <= 1
        except Exception:
            _homog = False
        if not _homog:
            if arena:
                raise ValueError(
                    "arena=True packs one contiguous buffer and needs a "
                    "single parameter dtype"
                )
            arena_on = False
    # --- bucketed-gossip-schedule resolution (train/steps.py) ----------
    bucketed_k = int(bucketed) if bucketed else 1
    if bucketed_k < 1:
        raise ValueError(f"bucketed must be >= 1 (or None), got {bucketed}")
    if bucketed_k > 1:
        if algo not in ("eventgrad", "sp_eventgrad"):
            raise ValueError(
                "bucketed=K pipelines the event-exchange hot path "
                f"(eventgrad, sp_eventgrad); got algo={algo!r}"
            )
        if algo == "eventgrad" and not arena_on:
            raise ValueError(
                "bucketed=K segments the flat parameter arena, but this "
                "run resolved arena OFF (explicit arena=False, a "
                "sharded topology, or heterogeneous parameter dtypes) "
                "— drop bucketed or make the run arena-eligible"
            )
        if integ_cfg is not None:
            raise ValueError(
                "bucketed does not compose with the integrity engine: "
                "wire checksums, rejection verdicts, and rollback "
                "hardening are whole-wire monolithic contracts"
            )
        if chaos_sched is not None and chaos_sched.has_bitflips:
            raise ValueError(
                "bucketed does not compose with chaos bitflip= faults "
                "(the corruption transform targets one wire buffer per "
                "edge, which the bucketed schedule splits K ways)"
            )
        if fused_update and not arena_tuning.bucketed_tail_ok(bucketed_k):
            import warnings
            warnings.warn(
                f"bucketed fused tail has no measured winning "
                f"bucketed_tail_speedup entry for K={bucketed_k} in "
                "ops/arena_tuning.json on this backend — falling back "
                "to the MONOLITHIC fused path; run `python "
                "bench_kernels.py bucketed` on this device to write "
                "the entry",
                RuntimeWarning,
            )
            bucketed_k = 1
    # --- bounded-async resolution (train/steps.py staleness=D >= 2):
    # the EventState layout grows D-deep per-edge delivery queues, so
    # the combinability guards must fire BEFORE state init
    staleness = int(staleness)
    if staleness >= 2:
        if algo not in ("eventgrad", "sp_eventgrad"):
            raise ValueError(
                f"staleness={staleness} (the bounded-async bound D) "
                "rides the event exchange's per-edge delivery queues "
                f"(algos: eventgrad, sp_eventgrad); got algo={algo!r}"
            )
        if algo == "eventgrad" and not arena_on:
            raise ValueError(
                f"staleness={staleness} carries its delivery queues as "
                "flat arena buffers, but this run resolved arena OFF "
                "(explicit arena=False, a sharded topology, or "
                "heterogeneous parameter dtypes) — drop staleness>=2 "
                "or make the run arena-eligible"
            )
        if fused_update:
            raise ValueError(
                f"staleness={staleness} is not combinable with "
                "fused_update: the kernel bakes in a mix-stale bool, "
                "not a D-deep delivery queue"
            )
        if memb_on:
            raise ValueError(
                f"staleness={staleness} does not compose with "
                "membership transitions: a joining rank would inherit "
                "its bootstrap source's in-flight delivery queues — "
                "run bounded-async without membership, or staleness<=1"
            )
    # --- carrier-resident resolution (train/steps.py): the EventState
    # receive buffers then live in the WIRE dtype (+ per-leaf int8
    # scales in EventState.buf_scales), so the layout must resolve
    # BEFORE state init. Structural eligibility is checked here (the
    # state builder needs the answer); the step factory re-validates
    # the full combinability set (integrity/chaos) with the same
    # messages. Default OFF: the resident dtype is checkpoint layout,
    # flipping it is an explicit opt-in.
    resident_wire = None
    if carrier_resident:
        _wire_now = wire or ("bf16" if wire_bf16 else None)
        if algo == "sp_eventgrad":
            pass  # documented no-op (steps.py carrier resolution)
        elif algo != "eventgrad":
            raise ValueError(
                "carrier_resident=True re-dtypes the event exchange's "
                f"receive buffers (algo='eventgrad'); got algo={algo!r}"
            )
        elif not arena_on:
            raise ValueError(
                "carrier_resident=True rides the flat arena buffer "
                "layout, but this run resolved arena OFF (explicit "
                "arena=False, a sharded topology, or heterogeneous "
                "parameter dtypes) — drop carrier_resident or make the "
                "run arena-eligible"
            )
        elif _wire_now not in ("bf16", "int8"):
            raise ValueError(
                "carrier_resident=True keeps the buffers in the wire "
                f"carrier dtype, but wire={_wire_now!r} has none — use "
                "wire='bf16'/'int8' (f32 wires are already resident)"
            )
        else:
            # bounded-async composes: the delivery queues allocate their
            # candidate slots in the wire dtype with per-slot scales
            # (arena.alloc_event_queue)
            resident_wire = _wire_now
    state = init_fn(
        model, input_shape, tx, topo, algo, event_cfg, seed=seed,
        input_dtype=input_dtype, arena=arena_on, bucketed=bucketed_k,
        staleness=staleness, resident_wire=resident_wire,
        sparse_cfg=sparse_cfg,
    )
    if chaos_sched is not None:
        # per-edge receiver-side health, stacked like every other state
        # leaf (also the checkpoint-restore target shape: chaos runs
        # snapshot and resume WITH their monitor counters)
        state = state.replace(
            chaos=stack_for_ranks(chaos_monitor.PeerHealth.init(topo), topo)
        )
    if obs_on:
        # cumulative telemetry counters, stacked like chaos health; part
        # of the snapshot, so a resumed obs run keeps counting where the
        # interrupted one stopped
        state = state.replace(
            telemetry=stack_for_ranks(
                obs_device.TelemetryState.init(
                    trees.tree_num_leaves(state.params), topo.n_neighbors,
                    n_buckets=min(
                        bucketed_k, trees.tree_num_leaves(state.params)
                    ),
                    # bounded-async: size the ledger's in-flight count
                    # queue like the payload queues (obs/ledger.py)
                    queue_depth=staleness if staleness >= 2 else 0,
                ),
                topo,
            )
        )

    multi = multihost.is_multiprocess()
    # --- dispatch-pipeline resolution (docs/ARCHITECTURE.md): auto = on
    # wherever the serialized host chain is the only thing it removes
    if pipeline is None:
        pipeline_on = (
            not multi and fault_mode is None and not memb_on
            and not integ_engine_on
        )
    else:
        pipeline_on = bool(pipeline)
        if pipeline_on and multi:
            raise ValueError(
                "pipeline=True needs the single-process path — multi-"
                "process runs keep the serial schedule (collective and "
                "checkpoint ordering is cross-process); use pipeline="
                "None/False"
            )
        if pipeline_on and fault_mode is not None:
            raise ValueError(
                "pipeline=True cannot honor fault_inject (the fault must "
                "land at an exact post-snapshot epoch boundary, which "
                "needs the serial schedule); use pipeline=None/False"
            )
        if pipeline_on and memb_on:
            raise ValueError(
                "pipeline=True cannot honor membership transitions (they "
                "re-shape the state between blocks, which needs the "
                "serial schedule); use pipeline=None/False"
            )
        if pipeline_on and integ_engine_on:
            raise ValueError(
                "pipeline=True cannot honor the integrity sentinel/"
                "rollback engine (the verdict on block B gates what "
                "block B+1 may dispatch); use pipeline=None/False, or "
                "keep only the in-step defenses (IntegrityConfig("
                "sentinel=False, rollback=False))"
            )
    # shape metadata only — never dispatch a device op just to count
    n_params = trees.tree_count_params(state.params) // topo.n_ranks
    sz = trees.tree_num_leaves(state.params)
    # recv-trace staleness carry — part of the snapshot so a resumed run's
    # recv{r} records continue the interrupted trajectory exactly
    trace_carry: Dict[str, np.ndarray] = {
        "recv_norm": np.zeros((topo.n_neighbors, topo.n_ranks, sz))
    }
    start_epoch = 0
    if ckpt_path and resume:
        found = checkpoint.latest(ckpt_path)
        if found:
            import warnings

            if memb_raw is None:
                # one template-free read serves every restore attempt
                # below (raw= short-circuits their disk reads) and —
                # the point — routes EVERY resume through peek's
                # corrupt-primary -> .prev auto-fallback: a truncated
                # snapshot with a complete demoted twin recovers loudly
                # instead of failing the service
                memb_raw = checkpoint.peek(found)

            # bounded-async D-clock layout guard, BOTH directions: the
            # queue depth is part of the checkpoint layout like the
            # bucket count, and the shrink direction would otherwise
            # restore SILENTLY (the path graft ignores extra snapshot
            # leaves), dropping in-flight messages on the floor
            snap_depth = _snapshot_async_depth(memb_raw)
            want_depth = staleness if staleness >= 2 else 0
            if (snap_depth != want_depth
                    and algo in ("eventgrad", "sp_eventgrad")):
                snap_word = (
                    f"staleness={snap_depth} (bounded-async, "
                    f"{snap_depth}-deep per-edge delivery queues)"
                    if snap_depth else "staleness<=1 (no delivery queues)"
                )
                raise RuntimeError(
                    f"checkpoint restore failed with staleness="
                    f"{staleness}: this snapshot was written by a "
                    f"{snap_word} run, and the bounded-async queue "
                    "depth D is part of the state layout (EventState"
                    ".pending / SparseState.pending) — "
                    "resuming across a different D would "
                    + ("silently drop the snapshot's in-flight "
                       "messages" if snap_depth else
                       "fabricate empty in-flight queues")
                    + "; resume with the snapshot's original "
                    f"staleness={'%d' % snap_depth if snap_depth >= 2 else '0/1'}"
                    " setting, then re-snapshot to migrate"
                )

            # carrier-resident layout guard, BOTH directions: the
            # resident dtype is part of the checkpoint layout, and a
            # cross-resident restore is structurally LEGAL in at least
            # one direction (bf16-carrier and f32-resident buffers have
            # identical pytree structure and shapes) — the path graft
            # would silently cast the buffers, corrupting the bitwise
            # trajectory instead of failing
            snap_res = _snapshot_resident_wire(memb_raw)
            if snap_res != resident_wire and algo == "eventgrad":
                _res_word = lambda w: (
                    f"carrier-resident wire={w!r}" if w
                    else "f32-resident"
                )
                raise RuntimeError(
                    "checkpoint restore failed with carrier_resident="
                    f"{'on (wire=%r)' % resident_wire if resident_wire else 'off'}: "
                    f"this snapshot was written by a "
                    f"{_res_word(snap_res)} run, and the resident dtype "
                    "of the EventState receive buffers is part of the "
                    "checkpoint layout — a cross-resident restore would "
                    "silently cast the buffers (and orphan or fabricate "
                    "the int8 dequant scales); resume with the "
                    "snapshot's original carrier_resident/wire setting, "
                    "then re-snapshot to migrate"
                )

            # bucketed-K layout guard, BOTH directions: receive buffers
            # (and under D >= 2 the delivery queues) are carried
            # per-bucket, so K is checkpoint layout like the queue
            # depth — sniffed up front so the K=4 -> K=1 direction gets
            # the cause named instead of a raw treedef mismatch
            snap_k = _snapshot_bucket_count(memb_raw)
            if snap_k != bucketed_k and algo == "eventgrad":
                snap_kword = (
                    f"bucketed={snap_k} (per-bucket EventState buffers)"
                    if snap_k > 1 else
                    "monolithic (bucketed off) layout"
                )
                raise RuntimeError(
                    "checkpoint restore failed with bucketed="
                    f"{bucketed_k if bucketed_k > 1 else 'off'}: this "
                    f"snapshot was written by a {snap_kword} run, and "
                    "the bucket count K is part of the EventState "
                    "layout (receive buffers, dequant scales, and "
                    "bounded-async delivery queues are carried "
                    "per-bucket) — resume with the snapshot's original "
                    "bucketed="
                    f"{'%d' % snap_k if snap_k > 1 else 'off'} setting, "
                    "then re-snapshot to migrate"
                )

            def _restore(tmpl_state):
                """(restored, trace_carry-or-None): a snapshot from before
                the trace carry existed resumes the training state and
                lets the carry restart from zeros (loud below — a corrupt
                carry also lands there and recv traces diverge)."""
                try:
                    r = checkpoint.restore(
                        found,
                        {"state": tmpl_state, "epoch": np.int64(0),
                         "trace_carry": trace_carry},
                        raw=memb_raw,
                    )
                    return r, r["trace_carry"]
                except Exception:
                    return checkpoint.restore(
                        found, {"state": tmpl_state, "epoch": np.int64(0)},
                        raw=memb_raw,
                    ), None

            def _attempt(tmpl_state):
                try:
                    return _restore(tmpl_state)
                except Exception:
                    # migration: a snapshot from before a state field
                    # existed (e.g. EventState.num_deferred) fails the
                    # exact-structure restore — graft it onto the
                    # template by path; added fields resume from their
                    # init values, loudly
                    restored, missing = checkpoint.restore_with_fill(
                        found,
                        {"state": tmpl_state, "epoch": np.int64(0),
                         "trace_carry": trace_carry},
                        raw=memb_raw,
                    )
                    # ONLY known-added fields may fill from init —
                    # anything else missing (opt_state restructured,
                    # params renamed, ...) keeps the exact restore's
                    # loud failure instead of resuming with silently
                    # reset state
                    known_added = lambda m: (
                        m == "state/event/num_deferred"
                        or m.startswith("state/telemetry")
                        or m.startswith("trace_carry")
                    )
                    if not missing or not all(known_added(m) for m in missing):
                        raise  # not a field-added migration: real mismatch
                    carry = (
                        None
                        if any(m.startswith("trace_carry") for m in missing)
                        else restored["trace_carry"]
                    )
                    warnings.warn(
                        "snapshot predates state fields "
                        f"{missing}; they resume from init values"
                    )
                    return restored, carry

            try:
                restored, carry = _attempt(state)
            except Exception as exc:
                if bucketed_k > 1 and algo == "eventgrad":
                    # per-bucket EventState buffers (eventgrad only —
                    # sp_eventgrad's bucketed state layout is
                    # unchanged): a monolithic (or different-K)
                    # snapshot cannot restore into this template —
                    # fail loudly with the cause named
                    raise RuntimeError(
                        "checkpoint restore failed with bucketed="
                        f"{bucketed_k}: EventState receive buffers are "
                        "carried PER-BUCKET under the bucketed gossip "
                        "schedule, and cross-layout restores fail "
                        "loudly by design — resume with the snapshot's "
                        "original bucketed/monolithic setting, then "
                        "re-snapshot to migrate"
                    ) from exc
                # the EventState receive buffers changed layout with the
                # flat arena: a snapshot written by a pre-arena (or
                # arena=False) run holds tree-shaped bufs and cannot
                # restore into the flat template. In AUTO mode, fall
                # back to the tree layout so old checkpoints keep
                # resuming (loudly); an EXPLICIT arena=True keeps the
                # hard failure, with the cause named.
                if not arena_on:
                    raise
                if arena is not None:  # explicit request: fail loudly
                    raise RuntimeError(
                        "checkpoint restore failed with arena=True; if "
                        "this snapshot predates the flat arena (tree-"
                        "shaped EventState.bufs), resume it with "
                        "arena=False / --arena off"
                    ) from exc
                legacy = init_fn(
                    model, input_shape, tx, topo, algo, event_cfg,
                    seed=seed, input_dtype=input_dtype, arena=False,
                )
                if chaos_sched is not None:
                    legacy = legacy.replace(chaos=state.chaos)
                if obs_on:
                    legacy = legacy.replace(telemetry=state.telemetry)
                restored, carry = _attempt(legacy)  # real mismatch: raises
                warnings.warn(
                    "checkpoint predates the flat-arena buffer layout; "
                    "resuming with arena=False (re-snapshot to migrate)"
                )
                arena_on = False
            if carry is not None:
                trace_carry = carry
            elif not memb_on:
                # membership snapshots deliberately omit the rank-shaped
                # carry (trace_file is unsupported there) — not a loss
                warnings.warn(
                    "checkpoint has no restorable trace_carry; "
                    "recv-trace staleness restarts from zeros"
                )
            state = restored["state"]
            start_epoch = int(restored["epoch"])

    # host-side pass counter (the sharded pass_num leaf is not addressable
    # across processes); read once here, advance arithmetically per epoch
    start_passes = int(np.asarray(state.pass_num).reshape(-1)[0])
    if mesh is not None:
        state = multihost.put_stacked(state, mesh, topo)
    # the ACTIVE in-step integrity config: a rollback with escalate=True
    # swaps it for cfg.hardened() and rebuilds the runners once
    integ_now = integ_cfg

    def _build_step(wire_mode: str, capacity: Optional[int] = None):
        return make_train_step(
            model, tx, topo, algo,
            event_cfg=event_cfg, sparse_cfg=sparse_cfg, augment=augment,
            sync_bn=sync_bn, trace=trace_file is not None,
            fused_sgd=(learning_rate, momentum) if fused_update and algo != "allreduce" else None,
            wire_bf16=wire_bf16, wire=wire, staleness=staleness,
            chaos=chaos_sched, chaos_policy=chaos_policy,
            gossip_wire=wire_mode, compact_capacity=capacity,
            obs=obs_on,
            arena=arena_on,
            integrity=integ_now,
            bucketed=bucketed_k,
            trigger_policy=trigger_policy,
            carrier_resident=carrier_resident,
            # NOTE arena_sgd (the all-flat SGD tail) stays off: it costs
            # two extra full-model ravels per step, and the measured CPU
            # ravel price (see ArenaSpec.ravel) makes the unflatten +
            # per-leaf optax tail strictly cheaper on every backend we
            # can measure
        )

    # a capacity-budgeted compact-wire run starts DENSE: warmup fires
    # everything (no budget could hold it), and the autotuner needs
    # observed post-warmup fired sizes before it can size the buffer;
    # _maybe_activate_compact below rebuilds the runners exactly once.
    # A capacity-FREE compact wire (compact_static) builds compact
    # directly — nothing to size, nothing to rebuild.
    lifted = spmd(
        _build_step("compact" if compact_static else "dense"),
        topo, mesh=mesh,
    )

    # --- dispatch-mode resolution (device-resident data + K-epoch blocks)
    # eligibility: the single-process vmap/single-mesh path only — hybrid
    # meshes reshape/slice batches per rank (expand_to_mesh) and multihost
    # runs place shards across processes; both keep the host path.
    eligible = mesh is None and not hybrid and not multi and not memb_on
    data_bytes = np.asarray(x_train).size * 4  # post-cast f32/int32 bytes
    if device_data is None:
        device_data = (
            eligible
            and jax.default_backend() == "tpu"
            and data_bytes <= int(os.environ.get(
                "EG_DEVICE_DATA_MAX_BYTES", str(1_500_000_000)
            ))
        )
    elif device_data and not eligible:
        raise ValueError(
            "device_data requires the single-process, non-hybrid, "
            "mesh=None path without membership transitions (hybrid/"
            "multihost runs shard batches on host; membership re-shards "
            "the resident plan per transition)"
        )
    K = max(1, int(epochs_per_dispatch))
    if fault_mode is not None:
        K = 1  # the fault must land at an exact epoch boundary
    if memb_on:
        K = 1  # every epoch end is a block boundary a transition can use
    if obs == "epoch":
        # per-epoch telemetry wants every epoch to BE a block end; the
        # flush stays once-per-dispatch — it is the dispatch that shrinks
        K = 1
    total_epochs = max(0, epochs - start_epoch)
    # keep at least two blocks so a steady-state (post-compile) slice
    # always exists: a single mega-block would smear the jit compile into
    # every history record (steady_records' cold-block rule needs a warm
    # block to keep)
    if total_epochs >= 2:
        K = min(K, total_epochs // 2)
    else:
        K = 1
    if not device_data and K > 1:
        # host path: a K-epoch block materializes K stacked epoch copies
        # in host RAM + HBM at once (no resident-dataset dedup) — cap the
        # block bytes rather than multiply peak memory by K. The block
        # prefetcher DOUBLE-buffers (block B consumed while B+1 is
        # speculatively assembled, and on the plain path device_put too),
        # so two blocks are resident at the peak: the cap covers both.
        K = max(1, min(K, int(os.environ.get(
            "EG_HOST_BLOCK_MAX_BYTES", str(1_500_000_000)
        )) // max(1, 2 * data_bytes)))
    if save_every and K > 1:
        # blocks split at save points: keep K a divisor of save_every so
        # block sizes REPEAT across save segments — otherwise every block
        # could be a distinct (all-cold) size and no warm steady slice
        # would exist. Runs AFTER the host-RAM clamp (which only ever
        # lowers K, preserving the memory bound): clamping second could
        # leave a non-divisor K and pollute steady-state step math with
        # extra cold blocks (ADVICE r5 #1).
        K = max(d for d in range(1, K + 1) if save_every % d == 0)

    # donate the carried state: the scan updates params/opt/event state in
    # place instead of holding two copies in HBM (batches can't alias — the
    # steps-major swapaxes relayouts them). A factory, because the compact
    # autotuner swaps the lifted step once capacity is known.
    def _build_runners(lifted_step):
        @functools.partial(jax.jit, donate_argnums=(0,))
        def run_epoch(st, xb, yb):
            def body(s, batch):
                return lifted_step(s, batch)

            # [n_ranks, steps, ...] -> scan over steps
            xs = (jnp.swapaxes(xb, 0, 1), jnp.swapaxes(yb, 0, 1))
            return jax.lax.scan(body, st, xs)

        # device-resident variant: batches are gathered on-device from the
        # resident dataset each scan step — only the index plan crosses the
        # host->device boundary per dispatch
        @functools.partial(jax.jit, donate_argnums=(0,))
        def run_epoch_idx(st, x_all, y_all, idx):
            def body(s, ib):
                return lifted_step(s, (x_all[ib], y_all[ib]))

            # [n_ranks, S, B] -> scan over S; gather yields [n_ranks, B, ...]
            return jax.lax.scan(body, st, jnp.swapaxes(idx, 0, 1))

        return run_epoch, run_epoch_idx

    run_epoch, run_epoch_idx = _build_runners(lifted)

    history: List[Dict[str, Any]] = []

    x_dev = y_dev = None
    prefetcher = None
    if device_data:
        from eventgrad_tpu.data.sharding import input_cast_dtype

        x_dev = jnp.asarray(
            np.ascontiguousarray(x_train, input_cast_dtype(x_train))
        )
        y_dev = jnp.asarray(np.ascontiguousarray(y_train, np.int32))
        steps_per_epoch = epoch_steps(len(x_train), n_data, batch_size)
    else:
        # plain single-process path: the prefetcher worker also runs the
        # device_put, so block B+1's stacked arrays land on device while
        # block B computes (hybrid/mesh/multihost batches need host-side
        # expand/placement first and keep the numpy hand-off)
        transfer = (
            jnp.asarray if (mesh is None and not hybrid and not multi)
            else None
        )
        prefetcher = EpochPrefetcher(
            x_train, y_train, n_data, batch_size,
            random=random_sampler, seed=seed, last_epoch=epochs,
            transfer=transfer,
        )
        steps_per_epoch = prefetcher.steps

    def _blocks():
        """Consecutive (first, last) epoch blocks of up to K epochs, split
        so every `save_every` multiple lands exactly on a block end."""
        e = start_epoch + 1
        while e <= epochs:
            be = min(e + K - 1, epochs)
            if save_every:
                nxt = ((e + save_every - 1) // save_every) * save_every
                if e <= nxt <= be:
                    be = nxt
            yield e, be
            e = be + 1

    # compact-wire autotune state: the loop runs dense until warmup is
    # past and enough post-warmup fired sizes were observed, then picks a
    # static capacity ONCE and rebuilds the runners (one extra compile,
    # zero recompile churn afterwards)
    compact_capacity: Optional[int] = None
    compact_done = gossip_wire != "compact" or compact_static
    compact_note: Optional[Dict[str, Any]] = None
    compact_fired_peak = 0.0
    compact_post_steps = 0
    warmup_passes = (event_cfg or EventConfig()).warmup_passes
    compact_min_samples = int(os.environ.get("EG_COMPACT_MIN_SAMPLES", "16"))

    seen_block_sizes: set = set()
    # telemetry flush bookkeeping: previous cumulative host snapshot (the
    # diff base) and the one-time run metadata rider
    obs_prev = None
    obs_meta_pending = obs_on
    # cumulative count of flush windows whose conservation audit failed
    # (the ledger_audit_failures_total Prometheus gauge)
    ledger_audit_fails = 0
    eval_on = (
        x_test is not None and log_every_epoch and not multi and not hybrid
    )
    # multi-process callers evaluate once at the end on allgathered params
    # (multihost.to_host); hybrid meshes skip consensus eval — averaging
    # across sp/tp/pp/ep ranks would mix differently-sharded parameters.
    # One evaluator per run: the jitted scan and the device-resident test
    # set are reused at every block end.
    evaluator = DeviceEvaluator(model, x_test, y_test) if eval_on else None
    probe_on = (
        (chaos_sched is not None or obs_on or integ_cfg is not None)
        and not multi and not hybrid
    )
    ckpt_writer = (
        checkpoint.AsyncWriter() if (ckpt_path and pipeline_on) else None
    )
    # --- integrity engine state (chaos/integrity.py) -------------------
    integ_sentinel = (
        chaos_integrity.DivergenceSentinel(integ_cfg) if integ_engine_on
        else None
    )
    integ_retention = (
        checkpoint.RollingRetention(
            os.path.join(checkpoint_dir, "good"), keep=integ_cfg.keep_good,
        )
        if integ_rollback_on and checkpoint_dir else None
    )
    integ_good: Optional[Dict[str, Any]] = None  # last-known-good snapshot
    integ_trip: Optional[str] = None      # set by _drain, consumed below
    integ_rollbacks = 0
    integ_rollback_info: Optional[Dict[str, Any]] = None
    integ_totals = {"wire_rejects": 0, "quarantined_steps": 0}
    blocks = list(_blocks())
    # observed-readiness clock for wall_s: dt of a block runs from its
    # dispatch (or the previous block's observed readiness, whichever is
    # later) to its own observed readiness — under the full pipe that is
    # back-to-back device time, and with the pipe empty (serial mode) it
    # reduces to the old dispatch-to-block_until_ready measurement
    last_ready_t = float("-inf")
    # pass bookkeeping rides hw per block instead of closed-over
    # arithmetic: under membership the steps-per-epoch and rank count
    # change at transitions (without membership the values are identical
    # to the old start_passes + (epoch - start_epoch) * steps form)
    passes_done = start_passes
    rank_passes_done = start_passes * topo.n_ranks
    if memb_on and start_epoch > 0:
        # resumed elastic run: the rank count (and steps/epoch) varied
        # over the resumed history — reconstruct cumulative rank-passes
        # from the schedule so msgs_saved_pct matches the uninterrupted
        # run's denominators exactly
        rank_passes_done = sum(
            epoch_steps(len(x_train), nr, batch_size) * nr
            for e in range(1, start_epoch + 1)
            for nr in (memb_sched.n_ranks_at(memb_base_n, e - 1),)
        )
    memb_recs_pending: List[Dict[str, Any]] = []

    def _drain(hw: Dict[str, Any]) -> None:
        """Run one block's host work: metrics readback, telemetry flush,
        history records + trace stream, eval readback, compact autotune.
        Serial mode calls this right after the dispatch; pipelined mode
        one block late, while the device computes the NEXT block. All
        device values it touches were dispatched before the next block
        donated the state, so the results are bitwise mode-independent.
        """
        nonlocal obs_prev, obs_meta_pending, last_ready_t
        nonlocal ledger_audit_fails
        nonlocal compact_capacity, compact_done, compact_note
        nonlocal compact_fired_peak, compact_post_steps
        nonlocal run_epoch, run_epoch_idx
        nonlocal integ_trip, integ_rollback_info
        blk_i, blk_start, blk_end = hw["blk_i"], hw["blk_start"], hw["blk_end"]
        n_e = blk_end - blk_start + 1
        mode_now, cold, label_shape = hw["mode"], hw["cold"], hw["label_shape"]
        # rank count / pass base AT DISPATCH TIME: under membership the
        # topology changes between blocks, so every per-block quantity
        # rides hw instead of reading the loop's current topo
        n_ranks_blk, n_nb_blk = hw["n_ranks"], hw["n_nb"]
        with _span("block_ready", cat="device", block=blk_i):
            jax.block_until_ready(hw["m"])
        # stamp readiness BEFORE the metrics D2H copy: wall_s measures
        # device compute, and the copy (large with --trace-file's
        # per-leaf vectors) is host work like the rest of the drain
        t_ready = time.perf_counter()
        dt = t_ready - max(last_ready_t, hw["t_dispatched"])
        last_ready_t = t_ready
        m = multihost.to_host(hw["m"])

        # telemetry flush: ONE device->host read of the cumulative
        # counters per dispatch block, diffed against the previous
        # snapshot on the host (no device-side reset write)
        obs_rec = None
        if obs_on:
            with _span("obs_flush", cat="obs", block=blk_i):
                tel_host = jax.tree.map(
                    np.asarray, multihost.to_host(hw["tel"])
                )
                obs_rec = obs_device.window_record(tel_host, obs_prev)
                if tel_host.ledger is not None:
                    # conservation-law audit of the flush window, BEFORE
                    # obs_prev is overwritten (the window's other end).
                    # Integer-exact per edge; violations name the law
                    # and the (rank, edge) that broke it (obs/ledger.py)
                    obs_rec["ledger_audit"] = obs_ledger.audit_window(
                        tel_host.ledger,
                        None if obs_prev is None else obs_prev.ledger,
                        topo,
                    )
                    if not obs_rec["ledger_audit"]["ok"]:
                        ledger_audit_fails += 1
                obs_prev = tel_host
            if obs_meta_pending:
                obs_rec["meta"] = {
                    "leaves": [
                        "/".join(
                            str(getattr(p, "key", p)) for p in kp
                        )
                        for kp, _ in
                        jax.tree_util.tree_flatten_with_path(
                            hw["state"].params
                        )[0]
                    ],
                    "edges": [nb.name for nb in topo.neighbors],
                    "silence_buckets": int(
                        np.asarray(tel_host.silence_hist).shape[-1]
                    ),
                    "n_ranks": n_ranks_blk,
                    "n_neighbors": n_nb_blk,
                    "wire": wire or ("bf16" if wire_bf16 else None),
                }
                obs_meta_pending = False

        # block metrics are [n_e * steps, n_ranks]; split per epoch
        steps = hw["steps"]
        for j, epoch in enumerate(range(blk_start, blk_end + 1)):
            sl = slice(j * steps, (j + 1) * steps)
            m_e = {k: np.asarray(v)[sl] for k, v in m.items()}
            total_passes = hw["pass_base"] + (j + 1) * steps
            rec = {
                "epoch": epoch,
                "algo": algo,
                "steps": steps,
                # 0-based jit-dispatch block index; dispatch_cold marks
                # records from a block that paid a compile (first block
                # of its size) — steady-state step math drops those
                # (utils.metrics.steady_records)
                "dispatch_block": blk_i,
                "dispatch_cold": cold,
                "wall_s": dt / n_e,
                "loss": float(m_e["loss"].mean()),
                # ranks alive during this block (membership elasticity:
                # the per-epoch active-rank count, docs/OBSERVABILITY.md)
                "active_ranks": n_ranks_blk,
                # targets per step per rank: batch for classification,
                # batch x t_local for LM (correct counts tokens
                # elementwise)
                "train_acc": 100.0 * float(m_e["correct"].sum())
                / (n_ranks_blk * steps * int(np.prod(label_shape) or 1)),
                "sent_bytes_per_step_per_chip": float(
                    m_e["sent_bytes"][..., 0].mean()
                ),
                # the SPMD wire truth next to the accounting model:
                # bytes the collective actually moved (docs/compaction.md)
                "sent_bytes_wire_real_per_step_per_chip": float(
                    m_e["sent_bytes_wire_real"][..., 0].mean()
                ),
                "n_params": n_params,
                "arena": bool(arena_on),
                # resident dtype of the EventState receive buffers —
                # 'f32' unless carrier-resident (the perf ledger keys
                # byte comparisons on it; docs/OBSERVABILITY.md)
                "resident_dtype": resident_wire or "f32",
                # which SPMD lift ran this block (vmap sim vs shard_map
                # device mesh) — the perf ledger's comparability-group
                # key, so mesh rows never gate against vmap rows
                "backend": backend_name,
            }
            if bucketed_k > 1:
                # bucketed gossip schedule: the bucket count and the
                # per-bucket wire split next to the totals
                rec["buckets"] = min(bucketed_k, sz)
                if "sent_bytes_wire_real_per_bucket" in m_e:
                    rec["sent_bytes_wire_real_per_bucket"] = [
                        round(float(v), 1)
                        for v in np.asarray(
                            m_e["sent_bytes_wire_real_per_bucket"]
                        )[-1, 0]
                    ]
            if gossip_wire == "compact":
                rec["gossip_wire"] = mode_now
                if compact_capacity is not None:
                    rec["compact_capacity"] = int(compact_capacity)
                if compact_note is not None:
                    rec.update(compact_note)
                    compact_note = None
            if algo in ("eventgrad", "sp_eventgrad"):
                rec["policy"] = pol.name
                rec["num_deferred"] = int(m_e["num_deferred"][-1].sum())
                # msgs-saved vs D-PSGD: events/(n_neighbors * passes *
                # sz) fired
                events_total = int(m_e["num_events"][-1].sum())
                rec["num_events"] = events_total
                if memb_on:
                    # elastic denominator: cumulative RANK-passes (the
                    # rank count varied); approximate — a departed rank
                    # takes its event count with it, a newcomer starts
                    # at zero (chaos/membership.py docstring)
                    rec["msgs_saved_pct"] = msgs_saved_pct(
                        events_total,
                        hw["rank_base"] + (j + 1) * steps * n_ranks_blk,
                        sz, n_nb_blk, 1,
                    )
                else:
                    rec["msgs_saved_pct"] = msgs_saved_pct(
                        events_total, total_passes, sz, n_nb_blk,
                        n_ranks_blk,
                    )
                rec["fired_frac"] = float(m_e["fired_frac"].mean())
                if "edge_staleness" in m_e:
                    # bounded-async failure surface (staleness=D >= 2):
                    # end-of-epoch per-edge staleness peak and the
                    # cumulative late-delivery commits
                    rec["staleness"] = staleness
                    rec["edge_staleness_max"] = int(
                        np.asarray(m_e["edge_staleness"])[-1].max()
                    )
                    rec["late_commits"] = int(
                        np.asarray(m_e["late_commits"])[-1].sum()
                    )
            if memb_on:
                if not history:  # replayability: the membership log
                    # alone reproduces the final state bitwise
                    rec["membership"] = memb_sched.to_dict()
                if j == 0 and hw.get("memb_recs"):
                    # transitions applied at the previous block boundary
                    rec["membership_transitions"] = hw["memb_recs"]
            if crash_armed is not None and not history:
                # crash-drill rider: the log of a killed run names the
                # armed site, so the matrix can verify WHERE it died
                rec["crashpoint"] = dict(crash_armed)
            if chaos_sched is not None:
                if not history:  # replayability: schedule rides record 1
                    rec["chaos"] = chaos_sched.to_dict()
                    if chaos_policy is not None:
                        rec["chaos_policy"] = chaos_policy.to_dict()
                # silence/drops are carried state: the epoch's last
                # step is its end-of-epoch snapshot
                rec.update(chaos_monitor.health_record(
                    np.asarray(m_e["edge_silence"])[-1],
                    np.asarray(m_e["chaos_drops"])[-1],
                    event_cfg.max_silence if event_cfg else 0,
                ))
            if integ_cfg is not None:
                if not history:  # replayability: config rides record 1
                    rec["integrity"] = integ_cfg.to_dict()
                if "integrity_wire_reject" in m_e:
                    # per-step in-step verdicts, summed over the epoch
                    # (ranks x edges / ranks); cumulative forms feed the
                    # *_total gauges below
                    wr = int(np.asarray(m_e["integrity_wire_reject"]).sum())
                    qs = int(np.asarray(m_e["integrity_quarantined"]).sum())
                    integ_totals["wire_rejects"] += wr
                    integ_totals["quarantined_steps"] += qs
                    rec["wire_rejects"] = wr
                    rec["quarantined_steps"] = qs
                rec["integrity_rollbacks"] = integ_rollbacks
                if integ_rollback_info is not None:
                    # first record AFTER the engine restored last-good
                    rec["integrity_rollback"] = integ_rollback_info
                    integ_rollback_info = None
            if trace_file and "trace_fired" in m_e and multihost.is_primary():
                _write_trace(
                    trace_file, m_e, total_passes - steps, topo,
                    hw["state"], trace_carry,
                )
            elif trace_file and multihost.is_primary():
                # non-event algos: per-step per-rank loss records — the
                # (epoch, loss) stream cent/decent call values{r}.txt
                # (cent.cpp:124, decent.cpp:166)
                loss_all = np.asarray(m_e["loss"])
                with open(trace_file, "a") as tf:
                    for s_i in range(steps):
                        for r in range(topo.n_ranks):
                            tf.write(json.dumps(_loss_record(
                                total_passes - steps, s_i, r, loss_all
                            )) + "\n")
            is_block_end = epoch == blk_end
            if is_block_end and obs_rec is not None:
                rec["obs"] = obs_rec
            if is_block_end and hw["probe"] is not None:
                # periodic consensus-error probe ||p_i - mean(p)||:
                # the ground-truth drift metric that tells "quiet
                # because the threshold says so" from "quiet because
                # the link is dead" (chaos/monitor.py) — chaos and
                # telemetry runs both log it at block ends. Dispatched
                # at block end; this is just the readback.
                cerr = np.asarray(hw["probe"])
                rec["consensus_err_max"] = float(cerr.max())
                rec["consensus_err_mean"] = float(cerr.mean())
            if is_block_end and hw["eval_fut"] is not None:
                # the jitted device eval was dispatched at the block end
                # (before the next block donated the state); only the
                # two-scalar readback lands here — one block late under
                # the pipeline, same record either way
                with _span("eval_readback", cat="host", epoch=epoch):
                    rec.update(
                        {
                            "test_" + k: v
                            for k, v in evaluator.result(
                                hw["eval_fut"]
                            ).items()
                        }
                    )
            history.append(rec)
            if on_epoch is not None:  # live metrics (liveness signal)
                on_epoch(rec)
        if registry is not None:
            # Prometheus faces of the elasticity story: the live rank
            # count and the cumulative transition counter
            registry.gauge("active_ranks", n_ranks_blk)
            if "edge_staleness" in m:
                # bounded-async: the per-edge staleness gauge
                # (eventgrad_edge_staleness{edge=...}, max over ranks
                # at the block's last pass) and cumulative late commits
                es = np.asarray(m["edge_staleness"])[-1]
                for k, nb in enumerate(topo.neighbors):
                    registry.gauge(
                        "edge_staleness", float(es[..., k].max()),
                        labels={"edge": nb.name},
                    )
                registry.gauge(
                    "late_commits_total",
                    float(np.asarray(m["late_commits"])[-1].sum()),
                )
            if obs_rec is not None and "message_ledger" in obs_rec:
                # message-lifecycle ledger faces (obs/schema.py
                # PROM_EXPORTED): cumulative per-disposition totals, the
                # in-flight gauge at the block boundary, and how many
                # flush-window conservation audits have failed
                _cum = np.asarray(obs_prev.ledger.counts, np.int64)
                for _name, _ri in obs_ledger.ROW.items():
                    registry.gauge(
                        "ledger_disposition_total",
                        float(_cum[:, _ri, :].sum()),
                        labels={"disposition": _name},
                    )
                registry.gauge(
                    "ledger_in_flight",
                    float(sum(obs_rec["message_ledger"]["in_flight"])),
                )
                registry.gauge(
                    "ledger_audit_failures_total",
                    float(ledger_audit_fails),
                )
            if memb_engine is not None:
                registry.gauge(
                    "membership_transitions_total",
                    float(len(memb_engine.log)),
                )
            if integ_cfg is not None:
                # Prometheus faces of the integrity story (obs/schema.py
                # INTEGRITY_FIELDS): cumulative rejections, quarantined
                # rank-passes, and rollbacks performed
                registry.gauge(
                    "wire_rejects_total", float(integ_totals["wire_rejects"])
                )
                registry.gauge(
                    "quarantined_steps_total",
                    float(integ_totals["quarantined_steps"]),
                )
                registry.gauge(
                    "integrity_rollbacks_total", float(integ_rollbacks)
                )
        if integ_sentinel is not None:
            # divergence sentinel: judge the BLOCK (mean loss over every
            # step in the dispatch block + the block-end consensus-error
            # probe); the verdict gates what the next block may dispatch
            # (the loop's trip handler performs the rollback)
            blk_loss = float(np.asarray(m["loss"], np.float64).mean())
            cerr = (
                float(np.asarray(hw["probe"]).max())
                if hw["probe"] is not None else None
            )
            integ_trip = integ_sentinel.observe(blk_loss, cerr)
        if not compact_done:
            # collect post-warmup fired sizes from this block; once
            # enough are in (or warmup is past, with an explicit
            # compact_frac), size the buffer and switch — exactly once
            # [n_e*steps, n_ranks]: the capacity is one static number
            # shared by every rank, so the peak is taken across ranks
            fe = np.asarray(m["fired_elems"])
            pnums = hw["pass_base"] + 1 + np.arange(fe.shape[0])
            # warm is pass_num < warmup_passes (events.propose), so
            # pass == warmup_passes is already real trigger data
            keep = pnums >= warmup_passes
            if hw.get("memb_recs"):
                # a block that opens with a membership force-fire is
                # transient: the full-fire rewire pass, then a couple
                # of passes of threshold re-adaptation — sampling it
                # sizes the budget toward the whole model and silently
                # disables compaction. Resume sampling next block.
                keep[:] = False
            post = fe[keep]
            if post.size:
                compact_fired_peak = max(
                    compact_fired_peak, float(post.max())
                )
                compact_post_steps += int(post.shape[0])
            enough = (
                compact_post_steps >= compact_min_samples
                if compact_frac is None
                else bool(pnums.size and pnums[-1] >= warmup_passes)
            )
            if enough:
                # per-rank leaf sizes (leading axis is the rank stack);
                # the floor rule lives with the collective
                floor = collectives.compact_capacity_floor(
                    int(np.prod(l.shape[1:], dtype=np.int64)) or 1
                    for l in jax.tree.leaves(hw["state"].params)
                )
                if bucketed_k > 1 and algo == "eventgrad":
                    # every bucket must fit its own largest leaf: the
                    # bucketed floor is the sum of per-bucket floors
                    # (split_capacity's feasibility bound)
                    _bspec = arena_lib.arena_spec(jax.tree.map(
                        lambda l: jax.ShapeDtypeStruct(
                            l.shape[1:], l.dtype
                        ),
                        hw["state"].params,
                    ))
                    floor = max(floor, collectives.bucketed_capacity_floor(
                        _bspec.buckets(bucketed_k)
                    ))
                if compact_frac is not None:
                    cap = min(n_params, max(
                        floor, int(np.ceil(compact_frac * n_params))
                    ))
                    autotuned = False
                else:
                    cap = collectives.choose_capacity(
                        n_params, compact_fired_peak, floor
                    )
                    autotuned = True
                compact_note = {"compact_autotuned": autotuned}
                if autotuned:
                    compact_note["compact_fired_peak_elems"] = (
                        compact_fired_peak
                    )
                if autotuned and cap >= n_params:
                    # fire rate ~1: the budget would be the whole
                    # model — nothing to compact; stay dense, loudly
                    compact_note["compact_skipped"] = (
                        "observed fire rate needs capacity >= n_params"
                    )
                else:
                    compact_capacity = cap
                    run_epoch, run_epoch_idx = _build_runners(
                        spmd(_build_step("compact", cap), topo, mesh=mesh)
                    )
                compact_done = True

    if integ_rollback_on:
        # an in-memory snapshot ALWAYS backs the rollback: seed the
        # last-known-good with the initial (or resumed) state, so a trip
        # on the very first block rolls back to the start and replays
        # hardened instead of escalating
        integ_good = {
            "snap": checkpoint.host_snapshot({
                "state": state,
                "epoch": np.int64(start_epoch),
                "trace_carry": trace_carry,
            }),
            "epoch": start_epoch,
            "next_bi": 0,
            "sentinel": integ_sentinel.snapshot(),
            "obs_prev": obs_prev,
            "passes_done": passes_done,
            "rank_passes_done": rank_passes_done,
        }
    def _boundary_payload(blk_end: int) -> Dict[str, Any]:
        """The snapshot payload at a block boundary — ONE definition
        shared by the periodic serial save and the preemption drain, so
        a drained snapshot can never diverge from a scheduled one.
        Reads the loop's current state/trace_carry at call time."""
        save_state = multihost.to_host(state) if multi else state
        payload: Dict[str, Any] = {
            "state": save_state, "epoch": np.int64(blk_end),
        }
        if not memb_on:
            # the recv-trace carry is rank-shaped; the elastic run
            # (trace_file unsupported there) omits it so a resume can
            # re-shape the template from the membership log alone
            payload["trace_carry"] = trace_carry
        return payload

    # --- graceful preemption (chaos/crashpoint.py) ---------------------
    # scheduled notices: the first one strictly beyond this run's start
    # epoch belongs to THIS incarnation (a resume ignores the notices
    # its drained predecessor already honored)
    preempt_at: Optional[Tuple[int, int]] = None
    if chaos_sched is not None and chaos_sched.preempt:
        preempt_at = next(
            ((e, s) for e, s in chaos_sched.preempt if e > start_epoch),
            None,
        )
    # SIGTERM/SIGINT handlers set a flag the block loop drains on; only
    # installed where the drain can actually snapshot (a checkpoint_dir
    # exists) and the process owns its signals (single-process) — every
    # other run keeps today's default signal behavior, bit for bit
    preempt_guard = crashpoint.PreemptGuard(
        enabled=ckpt_path is not None and not multi
    )
    _root_span = contextlib.ExitStack()
    pending: Optional[Dict[str, Any]] = None
    try:
        _root_span.enter_context(
            _span("train", cat="run", algo=algo, pipelined=pipeline_on)
        )
        _root_span.enter_context(preempt_guard)
        bi = 0
        while bi < len(blocks):
            # index-based iteration: an integrity rollback REWINDS bi to
            # the block after the restored snapshot and replays
            blk_i = bi
            blk_start, blk_end = blocks[bi]
            n_e = blk_end - blk_start + 1
            # first block of each distinct (size, wire-mode) pays a jit
            # trace+compile (scan length is part of the shape, and the
            # compact switch is a new program) — tag its records so
            # steady-state step math can exclude them (the tail-remainder
            # block recompiles too, not just block 0)
            mode_now = (
                "compact"
                if (compact_capacity is not None or compact_static)
                else "dense"
            )
            # the rank count is part of the compiled shape too: a
            # membership transition recompiles even at an already-seen
            # block size
            cold = (n_e, mode_now, topo.n_ranks) not in seen_block_sizes
            seen_block_sizes.add((n_e, mode_now, topo.n_ranks))
            label_shape: Tuple[int, ...] = ()
            with _span("data", cat="host", block=blk_i):
                if device_data:
                    idx_np = np.concatenate(
                        [
                            epoch_index_plan(
                                len(x_train), n_data, batch_size,
                                random=random_sampler, seed=seed, epoch=e,
                            )
                            for e in range(blk_start, blk_end + 1)
                        ],
                        axis=1,
                    ).astype(np.int32)
                    # per-(step, rank) target count: batch plus any
                    # trailing label dims (LM token axes)
                    label_shape = (batch_size,) + tuple(y_dev.shape[1:])
                    idx_dev = jnp.asarray(idx_np)
                else:
                    nxt = (
                        blocks[blk_i + 1] if blk_i + 1 < len(blocks)
                        else None
                    )
                    xb, yb = prefetcher.get_block(
                        blk_start, blk_end, next_span=nxt
                    )
                    if hybrid:
                        xb, yb = expand_to_mesh(xb, yb, topo)
                    if mesh is not None:  # global placement (spans hosts)
                        xb = multihost.put_stacked(xb, mesh, topo)
                        yb = multihost.put_stacked(yb, mesh, topo)
                    elif not isinstance(xb, jax.Array):
                        # prefetcher.transfer already uploaded the common
                        # path; this is the fallback (e.g. transfer=None)
                        xb, yb = jnp.asarray(xb), jnp.asarray(yb)
                    label_shape = tuple(yb.shape[2:])
            t0 = time.perf_counter()
            with _span(
                "dispatch_block", cat="device",
                block=blk_i, epochs=n_e, cold=cold, wire=mode_now,
                pipelined=pipeline_on,
            ):
                if device_data:
                    state, m = run_epoch_idx(state, x_dev, y_dev, idx_dev)
                else:
                    state, m = run_epoch(state, xb, yb)
                if not pipeline_on:
                    jax.block_until_ready(state.params)
            # seeded kill drill: the block is on device, none of its
            # host work has run (pipeline on and off both pass here)
            crashpoint.hit("loop.block_dispatched")
            # post-block device enqueues: every read of the NEW state is
            # dispatched HERE, before the next iteration's run_epoch
            # donates its buffers — in-order device execution sequences
            # them after this block and before the next
            tel_fut = None
            if obs_on:
                tel_fut = (
                    _device_copy(state.telemetry) if pipeline_on
                    else state.telemetry
                )
            probe_fut = (
                chaos_monitor.consensus_error(state.params) if probe_on
                else None
            )
            eval_fut = None
            if evaluator is not None:
                # K-epoch blocks evaluate at block ends (every-K cadence)
                # — the final epoch is always a block end
                with _span("eval", cat="device", epoch=blk_end):
                    eval_fut = evaluator.dispatch(
                        consensus_params(state.params),
                        rank0_slice(state.batch_stats),
                    )
            hw = {
                "blk_i": blk_i, "blk_start": blk_start, "blk_end": blk_end,
                "m": m, "tel": tel_fut, "probe": probe_fut,
                "eval_fut": eval_fut, "label_shape": label_shape,
                "mode": mode_now, "cold": cold, "state": state,
                "t_dispatched": t0,
                "steps": steps_per_epoch,
                "pass_base": passes_done,
                "rank_base": rank_passes_done,
                "n_ranks": topo.n_ranks,
                "n_nb": topo.n_neighbors,
                "memb_recs": memb_recs_pending or None,
            }
            memb_recs_pending = []
            passes_done += n_e * steps_per_epoch
            rank_passes_done += n_e * steps_per_epoch * topo.n_ranks
            if pending is not None:  # previous block's deferred host work
                _drain(pending)
                pending = None
            ckpt_due = bool(ckpt_path and (
                blk_end == epochs
                or (save_every and blk_end % save_every == 0)
            ))
            if not pipeline_on or ckpt_due or not compact_done:
                # serialized drain: serial mode by definition; a due
                # checkpoint must snapshot the post-host-work trace
                # carry; a compact autotune decision gates what the next
                # block dispatches
                _drain(hw)
            else:
                pending = hw
            if integ_sentinel is not None:
                # the sentinel forces the serial schedule, so this
                # block's verdict landed in the _drain above
                reason, integ_trip = integ_trip, None
                if reason is not None:
                    if (
                        not integ_rollback_on
                        or integ_rollbacks >= integ_cfg.max_rollbacks
                    ):
                        raise chaos_integrity.IntegrityEscalation(
                            f"divergence sentinel tripped ({reason}) at "
                            f"epoch {blk_end} with "
                            + ("rollback disarmed"
                               if not integ_rollback_on else
                               "the rollback budget spent "
                               f"({integ_rollbacks}/"
                               f"{integ_cfg.max_rollbacks})")
                            + "; the retained last-known-good state "
                            "cannot outrun this fault — restarting "
                            "would replay the same divergence"
                        )
                    integ_rollbacks += 1
                    with _span(
                        "integrity_rollback", cat="host", epoch=blk_end
                    ):
                        # restore EVERY rank from last-known-good, then
                        # re-arm all event buffers through the
                        # membership engine's force_refresh — the next
                        # pass force-fires every exchange, so stale
                        # receive buffers rewire in one cycle
                        state = jax.tree.map(
                            jnp.asarray, integ_good["snap"]["state"]
                        )
                        state = chaos_membership.force_refresh(
                            state, event_cfg
                        )
                        # owned copy: trace writes during the replay
                        # must not mutate the retained snapshot
                        trace_carry = {
                            k: np.array(v)
                            for k, v in
                            integ_good["snap"]["trace_carry"].items()
                        }
                        # seeded kill drill: state restored in memory,
                        # replay not yet re-dispatched — a kill here
                        # must resume into the same rollback
                        crashpoint.hit("integrity.rollback")
                    hardened = False
                    if integ_cfg.escalate:
                        # harden the step: the replayed segment meets
                        # the same scheduled faults (replay is pass-
                        # keyed), so rolling back without checksums +
                        # quarantine would diverge identically and
                        # burn the budget. One recompile.
                        new_cfg = integ_now.hardened()
                        if new_cfg != integ_now:
                            integ_now = new_cfg
                            hardened = True
                            run_epoch, run_epoch_idx = _build_runners(
                                spmd(
                                    _build_step(
                                        "compact"
                                        if (compact_capacity is not None
                                            or compact_static)
                                        else "dense",
                                        compact_capacity,
                                    ),
                                    topo, mesh=mesh,
                                )
                            )
                            # a new program: every block size pays a
                            # fresh compile — keep the cold tags honest
                            seen_block_sizes.clear()
                    if prefetcher is not None:
                        # the worker speculates FORWARD; a rewind needs
                        # a fresh prefetcher at the replay start
                        prefetcher.close()
                        prefetcher = EpochPrefetcher(
                            x_train, y_train, n_data, batch_size,
                            random=random_sampler, seed=seed,
                            last_epoch=epochs, transfer=transfer,
                        )
                    integ_sentinel.rewind(integ_good["sentinel"])
                    obs_prev = integ_good["obs_prev"]
                    passes_done = integ_good["passes_done"]
                    rank_passes_done = integ_good["rank_passes_done"]
                    integ_rollback_info = {
                        "reason": reason,
                        "tripped_epoch": blk_end,
                        "restored_epoch": integ_good["epoch"],
                        "hardened": hardened,
                    }
                    bi = integ_good["next_bi"]
                    continue
                if integ_rollback_on:
                    # a HEALTHY block becomes the new last-known-good:
                    # host-memory always; validated rolling retention on
                    # disk at checkpoint cadence (each snapshot rides
                    # save()'s fsynced atomic swap)
                    with _span(
                        "integrity_retain", cat="host", epoch=blk_end
                    ):
                        snap = checkpoint.host_snapshot({
                            "state": state,
                            "epoch": np.int64(blk_end),
                            "trace_carry": trace_carry,
                        })
                    integ_good = {
                        "snap": snap,
                        "epoch": blk_end,
                        "next_bi": bi + 1,
                        "sentinel": integ_sentinel.snapshot(),
                        "obs_prev": obs_prev,
                        "passes_done": passes_done,
                        "rank_passes_done": rank_passes_done,
                    }
                    if integ_retention is not None and ckpt_due:
                        integ_retention.save_good(blk_end, snap)
            if memb_engine is not None:
                # elastic membership transitions land HERE: after the
                # block's host work drained (membership forces the serial
                # schedule) and BEFORE any checkpoint, so snapshots are
                # always post-transition — a resume at epoch E rebuilds
                # the topology from every event with epoch <= E
                for ev in memb_engine.events_at(blk_end):
                    state, topo, info = memb_engine.apply(state, topo, ev)
                    memb_recs_pending.append(info)
                    if registry is not None:
                        # last-write-wins: keep the cumulative gauge
                        # current even for a final-epoch transition (no
                        # drain runs after it)
                        registry.gauge(
                            "membership_transitions_total",
                            float(len(memb_engine.log)),
                        )
                    if obs_prev is not None:
                        # the telemetry diff base tracks the device
                        # state's row layout (newcomer counters start 0)
                        obs_prev = (
                            chaos_membership.take_rows_host(
                                obs_prev, tuple(info["survivors"])
                            )
                            if ev.kind == "leave"
                            else chaos_membership.insert_zero_row_host(
                                obs_prev, ev.index
                            )
                        )
                if memb_recs_pending and blk_end < epochs:
                    # the rank count changed: rebuild the data shards and
                    # the jitted runners for the new topology (one fresh
                    # compile per transition — the price of keeping every
                    # dispatched shape static). A final-epoch transition
                    # skips the rebuild (nothing left to dispatch): it
                    # exists for resume continuity — the final snapshot
                    # is post-transition and the force-fire cycle runs on
                    # the resumed run's first pass
                    n_data = topo.n_data_ranks
                    if prefetcher is not None:
                        prefetcher.close()
                        prefetcher = EpochPrefetcher(
                            x_train, y_train, n_data, batch_size,
                            random=random_sampler, seed=seed,
                            last_epoch=epochs, transfer=transfer,
                        )
                        steps_per_epoch = prefetcher.steps
                    run_epoch, run_epoch_idx = _build_runners(
                        spmd(
                            _build_step(
                                "compact"
                                if (compact_capacity is not None
                                    or compact_static)
                                else "dense",
                                compact_capacity,
                            ),
                            topo, mesh=mesh,
                        )
                    )
            if ckpt_due:
                if pipeline_on:
                    # eager device->host snapshot (owned copies — later
                    # trace writes keep mutating the live carry), then
                    # serialization + atomic swap on the writer thread
                    # overlapping the next block's compute; save() joins
                    # any in-flight write first
                    with _span("ckpt_snapshot", cat="host", epoch=blk_end):
                        snap = checkpoint.host_snapshot({
                            "state": state,
                            "epoch": np.int64(blk_end),
                            "trace_carry": trace_carry,
                        })
                    ckpt_writer.save(
                        ckpt_path, snap,
                        span=lambda _e=blk_end: _span(
                            "ckpt_write", cat="host", epoch=_e
                        ),
                    )
                else:
                    # multi-process: allgather the global-mesh state to
                    # host; checkpoint.save coordinates the one-writer
                    # snapshot (checkpoint_dir visible to all processes)
                    with _span("checkpoint", cat="host", epoch=blk_end):
                        checkpoint.save(
                            ckpt_path, _boundary_payload(blk_end)
                        )
            # --- graceful preemption drain (chaos/crashpoint.py) -------
            # a SIGTERM/SIGINT that landed since the last boundary, or a
            # scheduled preempt= notice whose epoch this block reached:
            # drain the pipeline, join the writer, force-snapshot at
            # THIS boundary, leave the PREEMPTED marker, and raise — the
            # CLI exits PREEMPTED_EXIT, the supervisor relaunches
            # without charging its budget, and the resume replays at
            # most the block that was in flight when the notice arrived
            preempt_reason = None
            if preempt_guard.requested is not None:
                preempt_reason = f"signal:{preempt_guard.requested}"
            elif preempt_at is not None and blk_end >= preempt_at[0]:
                preempt_reason = f"schedule:{preempt_at[0]}@{preempt_at[1]}"
            if preempt_reason is not None:
                t_preempt = time.perf_counter()
                with _span("preempt_drain", cat="host", epoch=blk_end):
                    if pending is not None:
                        _drain(pending)
                        pending = None
                    if ckpt_writer is not None:
                        # joins the in-flight (possibly just-dispatched)
                        # async save; re-raises its errors
                        ckpt_writer.wait()
                    if ckpt_path and not ckpt_due:
                        # boundary snapshot: nothing past this block
                        # existed, so the resume loses NOTHING that ran
                        checkpoint.save(
                            ckpt_path, _boundary_payload(blk_end)
                        )
                info = {
                    "reason": preempt_reason,
                    "epoch": int(blk_end),
                    "snapshot": bool(ckpt_path),
                    "drain_s": round(time.perf_counter() - t_preempt, 4),
                }
                if registry is not None:
                    registry.gauge("preemptions_total", 1.0)
                if checkpoint_dir and multihost.is_primary():
                    info["marker"] = crashpoint.write_marker(
                        checkpoint_dir, info
                    )
                raise crashpoint.GracefulPreemption(info)
            if blk_end == fault_epoch:  # pipeline off under fault_inject
                if fault_mode == "crash":
                    os._exit(13)
                while True:  # "hang": alive but no progress (no heartbeat)
                    time.sleep(3600)
            # seeded kill drill: the boundary is fully processed (host
            # work drained or deferred, due checkpoint committed)
            crashpoint.hit("loop.block_end")
            bi += 1
        if pending is not None:
            _drain(pending)
            pending = None
        if memb_recs_pending and history:
            # transitions at the FINAL epoch boundary have no next block
            # record to ride: attach them to the returned history's last
            # record so the in-process log stays complete (the JSONL
            # stream already emitted that line — its readers replay from
            # the schedule rider, which names every event regardless)
            history[-1].setdefault("membership_transitions", [])
            history[-1]["membership_transitions"] += memb_recs_pending
            memb_recs_pending = []
        if ckpt_writer is not None:
            ckpt_writer.wait()  # on-exit join barrier; re-raises errors
    finally:
        _root_span.close()
        if ckpt_writer is not None:
            # unwind path: join without masking the primary exception
            ckpt_writer.close(raise_errors=False)
        if prefetcher is not None:
            prefetcher.close()

    return state, history
