"""Measured dispatch policy for the fused_mix_sgd Pallas kernel.

Round-4 chip capture (KERNELS_TPU.json): the kernel is ~1.0x on one big
lane-aligned leaf but 0.87x on the flagship ResNet's real 86-leaf tree —
86 separate launches lose to XLA's fused elementwise chains. Packing the
tree into one superleaf would add a concat+split pass over every element
(strictly worse than XLA's fusion), so the honest mechanism is the same
one flash_tuning uses: measure on chip, record the verdict, and demote
the losing case automatically.

`fused_tuning.json` (next to this module) is written by
`bench_kernels.py fused` on the real chip:

  {"platform": "...", "tree_speedup": 0.87, "single_leaf_speedup": 1.0}

Policy: `tree_fused_ok()` gates the MULTI-LEAF pytree case of
train.steps' fused tail. With no table the kernel runs (legacy
behavior); a measured tree_speedup < 1.0 demotes it. EG_FORCE_FUSED=1
overrides (manual experiments). Single-leaf callers are not affected —
their measured case is ~break-even and the kernel keeps its guaranteed
one-HBM-pass property there.
"""

from __future__ import annotations

import functools
import json
import os

_TABLE_PATH = os.path.join(os.path.dirname(__file__), "fused_tuning.json")

#: a params pytree with at most this many leaves counts as "single-leaf
#: like" (launch overhead amortized); above it the tree verdict governs
SMALL_TREE_LEAVES = 4


@functools.lru_cache(maxsize=1)
def _table():
    try:
        with open(_TABLE_PATH) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}


def tree_fused_ok(n_leaves: int) -> bool:
    """Should the fused Pallas tail run on an `n_leaves`-leaf tree?

    True when the tree is small (launch overhead amortized over few
    launches), when no measurement exists (legacy opt-in behavior), when
    the chip measured a win, or when EG_FORCE_FUSED=1 pins it on.
    """
    if os.environ.get("EG_FORCE_FUSED") == "1":
        return True
    if n_leaves <= SMALL_TREE_LEAVES:
        return True
    ratio = _table().get("tree_speedup")
    return ratio is None or float(ratio) >= 1.0
