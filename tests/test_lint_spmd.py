"""Tier-1 source lints, served by the shared AST lint framework.

The shard_map skip-pattern rules (the one `requires_shard_map` marker
in tests/_spmd.py, no re-spelled skipifs, an honest seed-exemption
list) and the package rules (exit-code literals confined to
exitcodes.py, `os._exit` confined to chaos/crashpoint.py, no
`block_until_ready`/`device_get` on traced paths) all live as `Rule`
objects in eventgrad_tpu/analysis/lint.py — this file asserts the repo
is clean rule by rule (so a failure names its rule) and proves each
rule can actually FIRE by feeding it seeded-violation sources.  The
grep plumbing that used to live here moved into the framework with the
failure messages preserved; tests/test_crashpoint.py's instrumentation
lint rides the same framework.
"""

import os

from eventgrad_tpu.analysis import lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_rule(rule, files=None):
    return rule.check(files if files is not None else lint.collect_sources(REPO))


def _fmt(violations):
    return "\n".join(str(v) for v in violations)


# --- the repo is clean, rule by rule ----------------------------------------


def test_shard_map_tests_use_shared_marker():
    """Any non-seed test file touching shard_map imports the single
    `requires_shard_map` definition from tests/_spmd.py."""
    offenders = _run_rule(lint.ShardMapMarkerImport())
    assert not offenders, _fmt(offenders)


def test_no_respelled_shard_map_skipif():
    """Nobody — seed files included — re-spells the skipif condition:
    the definition lives in tests/_spmd.py and nowhere else."""
    offenders = _run_rule(lint.ShardMapRespell())
    assert not offenders, _fmt(offenders)


def test_seed_exemption_list_matches_reality():
    """The exemption list stays honest: every exempt file still exists
    and still touches shard_map (a renamed/retired file must leave the
    list, or the lint silently covers nothing)."""
    offenders = _run_rule(lint.ShardMapExemptHonest())
    assert not offenders, _fmt(offenders)


def test_exit_code_literals_confined():
    """The process exit codes are a cross-process contract owned by
    eventgrad_tpu/exitcodes.py; the package spells them by name."""
    offenders = _run_rule(lint.ExitCodeLiterals())
    assert not offenders, _fmt(offenders)


def test_os_exit_confined():
    """`os._exit` belongs to the crashpoint engine (one named, honesty-
    checked exemption: train/loop.py's fault_inject hard-kill)."""
    offenders = _run_rule(lint.OsExitConfined())
    assert not offenders, _fmt(offenders)


def test_no_host_sync_on_traced_paths():
    """No block_until_ready/device_get in parallel/, ops/, or
    train/steps.py — host round-trips the dispatch pipeline cannot
    hide."""
    offenders = _run_rule(lint.NoHostSyncInTraced())
    assert not offenders, _fmt(offenders)


def test_wall_clock_confined():
    """Wall-clock timing (`time.time`/`time.perf_counter`/
    `time.monotonic`) is confined to obs/ — spans are the one timing
    API; the pre-existing metric sites are exempt by name."""
    offenders = _run_rule(lint.WallClockConfined())
    assert not offenders, _fmt(offenders)


def test_pallas_kernels_registered():
    """Every pallas_call site in the package references a kernel with a
    declared rank-dim signature (analysis/kernels.py), and no registry
    entry has gone stale (one named exemption: the auditor's seeded
    oracle source)."""
    offenders = _run_rule(lint.PallasKernelRegistered())
    assert not offenders, _fmt(offenders)


def test_full_lint_run_clean():
    """The aggregate entry point tools/audit.py pins in the artifact."""
    violations = lint.run(root=REPO)
    assert not violations, _fmt(violations)


# --- and every rule can FIRE (seeded-violation oracles) ---------------------


def _pkg_file(rel, text):
    return lint.SourceFile(path="/" + rel, rel=rel, text=text)


def test_rules_detect_seeded_violations():
    sep = os.sep
    bad_exit = _pkg_file(
        f"eventgrad_tpu{sep}bad.py", "import sys\nsys.exit(77)\n"
    )
    bad_os_exit = _pkg_file(
        f"eventgrad_tpu{sep}bad2.py", "import os\nos._exit(1)\n"
    )
    bad_sync = _pkg_file(
        f"eventgrad_tpu{sep}parallel{sep}bad3.py",
        "def f(x):\n    return x.block_until_ready()\n",
    )
    bad_marker = _pkg_file(
        f"tests{sep}test_bad4.py",
        "import jax\njax.shard_map\n",
    )
    bad_respell = _pkg_file(
        f"tests{sep}test_bad5.py",
        'import pytest, jax\n'
        'm = pytest.mark.skipif(not hasattr(jax, "shard_map"), reason="x")\n',
    )
    assert _run_rule(lint.ExitCodeLiterals(), [bad_exit])
    assert _run_rule(lint.OsExitConfined(), [bad_os_exit])
    assert _run_rule(lint.NoHostSyncInTraced(), [bad_sync])
    assert _run_rule(lint.ShardMapMarkerImport(), [bad_marker])
    assert _run_rule(lint.ShardMapRespell(), [bad_respell])
    # comments and docstrings never false-positive (the AST advantage
    # over the old grep): 77 in prose is not a violation
    ok_comment = _pkg_file(
        f"eventgrad_tpu{sep}ok.py",
        '"""exit 77 is the integrity abort."""\n# also 83 here\nX = 1\n',
    )
    assert not _run_rule(lint.ExitCodeLiterals(), [ok_comment])


def test_wall_clock_rule_fires_on_seeded_violations():
    """The timing-confinement rule detects a stray perf_counter on a
    traced-adjacent path, a `from time import` alias, and a stale
    exemption — and obs/ itself stays allowed."""
    sep = os.sep
    bad_call = _pkg_file(
        f"eventgrad_tpu{sep}parallel{sep}bad6.py",
        "import time\n\ndef f():\n    return time.perf_counter()\n",
    )
    bad_from = _pkg_file(
        f"eventgrad_tpu{sep}chaos{sep}bad7.py",
        "from time import monotonic\n",
    )
    bad_alias = _pkg_file(
        f"eventgrad_tpu{sep}train{sep}bad7b.py",
        "import time as clock\n\nT0 = clock.perf_counter()\n",
    )
    ok_obs = _pkg_file(
        f"eventgrad_tpu{sep}obs{sep}ok8.py",
        "import time\n\nT0 = time.perf_counter()\n",
    )
    assert _run_rule(lint.WallClockConfined(), [bad_call])
    assert _run_rule(lint.WallClockConfined(), [bad_from])
    assert _run_rule(lint.WallClockConfined(), [bad_alias])
    assert not _run_rule(lint.WallClockConfined(), [ok_obs])
    # comments/docstrings never false-positive (AST, not grep)
    ok_prose = _pkg_file(
        f"eventgrad_tpu{sep}ok9.py",
        '"""never call time.perf_counter() here"""\nX = 1\n',
    )
    assert not _run_rule(lint.WallClockConfined(), [ok_prose])
    # a stale exemption (file stopped reading the clock) fires too
    rel = f"eventgrad_tpu{sep}supervise.py"
    stale = _pkg_file(rel, "X = 1\n")
    live = _pkg_file(rel, "import time\n\nNOW = time.time()\n")
    assert _run_rule(lint.WallClockConfined(), [stale])
    assert not _run_rule(lint.WallClockConfined(), [live])


def test_pallas_rule_fires_on_seeded_violations():
    """The declared-kernel lint detects an unregistered kernel, an
    unresolvable kernel argument, a kernel registered for a DIFFERENT
    module, and a stale registry entry — while the real call sites
    (functools.partial / conditional kernels included) stay clean."""
    sep = os.sep
    rule = lint.PallasKernelRegistered()
    bad_unreg = _pkg_file(
        f"eventgrad_tpu{sep}ops{sep}bad10.py",
        "import jax.experimental.pallas as pl\n"
        "def _mystery_kernel(x_ref, o_ref):\n    o_ref[...] = x_ref[...]\n"
        "out = pl.pallas_call(_mystery_kernel, out_shape=None)(1)\n",
    )
    viols = rule.check([bad_unreg])
    assert any("_mystery_kernel" in v.message for v in viols), _fmt(viols)
    bad_opaque = _pkg_file(
        f"eventgrad_tpu{sep}ops{sep}bad11.py",
        "import jax.experimental.pallas as pl\n"
        "KERNELS = {}\n"
        "out = pl.pallas_call(KERNELS['k'], out_shape=None)(1)\n",
    )
    viols = rule.check([bad_opaque])
    assert any("not statically resolvable" in v.message for v in viols)
    # keyword-form calls cannot dodge the rule either
    bad_kw = _pkg_file(
        f"eventgrad_tpu{sep}ops{sep}bad11b.py",
        "import jax.experimental.pallas as pl\n"
        "def _mystery_kernel(x_ref, o_ref):\n    o_ref[...] = x_ref[...]\n"
        "out = pl.pallas_call(kernel=_mystery_kernel, out_shape=None)(1)\n",
    )
    viols = rule.check([bad_kw])
    assert any("not statically resolvable" in v.message for v in viols)
    # a registered kernel name called from the WRONG module
    bad_module = _pkg_file(
        f"eventgrad_tpu{sep}ops{sep}bad12.py",
        "import jax.experimental.pallas as pl\n"
        "def _fwd_kernel(x_ref, o_ref):\n    o_ref[...] = x_ref[...]\n"
        "out = pl.pallas_call(_fwd_kernel, out_shape=None)(1)\n",
    )
    viols = rule.check([bad_module])
    assert any("registered for" in v.message for v in viols), _fmt(viols)
    # a registry module that stopped calling its kernel = stale entry
    stale = _pkg_file(
        f"eventgrad_tpu{sep}ops{sep}fused_update.py", "X = 1\n"
    )
    viols = rule.check([stale])
    assert any("gone stale" in v.message for v in viols), _fmt(viols)
    # partial(...) and conditional kernels resolve (the shipped idioms)
    ok_partial = _pkg_file(
        f"eventgrad_tpu{sep}ops{sep}fused_update.py",
        "import functools\nimport jax.experimental.pallas as pl\n"
        "def _kernel(*refs, lr): pass\n"
        "out = pl.pallas_call(functools.partial(_kernel, lr=0.1),\n"
        "                     out_shape=None)(1)\n",
    )
    assert not rule.check([ok_partial]), _fmt(rule.check([ok_partial]))
    # the exemption stays honest: audit.py without a seeded
    # unregistered kernel flags as stale
    stale_exempt = _pkg_file(
        f"eventgrad_tpu{sep}analysis{sep}audit.py", "X = 1\n"
    )
    viols = rule.check([stale_exempt])
    assert any("drop it from" in v.message for v in viols), _fmt(viols)


def test_exempt_file_exemption_stays_honest():
    """train/loop.py's os._exit exemption covers EXACTLY one call — a
    second one (or zero) is a violation again."""
    sep = os.sep
    rel = f"eventgrad_tpu{sep}train{sep}loop.py"
    two = _pkg_file(rel, "import os\nos._exit(1)\nos._exit(2)\n")
    zero = _pkg_file(rel, "X = 1\n")
    assert _run_rule(lint.OsExitConfined(), [two])
    assert _run_rule(lint.OsExitConfined(), [zero])
    one = _pkg_file(rel, "import os\nos._exit(13)\n")
    assert not _run_rule(lint.OsExitConfined(), [one])


def test_carrier_dtype_rule_clean_and_fires():
    """carrier-dtype-declared: the repo's EventState buffer sites all
    route through the arena carrier-layout helper (clean run), a seeded
    ad-hoc `.astype` inside a bufs=/buf_scales= site fires, and the
    honesty direction fires when the EventState owner stops calling
    alloc_event_bufs."""
    sep = os.sep
    rule = lint.CarrierDtypeDeclared()
    offenders = _run_rule(rule)
    assert not offenders, _fmt(offenders)

    bad_bufs = _pkg_file(
        f"eventgrad_tpu{sep}train{sep}bad_carrier.py",
        "def f(state, vals):\n"
        "    return state.replace(bufs=tuple(\n"
        "        v.astype('bfloat16') for v in vals))\n",
    )
    viols = rule.check([bad_bufs])
    assert any("ad-hoc astype" in v.message for v in viols), _fmt(viols)
    bad_scales = _pkg_file(
        f"eventgrad_tpu{sep}parallel{sep}bad_carrier2.py",
        "def g(state, s):\n"
        "    return EventState(buf_scales=s.astype('float32'))\n",
    )
    viols = rule.check([bad_scales])
    assert any("buf_scales" in v.message for v in viols), _fmt(viols)
    # the honesty direction: an owner that stopped routing through the
    # helper covers nothing and flags
    stale_owner = _pkg_file(
        f"eventgrad_tpu{sep}parallel{sep}events.py", "X = 1\n"
    )
    viols = rule.check([stale_owner])
    assert any("alloc_event_bufs" in v.message for v in viols), _fmt(viols)
    # passing existing carrier buffers through unchanged stays clean,
    # and astype on NON-buffer kwargs is out of scope
    ok_pass = _pkg_file(
        f"eventgrad_tpu{sep}train{sep}ok_carrier.py",
        "def h(state, new_bufs, x):\n"
        "    state = state.replace(bufs=new_bufs)\n"
        "    return state.replace(thres=x.astype('float32'))\n",
    )
    assert not rule.check([ok_pass]), _fmt(rule.check([ok_pass]))
    # test files may seed violations freely (package scope only)
    ok_test = _pkg_file(
        f"tests{sep}test_whatever2.py",
        "s = s.replace(bufs=b.astype('int8'))\n",
    )
    assert not rule.check([ok_test]), _fmt(rule.check([ok_test]))


def test_trigger_policy_rule_clean_and_fires():
    """trigger-policy-registered: the repo's policy-name references all
    resolve (clean run), and every detection site fires on a seeded bad
    name — train kwarg, AuditConfig(policy=), CLI choices, the
    EG_BENCH_POLICY env default — plus the stale direction (a registry
    entry the CLI flag cannot name)."""
    sep = os.sep
    rule = lint.TriggerPolicyRegistered()
    offenders = _run_rule(rule)
    assert not offenders, _fmt(offenders)

    bad_train = _pkg_file(
        f"eventgrad_tpu{sep}bad_pol.py",
        'train(algo="eventgrad", trigger_policy="bogus")\n',
    )
    bad_audit = _pkg_file(
        f"eventgrad_tpu{sep}bad_pol2.py",
        'c = AuditConfig(name="x", policy="stale_one")\n',
    )
    bad_cli = _pkg_file(
        f"eventgrad_tpu{sep}bad_pol3.py",
        'p.add_argument("--trigger-policy", choices=["norm_delta", "typo_k"])\n',
    )
    bad_env = _pkg_file(
        f"eventgrad_tpu{sep}bad_pol4.py",
        'import os\npol = os.environ.get("EG_BENCH_POLICY", "nope")\n',
    )
    for bad in (bad_train, bad_audit, bad_cli, bad_env):
        viols = rule.check([bad])
        assert any("not a registry entry" in v.message for v in viols), (
            bad.rel, _fmt(viols)
        )
    # stale direction: the CLI flag must be able to name EVERY
    # registered policy — dropping one from choices fires
    stale_cli = _pkg_file(
        f"eventgrad_tpu{sep}bad_pol5.py",
        'p.add_argument("--trigger-policy", '
        'choices=["norm_delta", "topk", "micro"])\n',
    )
    viols = rule.check([stale_cli])
    assert any("hybrid" in v.message and "missing" in v.message
               for v in viols), _fmt(viols)
    # scope honesty: a policy= kwarg on a non-AuditConfig call is not a
    # policy-name site, the empty env default means "inherit", and test
    # files may seed bad names freely
    ok_chaos = _pkg_file(
        f"eventgrad_tpu{sep}ok_pol.py", 'chaos(policy="kill_random")\n'
    )
    ok_env = _pkg_file(
        f"eventgrad_tpu{sep}ok_pol2.py",
        'import os\np = os.environ.get("EG_BENCH_POLICY", "")\n',
    )
    ok_test = _pkg_file(
        f"tests{sep}test_whatever.py", 'train(trigger_policy="bogus")\n'
    )
    for ok in (ok_chaos, ok_env, ok_test):
        assert not rule.check([ok]), ok.rel


def test_telemetry_counter_ledgered_rule_clean_and_fires():
    """telemetry-counter-ledgered: the repo routes every disposition
    count through obs.ledger.ledger_update (clean run); a seeded
    `.at[...]` mutation of the ledger's counter arrays fires, a
    computed ledger= value outside obs/ fires, and the honesty
    direction fires when the helper stops doing the scatter-adds."""
    sep = os.sep
    rule = lint.TelemetryCounterLedgered()
    offenders = _run_rule(rule)
    assert not offenders, _fmt(offenders)

    bad_scatter = _pkg_file(
        f"eventgrad_tpu{sep}train{sep}bad_ledger.py",
        "def f(tel, row):\n"
        "    return tel.ledger.counts.at[row].add(1)\n",
    )
    viols = rule.check([bad_scatter])
    assert any("ad-hoc mutation" in v.message for v in viols), _fmt(viols)
    bad_queue = _pkg_file(
        f"eventgrad_tpu{sep}parallel{sep}bad_ledger2.py",
        "def g(ledger, msgs):\n"
        "    return ledger.replace(queue=ledger.queue.at[0].add(msgs))\n",
    )
    viols = rule.check([bad_queue])
    assert any("ad-hoc mutation" in v.message for v in viols), _fmt(viols)
    bad_kwarg = _pkg_file(
        f"eventgrad_tpu{sep}train{sep}bad_ledger3.py",
        "def h(tel):\n"
        "    return tel.replace(ledger=make_counts(tel) + 1)\n",
    )
    viols = rule.check([bad_kwarg])
    assert any("computed ledger=" in v.message for v in viols), _fmt(viols)
    # the honesty direction: a helper that no longer scatter-adds the
    # counters covers nothing and flags
    stale_owner = _pkg_file(
        f"eventgrad_tpu{sep}obs{sep}ledger.py",
        "def ledger_update(led):\n    return led\n",
    )
    viols = rule.check([stale_owner])
    assert any("scatter-adds" in v.message for v in viols), _fmt(viols)
    # pass-throughs, None defaults, and helper calls stay clean; obs/
    # itself owns the counter math; tests may mutate freely
    ok_pass = _pkg_file(
        f"eventgrad_tpu{sep}train{sep}ok_ledger.py",
        "def k(tel, led):\n"
        "    tel = tel.replace(ledger=led)\n"
        "    tel = tel.replace(ledger=None)\n"
        "    return tel.replace(ledger=obs_ledger.ledger_update(led))\n",
    )
    ok_obs = _pkg_file(
        f"eventgrad_tpu{sep}obs{sep}device.py",
        "def m(tel):\n"
        "    return tel.ledger.counts.at[0].add(1)\n",
    )
    ok_test = _pkg_file(
        f"tests{sep}test_whatever.py",
        "led.counts.at[0].add(9)\n",
    )
    for ok in (ok_pass, ok_obs, ok_test):
        assert not rule.check([ok]), ok.rel
