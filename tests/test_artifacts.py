"""Committed-artifact schema validation (tools/validate_artifacts.py).

Tier-1 by design: a malformed committed artifact — truncated JSON, a
tool drifting from its documented schema, a hand-edit typo — fails the
suite instead of silently rotting the repo's evidence chain.
"""

import importlib.util
import json
import os

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "validate_artifacts", os.path.join(_ROOT, "tools", "validate_artifacts.py")
)
va = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(va)


def test_repo_artifacts_all_valid():
    out = va.validate_repo(_ROOT)
    assert out["checked"], "expected committed artifacts to validate"
    # the families with real schemas must actually be among the checked
    names = {os.path.basename(p) for p in out["checked"]}
    assert any(n.startswith("BENCH_r") for n in names)
    assert any(n.startswith("MULTICHIP_r") for n in names)
    assert "obs_report_cpu.json" in names
    # the dispatch-pipeline proof must be committed AND schema-gated
    # (pipelined-vs-serial bubble ratio < 1.0, bitwise_state true)
    assert "pipeline_bubble_cpu.json" in names
    # the elastic-membership soak proof (ISSUE 6): >= 6 transitions,
    # zero escalations, bounded recovery, bitwise replay, <= 0.5 pt gap
    assert "soak_cpu.json" in names
    # the integrity-engine proof (ISSUE 7): zero silent acceptances,
    # <= 1 hardened rollback, <= 0.5 pt gap, bitwise replay, off ==
    # today's step, <= 2% in-step overhead
    assert "integrity_cpu.json" in names
    # the crash-consistency proof (ISSUE 8): every crash site x config
    # cell killed at the armed seam, resumed, bitwise final state and
    # history; zero unresumable cells, zero silent data loss; graceful
    # preemption <= 1 dispatch block
    assert "crash_matrix_cpu.json" in names
    # the trace-auditor proof (ISSUE 9): the full step-config matrix
    # reports zero rank-isolation violations with exact wire-byte
    # truth, every seeded oracle violation detected, zero lint
    # violations (tools/audit.py, AUDIT_SCHEMA)
    assert "audit_cpu.json" in names
    # the bucketed-gossip-schedule proof (ISSUE 10): K-sweep overhead
    # <= 1.02 vs monolithic, bitwise state, jaxpr interleaving gate
    # (BUCKETED_ABLATION_SCHEMA)
    assert "bucketed_ablation_cpu.json" in names
    # the perf ledger (ISSUE 11): all six BENCH rounds in one
    # trajectory with MFU/roofline populated and every
    # ratio-vs-previous-round regression gate passing
    # (PERF_LEDGER_SCHEMA pins gates_all_ok)
    assert "perf_ledger_cpu.json" in names
    # the real-mesh SPMD proof (ISSUE 14): EventGraD-vs-D-PSGD step
    # ratio with REAL collectives on an 8-device mesh, bitwise state
    # across the lifts, mesh-program audit clean at production
    # geometry with the seeded mesh oracle caught, and the 64-rank
    # scale leg's wire bytes exact (MESH_ABLATION_SCHEMA)
    assert "mesh_ablation_cpu.json" in names
    # the bounded-async proof (ISSUE 15): under an injected persistent
    # straggler, D >= 2 strictly beats the lockstep's modeled step
    # time at a <= 0.5 pt accuracy gap, with every bounded leg
    # replaying bitwise (STRAGGLER_ABLATION_SCHEMA)
    assert "straggler_ablation_cpu.json" in names
    # the trigger-policy frontier (ISSUE 16): >= 4 policies x >= 2 wire
    # dtypes of real train() legs; micro's measured bytes strictly
    # below topk's at equal capacity, per-policy dtype accuracy gap
    # <= 0.5 pt, f32 legs replay bitwise (FRONTIER_SCHEMA)
    assert "frontier_cpu.json" in names
    # the carrier-residency proof (ISSUE 17): buffer-consumer analytic
    # bytes drop >= 25% with the whole-step drop strictly positive,
    # scanned paired step ratio <= 1.02, bitwise state
    # (RESIDENT_ABLATION_SCHEMA)
    assert "resident_ablation_cpu.json" in names
    # the message-lifecycle conservation proof (ISSUE 18): every flush
    # window audits ok, zero violations, all dispositions exercised,
    # both leak oracles caught, obs='off' bitwise-unchanged
    # (LEDGER_CONSERVATION_SCHEMA)
    assert "ledger_conservation_cpu.json" in names
    assert out["errors"] == []


def test_ledger_conservation_gates_encoded_in_schema():
    """The conservation gates live IN the schema: a window that fails
    its audit, a nonzero violation count, a missed leak oracle, or a
    perturbed obs='off' run is a schema violation, not a judgment
    call."""
    with open(os.path.join(
        _ROOT, "artifacts", "ledger_conservation_cpu.json"
    )) as f:
        rec = json.load(f)
    assert va.validate(rec, va.LEDGER_CONSERVATION_SCHEMA) == []
    for k, bad in [
        ("all_dispositions_exercised", False),
        ("all_leaks_caught", False),
        ("obs_off_deterministic", False),
        ("obs_off_matches_obs_run", False),
        ("conservation", dict(rec["conservation"], violations=3)),
        ("conservation", dict(rec["conservation"], all_windows_ok=False)),
        ("leak_oracles", [dict(rec["leak_oracles"][0], caught=False)]
         + rec["leak_oracles"][1:]),
        ("windows", [dict(rec["windows"][0], audit_ok=False)]
         + rec["windows"][1:]),
        ("leak_oracles", rec["leak_oracles"][:1]),  # minItems 2
    ]:
        broken = dict(rec, **{k: bad})
        assert va.validate(broken, va.LEDGER_CONSERVATION_SCHEMA), (
            f"schema must reject {k}={bad!r}"
        )


def test_resident_gates_encoded_in_schema():
    """The carrier-residency gates live IN the schema: an artifact
    violating a gate is a schema violation, not a judgment call."""
    with open(os.path.join(
        _ROOT, "artifacts", "resident_ablation_cpu.json"
    )) as f:
        rec = json.load(f)
    assert va.validate(rec, va.RESIDENT_ABLATION_SCHEMA) == []
    for k, bad in [
        ("bitwise_state", False),
        ("step_ratio", 1.2),
        ("consumer_bytes_drop_pct", 20.0),
        ("analytic_bytes_drop_pct", -1.0),
        ("analytic_bytes_drop_pct", 0.0),
    ]:
        broken = dict(rec, **{k: bad})
        assert va.validate(broken, va.RESIDENT_ABLATION_SCHEMA), (
            f"schema must reject {k}={bad!r}"
        )


def test_frontier_gates_encoded_in_schema():
    """The frontier gates live IN the schema: an artifact violating a
    gate is a schema violation, not a judgment call."""
    with open(os.path.join(_ROOT, "artifacts", "frontier_cpu.json")) as f:
        rec = json.load(f)
    assert va.validate(rec, va.FRONTIER_SCHEMA) == []
    for k, bad in [
        ("micro_below_topk_bytes", False),
        ("replay_bitwise", False),
        ("acc_gap_pt", 0.8),
        ("n_policies", 3),
        ("n_wire_dtypes", 1),
    ]:
        broken = dict(rec, **{k: bad})
        assert va.validate(broken, va.FRONTIER_SCHEMA), (
            f"schema must reject {k}={bad!r}"
        )
    # a leg whose replay broke must also be rejected
    legs = [dict(l) for l in rec["legs"]]
    f32 = next(l for l in legs if "replay_bitwise" in l)
    f32["replay_bitwise"] = False
    assert va.validate(dict(rec, legs=legs), va.FRONTIER_SCHEMA)


def test_validator_flags_schema_violations():
    assert va.validate(5, {"type": "string"})  # wrong type
    assert va.validate(True, {"type": "integer"})  # bool is not integer
    assert not va.validate(5, {"type": ["string", "integer"]})
    assert va.validate({}, {"type": "object", "required": ["metric"]})
    assert va.validate({"v": -1}, {
        "type": "object", "properties": {"v": {"minimum": 0}},
    })
    assert va.validate([1], {"type": "array", "minItems": 2})
    assert va.validate(["x"], {"type": "array", "items": {"type": "number"}})
    assert va.validate("bad", {"enum": ["good"]})
    # nested paths name the offending key
    errs = va.validate(
        {"results": {"obs_on": {}}},
        {"type": "object",
         "properties": {"results": {
             "type": "object",
             "properties": {"obs_on": {
                 "type": "object", "required": ["step_ms_p50"],
             }},
         }}},
    )
    assert errs and "obs_on" in errs[0] and "step_ms_p50" in errs[0]


def test_validator_flags_malformed_files(tmp_path):
    bad_json = tmp_path / "bad.json"
    bad_json.write_text("{truncated")
    assert va.validate_json_file(str(bad_json), {"type": "object"})

    bad_jsonl = tmp_path / "bad.jsonl"
    bad_jsonl.write_text(
        json.dumps({"ok": 1}) + "\n" + "not json\n"
    )
    errs = va.validate_jsonl_file(str(bad_jsonl))
    assert len(errs) == 1 and ":2:" in errs[0]

    good = tmp_path / "good.jsonl"
    good.write_text(json.dumps({"ok": 1}) + "\n\n" + json.dumps({"b": 2}) + "\n")
    assert va.validate_jsonl_file(str(good)) == []


def test_repo_validation_catches_planted_corruption(tmp_path):
    """End-to-end: a repo clone with one corrupted artifact fails."""
    art = tmp_path / "artifacts"
    art.mkdir()
    (art / "some_measurement.json").write_text('{"ok": true}')
    assert va.validate_repo(str(tmp_path))["errors"] == []
    (art / "broken.json").write_text('{"ok": ')
    errs = va.validate_repo(str(tmp_path))["errors"]
    assert len(errs) == 1 and "broken.json" in errs[0]
