"""Integrity sweep: the end-to-end proof that corruption cannot pass silently.

The robustness analogue of chaos_sweep's drop curve, for LYING peers and
SICK ranks (docs/chaos.md "Integrity & rollback"): run a seeded
bitflip+nanstep chaos schedule against the integrity engine and account
for every injected corruption. A corruption is SILENTLY ACCEPTED when it
enters the final committed training trajectory without ever being
detected — i.e. it was neither rejected at the wire (checksum), nor
quarantined at the step (finite guard), nor erased by a
rollback-to-last-good. The artifact proves that number is ZERO.

Five legs, one JSON artifact (artifacts/integrity_cpu.json, schema-gated
by INTEGRITY_SCHEMA in tools/validate_artifacts.py):

  * baseline  — the fault-free run (no chaos, no integrity): the
                accuracy yardstick.
  * faulted   — the same op-point under `bitflip=` (wire corruption on a
                mid-run window) + `nanstep=` (one rank's grads poisoned)
                with checksums ON but quarantine OFF (escalate=True):
                every bitflip is rejected at the wire; the nanstep lands
                — detection comes too late by construction — the
                divergence sentinel trips, the loop restores
                last-known-good, HARDENS (quarantine on) and replays,
                where the same pass-keyed nanstep is quarantined. The
                zero-silent-acceptance ledger reconciles observed
                wire_rejects / quarantined_steps / the rollback against
                the host-replayed ground truth
                (chaos.inject.corruption_table, pass-exact — the
                replayed segment's draws counted twice, exactly like
                the engine meets them).
  * replay    — the faulted leg re-run from the seed: parameters and
                every integrity counter must be bitwise/equal —
                faults, trip, rollback and hardened replay are all
                deterministic.
  * off       — integrity="off" vs no flag at all: bitwise-identical
                parameters (resolve("off") -> None; the traced step IS
                today's step).
  * overhead  — checksum+quarantine cost on the traced step: the
                overhead_ablation protocol (one jitted scan-of-K
                program per variant — the production dispatch shape —
                interleaved rounds, MEDIAN PAIRED per-round ratios; the
                only stable step-time estimator on a noisy shared CPU).
                Acceptance: p50 ratio <= 1.02.

Runs on CPU in ~2 min. Usage:
    python tools/integrity_sweep.py [--epochs 6] [--seed 0]
                                    [--rounds 8] [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from eventgrad_tpu.utils import compile_cache

compile_cache.honor_cpu_pin()
compile_cache.enable()

import optax

from eventgrad_tpu.chaos import inject
from eventgrad_tpu.chaos.integrity import IntegrityConfig
from eventgrad_tpu.chaos.schedule import ChaosSchedule
from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.data.sharding import batched_epoch
from eventgrad_tpu.models import MLP
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.spmd import spmd
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.loop import train
from eventgrad_tpu.train.state import init_train_state
from eventgrad_tpu.train.steps import make_train_step

# the chaos_sweep miniature op-point (trains to >50% in seconds/CPU);
# constant-threshold events keep the wire active from pass one, so the
# bitflip window has payloads to corrupt on every edge it draws
N_RANKS = 4
BATCH = 16
LR = 0.1
EVENT_CFG = EventConfig(adaptive=True, horizon=0.95, warmup_passes=5,
                        max_silence=5)

#: the faulted leg's integrity config: checksums on, quarantine OFF —
#: the nanstep must LAND so the sentinel/rollback path is exercised;
#: escalate=True hardens the replay (quarantine on) so the replayed
#: nanstep is caught at the step instead of burning the budget
FAULT_CFG = IntegrityConfig(checksum=True, quarantine=False,
                            escalate=True, max_rollbacks=1)


def _params_equal_bitwise(a, b) -> bool:
    return all(
        np.array_equal(np.asarray(la), np.asarray(lb))
        for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


def _data(n_train=2048, n_test=256):
    x, y = synthetic_dataset(n_train, (8, 8, 1), seed=1)
    xt, yt = synthetic_dataset(n_test, (8, 8, 1), seed=1, split="test")
    return x, y, xt, yt


def _run(x, y, xt, yt, epochs, seed, chaos=None, integrity=None):
    return train(
        MLP(hidden=32), Ring(N_RANKS), x, y,
        algo="eventgrad", epochs=epochs, batch_size=BATCH,
        learning_rate=LR, event_cfg=EVENT_CFG, seed=seed,
        x_test=xt, y_test=yt, chaos=chaos, integrity=integrity,
        log_every_epoch=True,
    )


def _fault_schedule(seed: int, spe: int, epochs: int) -> ChaosSchedule:
    """bitflip window across the middle third; one nanstep at ~2/3 of
    the run, AFTER the window (the NaN segment must not eat the window's
    rejection accounting) and early enough that the post-rollback replay
    still has epochs left to converge."""
    total = spe * epochs
    return ChaosSchedule.parse(
        f"seed={seed + 13},"
        f"bitflip={total // 3}-{2 * total // 3}@0.15,"
        f"nanstep=2@{2 * total // 3 + spe // 2}"
    )


def _silent_acceptance_ledger(sched, epochs, spe, hist):
    """Reconcile observed integrity counters against the host-replayed
    injection ground truth; returns the ledger dict (silent == 0 is the
    headline). The replayed segment (restored_epoch, tripped_epoch]
    executes twice — replay is pass-keyed, so its scheduled draws are
    met twice and must be expected twice."""
    topo = Ring(N_RANKS)
    total = spe * epochs
    per_pass = inject.corruption_table(sched, topo, total).sum(axis=(1, 2))

    rbs = [r["integrity_rollback"] for r in hist if "integrity_rollback" in r]
    expected_flips = int(per_pass.sum())
    replayed_nansteps = 0
    for rb in rbs:
        lo, hi = rb["restored_epoch"] * spe, rb["tripped_epoch"] * spe
        expected_flips += int(per_pass[lo:hi].sum())
        replayed_nansteps += sum(
            1 for _r, t in sched.nanstep if lo < t <= hi
        )

    wire_rejects = sum(r.get("wire_rejects", 0) for r in hist)
    quarantined = sum(r.get("quarantined_steps", 0) for r in hist)
    nominal_nansteps = inject.nansteps_in_range(sched, N_RANKS, total)
    nanstep_visits = nominal_nansteps + replayed_nansteps
    # every nanstep visit is either quarantined at the step or landed
    # inside a segment a rollback later erased
    rollback_covered = replayed_nansteps
    silent = (expected_flips - wire_rejects) + (
        nanstep_visits - quarantined - rollback_covered
    )
    return {
        "injected_bitflips": expected_flips,
        "injected_nansteps": nanstep_visits,
        "wire_rejects": wire_rejects,
        "quarantined_steps": quarantined,
        "rollback_covered_nansteps": rollback_covered,
        "silent_acceptances": silent,
    }


def _overhead_leg(seed: int, n_rounds: int, K: int = 16):
    """Traced-step cost of the in-step defenses (checksum + quarantine,
    no faults): the overhead_ablation protocol AND op-point (LeNetCifar
    on Ring(8), the bench production shape — a step where compute
    amortizes the per-exchange integer reduction; the MLP miniature's
    sub-ms steps would price the checksum against nothing) — one jitted
    scan-of-K-steps program per variant, interleaved rounds, median
    paired per-round ratio."""
    from eventgrad_tpu.data.datasets import load_or_synthesize
    from eventgrad_tpu.models import LeNetCifar

    topo = Ring(8)
    per_rank = 8
    model = LeNetCifar()
    tx = optax.sgd(1e-2, momentum=0.9)
    cfg = EventConfig(
        adaptive=True, horizon=1.05, warmup_passes=10, max_silence=50
    )
    x, y = load_or_synthesize("cifar10", None, "train", n_synth=1024)
    xb, yb = batched_epoch(x, y, topo.n_ranks, per_rank)
    xs = jnp.asarray(np.stack([xb[:, s % xb.shape[1]] for s in range(K)], 0))
    ys = jnp.asarray(np.stack([yb[:, s % yb.shape[1]] for s in range(K)], 0))

    variants = {}
    for name, integ in (
        ("off", None),
        ("on", IntegrityConfig(sentinel=False, rollback=False)),
    ):
        state = init_train_state(
            model, x.shape[1:], tx, topo, "eventgrad", cfg, seed=seed
        )
        lifted = spmd(make_train_step(
            model, tx, topo, "eventgrad", event_cfg=cfg,
            integrity=integ,
        ), topo)

        def run(s, xs, ys, _l=lifted):
            return jax.lax.scan(lambda s, b: _l(s, b), s, (xs, ys))

        run = jax.jit(run)
        out, _ = run(state, xs, ys)  # compile + warm
        jax.block_until_ready(out.params)
        variants[name] = (state, run)

    times = {k: [] for k in variants}
    for _ in range(n_rounds):
        for k, (state, run) in variants.items():
            t0 = time.perf_counter()
            out, _ = run(state, xs, ys)
            jax.block_until_ready(out.params)
            times[k].append((time.perf_counter() - t0) / K * 1000)

    from eventgrad_tpu.utils.metrics import median as _median

    paired = [on / off for on, off in zip(times["on"], times["off"])]
    return {
        "protocol": "scan-of-%d, %d interleaved rounds, median paired "
                    "per-round on/off ratios" % (K, n_rounds),
        "step_ms_off_p50": round(_median(times["off"]), 4),
        "step_ms_on_p50": round(_median(times["on"]), 4),
        "overhead_ratio_p50": round(_median(paired), 4),
        "n_rounds": n_rounds,
    }


def run_sweep(epochs: int, seed: int, n_rounds: int, out_path: str):
    t_start = time.time()
    x, y, xt, yt = _data()

    # --- baseline: the fault-free yardstick ----------------------------
    st_base, hist_base = _run(x, y, xt, yt, epochs, seed)
    spe = int(hist_base[0]["steps"])
    acc_base = float(hist_base[-1]["test_accuracy"])
    print(json.dumps({"leg": "baseline", "acc": acc_base, "steps_per_epoch":
                      spe}), flush=True)

    # --- faulted: bitflips rejected, nanstep -> rollback -> hardened ---
    sched = _fault_schedule(seed, spe, epochs)
    st_f, hist_f = _run(
        x, y, xt, yt, epochs, seed, chaos=sched, integrity=FAULT_CFG,
    )
    rbs = [r["integrity_rollback"] for r in hist_f
           if "integrity_rollback" in r]
    rollbacks = hist_f[-1]["integrity_rollbacks"]
    ledger = _silent_acceptance_ledger(sched, epochs, spe, hist_f)
    acc_f = float(hist_f[-1]["test_accuracy"])
    assert all(np.isfinite(np.asarray(l)).all()
               for l in jax.tree.leaves(st_f.params))
    print(json.dumps({"leg": "faulted", "acc": acc_f,
                      "rollbacks": rollbacks, **ledger}), flush=True)
    assert ledger["silent_acceptances"] == 0, ledger
    assert rollbacks == 1 and rbs and rbs[0]["hardened"], (
        "the sweep schedule is built to trip exactly one hardened "
        "rollback; got %r" % (rbs,)
    )

    # --- replay: the whole story is deterministic from the seed --------
    st_r, hist_r = _run(
        x, y, xt, yt, epochs, seed, chaos=sched, integrity=FAULT_CFG,
    )
    replay_bitwise = _params_equal_bitwise(st_f.params, st_r.params) and (
        [(r.get("wire_rejects"), r.get("quarantined_steps"),
          r.get("integrity_rollbacks")) for r in hist_f]
        == [(r.get("wire_rejects"), r.get("quarantined_steps"),
             r.get("integrity_rollbacks")) for r in hist_r]
    )
    print(json.dumps({"leg": "replay", "bitwise": replay_bitwise}),
          flush=True)

    # --- off: `--integrity off` IS today's traced step -----------------
    st_off, _ = _run(x, y, xt, yt, 2, seed, integrity="off")
    st_none, _ = _run(x, y, xt, yt, 2, seed)
    off_bitwise = _params_equal_bitwise(st_off.params, st_none.params)
    print(json.dumps({"leg": "off", "bitwise": off_bitwise}), flush=True)

    # --- overhead ------------------------------------------------------
    overhead = _overhead_leg(seed, n_rounds)
    print(json.dumps({"leg": "overhead", **overhead}), flush=True)

    out = {
        "bench": "integrity",
        "platform": jax.devices()[0].platform,
        "op_point": {
            "model": "mlp32", "n_ranks": N_RANKS, "batch": BATCH,
            "epochs": epochs, "steps_per_epoch": spe, "lr": LR,
            "event_cfg": "adaptive h=0.95 warmup=5 max_silence=5",
        },
        "schedule": sched.to_dict(),
        "integrity": FAULT_CFG.to_dict(),
        **ledger,
        "rollbacks": rollbacks,
        "rollback": rbs[0],
        "final_acc_baseline": round(acc_base, 2),
        "final_acc_faulted": round(acc_f, 2),
        "acc_gap_pt": round(abs(acc_base - acc_f), 2),
        "replay_bitwise": bool(replay_bitwise),
        "integrity_off_bitwise": bool(off_bitwise),
        "overhead": overhead,
        "wall_s": round(time.time() - t_start, 1),
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--rounds", type=int, default=8,
                    help="overhead-leg interleaved rounds")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out_path = args.out or os.path.join(
        repo, "artifacts",
        f"integrity_{jax.devices()[0].platform}.json",
    )
    out = run_sweep(args.epochs, args.seed, args.rounds, out_path)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
