"""Chaos sweep: drop-rate vs accuracy and recovery-latency curves.

The robustness analogue of BASELINE.json's msgs-saved-vs-accuracy
headline: how much wire loss can EventGraD's stale-buffer semantics
absorb before accuracy collapses, how fast do the recovery policies
(chaos/policy.py) restore consensus after a flaky window, and how does a
ring heal after a permanent peer death. Everything is deterministic — the
serialized schedules ride in the artifact, so every point replays.

Three legs, one JSON artifact (artifacts/chaos_sweep_<platform>.json):

  * drop curve   — train() at >= 3 drop rates on the miniature op-point;
                   final consensus-model test accuracy, per-edge silence
                   maxima / injected-drop counts / consensus error per
                   point. The 0.0 point doubles as the regression guard:
                   its trajectory must be BITWISE-identical to a chaos=None
                   run (also asserted in tests/test_chaos.py).
  * flaky window — a total blackout window mid-run with the forced-sync
                   policy on; recovery latency = passes from window end
                   until consensus error returns to its pre-window level.
  * ring heal    — permanent death of one rank; detection latency (silence
                   crossing the suspect bound, chaos/monitor.edge_status),
                   then policy.apply_ring_heal to the survivor ring and
                   passes until survivor consensus recovers.

Runs on CPU in tier-1 time (~30 s; MLP miniature, the test_loop op-point).
Also reachable as bench.py's chaos mode: EG_BENCH_CHAOS=1 python bench.py.

Usage: python tools/chaos_sweep.py [--drops 0,0.2,0.5] [--epochs 6]
                                   [--seed 0] [--out PATH] [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from eventgrad_tpu.utils import compile_cache

compile_cache.honor_cpu_pin()
# persistent XLA cache: repeated sweep invocations must not re-pay the
# jit compile per process (no-op on the CPU backend)
compile_cache.enable()

from eventgrad_tpu.chaos import monitor as chaos_monitor
from eventgrad_tpu.chaos.policy import RecoveryPolicy, apply_ring_heal
from eventgrad_tpu.chaos.schedule import ChaosSchedule, FlakyWindow
from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.data.sharding import batched_epoch
from eventgrad_tpu.models import MLP
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.spmd import spmd
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.loop import train
from eventgrad_tpu.train.state import init_train_state
from eventgrad_tpu.train.steps import make_train_step

# miniature op-point (test_loop scale: trains to >50% on the prototype
# task in seconds on one CPU core); max_silence=5 gives the sender-side
# silence guarantee the monitor needs to classify edges, and the policy
# bounds sit comfortably above it (policy.validate_against)
N_RANKS = 4
BATCH = 16
LR = 0.1
EVENT_CFG = EventConfig(
    adaptive=True, horizon=0.95, warmup_passes=5, max_silence=5
)
POLICY = RecoveryPolicy(sync_after=12, freeze_after=24)


def _data(n_train=2048, n_test=256):
    x, y = synthetic_dataset(n_train, (8, 8, 1), seed=1)
    xt, yt = synthetic_dataset(n_test, (8, 8, 1), seed=1, split="test")
    return x, y, xt, yt


def _train_point(x, y, xt, yt, epochs, seed, chaos=None, policy=None):
    topo = Ring(N_RANKS)
    state, hist = train(
        MLP(hidden=32), topo, x, y,
        algo="eventgrad", epochs=epochs, batch_size=BATCH,
        learning_rate=LR, event_cfg=EVENT_CFG, seed=seed,
        x_test=xt, y_test=yt, chaos=chaos, chaos_policy=policy,
    )
    return state, hist


def _params_equal_bitwise(a, b) -> bool:
    return all(
        np.array_equal(np.asarray(la), np.asarray(lb))
        for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


def _manual_leg(sched, policy, passes, seed=0, event_cfg=EVENT_CFG,
                hidden=32, lr=LR, data_seed=2, batch=BATCH):
    """Step-at-a-time run with a per-pass consensus-error trace (train()
    probes only at block ends; the latency legs need pass resolution).
    Also the shared chaos micro-harness reused by tests/test_chaos.py."""
    from eventgrad_tpu.parallel.spmd import stack_for_ranks

    topo = Ring(N_RANKS)
    model = MLP(hidden=hidden)
    import optax

    tx = optax.sgd(lr)
    x, y = synthetic_dataset(
        N_RANKS * batch * passes, (8, 8, 1), seed=data_seed
    )
    xb, yb = batched_epoch(x, y, N_RANKS, batch)
    state = init_train_state(model, (8, 8, 1), tx, topo, "eventgrad",
                             event_cfg, seed=seed)
    state = state.replace(
        chaos=stack_for_ranks(chaos_monitor.PeerHealth.init(topo), topo)
    )
    step = make_train_step(model, tx, topo, "eventgrad",
                           event_cfg=event_cfg, chaos=sched,
                           chaos_policy=policy)
    lifted = jax.jit(spmd(step, topo))
    errs, silences = [], []
    for s in range(passes):
        state, _ = lifted(
            state, (jnp.asarray(xb[:, s % xb.shape[1]]),
                    jnp.asarray(yb[:, s % yb.shape[1]]))
        )
        errs.append(float(chaos_monitor.consensus_error(state.params).max()))
        silences.append(np.asarray(state.chaos.silence).max(axis=0))
    return state, topo, np.asarray(errs), np.asarray(silences)


def _flaky_recovery_leg(seed):
    """Blackout window mid-run; latency until consensus error returns to
    its pre-window level with the forced-sync policy active."""
    w_start, w_end, passes = 20, 32, 70
    sched = ChaosSchedule(
        seed=seed, flaky=(FlakyWindow(w_start, w_end, 1.0),)
    )
    _, _, errs, _ = _manual_leg(sched, POLICY, passes, seed=seed)
    pre = float(errs[w_start - 2])
    target = max(pre * 1.5, 1e-6)
    rec_pass = next(
        (p for p in range(w_end, passes) if errs[p] <= target), None
    )
    return {
        "schedule": sched.to_dict(),
        "policy": POLICY.to_dict(),
        "window": [w_start, w_end],
        "pre_window_consensus_err": round(pre, 6),
        "peak_consensus_err": round(float(errs[w_start:w_end + 5].max()), 6),
        "recovered": rec_pass is not None,
        "recovery_latency_passes": (
            rec_pass - w_end if rec_pass is not None else None
        ),
    }


def _ring_heal_leg(seed):
    """Kill rank 2 permanently; detect via the silence bound, heal the
    ring to the 3 survivors, and time the survivor consensus recovery."""
    death_pass, pre_passes = 15, 40
    dead_rank = 2
    sched = ChaosSchedule(seed=seed, death=((dead_rank, death_pass),))
    # freeze keeps the dead peer's fossil buffer out of the mix while the
    # death is still undetected; sync keeps survivor edges fresh
    state, topo, errs, silences = _manual_leg(
        sched, POLICY, pre_passes, seed=seed
    )
    detect_pass = next(
        (
            p + 1 for p in range(pre_passes)
            if chaos_monitor.edge_status(
                int(silences[p].max()), EVENT_CFG.max_silence
            ) == "suspect"
        ),
        None,
    )
    survivors_pre = [r for r in range(N_RANKS) if r != dead_rank]
    pre_err = float(
        np.asarray(
            chaos_monitor.consensus_error(
                jax.tree.map(
                    lambda p: p[np.asarray(survivors_pre)], state.params
                )
            )
        ).max()
    )
    healed_state, healed_topo, survivors = apply_ring_heal(
        state, topo, {dead_rank}
    )
    # continue on the healed ring (no injected faults remain: the dead
    # rank is gone from the topology)
    import optax

    tx = optax.sgd(LR)
    model = MLP(hidden=32)
    x, y = synthetic_dataset(len(survivors) * BATCH * 40, (8, 8, 1), seed=4)
    xb, yb = batched_epoch(x, y, len(survivors), BATCH)
    step = make_train_step(model, tx, healed_topo, "eventgrad",
                           event_cfg=EVENT_CFG, chaos=ChaosSchedule(seed=seed),
                           chaos_policy=POLICY)
    lifted = jax.jit(spmd(step, healed_topo))
    heal_errs = []
    for s in range(40):
        healed_state, _ = lifted(
            healed_state, (jnp.asarray(xb[:, s]), jnp.asarray(yb[:, s]))
        )
        heal_errs.append(
            float(chaos_monitor.consensus_error(healed_state.params).max())
        )
    target = max(pre_err, 1e-6)
    rec = next((i + 1 for i, e in enumerate(heal_errs) if e <= target), None)
    return {
        "schedule": sched.to_dict(),
        "policy": POLICY.to_dict(),
        "dead_rank": dead_rank,
        "death_pass": death_pass,
        "detect_pass": detect_pass,
        "detect_latency_passes": (
            detect_pass - death_pass if detect_pass else None
        ),
        "survivors": list(survivors),
        "pre_heal_survivor_consensus_err": round(pre_err, 6),
        "healed_consensus_err_final": round(heal_errs[-1], 6),
        "recovered": rec is not None,
        "recovery_latency_passes": rec,
    }


def run_sweep(drops=(0.0, 0.2, 0.5), epochs=6, seed=0, out_path=None,
              legs=("drop", "flaky", "heal")) -> dict:
    if len(drops) < 3:
        raise ValueError(f"need >= 3 drop-rate points, got {drops}")
    t0 = time.perf_counter()
    x, y, xt, yt = _data()
    out = {
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "platform": jax.devices()[0].platform,
        "op_point": {
            "model": "mlp32", "n_ranks": N_RANKS, "batch": BATCH,
            "epochs": epochs, "lr": LR,
            "horizon": EVENT_CFG.horizon,
            "max_silence": EVENT_CFG.max_silence,
        },
        "policy": POLICY.to_dict(),
        "points": [],
    }

    if "drop" in legs:
        base_state, base_hist = _train_point(x, y, xt, yt, epochs, seed)
        out["baseline_test_acc"] = round(base_hist[-1]["test_accuracy"], 2)
        for p in drops:
            sched = ChaosSchedule(seed=seed, drop_p=float(p))
            st, hist = _train_point(
                x, y, xt, yt, epochs, seed, chaos=sched, policy=POLICY
            )
            point = {
                "drop_p": float(p),
                "schedule": sched.to_dict(),
                "test_acc": round(hist[-1]["test_accuracy"], 2),
                "final_loss": round(hist[-1]["loss"], 4),
                "msgs_saved_pct": round(hist[-1]["msgs_saved_pct"], 2),
                "edge_silence_max": hist[-1]["edge_silence_max"],
                "edge_status": hist[-1]["edge_status"],
                "chaos_drops": hist[-1]["chaos_drops"],
                "consensus_err_max": round(
                    hist[-1]["consensus_err_max"], 6
                ),
            }
            if p == 0.0:
                # the regression guard: zero injected loss must be the
                # unmodified trajectory, bit for bit
                point["bitwise_identical_to_baseline"] = (
                    _params_equal_bitwise(base_state.params, st.params)
                )
            out["points"].append(point)

    if "flaky" in legs:
        out["flaky_recovery"] = _flaky_recovery_leg(seed)
    if "heal" in legs:
        out["ring_heal"] = _ring_heal_leg(seed)

    out["wall_s"] = round(time.perf_counter() - t0, 1)
    if out_path:
        tmp = out_path + ".tmp"
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(out, f, indent=1)
            f.write("\n")
        os.replace(tmp, out_path)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--drops", default="0,0.2,0.5",
                    help="comma-separated drop rates (>= 3 points)")
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None)
    ap.add_argument("--quick", action="store_true",
                    help="drop curve only (skip the latency legs)")
    args = ap.parse_args(argv)
    drops = tuple(float(d) for d in args.drops.split(","))
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out_path = args.out or os.path.join(
        repo, "artifacts",
        f"chaos_sweep_{jax.devices()[0].platform}.json",
    )
    legs = ("drop",) if args.quick else ("drop", "flaky", "heal")
    out = run_sweep(drops, args.epochs, args.seed, out_path, legs)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
