"""EpochPrefetcher: background assembly == inline assembly, any access order."""

import numpy as np
import pytest

from eventgrad_tpu.data import native
from eventgrad_tpu.data.prefetch import EpochPrefetcher


def _data(n=64, shape=(4, 4, 1), seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n,) + shape).astype(np.float32)
    y = rng.integers(0, 10, n).astype(np.int32)
    return x, y


@pytest.mark.parametrize("random", [False, True])
def test_prefetched_epochs_match_inline(random):
    x, y = _data()
    pre = EpochPrefetcher(x, y, n_ranks=4, batch_size=4, random=random, seed=3)
    try:
        for epoch in (1, 2, 3):
            xb, yb = pre.get(epoch)  # epochs 2,3 come from the background thread
            xe, ye = pre._assemble(epoch)
            np.testing.assert_array_equal(xb, xe)
            np.testing.assert_array_equal(yb, ye)
            assert xb.shape == (4, 4, 4, 4, 4, 1) and yb.shape == (4, 4, 4)
    finally:
        pre.close()


def test_out_of_order_epoch_still_correct():
    x, y = _data(seed=1)
    pre = EpochPrefetcher(x, y, n_ranks=2, batch_size=8, random=True, seed=0)
    try:
        pre.get(1)  # pending is now epoch 2
        xb, yb = pre.get(7)  # jump: miss path assembles inline
        xe, ye = pre._assemble(7)
        np.testing.assert_array_equal(xb, xe)
        np.testing.assert_array_equal(yb, ye)
    finally:
        pre.close()


def test_sequential_plan_is_disjoint_cover():
    x, y = _data(n=32)
    pre = EpochPrefetcher(x, y, n_ranks=4, batch_size=8, random=False)
    try:
        xb, yb = pre.get(1)
        # sequential sharding: rank r sees samples [r*8, (r+1)*8)
        np.testing.assert_array_equal(
            xb.reshape(4, 8, -1), x.reshape(32, -1).reshape(4, 8, -1)
        )
    finally:
        pre.close()


def test_no_speculation_past_last_epoch():
    x, y = _data()
    pre = EpochPrefetcher(x, y, 2, 8, random=True, last_epoch=3)
    try:
        pre.get(1)
        assert pre._pending is not None
        pre.get(2)
        pre.get(3)  # final epoch: nothing further to assemble
        assert pre._pending is None
    finally:
        pre.close()


def test_plan_identical_with_and_without_native(monkeypatch):
    """Shuffle order must not depend on whether libeg_dataio built."""
    from eventgrad_tpu.data import native as native_mod

    x, y = _data(n=96, seed=5)
    a = EpochPrefetcher(x, y, 2, 8, random=True, seed=9)
    xa, ya = a._assemble(4)
    monkeypatch.setattr(native_mod, "load_library", lambda: None)
    b = EpochPrefetcher(x, y, 2, 8, random=True, seed=9)
    xb, yb = b._assemble(4)
    np.testing.assert_array_equal(xa, xb)
    np.testing.assert_array_equal(ya, yb)


def test_batch_too_large_raises():
    x, y = _data(n=16)
    with pytest.raises(ValueError, match="larger than per-rank shard"):
        EpochPrefetcher(x, y, n_ranks=4, batch_size=8)


def test_speculation_misses_counted_and_logged(caplog):
    """Out-of-order access falls back to synchronous assembly — but now
    visibly: the miss is counted and logged (a silently cold prefetcher
    is a perf bug)."""
    import logging

    x, y = _data(seed=1)
    pre = EpochPrefetcher(x, y, n_ranks=2, batch_size=8, random=True, seed=0)
    try:
        pre.get(1)  # speculates epoch 2
        assert pre.misses == 0
        with caplog.at_level(logging.WARNING, "eventgrad_tpu.data.prefetch"):
            pre.get(7)  # miss
        assert pre.misses == 1
        assert any("speculation miss" in r.message for r in caplog.records)
        pre.get(8)  # predicted: no new miss
        assert pre.misses == 1
    finally:
        pre.close()


def test_get_block_matches_epoch_concat():
    """Block-granular assembly == the loop's old per-epoch concat, and
    the next block's speculation is consumed without a miss."""
    x, y = _data(n=96, seed=6)
    pre = EpochPrefetcher(x, y, n_ranks=2, batch_size=8, random=True, seed=2)
    try:
        xb, yb = pre.get_block(1, 3, next_span=(4, 5))
        xs = [pre._assemble(e) for e in (1, 2, 3)]
        np.testing.assert_array_equal(
            xb, np.concatenate([p[0] for p in xs], axis=1)
        )
        np.testing.assert_array_equal(
            yb, np.concatenate([p[1] for p in xs], axis=1)
        )
        pre.get_block(4, 5)  # the speculated block: served, no miss
        assert pre.misses == 0
    finally:
        pre.close()


def test_block_transfer_runs_on_worker():
    """transfer= is applied to the speculated block on the background
    thread (the device_put overlap of the dispatch pipeline)."""
    import threading

    x, y = _data(seed=8)
    threads = []

    def tag(arr):
        threads.append(threading.current_thread().name)
        return ("transferred", arr)

    pre = EpochPrefetcher(x, y, 2, 8, random=True, seed=1, transfer=tag)
    try:
        xb, yb = pre.get_block(1, 1, next_span=(2, 2))
        assert xb[0] == "transferred" and yb[0] == "transferred"
        xb2, _ = pre.get_block(2, 2)
        assert xb2[0] == "transferred"
        # the speculated block's transfer ran on a prefetch worker
        assert any(t.startswith("eg-prefetch-") for t in threads)
    finally:
        pre.close()


def test_close_idempotent_and_safe_after_worker_error(monkeypatch):
    """close() must retire a failed speculation WITHOUT raising (the
    loop calls it in `finally` — it must never mask the real exception)
    and stay safe when called repeatedly."""
    x, y = _data(seed=9)
    pre = EpochPrefetcher(x, y, 2, 8, random=True, seed=1)
    monkeypatch.setattr(
        pre, "_assemble", lambda e: (_ for _ in ()).throw(RuntimeError("boom"))
    )
    pre._pending = pre._start((2, 2))  # doomed background assembly
    pre.close()  # swallows the worker error
    pre.close()  # idempotent
    assert pre._pending is None
    # a CONSUMED speculation still surfaces its error to the caller
    pre._pending = pre._start((3, 3))
    with pytest.raises(RuntimeError, match="boom"):
        pre.get_block(3, 3)
    pre.close()


def test_shuffled_epochs_differ_and_are_deterministic():
    x, y = _data(n=128, seed=2)
    a = EpochPrefetcher(x, y, 2, 8, random=True, seed=5)
    b = EpochPrefetcher(x, y, 2, 8, random=True, seed=5)
    try:
        x1, _ = a.get(1)
        x2, _ = a.get(2)
        assert not np.array_equal(x1, x2)  # reshuffled per epoch
        x1b, _ = b.get(1)
        np.testing.assert_array_equal(x1, x1b)  # same (seed, epoch) -> same plan
    finally:
        a.close()
        b.close()
