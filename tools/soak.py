"""Supervised soak harness: survive a scripted kill/join/leave/flaky
schedule and prove it with a schema-validated artifact.

The elastic-membership acceptance run (ISSUE 6): one supervised training
service is driven through

  * >= 6 scripted MEMBERSHIP transitions (>= 2 joins) — ranks leave
    cleanly and newcomers bootstrap from neighbor snapshots
    (chaos/membership.py), applied live between dispatch blocks;
  * a FLAKY network window (chaos schedule, total blackout for a slice
    of passes) riding the same run;
  * a process KILL (`--fault-inject crash:N`) that the supervisor
    (`eventgrad_tpu.supervise`, sliding restart-budget window +
    exponential backoff) recovers from the latest snapshot.

Then three verdicts are measured, not asserted:

  * recovery — per-transition lost recomputation epochs, bounded by one
    `--save-every` interval (membership transitions lose ZERO epochs:
    state carries over live; the supervisor restart loses at most the
    epochs since the last snapshot);
  * accuracy — final consensus test accuracy within 0.5 pt of a
    transition-free baseline trained in-process on the same data;
  * replayability — the membership + chaos schedules parsed back out of
    the soak run's OWN log reproduce its final snapshot bitwise in a
    clean in-process replay (crash recovery + elastic transitions leave
    no numerical trace).

Output: artifacts/soak_<platform>.json, validated against
`tools/validate_artifacts.SOAK_SCHEMA` (tier-1 gated by
tests/test_artifacts.py; the short `--smoke` leg runs inside
tests/test_soak.py, the full schedule behind the `slow` marker).

Usage:
    python tools/soak.py [--smoke] [--out artifacts/soak_cpu.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

# CPU proxy by design (the artifact is soak_cpu.json): pin THIS process
# and every supervised child to the CPU backend, and make the package
# importable from the children regardless of install state
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PYTHONPATH"] = (
    _ROOT + os.pathsep + os.environ.get("PYTHONPATH", "")
).rstrip(os.pathsep)

from eventgrad_tpu.utils import compile_cache  # noqa: E402

compile_cache.honor_cpu_pin()
compile_cache.enable()

import jax  # noqa: E402
import numpy as np  # noqa: E402


#: (ranks, epochs, n_synth, batch, save_every, crash_epoch,
#:  membership spec, chaos spec) per mode. The crash epoch must sit ON
#: the save_every grid: `fault_inject` re-fires on any recomputed epoch
#: (the drill's contract since PR 1), so the kill lands right after a
#: snapshot — at a membership-transition boundary, which additionally
#: exercises the elastic resume path (the restored topology follows from
#: the membership log at the peeked epoch). Flaky windows are
#: pass-indexed.
_OP_POINTS = {
    "full": dict(
        ranks=5, epochs=18, n_synth=768, batch=8, save_every=2,
        crash_epoch=8,
        membership=("leave=2@2,join=2@4,leave=4@6,join=4@8,"
                    "leave=1@11,join=1@13,leave=3@15,join=3@16"),
        chaos="drop=0,seed=11,flaky=40-60@1.0",
    ),
    "smoke": dict(
        ranks=4, epochs=6, n_synth=192, batch=8, save_every=2,
        crash_epoch=4,
        membership=("leave=1@1,join=1@2,leave=2@3,join=2@4,"
                    "leave=0@5,join=0@5"),
        chaos="drop=0,seed=11,flaky=10-16@1.0",
    ),
}

_COMMON_CLI = ["--algo", "eventgrad", "--mesh", None, "--dataset",
               "synthetic", "--model", "mlp", "--warmup-passes", "2",
               "--max-silence", "8", "--lr", "0.1"]


def _train_kwargs(op: Dict[str, Any]) -> Dict[str, Any]:
    """The in-process mirror of the child CLI flags (baseline/replay legs
    must train the exact program the supervised child did)."""
    from eventgrad_tpu.parallel.events import EventConfig

    return dict(
        algo="eventgrad",
        epochs=op["epochs"],
        batch_size=op["batch"],
        learning_rate=0.1,
        event_cfg=EventConfig(warmup_passes=2, max_silence=8),
        seed=0,
    )


def _load_data(op: Dict[str, Any]):
    from eventgrad_tpu.data.datasets import load_or_synthesize

    n_test = max(512, op["n_synth"] // 8)
    x, y = load_or_synthesize("mnist", None, "train", op["n_synth"], 0)
    xt, yt = load_or_synthesize("mnist", None, "test", n_test, 0)
    return x, y, xt, yt


def _records(path: str) -> List[Dict[str, Any]]:
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _restart_transitions(
    epoch_recs: List[Dict[str, Any]], save_every: int
) -> List[Dict[str, Any]]:
    """Supervisor restarts, recovered from the log itself: every attempt
    stamps the serialized membership schedule on ITS first record, so
    attempt boundaries are the records after the first that carry the
    `membership` rider. Each restart lost `prev_epoch - (cur_epoch - 1)`
    epochs of recompute (0 when the kill landed right on a snapshot)."""
    out = []
    starts = [i for i, r in enumerate(epoch_recs) if "membership" in r]
    for i in starts[1:]:
        prev = int(epoch_recs[i - 1]["epoch"])
        cur = int(epoch_recs[i]["epoch"])
        out.append({
            "kind": "restart", "epoch": prev,
            "lost_epochs": max(0, prev - (cur - 1)),
            "save_every": int(save_every),
        })
    return out


def run_soak(
    out_path: str, mode: str = "full", workdir: Optional[str] = None,
) -> Dict[str, Any]:
    import tempfile

    from eventgrad_tpu import supervise
    from eventgrad_tpu.models import MODEL_REGISTRY
    from eventgrad_tpu.parallel.topology import Ring
    from eventgrad_tpu.train.loop import evaluate, train
    from eventgrad_tpu.train.loop import consensus_params, rank0_slice
    from eventgrad_tpu.utils import checkpoint

    op = _OP_POINTS[mode]
    t_start = time.perf_counter()
    x, y, xt, yt = _load_data(op)
    model = MODEL_REGISTRY["mlp"]()
    kw = _train_kwargs(op)

    ctx = tempfile.TemporaryDirectory() if workdir is None else None
    tmp = workdir if workdir is not None else ctx.name
    os.makedirs(tmp, exist_ok=True)
    try:
        # --- leg 1: transition-free baseline (in-process) --------------
        st_base, _ = train(model, Ring(op["ranks"]), x, y, **kw)
        acc_base = evaluate(
            model,
            consensus_params(st_base.params),
            rank0_slice(st_base.batch_stats),
            xt, yt,
        )["accuracy"]

        # --- leg 2: the supervised soak (subprocess, killed once) ------
        ck = os.path.join(tmp, "ck")
        log = os.path.join(tmp, "soak.jsonl")
        child = [
            a if a is not None else f"ring:{op['ranks']}"
            for a in _COMMON_CLI
        ] + [
            "--epochs", str(op["epochs"]),
            "--batch-size", str(op["batch"]),
            "--n-synth", str(op["n_synth"]),
            "--membership", op["membership"],
            "--chaos", op["chaos"],
            "--fault-inject", f"crash:{op['crash_epoch']}",
            "--checkpoint-dir", ck,
            "--save-every", str(op["save_every"]),
            "--log-file", log,
        ]
        rc = supervise.supervise(
            child, timeout=0.0, max_restarts=3, restart_window=600.0,
            backoff_base=0.2, backoff_max=2.0,
        )
        escalations = 0 if rc == 0 else 1
        recs = _records(log)
        epoch_recs = [r for r in recs if "epoch" in r]
        final_rec = next(r for r in reversed(recs) if r.get("final"))
        acc_soak = float(final_rec["accuracy"])
        msgs_saved = float(
            next(
                r["msgs_saved_pct"] for r in reversed(epoch_recs)
                if "msgs_saved_pct" in r
            )
        )

        # --- transition accounting -------------------------------------
        # ground truth is the schedule the run LOGGED about itself
        # (rec["membership"] on each attempt's first record); per-epoch
        # active_ranks must track it exactly — the "survived" proof. A
        # membership transition loses ZERO epochs (state carries over
        # live); transition records enrich with apply timings where the
        # process lived long enough to write the next record (a kill at
        # the transition epoch eats the record, never the transition).
        from eventgrad_tpu.chaos.membership import MembershipSchedule

        memb_logged = next(
            r["membership"] for r in epoch_recs if "membership" in r
        )
        sched = MembershipSchedule.from_dict(memb_logged)
        active_ranks_verified = all(
            int(r["active_ranks"])
            == sched.n_ranks_at(op["ranks"], int(r["epoch"]) - 1)
            for r in epoch_recs
        )
        applied = {
            (t["kind"], int(t["epoch"]), int(t["index"])): t
            for r in epoch_recs
            for t in r.get("membership_transitions", ())
        }
        transitions: List[Dict[str, Any]] = []
        for e in sched.events:
            t = {"kind": e.kind, "epoch": e.epoch, "index": e.index,
                 "lost_epochs": 0}
            seen = applied.get((e.kind, e.epoch, e.index))
            if seen is not None:
                t["apply_s"] = float(seen.get("apply_s", 0.0))
                t["n_ranks_after"] = int(seen["n_ranks_after"])
            transitions.append(t)
        restarts = _restart_transitions(epoch_recs, op["save_every"])
        transitions = sorted(
            transitions + restarts, key=lambda t: t["epoch"]
        )
        n_joins = sum(1 for t in transitions if t["kind"] == "join")
        n_memb = sum(1 for t in transitions if t["kind"] != "restart")
        recovery_ok = all(
            t["lost_epochs"] <= op["save_every"] for t in transitions
        )

        # --- leg 3: replay from the run's OWN logged schedules ---------
        chaos_logged = next(
            r["chaos"] for r in epoch_recs if "chaos" in r
        )
        st_replay, _ = train(
            model, Ring(op["ranks"]), x, y,
            membership=memb_logged, chaos=chaos_logged, **kw,
        )
        found = checkpoint.latest(os.path.join(ck, "ckpt"))
        snap = checkpoint.restore(
            found, {"state": st_replay, "epoch": np.int64(0)}
        )
        replay_bitwise = all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(
                jax.tree.leaves(st_replay.params),
                jax.tree.leaves(snap["state"].params),
            )
        )
    finally:
        if ctx is not None:
            ctx.cleanup()

    out = {
        "bench": "soak",
        "platform": jax.default_backend(),
        "mode": mode,
        "op_point": {
            k: op[k]
            for k in ("ranks", "epochs", "n_synth", "batch", "crash_epoch",
                      "membership", "chaos")
        },
        "save_every": op["save_every"],
        "n_transitions": n_memb,
        "n_joins": n_joins,
        "supervisor_restarts": len(restarts),
        "supervisor_escalations": escalations,
        "transitions": transitions,
        "active_ranks_verified": bool(active_ranks_verified),
        "recovery_ok": bool(recovery_ok),
        "final_acc_baseline": round(float(acc_base), 3),
        "final_acc_soak": round(acc_soak, 3),
        "final_acc_gap_pt": round(abs(float(acc_base) - acc_soak), 3),
        "msgs_saved_pct": round(msgs_saved, 2),
        "replay_bitwise": bool(replay_bitwise),
        "wall_s": round(time.perf_counter() - t_start, 1),
    }
    if out_path:
        os.makedirs(os.path.dirname(os.path.abspath(out_path)),
                    exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="reduced op point (<= ~60 s on CPU; same schema, "
                         "same >= 6-transition floor)")
    ap.add_argument("--out", default=os.path.join(
        _ROOT, "artifacts", f"soak_{jax.default_backend()}.json"
    ))
    args = ap.parse_args(argv)
    out = run_soak(args.out, mode="smoke" if args.smoke else "full")
    print(json.dumps(out, indent=1, sort_keys=True))

    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "validate_artifacts",
        os.path.join(_ROOT, "tools", "validate_artifacts.py"),
    )
    va = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(va)
    errs = va.validate(out, va.SOAK_SCHEMA)
    for e in errs:
        print(f"SOAK_SCHEMA violation: {e}", file=sys.stderr)
    return 1 if errs else 0


if __name__ == "__main__":
    sys.exit(main())
