"""Integrity engine: wire checksums, quarantine, rollback — guarantees.

The load-bearing contracts (ISSUE 7 acceptance):
  * a checksum-failed payload is BITWISE an event that did not fire
    (the stale buffer survives; rejection == drop at the params level);
  * with integrity OFF the same injected corruption lands SILENTLY —
    the measured counterfactual;
  * a nanstep-poisoned rank quarantines (update skipped, sends
    suppressed) and the run stays finite;
  * integrity="off" resolves to None — the traced step IS today's step;
    integrity ON with no faults firing is bitwise-unchanged;
  * the divergence sentinel trips on a landed fault, the loop restores
    last-known-good, hardens, replays — and the whole run (faults,
    trip, rollback, replay) is bitwise-reproducible from the seed;
  * a trip beyond the budget raises IntegrityEscalation (exit 77; the
    supervisor gives up without a restart — tests/test_supervise.py).
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from _spmd import requires_shard_map

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from eventgrad_tpu.chaos import inject
from eventgrad_tpu.chaos.integrity import (
    INTEGRITY_ABORT_EXIT, DivergenceSentinel, IntegrityConfig,
    IntegrityEscalation, resolve,
)
from eventgrad_tpu.chaos.schedule import ChaosSchedule, FlakyWindow
from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP
from eventgrad_tpu.parallel import collectives
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.spmd import build_mesh, spmd
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.loop import train
from eventgrad_tpu.utils import checkpoint


def _params_equal_bitwise(a, b) -> bool:
    return all(
        bool(np.array_equal(np.asarray(x), np.asarray(y)))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


def _params_finite(tree) -> bool:
    return all(
        bool(np.isfinite(np.asarray(l)).all()) for l in jax.tree.leaves(tree)
    )


# --- (a) config + sentinel units ---------------------------------------


def test_integrity_config_parse_and_resolve():
    assert IntegrityConfig.parse("on") == IntegrityConfig()
    off = IntegrityConfig.parse("off")
    assert off.is_noop
    # "off" IS today's step: it resolves to None, so train() builds the
    # exact same traced program as no flag at all
    assert resolve("off") is None
    assert resolve(None) is None
    assert resolve("on") == IntegrityConfig()
    kv = resolve("checksum=0,quarantine=1,max_rollbacks=2,loss_spike=8.5")
    assert kv == IntegrityConfig(
        checksum=False, quarantine=True, max_rollbacks=2, loss_spike=8.5
    )
    # dict round trip (the first-record replayability rider)
    assert resolve(kv.to_dict()) == kv
    assert kv.hardened().checksum and kv.hardened().quarantine
    with pytest.raises(ValueError, match="integrity clause"):
        IntegrityConfig.parse("bogus")
    with pytest.raises(ValueError, match="0/1/true/false"):
        IntegrityConfig.parse("checksum=maybe")
    with pytest.raises(ValueError, match="max_rollbacks"):
        IntegrityConfig(max_rollbacks=-1)
    with pytest.raises(ValueError, match="loss_spike"):
        IntegrityConfig(loss_spike=0.5)
    with pytest.raises(TypeError):
        resolve(42)


def test_divergence_sentinel_trips_and_rewinds():
    cfg = IntegrityConfig(loss_spike=4.0, loss_floor=1.0,
                          consensus_spike=100.0, consensus_floor=10.0)
    s = DivergenceSentinel(cfg)
    # baselines establish; healthy blocks advance them
    assert s.observe(2.0, 0.5) is None
    assert s.observe(1.5, 0.4) is None
    snap = s.snapshot()
    # a spike above loss_spike x best AND the floor trips
    reason = s.observe(1.5 * 4.0 + 0.1, 0.4)
    assert reason is not None and "loss spike" in reason
    # a tripped block must NOT become the yardstick
    assert s.best_loss == 1.5
    # below the floor never trips (early high-loss epochs), even at a
    # large ratio over a tiny best
    s2 = DivergenceSentinel(cfg)
    assert s2.observe(0.001) is None
    assert s2.observe(0.9) is None  # 900x best, but under loss_floor
    # non-finite always trips (NaN's compare-False must not slip through)
    assert "non-finite" in s2.observe(float("nan"))
    s3 = DivergenceSentinel(cfg)
    assert s3.observe(2.0, 1.0) is None
    assert "consensus" in s3.observe(1.9, 1.0 * 100.0 + 11.0)
    assert "non-finite consensus" in s3.observe(1.9, float("inf"))
    # rewind restores the judged-healthy baseline (deterministic replay)
    s.rewind(snap)
    assert s.best_loss == snap["best_loss"]
    assert s.best_cerr == snap["best_cerr"]


# --- (b) wire primitives -----------------------------------------------


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16, jnp.int8])
def test_wire_checksum_catches_single_bitflip(dtype):
    """Any single flipped bit changes the int32 wire checksum, for every
    wire dtype; an un-flipped buffer checksums identically."""
    if dtype == jnp.int8:
        buf = jnp.arange(-16, 16, dtype=dtype).reshape(4, 8)
    else:
        buf = (jnp.arange(32, dtype=jnp.float32) / 7.0).astype(dtype)
    base = collectives.wire_checksum(buf)
    same = collectives.wire_checksum(
        inject.flip_one_bit(buf, jnp.asarray(False), jnp.int32(11))
    )
    assert int(base) == int(same)
    for salt in (0, 7, 31, 2**30):
        flipped = inject.flip_one_bit(buf, jnp.asarray(True), jnp.int32(salt))
        assert not bool(jnp.all(flipped == buf))
        assert int(collectives.wire_checksum(flipped)) != int(base), salt


def test_corrupt_mask_independent_of_drop_draws():
    """Adding bitflip= clauses never perturbs a schedule's drop draws
    (independent fold_in tags), and the host corruption_table replays
    the in-step draws deterministically."""
    topo = Ring(4)
    plain = ChaosSchedule(seed=7, drop_p=0.3)
    flipped = ChaosSchedule(
        seed=7, drop_p=0.3, bitflip=(FlakyWindow(0, 100, 0.5),)
    )
    np.testing.assert_array_equal(
        inject.delivery_table(plain, topo, 12),
        inject.delivery_table(flipped, topo, 12),
    )
    t1 = inject.corruption_table(flipped, topo, 12)
    t2 = inject.corruption_table(flipped, topo, 12)
    np.testing.assert_array_equal(t1, t2)
    assert t1.any(), "p=0.5 over 12 passes x 4 ranks x 2 edges must hit"
    assert not t1.all()
    # outside the window nothing corrupts; p=0 never corrupts
    late = ChaosSchedule(seed=7, bitflip=(FlakyWindow(50, 60, 1.0),))
    assert not inject.corruption_table(late, topo, 10).any()
    assert not inject.corruption_table(
        ChaosSchedule(seed=7, bitflip=(FlakyWindow(0, 100, 0.0),)), topo, 10
    ).any()


def test_nanstep_helpers():
    topo = Ring(4)
    s = ChaosSchedule(seed=0, nanstep=((2, 5), (0, 7), (3, 99)))
    assert inject.nansteps_in_range(s, n_ranks=4, n_passes=10) == 2
    assert inject.nansteps_in_range(s, n_ranks=4, n_passes=200) == 3
    # rank-indexed, pass-exact
    for pass_num, expect in ((5, [False, False, True, False]),
                             (7, [True, False, False, False])):
        def fn(_x, _p=pass_num):
            return inject.nanstep_mask(s, topo, jnp.int32(_p))

        got = np.asarray(spmd(fn, topo)(jnp.zeros(4)))
        np.testing.assert_array_equal(got, expect)


def test_schedule_round_trip_with_integrity_faults():
    s = ChaosSchedule(
        seed=9, drop_p=0.1, bitflip=(FlakyWindow(10, 20, 0.5),),
        nanstep=((2, 15), (0, 3)),
    )
    assert ChaosSchedule.parse(s.to_spec()) == s
    assert ChaosSchedule.from_dict(s.to_dict()) == s
    assert s.has_bitflips and s.has_nansteps
    assert not s.is_noop
    # bare bitflip=p covers the whole run — including scientific
    # notation, whose '-' must not be misread as a pass range
    bare = ChaosSchedule.parse("bitflip=0.25")
    assert bare.bitflip[0].drop_p == 0.25
    assert bare.bitflip[0].end_pass > 10**6
    sci = ChaosSchedule.parse("bitflip=1e-3")
    assert sci.bitflip[0].drop_p == 1e-3
    assert sci.bitflip[0].end_pass > 10**6
    # legacy schedules round-trip unchanged (absent keys stay absent)
    legacy = ChaosSchedule(seed=1, drop_p=0.2)
    assert "bitflip" not in legacy.to_dict()
    assert "nanstep" not in legacy.to_dict()
    with pytest.raises(ValueError, match="nanstep"):
        ChaosSchedule(nanstep=((-1, 5),))


# --- (c) rejection is BITWISE the not-fired path -----------------------


@pytest.mark.parametrize("wire", [None, "int8"])
def test_rejected_payload_bitwise_equals_dropped(wire):
    """A checksum-failed payload keeps the stale buffer EXACTLY like an
    injected drop (and like an event that did not fire) — masked and
    compact wires, float and int8."""
    topo = Ring(4)
    p = {"w": jnp.arange(4.0) + 1.0, "b": 10.0 + jnp.arange(8.0).reshape(4, 2)}
    fire = {"w": jnp.ones(4, bool), "b": jnp.ones(4, bool)}
    last = {"w": jnp.full(4, -7.0), "b": jnp.full((4, 2), -9.0)}
    corrupt = lambda i, buf: inject.flip_one_bit(
        buf, jnp.asarray(True), jnp.int32(3 + i)
    )

    def rejected(pp, ff, ll):
        bufs, _, oks = collectives.masked_neighbor_vals(
            pp, ff, (ll, ll), topo, wire,
            checksum=True, corrupt=corrupt,
        )
        return bufs, oks

    def dropped(pp, ff, ll):
        bufs, _ = collectives.masked_neighbor_vals(
            pp, ff, (ll, ll), topo, wire,
            deliver=jnp.zeros((2,), bool),
        )
        return bufs

    got_rej, oks = spmd(rejected, topo)(p, fire, last)
    got_drop = spmd(dropped, topo)(p, fire, last)
    assert not np.asarray(oks).any(), "every corrupted payload rejected"
    assert _params_equal_bitwise(got_rej, got_drop)
    assert _params_equal_bitwise(got_rej, (last, last))

    def rejected_compact(pp, ff, ll):
        bufs, _, oks = collectives.compact_neighbor_vals(
            pp, ff, (ll, ll), topo, 12, wire,
            checksum=True, corrupt=corrupt,
        )
        return bufs, oks

    got_c, oks_c = spmd(rejected_compact, topo)(p, fire, last)
    assert not np.asarray(oks_c).any()
    assert _params_equal_bitwise(got_c, (last, last))

    # an UNcorrupted wire passes verification and delivers normally
    def clean(pp, ff, ll):
        bufs, _, oks = collectives.masked_neighbor_vals(
            pp, ff, (ll, ll), topo, wire, checksum=True,
        )
        return bufs, oks

    def plain(pp, ff, ll):
        bufs, _ = collectives.masked_neighbor_vals(
            pp, ff, (ll, ll), topo, wire,
        )
        return bufs

    got_clean, oks_ok = spmd(clean, topo)(p, fire, last)
    assert np.asarray(oks_ok).all()
    assert _params_equal_bitwise(got_clean, spmd(plain, topo)(p, fire, last))


def test_finite_guard_rejects_nan_payload():
    """`finite=True` rejects a payload carrying NaN even with a valid
    checksum (the sender-side guard's belt-and-suspenders twin): only
    the edges sourced at the sick rank reject, and the NaN is never
    committed anywhere."""
    topo = Ring(4)
    # rank 1's payload goes NaN (leaf shapes per rank: w scalar, b [2])
    p = {"w": jnp.array([1.0, jnp.nan, 3.0, 4.0]), "b": jnp.ones((4, 2))}
    fire = {"w": jnp.ones(4, bool), "b": jnp.ones(4, bool)}
    last = {"w": jnp.full(4, -7.0), "b": jnp.full((4, 2), -9.0)}

    def fn(pp, ff, ll):
        bufs, _, oks = collectives.masked_neighbor_vals(
            pp, ff, (ll, ll), topo, checksum=True, finite=True,
        )
        return bufs, oks

    bufs, oks = spmd(fn, topo)(p, fire, last)
    oks = np.asarray(oks)  # [rank, edge]
    expect = np.array([
        [topo.neighbor_source(r, nb) != 1 for nb in topo.neighbors]
        for r in range(4)
    ])
    np.testing.assert_array_equal(oks, expect)
    assert _params_finite(bufs)  # the NaN never reached a buffer
    # rejected edges kept the stale value; clean edges delivered
    w_bufs = np.asarray(bufs[0]["w"]), np.asarray(bufs[1]["w"])
    for r in range(4):
        for e in range(2):
            src = topo.neighbor_source(r, topo.neighbors[e])
            assert w_bufs[e][r] == (-7.0 if src == 1 else float(src + 1))


# --- (d) train-level: rejection, silence, quarantine -------------------


def _data():
    (x, y) = synthetic_dataset(512, (8, 8, 1), seed=1)
    (xt, yt) = synthetic_dataset(128, (8, 8, 1), seed=1, split="test")
    return x, y, xt, yt


_MODEL = dict(hidden=16)
_CFG = dict(adaptive=False, constant=0.0)  # fire always -> wire active


def _train(x, y, **kw):
    return train(
        MLP(**_MODEL), Ring(4), x, y, algo="eventgrad", batch_size=32,
        event_cfg=EventConfig(**_CFG), seed=0, log_every_epoch=True, **kw,
    )


#: in-step defenses only: the host-side engine stays out of the way so
#: the equivalences below compare pure step semantics
_INSTEP = IntegrityConfig(sentinel=False, rollback=False)


def test_train_bitflip_rejected_counted_and_drop_equivalent():
    """End-to-end: every all-edges bitflip window payload is rejected at
    the wire (counted per edge), and the parameters are BITWISE a run
    whose same window was simply dropped (flaky@1.0): rejection == one
    more event that did not fire."""
    x, y, xt, yt = _data()
    st_rej, hist = _train(
        x, y, epochs=3, x_test=xt, y_test=yt,
        chaos=ChaosSchedule.parse("seed=5,bitflip=4-12@1.0"),
        integrity=_INSTEP,
    )
    wr = sum(r.get("wire_rejects", 0) for r in hist)
    # passes 4..11, 4 ranks x 2 edges, every payload corrupt: all
    # rejected. (16 steps/epoch; warmup fires dense through it all.)
    assert wr == 8 * 4 * 2
    assert hist[0]["integrity"] == _INSTEP.to_dict()  # replay rider
    st_drop, _ = _train(
        x, y, epochs=3, x_test=xt, y_test=yt,
        chaos=ChaosSchedule.parse("seed=5,flaky=4-12@1.0"),
    )
    assert _params_equal_bitwise(st_rej.params, st_drop.params)


def test_train_bitflip_lands_silently_without_integrity():
    """The counterfactual: the SAME corruption schedule with integrity
    off reaches the parameters (no rejection, trajectories diverge) —
    exactly what the wire checksum exists to stop."""
    x, y, xt, yt = _data()
    chaos = ChaosSchedule.parse("seed=5,bitflip=4-12@1.0")
    st_silent, hist = _train(
        x, y, epochs=3, x_test=xt, y_test=yt, chaos=chaos,
    )
    assert not any("wire_rejects" in r for r in hist)
    st_rej, _ = _train(
        x, y, epochs=3, x_test=xt, y_test=yt, chaos=chaos,
        integrity=_INSTEP,
    )
    assert not _params_equal_bitwise(st_silent.params, st_rej.params)


def test_train_nanstep_quarantines_and_stays_finite():
    """A poisoned rank skips its update and suppresses its sends; the
    run stays finite and the quarantine is counted."""
    x, y, xt, yt = _data()
    st, hist = _train(
        x, y, epochs=3, x_test=xt, y_test=yt,
        chaos=ChaosSchedule.parse("seed=5,nanstep=2@6,nanstep=0@9"),
        integrity=_INSTEP,
    )
    qs = sum(r.get("quarantined_steps", 0) for r in hist)
    assert qs == 2  # exactly the scheduled poisonings, nothing else
    assert _params_finite(st.params)
    # without quarantine the same schedule reaches the parameters
    st_off, _ = _train(
        x, y, epochs=3, x_test=xt, y_test=yt,
        chaos=ChaosSchedule.parse("seed=5,nanstep=2@6,nanstep=0@9"),
    )
    assert not _params_finite(st_off.params)


def test_integrity_on_without_faults_is_bitwise_unchanged():
    """Armed-but-idle defenses are free: gates that never trip select
    the same values, so the trajectory is bitwise the plain run's."""
    x, y, xt, yt = _data()
    st_plain, _ = _train(x, y, epochs=2, x_test=xt, y_test=yt)
    st_on, hist = _train(
        x, y, epochs=2, x_test=xt, y_test=yt, integrity="on",
    )
    assert _params_equal_bitwise(st_plain.params, st_on.params)
    assert sum(r.get("wire_rejects", 0) for r in hist) == 0
    assert sum(r.get("quarantined_steps", 0) for r in hist) == 0
    assert all(r["integrity_rollbacks"] == 0 for r in hist)
    # integrity="off" resolves to None: literally the same build
    st_off, hist_off = _train(x, y, epochs=2, x_test=xt, y_test=yt,
                              integrity="off")
    assert _params_equal_bitwise(st_plain.params, st_off.params)
    assert not any("integrity" in r for r in hist_off)


def test_arena_on_off_bitwise_with_integrity():
    """The integrity gates are layout-agnostic: arena and tree paths
    reject/quarantine bit-identically under the same fault schedule."""
    x, y, xt, yt = _data()
    chaos = ChaosSchedule.parse("seed=5,bitflip=4-10@0.7,nanstep=2@6")
    st_tree, h_tree = _train(
        x, y, epochs=2, x_test=xt, y_test=yt, chaos=chaos,
        integrity=_INSTEP, arena=False,
    )
    st_arena, h_arena = _train(
        x, y, epochs=2, x_test=xt, y_test=yt, chaos=chaos,
        integrity=_INSTEP, arena=True,
    )
    assert _params_equal_bitwise(st_tree.params, st_arena.params)
    assert (
        [r.get("wire_rejects") for r in h_tree]
        == [r.get("wire_rejects") for r in h_arena]
    )
    assert (
        [r.get("quarantined_steps") for r in h_tree]
        == [r.get("quarantined_steps") for r in h_arena]
    )


# --- (e) rollback engine -----------------------------------------------


def test_sentinel_trip_rolls_back_hardens_and_replays_bitwise():
    """A nanstep landing with quarantine OFF poisons the ring; the
    sentinel trips on the divergence, the loop restores last-known-good,
    hardens the step (quarantine now ON), and the replay survives the
    same scheduled fault. The whole run replays bitwise from the seed."""
    x, y, xt, yt = _data()
    chaos = ChaosSchedule.parse("seed=5,nanstep=2@20")
    icfg = IntegrityConfig(checksum=False, quarantine=False, escalate=True)
    st, hist = _train(
        x, y, epochs=5, x_test=xt, y_test=yt, chaos=chaos, integrity=icfg,
    )
    rb = [r for r in hist if "integrity_rollback" in r]
    assert len(rb) == 1, "exactly one rollback"
    info = rb[0]["integrity_rollback"]
    assert info["hardened"] is True
    assert "non-finite" in info["reason"]
    assert info["restored_epoch"] < info["tripped_epoch"]
    assert hist[-1]["integrity_rollbacks"] == 1
    assert _params_finite(st.params)
    # the hardened replay quarantined the replayed nanstep
    assert sum(r.get("quarantined_steps", 0) for r in hist) >= 1
    # bitwise replay: faults + trip + rollback + hardened replay, all
    # reproduced from the seed
    st2, hist2 = _train(
        x, y, epochs=5, x_test=xt, y_test=yt, chaos=chaos, integrity=icfg,
    )
    assert _params_equal_bitwise(st.params, st2.params)
    assert [r.get("integrity_rollbacks") for r in hist] == [
        r.get("integrity_rollbacks") for r in hist2
    ]


def test_rollback_budget_spent_escalates():
    """rollback disarmed or budget spent -> IntegrityEscalation (the
    CLI maps it to exit 77; the supervisor gives up without restart)."""
    x, y, xt, yt = _data()
    chaos = ChaosSchedule.parse("seed=5,nanstep=2@20")
    with pytest.raises(IntegrityEscalation, match="budget spent"):
        _train(
            x, y, epochs=5, x_test=xt, y_test=yt, chaos=chaos,
            integrity=IntegrityConfig(
                checksum=False, quarantine=False, max_rollbacks=0,
            ),
        )
    with pytest.raises(IntegrityEscalation, match="disarmed"):
        _train(
            x, y, epochs=5, x_test=xt, y_test=yt, chaos=chaos,
            integrity=IntegrityConfig(
                checksum=False, quarantine=False, rollback=False,
            ),
        )


def test_rollback_disk_retention(tmp_path):
    """With a checkpoint_dir the engine retains validated last-known-
    good snapshots on disk (RollingRetention under <dir>/good), each
    individually restorable."""
    x, y, xt, yt = _data()
    ckdir = str(tmp_path / "ck")
    st, hist = _train(
        x, y, epochs=3, x_test=xt, y_test=yt,
        integrity=IntegrityConfig(keep_good=2),
        checkpoint_dir=ckdir, save_every=1,
    )
    ret = checkpoint.RollingRetention(os.path.join(ckdir, "good"), keep=2)
    snaps = ret.snapshots()
    assert 1 <= len(snaps) <= 2
    epoch, path = snaps[-1]
    got = checkpoint.peek(path)
    assert int(np.asarray(got["epoch"])) == epoch


def test_train_validation_errors():
    x, y, _, _ = _data()
    with pytest.raises(ValueError, match="event exchange"):
        train(
            MLP(hidden=16), Ring(4), x, y, algo="dpsgd", epochs=1,
            batch_size=32, seed=0, integrity="on",
        )
    with pytest.raises(ValueError, match="membership"):
        _train(x, y, epochs=2, integrity="on",
               membership="leave=1@1")
    with pytest.raises(ValueError, match="pipeline"):
        _train(x, y, epochs=2, integrity="on", pipeline=True)
    # the CLI-reserved exit code is pinned in both modules (supervise
    # must stay jax-free, so it re-declares rather than imports)
    from eventgrad_tpu import supervise
    assert supervise.INTEGRITY_ABORT_EXIT == INTEGRITY_ABORT_EXIT == 77


# --- (f) the mesh lift -------------------------------------------------


@requires_shard_map
def test_integrity_bitwise_shard_map():
    """The in-step defenses are lift-agnostic: the shard_map mesh run
    rejects and quarantines bit-identically to the vmap simulator."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    x, y, xt, yt = _data()
    chaos = ChaosSchedule.parse("seed=5,bitflip=4-10@0.7,nanstep=2@6")
    st_vmap, _ = _train(
        x, y, epochs=2, x_test=xt, y_test=yt, chaos=chaos, integrity=_INSTEP,
    )
    st_mesh, _ = _train(
        x, y, epochs=2, x_test=xt, y_test=yt, chaos=chaos, integrity=_INSTEP,
        mesh=build_mesh(Ring(4)),
    )
    assert _params_equal_bitwise(st_vmap.params, st_mesh.params)
