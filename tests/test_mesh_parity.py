"""Bitwise parity of the two SPMD lifts: vmap simulator vs shard_map mesh.

The tentpole contract of the real-mesh backend (ROADMAP open item 1,
docs/ARCHITECTURE.md "Mesh backends"): lifting the SAME per-rank step
onto a real device mesh (`spmd(fn, topo, mesh=...)` — one rank per
device, `ppermute` as an actual collective) instead of the single-chip
vmap simulator changes WHERE the program runs, never a single bit of
what it computes. The matrix here proves it on FULL TrainState +
metrics across the event-exchange variants the headline numbers ship:
masked|compact wire x f32/int8 lanes x bucketed K in {1,4} x
staleness 0/1 — every leaf of the state pytree (params, optimizer,
event thresholds AND stale neighbor buffers, rng, telemetry) compared
with `==`, not allclose.

The 64-rank scale leg runs in a subprocess (tests/mesh64_worker.py —
the tier-1 process pins an 8-device CPU host platform, the scale leg
needs 64) and asserts the wire truth THREE ways at scale: per-edge
telemetry bytes == steps x `collectives.wire_real_bytes_per_neighbor`
== the step's sent_bytes_wire_real metric, exactly, on every one of
the 64 ranks — plus ppermute-offsets == the declared ring offsets in
the traced mesh program.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from _spmd import requires_shard_map

from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.data.sharding import batched_epoch
from eventgrad_tpu.models import MLP
from eventgrad_tpu.obs import device as obs_device
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.spmd import (
    build_mesh, resolve_backend, shard_map_available, spmd,
    stack_for_ranks,
)
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.state import init_train_state
from eventgrad_tpu.train.steps import make_train_step
from eventgrad_tpu.utils import trees

pytestmark = requires_shard_map

N_RANKS = 4
PER_RANK = 4
IN_SHAPE = (8, 8, 1)
STEPS = 5
CFG = EventConfig(adaptive=True, horizon=0.9, warmup_passes=2,
                  max_silence=4)
MLP_HIDDEN = 8


def _batches(seed=3):
    x, y = synthetic_dataset(N_RANKS * PER_RANK * STEPS, IN_SHAPE, seed=seed)
    xb, yb = batched_epoch(x, y, N_RANKS, PER_RANK)
    return [
        (jnp.asarray(xb[:, s]), jnp.asarray(yb[:, s])) for s in range(STEPS)
    ]


def _run(backend, *, gossip_wire="dense", wire=None, bucketed=None,
         staleness=0, obs=False, chaos=None, carrier=False):
    topo = Ring(N_RANKS)
    model = MLP(hidden=MLP_HIDDEN)
    tx = optax.sgd(0.05)
    state = init_train_state(
        model, IN_SHAPE, tx, topo, "eventgrad", CFG, seed=0, arena=True,
        bucketed=bucketed or 1, staleness=staleness,
        resident_wire=(wire if carrier else None),
    )
    if chaos is not None:
        from eventgrad_tpu.chaos import monitor as chaos_monitor
        state = state.replace(
            chaos=stack_for_ranks(chaos_monitor.PeerHealth.init(topo), topo)
        )
    if obs:
        n_leaves = len(jax.tree.leaves(state.params))
        state = state.replace(
            telemetry=stack_for_ranks(
                obs_device.TelemetryState.init(
                    n_leaves, topo.n_neighbors,
                    n_buckets=min(bucketed or 1, n_leaves),
                ),
                topo,
            )
        )
    capacity = None
    if gossip_wire == "compact":
        # non-binding capacity (the full per-rank element count) so the
        # per-bucket splits admit exactly what the monolithic gate
        # admits and the parity claim stays exact; binding budgets are
        # bucket-local by design and unit-tested in tests/test_bucketed.py
        capacity = trees.tree_count_params(state.params) // topo.n_ranks
    step = make_train_step(
        model, tx, topo, "eventgrad", event_cfg=CFG, arena=True,
        gossip_wire=gossip_wire, compact_capacity=capacity, wire=wire,
        bucketed=bucketed, staleness=staleness, obs=obs, chaos=chaos,
        carrier_resident=carrier,
    )
    mesh = build_mesh(topo) if backend == "shard_map" else None
    lifted = jax.jit(spmd(step, topo, mesh=mesh))
    m = None
    for b in _batches():
        state, m = lifted(state, b)
    return state, m


def _assert_bitwise(s_v, s_s, m_v, m_s):
    lv, ls = jax.tree.leaves(s_v), jax.tree.leaves(s_s)
    assert len(lv) == len(ls)
    for a, b in zip(lv, ls):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert set(m_v) == set(m_s)
    for k in m_v:
        np.testing.assert_array_equal(
            np.asarray(m_v[k]), np.asarray(m_s[k]), err_msg=k
        )


@pytest.mark.parametrize("staleness", [0, 1])
@pytest.mark.parametrize("bucketed", [None, 4])
@pytest.mark.parametrize("wire", [None, "int8"])
@pytest.mark.parametrize("gossip_wire", ["dense", "compact"])
def test_full_state_bitwise_across_lifts(gossip_wire, wire, bucketed,
                                         staleness):
    s_v, m_v = _run("vmap", gossip_wire=gossip_wire, wire=wire,
                    bucketed=bucketed, staleness=staleness)
    s_s, m_s = _run("shard_map", gossip_wire=gossip_wire, wire=wire,
                    bucketed=bucketed, staleness=staleness)
    _assert_bitwise(s_v, s_s, m_v, m_s)


@pytest.mark.parametrize("wire", [None, "int8"])
@pytest.mark.parametrize("gossip_wire", ["dense", "compact"])
def test_bounded_async_bitwise_across_lifts(gossip_wire, wire):
    """The bounded-async engine (ISSUE 15, staleness=D >= 2) under an
    injected straggler is part of the cross-lift parity surface: the
    per-edge delivery queues, staleness clocks, and late-commit
    counters are carried state like everything else, compared `==`
    across the vmap simulator and the shard_map mesh."""
    from eventgrad_tpu.chaos.schedule import ChaosSchedule

    sched = ChaosSchedule(seed=5, slow=((1, 3),))
    s_v, m_v = _run("vmap", gossip_wire=gossip_wire, wire=wire,
                    staleness=2, chaos=sched)
    s_s, m_s = _run("shard_map", gossip_wire=gossip_wire, wire=wire,
                    staleness=2, chaos=sched)
    _assert_bitwise(s_v, s_s, m_v, m_s)
    # the straggler actually exercised the late path on both lifts
    assert int(np.asarray(m_v["late_commits"]).sum()) > 0
    assert int(np.asarray(m_v["edge_staleness"]).max()) == 2


@pytest.mark.parametrize("bucketed", [None, 4])
@pytest.mark.parametrize("wire", ["int8", "bf16"])
def test_carrier_resident_bitwise_across_lifts(wire, bucketed):
    """Carrier-resident gossip state (ISSUE 17) is part of the
    cross-lift parity surface: the wire-dtype receive buffers and the
    per-leaf dequant scales are carried state like everything else,
    compared `==` (in the carrier dtype — both lifts store the same
    bits) across the vmap simulator and the shard_map mesh."""
    s_v, m_v = _run("vmap", wire=wire, bucketed=bucketed, carrier=True)
    s_s, m_s = _run("shard_map", wire=wire, bucketed=bucketed,
                    carrier=True)
    # the parity claim is about the CARRIER program: both lifts must
    # actually hold wire-dtype buffers, not a silently demoted f32 copy
    wdt = {"int8": jnp.int8, "bf16": jnp.bfloat16}[wire]
    for s in (s_v, s_s):
        assert all(b.dtype == wdt for b in jax.tree.leaves(s.event.bufs))
    _assert_bitwise(s_v, s_s, m_v, m_s)


def test_telemetry_bitwise_across_lifts():
    """The on-device obs accumulators (per-edge wire bytes included)
    are part of the parity surface too."""
    s_v, m_v = _run("vmap", obs=True, bucketed=4)
    s_s, m_s = _run("shard_map", obs=True, bucketed=4)
    _assert_bitwise(s_v, s_s, m_v, m_s)


def test_resolve_backend_auto_prefers_mesh():
    """'auto' takes the mesh on this 8-device fixture and falls back to
    vmap when the topology outgrows the device count."""
    assert shard_map_available()
    mesh = resolve_backend("auto", Ring(4))
    assert mesh is not None
    assert resolve_backend("auto", Ring(1024)) is None
    assert resolve_backend("vmap", Ring(4)) is None
    with pytest.raises(ValueError):
        resolve_backend("nonsense", Ring(4))


def test_mesh64_scale_smoke():
    """The 64-rank scale leg: a real 64-device mesh program exchanges
    on the declared ring offsets only, and the per-neighbor wire bytes
    match `wire_real_bytes_per_neighbor` EXACTLY three ways (telemetry
    per edge / analytic formula / step metric) on all 64 ranks."""
    worker = os.path.join(os.path.dirname(__file__), "mesh64_worker.py")
    env = dict(os.environ)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, worker], env=env, capture_output=True,
        text=True, timeout=300,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["n_devices"] == 64 and rec["n_ranks"] == 64
    assert rec["exchange_offsets"] == rec["declared_offsets"] == [-1, 1]
    assert rec["undeclared_collectives"] == []
    assert rec["loss_finite"]
    per_nb = rec["per_neighbor_bytes_formula"]
    edge = np.asarray(rec["edge_bytes"])  # [64, n_nb] cumulative
    assert edge.shape == (64, rec["n_neighbors"])
    np.testing.assert_array_equal(
        edge, np.full_like(edge, rec["steps"] * per_nb)
    )
    metric = np.asarray(rec["sent_bytes_wire_real"])  # [64] per step
    np.testing.assert_array_equal(
        metric, np.full_like(metric, rec["n_neighbors"] * per_nb)
    )
