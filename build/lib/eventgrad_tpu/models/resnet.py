"""ResNet family (M4) — rebuild of /root/reference/dcifar10/common/resnet.hpp.

CIFAR-adapted ImageNet-style ResNet: 3x3 stem stride 1 (resnet.hpp:123), no
stem maxpool (commented out, :145), stages 64/128/256/512 with strides
1/2/2/2 (:125-128), avg_pool(4) head (:152), fc to num_classes.

**Faithful off-by-one preserved:** the reference's `make_layer` pushes one
stride-carrying block *plus* `blocks` more (:172-178), so the nominal
{2,2,2,2} "ResNet18" has 3 blocks per stage (~ResNet-26, ~17.4M params, 86
named tensors) — exactly what dcifar10/event/event.cpp:119-123 trains.
`extra_block=True` (default) reproduces that; set False for canonical
counts.

TPU-first choices: NHWC layout, optional bfloat16 compute dtype with fp32
params and fp32 BatchNorm statistics (MXU-friendly), flax BatchNorm with an
explicit `batch_stats` collection. BatchNorm running stats are *buffers,
not parameters* in the reference and are never gossiped
(dcifar10/event/event.cpp:122-123 communicates named_parameters() only) —
the training layer here keeps `batch_stats` rank-local for the same
semantics.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BasicBlock(nn.Module):
    """resnet.hpp:11-52. expansion = 1."""

    filters: int
    stride: int
    conv: ModuleDef
    norm: ModuleDef
    expansion: int = 1

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.stride, self.stride))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm()(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * self.expansion,
                (1, 1),
                strides=(self.stride, self.stride),
                name="downsample_conv",
            )(x)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class Bottleneck(nn.Module):
    """resnet.hpp:56-107. expansion = 4. Note the reference puts the stride on
    conv2 (3x3), torchvision-style (:73)."""

    filters: int
    stride: int
    conv: ModuleDef
    norm: ModuleDef
    expansion: int = 4

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), strides=(self.stride, self.stride))(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * self.expansion, (1, 1))(y)
        y = self.norm()(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * self.expansion,
                (1, 1),
                strides=(self.stride, self.stride),
                name="downsample_conv",
            )(x)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: Callable
    num_classes: int = 10
    num_filters: int = 64
    extra_block: bool = True  # faithful make_layer off-by-one (resnet.hpp:172-178)
    dtype: Any = jnp.float32  # compute dtype; bfloat16 for MXU throughput

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, padding="SAME", dtype=self.dtype)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )

        x = x.astype(self.dtype)
        x = conv(self.num_filters, (3, 3), name="stem_conv")(x)
        x = norm(name="stem_bn")(x)
        x = nn.relu(x)

        for stage, blocks in enumerate(self.stage_sizes):
            filters = self.num_filters * 2**stage
            n_blocks = blocks + 1 if self.extra_block else blocks
            for b in range(n_blocks):
                stride = 2 if (stage > 0 and b == 0) else 1
                x = self.block_cls(
                    filters=filters, stride=stride, conv=conv, norm=norm
                )(x)

        x = nn.avg_pool(x, window_shape=(4, 4), strides=(4, 4))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


def ResNet18(**kw) -> ResNet:
    """As instantiated by the reference: {2,2,2,2} -> 3 blocks/stage with
    extra_block=True (dcifar10/event/event.cpp:119-120)."""
    return ResNet(stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock, **kw)


def ResNet34(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock, **kw)


def ResNet50(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), block_cls=Bottleneck, **kw)


def ResNet101(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 23, 3), block_cls=Bottleneck, **kw)


def ResNet152(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 8, 36, 3), block_cls=Bottleneck, **kw)
