"""Tensor parallelism: TP-sharded Transformer == unsharded twin, exactly.

The strongest TP correctness check available without hardware: build the
tp_size=1 model, slice its weights into TP shards, and demand (a) identical
logits and (b) identical one-SGD-step weight updates (slice-for-slice)
between the TP=2 mesh run and the single-rank run. (b) exercises the psum
transpose rule through the whole backward pass.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from eventgrad_tpu.models.tp import TPTransformerLM
from eventgrad_tpu.parallel.spmd import spmd, stack_for_ranks
from eventgrad_tpu.parallel.topology import Ring, Topology
from eventgrad_tpu.train.state import TrainState, init_train_state_spmd
from eventgrad_tpu.train.steps import make_train_step

VOCAB, DIM, HEADS, LAYERS, T = 32, 32, 4, 2, 16
TP = 2


def _slice_params(full, tp_rank):
    """Slice the tp_size=1 params into the shard tp_rank would own.

    The qkv projection (ColParallelDense_0) is the fused [q|k|v] kernel:
    rank r owns head block r of EACH of q, k, v, so its shard slices each
    third separately; the MLP ColParallelDense_1 is structureless and
    slices contiguously."""

    def walk(path, leaf):
        name = "/".join(str(p.key) for p in path)
        if "ColParallelDense_0" in name and name.endswith("tp_kernel"):
            thirds = jnp.split(leaf, 3, axis=1)
            local = thirds[0].shape[1] // TP
            return jnp.concatenate(
                [t[:, tp_rank * local : (tp_rank + 1) * local] for t in thirds], axis=1
            )
        if "ColParallelDense" in name and name.endswith("tp_kernel"):
            local = leaf.shape[1] // TP
            return leaf[:, tp_rank * local : (tp_rank + 1) * local]
        if "RowParallelDense" in name and name.endswith("tp_kernel"):
            local = leaf.shape[0] // TP
            return leaf[tp_rank * local : (tp_rank + 1) * local, :]
        return leaf

    return jax.tree_util.tree_map_with_path(walk, full)


def _models():
    full = TPTransformerLM(vocab=VOCAB, dim=DIM, n_heads=HEADS, n_layers=LAYERS,
                           max_len=T, tp_size=1)
    tp = TPTransformerLM(vocab=VOCAB, dim=DIM, n_heads=HEADS, n_layers=LAYERS,
                         max_len=T, axis="tp", tp_size=TP)
    return full, tp


def _qkv_note():
    """ColParallelDense for qkv concatenates [q|k|v] per shard: slicing the
    full kernel's columns per rank keeps each rank's q,k,v for its local
    heads IFF the full model's reshape order groups heads contiguously.
    The models reshape to (b, t, 3*h_local, d) per rank, so a column slice
    of the fused qkv kernel is NOT the per-head slice — to sidestep this,
    the equivalence test compares the tp run against a full run whose qkv
    kernel was built by re-concatenating the tp shards, which is always
    consistent."""


def test_tp_forward_and_step_match_unsharded():
    topo = Topology(axes=("tp",), shape=(TP,), sharded_axes=("tp",))
    assert topo.neighbors == ()  # sharded axis never gossips
    full_model, tp_model = _models()

    tx = optax.sgd(0.1)
    state = init_train_state_spmd(
        tp_model, (T,), tx, topo, "dpsgd", input_dtype=jnp.int32
    )

    # build the unsharded twin by concatenating the TP shards
    def merge(path, *leaves):
        name = "/".join(str(p.key) for p in path)
        if "ColParallelDense_0" in name and name.endswith("tp_kernel"):
            # per-rank [q_r|k_r|v_r] -> full [q_all|k_all|v_all]
            thirds = [jnp.split(l, 3, axis=1) for l in leaves]
            return jnp.concatenate(
                [jnp.concatenate([t[i] for t in thirds], axis=1) for i in range(3)],
                axis=1,
            )
        if "ColParallelDense" in name and name.endswith("tp_kernel"):
            return jnp.concatenate(leaves, axis=1)
        if "RowParallelDense" in name and name.endswith("tp_kernel"):
            return jnp.concatenate(leaves, axis=0)
        for l in leaves[1:]:
            np.testing.assert_allclose(np.asarray(leaves[0]), np.asarray(l), atol=1e-7)
        return leaves[0]

    shards = [jax.tree.map(lambda p: p[r], state.params) for r in range(TP)]
    full_params = jax.tree_util.tree_map_with_path(merge, *shards)

    toks = jax.random.randint(jax.random.PRNGKey(5), (2, T), 0, VOCAB)
    tgts = jnp.roll(toks, -1, axis=-1)

    # (a) forward parity
    tp_logits = spmd(
        lambda p, t: tp_model.apply({"params": p}, t), topo
    )(state.params, jnp.broadcast_to(toks, (TP,) + toks.shape))
    full_logits = full_model.apply({"params": full_params}, toks)
    np.testing.assert_allclose(
        np.asarray(tp_logits[0]), np.asarray(full_logits), atol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(tp_logits[0]), np.asarray(tp_logits[1]), atol=1e-6
    )

    # (b) one-SGD-step parity, slice for slice (psum transpose correctness)
    step = make_train_step(tp_model, tx, topo, "dpsgd")
    lifted = jax.jit(spmd(step, topo))
    xb = jnp.broadcast_to(toks, (TP,) + toks.shape)
    yb = jnp.broadcast_to(tgts, (TP,) + tgts.shape)
    new_state, m = lifted(state, (xb, yb))

    def full_loss(p):
        out = full_model.apply({"params": p}, toks)
        logp = jax.nn.log_softmax(out)
        return -jnp.mean(jnp.take_along_axis(logp, tgts[..., None], -1))

    g = jax.grad(full_loss)(full_params)
    full_new = jax.tree.map(lambda p, g: p - 0.1 * g, full_params, g)

    for r in range(TP):
        expect = _slice_params(full_new, r)
        got = jax.tree.map(lambda p: p[r], new_state.params)
        for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(expect),
            jax.tree_util.tree_leaves_with_path(got),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=3e-5,
                err_msg=f"rank {r}: {jax.tree_util.keystr(pa)}",
            )


def test_allreduce_times_tp_matches_unsharded_ddp():
    """algo=allreduce on a dp x tp mesh: gradients average over dp ONLY.

    Regression for the advisor's round-1 finding: a blanket all-axes pmean
    would elementwise-average the tp-sharded kernels' gradients (distinct
    parameter shards), silently corrupting training. The twin is the
    unsharded model taking one DDP step on the mean of the two dp batches;
    every tp shard of every dp rank must equal the twin's slice."""
    topo = Topology(
        axes=("dp", "tp"), shape=(2, TP), gossip_axes=("dp",), sharded_axes=("tp",)
    )
    full_model, tp_model = _models()
    tx = optax.sgd(0.1)
    state = init_train_state_spmd(
        tp_model, (T,), tx, topo, "allreduce", input_dtype=jnp.int32
    )

    def merge(path, *leaves):
        name = "/".join(str(p.key) for p in path)
        if "ColParallelDense_0" in name and name.endswith("tp_kernel"):
            thirds = [jnp.split(l, 3, axis=1) for l in leaves]
            return jnp.concatenate(
                [jnp.concatenate([t[i] for t in thirds], axis=1) for i in range(3)],
                axis=1,
            )
        if "ColParallelDense" in name and name.endswith("tp_kernel"):
            return jnp.concatenate(leaves, axis=1)
        if "RowParallelDense" in name and name.endswith("tp_kernel"):
            return jnp.concatenate(leaves, axis=0)
        return leaves[0]

    shards = [jax.tree.map(lambda p: p[r], state.params) for r in range(TP)]
    full_params = jax.tree_util.tree_map_with_path(merge, *shards)

    key = jax.random.PRNGKey(11)
    toks = jax.random.randint(key, (2, 2, T), 0, VOCAB)  # one batch per dp rank
    tgts = jnp.roll(toks, -1, axis=-1)
    # mesh layout [dp, tp] row-major: replicate each dp batch over tp
    xb = jnp.repeat(toks, TP, axis=0).reshape(4, 2, T)
    yb = jnp.repeat(tgts, TP, axis=0).reshape(4, 2, T)

    step = make_train_step(tp_model, tx, topo, "allreduce")
    new_state, _ = jax.jit(spmd(step, topo))(state, (xb, yb))

    def full_loss(p, t, g):
        out = full_model.apply({"params": p}, t)
        logp = jax.nn.log_softmax(out)
        return -jnp.mean(jnp.take_along_axis(logp, g[..., None], -1))

    g0 = jax.grad(full_loss)(full_params, toks[0], tgts[0])
    g1 = jax.grad(full_loss)(full_params, toks[1], tgts[1])
    g = jax.tree.map(lambda a, b: (a + b) / 2.0, g0, g1)
    full_new = jax.tree.map(lambda p, gg: p - 0.1 * gg, full_params, g)

    for dp_r in range(2):
        for tp_r in range(TP):
            expect = _slice_params(full_new, tp_r)
            got = jax.tree.map(
                lambda p: p[dp_r * TP + tp_r], new_state.params
            )
            for (pa, a), (_, b) in zip(
                jax.tree_util.tree_leaves_with_path(expect),
                jax.tree_util.tree_leaves_with_path(got),
            ):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), atol=3e-5,
                    err_msg=f"dp {dp_r} tp {tp_r}: {jax.tree_util.keystr(pa)}",
                )


def test_dp_gossip_times_tp():
    """EventGraD across dp while blocks are TP-sharded: 4x2 mesh."""
    from eventgrad_tpu.parallel.events import EventConfig

    topo = Topology(
        axes=("dp", "tp"), shape=(4, TP), gossip_axes=("dp",), sharded_axes=("tp",)
    )
    assert len(topo.neighbors) == 2 and topo.aux_axes == ()
    tp_model = TPTransformerLM(vocab=VOCAB, dim=DIM, n_heads=HEADS, n_layers=LAYERS,
                               max_len=T, axis="tp", tp_size=TP)
    tx = optax.sgd(0.1)
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=2)
    state = init_train_state_spmd(
        tp_model, (T,), tx, topo, "eventgrad", cfg, input_dtype=jnp.int32
    )
    step = make_train_step(tp_model, tx, topo, "eventgrad", event_cfg=cfg)
    lifted = jax.jit(spmd(step, topo))

    key = jax.random.PRNGKey(9)
    toks = jax.random.randint(key, (4, 2, T), 0, VOCAB)  # per-dp batches
    xb = jnp.repeat(toks, TP, axis=0).reshape(8, 2, T)  # replicate over tp
    yb = jnp.roll(xb, -1, axis=-1)

    losses = []
    for _ in range(6):
        state, m = lifted(state, (xb, yb))
        losses.append(float(np.asarray(m["loss"]).mean()))
    assert losses[-1] < losses[0]
    assert int(np.asarray(state.event.num_events).sum()) > 0

    # tp shards of a dp rank must stay consistent: replicated leaves equal
    emb = state.params["Embed_0"]["embedding"].reshape(4, TP, VOCAB, DIM)
    np.testing.assert_allclose(
        np.asarray(emb[:, 0]), np.asarray(emb[:, 1]), atol=1e-5
    )
