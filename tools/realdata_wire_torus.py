"""Wire-compression and 2D-torus legs on real pixels (round 4).

Extends the real-data evidence (tools/realdata_digits.py: refpure 64.2%
saved at -1.4pp on the UCI scans) to two beyond-reference capabilities
that so far had synthetic-only measurements:

  wire bf16 / int8   compressed gossip payloads (collectives.py wire
                     codecs) — same op-point as the r3 refpure leg, so
                     accuracy deltas read directly against
                     realdata_digits_r3_cpu.json
  torus:2x4          the 4-neighbor /5-mixing 2D torus (BASELINE's
                     stress topology class) on real pixels

Writes artifacts/realdata_wire_torus_r4_cpu.json.
Usage: python tools/realdata_wire_torus.py [epochs]
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax


def main() -> None:
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from eventgrad_tpu.data.datasets import load_digits
    from eventgrad_tpu.models import CNN2
    from eventgrad_tpu.parallel.events import EventConfig
    from eventgrad_tpu.parallel.topology import Ring, Torus
    from eventgrad_tpu.train.loop import consensus_params, evaluate, rank0_slice, train

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    epochs = int(sys.argv[1]) if len(sys.argv) > 1 else 60
    (x, y), (xt, yt) = (load_digits("train"), load_digits("test"))
    x, y = jnp.asarray(x), jnp.asarray(y)
    batch = 20  # 9 steps/epoch on Ring(8) — r3 digits op-point
    cfg = EventConfig(adaptive=True, horizon=1.0, warmup_passes=30)
    out = {
        "dataset": "sklearn-digits (real scans, MNIST geometry)",
        "epochs": epochs,
        "reference_leg": "realdata_digits_r3_cpu.json (refpure 64.2% at -1.4pp)",
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    legs = [
        ("wire_bf16", Ring(8), {"wire": "bf16"}),
        ("wire_int8", Ring(8), {"wire": "int8"}),
        ("torus2x4", Torus(2, 4), {}),
    ]
    for tag, topo, extra in legs:
        t0 = time.perf_counter()
        state, hist = train(
            CNN2(), topo, x, y, algo="eventgrad", event_cfg=cfg,
            epochs=epochs, batch_size=batch, learning_rate=0.05,
            random_sampler=False, log_every_epoch=False, **extra,
        )
        cons = consensus_params(state.params)
        stats0 = rank0_slice(state.batch_stats)
        acc = evaluate(CNN2(), cons, stats0, xt, yt)["accuracy"]
        out[tag] = {
            "passes": epochs * (len(x) // (batch * topo.n_ranks)),
            "msgs_saved_pct": round(hist[-1]["msgs_saved_pct"], 2),
            "test_acc": round(acc, 2),
            "sent_bytes_per_step": round(
                hist[-1]["sent_bytes_per_step_per_chip"], 1
            ),
            "final_loss": round(hist[-1]["loss"], 4),
            "n_neighbors": topo.n_neighbors,
            "wall_s": round(time.perf_counter() - t0, 1),
        }
        print(tag, out[tag], flush=True)

    path = os.path.join(repo, "artifacts", "realdata_wire_torus_r4_cpu.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
