"""Elastic membership engine (chaos/membership.py, ISSUE 6).

The load-bearing guarantees:
  * `Ring(n) -> leave -> Ring(n-1) -> join -> Ring(n)` round-trips
    BITWISE against never having transitioned, once buffers refresh
    (one force-fire cycle) — across arena on/off and masked|compact
    wires. Compared state: params, optimizer moments, event
    thresholds/norms/slopes, receive buffers, batch stats, pass counter.
    Excluded by design: the newcomer's PRNG stream (salted per join) and
    the cumulative send counters (a newcomer's accounting starts at 0 —
    membership.py docstring).
  * a join's bootstrap row IS the source neighbor's state (streamed
    through the checkpoint writer losslessly when a bootstrap dir
    exists);
  * train(membership=...) applies transitions at block boundaries,
    replays bitwise from the schedule, and resumes mid-schedule from a
    snapshot bitwise;
  * force_refresh arms a full fire on the next pass.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from _spmd import requires_shard_map

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from eventgrad_tpu.chaos.membership import (
    MembershipEngine, MembershipEvent, MembershipSchedule, force_refresh,
)
from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP
from eventgrad_tpu.parallel.events import EventConfig, propose
from eventgrad_tpu.parallel.spmd import build_mesh, spmd
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.loop import train
from eventgrad_tpu.train.state import init_train_state
from eventgrad_tpu.train.steps import make_train_step
from eventgrad_tpu.utils import trees

#: fire-every-pass trigger: constant threshold 0 is the documented exact
#: D-PSGD knob, so "one force-fire cycle" holds on every pass and the
#: round-trip comparison needs no special-cased refresh pass
_FIRE_ALWAYS = EventConfig(adaptive=False, constant=0.0, warmup_passes=0)


def _identical_batches(n_ranks: int, steps: int, batch: int = 4, seed=0):
    """Per-step batches with IDENTICAL content per rank: with replicated
    init this keeps every rank's row bitwise-equal across steps, which is
    what makes a leave->join round trip content-restoring (the newcomer
    copies a neighbor that equals the departed rank)."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(steps):
        xb = rng.standard_normal((batch, 8, 8, 1)).astype(np.float32)
        yb = rng.integers(0, 10, (batch,)).astype(np.int32)
        out.append((
            jnp.asarray(np.broadcast_to(xb[None], (n_ranks,) + xb.shape)),
            jnp.asarray(np.broadcast_to(yb[None], (n_ranks,) + yb.shape)),
        ))
    return out


def _build(topo, arena: bool, wire_mode: str, mesh=None):
    model = MLP(hidden=8)
    tx = optax.sgd(0.1)
    state = init_train_state(
        model, (8, 8, 1), tx, topo, "eventgrad", _FIRE_ALWAYS, arena=arena
    )
    # one shared PRNG row: rank rows must be fully identical for the
    # round-trip content argument (the stock per-rank split decorrelates
    # augmentation, which this harness doesn't use)
    state = state.replace(
        rng=jnp.broadcast_to(state.rng[0], state.rng.shape)
    )
    cap = (
        trees.tree_count_params(state.params) // topo.n_ranks
        if wire_mode == "compact" else None
    )
    step = make_train_step(
        model, tx, topo, "eventgrad", event_cfg=_FIRE_ALWAYS, arena=arena,
        gossip_wire=wire_mode if wire_mode == "compact" else "dense",
        compact_capacity=cap,
    )
    return state, jax.jit(spmd(step, topo, mesh=mesh))


def _run(lift, state, batches):
    for b in batches:
        state, _ = lift(state, b)
    return state


def _assert_bitwise_except_salted(a, b):
    """Full-state bitwise equality minus the per-join salted PRNG rows
    and the cumulative send counters (zeroed for newcomers by design)."""
    def strip(s):
        ev = s.event
        if ev is not None:
            ev = ev.replace(
                num_events=jnp.zeros_like(ev.num_events),
                num_deferred=jnp.zeros_like(ev.num_deferred),
            )
        return s.replace(rng=jnp.zeros_like(s.rng), event=ev)

    la, lb = jax.tree.leaves(strip(a)), jax.tree.leaves(strip(b))
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _round_trip(state, topo, pos: int):
    """leave(pos) then join(pos) at one boundary; returns state on the
    restored Ring(n)."""
    eng = MembershipEngine(MembershipSchedule(), event_cfg=_FIRE_ALWAYS)
    st, t2, info_l = eng.apply(
        state, topo, MembershipEvent(epoch=1, kind="leave", index=pos)
    )
    assert t2.n_ranks == topo.n_ranks - 1
    st, t3, info_j = eng.apply(
        st, t2, MembershipEvent(epoch=1, kind="join", index=pos)
    )
    assert t3.n_ranks == topo.n_ranks
    assert info_j["src"] == (pos - 1) % t2.n_ranks
    return st, t3


@pytest.mark.parametrize("wire_mode", ["masked", "compact"])
@pytest.mark.parametrize("arena", [False, True])
def test_leave_join_round_trip_bitwise(arena, wire_mode):
    topo = Ring(4)
    state, lift = _build(topo, arena, wire_mode)
    batches = _identical_batches(4, 5)
    state = _run(lift, state, batches[:3])

    baseline = _run(lift, state, batches[3:])
    st_rt, topo_rt = _round_trip(state, topo, pos=1)
    transitioned = _run(lift, st_rt, batches[3:])

    _assert_bitwise_except_salted(baseline, transitioned)


def test_round_trip_through_ring2():
    """Heal-to-2 and join-from-2: the degenerate ring where both neighbor
    shifts resolve to the same peer must round-trip like any other size
    (the Ring(2) mixing semantics themselves are pinned in
    tests/test_topology.py)."""
    topo = Ring(3)
    state, lift = _build(topo, arena=False, wire_mode="masked")
    batches = _identical_batches(3, 4)
    state = _run(lift, state, batches[:2])

    baseline = _run(lift, state, batches[2:])
    st_rt, _ = _round_trip(state, topo, pos=2)
    transitioned = _run(lift, st_rt, batches[2:])

    _assert_bitwise_except_salted(baseline, transitioned)


@requires_shard_map
def test_round_trip_bitwise_shard_map():
    """The membership round trip composes with the real-mesh shard_map
    lift exactly like the vmap simulator (usual env skipif)."""
    topo = Ring(4)
    mesh = build_mesh(topo)
    state, lift = _build(topo, arena=False, wire_mode="masked", mesh=mesh)
    batches = _identical_batches(4, 4)
    state = _run(lift, state, batches[:2])
    baseline = _run(lift, state, batches[2:])
    st_rt, _ = _round_trip(state, topo, pos=1)
    transitioned = _run(lift, st_rt, batches[2:])
    _assert_bitwise_except_salted(baseline, transitioned)


# --- engine unit behavior ----------------------------------------------


def _distinct_rows_state(topo):
    state, lift = _build(topo, arena=False, wire_mode="masked")
    # decorrelate rows so bootstrap provenance is observable
    rng = np.random.default_rng(3)
    batches = [(
        jnp.asarray(
            rng.standard_normal((topo.n_ranks, 4, 8, 8, 1)).astype(
                np.float32
            )
        ),
        jnp.asarray(
            rng.integers(0, 10, (topo.n_ranks, 4)).astype(np.int32)
        ),
    ) for _ in range(2)]
    return _run(lift, state, batches)


def test_join_bootstraps_src_row_and_zeroes_counters():
    topo = Ring(4)
    state = _distinct_rows_state(topo)
    eng = MembershipEngine(MembershipSchedule(), event_cfg=_FIRE_ALWAYS)
    st, t2, info = eng.apply(
        state, topo, MembershipEvent(epoch=3, kind="join", index=2, src=0)
    )
    assert t2.n_ranks == 5 and info["src"] == 0
    for new, old in zip(
        jax.tree.leaves(st.params), jax.tree.leaves(state.params)
    ):
        new, old = np.asarray(new), np.asarray(old)
        np.testing.assert_array_equal(new[2], old[0])   # bootstrap copy
        np.testing.assert_array_equal(new[:2], old[:2])  # survivors keep
        np.testing.assert_array_equal(new[3:], old[2:])  # rows shift up
    assert int(np.asarray(st.event.num_events)[2]) == 0
    assert int(np.asarray(st.event.num_deferred)[2]) == 0
    # the newcomer's PRNG stream is salted, not a correlated copy
    assert not np.array_equal(
        np.asarray(st.rng)[2], np.asarray(state.rng)[0]
    )


def test_join_streams_through_checkpoint_writer(tmp_path):
    """bootstrap_dir routes the neighbor snapshot through host_snapshot +
    checkpoint.save + restore — and the stream is lossless (bitwise vs
    the in-memory handoff)."""
    topo = Ring(4)
    state = _distinct_rows_state(topo)
    ev = MembershipEvent(epoch=3, kind="join", index=1)
    mem_eng = MembershipEngine(MembershipSchedule(), event_cfg=_FIRE_ALWAYS)
    st_mem, _, info_mem = mem_eng.apply(state, topo, ev)
    disk_eng = MembershipEngine(
        MembershipSchedule(), event_cfg=_FIRE_ALWAYS,
        bootstrap_dir=str(tmp_path),
    )
    st_disk, _, info_disk = disk_eng.apply(state, topo, ev)
    assert not info_mem["bootstrap_streamed"]
    assert info_disk["bootstrap_streamed"]
    assert os.path.exists(str(tmp_path / "bootstrap"))
    for a, b in zip(jax.tree.leaves(st_mem), jax.tree.leaves(st_disk)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_leave_matches_apply_ring_heal():
    from eventgrad_tpu.chaos.policy import apply_ring_heal

    topo = Ring(4)
    state = _distinct_rows_state(topo)
    eng = MembershipEngine(MembershipSchedule(), event_cfg=None)
    st, t2, info = eng.apply(
        state, topo, MembershipEvent(epoch=1, kind="leave", index=1)
    )
    ref, ref_topo, survivors = apply_ring_heal(state, topo, {1})
    assert info["survivors"] == list(survivors) == [0, 2, 3]
    assert t2.n_ranks == ref_topo.n_ranks == 3
    # engine leave == heal + force_refresh (None cfg -> adaptive arming)
    ref = force_refresh(ref, None)
    for a, b in zip(jax.tree.leaves(st), jax.tree.leaves(ref)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_force_refresh_arms_full_fire():
    topo = Ring(2)
    params = {"w": jnp.ones((3,)), "b": jnp.ones((2,))}
    cfg = EventConfig(adaptive=True, horizon=1.0, warmup_passes=0)
    from eventgrad_tpu.parallel.events import EventState

    st = EventState.init(params, topo, cfg)
    st = st.replace(thres=jnp.full_like(st.thres, 1e9))  # silenced

    quiet = propose(params, st, jnp.int32(5), cfg)
    assert not bool(np.asarray(quiet.fire_vec).any())

    # force_refresh only touches .event via .replace — a tiny shim state
    import dataclasses

    @dataclasses.dataclass
    class Shim:
        event: object

        def replace(self, **kw):
            return Shim(**{**{"event": self.event}, **kw})

    armed = force_refresh(Shim(event=st), cfg).event
    fired = propose(params, armed, jnp.int32(5), cfg)
    assert bool(np.asarray(fired.fire_vec).all())


def test_schedule_validation():
    with pytest.raises(ValueError, match="below 2"):
        MembershipSchedule.parse("leave=0@1,leave=0@2").n_ranks_at(3, 5)
    with pytest.raises(ValueError):
        MembershipEvent(epoch=0, kind="leave", index=1)
    with pytest.raises(ValueError):
        MembershipEvent(epoch=1, kind="leave", index=1, src=0)
    with pytest.raises(ValueError, match="bad membership clause"):
        MembershipSchedule.parse("leave=1")
    with pytest.raises(ValueError, match="unknown membership key"):
        MembershipSchedule.parse("die=1@2")
    eng = MembershipEngine(MembershipSchedule(), event_cfg=None)
    topo = Ring(4)
    state = init_train_state(
        MLP(hidden=8), (8, 8, 1), optax.sgd(0.1), topo, "dpsgd"
    )
    with pytest.raises(ValueError, match="outside"):
        eng.apply(
            state, topo, MembershipEvent(epoch=1, kind="join", index=9)
        )
    from eventgrad_tpu.parallel.topology import Torus

    with pytest.raises(ValueError, match="single-axis"):
        eng.apply(
            state, Torus(2, 2),
            MembershipEvent(epoch=1, kind="leave", index=0),
        )


# --- train()-level integration -----------------------------------------


_TRAIN_CFG = EventConfig(
    adaptive=True, horizon=0.95, warmup_passes=2, max_silence=5
)


def _train_kw():
    return dict(
        algo="eventgrad", batch_size=8, learning_rate=0.1,
        event_cfg=_TRAIN_CFG,
    )


def test_train_membership_records_and_replay_bitwise():
    x, y = synthetic_dataset(256, (8, 8, 1), seed=1)
    memb = "leave=1@2,join=1@4"
    st1, hist = train(
        MLP(hidden=16), Ring(4), x, y, epochs=6, membership=memb,
        **_train_kw(),
    )
    # transitions landed at the block boundaries the schedule named
    assert [h["active_ranks"] for h in hist] == [4, 4, 3, 3, 4, 4]
    assert hist[0]["membership"] == MembershipSchedule.parse(
        memb
    ).to_dict()  # replayability rider
    t_leave = hist[2]["membership_transitions"]
    t_join = hist[4]["membership_transitions"]
    assert t_leave[0]["kind"] == "leave" and t_leave[0]["epoch"] == 2
    assert t_join[0]["kind"] == "join" and t_join[0]["n_ranks_after"] == 4
    assert "membership_transitions" not in hist[0]
    assert jax.tree.leaves(st1.params)[0].shape[0] == 4
    # the logged schedule replays the final state bitwise
    st2, _ = train(
        MLP(hidden=16), Ring(4), x, y, epochs=6,
        membership=hist[0]["membership"], **_train_kw(),
    )
    for a, b in zip(
        jax.tree.leaves(st1.params), jax.tree.leaves(st2.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_train_chaos_inline_membership_replays_from_both_riders():
    """A chaos spec with inline join=/leave= clauses stamps BOTH riders
    (rec["membership"] and the chaos dict's embedded events); replaying
    from the run's own log feeds both back — identical events must not
    trip the pass-one-schedule conflict check."""
    x, y = synthetic_dataset(64, (8, 8, 1), seed=1)
    chaos = "drop=0.0,seed=3,leave=1@1,join=1@2"
    st1, hist = train(
        MLP(hidden=8), Ring(3), x, y, epochs=3, chaos=chaos, **_train_kw()
    )
    assert hist[0]["chaos"]["membership"]  # events ride the chaos rider
    st2, _ = train(
        MLP(hidden=8), Ring(3), x, y, epochs=3,
        membership=hist[0]["membership"], chaos=hist[0]["chaos"],
        **_train_kw(),
    )
    for a, b in zip(
        jax.tree.leaves(st1.params), jax.tree.leaves(st2.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    with pytest.raises(ValueError, match="disagree"):
        train(
            MLP(hidden=8), Ring(3), x, y, epochs=3, chaos=chaos,
            membership="leave=0@1", **_train_kw(),
        )


def test_compact_autotune_ignores_force_fire_pass():
    """The force-fired rewire pass after a transition is NOT steady-state
    trigger data: sampling it would push the observed fired peak to
    n_params, size the compact budget to the whole model, and silently
    keep the run dense. Schedule a leave so the forced pass lands first
    in the autotune window — compaction must still activate."""
    import flax.linen as nn

    class ManyLeaf(nn.Module):
        @nn.compact
        def __call__(self, x, train=False, **kw):
            x = x.reshape((x.shape[0], -1))
            x = nn.Dense(64)(x)
            for _ in range(6):
                x = nn.relu(nn.Dense(64)(x))
            return nn.Dense(10)(x)

    os.environ["EG_COMPACT_MIN_SAMPLES"] = "4"
    try:
        x, y = synthetic_dataset(128, (8, 8, 1), seed=6)
        # 4 steps/epoch at 4 ranks: warmup_passes=5 keeps every epoch-1
        # pass out of the window, so sampling would begin exactly at
        # epoch 2's first pass — the force-fired one (leave applies at
        # the end of epoch 1); the sampler must push the window past
        # the transient block. horizon=2.0 keeps the steady-state fire
        # rate low enough that the budget beats n_params
        cfg = EventConfig(adaptive=True, horizon=2.0, warmup_passes=5)
        _, h = train(
            ManyLeaf(), Ring(4), x, y,
            algo="eventgrad", epochs=5, batch_size=8, learning_rate=0.05,
            seed=1, gossip_wire="compact", event_cfg=cfg,
            membership="leave=1@1,join=1@4",
        )
    finally:
        del os.environ["EG_COMPACT_MIN_SAMPLES"]
    tuned = [r for r in h if "compact_autotuned" in r]
    assert len(tuned) == 1 and tuned[0]["compact_autotuned"]
    assert "compact_skipped" not in tuned[0]
    assert tuned[0]["compact_fired_peak_elems"] < h[0]["n_params"]
    assert h[-1]["gossip_wire"] == "compact"
    assert h[-1]["compact_capacity"] < h[0]["n_params"]


def test_train_membership_resume_bitwise(tmp_path):
    """A membership run interrupted at an epoch where the ring had
    already shrunk resumes from its snapshot (topology re-derived from
    the membership log at the peeked epoch) and finishes bitwise-equal
    to the uninterrupted run."""
    x, y = synthetic_dataset(256, (8, 8, 1), seed=1)
    memb = "leave=1@2,join=1@4"
    kw = _train_kw()
    st_ref, _ = train(
        MLP(hidden=16), Ring(4), x, y, epochs=6, membership=memb, **kw
    )
    ck = str(tmp_path / "ck")
    train(
        MLP(hidden=16), Ring(4), x, y, epochs=3, membership=memb,
        checkpoint_dir=ck, **kw
    )
    st_res, hist = train(
        MLP(hidden=16), Ring(4), x, y, epochs=6, membership=memb,
        checkpoint_dir=ck, resume=True, **kw
    )
    assert [h["active_ranks"] for h in hist] == [3, 4, 4]
    for a, b in zip(jax.tree.leaves(st_ref), jax.tree.leaves(st_res)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_train_membership_validation():
    x, y = synthetic_dataset(64, (8, 8, 1), seed=1)
    kw = dict(epochs=2, batch_size=8)
    with pytest.raises(ValueError, match="gossip"):
        train(MLP(hidden=8), Ring(4), x, y, algo="allreduce",
              membership="leave=1@1", **kw)
    with pytest.raises(ValueError, match="pipeline"):
        train(MLP(hidden=8), Ring(4), x, y, algo="dpsgd",
              membership="leave=1@1", pipeline=True, **kw)
    with pytest.raises(ValueError, match="trace_file"):
        train(MLP(hidden=8), Ring(4), x, y, algo="dpsgd",
              membership="leave=1@1", trace_file="/tmp/t.jsonl", **kw)
    with pytest.raises(ValueError, match="one"):
        train(MLP(hidden=8), Ring(4), x, y, algo="dpsgd",
              membership="leave=1@1", chaos="drop=0,leave=2@1", **kw)
    with pytest.raises(ValueError, match="below 2"):
        train(MLP(hidden=8), Ring(4), x, y, algo="dpsgd",
              membership="leave=0@1,leave=0@2,leave=0@3", **kw)
