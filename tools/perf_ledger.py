"""Performance ledger: one schema-gated trajectory across every round.

Six BENCH_r*.json rounds existed with no perf trajectory between them —
nothing detected a step_ms or msgs-saved regression from one PR to the
next, and the repo's only hardware-efficiency number was a hand-derived
MFU in a ROADMAP aside. This tool ingests every committed driver record
(BENCH_r*.json, MULTICHIP_r*.json) plus the perf-ablation artifacts into
ONE ledger (`artifacts/perf_ledger_<backend>.json`, PERF_LEDGER_SCHEMA
in tools/validate_artifacts.py):

  * per-round trajectory — step_ms, MFU, msgs-saved-%, acc-gap,
    sent_bytes_wire_real, host-bubble-frac, with data provenance
    (`synthetic-prototype` vs real) and the producing git round;
  * MFU/roofline backfill — rounds whose records predate the cost model
    get analytic FLOPs/bytes from `obs.costmodel.analyze_step` at the
    round's recorded op-point and peaks from `obs.devicespec` (CPU
    rounds use the NOMINAL generic-cpu spec: a cross-round tracking
    number, never a hardware claim — `nominal_spec` marks it);
  * mesh-backend rows — artifacts/mesh_ablation_*.json
    (tools/mesh_ablation.py) joins the trajectory as
    backend="shard_map" entries: real-collective step times at the
    ablation op-point plus the 64-rank scale leg;
  * frontier rows — artifacts/frontier_*.json (tools/frontier.py)
    joins per policy x wire leg (config="frontier-<wire>", `policy` in
    the group key), so the bytes-vs-accuracy sweep's sent-bytes and
    msgs-saved numbers get the same regression tracking as the bench
    tiers without ever cross-gating between legs;
  * residency rows — artifacts/resident_ablation_*.json
    (tools/overhead_ablation.py resident) joins per residency leg
    (config="resident-<dtype>", `resident_dtype` on the row) with each
    leg's analytic bytes/step and roofline next to its measured
    scanned step time;
  * regression gates — explicit ratio-vs-previous-round thresholds,
    evaluated within comparability groups (same
    platform+model+config+backend+policy; a TPU flagship round is
    never compared against a CPU tiny smoke, a shard_map mesh row
    never gates against a vmap simulator row, and a sparse trigger
    policy's traffic never gates against a dense one's).
    A failed gate fails `--check` (exit 1) AND the committed artifact
    (the schema pins `gates_all_ok: true`), so a regression cannot be
    committed silently.

bench.py prints a one-line trajectory delta against this ledger at the
end of every run (`format_delta`); `tools/obs_report.py --ledger`
renders the trajectory. The acceptance instrument for ROADMAP open
item 1: the shard_map lift must MOVE the MFU/roofline trajectory, not
just pass parity.

Usage: python tools/perf_ledger.py [--root PATH] [--out PATH]
                                   [--no-costmodel] [--check] [--quiet]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LEDGER_SCHEMA_VERSION = 1

#: regression gates: (metric, kind, threshold). Ratios are cur/prev over
#: consecutive rounds of one comparability group; both values must be
#: present (a round that lacks a metric is not a regression — the gate
#: for a VANISHED metric is the schema's required-fields list).
#:   max-ratio:    cur/prev <= t   (step time, wire bytes may not blow up)
#:   min-ratio:    cur/prev >= t   (MFU, msgs-saved may not collapse)
#:   max-abs-rise: |cur| <= |prev| + t  (accuracy gap, bubble fraction)
GATES: Tuple[Tuple[str, str, float], ...] = (
    ("step_ms", "max-ratio", 1.5),
    ("mfu", "min-ratio", 0.6),
    ("msgs_saved_pct", "min-ratio", 0.75),
    ("sent_bytes_wire_real", "max-ratio", 1.5),
    ("acc_gap_vs_dpsgd", "max-abs-rise", 1.0),
    ("host_bubble_frac", "max-abs-rise", 0.05),
)

#: per-rank batch by bench tier (bench.py op-points: global 256 on the
#: full tier, 64 on the CPU tiers, 8 ranks) — the records don't carry
#: the batch size, the tier pins it; "mesh-cpu" is the shard_map
#: ablation's op-point (tools/mesh_ablation.py, per-rank 8)
_PER_RANK_BY_CONFIG = {
    "full": 32, "full-rehearsal": 8, "reduced": 8, "tiny": 8,
    "mesh-cpu": 8,
}


def comparable_key(
    rec: Dict[str, Any],
) -> Optional[Tuple[str, str, str, str, str, str]]:
    """Comparability group of a bench record/ledger entry: rounds are
    gated against each other ONLY within (platform, model, config,
    backend, policy, staleness). The backend dimension (vmap single-chip
    simulator vs shard_map device mesh, ISSUE 14) keeps mesh rows from
    ever gating against vmap rows — a real-collective step time is not a
    regression of a batched-simulation one; records predating the
    field were all vmap. The policy dimension (trigger policies,
    ISSUE 16: threshold vs micro vs topk rows from the frontier sweep)
    keeps a sparser policy's sent-bytes/msgs-saved from ever gating
    against a denser one's; records predating the field all ran the
    default adaptive-threshold trigger. The staleness dimension
    (bounded-async delivery queues, ISSUE 20: EG_BENCH_STALENESS=D
    rows) keeps a D >= 2 run's step time — which carries the queue
    commit work and mixes post-arrival buffers — from gating against a
    lockstep round's; records predating the field all ran lockstep
    (staleness 0)."""
    plat, model, cfg = (
        rec.get("platform"), rec.get("model"), rec.get("config"),
    )
    if not (plat and model and cfg):
        return None
    return (
        str(plat), str(model), str(cfg),
        str(rec.get("backend") or "vmap"),
        str(rec.get("policy") or "default"),
        str(rec.get("staleness") or 0),
    )


# --- ingestion -------------------------------------------------------------


def _round_of(name: str) -> int:
    m = re.search(r"_r(\d+)\.json$", name)
    return int(m.group(1)) if m else 0


def _bench_entry(path: str) -> Dict[str, Any]:
    name = os.path.basename(path)
    with open(path) as f:
        raw = json.load(f)
    n = int(raw.get("n") or _round_of(name))
    rec = raw.get("parsed")
    if not isinstance(rec, dict) or "metric" not in rec:
        return {
            "round": n, "source": name, "status": "no-data",
            "git_round": n, "provenance": None,
            "note": f"rc={raw.get('rc')}; no parseable metric line "
                    "(device stalled / bench failed)",
        }
    return {
        "round": n, "source": name, "status": "ok", "git_round": n,
        # bench data has always been the synthetic class-prototype set;
        # records before the `data` field default to that, the real-data
        # flagship (ROADMAP open item 2) will stamp "real"
        "provenance": rec.get("data", "synthetic-prototype"),
        "platform": rec.get("platform"),
        "device_kind": rec.get("device_kind"),
        "config": rec.get("config"),
        "model": rec.get("model"),
        # SPMD lift that produced the numbers; pre-field records were
        # all the single-chip vmap simulator (ISSUE 14)
        "backend": rec.get("backend", "vmap"),
        # bounded-async staleness bound of the event legs; pre-field
        # records all ran lockstep (ISSUE 20)
        "staleness": rec.get("staleness", 0),
        "passes": rec.get("passes"),
        "collapsed": rec.get("collapsed", False),
        "step_ms": rec.get("step_ms"),
        "step_ms_dpsgd": rec.get("step_ms_dpsgd"),
        "step_overhead_ratio": rec.get("step_overhead_ratio"),
        "msgs_saved_pct": rec.get("value"),
        "mnist_msgs_saved": rec.get("mnist_msgs_saved"),
        "acc_gap_vs_dpsgd": rec.get("acc_gap_vs_dpsgd"),
        "sent_bytes_wire_real": rec.get("sent_bytes_wire_real"),
        "host_bubble_frac": rec.get("host_bubble_frac"),
        "buckets": rec.get("buckets"),
        "horizon": rec.get("horizon"),
        "max_silence": rec.get("max_silence"),
        "warmup_passes": rec.get("warmup_passes"),
        "flops_per_step": rec.get("flops_per_step"),
        "mfu": rec.get("mfu"),
        "mfu_source": "record" if rec.get("mfu") is not None else None,
    }


def _multichip_entry(path: str) -> Dict[str, Any]:
    name = os.path.basename(path)
    with open(path) as f:
        raw = json.load(f)
    return {
        "round": _round_of(name), "source": name,
        "n_devices": raw.get("n_devices"), "ok": raw.get("ok"),
        "skipped": raw.get("skipped"),
    }


def _mesh_entries(root: str, next_round: int) -> List[Dict[str, Any]]:
    """Mesh-backend rows from artifacts/mesh_ablation_*.json
    (tools/mesh_ablation.py, ISSUE 14): the real-collective step times
    join the trajectory as backend="shard_map" entries — their own
    comparability groups, so the MFU/roofline trajectory finally
    tracks REAL exchange cost without ever gating against the vmap
    simulator's rows."""
    out: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(
        os.path.join(root, "artifacts", "mesh_ablation_*.json")
    )):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        name = os.path.basename(path)
        op = rec.get("op_point", {})
        sm = rec.get("results", {}).get("shard_map", {})
        ev, dp = sm.get("eventgrad", {}), sm.get("dpsgd", {})
        out.append({
            "round": next_round, "source": name, "status": "ok",
            "git_round": None,
            "provenance": op.get("data", "synthetic-prototype"),
            "platform": rec.get("platform"),
            "config": "mesh-cpu",
            "model": op.get("model"),
            "backend": "shard_map",
            "n_ranks": 8,
            "step_ms": ev.get("step_ms_p50"),
            "step_ms_dpsgd": dp.get("step_ms_p50"),
            "step_overhead_ratio": rec.get("step_overhead_ratio_mesh"),
            "mesh_vs_vmap_ratio": rec.get("mesh_vs_vmap_ratio"),
            "mfu": None,
            "mfu_source": None,
        })
        scale = rec.get("scale64") or {}
        if scale.get("step_ms") is not None:
            out.append({
                "round": next_round, "source": name + "#scale64",
                "status": "ok", "git_round": None,
                "provenance": "synthetic-prototype",
                "platform": rec.get("platform"),
                "config": "mesh-scale64",
                "model": scale.get("model"),
                "backend": "shard_map",
                "n_ranks": scale.get("n_ranks"),
                "step_ms": scale.get("step_ms"),
                "mfu": None,
                "mfu_source": None,
            })
    return out


def _frontier_entries(root: str, next_round: int) -> List[Dict[str, Any]]:
    """Bytes-vs-accuracy frontier rows from artifacts/frontier_*.json
    (tools/frontier.py, ISSUE 16): each policy x wire leg joins the
    trajectory as its own comparability group — `policy` rides the
    group key and the wire folds into `config` ("frontier-<wire>"), so
    an int8 leg's sent-bytes never gates against an f32 leg's and a
    sparse policy's msgs-saved never gates against a dense one's."""
    out: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(
        os.path.join(root, "artifacts", "frontier_*.json")
    )):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        name = os.path.basename(path)
        op = rec.get("op_point", {})
        for leg in rec.get("legs", ()):
            wire = leg.get("wire") or "f32"
            out.append({
                "round": next_round, "source": f"{name}#{leg.get('policy')}-{wire}",
                "status": "ok", "git_round": None,
                "provenance": op.get("data", "synthetic-prototype"),
                "platform": rec.get("platform"),
                "config": f"frontier-{wire}",
                "model": rec.get("model"),
                "backend": leg.get("backend", "vmap"),
                "policy": leg.get("policy"),
                "wire": leg.get("wire"),
                "gossip_wire": leg.get("gossip_wire"),
                "msgs_saved_pct": leg.get("msgs_saved_pct"),
                "sent_bytes_wire_real": leg.get("bytes_per_step_per_chip"),
                "test_accuracy": leg.get("test_accuracy"),
                "fired_frac": leg.get("fired_frac"),
                "mfu": None,
                "mfu_source": None,
            })
    return out


def _resident_entries(root: str, next_round: int) -> List[Dict[str, Any]]:
    """Carrier-residency rows from artifacts/resident_ablation_*.json
    (tools/overhead_ablation.py resident, ISSUE 17): the f32-resident
    and carrier-resident legs join as separate comparability groups
    (the residency folds into `config`), each carrying its analytic
    bytes/step and roofline next to the measured scanned step time —
    the ledger's record of WHERE the bytes went when the buffers
    shrank."""
    out: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(
        os.path.join(root, "artifacts", "resident_ablation_*.json")
    )):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        name = os.path.basename(path)
        op = rec.get("op_point", {})
        for leg, res in (rec.get("results") or {}).items():
            if not isinstance(res, dict):
                continue
            out.append({
                "round": next_round, "source": f"{name}#{leg}",
                "status": "ok", "git_round": None,
                "provenance": "synthetic-prototype",
                "platform": rec.get("platform"),
                "config": f"resident-{res.get('resident_dtype')}",
                "model": op.get("model"),
                "backend": "vmap",
                "policy": "default",
                "resident_dtype": res.get("resident_dtype"),
                "wire": op.get("wire"),
                "gossip_wire": op.get("gossip_wire"),
                "step_ms": res.get("step_ms_p50"),
                "hbm_bytes_per_step": res.get("hbm_bytes_per_step"),
                "arithmetic_intensity": res.get("arithmetic_intensity"),
                "roofline_bound": res.get("roofline_bound"),
                "roofline_frac": res.get("roofline_frac"),
                "mfu": None,
                "mfu_source": None,
            })
    return out


#: perf-ablation artifacts folded in as trajectory snapshots: each is
#: already schema-gated on its own acceptance bound; the ledger records
#: the headline number so one file answers "where does the perf stand"
_ABLATIONS = (
    ("arena", "arena_ablation_cpu.json", "overhead_ratio_after"),
    ("bucketed", "bucketed_ablation_cpu.json", "overhead_ratio"),
    ("pipeline_bubble", "pipeline_bubble_cpu.json", "bubble_ratio"),
    ("obs_overhead", "obs_overhead_cpu.json", "overhead_pct_p50"),
    ("resident", "resident_ablation_cpu.json", "consumer_bytes_drop_pct"),
)


def _ablation_snapshot(root: str) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for key, name, field in _ABLATIONS:
        path = os.path.join(root, "artifacts", name)
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        out[key] = {
            "metric": field, "value": rec.get(field),
            "artifact": f"artifacts/{name}",
        }
    ipath = os.path.join(root, "artifacts", "integrity_cpu.json")
    try:
        with open(ipath) as f:
            irec = json.load(f)
        out["integrity"] = {
            "metric": "overhead_ratio_p50",
            "value": irec.get("overhead", {}).get("overhead_ratio_p50"),
            "artifact": "artifacts/integrity_cpu.json",
        }
    except (OSError, json.JSONDecodeError):
        pass
    return out


# --- cost-model backfill ---------------------------------------------------


def _costmodel_fill(entries: List[Dict[str, Any]], quiet: bool) -> None:
    """Populate flops/hbm/MFU/roofline for every ok entry from the
    analytic cost model at the entry's recorded op-point. One trace per
    distinct (model, per_rank) — results are cached. Entries whose
    record already carries an XLA-compiled MFU keep it (mfu_source
    "record"); the analytic roofline fields ride next to it either way."""
    from eventgrad_tpu.obs import costmodel
    from eventgrad_tpu.obs.devicespec import spec_for_kind

    cache: Dict[Tuple[str, int], Dict[str, Any]] = {}

    def _analyze(model_name: str, per_rank: int) -> Optional[Dict[str, Any]]:
        key = (model_name, per_rank)
        if key in cache:
            return cache[key]
        import jax.numpy as jnp
        import optax

        from eventgrad_tpu.data.datasets import load_or_synthesize
        from eventgrad_tpu.parallel.events import EventConfig
        from eventgrad_tpu.parallel.topology import Ring
        from eventgrad_tpu.train.state import init_train_state

        if model_name in ("ResNet", "ResNet18"):
            from eventgrad_tpu.models import ResNet18

            model = ResNet18(dtype=jnp.bfloat16)
        elif model_name == "LeNetCifar":
            from eventgrad_tpu.models import LeNetCifar

            model = LeNetCifar()
        else:
            cache[key] = None
            return None
        topo = Ring(8)
        tx = optax.sgd(1e-2, momentum=0.9)
        cfg = EventConfig(
            adaptive=True, horizon=1.05, warmup_passes=10, max_silence=50,
        )
        n = topo.n_ranks * per_rank
        x, y = load_or_synthesize("cifar10", None, "train", n_synth=n)
        state = init_train_state(
            model, x.shape[1:], tx, topo, "eventgrad", cfg, seed=0
        )
        if not quiet:
            print(
                f"costmodel: tracing {model_name} @ {per_rank}/rank ...",
                file=sys.stderr,
            )
        cm = costmodel.analyze_step(
            model, tx, topo, "eventgrad", cfg, x, y, per_rank, state
        )
        cache[key] = cm
        return cm

    for e in entries:
        if e.get("status") != "ok" or not e.get("step_ms"):
            continue
        per_rank = _PER_RANK_BY_CONFIG.get(e.get("config") or "", None)
        cm = _analyze(e.get("model") or "", per_rank) if per_rank else None
        if cm is None:
            continue
        spec = spec_for_kind(e.get("platform"), e.get("device_kind"))
        step_s = float(e["step_ms"]) / 1000.0
        rl = costmodel.roofline(
            cm["flops_total"], cm["hbm_bytes_total"], step_s, spec
        )
        e["hbm_bytes_per_step"] = cm["hbm_bytes_total"]
        e["arithmetic_intensity"] = rl["arithmetic_intensity"]
        e["ridge_intensity"] = rl["ridge_intensity"]
        e["roofline_bound"] = rl["roofline_bound"]
        e["roofline_frac"] = rl["roofline_frac"]
        e["achieved_bytes_per_s"] = rl["achieved_bytes_per_s"]
        e["device_spec"] = rl["device_spec"]
        e["nominal_spec"] = rl["nominal_spec"]
        if e.get("mfu") is None:
            # the backfilled MFU is the ANALYTIC meter end to end
            # (rl["mfu"] = cm flops / step_s / spec peak) and the
            # entry's flops_per_step is set to the same analytic count,
            # so a backfilled entry's mfu is always derivable from its
            # own fields; record-carried (XLA-compiled) MFUs are kept
            # as-is and never mixed with the analytic numerator
            e["flops_per_step"] = cm["flops_total"]
            e["mfu"] = round(rl["mfu"], 6)
            e["mfu_source"] = "costmodel"


# --- regression gates ------------------------------------------------------


def evaluate_gates(entries: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Pairwise ratio gates over consecutive ok entries of each
    comparability group. Pure on the entry dicts — the seeded-regression
    test drives this directly."""
    results: List[Dict[str, Any]] = []
    by_group: Dict[Tuple[str, ...], List[Dict[str, Any]]] = {}
    for e in sorted(
        (e for e in entries if e.get("status") == "ok"),
        key=lambda e: e["round"],
    ):
        key = comparable_key(e)
        if key is not None:
            by_group.setdefault(key, []).append(e)
    for key, group in sorted(by_group.items()):
        for prev, cur in zip(group, group[1:]):
            for metric, kind, thr in GATES:
                a, b = prev.get(metric), cur.get(metric)
                if a is None or b is None:
                    continue
                a, b = float(a), float(b)
                if kind == "max-ratio":
                    if a <= 0:
                        continue
                    ratio = b / a
                    ok = ratio <= thr
                elif kind == "min-ratio":
                    if a <= 0:
                        continue
                    ratio = b / a
                    ok = ratio >= thr
                else:  # max-abs-rise
                    ratio = abs(b) - abs(a)
                    ok = ratio <= thr
                results.append({
                    "metric": metric, "kind": kind, "threshold": thr,
                    "group": list(key), "prev_round": prev["round"],
                    "round": cur["round"], "prev": a, "cur": b,
                    "ratio": round(ratio, 4), "ok": bool(ok),
                })
    return results


# --- assembly --------------------------------------------------------------


def build_ledger(root: str, with_costmodel: bool = True,
                 quiet: bool = False) -> Dict[str, Any]:
    entries = [
        _bench_entry(p)
        for p in sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))
    ]
    entries.sort(key=lambda e: e["round"])
    next_round = (entries[-1]["round"] + 1) if entries else 1
    entries.extend(_mesh_entries(root, next_round))
    entries.extend(_frontier_entries(root, next_round))
    entries.extend(_resident_entries(root, next_round))
    if with_costmodel:
        _costmodel_fill(entries, quiet)
    gates = evaluate_gates(entries)
    return {
        "bench": "perf_ledger",
        "schema_version": LEDGER_SCHEMA_VERSION,
        "generated_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "n_rounds": len(entries),
        "rounds_with_mfu": sum(
            1 for e in entries if e.get("mfu") is not None
        ),
        "rounds": entries,
        "multichip": [
            _multichip_entry(p)
            for p in sorted(
                glob.glob(os.path.join(root, "MULTICHIP_r*.json"))
            )
        ],
        "ablations": _ablation_snapshot(root),
        "gates": gates,
        "gates_all_ok": all(g["ok"] for g in gates),
    }


def format_delta(prev: Dict[str, Any], cur: Dict[str, Any]) -> str:
    """One-line step_ms/MFU trajectory delta (bench.py prints this to
    stderr at the end of every run) — the backend rides next to the
    numbers so a shard_map capture is never misread as a vmap one."""
    # .get throughout: rows written before the policy/backend/residency
    # keys existed (or hand-trimmed fixtures) must still render
    bits = [
        f"perf trajectory vs round {prev.get('round', '-')} "
        f"({prev.get('source') or '-'}, "
        f"backend={cur.get('backend') or 'vmap'}):"
    ]
    for name, key in (("step_ms", "step_ms"), ("mfu", "mfu")):
        a, b = prev.get(key), cur.get(key)
        if a and b:
            bits.append(f"{name} {a:g} -> {b:g} ({float(b) / float(a):.2f}x)")
        elif b is not None:
            bits.append(f"{name} {b:g} (no prior)")
    return " ".join(bits)


def last_comparable(ledger: Dict[str, Any],
                    rec: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Most recent ledger entry in `rec`'s comparability group."""
    key = comparable_key(rec)
    if key is None:
        return None
    matches = [
        e for e in ledger.get("rounds", ())
        if e.get("status") == "ok" and comparable_key(e) == key
    ]
    return matches[-1] if matches else None


def render_text(ledger: Dict[str, Any]) -> str:
    # tolerant of pre-current-schema ledgers throughout (.get with '-'
    # placeholders): rows and gates written before the policy/backend/
    # resident-* keys existed — or trimmed by hand for a bisect — must
    # render, not KeyError (tests/test_ledger.py pins this on the
    # committed artifact with those keys stripped)
    rounds = ledger.get("rounds") or []
    gates = ledger.get("gates") or []
    lines = [
        f"perf ledger — {ledger.get('n_rounds', len(rounds))} rounds "
        f"({ledger.get('rounds_with_mfu', '-')} with MFU), gates "
        + ("ALL OK" if ledger.get("gates_all_ok", True) else "FAILING"),
        f"{'rnd':>3} {'cfg':<14} {'model':<10} {'plat':<4} "
        f"{'step_ms':>8} {'mfu':>8} {'saved%':>7} {'gap':>6} "
        f"{'bound':>7} prov",
    ]
    for e in rounds:
        if e.get("status") != "ok":
            lines.append(
                f"{e.get('round', '-'):>3} -- no data ({e.get('note', '')})"
            )
            continue

        def _f(v, fmt):
            return format(v, fmt) if v is not None else "-"

        lines.append(
            f"{e.get('round', '-'):>3} {e.get('config') or '-':<14} "
            f"{e.get('model') or '-':<10} {e.get('platform') or '-':<4} "
            f"{_f(e.get('step_ms'), '8.2f'):>8} "
            f"{_f(e.get('mfu'), '8.4f'):>8} "
            f"{_f(e.get('msgs_saved_pct'), '7.2f'):>7} "
            f"{_f(e.get('acc_gap_vs_dpsgd'), '6.2f'):>6} "
            f"{e.get('roofline_bound') or '-':>7} "
            f"{e.get('provenance') or '-'}"
        )
    bad = [g for g in gates if not g.get("ok")]
    lines.append(
        f"gates: {len(gates)} evaluated, {len(bad)} failing"
    )
    for g in bad:
        def _g(v):
            return format(float(v), "g") if v is not None else "-"

        lines.append(
            f"  FAIL {g.get('metric', '?')} "
            f"r{g.get('prev_round', '-')}->r{g.get('round', '-')} "
            f"{_g(g.get('prev'))} -> {_g(g.get('cur'))} "
            f"({g.get('kind', '?')} {g.get('ratio', '-')} "
            f"vs {g.get('threshold', '-')}) group={g.get('group', '-')}"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--root",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    ap.add_argument("--out", default=None,
                    help="ledger path (default artifacts/"
                         "perf_ledger_<backend>.json)")
    ap.add_argument("--no-costmodel", action="store_true",
                    help="skip the analytic backfill traces (MFU only "
                         "where records carry it)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 when any regression gate fails")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    if not args.no_costmodel:
        from eventgrad_tpu.utils import compile_cache

        compile_cache.honor_cpu_pin()
    ledger = build_ledger(
        args.root, with_costmodel=not args.no_costmodel, quiet=args.quiet
    )
    out = args.out
    if out is None:
        import jax

        out = os.path.join(
            args.root, "artifacts",
            f"perf_ledger_{jax.default_backend()}.json",
        )
    with open(out, "w") as f:
        json.dump(ledger, f, indent=1)
        f.write("\n")
    if not args.quiet:
        print(render_text(ledger))
    print(f"wrote {out}", file=sys.stderr)
    if args.check and not ledger["gates_all_ok"]:
        print("perf ledger: regression gates FAILING", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
