"""MNIST CNNs (M2/M3) and the CIFAR LeNet (M5), TPU layout (NHWC).

  * CNN1 — the EventGraD paper's first MNIST model, present but commented out
    in the reference (/root/reference/dmnist/event/event.cpp:15-48):
    conv(1->10,k5) pool relu, conv(10->20,k5) drop2d pool relu,
    fc 320->100 relu, dropout .5, fc 100->10, log_softmax.
  * CNN2 — the model `event` actually trains (event.cpp:50-83):
    conv(1->10,k3) pool relu, conv(10->20,k3) drop2d pool relu,
    fc 500->50 relu, dropout .5, fc 50->10, log_softmax.
    27,480 params in 8 tensors (printed by event.cpp:162-165).
  * LeNetCifar — dcifar10/common/nnet.hpp:3-33: conv(3->6,k5) relu pool,
    conv(6->16,k5) drop2d relu? — note the reference order is
    pool(relu(drop(conv2))) for conv2 (nnet.hpp:18) and pool(relu(conv1))
    for conv1 (nnet.hpp:17); fc 400->120->84->10, log_softmax. ~62K params.

All convolutions are VALID-padded like torch's default. Dropout2d (channel
dropout) maps to nn.Dropout broadcast over the spatial dims of NHWC.
Outputs are log-probabilities; pairing them with an NLL loss matches the
reference's double-log_softmax quirk exactly, since log_softmax is
idempotent (event.cpp:291 applies log_softmax to an already-log_softmax'd
forward output).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


def _max_pool2(x):
    return nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))


class CNN1(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(10, (5, 5), padding="VALID")(x)
        x = nn.relu(_max_pool2(x))
        x = nn.Conv(20, (5, 5), padding="VALID")(x)
        x = nn.Dropout(0.5, broadcast_dims=(1, 2), deterministic=not train)(x)
        x = nn.relu(_max_pool2(x))
        x = x.reshape((x.shape[0], -1))  # 4*4*20 = 320
        x = nn.relu(nn.Dense(100)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes)(x)
        return nn.log_softmax(x, axis=-1)


class CNN2(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(10, (3, 3), padding="VALID")(x)
        x = nn.relu(_max_pool2(x))
        x = nn.Conv(20, (3, 3), padding="VALID")(x)
        x = nn.Dropout(0.5, broadcast_dims=(1, 2), deterministic=not train)(x)
        x = nn.relu(_max_pool2(x))
        x = x.reshape((x.shape[0], -1))  # 5*5*20 = 500
        x = nn.relu(nn.Dense(50)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes)(x)
        return nn.log_softmax(x, axis=-1)


class LeNetCifar(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(6, (5, 5), padding="VALID")(x)
        x = _max_pool2(nn.relu(x))
        x = nn.Conv(16, (5, 5), padding="VALID")(x)
        x = nn.Dropout(0.5, broadcast_dims=(1, 2), deterministic=not train)(x)
        x = _max_pool2(nn.relu(x))
        x = x.reshape((x.shape[0], -1))  # 5*5*16 = 400
        x = nn.relu(nn.Dense(120)(x))
        x = nn.relu(nn.Dense(84)(x))
        x = nn.Dense(self.num_classes)(x)
        return nn.log_softmax(x, axis=-1)
