"""Pallas FlashAttention kernel == materialized-score reference.

Runs in interpret mode on the CPU test harness; the same kernels compile to
Mosaic on real TPU (exercised by bench/driver runs). Covers forward,
custom-VJP gradients, padding (T and D not multiples of the 128 tile),
causal and bidirectional masks, bf16 inputs, and vmap (the single-chip
rank-simulation lifting path wraps everything in vmap).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from eventgrad_tpu.ops import flash_attention, flash_attention_reference


def _qkv(key, b=2, t=48, h=2, d=32, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    mk = lambda k: jax.random.normal(k, (b, t, h, d), dtype)
    return mk(ks[0]), mk(ks[1]), mk(ks[2])


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t,d", [(48, 32), (128, 64), (160, 24)])
def test_forward_matches_reference(causal, t, d):
    q, k, v = _qkv(jax.random.PRNGKey(0), t=t, d=d)
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    ref = flash_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t", [80, 160])  # 160 pads to 2 blocks: exercises
def test_gradients_match_reference(causal, t):  # cross-block scratch accum
    q, k, v = _qkv(jax.random.PRNGKey(1), t=t, d=32)
    w = jax.random.normal(jax.random.PRNGKey(2), q.shape)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v, causal) * w)

    g_flash = jax.grad(
        lambda q, k, v: jnp.sum(flash_attention(q, k, v, causal, interpret=True) * w),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(loss(flash_attention_reference), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-4)


def test_bf16_forward_stable():
    q, k, v = _qkv(jax.random.PRNGKey(3), t=64, d=64, dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = flash_attention_reference(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32), causal=True
    )
    assert out.dtype == jnp.bfloat16
    assert not np.any(np.isnan(np.asarray(out, np.float32)))
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=0.05, rtol=0.05
    )


def test_vmap_over_ranks():
    q, k, v = _qkv(jax.random.PRNGKey(4), b=1, t=32, d=16)
    qs = jnp.stack([q, 2 * q]), jnp.stack([k, k]), jnp.stack([v, -v])
    out = jax.vmap(lambda q, k, v: flash_attention(q, k, v, True, interpret=True))(*qs)
    for r in range(2):
        ref = flash_attention_reference(qs[0][r], qs[1][r], qs[2][r], causal=True)
        np.testing.assert_allclose(
            np.asarray(out[r]), np.asarray(ref), atol=2e-5, rtol=2e-5
        )


def test_transformer_flash_mode_trains():
    from eventgrad_tpu.models.transformer import TransformerLM

    model = TransformerLM(vocab=32, dim=32, n_heads=2, n_layers=1, max_len=16,
                          attn="flash")
    x = jax.random.randint(jax.random.PRNGKey(5), (2, 16), 0, 32)
    params = model.init(jax.random.PRNGKey(6), x)["params"]

    def loss(p):
        logits = model.apply({"params": p}, x)
        return -jnp.mean(
            jnp.take_along_axis(
                jax.nn.log_softmax(logits[:, :-1]), x[:, 1:, None], axis=-1
            )
        )

    l, g = jax.value_and_grad(loss)(params)
    assert np.isfinite(float(l))
    assert all(np.all(np.isfinite(np.asarray(x))) for x in jax.tree.leaves(g))

    # flash and full attention agree through the whole model
    model_full = TransformerLM(vocab=32, dim=32, n_heads=2, n_layers=1, max_len=16,
                               attn="full")
    np.testing.assert_allclose(
        np.asarray(model.apply({"params": params}, x)),
        np.asarray(model_full.apply({"params": params}, x)),
        atol=2e-5, rtol=2e-5,
    )
