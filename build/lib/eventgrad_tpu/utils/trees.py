"""Small pytree helpers shared across the framework."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def tree_norm(tree: Any) -> Any:
    """Per-leaf L2 norm of the flattened leaf — the event metric
    `torch::norm(flatten(param))` (/root/reference/dmnist/event/event.cpp:325),
    returned as a pytree of scalars."""
    return jax.tree.map(lambda x: jnp.linalg.norm(x.reshape(-1)), tree)


def tree_scalar_zeros(tree: Any, dtype=jnp.float32) -> Any:
    """A pytree of scalar zeros matching `tree`'s structure — the per-parameter
    C arrays of the reference (event.cpp:181-225) as explicit state."""
    return jax.tree.map(lambda _: jnp.zeros((), dtype), tree)


def tree_zeros_like(tree: Any) -> Any:
    return jax.tree.map(jnp.zeros_like, tree)


def tree_where(cond_tree: Any, a: Any, b: Any) -> Any:
    """Per-leaf select; `cond_tree` holds scalars broadcast against leaves."""
    return jax.tree.map(lambda c, x, y: jnp.where(c, x, y), cond_tree, a, b)


def tree_count_params(tree: Any) -> int:
    """Total element count (reference prints this at startup, event.cpp:158-165)."""
    return sum(x.size for x in jax.tree.leaves(tree))


def tree_num_leaves(tree: Any) -> int:
    return len(jax.tree.leaves(tree))


def tree_cast(tree: Any, dtype) -> Any:
    return jax.tree.map(lambda x: x.astype(dtype), tree)
