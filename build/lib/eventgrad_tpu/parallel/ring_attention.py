"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has no attention or sequence dimension at all (image CNNs
only — SURVEY §5 "long-context: absent"), but its core communication
primitive — a ring of neighbor exchanges — is *exactly* the collective that
long-context attention needs. This module generalizes the framework's ring
machinery (`collectives.recv_from` on a named mesh axis) from gossiping
parameters to rotating KV blocks, making long-sequence training a
first-class capability of the same topology layer:

  * `ring_attention`: the sequence is sharded across the ring axis; each
    rank keeps its Q shard resident and the (K, V) shards rotate one hop
    per step (N ppermutes on ICI), accumulating attention with an online
    (flash-style) running max/denominator — memory O(T/N) per chip,
    overlap-friendly, exact.
  * `ulysses_attention`: all-to-all switches sequence sharding to head
    sharding, computes full attention locally over heads, and switches
    back — one collective pair instead of N hops; needs n_heads % N == 0.

Both are pure per-rank SPMD functions: lift with `parallel.spmd` under
vmap (tests, single chip) or shard_map (real mesh), like every other
collective here.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from eventgrad_tpu.parallel.topology import NeighborSpec, Topology


def _block_attend(q, k, v, bias):
    """Scaled dot-product scores of a local Q block against one KV block.

    q: [B, Tq, H, D], k/v: [B, Tk, H, D]; bias broadcastable to [B,H,Tq,Tk].
    Returns (scores [B,H,Tq,Tk] fp32, v) ready for online-softmax merge.
    """
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores * (1.0 / jnp.sqrt(jnp.float32(d)))
    if bias is not None:
        scores = scores + bias
    return scores


def _online_merge(m, l, o, scores, v):
    """Numerically-stable streaming softmax accumulation (the flash
    recurrence): fold one block's scores/values into running (max m,
    denominator l, unnormalized output o)."""
    m_new = jnp.maximum(m, scores.max(axis=-1))
    correction = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])  # [B,H,Tq,Tk]
    l_new = l * correction + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    topo: Topology,
    axis: Optional[str] = None,
    causal: bool = False,
    use_flash: bool = False,
) -> jnp.ndarray:
    """Exact attention over a sequence sharded on a ring axis.

    q/k/v: per-rank shards [B, T_local, H, D]; global sequence length is
    T_local * axis_size, shard r owning positions [r*T_local, (r+1)*T_local).
    Returns the local output shard [B, T_local, H, D] (q.dtype).

    use_flash=True computes each hop's block attention with the Pallas
    FlashAttention kernel (out + logsumexp, global-position causal offsets)
    and folds hops together with the two-way online-softmax merge — scores
    stay in VMEM instead of materializing [B,H,T/N,T/N] per hop.
    """
    axis = axis or topo.axes[0]
    n = topo.axis_size(axis)
    nb = NeighborSpec(axis, -1)  # KV block arrives from the left each hop
    b, t_local, h, d = q.shape
    my_rank = lax.axis_index(axis)

    if use_flash:
        from eventgrad_tpu.ops.attention import flash_attention_lse

        def body_flash(step, carry):
            o, lse, kv = carry  # o [B,T,H,D] f32; lse [B,T,H] f32
            k_cur, v_cur = kv
            src = (my_rank - step) % n
            o_blk, lse_blk = flash_attention_lse(
                q, k_cur, v_cur, causal=causal,
                q_offset=my_rank * t_local, k_offset=src * t_local,
            )
            lse_new = jnp.logaddexp(lse, lse_blk)
            w_old = jnp.exp(lse - lse_new)[..., None]
            w_blk = jnp.exp(lse_blk - lse_new)[..., None]
            o = o * w_old + o_blk.astype(jnp.float32) * w_blk
            kv = jax.tree.map(lambda x: lax.ppermute(
                x, axis, [((r + nb.offset) % n, r) for r in range(n)]), kv)
            return o, lse_new, kv

        o0 = jnp.zeros((b, t_local, h, d), jnp.float32)
        lse0 = jnp.full((b, t_local, h), -jnp.inf, jnp.float32)
        o, _, _ = lax.fori_loop(0, n, body_flash, (o0, lse0, (k, v)))
        return o.astype(q.dtype)

    m = jnp.full((b, h, t_local), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, t_local), jnp.float32)
    o = jnp.zeros((b, t_local, h, d), jnp.float32)

    def body(step, carry):
        m, l, o, kv = carry
        k_cur, v_cur = kv
        # after `step` hops the resident KV block originated at rank r-step
        src = (my_rank - step) % n
        bias = None
        if causal:
            q_pos = my_rank * t_local + jnp.arange(t_local)
            k_pos = src * t_local + jnp.arange(t_local)
            mask = q_pos[:, None] >= k_pos[None, :]
            bias = jnp.where(mask, 0.0, -jnp.inf)[None, None]
        scores = _block_attend(q, k_cur, v_cur, bias)
        m, l, o = _online_merge(m, l, o, scores, v_cur)
        kv = jax.tree.map(lambda x: lax.ppermute(
            x, axis, [((r + nb.offset) % n, r) for r in range(n)]), kv)
        return m, l, o, kv

    m, l, o, _ = lax.fori_loop(0, n, body, (m, l, o, (k, v)))
    # guard fully-masked rows (can't happen for causal with aligned shards,
    # but keeps the primitive total)
    l = jnp.maximum(l, 1e-30)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    topo: Topology,
    axis: Optional[str] = None,
    causal: bool = False,
    use_flash: bool = False,
) -> jnp.ndarray:
    """DeepSpeed-Ulysses-style SP: all_to_all seq-sharded -> head-sharded,
    full local attention, all_to_all back. Requires H % axis_size == 0.

    use_flash=True runs the local attention through the Pallas
    FlashAttention kernel (ops/attention.py) — after the all_to_all each
    rank holds full-sequence causal self-attention over its head shard,
    which is exactly the kernel's contract."""
    axis = axis or topo.axes[0]
    n = topo.axis_size(axis)
    b, t_local, h, d = q.shape
    if h % n != 0:
        raise ValueError(f"n_heads {h} not divisible by axis size {n}")

    def seq_to_heads(x):
        # [B, T/N, H, D] -> [B, T, H/N, D]: head chunk i ships to rank i,
        # received shards concatenate in rank order along the sequence
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):
        # [B, T, H/N, D] -> [B, T/N, H, D]
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if use_flash:
        from eventgrad_tpu.ops.attention import flash_attention

        return heads_to_seq(flash_attention(qg, kg, vg, causal=causal))
    t = t_local * n
    bias = None
    if causal:
        mask = jnp.tril(jnp.ones((t, t), bool))
        bias = jnp.where(mask, 0.0, -jnp.inf)[None, None]
    scores = _block_attend(qg, kg, vg, bias)
    m = scores.max(axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    out = jnp.einsum("bhqk,bkhd->bqhd", (p / p.sum(-1, keepdims=True)).astype(vg.dtype),
                     vg, preferred_element_type=jnp.float32)
    return heads_to_seq(out.astype(q.dtype))


def full_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, causal: bool = False
) -> jnp.ndarray:
    """Single-device reference attention (for tests and 1-rank fallback)."""
    t, s = q.shape[1], k.shape[1]
    bias = None
    if causal:
        mask = jnp.tril(jnp.ones((t, s), bool))
        bias = jnp.where(mask, 0.0, -jnp.inf)[None, None]
    scores = _block_attend(q, k, v, bias)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)
