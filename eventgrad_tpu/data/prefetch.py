"""Epoch prefetcher: overlap host batch assembly with device compute.

The reference's data layer is synchronous C++ inside the train loop
(custom.hpp get() per sample, assembled by the libtorch dataloader between
steps). On TPU the equivalent host-side cost is assembling the stacked
[n_ranks, steps, batch, ...] epoch arrays that the scan-compiled epoch
consumes. `EpochPrefetcher` hides that cost: while the device runs epoch E,
a background thread assembles epoch E+1: the shard plan comes from
`sharding.shard_random/shard_sequential` (numpy-PCG, so the data order is
identical whether or not the native library built — resume bit-parity
holds across machines) and the batch gather uses the native memcpy kernels
(native/dataio.cpp) when available — ctypes calls drop the GIL, so the
overlap is real.
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple

import numpy as np

from eventgrad_tpu.data import native
from eventgrad_tpu.data.sharding import epoch_index_plan


class EpochPrefetcher:
    """Double-buffered epoch batch assembly.

    get(epoch) returns (xb, yb) shaped [n_ranks, steps, batch, ...] /
    [n_ranks, steps, batch] — identical layout and shard semantics to
    `sharding.batched_epoch` — and immediately starts assembling
    epoch+1 in the background.
    """

    def __init__(
        self,
        x: np.ndarray,
        y: np.ndarray,
        n_ranks: int,
        batch_size: int,
        *,
        random: bool = False,
        seed: int = 0,
        last_epoch: Optional[int] = None,
    ):
        # preserve integer inputs (token sequences); images go to float32
        # (one rule with the device-resident path: sharding.input_cast_dtype)
        from eventgrad_tpu.data.sharding import input_cast_dtype

        self.x = np.ascontiguousarray(x, input_cast_dtype(x))
        self.y = np.ascontiguousarray(y, np.int32)
        self.n_ranks = n_ranks
        self.batch = batch_size
        self.random = random
        self.seed = seed
        self.last_epoch = last_epoch  # no speculative assembly past this
        # validates batch/shard sizes too (single source of truth)
        self.steps = epoch_index_plan(len(x), n_ranks, batch_size).shape[1]
        self._pending: Optional[Tuple[int, threading.Thread, dict]] = None

    def _assemble(self, epoch: int) -> Tuple[np.ndarray, np.ndarray]:
        idx = epoch_index_plan(
            len(self.x), self.n_ranks, self.batch,
            random=self.random, seed=self.seed, epoch=epoch,
        )
        return native.gather_batches(self.x, self.y, idx)

    def _start(self, epoch: int):
        box: dict = {}

        def work():
            try:
                box["out"] = self._assemble(epoch)
            except BaseException as e:  # surfaced by the consuming get()
                box["err"] = e

        th = threading.Thread(target=work, daemon=True, name=f"eg-prefetch-{epoch}")
        th.start()
        return (epoch, th, box)

    def get(self, epoch: int) -> Tuple[np.ndarray, np.ndarray]:
        out = None
        if self._pending is not None:
            ep, th, box = self._pending
            th.join()  # either our epoch, or stale speculation to retire
            if ep == epoch:
                if "err" in box:
                    raise box["err"]
                out = box["out"]
            self._pending = None
        if out is None:  # miss (first call or out-of-order epoch)
            out = self._assemble(epoch)
        if self.last_epoch is None or epoch < self.last_epoch:
            self._pending = self._start(epoch + 1)
        return out

    def close(self) -> None:
        if self._pending is not None:
            self._pending[1].join()
            self._pending = None
