"""Prove the dispatch pipeline deletes the host bubble (ISSUE 5).

Runs the SAME training job twice — `pipeline=False` (the legacy
serialized `block_until_ready -> flush -> eval -> checkpoint` chain) and
`pipeline=True` (the software-pipelined schedule, train/loop.py) — with
an `obs.Registry` recording the loop's span trace, and decomposes each
leg's wall into steps + flush + eval + checkpoint + data + other
(`obs.bubble.decompose`). The op-point deliberately loads every host
phase the pipeline is supposed to hide: per-block consensus eval,
`obs=block` telemetry flushes, periodic checkpoints, and host batch
assembly (K=1 blocks, so every epoch boundary pays the full chain).

Emits artifacts/pipeline_bubble_<platform>.json, schema-validated by
tools/validate_artifacts.py (PIPELINE_BUBBLE_SCHEMA): the gate pins
`bubble_ratio` (pipelined host_bubble_frac / serial host_bubble_frac)
strictly below 1.0 and `bitwise_state` — the two legs' final parameters
must be bit-identical, or the "optimization" changed training.

This is the CPU proxy of the r05 TPU flagship finding (steps ~531 s of
EventGraD's 851 s wall = ~38% bubble vs ~22% for D-PSGD): same loop,
same spans, same decomposition — the chip run re-measures it with
`tools/tpu_flagship.py` + `EG_BENCH_OBS_TRACE`.

Usage: python tools/bubble_decomposition.py [--epochs 8] [--out PATH]
                                            [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from eventgrad_tpu.utils import compile_cache  # noqa: E402

compile_cache.honor_cpu_pin()
compile_cache.enable()

import jax  # noqa: E402
import numpy as np  # noqa: E402

from eventgrad_tpu.data.datasets import synthetic_dataset  # noqa: E402
from eventgrad_tpu.models import CNN2  # noqa: E402
from eventgrad_tpu.obs import Registry  # noqa: E402
from eventgrad_tpu.obs import bubble  # noqa: E402
from eventgrad_tpu.parallel.events import EventConfig  # noqa: E402
from eventgrad_tpu.parallel.topology import Ring  # noqa: E402
from eventgrad_tpu.train.loop import train  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_leg(pipeline: bool, *, epochs: int, n_train: int, batch: int,
            ckpt_dir: str):
    """One train() leg with its own registry; returns (params, decomp)."""
    topo = Ring(4)
    x, y = synthetic_dataset(n_train, (28, 28, 1), seed=3)
    xt, yt = synthetic_dataset(256, (28, 28, 1), seed=3, split="test")
    cfg = EventConfig(adaptive=True, horizon=0.95, warmup_passes=5)
    reg = Registry(run_meta={"tool": "bubble_decomposition",
                             "pipeline": pipeline})
    state, hist = train(
        CNN2(), topo, x, y,
        algo="eventgrad", epochs=epochs, batch_size=batch,
        learning_rate=0.05, event_cfg=cfg, random_sampler=True, seed=7,
        x_test=xt, y_test=yt, obs="block", registry=reg,
        checkpoint_dir=ckpt_dir, save_every=max(2, epochs // 3),
        epochs_per_dispatch=1, pipeline=pipeline,
    )
    decomp = bubble.decompose(reg.spans)
    params = [np.asarray(l) for l in jax.tree.leaves(state.params)]
    metrics = [
        {k: v for k, v in h.items() if k != "wall_s"} for h in hist
    ]
    return params, metrics, decomp


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--n-train", type=int, default=2048)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--quick", action="store_true",
                    help="smoke scale (seconds; no artifact quality)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    if args.quick:
        args.epochs, args.n_train = 4, 512

    import tempfile

    op_point = {
        "model": "CNN2", "topo": "ring:4", "algo": "eventgrad",
        "epochs": args.epochs, "n_train": args.n_train,
        "batch_per_rank": args.batch, "obs": "block",
        "epochs_per_dispatch": 1, "eval_every_block": True,
    }
    results = {}
    params = {}
    metrics = {}
    # pipelined leg FIRST: in-process jit/orbax warmup then benefits the
    # serial leg, biasing the comparison AGAINST the pipeline — the gate
    # passing means the win survives a conservative measurement
    with tempfile.TemporaryDirectory() as td:
        for name, flag in (("pipelined", True), ("serial", False)):
            t0 = time.perf_counter()
            params[name], metrics[name], results[name] = run_leg(
                flag, epochs=args.epochs, n_train=args.n_train,
                batch=args.batch, ckpt_dir=os.path.join(td, name),
            )
            print(
                f"{name}: {time.perf_counter() - t0:.1f}s\n"
                + bubble.render_text(results[name], label=name),
                file=sys.stderr,
            )

    bitwise = len(params["serial"]) == len(params["pipelined"]) and all(
        a.tobytes() == b.tobytes()
        for a, b in zip(params["serial"], params["pipelined"])
    ) and metrics["serial"] == metrics["pipelined"]
    serial_frac = results["serial"]["host_bubble_frac"]
    pipe_frac = results["pipelined"]["host_bubble_frac"]
    ratio = pipe_frac / serial_frac if serial_frac else 1.0
    out = {
        "bench": "pipeline_bubble",
        "platform": jax.default_backend(),
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "op_point": op_point,
        "results": results,
        "bubble_ratio": round(ratio, 4),
        "bitwise_state": bool(bitwise),
        "quick": bool(args.quick),
    }
    # gate BEFORE touching the committed artifact, with the SAME bound
    # the schema enforces (PIPELINE_BUBBLE_SCHEMA: bubble_ratio <= 0.999,
    # bitwise_state true) — a failing run must never overwrite the good
    # committed proof and then report success
    ok = bitwise and out["bubble_ratio"] <= 0.999
    path = args.out or os.path.join(
        REPO, "artifacts", f"pipeline_bubble_{jax.default_backend()}.json"
    )
    if not ok:
        path += ".rejected"
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {path}", file=sys.stderr)
    print(json.dumps({k: out[k] for k in
                      ("bench", "bubble_ratio", "bitwise_state")}))
    if not bitwise:
        print("FAIL: pipeline changed training state/metrics",
              file=sys.stderr)
        return 1
    if not ok:
        print("FAIL: pipelined bubble not measurably below serial",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
