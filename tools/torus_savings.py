"""EventGraD on the 2D torus — the BASELINE stress topology, measured.

The reference only ever runs a 1-D ring (left/right neighbors,
dmnist/decent/decent.cpp:56-64); the rebuild's topology layer generalizes
to a 4-exchange torus with uniform 1/5 mixing (parallel/topology.py). On
the 8-device Torus(4,2) the y-axis has size 2, so both y-shifts reach the
SAME peer (counted twice, 2/5 weight) — faithfully matching the
reference's own size-2 ring behavior (both messages still sent,
decent.cpp:56-64) but meaning each rank has 3 DISTINCT peers, not 4; a
real v4-256 torus has 4. The op-point is tools/tune_horizon.py's
`run_point` (one definition across all artifact families) with the
topology swapped.

Output: one JSON line; committed as artifacts/torus_savings_r2_cpu.json.
Usage: JAX_PLATFORMS=cpu python tools/torus_savings.py [epochs]
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from tune_horizon import run_point  # noqa: E402

from eventgrad_tpu.parallel.topology import Torus  # noqa: E402


def main() -> None:
    epochs = int(sys.argv[1]) if len(sys.argv) > 1 else 32  # 32x16 = 512
    topo = Torus(4, 2)
    assert topo.n_neighbors == 4 and abs(topo.mix_weight - 0.2) < 1e-9
    rec = run_point("cifar", 1.0, warmup=30, epochs=epochs,
                    dpsgd_leg=True, trail_every=4, topo=topo)
    rec = {
        "topology": "torus:4x2", "n_neighbor_exchanges": 4,
        "n_distinct_peers": 3, "mix_weight": 0.2, **rec,
    }
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    os.makedirs(os.path.join(repo, "artifacts"), exist_ok=True)
    with open(os.path.join(repo, "artifacts", "torus_savings_r2_cpu.json"), "w") as f:
        json.dump(rec, f, indent=1)


if __name__ == "__main__":
    main()
