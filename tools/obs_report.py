"""Run-report generator: telemetry JSONL/history in, one report JSON out.

Renders the derived observability series (obs.schema.REPORT_FIELDS) from
any metrics stream a `train(obs=...)` run wrote — the `--log-file` JSONL
of cli.py, or a history list saved by a tool:

  * per-layer msgs-saved-% vs epoch (the headline metric, finally
    attributable: WHICH layers save the messages);
  * threshold / fire-rate heatmap data (when do thresholds go quiet);
  * compact-wire capacity utilization — fired bytes vs the static C,
    deferral rate (is the budget actually used);
  * consensus-error trajectory (quiet-by-threshold vs drifting-apart).

The committed example artifact (artifacts/obs_report_cpu.json) comes from
a 4-rank CPU EventGraD + compact-wire run:

  python -m eventgrad_tpu.cli --algo eventgrad --mesh ring:4 \
      --dataset synthetic --model cnn2 --epochs 8 --batch-size 16 \
      --n-synth 2048 --warmup-passes 5 --max-silence 40 \
      --gossip-wire compact --obs block --log-file /tmp/obs_hist.jsonl
  python tools/obs_report.py /tmp/obs_hist.jsonl \
      --out artifacts/obs_report_cpu.json

With --trace TRACE.json (a Chrome-trace span export — cli.py
`--obs-dir`/trace.json or bench `EG_BENCH_OBS_TRACE`), the report also
renders the HOST-BUBBLE decomposition (obs.bubble): wall = steps +
flush + eval + checkpoint + data + other, the dispatch-pipeline metric
of docs/ARCHITECTURE.md "The dispatch pipeline" — one `bubble` section
per train() window in the trace. A truncated or partial trace (a run
killed mid-flight, a raw event list, missing span types) degrades to a
NAMED warning and a partial decomposition instead of a crash — the
report of a dead run is exactly when you need this tool.

With --ledger LEDGER.json (a tools/perf_ledger.py artifact), the
cross-round perf trajectory (step_ms / MFU / roofline per round, with
the regression-gate verdicts) renders after the run report.

Usage: python tools/obs_report.py HISTORY.jsonl [--trace TRACE.json]
                                  [--ledger LEDGER.json]
                                  [--out PATH] [--quiet]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from eventgrad_tpu.obs.report import (  # noqa: E402
    build_report, load_history_jsonl, render_text,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("history", help="metrics JSONL (cli.py --log-file)")
    ap.add_argument("--trace", default=None, metavar="TRACE.json",
                    help="span-trace export (Chrome-trace JSON): adds "
                         "the host-bubble decomposition (obs.bubble)")
    ap.add_argument("--ledger", default=None, metavar="LEDGER.json",
                    help="perf-ledger artifact (tools/perf_ledger.py): "
                         "renders the cross-round trajectory + gates")
    ap.add_argument("--out", default=None, help="report JSON path")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the text summary on stdout")
    args = ap.parse_args(argv)

    history = load_history_jsonl(args.history)
    if not history:
        print(f"no epoch records in {args.history}", file=sys.stderr)
        return 1
    report = build_report(history)
    bubbles = []
    if args.trace:
        import warnings

        from eventgrad_tpu.obs import bubble as obs_bubble
        from eventgrad_tpu.obs.bubble import IncompleteTraceWarning

        events = None
        try:
            with open(args.trace) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            warnings.warn(
                f"trace {args.trace} unreadable ({e}); skipping the "
                "bubble decomposition",
                IncompleteTraceWarning, stacklevel=1,
            )
            data = None
        if isinstance(data, dict):
            events = data.get("traceEvents")
        elif isinstance(data, list):  # a raw event list still decomposes
            events = data
        if not isinstance(events, list) or not events:
            if data is not None:
                warnings.warn(
                    f"trace {args.trace} carries no traceEvents; "
                    "skipping the bubble decomposition",
                    IncompleteTraceWarning, stacklevel=1,
                )
        else:
            windows = obs_bubble.train_windows(events) or [events]
            bubbles = [obs_bubble.decompose(w) for w in windows]
            report["bubble"] = bubbles
            report["bubble_source"] = os.path.basename(args.trace)
    ledger = None
    if args.ledger:
        with open(args.ledger) as f:
            ledger = json.load(f)
        report["perf_ledger"] = {
            "source": os.path.basename(args.ledger),
            "n_rounds": ledger.get("n_rounds"),
            "gates_all_ok": ledger.get("gates_all_ok"),
        }
    report["source"] = os.path.basename(args.history)
    report["generated_at"] = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
    )
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
        print(f"wrote {args.out}", file=sys.stderr)
    if not args.quiet:
        print(render_text(report))
        if bubbles:
            from eventgrad_tpu.obs import bubble as obs_bubble

            for i, d in enumerate(bubbles):
                print(obs_bubble.render_text(d, label=f"train window {i}"))
        if ledger is not None:
            from tools import perf_ledger as perf_ledger_mod

            print(perf_ledger_mod.render_text(ledger))
    return 0


if __name__ == "__main__":
    sys.exit(main())
