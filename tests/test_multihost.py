"""Multi-host (multi-process) runtime: the same per-rank programs span
process boundaries via JAX's global mesh, with cross-process collectives
(Gloo on CPU standing in for DCN). Two 4-device processes form one
8-rank ring; EventGraD training there must match the single-process
simulation exactly."""

import os
import socket
import subprocess
import sys


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_training_matches_single_process(tmp_path):
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "mh_worker.py")
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), "2", str(port), str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=os.path.dirname(os.path.dirname(worker)),
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert f"MH-WORKER-{pid}-OK" in out
