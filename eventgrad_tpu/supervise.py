"""Elastic supervisor: failure detection + automatic restart-from-snapshot.

The reference has no failure story at all — a dead rank leaves its ring
neighbors blocked in MPI_Recv forever (/root/reference/dmnist/decent/
decent.cpp:200-205) and an MPI RMA window silently freezes. Here the
training job runs under a supervisor that detects both failure modes:

  * **crash** — the child exits nonzero;
  * **hang** — the child stays alive but its heartbeat (the metrics
    log / checkpoint dir) stops advancing for `--timeout` seconds, the
    moral equivalent of a wedged collective.

Either way the child is killed and relaunched with `--resume`, restoring
the full gossip TrainState (params, optimizer moments, event thresholds,
stale neighbor buffers) from the latest orbax snapshot — so recovery costs
at most one `--save-every` interval of recomputation. Pair with the train
loop's `fault_inject` ("crash:N" / "hang:N") for end-to-end drills, and
with `--membership` schedules for elastic soak runs (tools/soak.py).

Built for LONG-RUNNING soaks, not just drills:

  * **sliding restart-budget window** (`--max-restarts N
    --restart-window SEC`) — give up only when more than N restarts land
    within any trailing SEC-second window, so a service that fails once
    a day is not killed by a lifetime counter after N days
    (`--restart-window 0` keeps the legacy lifetime budget);
  * **exponential backoff with jitter** between relaunches
    (`--backoff-base/--backoff-max/--backoff-jitter`) — a crash-looping
    child does not hammer the machine (or its checkpoint store), and the
    jitter decorrelates a fleet of supervisors restarting together.

Usage:
    python -m eventgrad_tpu.supervise --timeout 120 \
        --max-restarts 3 --restart-window 3600 -- \
        --algo eventgrad --mesh ring:8 --dataset cifar10 --model resnet18 \
        --checkpoint-dir /ckpt --save-every 1 --log-file /logs/run.jsonl
"""

from __future__ import annotations

import argparse
import os
import random
import subprocess
import sys
import time
from typing import Callable, List, Optional, Sequence


# the process exit-code contract lives in ONE import-bare module
# (eventgrad_tpu/exitcodes.py) shared with the children that pick the
# codes — the value-pinning re-declaration this file used to carry is
# gone. Honest caveat: importing it through the package runs
# eventgrad_tpu/__init__ (which pulls jax) — exactly what every real
# invocation (`python -m eventgrad_tpu.supervise`) already paid before
# this import existed, so the supervisor's import cost is unchanged;
# only a copied-out supervise.py on a jax-less host would notice.
# INTEGRITY_ABORT_EXIT (sentinel tripped beyond the rollback budget:
# give up, a relaunch would replay the same divergence) and
# PREEMPTED_EXIT (graceful drain: relaunch immediately, charge nothing)
# stay pinned by tests/test_supervise.py.
from eventgrad_tpu.exitcodes import INTEGRITY_ABORT_EXIT, PREEMPTED_EXIT


class RestartBudget:
    """Sliding-window restart budget: allow at most `max_restarts`
    restarts within any trailing `window_s` seconds. `window_s=0` means
    a lifetime budget (the legacy `--max-restarts` counter). `now` is
    injectable for tests."""

    def __init__(
        self, max_restarts: int, window_s: float = 0.0,
        now: Callable[[], float] = time.time,
    ):
        if max_restarts < 0 or window_s < 0:
            raise ValueError(
                f"budget must be >= 0 (got {max_restarts}, {window_s})"
            )
        self.max_restarts = max_restarts
        self.window_s = window_s
        self._now = now
        self._fails: List[float] = []

    def record_failure(self) -> bool:
        """Register one failure; True = a restart is still within budget.
        With a window, failures older than `window_s` roll off first."""
        t = self._now()
        if self.window_s:
            self._fails = [f for f in self._fails if t - f < self.window_s]
        self._fails.append(t)
        return len(self._fails) <= self.max_restarts


def backoff_delay(
    consecutive_failures: int,
    base: float = 1.0,
    cap: float = 30.0,
    jitter: float = 0.5,
    rng: Optional[random.Random] = None,
) -> float:
    """Exponential backoff with jitter: `min(cap, base * 2^(k-1))` for
    the k-th consecutive failure, scaled by `1 + jitter * U[0, 1)`.
    `base=0` disables backoff entirely."""
    if base <= 0 or consecutive_failures <= 0:
        return 0.0
    d = min(cap, base * (2.0 ** (consecutive_failures - 1)))
    if jitter:
        d *= 1.0 + jitter * (rng or random).random()
    return d


def _latest_mtime(path: str) -> float:
    """Newest mtime under `path` (file, or dir scanned recursively)."""
    if not os.path.exists(path):
        return 0.0
    newest = os.path.getmtime(path)
    if os.path.isdir(path):
        for root, _, files in os.walk(path):
            for f in files:
                try:
                    newest = max(newest, os.path.getmtime(os.path.join(root, f)))
                except OSError:
                    pass  # snapshot promotion may race the walk
    return newest


def _flag_value(args: Sequence[str], flag: str) -> Optional[str]:
    for i, a in enumerate(args):
        if a == flag and i + 1 < len(args):
            return args[i + 1]
        if a.startswith(flag + "="):
            return a.split("=", 1)[1]
    return None


def _terminate(proc: subprocess.Popen, grace: float = 10.0) -> None:
    proc.terminate()
    try:
        proc.wait(timeout=grace)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()


def supervise(
    child_args: List[str],
    timeout: float = 0.0,
    max_restarts: int = 3,
    heartbeat: Optional[str] = None,
    poll_s: float = 0.5,
    restart_window: float = 0.0,
    backoff_base: float = 1.0,
    backoff_max: float = 30.0,
    backoff_jitter: float = 0.5,
    _now: Callable[[], float] = time.time,
    _sleep: Callable[[float], None] = time.sleep,
) -> int:
    """Run the CLI under supervision; returns the final exit code (0 on
    eventual success). `child_args` are eventgrad_tpu.cli flags and must
    include --checkpoint-dir (restarts would lose all progress otherwise).

    `restart_window` makes the budget sliding (`RestartBudget`): more
    than `max_restarts` failures within any trailing window escalate;
    0 keeps the lifetime counter. Between relaunches the supervisor
    sleeps `backoff_delay(consecutive failures in the window)` —
    exponential with jitter, capped; `backoff_base=0` disables. `_now`/
    `_sleep` are injectable for tests (backoff only — the liveness poll
    keeps real time)."""
    ckpt_dir = _flag_value(child_args, "--checkpoint-dir")
    if not ckpt_dir:
        raise SystemExit("supervise: child args must include --checkpoint-dir")
    heartbeat = heartbeat or _flag_value(child_args, "--log-file") or ckpt_dir

    budget = RestartBudget(max_restarts, restart_window, now=_now)
    attempt = 0
    # backoff exponent: CONSECUTIVE failures — a child that ran healthily
    # past every backoff scale resets it, so a service failing once a day
    # keeps restarting fast even under the lifetime (window=0) budget
    consecutive = 0
    backoff_reset_s = max(backoff_max, 60.0)
    while True:
        t_launch = _now()
        argv = list(child_args)
        if attempt > 0 and "--resume" not in argv:
            argv.append("--resume")
        cmd = [sys.executable, "-m", "eventgrad_tpu.cli", *argv]
        started = time.time()
        proc = subprocess.Popen(cmd)
        reason = None
        # stat the heartbeat at a fraction of the timeout, not every poll —
        # a checkpoint-dir heartbeat on shared storage shouldn't see a
        # metadata storm from its own supervisor
        hb_every = max(poll_s, timeout / 4.0) if timeout else poll_s
        last_hb_check, last_hb = 0.0, 0.0
        while proc.poll() is None:
            time.sleep(poll_s)
            if not timeout:
                continue
            now = time.time()
            if now - last_hb_check >= hb_every:
                last_hb_check = now
                last_hb = _latest_mtime(heartbeat)
            if now - max(started, last_hb) > timeout:
                # the cached mtime may be up to hb_every stale — re-stat
                # before declaring a live child hung
                last_hb_check = now
                last_hb = _latest_mtime(heartbeat)
                if now - max(started, last_hb) <= timeout:
                    continue
                reason = f"no heartbeat on {heartbeat} for {timeout:.0f}s"
                _terminate(proc)
                break
        rc = proc.returncode
        if rc == 0:
            return 0
        if rc == PREEMPTED_EXIT and reason is None:
            # graceful preemption (chaos/crashpoint.py): the child
            # drained its pipeline, snapshotted at a block boundary, and
            # exited ON PURPOSE — the dominant healthy exit on spot/
            # preemptible capacity. Relaunch immediately: no restart-
            # budget charge (a once-an-hour preemption must never
            # exhaust a crash budget) and no backoff (at most one
            # dispatch block of work is waiting on the relaunch).
            # `reason is None` guards the hang path: a heartbeat-stalled
            # child that drains to 75 under the supervisor's OWN SIGTERM
            # was still a hang — it keeps charging the budget, or a
            # stall-loop would relaunch forever.
            attempt += 1
            consecutive = 0
            print(
                f"supervise: child preempted (exit {rc}); relaunching "
                "immediately from its drained snapshot (no budget "
                "charge, no backoff)",
                file=sys.stderr, flush=True,
            )
            continue
        if rc == INTEGRITY_ABORT_EXIT:
            # permanent escalation from the integrity engine: restarting
            # would restore the same last-known-good snapshot and replay
            # the same divergence — human (or policy) attention required
            print(
                f"supervise: child exited {rc} (integrity escalation); "
                "giving up without restart — a relaunch would replay "
                "the same divergence",
                file=sys.stderr, flush=True,
            )
            return rc
        attempt += 1
        if _now() - t_launch >= backoff_reset_s:
            consecutive = 0
        consecutive += 1
        allowed = budget.record_failure()
        desc = reason or f"exit code {rc}"
        print(
            f"supervise: attempt {attempt} failed ({desc}); "
            + ("restarting from latest snapshot" if allowed
               else "giving up"),
            file=sys.stderr, flush=True,
        )
        if not allowed:
            if rc is None:
                return 1
            # signal deaths (rc < 0) would wrap around in sys.exit; report
            # them the shell way
            return 128 + abs(rc) if rc < 0 else rc
        delay = backoff_delay(
            consecutive, base=backoff_base, cap=backoff_max,
            jitter=backoff_jitter,
        )
        if delay:
            print(
                f"supervise: backing off {delay:.1f}s before relaunch",
                file=sys.stderr, flush=True,
            )
            _sleep(delay)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="eventgrad-tpu-supervise", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--timeout", type=float, default=0.0,
                   help="seconds without heartbeat progress before the child "
                        "is declared hung and killed (0 = crash detection only)")
    p.add_argument("--max-restarts", type=int, default=3)
    p.add_argument("--restart-window", type=float, default=0.0,
                   metavar="SEC",
                   help="sliding budget window: give up only when more "
                        "than --max-restarts failures land within any "
                        "trailing SEC seconds (0 = lifetime counter, the "
                        "legacy behavior)")
    p.add_argument("--backoff-base", type=float, default=1.0,
                   help="first-relaunch delay in seconds; doubles per "
                        "consecutive failure in the window (0 = no "
                        "backoff)")
    p.add_argument("--backoff-max", type=float, default=30.0,
                   help="backoff delay cap in seconds")
    p.add_argument("--backoff-jitter", type=float, default=0.5,
                   help="multiplicative jitter J: delays scale by "
                        "1 + J*U[0,1) to decorrelate fleet restarts")
    p.add_argument("--heartbeat", default=None,
                   help="file/dir whose mtime is the liveness signal "
                        "(default: the child's --log-file, else its "
                        "--checkpoint-dir)")
    p.add_argument("child", nargs=argparse.REMAINDER,
                   help="-- followed by eventgrad_tpu.cli flags")
    args = p.parse_args(argv)
    child = args.child
    if child and child[0] == "--":
        child = child[1:]
    if not child:
        raise SystemExit("supervise: pass CLI flags after --")
    return supervise(
        child, timeout=args.timeout, max_restarts=args.max_restarts,
        heartbeat=args.heartbeat, restart_window=args.restart_window,
        backoff_base=args.backoff_base, backoff_max=args.backoff_max,
        backoff_jitter=args.backoff_jitter,
    )


if __name__ == "__main__":
    sys.exit(main())
