"""Run the trace auditor + project lint and commit the audit artifact.

Walks the full configuration matrix of the fused train step
(eventgrad_tpu/analysis/audit.py: dpsgd/eventgrad/sp_eventgrad x
masked|compact x arena on/off x obs/chaos/integrity on/off x wire
dtypes x the bucketed gossip schedule at K=4 x carrier-resident
receive buffers (EventState.bufs held in the wire dtype) — ON THE
PRODUCTION
GEOMETRIES: LeNetCifar and ResNet18 (conv rank-major merges tracked as
blocked layouts), a small transformer full+flash (Pallas kernels via
the declared-kernel registry, analysis/kernels.py), alongside the MLP
regression base), proving per cell: rank isolation (the only
cross-rank flow is the declared neighbor exchange), wire-byte truth
(jaxpr-derived bytes == accounting formula == the executed step's
`sent_bytes_wire_real`, exactly in the metric's f32 carrier — summed
over buckets on the bucketed cells, whose offsets must carry K
declared lane groups), and step hygiene (no host callbacks, ravel
budget, wire dtype fidelity, donation aliasing).  Then fires every
seeded ORACLE violation to prove each check can detect its failure
class (including a conv rank-merge without group confinement, an
unregistered pallas kernel, and a data-dependent cross-rank attention
gather), and runs the AST lint rules (analysis/lint.py) over the repo.

Usage:
    JAX_PLATFORMS=cpu python tools/audit.py [--out artifacts/audit_cpu.json]
    JAX_PLATFORMS=cpu python tools/audit.py --census  # primitive inventory

Exit 0 = every cell clean, every oracle detected, zero lint
violations; 1 otherwise.  The committed artifacts/audit_cpu.json is
schema-gated (AUDIT_SCHEMA in tools/validate_artifacts.py via
tests/test_artifacts.py), so a regression in any invariant fails
tier-1 twice: once in tests/test_audit.py, once at the artifact gate
when the refreshed artifact stops matching.  See docs/ANALYSIS.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

if jax.default_backend() != "cpu":
    jax.config.update("jax_platforms", "cpu")

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--out", default=os.path.join(_ROOT, "artifacts", "audit_cpu.json")
    )
    ap.add_argument(
        "--census", action="store_true",
        help="print the primitive inventory of each config and exit",
    )
    args = ap.parse_args(argv)

    from eventgrad_tpu.analysis import audit, lint, walker
    from eventgrad_tpu.parallel.spmd import spmd

    if args.census:
        for cfg in audit.CONFIGS:
            state, step, topo = audit.build(cfg)
            closed = jax.make_jaxpr(spmd(step, topo))(state, audit._batch(cfg))
            print(cfg.name, json.dumps(
                walker.primitive_census(closed.jaxpr), sort_keys=True
            ))
        return 0

    t0 = time.perf_counter()
    configs = audit.audit_matrix(run_metric=True)
    oracles = audit.run_oracles()
    lint_violations = lint.run(root=_ROOT)
    for v in lint_violations:
        print(f"LINT {v}", file=sys.stderr)

    n_clean = sum(1 for r in configs if audit.clean(r))
    n_detected = sum(1 for o in oracles if o["detected"])
    record = {
        "bench": "audit",
        "platform": jax.default_backend(),
        "op_point": (
            f"Ring({audit.N_RANKS}) geometries "
            + "+".join(sorted({c.model for c in audit.CONFIGS}))
            + f" mlp_capacity={audit.CAPACITY}"
        ),
        "n_configs": len(configs),
        "n_clean": n_clean,
        "models": sorted({r["model"] for r in configs}),
        "configs": [
            {k: v for k, v in r.items() if k != "violation_details"}
            | {"clean": audit.clean(r)}
            for r in configs
        ],
        "n_oracles": len(oracles),
        "n_detected": n_detected,
        "oracles": oracles,
        "lint_rules": len(lint.RULES),
        "lint_violations": len(lint_violations),
        "wall_s": round(time.perf_counter() - t0, 2),
    }
    ok = (
        n_clean == len(configs)
        and n_detected == len(oracles)
        and not lint_violations
    )
    with open(args.out, "w") as f:
        json.dump(record, f, indent=1, sort_keys=True)
        f.write("\n")
    for r in configs:
        mark = "CLEAN" if audit.clean(r) else "DIRTY"
        print(
            f"{mark} {r['name']}: violations={r['violations']} "
            f"wire={r['wire_bytes_per_neighbor_derived']:.0f}B/nb "
            f"(formula {r['wire_bytes_per_neighbor_formula']:.0f}, "
            f"metric match {r['metric_match']}) "
            f"ravel {r['ravel_count']}/{r['ravel_budget']} "
            f"callbacks={r['callbacks']}"
        )
    for o in oracles:
        mark = "DETECTED" if o["detected"] else "MISSED"
        print(f"{mark} oracle {o['name']}: {o['reason']}")
    print(
        f"audit: {n_clean}/{len(configs)} configs clean, "
        f"{n_detected}/{len(oracles)} oracles detected, "
        f"{len(lint_violations)} lint violations, "
        f"{record['wall_s']}s -> {args.out}"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
