"""Real pixels for the CIFAR path (round-3 verdict item 6).

The E4/E5 CIFAR evidence so far is synthetic class-prototypes that
saturate at 99-100% accuracy, making "gap 0.0" weak evidence; the real
CIFAR-10 bytes (raw-JPEG mirror, dcifar10/common/custom.hpp:26-122) are
unreachable in a zero-egress image. This runs the CIFAR *pipeline* —
3-channel inputs, pad4/flip/crop augmentation, BatchNorm with rank-local
(never-gossiped) statistics, momentum SGD at the reference's lr — on the
one real image corpus available offline: scikit-learn's UCI digit scans
upsampled to the 32x32x3 CIFAR geometry (data/datasets.py::load_digits
geometry="cifar32"). Not CIFAR images, but real pixels with real
intra-class variation at CIFAR shapes, on a task hard enough not to
saturate.

Per model (LeNetCifar = the reference's M5; a small BatchNorm ResNet of
the same block structure as M4), four twins at the same op-point:

  refpure     EventGraD, neutral horizon, no guard (the paper's trigger)
  stabilized  EventGraD, horizon 1.05 + max-silence 50 (bench trigger)
  spevent     sparsified EventGraD, top-k 10% (E5, ResNet leg skipped —
              the sparse scatter micro-path is shape-agnostic)
  dpsgd       the dense baseline the gaps are measured against

Note: horizontal flip is label-preserving for CIFAR objects but not for
digits; both twins share the handicap, so the eventgrad-vs-dpsgd GAP —
the quantity under test — is unaffected.

Writes artifacts/realdata_cifar_r4_cpu.json.
Usage: python tools/realdata_cifar.py [epochs]
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax


def main() -> None:
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from eventgrad_tpu.data.datasets import load_digits
    from eventgrad_tpu.models import LeNetCifar
    from eventgrad_tpu.models.resnet import BasicBlock, ResNet
    from eventgrad_tpu.parallel.events import EventConfig
    from eventgrad_tpu.parallel.sparsify import SparseConfig
    from eventgrad_tpu.parallel.topology import Ring
    from eventgrad_tpu.train.loop import consensus_params, evaluate, rank0_slice, train

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    epochs = int(sys.argv[1]) if len(sys.argv) > 1 else 60
    x, y = load_digits("train", geometry="cifar32")
    xt, yt = load_digits("test", geometry="cifar32")
    topo = Ring(8)
    batch = 20  # 1440 / (20 x 8) = 9 steps per epoch
    x, y = jnp.asarray(x), jnp.asarray(y)

    def tiny_resnet():
        # M4's exact block structure (incl. the extra_block off-by-one and
        # rank-local BatchNorm) at a 1-core-trainable width
        return ResNet(
            stage_sizes=(1, 1), block_cls=BasicBlock, num_filters=8
        )

    # the reference CIFAR op-point: momentum SGD 0.9, lr 1e-2, pad/flip/
    # crop augmentation (dcifar10/event/event.cpp:94-98,196-200)
    common = dict(
        epochs=epochs, batch_size=batch, learning_rate=1e-2, momentum=0.9,
        augment=True, random_sampler=True, log_every_epoch=False,
    )
    refpure = EventConfig(adaptive=True, horizon=1.0, warmup_passes=30)
    stabilized = EventConfig(
        adaptive=True, horizon=1.05, warmup_passes=30, max_silence=50
    )

    out = {
        "dataset": "sklearn-digits at CIFAR geometry (real scans, 32x32x3)",
        "n_train": int(x.shape[0]), "n_test": int(xt.shape[0]),
        "n_ranks": topo.n_ranks, "batch_per_rank": batch,
        "epochs": epochs,
        "passes": epochs * (int(x.shape[0]) // (batch * topo.n_ranks)),
        "augment": True, "lr": 1e-2, "momentum": 0.9,
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }

    # the BN ResNet costs ~8-9 s/pass on one core (measured at the 2-epoch
    # validation) vs ~1 s for LeNet — its legs run a shorter schedule; the
    # artifact's value is the non-saturated twin GAP, not absolute accuracy
    resnet_epochs = max(2, epochs // 5)
    for model_tag, make_model, model_epochs in (
        ("lenetcifar", LeNetCifar, epochs),
        ("tinyresnet_bn", tiny_resnet, resnet_epochs),
    ):
        legs = [
            ("refpure", "eventgrad", refpure, None),
            ("stabilized", "eventgrad", stabilized, None),
            ("dpsgd", "dpsgd", None, None),
        ]
        if model_tag == "lenetcifar":
            legs.insert(2, ("spevent", "sp_eventgrad", refpure,
                            SparseConfig(10.0)))
        sec = {"epochs": model_epochs,
               "passes": model_epochs * (int(x.shape[0]) // (batch * topo.n_ranks))}
        for tag, algo, cfg, scfg in legs:
            kw = dict(common, epochs=model_epochs)
            if cfg is not None:
                kw["event_cfg"] = cfg
            if scfg is not None:
                kw["sparse_cfg"] = scfg
            t0 = time.perf_counter()
            state, hist = train(make_model(), topo, x, y, algo=algo, **kw)
            cons = consensus_params(state.params)
            # rank-0 local BN statistics evaluate the consensus model —
            # the reference's never-synced-buffers semantics (E4)
            stats0 = rank0_slice(state.batch_stats)
            acc = evaluate(make_model(), cons, stats0, xt, yt)["accuracy"]
            sec[f"test_acc_{tag}"] = round(acc, 2)
            sec[f"final_loss_{tag}"] = round(hist[-1]["loss"], 4)
            sec[f"wall_s_{tag}"] = round(time.perf_counter() - t0, 1)
            if algo != "dpsgd":
                sec[f"msgs_saved_pct_{tag}"] = round(
                    hist[-1]["msgs_saved_pct"], 2
                )
                sec[f"sent_bytes_per_step_{tag}"] = round(
                    hist[-1]["sent_bytes_per_step_per_chip"], 1
                )
            print(model_tag, tag, sec.get(f"msgs_saved_pct_{tag}"),
                  round(acc, 2), flush=True)
        for tag in ("refpure", "stabilized", "spevent"):
            if f"test_acc_{tag}" in sec:
                sec[f"acc_gap_{tag}"] = round(
                    sec[f"test_acc_{tag}"] - sec["test_acc_dpsgd"], 2
                )
        out[model_tag] = sec

    path = os.path.join(repo, "artifacts", "realdata_cifar_r4_cpu.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
