"""Pure-logic tests for the bench supervisor's two-phase policy.

bench.py is import-safe (main/_supervised run only under __main__); these
exercise the decision functions the live two-phase validation runs
(artifacts/bench_default_twophase_r4_cpu.log) depend on.
"""

import json

import bench


def test_last_metric_line_takes_last_parseable():
    first = json.dumps({"metric": "m", "value": 1.0})
    second = json.dumps({"metric": "m", "value": 2.0})
    out = "\n".join([
        "stderr-ish noise", first, "not json {", second, "trailing noise",
    ])
    line, rec = bench._last_metric_line(out)
    assert rec["value"] == 2.0 and json.loads(line) == rec
    # records without "metric" are skipped; none at all -> (None, None)
    assert bench._last_metric_line(json.dumps({"value": 3}))[1] is None
    assert bench._last_metric_line("") == (None, None)
    assert bench._last_metric_line(None) == (None, None)


def test_upgrade_wins_policy():
    floor = {"vs_baseline": 1.0769, "mnist_vs_baseline": 0.8774}
    top = {"vs_baseline": 1.1219, "mnist_vs_baseline": 1.0156,
           "platform": "cpu"}
    assert bench._upgrade_wins(floor, top)
    # never downgrade, never tie-break on CPU
    assert not bench._upgrade_wins(top, floor)
    assert not bench._upgrade_wins(floor, dict(floor, platform="cpu"))
    # a collapsed run can never supersede (the cliff guard extends here)
    assert not bench._upgrade_wins(
        floor, dict(top, collapsed=True)
    )
    # chip-captured evidence supersedes at an equal score
    assert bench._upgrade_wins(floor, dict(floor, platform="tpu"))
    assert not bench._upgrade_wins(
        top, dict(floor, platform="tpu")  # ...but not at a worse one
    )
    # malformed second record is rejected, missing ratios default to 0
    assert not bench._upgrade_wins(floor, None)
    assert not bench._upgrade_wins(floor, {"metric": "m"})


def test_upgrade_eligibility_gate():
    """An un-downshifted chip line ends the ladder; a downshifted one
    stays eligible so the remaining budget can fund a longer full-tier
    run (round-4: the first live window lands short attempts first)."""
    import bench

    cpu_line = {"platform": "cpu", "downshifted": False}
    assert bench._upgrade_eligible(cpu_line, {})
    assert not bench._upgrade_eligible(
        {"platform": "tpu", "downshifted": False}, {}
    )
    assert bench._upgrade_eligible(
        {"platform": "tpu", "downshifted": True}, {}
    )
    assert not bench._upgrade_eligible(cpu_line, {"EG_BENCH_UPGRADE": "0"})
    assert not bench._upgrade_eligible(cpu_line, {"EG_BENCH_TIER": "tiny"})
    assert not bench._upgrade_eligible(cpu_line, {"EG_BENCH_TINY": "1"})
    assert bench._upgrade_eligible(cpu_line, {"EG_BENCH_TIER": "reduced"})


def test_chip_line_never_superseded_by_cpu():
    """_upgrade_wins: higher CPU ladder ratios must not discard a
    chip-captured record's platform/step_ms/MFU evidence."""
    import bench

    tpu_line = {"platform": "tpu", "vs_baseline": 1.0,
                "mnist_vs_baseline": 1.0}
    cpu_better = {"platform": "cpu", "vs_baseline": 1.2,
                  "mnist_vs_baseline": 1.2}
    assert not bench._upgrade_wins(tpu_line, cpu_better)
    tpu_better = dict(cpu_better, platform="tpu")
    assert bench._upgrade_wins(tpu_line, tpu_better)
