"""Message-lifecycle ledger: per-edge disposition accounting + auditor.

EventGraD's value claim is an accounting claim ("~70% of messages saved
at no accuracy cost"), but the counters that tell each message's fate
grew up in five different subsystems: capacity deferrals in
`EventState.num_deferred`, chaos drops, integrity wire rejections,
bounded-async late commits, membership forced fires. Nothing proved
them mutually consistent — a path that silently leaks messages (a drop
nobody counts, a rejection counted twice) was invisible.

This module is the one place message counters move. `MessageLedger`
rides inside `TelemetryState` (cumulative int32 per-edge counters, one
row per `schema.DISPOSITIONS` leaf) and **every** message-affecting
path — the event branches of train/steps.py, the chaos delivery mask,
the integrity verdicts, the bounded-async delivery queue — feeds one
call to `ledger_update` per pass. The helper derives each disposition
from the branch's raw observables (proposal bits, suppress mask, fire
bits, raw wire census, deliver/integrity verdicts, lag), so no ad-hoc
counter math lives in the step, and the derivation makes the balance
laws hold by construction:

    proposed = suppressed + deferred + fired          (per rank, edge)
    fired    = delivered + dropped + rejected + in_flight
                                           (per edge, summed over ranks)
    sender.fired(e) = receiver.(delivered + dropped + rejected +
                     in_flight)(e)                    (per rank, edge)

`audit_window` re-checks those laws on the host with INTEGER equality,
per edge per flush window — tools/ledger_audit.py proves the auditor
catches seeded leaks (an uncounted drop, a double-counted rejection,
enabled via EG_LEDGER_LEAK for the oracle runs only).

Message unit: one leaf-fire per edge (matching `EventState.num_events`
= fires x neighbors). Sender-side rows broadcast the same count to all
edges; receiver-side rows attribute the neighbor's raw wire bits to
exactly one of delivered / dropped / rejected / in-flight.
"""

from __future__ import annotations

import os
from typing import Optional

import jax.numpy as jnp
from flax import struct

from eventgrad_tpu.obs.schema import LEDGER_COUNTER_ROWS

#: row index of each cumulative disposition counter in MessageLedger.counts
ROW = {name: i for i, name in enumerate(LEDGER_COUNTER_ROWS)}

#: seeded leak oracles (tools/ledger_audit.py): read at TRACE time, so a
#: leaky step only exists in processes that ask for one. The two leaks
#: are the two classic counter bugs the auditor must catch — a message
#: fate nobody counts, and one counted twice.
LEAK_ENV = "EG_LEDGER_LEAK"
LEAKS = ("uncounted_drop", "double_reject")


def _leak() -> str:
    v = os.environ.get(LEAK_ENV, "")
    if v and v not in LEAKS:
        raise ValueError(f"{LEAK_ENV}={v!r}: known leaks are {LEAKS}")
    return v


class MessageLedger(struct.PyTreeNode):
    """Cumulative per-edge disposition counters + the bounded-async
    in-flight queue (schema.DISPOSITIONS / LEDGER_COUNTER_ROWS).

    `queue[s, e]` counts accepted messages on edge e committing in s+1
    passes (the count twin of `EventState.pending`: slot 0 drains into
    `delivered` this pass, then the queue shifts and this pass's
    accepted census enters at slot lag-1 — additive where the payload
    queue merges, because committing a merged slot is bitwise
    committing every message in it). `late_queue` carries the lag >= 2
    sub-census the same way; its drain is `late_committed`. Both are
    None on the synchronous paths (staleness <= 1), where acceptance
    commits the same pass and in_flight is identically zero."""

    counts: jnp.ndarray                 # i32 [n_rows, n_edges]
    queue: jnp.ndarray = None           # type: ignore[assignment]  # i32 [D, n_edges]
    late_queue: jnp.ndarray = None      # type: ignore[assignment]  # i32 [D, n_edges]

    @classmethod
    def init(cls, n_edges: int, queue_depth: int = 0) -> "MessageLedger":
        q = (
            jnp.zeros((queue_depth, n_edges), jnp.int32)
            if queue_depth else None
        )
        return cls(
            counts=jnp.zeros((len(LEDGER_COUNTER_ROWS), n_edges), jnp.int32),
            queue=q,
            late_queue=q,
        )

    def in_flight(self) -> jnp.ndarray:
        """Gauge: queued-but-uncommitted messages per edge, i32 [n_edges]."""
        if self.queue is None:
            return jnp.zeros(self.counts.shape[-1:], jnp.int32)
        return jnp.sum(self.queue, axis=0)


def ledger_update(
    led: MessageLedger,
    *,
    prop_fire: Optional[jnp.ndarray] = None,   # bool [L] trigger proposals
    suppress: Optional[jnp.ndarray] = None,    # bool [L] quarantine/policy veto
    fire_vec: Optional[jnp.ndarray] = None,    # bool [L] on-the-wire fires
    n_msgs: Optional[jnp.ndarray] = None,      # i32 [E] raw wire census
    deliver: Optional[jnp.ndarray] = None,     # bool [E] chaos delivery bits
    oks: Optional[jnp.ndarray] = None,         # bool [E] integrity verdicts
    lag_vec: Optional[jnp.ndarray] = None,     # i32 [E] bounded-async lags
) -> MessageLedger:
    """THE disposition helper: one pass of message accounting.

    Every message-affecting path calls this once per pass with its raw
    observables; the disposition derivation lives here and nowhere else
    (the `telemetry-counter-ledgered` lint rule keeps it that way).

    Sender side (`prop_fire`/`suppress`/`fire_vec`, broadcast per edge):
    suppressed counts proposals the mask vetoed, deferred counts
    proposals that survived the mask but missed the wire (the capacity
    gate), fired counts what actually shipped — computed independently,
    so the proposed = suppressed + deferred + fired law checks the
    mask-subset invariants instead of restating an identity.

    Receiver side (`n_msgs` = per-edge sum of the neighbor's RAW fire
    bits on the wire): a dropped edge loses its whole census, a
    delivered-but-rejected edge refuses it, the rest commits — same
    pass without `lag_vec`, through the delivery queue with it (the
    count twin of events.async_delivery_commit: drain slot 0, shift,
    enqueue this pass's accepted census at slot lag-1)."""
    leak = _leak()
    counts = led.counts
    queue, late_queue = led.queue, led.late_queue
    n_edges = counts.shape[-1]

    if prop_fire is not None:
        prop = prop_fire.astype(bool)
        fire = fire_vec.astype(bool)
        sup_mask = (
            prop & suppress.astype(bool)
            if suppress is not None
            else jnp.zeros_like(prop)
        )
        kept = prop & ~sup_mask
        proposed = jnp.sum(prop.astype(jnp.int32))
        suppressed = jnp.sum(sup_mask.astype(jnp.int32))
        deferred = jnp.sum((kept & ~fire).astype(jnp.int32))
        fired = jnp.sum(fire.astype(jnp.int32))
        for row, inc in (
            ("proposed", proposed), ("suppressed", suppressed),
            ("deferred", deferred), ("fired", fired),
        ):
            counts = counts.at[ROW[row]].add(
                jnp.broadcast_to(inc, (n_edges,))
            )

    if n_msgs is not None:
        msgs = n_msgs.astype(jnp.int32)
        ok_e = (
            oks.astype(bool) if oks is not None
            else jnp.ones((n_edges,), bool)
        )
        del_e = (
            deliver.astype(bool) if deliver is not None
            else jnp.ones((n_edges,), bool)
        )
        dropped = jnp.where(~del_e, msgs, 0)
        rejected = jnp.where(del_e & ~ok_e, msgs, 0)
        accepted = jnp.where(del_e & ok_e, msgs, 0)
        if leak == "uncounted_drop":
            # seeded leak oracle: the drop path forgets its census
            dropped = jnp.zeros_like(dropped)
        if leak == "double_reject":
            # seeded leak oracle: rejections booked twice
            rejected = 2 * rejected
        counts = counts.at[ROW["dropped"]].add(dropped)
        counts = counts.at[ROW["rejected"]].add(rejected)
        if lag_vec is None:
            counts = counts.at[ROW["delivered"]].add(accepted)
        else:
            # bounded async: accepted messages commit when their lag
            # elapses — mirror events.async_delivery_commit exactly
            # (drain slot 0, shift, enqueue at slot lag-1), so the
            # in-flight gauge balances fired against delivered at any
            # block boundary
            lag = jnp.clip(
                lag_vec.astype(jnp.int32), 1, queue.shape[0]
            )
            slot = (
                jnp.arange(queue.shape[0], dtype=jnp.int32)[:, None]
                == (lag - 1)[None, :]
            )
            late_acc = jnp.where(lag >= 2, accepted, 0)
            counts = counts.at[ROW["delivered"]].add(queue[0])
            counts = counts.at[ROW["late_committed"]].add(late_queue[0])
            shift = jnp.zeros_like(queue).at[:-1].set(queue[1:])
            queue = shift + jnp.where(slot, accepted[None, :], 0)
            lshift = jnp.zeros_like(late_queue).at[:-1].set(late_queue[1:])
            late_queue = lshift + jnp.where(slot, late_acc[None, :], 0)

    return led.replace(counts=counts, queue=queue, late_queue=late_queue)


# ---------------------------------------------------------------------------
# host side: the flush-window record block and the conservation auditor


def window_block(cur: MessageLedger, prev=None):
    """Host-side flush twin of obs.device.window_record for the ledger:
    per-disposition per-edge window deltas summed over ranks (stacked
    snapshots, leading axis = ranks), plus the in-flight gauge at the
    window end — the `message_ledger` block of the record's obs dict."""
    import numpy as np

    c = np.asarray(cur.counts, np.int64)
    if prev is not None:
        c = c - np.asarray(prev.counts, np.int64)
    blk = {
        name: [int(v) for v in c[:, ROW[name]].sum(axis=0)]
        for name in LEDGER_COUNTER_ROWS
    }
    q = (
        np.asarray(cur.queue, np.int64).sum(axis=1)
        if cur.queue is not None
        else np.zeros(c.shape[:1] + c.shape[2:], np.int64)
    )
    blk["in_flight"] = [int(v) for v in q.sum(axis=0)]
    return blk


def audit_window(cur: MessageLedger, prev, topo, max_violations: int = 8):
    """The conservation-law auditor: integer equality per edge per flush
    window, on the stacked host snapshots (leading axis = ranks).

    Checks, in order:
      1. monotonicity — every cumulative counter's window delta >= 0;
      2. sender law, per rank per edge:
         proposed = suppressed + deferred + fired;
      3. receiver law, per edge summed over ranks:
         fired = delivered + dropped + rejected + delta(in_flight);
      4. cross-rank law, per rank per edge: the fired count of the
         edge's source rank (on the reverse edge, chaos.inject.
         reverse_edge_index) equals this rank's received census
         delivered + dropped + rejected + delta(in_flight);
      5. late sub-law, per rank per edge:
         late_committed <= delivered.

    Returns {"ok": bool, "checks": int, "violations": [...]} with at
    most `max_violations` named violations (law, rank, edge, lhs, rhs).
    """
    import numpy as np

    from eventgrad_tpu.chaos import inject as chaos_inject

    cumc = np.asarray(cur.counts, np.int64)        # [R, rows, E]
    d = cumc - (
        np.asarray(prev.counts, np.int64) if prev is not None else 0
    )
    n_ranks, _, n_edges = d.shape

    def q_sum(led):
        if led is None or led.queue is None:
            return np.zeros((n_ranks, n_edges), np.int64)
        return np.asarray(led.queue, np.int64).sum(axis=1)

    d_inflight = q_sum(cur) - q_sum(prev)

    def row(name, arr=None):
        return (arr if arr is not None else d)[:, ROW[name], :]

    violations = []
    checks = 0

    def check(ok_mask, law, lhs, rhs):
        nonlocal checks
        checks += int(ok_mask.size)
        if bool(ok_mask.all()):
            return
        for r, e in zip(*np.nonzero(~ok_mask)):
            if len(violations) >= max_violations:
                return
            violations.append({
                "law": law, "rank": int(r), "edge": int(e),
                "lhs": int(lhs[r, e]), "rhs": int(rhs[r, e]),
            })

    # 1. monotone counters
    for name in LEDGER_COUNTER_ROWS:
        check(
            row(name) >= 0, f"monotone:{name}",
            row(name), np.zeros_like(row(name)),
        )

    # 2. sender law
    lhs = row("proposed")
    rhs = row("suppressed") + row("deferred") + row("fired")
    check(lhs == rhs, "proposed=suppressed+deferred+fired", lhs, rhs)

    recv = (
        row("delivered") + row("dropped") + row("rejected") + d_inflight
    )

    # 3. receiver law, rank-summed per edge (every rank's send on edge
    # index e is received by exactly one rank on e's reverse, so the
    # rank sums balance even though each rank's own fired and received
    # census count different messages)
    lhs_e = row("fired").sum(axis=0, keepdims=True)
    rhs_e = recv.sum(axis=0, keepdims=True)
    check(
        lhs_e == rhs_e, "fired=delivered+dropped+rejected+in_flight",
        lhs_e, rhs_e,
    )

    # 4. cross-rank law: the per-rank refinement of (3)
    sources = chaos_inject.host_source_table(topo)      # [R, E]
    rev = chaos_inject.reverse_edge_index(topo)         # [E] or None
    if rev is not None and sources.shape == (n_ranks, n_edges):
        fired = row("fired")
        sender = fired[sources, np.asarray(rev)[None, :]]
        check(
            sender == recv, "sender.fired=receiver.census", sender, recv,
        )

    # 5. late commits are a sub-count of delivered
    lhs = row("late_committed")
    check(lhs <= row("delivered"), "late_committed<=delivered", lhs,
          row("delivered"))

    return {
        "ok": not violations,
        "checks": checks,
        "violations": violations,
    }
