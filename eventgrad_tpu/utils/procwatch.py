"""Deadlined subprocesses + accelerator liveness probing.

The TPU tunnel can wedge a blocked device op forever — no Python-level
interrupt works, and a child stuck in an uninterruptible device op can
even survive SIGKILL-then-reap. A supervising parent with a hard wall
deadline is the only reliable watchdog. This is the single home for that
logic: bench.py's supervisor and tools/tpu_watch.py both ride these two
helpers, so "tunnel alive" means exactly one thing repo-wide (an
*executed* jit — a wedged tunnel enumerates devices fine but blocks on
first use).
"""

from __future__ import annotations

import os
import subprocess
import sys


def run_deadlined(cmd, env, timeout_s, cwd=None, capture_stderr=False):
    """subprocess with a hard wall deadline that cannot hang the parent.

    subprocess.run(timeout=...)'s TimeoutExpired path waits forever on a
    child stuck in an uninterruptible device op: kill, give it a short
    grace to be reaped (salvaging anything already printed — a child that
    completed its measurement and then wedged in device teardown is a
    result), then abandon it unreaped.

    Returns (stdout_or_None, timed_out, returncode_or_None).
    """
    try:
        proc = subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE, text=True,
            stderr=subprocess.STDOUT if capture_stderr else None,
            cwd=cwd or os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
        )
    except OSError:
        return None, False, None
    try:
        out, _ = proc.communicate(timeout=timeout_s)
        return out, False, proc.returncode
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            out, _ = proc.communicate(timeout=10)
            return out, True, None
        except (subprocess.TimeoutExpired, OSError):
            pass  # unkillable child; abandon without reaping
        return None, True, None
    except OSError:
        # pipe read failed (e.g. EIO from a dying child) — callers'
        # contract is a result tuple, never an exception
        return None, False, None


def probe_device(env, timeout_s, require_tpu=False):
    """(verdict, platform): verdict is 'ok' iff the backend the child
    would use completes an *executed* jit in time, 'stalled' on deadline,
    'crashed' on fast failure; platform is the probed jax platform
    ('cpu'/'tpu'/...) or None. With require_tpu, a healthy non-TPU
    backend counts as 'crashed' (the watcher's notion of liveness)."""
    code = (
        "import os, jax, jax.numpy as jnp\n"
        "from eventgrad_tpu.utils import compile_cache\n"
        "compile_cache.honor_cpu_pin()\n"
        "jax.block_until_ready(jax.jit(lambda a: a @ a)(jnp.ones((256, 256))))\n"
        "d = jax.devices()[0]\n"
        + ("assert d.platform == 'tpu', d.platform\n" if require_tpu else "")
        + "print('EG_PROBE_OK', d.platform, d.device_kind)\n"
    )
    out, timed_out, _ = run_deadlined(
        [sys.executable, "-c", code], env, timeout_s
    )
    if timed_out:
        return "stalled", None
    for line in (out or "").splitlines():
        if line.startswith("EG_PROBE_OK"):
            parts = line.split()
            return "ok", parts[1] if len(parts) > 1 else None
    return "crashed", None
