"""Elastic membership: live rank join/leave with neighbor-stream bootstrap.

The chaos subsystem's ring heal (`policy.heal_ring`) only ever SHRINKS the
ring — a long-running service monotonically degrades, because nothing can
rejoin. This module is the full membership story: a replayable stream
of `join`/`leave` events processed at jit-dispatch-block boundaries
(the fused step never sees a dynamic shape):

  * **leave** — the clean generalization of peer death: `heal_ring`
    rewrites the topology to `Ring(n-1)`, survivor rows are re-sliced out
    of the stacked state, stale receive buffers are kept (legal gossip
    input by construction, event.cpp:177-179) and refresh within one
    force-fire cycle.
  * **join** — the new N -> N+1 path. The newcomer bootstraps its FULL
    gossip `TrainState` row (params, optimizer moments, event thresholds,
    stale neighbor buffers) from a neighbor's snapshot, streamed through
    the existing `utils/checkpoint.host_snapshot` + `AsyncWriter`
    machinery (the same eager-copy/background-serialize path the dispatch
    pipeline's checkpoints use — lossless, so replay stays bitwise), and
    the ring regrows to `Ring(n+1)`. `collectives.mix_weighted`'s uniform
    1/(1+n_neighbors) weighting needs no renormalization on a ring: the
    neighbor COUNT is 2 at every ring size >= 2, so regrowth only rewires
    `neighbor_source` — exactly like the heal, in reverse.

Every transition ends with a **force-fired first exchange**
(`force_refresh`): the next pass fires every parameter on every rank, so
all receive buffers — the newcomer's copied-stale ones and the survivors'
rewired-stale ones — refresh in one cycle. Forced fires ride the normal
event accounting (`num_events` counts them): elasticity spends savings,
visibly.

Determinism/replayability: a transition is a pure function of
(schedule, event, current state), the newcomer's PRNG stream is salted
from the source rank's key with (epoch, position), and the bootstrap
stream round-trips bitwise — so training state is bitwise-replayable
from the membership log alone (`train()` serializes the schedule into
the first history record, like chaos schedules).

Counters across transitions: a departed rank takes its cumulative
`num_events`/`num_deferred`/telemetry with it, and a newcomer starts its
counters at ZERO (copying the bootstrap source's counters would double-
count sends that happened once). Aggregate msgs-saved-% under membership
is therefore computed against cumulative rank-passes (train/loop.py),
and is approximate across leaves' histories by construction.

Ring(2) degenerate case: both neighbor shifts (-1/+1) resolve to the SAME
peer. The reference still sends two puts and weighs 1/3 (topology.py
`neighbors`), heal-to-2 keeps that contract (the healed topology IS
`Ring(2)`), and `mix_weighted` never half-counts the peer: both directed
edges share one source, so their health gates agree — regression-pinned
in tests/test_topology.py.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from eventgrad_tpu.chaos import crashpoint
from eventgrad_tpu.chaos.policy import apply_ring_heal
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.topology import Ring, Topology

KINDS = ("join", "leave")


@dataclasses.dataclass(frozen=True)
class MembershipEvent:
    """One transition, applied at the END of `epoch` (a dispatch-block
    boundary — train/loop.py forces one-epoch blocks under membership).

    kind="leave": `index` is the CURRENT stacked rank index removed.
    kind="join":  `index` is the ring position the newcomer takes (rows
    at >= index shift up by one); `src` is the CURRENT index of the
    bootstrap neighbor (default: the newcomer's left neighbor,
    `(index - 1) % n` at apply time).
    """

    epoch: int
    kind: str
    index: int
    src: Optional[int] = None

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"membership kind must be one of {KINDS}, "
                             f"got {self.kind!r}")
        if self.epoch < 1:
            raise ValueError(f"membership epoch must be >= 1, got {self.epoch}")
        if self.index < 0:
            raise ValueError(f"membership index must be >= 0, got {self.index}")
        if self.src is not None and self.src < 0:
            raise ValueError(f"membership src must be >= 0, got {self.src}")
        if self.kind == "leave" and self.src is not None:
            raise ValueError("leave events take no bootstrap src")


@dataclasses.dataclass(frozen=True)
class MembershipSchedule:
    """A replayable membership log: pure data, like `ChaosSchedule`.

    Events sort stably by epoch (same-epoch events apply in listed
    order); two runs of one schedule perform bit-identical transitions.

    `seed` is provenance only — no transition consumes it (they are
    deterministic functions of (event, state); the newcomer's PRNG salt
    derives from the source rank's key, not the schedule). It rides
    serialization so a schedule lifted from a chaos spec keeps its
    origin's seed label.
    """

    seed: int = 0
    events: Tuple[MembershipEvent, ...] = ()

    def __post_init__(self):
        object.__setattr__(
            self, "events",
            tuple(sorted(self.events, key=lambda e: e.epoch)),
        )

    @property
    def is_noop(self) -> bool:
        return not self.events

    def events_at(self, epoch: int) -> Tuple[MembershipEvent, ...]:
        return tuple(e for e in self.events if e.epoch == int(epoch))

    def n_ranks_at(self, base_n: int, epoch: int) -> int:
        """Rank count AFTER every event with `e.epoch <= epoch` applied
        (transitions land at epoch ends, so a snapshot taken at `epoch`
        reflects them)."""
        n = int(base_n)
        for e in self.events:
            if e.epoch <= epoch:
                n += 1 if e.kind == "join" else -1
                if n < 2:
                    raise ValueError(
                        f"membership schedule drops below 2 ranks at "
                        f"epoch {e.epoch}"
                    )
        return n

    def validate(self, base_n: int) -> None:
        """Fail-fast static walk: simulate the whole schedule from
        `base_n` ranks and reject any event whose index/src falls outside
        the ring it will meet — hours-deep apply-time surprises belong
        here, before any compute is spent. (Engine.apply keeps the same
        checks as its runtime guard.)"""
        n = int(base_n)
        for e in self.events:
            if e.kind == "leave":
                if not 0 <= e.index < n:
                    raise ValueError(
                        f"leave index {e.index} at epoch {e.epoch} "
                        f"outside 0..{n - 1}"
                    )
                n -= 1
            else:
                if not 0 <= e.index <= n:
                    raise ValueError(
                        f"join position {e.index} at epoch {e.epoch} "
                        f"outside 0..{n}"
                    )
                if e.src is not None and not 0 <= e.src < n:
                    raise ValueError(
                        f"join src {e.src} at epoch {e.epoch} "
                        f"outside 0..{n - 1}"
                    )
                n += 1
            if n < 2:
                raise ValueError(
                    f"membership schedule drops below 2 ranks at "
                    f"epoch {e.epoch}"
                )

    def topology_at(self, base_topo: Topology, epoch: int) -> Topology:
        """The ring topology after every event with epoch <= `epoch`."""
        n = self.n_ranks_at(base_topo.n_ranks, epoch)
        return (
            base_topo if n == base_topo.n_ranks
            else Ring(n, axis=base_topo.axes[0])
        )

    # --- serialization (history records / artifacts) -------------------

    def to_dict(self) -> Dict[str, Any]:
        return {
            "seed": self.seed,
            "events": [
                {"epoch": e.epoch, "kind": e.kind, "index": e.index,
                 **({"src": e.src} if e.src is not None else {})}
                for e in self.events
            ],
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "MembershipSchedule":
        return cls(
            seed=int(d.get("seed", 0)),
            events=tuple(
                MembershipEvent(
                    epoch=int(e["epoch"]), kind=str(e["kind"]),
                    index=int(e["index"]),
                    src=int(e["src"]) if e.get("src") is not None else None,
                )
                for e in d.get("events", ())
            ),
        )

    # --- CLI spec grammar: leave=IDX@EPOCH, join=POS@EPOCH[:SRC] -------

    def to_spec(self) -> str:
        parts = [f"seed={self.seed}"]
        parts += [format_event_clause(e) for e in self.events]
        return ",".join(parts)

    @classmethod
    def parse(cls, spec: str) -> "MembershipSchedule":
        kw: Dict[str, Any] = {"events": []}
        for clause in spec.split(","):
            clause = clause.strip()
            if not clause:
                continue
            key, sep, val = clause.partition("=")
            if not sep:
                raise ValueError(
                    f"bad membership clause {clause!r} (expected key=value)"
                )
            try:
                if key == "seed":
                    kw["seed"] = int(val)
                elif key in KINDS:
                    kw["events"].append(parse_event_clause(key, val))
                else:
                    raise ValueError(f"unknown membership key {key!r}")
            except ValueError as err:
                raise ValueError(
                    f"bad membership clause {clause!r}: {err}"
                ) from None
        kw["events"] = tuple(kw["events"])
        return cls(**kw)


def format_event_clause(e: MembershipEvent) -> str:
    """Inverse of `parse_event_clause` — the one emitter of the clause
    grammar, shared by both schedules' `to_spec`."""
    clause = f"{e.kind}={e.index}@{e.epoch}"
    if e.src is not None:
        clause += f":{e.src}"
    return clause


def parse_event_clause(kind: str, val: str) -> MembershipEvent:
    """`IDX@EPOCH` (leave) / `POS@EPOCH[:SRC]` (join) — the shared clause
    grammar of `MembershipSchedule.parse` and `ChaosSchedule.parse`'s
    join=/leave= vocabulary."""
    idx, _, rest = val.partition("@")
    epoch, _, src = rest.partition(":")
    if src and kind != "join":
        raise ValueError("only join events take a :SRC suffix")
    return MembershipEvent(
        epoch=int(epoch), kind=kind, index=int(idx),
        src=int(src) if src else None,
    )


def resolve(membership) -> "MembershipSchedule":
    """Accept a MembershipSchedule, spec string, or serialized dict — the
    one coercion used by train(), the CLI, and the soak tool."""
    if isinstance(membership, MembershipSchedule):
        return membership
    if isinstance(membership, str):
        return MembershipSchedule.parse(membership)
    if isinstance(membership, dict):
        return MembershipSchedule.from_dict(membership)
    raise TypeError(
        "membership must be a MembershipSchedule, spec string, or dict; "
        f"got {type(membership)}"
    )


def force_refresh(state, event_cfg: Optional[EventConfig]):
    """Arm a force-fired first exchange: the next pass fires EVERY
    parameter on every rank, so all receive buffers refresh in one cycle.

    Mechanism rides the trigger itself, so it works identically on the
    tree and arena engines and on both wires:
      * adaptive mode: thresholds drop to 0 — `value_diff >= 0` always
        holds, and the fire resets thres from the (real) slope history.
      * constant mode: `last_sent_norm` drops to -1e30 — the drift beats
        any constant; constant thresholds ignore the slope pollution.
        With constant == 0 every pass already fires: no-op.
    dpsgd/allreduce (no event state) need no arming — they ship
    everything every pass. On the compact wire a full-fire pass can
    overflow the budget; deferred leaves keep their armed trigger and
    drain under the capacity gate's starvation bound.
    """
    ev = getattr(state, "event", None)
    if ev is None:
        return state
    cfg = event_cfg or EventConfig()
    if cfg.adaptive:
        ev = ev.replace(thres=jnp.zeros_like(ev.thres))
    elif cfg.constant > 0.0:
        ev = ev.replace(
            last_sent_norm=jnp.full_like(ev.last_sent_norm, -1e30)
        )
    else:
        return state  # constant == 0: every pass fires already
    return state.replace(event=ev)


def _insert_row(tree: Any, pos: int, row: Any) -> Any:
    """Insert `row` (per-rank pytree) at stacked index `pos`."""
    return jax.tree.map(
        lambda x, r: jnp.concatenate(
            [x[:pos], jnp.asarray(r, x.dtype)[None], x[pos:]], axis=0
        ),
        tree, row,
    )


def take_rows_host(tree: Any, keep: Tuple[int, ...]) -> Any:
    """Host-side row slice of a numpy-leaf pytree (the loop's telemetry
    diff base must track the device state's row layout)."""
    idx = np.asarray(keep, np.int64)
    return jax.tree.map(lambda x: np.take(np.asarray(x), idx, axis=0), tree)


def insert_zero_row_host(tree: Any, pos: int) -> Any:
    """Host-side zero-row insertion (a newcomer's cumulative telemetry
    counters start at zero on device; the diff base matches)."""
    return jax.tree.map(
        lambda x: np.insert(np.asarray(x), pos, 0, axis=0), tree
    )


class MembershipEngine:
    """Applies one schedule's transitions to (state, topology) at
    dispatch-block boundaries. Host-side by design: a transition changes
    array shapes, so it can only happen between jitted dispatches.

    `bootstrap_dir` (optional) routes every join's neighbor snapshot
    through the on-disk checkpoint stream (`AsyncWriter` + atomic swap at
    `<dir>/bootstrap`) — the path a real newcomer process would read; in
    memory-only mode the same `host_snapshot` eager copy is handed over
    directly. Both are lossless, so the trained state is bitwise
    identical either way.
    """

    def __init__(
        self,
        schedule: MembershipSchedule,
        *,
        event_cfg: Optional[EventConfig] = None,
        bootstrap_dir: Optional[str] = None,
    ):
        self.schedule = schedule
        self.event_cfg = event_cfg
        self.bootstrap_dir = bootstrap_dir
        #: transitions applied so far (info dicts, in order)
        self.log: List[Dict[str, Any]] = []

    def events_at(self, epoch: int) -> Tuple[MembershipEvent, ...]:
        return self.schedule.events_at(epoch)

    # --- bootstrap stream ----------------------------------------------

    def _stream_row(self, row: Any) -> Tuple[Any, bool]:
        """Neighbor-row handoff through the checkpoint machinery:
        `host_snapshot` (eager device->host owned copies) always; with a
        bootstrap_dir, additionally `checkpoint.save` (the same
        write-tmp/atomic-swap as training snapshots — the transition
        blocks on the stream anyway, so no writer thread) and restore —
        the wire a joining process would consume. Returns
        (host row, streamed_via_disk)."""
        from eventgrad_tpu.utils import checkpoint

        snap = checkpoint.host_snapshot(row)
        if not self.bootstrap_dir:
            return snap, False
        path = os.path.join(self.bootstrap_dir, "bootstrap")
        checkpoint.save(path, snap)
        # seeded kill between the stream's commit and the newcomer's
        # restore: the transition must be repeatable from the main
        # snapshot (tools/crash_matrix.py proves it)
        crashpoint.hit("membership.bootstrap")
        found = checkpoint.latest(path)
        return checkpoint.restore(found, snap), True

    # --- transitions ---------------------------------------------------

    def apply(self, state, topo: Topology, ev: MembershipEvent):
        """Apply one transition; returns (state, topology, info record).

        Leave re-slices survivors (exactly `policy.apply_ring_heal`);
        join inserts the bootstrapped row at `ev.index` and regrows the
        ring. Both end force-refreshed (module docstring)."""
        if len(topo.axes) != 1 or topo.gossip_axes != topo.axes:
            raise ValueError(
                "membership transitions handle single-axis gossip rings; "
                f"got axes {topo.axes}"
            )
        t0 = time.perf_counter()
        n = topo.n_ranks
        info: Dict[str, Any] = {
            "kind": ev.kind, "epoch": ev.epoch, "index": ev.index,
            "n_ranks_before": n,
        }
        if ev.kind == "leave":
            if n <= 2:
                raise ValueError(
                    f"cannot leave at n_ranks={n}: a ring needs >= 2"
                )
            new_state, new_topo, survivors = apply_ring_heal(
                state, topo, {ev.index}
            )
            info["survivors"] = list(survivors)
        else:
            if not 0 <= ev.index <= n:
                raise ValueError(
                    f"join position {ev.index} outside 0..{n}"
                )
            src = ev.src if ev.src is not None else (ev.index - 1) % n
            if not 0 <= src < n:
                raise ValueError(f"join src {src} outside 0..{n - 1}")
            row = jax.tree.map(lambda x: x[src], state)
            row, streamed = self._stream_row(row)
            new_state = _insert_row(state, ev.index, row)
            new_state = self._init_newcomer(new_state, ev, src)
            new_topo = Ring(n + 1, axis=topo.axes[0])
            info.update(src=src, bootstrap_streamed=streamed)
        new_state = force_refresh(new_state, self.event_cfg)
        info["n_ranks_after"] = new_topo.n_ranks
        info["apply_s"] = round(time.perf_counter() - t0, 4)
        self.log.append(info)
        return new_state, new_topo, info

    def _init_newcomer(self, state, ev: MembershipEvent, src: int):
        """Post-insert fix-ups at row `ev.index`: cumulative counters
        start at zero (the bootstrap copies STATE, not HISTORY), the
        PRNG stream is salted deterministically from the source key with
        (epoch, position) so replay reproduces it, and — like the heal —
        every rank's per-edge health resets so fresh edges start
        healthy."""
        pos = ev.index
        upd = {}
        evs = getattr(state, "event", None)
        if evs is not None:
            upd["event"] = evs.replace(
                num_events=evs.num_events.at[pos].set(0),
                num_deferred=evs.num_deferred.at[pos].set(0),
            )
        tel = getattr(state, "telemetry", None)
        if tel is not None:
            upd["telemetry"] = jax.tree.map(
                lambda x: x.at[pos].set(jnp.zeros_like(x[pos])), tel
            )
        health = getattr(state, "chaos", None)
        if health is not None:
            upd["chaos"] = health.replace(
                silence=jnp.zeros_like(health.silence),
                sync_req=jnp.zeros_like(health.sync_req),
                drops=health.drops.at[pos].set(0),
            )
        rng = getattr(state, "rng", None)
        if rng is not None:
            salt = jax.random.fold_in(
                jax.random.fold_in(rng[pos], ev.epoch), pos
            )
            upd["rng"] = rng.at[pos].set(salt)
        return state.replace(**upd) if upd else state
