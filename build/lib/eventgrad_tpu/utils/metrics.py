"""Structured metrics — the reference's flat-file logs, upgraded to JSONL.

The reference writes per-rank `send{r}.txt`/`recv{r}.txt`/`train{r}.txt`
plus stdout accuracy (/root/reference/dmnist/event/event.cpp:232-252,
337-339, 385-391; dcifar10/event/event.cpp:271-273). Here every record is a
JSON line with the BASELINE metrics first-class: msgs-saved-%,
grad-sync bytes/step/chip, test-acc vs epoch.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional


class JsonlLogger:
    def __init__(self, path: Optional[str] = None, echo: bool = True):
        self.path = path
        self.echo = echo
        self._fh = open(path, "a") if path else None

    def log(self, record: Dict[str, Any]) -> None:
        record = {"ts": round(time.time(), 3), **record}
        line = json.dumps(record, default=float)
        if self._fh:
            self._fh.write(line + "\n")
            self._fh.flush()
        if self.echo:
            print(line)

    def close(self) -> None:
        if self._fh:
            self._fh.close()


def msgs_saved_pct(num_events: int, passes: int, n_tensors: int, n_neighbors: int, n_ranks: int) -> float:
    """1 - events/possible, the reference's headline metric
    (events counted per neighbor per tensor per pass, event.cpp:344,527-532)."""
    possible = n_neighbors * passes * n_tensors * n_ranks
    return 100.0 * (1.0 - num_events / possible) if possible else 0.0
