"""Hierarchical data parallelism: a "ddp" axis forms synchronous allreduce
subgroups inside each gossip rank — gossip across pods, allreduce within a
pod. Ranks along ddp hold identical parameters (gradients pmean like any
aux axis) but shard the DATA, so a (dp, ddp) mesh is numerically a dp-ring
whose per-rank batch is the concatenation of its ddp shards."""

import json

import jax
import numpy as np
import pytest

from eventgrad_tpu.cli import main, parse_mesh
from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.topology import Ring, Topology
from eventgrad_tpu.train.loop import train


def test_parse_mesh_ddp():
    t = parse_mesh("dp:2,ddp:4")
    assert t.gossip_axes == ("dp",) and t.aux_axes == ("ddp",)
    assert t.data_axes == ("dp", "ddp") and t.n_data_ranks == 8
    assert not t.sharded_axes


def test_ddp_group_equals_bigger_batch_ring():
    """dpsgd on dp:2,ddp:2 with per-rank batch B must match Ring(2) with
    per-rank batch 2B exactly: the ddp gradient pmean is the mean over the
    concatenated shards (cross-entropy is a mean). One full-shard step per
    epoch makes the sample groupings identical between the two layouts
    (with several steps per epoch they'd cover the data in different
    per-step groupings)."""
    x, y = synthetic_dataset(128, (28, 28, 1), seed=8)
    kw = dict(algo="dpsgd", epochs=2, learning_rate=0.05, seed=2,
              log_every_epoch=False)
    topo_h = Topology(axes=("dp", "ddp"), shape=(2, 2), gossip_axes=("dp",),
                      data_aux_axes=("ddp",))
    s_h, h_h = train(MLP(), topo_h, x, y, batch_size=32, **kw)
    s_r, h_r = train(MLP(), Ring(2), x, y, batch_size=64, **kw)

    # dp rank i's params live at stacked indices (2i, 2i+1) — identical
    # across the ddp pair, equal to the plain ring's rank i
    ph = jax.tree.map(np.asarray, s_h.params)
    pr = jax.tree.map(np.asarray, s_r.params)
    for a, b in zip(jax.tree.leaves(ph), jax.tree.leaves(pr)):
        np.testing.assert_allclose(a[0], a[1], atol=1e-6)  # ddp-identical
        np.testing.assert_allclose(a[2], a[3], atol=1e-6)
        np.testing.assert_allclose(a[::2], b, atol=1e-5)   # == ring ranks


def test_eventgrad_ddp_converges_with_consensus_eval(capsys):
    args = ["--algo", "eventgrad", "--mesh", "dp:2,ddp:2",
            "--dataset", "synthetic", "--model", "mlp", "--epochs", "2",
            "--batch-size", "8", "--n-synth", "128", "--warmup-passes", "2"]
    assert main(args) == 0
    recs = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    assert recs[-1]["final"] and "accuracy" in recs[-1]  # consensus eval ran
    assert recs[-2]["msgs_saved_pct"] >= 0


def test_gossipless_mesh_rejected_for_gossip_algos():
    with pytest.raises(SystemExit, match="gossip axis"):
        main(["--algo", "eventgrad", "--mesh", "ddp:8"])
