"""Distributed data sharding — the reference's samplers as array ops.

torch's DistributedSequentialSampler / DistributedRandomSampler with
allow_duplicates=false (/root/reference/dmnist/decent/decent.cpp:81-82,
dmnist/cent/cent.cpp:59-60, dcifar10/event/event.cpp:102-105) give each of N
ranks a disjoint 1/N slice of the dataset. Here a shard plan is materialized
up front as index arrays in the stacked layout [n_ranks, steps, batch], so an
entire epoch of per-rank batches is a single gather — friendly to
`jax.device_put` once and `lax.scan` over steps.
"""

from __future__ import annotations

from typing import Iterator, Tuple

import numpy as np


def input_cast_dtype(x: np.ndarray) -> np.dtype:
    """The ONE cast rule for training inputs: integer data (token
    sequences) stays int32, everything else (images) goes float32.
    Shared by the host prefetcher and the device-resident dataset path
    (train/loop.py device_data) so their trajectories stay bitwise
    equal."""
    return np.dtype(
        np.int32
        if np.issubdtype(np.asarray(x).dtype, np.integer)
        else np.float32
    )


def _per_rank_count(n: int, n_ranks: int) -> int:
    """Samples per rank, dropping the remainder (allow_duplicates=false)."""
    return n // n_ranks


def shard_sequential(n: int, n_ranks: int) -> np.ndarray:
    """[n_ranks, per_rank] contiguous index slices (sequential sampler)."""
    per = _per_rank_count(n, n_ranks)
    return np.arange(n_ranks * per, dtype=np.int64).reshape(n_ranks, per)


def shard_random(n: int, n_ranks: int, seed: int = 0, epoch: int = 0) -> np.ndarray:
    """[n_ranks, per_rank] disjoint shards of a global permutation
    (random sampler); reshuffled per epoch via the seed mix."""
    rng = np.random.default_rng(np.random.SeedSequence([seed, epoch]))
    per = _per_rank_count(n, n_ranks)
    perm = rng.permutation(n)[: n_ranks * per]
    return perm.reshape(n_ranks, per).astype(np.int64)


def epoch_steps(n: int, n_ranks: int, batch_size: int) -> int:
    """Steps per epoch, without materializing the index plan (same
    full-batch truncation as `epoch_index_plan`; sampler-independent —
    both shard to the same per-rank count)."""
    per = _per_rank_count(n, n_ranks)
    steps = per // batch_size
    if steps == 0:
        raise ValueError(
            f"batch_size {batch_size} larger than per-rank shard {per} "
            f"({n} samples / {n_ranks} ranks)"
        )
    return steps


def epoch_index_plan(
    n: int,
    n_ranks: int,
    batch_size: int,
    *,
    random: bool = False,
    seed: int = 0,
    epoch: int = 0,
) -> np.ndarray:
    """[n_ranks, steps, batch] sample indices for one epoch. Trailing
    partial batches are dropped, matching the reference loaders'
    full-batch iteration. The single source of truth for epoch assembly —
    `batched_epoch` and `prefetch.EpochPrefetcher` both consume it."""
    steps = epoch_steps(n, n_ranks, batch_size)
    shards = (
        shard_random(n, n_ranks, seed, epoch)
        if random
        else shard_sequential(n, n_ranks)
    )
    return shards[:, : steps * batch_size].reshape(n_ranks, steps, batch_size)


def batched_epoch(
    x: np.ndarray,
    y: np.ndarray,
    n_ranks: int,
    batch_size: int,
    *,
    random: bool = False,
    seed: int = 0,
    epoch: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """One epoch of per-rank batches in stacked layout: (xb, yb) shaped
    [n_ranks, steps, batch, ...] / [n_ranks, steps, batch]."""
    idx = epoch_index_plan(
        len(x), n_ranks, batch_size, random=random, seed=seed, epoch=epoch
    )
    return x[idx], y[idx]


def expand_to_mesh(
    xb: np.ndarray, yb: np.ndarray, topo, sp_axis: str = "sp"
) -> Tuple[np.ndarray, np.ndarray]:
    """Lift data-sharded batches onto a hybrid mesh's full rank set.

    `xb`/`yb` arrive in the stacked layout over the DATA ranks only
    ([n_data, steps, batch, ...] — each data rank owns a disjoint shard,
    the reference's sampler semantics; data axes = the gossip axes plus a
    "ddp" hierarchical-allreduce axis if present). The full mesh may carry
    more axes: a sequence-parallel axis (each rank holds its chunk of the
    token dimension — ring attention's layout) and sharded/replicated aux
    axes (tp/pp/ep — every rank in the group sees the same batch; the
    *model* is what differs). Returns [topo.n_ranks, steps, batch,
    ...(chunked)] in the topology's row-major rank order, matching
    `parallel.spmd.spmd`.
    """
    shape = topo.shape
    data_idx = [topo.axes.index(a) for a in topo.data_axes]
    sp_pos = topo.axes.index(sp_axis) if sp_axis in topo.axes else None
    n_sp = shape[sp_pos] if sp_pos is not None else 1
    if sp_pos is not None:
        t_global = xb.shape[-1]
        if n_sp > 1 and not np.issubdtype(xb.dtype, np.integer):
            raise ValueError(
                f"{sp_axis} axis chunks the TRAILING batch dimension (size "
                f"{t_global}) as a token sequence, but batches are "
                f"{xb.dtype} — image channels must not be sliced; "
                f"sequence parallelism requires integer token data"
            )
        if t_global % n_sp:
            raise ValueError(
                f"sequence length {t_global} not divisible by {sp_axis} size {n_sp}"
            )
        t_local = t_global // n_sp

    xs, ys = [], []
    for r in range(topo.n_ranks):
        multi = np.unravel_index(r, shape)
        g = 0
        for ax in data_idx:
            g = g * shape[ax] + multi[ax]
        xr, yr = xb[g], yb[g]
        if sp_pos is not None:
            sl = slice(multi[sp_pos] * t_local, (multi[sp_pos] + 1) * t_local)
            xr, yr = xr[..., sl], yr[..., sl]
        xs.append(xr)
        ys.append(yr)
    return np.stack(xs), np.stack(ys)
