"""The reference's 2-layer MNIST MLP (M1).

Rebuild of `struct Model` at /root/reference/dmnist/cent/cent.cpp:16-35
(identical copy in dmnist/decent/decent.cpp:19-38): 784 -> 128 ReLU -> 10
ReLU. The ReLU on the *logits* (cent.cpp:29) is a reference quirk preserved
behind `relu_logits` because it changes the training trajectory; the loss
applies its own log_softmax (cent.cpp:119). 101,770 params in 4 tensors.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    hidden: int = 128
    num_classes: int = 10
    relu_logits: bool = True  # faithful to cent.cpp:29

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1)).astype(jnp.float32)
        x = nn.relu(nn.Dense(self.hidden)(x))
        x = nn.Dense(self.num_classes)(x)
        if self.relu_logits:
            x = nn.relu(x)
        return x
