"""Pipeline parallelism: PP-staged Transformer == sequential twin, exactly.

Strategy mirrors test_tensor_parallel.py: run the pp_size=S model on an
S-rank mesh, reassemble its stage params into a pp_size=1 sequential twin
(stage-major: pp rank r's local layer i is global layer r*L+i), and demand
(a) identical logits on every rank and (b) identical one-SGD-step updates —
(b) exercises AD through the gpipe scan+ppermute schedule and the
masked-psum loss broadcast's cotangent scaling (sharded-leaf /N rule).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from eventgrad_tpu.models.pp import PPTransformerLM, gpipe
from eventgrad_tpu.parallel.spmd import spmd
from eventgrad_tpu.parallel.topology import Ring, Topology
from eventgrad_tpu.train.state import init_train_state_spmd
from eventgrad_tpu.train.steps import make_train_step

VOCAB, DIM, HEADS, T = 32, 32, 4, 16
PP = 4
LAYERS = 4  # one block per stage
MICRO = 2
BATCH = 4


def _models():
    pp = PPTransformerLM(vocab=VOCAB, dim=DIM, n_heads=HEADS, n_layers=LAYERS,
                         max_len=T, axis="pp", pp_size=PP, n_micro=MICRO)
    seq = PPTransformerLM(vocab=VOCAB, dim=DIM, n_heads=HEADS, n_layers=LAYERS,
                          max_len=T, pp_size=1)
    return pp, seq


def _assemble_twin(stacked):
    """Stacked pp params [S, ...] -> sequential twin params: stage r's
    tp_l{i}_* leaf becomes the twin's tp_l{r*L+i}_*; replicated leaves take
    rank 0 after asserting mesh-wide equality."""
    layers_local = LAYERS // PP
    twin = {}
    for name, leaf in stacked.items():
        if name.startswith("tp_l"):
            i, _, suffix = name[4:].partition("_")
            for r in range(PP):
                twin[f"tp_l{r * layers_local + int(i)}_{suffix}"] = leaf[r]
        else:
            sub = jax.tree.map(lambda x: x[0], leaf)
            for r in range(1, PP):
                jax.tree.map(
                    lambda a, b: np.testing.assert_allclose(
                        np.asarray(a), np.asarray(b[r]), atol=1e-7
                    ),
                    sub, leaf,
                )
            twin[name] = sub
    return twin


def _slice_stage(twin, r):
    """Inverse of _assemble_twin for one pp rank."""
    layers_local = LAYERS // PP
    out = {}
    for name, leaf in twin.items():
        if name.startswith("tp_l"):
            j, _, suffix = name[4:].partition("_")
            j = int(j)
            if j // layers_local == r:
                out[f"tp_l{j % layers_local}_{suffix}"] = leaf
        else:
            out[name] = leaf
    return out


def test_gpipe_schedule_identity_stage():
    """With an identity stage_fn the last stage must reproduce the feed."""
    topo = Topology(axes=("pp",), shape=(PP,), sharded_axes=("pp",))
    xm = jnp.arange(3 * 2 * 5, dtype=jnp.float32).reshape(1, 3, 2, 5)
    xm = jnp.broadcast_to(xm, (PP, 3, 2, 5))

    out = spmd(lambda x: gpipe(lambda h: h, x, "pp", PP), topo)(xm)
    np.testing.assert_allclose(np.asarray(out[PP - 1]), np.asarray(xm[0]))


def test_pp_forward_and_step_match_sequential():
    topo = Topology(axes=("pp",), shape=(PP,), sharded_axes=("pp",))
    assert topo.neighbors == ()  # sharded axis never gossips
    pp_model, seq_model = _models()

    tx = optax.sgd(0.1)
    state = init_train_state_spmd(
        pp_model, (T,), tx, topo, "dpsgd", input_dtype=jnp.int32
    )
    twin_params = _assemble_twin(state.params)

    toks = jax.random.randint(jax.random.PRNGKey(5), (BATCH, T), 0, VOCAB)
    tgts = jnp.roll(toks, -1, axis=-1)

    # (a) forward parity: every pp rank emits the twin's logits
    pp_logits = spmd(
        lambda p, t: pp_model.apply({"params": p}, t), topo
    )(state.params, jnp.broadcast_to(toks, (PP,) + toks.shape))
    seq_logits = seq_model.apply({"params": twin_params}, toks)
    for r in range(PP):
        np.testing.assert_allclose(
            np.asarray(pp_logits[r]), np.asarray(seq_logits), atol=2e-5,
            err_msg=f"rank {r}",
        )

    # (b) one-SGD-step parity (AD through the pipeline schedule)
    step = make_train_step(pp_model, tx, topo, "dpsgd")
    lifted = jax.jit(spmd(step, topo))
    xb = jnp.broadcast_to(toks, (PP,) + toks.shape)
    yb = jnp.broadcast_to(tgts, (PP,) + tgts.shape)
    new_state, m = lifted(state, (xb, yb))
    assert np.ptp(np.asarray(m["loss"])) < 1e-6  # same loss on every stage

    def twin_loss(p):
        out = seq_model.apply({"params": p}, toks)
        logp = jax.nn.log_softmax(out)
        return -jnp.mean(jnp.take_along_axis(logp, tgts[..., None], -1))

    g = jax.grad(twin_loss)(twin_params)
    twin_new = jax.tree.map(lambda p, g: p - 0.1 * g, twin_params, g)

    for r in range(PP):
        expect = _slice_stage(twin_new, r)
        got = jax.tree.map(lambda p: p[r], new_state.params)
        for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(expect),
            jax.tree_util.tree_leaves_with_path(got),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=3e-5,
                err_msg=f"rank {r}: {jax.tree_util.keystr(pa)}",
            )


def test_dp_gossip_times_pp():
    """EventGraD across dp while blocks are pipeline-staged: 2x4 mesh."""
    from eventgrad_tpu.parallel.events import EventConfig

    topo = Topology(
        axes=("dp", "pp"), shape=(2, PP), gossip_axes=("dp",), sharded_axes=("pp",)
    )
    assert len(topo.neighbors) == 2 and topo.aux_axes == ()
    pp_model, _ = _models()
    tx = optax.sgd(0.1)
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=2)
    state = init_train_state_spmd(
        pp_model, (T,), tx, topo, "eventgrad", cfg, input_dtype=jnp.int32
    )
    step = make_train_step(pp_model, tx, topo, "eventgrad", event_cfg=cfg)
    lifted = jax.jit(spmd(step, topo))

    toks = jax.random.randint(jax.random.PRNGKey(9), (2, BATCH, T), 0, VOCAB)
    xb = jnp.repeat(toks, PP, axis=0).reshape(2 * PP, BATCH, T)  # replicate over pp
    yb = jnp.roll(xb, -1, axis=-1)

    losses = []
    for _ in range(6):
        state, m = lifted(state, (xb, yb))
        losses.append(float(np.asarray(m["loss"]).mean()))
    assert losses[-1] < losses[0]
    assert int(np.asarray(state.event.num_events).sum()) > 0

    # pp stages of a dp rank must agree on replicated leaves post-gossip
    emb = state.params["Embed_0"]["embedding"].reshape(2, PP, VOCAB, DIM)
    np.testing.assert_allclose(
        np.asarray(emb[:, 0]), np.asarray(emb[:, 1]), atol=1e-5
    )
