"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric (BASELINE.json): messages-saved-% of EventGraD vs D-PSGD at
the CIFAR-10 operating point (reference claim ~60%, /root/reference/README.md:4),
with test accuracy of the consensus model compared against a D-PSGD run of
identical op-point (the reference's "comparable accuracy" claim). Flagship
config: ResNet-18-as-coded (3 blocks/stage, ~17.4M params), 8-rank ring,
global batch 256, SGD momentum 0.9, adaptive threshold, ~3.9k passes (the
reference's 20-epoch x ~195-step CIFAR scale, event.cpp:31-36).

All 8 ranks are vmap-simulated on the local accelerator (the single-chip
lifting path; identical trajectories to the shard_map path per
test_train_equivalence.py::test_shard_map_matches_vmap).

Data: synthetic teacher-labeled CIFAR-shaped set (no network egress here).
Augmentation stays OFF for synthetic data — the fixed linear teacher's
labels are not crop/flip-invariant, so the reference's pad4+flip+crop would
destroy the learning signal (the real-data CLI path applies it).
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np


def main() -> None:
    import jax.numpy as jnp

    from eventgrad_tpu.data.datasets import load_or_synthesize
    from eventgrad_tpu.models import ResNet18
    from eventgrad_tpu.parallel.events import EventConfig
    from eventgrad_tpu.parallel.topology import Ring
    from eventgrad_tpu.train.loop import consensus_params, evaluate, train
    from eventgrad_tpu.utils import trees

    topo = Ring(8)
    global_batch = 256
    per_rank = global_batch // topo.n_ranks
    n_train, n_test = 16384, 2048
    epochs = 61  # 61 x 64 steps = 3904 passes ~= the reference op-point

    x, y = load_or_synthesize("cifar10", None, "train", n_synth=n_train)
    xt, yt = load_or_synthesize("cifar10", None, "test", n_synth=n_test)
    model = ResNet18(dtype=jnp.bfloat16)
    event_cfg = EventConfig(adaptive=True, horizon=0.95, warmup_passes=30)

    common = dict(
        epochs=epochs, batch_size=per_rank,
        learning_rate=1e-2, momentum=0.9,  # dcifar10/event/event.cpp:196-200
        random_sampler=True, log_every_epoch=False,
    )

    t0 = time.perf_counter()
    state, hist = train(
        model, topo, x, y, algo="eventgrad", event_cfg=event_cfg, **common
    )
    wall_event = time.perf_counter() - t0
    cons = consensus_params(state.params)
    stats0 = jax.tree.map(lambda s: s[0], state.batch_stats)
    test = evaluate(model, cons, stats0, xt, yt)

    t0 = time.perf_counter()
    state_d, hist_d = train(model, topo, x, y, algo="dpsgd", **common)
    wall_dpsgd = time.perf_counter() - t0
    cons_d = consensus_params(state_d.params)
    stats_d = jax.tree.map(lambda s: s[0], state_d.batch_stats)
    test_d = evaluate(model, cons_d, stats_d, xt, yt)

    saved = hist[-1]["msgs_saved_pct"]
    steady = hist[1:] or hist
    step_ms = 1000 * float(np.mean([h["wall_s"] / h["steps"] for h in steady]))
    n_params = trees.tree_count_params(jax.tree.map(lambda p: p[0], state.params))

    print(
        json.dumps(
            {
                "metric": "cifar10_resnet_eventgrad_msgs_saved",
                "value": round(saved, 2),
                "unit": "%",
                "vs_baseline": round(saved / 60.0, 4),
                "test_acc": round(test["accuracy"], 2),
                "test_acc_dpsgd": round(test_d["accuracy"], 2),
                "acc_gap_vs_dpsgd": round(test["accuracy"] - test_d["accuracy"], 2),
                "step_ms": round(step_ms, 2),
                "sent_bytes_per_step_per_chip": hist[-1]["sent_bytes_per_step_per_chip"],
                "dense_bytes_per_step_per_chip": float(topo.n_neighbors * 4 * n_params),
                "final_train_loss": round(hist[-1]["loss"], 4),
                "passes": epochs * (n_train // global_batch),
                "wall_s_eventgrad": round(wall_event, 1),
                "wall_s_dpsgd": round(wall_dpsgd, 1),
                "platform": jax.devices()[0].platform,
                "n_ranks": topo.n_ranks,
            }
        )
    )


if __name__ == "__main__":
    main()
