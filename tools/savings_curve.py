"""Messages-saved trajectory at reference-scale pass counts (VERDICT item 4
evidence).

One eventgrad leg per headline config at horizon 1.0 / warmup 30
(the reference's sample adaptive run, dmnist/event/README.md): MNIST CNN-2
at the full 1168-pass op-point (event.cpp:255: 10 epochs x ~117 steps) and
CIFAR tiny-ResNet at 256 passes. Prints a JSON line per config with the
final msgs-saved-% and its trajectory (`trail`) — savings climb as training
converges because parameter-norm drift shrinks, so they must be judged at
the reference pass counts, not short smoke tiers.

Round-2 CPU result committed as artifacts/savings_curve_r2_cpu.jsonl:
MNIST 66.2% (rising; ~70% claim within reach of the full-scale run),
CIFAR 47.4% @256 passes rising ~1.5pp/32 passes toward the ~60% target
at the 3904-pass flagship scale.

Usage: JAX_PLATFORMS=cpu python tools/savings_curve.py"""
import json
import time

import jax
from eventgrad_tpu.utils import compile_cache

compile_cache.honor_cpu_pin()

from eventgrad_tpu.data.datasets import load_or_synthesize
from eventgrad_tpu.models import CNN2, ResNet
from eventgrad_tpu.models.resnet import BasicBlock
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.loop import train

topo = Ring(8)
cfg = EventConfig(adaptive=True, horizon=1.0, warmup_passes=30)

# MNIST CNN-2 at the reference op-point scale: 1168 passes, warmup 30
xm, ym = load_or_synthesize("mnist", None, "train", n_synth=2048)
t0 = time.time()
_, h = train(CNN2(), topo, xm, ym, algo="eventgrad", event_cfg=cfg,
             epochs=292, batch_size=64, learning_rate=0.05,
             random_sampler=False, log_every_epoch=False)
trail = [round(r["msgs_saved_pct"], 1) for r in h[::40]]
print(json.dumps({"mnist_passes": sum(r["steps"] for r in h),
                  "mnist_saved": round(h[-1]["msgs_saved_pct"], 2),
                  "trail": trail, "loss": round(h[-1]["loss"], 4),
                  "wall": round(time.time() - t0, 1)}), flush=True)

# CIFAR tiny ResNet, 256 passes
x, y = load_or_synthesize("cifar10", None, "train", n_synth=1024)
t0 = time.time()
_, h = train(ResNet(stage_sizes=(1, 1, 1, 1), block_cls=BasicBlock, num_filters=8),
             topo, x, y, algo="eventgrad", event_cfg=cfg,
             epochs=16, batch_size=8, learning_rate=1e-2, momentum=0.9,
             random_sampler=True, log_every_epoch=False)
trail = [round(r["msgs_saved_pct"], 1) for r in h[::2]]
print(json.dumps({"cifar_passes": sum(r["steps"] for r in h),
                  "cifar_saved": round(h[-1]["msgs_saved_pct"], 2),
                  "trail": trail, "loss": round(h[-1]["loss"], 4),
                  "wall": round(time.time() - t0, 1)}), flush=True)
