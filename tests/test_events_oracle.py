"""Randomized golden-trace cross-check of the EventGraD state machine.

`_oracle` re-implements the reference's sender-side semantics
(/root/reference/dmnist/event/event.cpp:324-391) the way the C++ does it —
an imperative per-parameter scalar loop over passes — written independently
of parallel/events.py's fused pytree version. Driving both with hundreds of
random norm trajectories and asserting identical fire decisions, thresholds,
slope buffers, and event counters is the property-test equivalent of
replaying the reference's send{r}.txt traces (SURVEY §4 test pyramid, item 4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from eventgrad_tpu.parallel.events import EventConfig, EventState
from eventgrad_tpu.parallel.events import decide_and_update
from eventgrad_tpu.parallel.topology import Ring


class _Oracle:
    """Scalar-loop twin of event.cpp's state arrays (:181-225)."""

    def __init__(self, n_params, cfg, n_neighbors):
        self.cfg = cfg
        self.nb = n_neighbors
        self.thres = np.zeros(n_params)
        self.last_sent_norm = np.zeros(n_params)
        self.last_sent_iter = np.zeros(n_params)
        self.slopes = np.zeros((n_params, cfg.history))
        self.num_events = 0

    def step(self, norms, pass_num):
        fires = []
        for i, norm in enumerate(norms):
            value_diff = abs(norm - self.last_sent_norm[i])
            if self.cfg.adaptive:  # decay BEFORE the check (event.cpp:330-332)
                self.thres[i] *= self.cfg.horizon
            else:  # constant mode re-assigns every pass (:332-334)
                self.thres[i] = self.cfg.constant
            fire = value_diff >= self.thres[i] or pass_num < self.cfg.warmup_passes
            if self.cfg.max_silence > 0:  # bounded staleness (beyond ref)
                fire = fire or (pass_num - self.last_sent_iter[i]) >= self.cfg.max_silence
            if fire:
                iter_diff = pass_num - self.last_sent_iter[i]
                self.slopes[i] = np.append(self.slopes[i][1:], value_diff / iter_diff)
                if self.cfg.adaptive:  # thres = mean slope (:363-378)
                    self.thres[i] = self.slopes[i].mean()
                self.last_sent_norm[i] = norm
                self.last_sent_iter[i] = pass_num
                self.num_events += self.nb  # += 2 on a ring (:344)
            fires.append(fire)
        return fires


def _run_pair(cfg, n_passes=120, n_params=6, seed=0):
    topo = Ring(4)
    rng = np.random.default_rng(seed)
    # random-walk positive norms, occasionally flat (drift can be ~0)
    steps = rng.normal(0, 0.05, (n_passes, n_params)) * (
        rng.random((n_passes, n_params)) > 0.25
    )
    norms = np.abs(2.0 + np.cumsum(steps, axis=0))

    # params chosen as single-element arrays whose L2 norm IS the trajectory
    params = {f"p{i}": jnp.zeros((1,)) for i in range(n_params)}
    state = EventState.init(params, topo, cfg)
    oracle = _Oracle(n_params, cfg, topo.n_neighbors)

    step = jax.jit(
        lambda p, s, t: decide_and_update(p, s, t, cfg, topo.n_neighbors),
        static_argnames=(),
    )
    for t in range(1, n_passes + 1):  # pass_num is 1-based (event.cpp:273)
        p = {f"p{i}": jnp.array([norms[t - 1, i]], jnp.float32) for i in range(n_params)}
        fire, state = step(p, state, jnp.array(t))
        fire_o = oracle.step(norms[t - 1].astype(np.float32), t)
        got = [bool(fire[f"p{i}"]) for i in range(n_params)]
        assert got == fire_o, f"fire mismatch at pass {t}: {got} vs {fire_o}"
    return state, oracle


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_adaptive_matches_oracle(seed):
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=8, history=2)
    state, oracle = _run_pair(cfg, seed=seed)
    for i in range(6):
        np.testing.assert_allclose(float(state.thres[i]), oracle.thres[i], rtol=1e-5)
        np.testing.assert_allclose(
            float(state.last_sent_norm[i]), oracle.last_sent_norm[i], rtol=1e-6
        )
        np.testing.assert_allclose(
            float(state.last_sent_iter[i]), oracle.last_sent_iter[i]
        )
        np.testing.assert_allclose(
            np.asarray(state.slopes[i]), oracle.slopes[i], rtol=1e-5
        )
    assert int(state.num_events) == oracle.num_events


@pytest.mark.parametrize("seed", [3, 4])
def test_constant_mode_matches_oracle(seed):
    cfg = EventConfig(adaptive=False, constant=0.08, warmup_passes=5)
    state, oracle = _run_pair(cfg, seed=seed)
    assert int(state.num_events) == oracle.num_events
    for i in range(6):
        np.testing.assert_allclose(
            float(state.last_sent_norm[i]), oracle.last_sent_norm[i], rtol=1e-6
        )


def test_zero_constant_always_fires():
    """threshold 0 == exact D-PSGD (dmnist/event/README.md:59-60)."""
    cfg = EventConfig(adaptive=False, constant=0.0, warmup_passes=0)
    state, oracle = _run_pair(cfg, n_passes=40)
    # every pass, every param, both neighbors
    assert int(state.num_events) == 40 * 6 * 2 == oracle.num_events


@pytest.mark.parametrize("seed", [5, 6])
def test_max_silence_matches_oracle(seed):
    """The bounded-staleness bound composes with the adaptive threshold
    identically in the fused pytree version and the scalar-loop twin —
    including an aggressive horizon > 1 where the bound actually binds."""
    cfg = EventConfig(adaptive=True, horizon=1.05, warmup_passes=5,
                      history=2, max_silence=12)
    state, oracle = _run_pair(cfg, seed=seed)
    assert int(state.num_events) == oracle.num_events
    for i in range(6):
        np.testing.assert_allclose(float(state.thres[i]), oracle.thres[i], rtol=1e-5)
        np.testing.assert_allclose(
            float(state.last_sent_iter[i]), oracle.last_sent_iter[i]
        )
