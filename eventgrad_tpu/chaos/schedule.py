"""Seeded, fully reproducible fault schedules for the gossip path.

A schedule is pure data: it names WHAT faults happen WHEN, never how they
are applied (that is `chaos.inject`). Everything is deterministic in
(seed, pass, receiver rank, edge index), so two runs of the same schedule
see bit-identical faults, and a schedule serialized into a bench record
replays exactly.

Fault vocabulary (all composable):

  * `drop_p`       — iid per-edge per-pass message-drop probability.
  * `flaky`        — windows `[start_pass, end_pass)` during which the
                     drop probability is raised to `max(drop_p, window p)`
                     (a link that flakes hard for a while, then recovers).
  * `deliver_every`— k-pass delivery THINNING (`delay=k`): an edge
                     refreshes its receive buffer at most every k passes
                     (per-edge phase derived from the seed), i.e.
                     staleness up to k-1 extra passes. NOT a true
                     queueing delay: the payloads of the skipped passes
                     are gone forever (the receiver next sees the
                     CURRENT pass's values, never the missed ones).
                     Kept for drop-like staleness studies; the true
                     queueing-delay vocabulary is `lag=`/`slow=` below,
                     which the bounded-async engine (train(staleness=D),
                     D >= 2) services with real per-edge delivery
                     queues — each in-flight payload is committed on
                     arrival, D passes deep. The two compose but model
                     different faults: `delay=` is a lossy slow link,
                     `lag=` a lossless late one.
  * `lag`          — QUEUEING DELAY window: `lag=S-E@d` makes every
                     message sent during passes [S, E) arrive d passes
                     after its send (d >= 1; the no-fault baseline is
                     lag 1 — the one-pass RMA asynchrony staleness=1
                     already models). Deterministic, no random draws.
                     Under bounded-async runs (train(staleness=D >= 2))
                     the payload is queued per edge and committed on
                     arrival; the effective lag is clamped to the bound
                     D (the fast rank WAITS rather than run further
                     ahead — that wait is what tools/straggler_ablation
                     charges the lockstep for). Under staleness <= 1
                     the clamp makes it a no-op in-step: the run is
                     already synchronous, and the scheduled lag shows up
                     only in the modeled wall-clock.
  * `slow`         — PERSISTENT STRAGGLER: `slow=R@f` makes every
                     message SENT by rank R arrive f passes late for
                     the whole run (f >= 1) — the heterogeneous-fleet
                     fault one slow host injects into a bulk-
                     synchronous ring. Composes with `lag=` windows by
                     max; same bound-clamp semantics.
  * `death`        — permanent peer death at pass T: from T on, the rank
                     neither sends nor receives (every edge touching it is
                     masked). Recovery is `policy.heal_ring`. NOT
                     composable with membership events below: death is
                     rank-indexed inside the traced step and a
                     transition re-indexes the rows (train() rejects
                     the combination — script the removal as `leave=`).
  * `bitflip`      — WIRE CORRUPTION: per-edge per-pass probability that
                     one bit of the received gossip payload flips in
                     transit (a lying peer / a bad link, as opposed to a
                     silent one). Windowed like flaky (`bitflip=S-E@p`;
                     bare `bitflip=p` corrupts for the whole run). The
                     defense is the integrity engine's wire checksums
                     (chaos/integrity.py): a failed check is treated
                     exactly as not-fired. Event-exchange (eventgrad)
                     runs only — the corruption rides the masked/compact
                     wire buffer.
  * `nanstep`      — SICK RANK: `nanstep=R@P` poisons rank R's gradients
                     with NaN on pass P (an overflowed loss, a bad batch,
                     a kernel bug). The defense is the integrity engine's
                     non-finite quarantine: the rank skips its update and
                     suppresses its sends for that step. Clauses
                     accumulate.
  * `preempt`      — GRACEFUL PREEMPTION notice (chaos/crashpoint.py):
                     `preempt=E@S` simulates the platform's "you have
                     been preempted" signal arriving during epoch E at
                     step S. Host-side like membership (never inside
                     the traced step): the training loop drains at the
                     enclosing dispatch-block boundary — pipeline
                     drained, writer joined, force-snapshot, PREEMPTED
                     marker — and exits `exitcodes.PREEMPTED_EXIT`, so
                     the ≤-one-block loss bound is measurable
                     deterministically (tools/crash_matrix.py). Clauses
                     accumulate: later ones fire in later incarnations
                     (a resume ignores notices at or before its start
                     epoch).
  * `leave`/`join` — MEMBERSHIP events (chaos/membership.py): unlike the
                     wire faults above they are keyed by EPOCH, applied
                     between jit dispatch blocks on the host (a rank
                     leaves cleanly / a newcomer bootstraps in), never
                     inside the traced step. `leave=1@3` removes rank 1
                     after epoch 3; `join=1@5[:SRC]` inserts a newcomer
                     at position 1 after epoch 5 (bootstrap source SRC,
                     default the left neighbor). train() routes them to
                     the MembershipEngine.

CLI spec grammar (comma-separated clauses, see `parse`):

    drop=0.2,seed=7,flaky=100-200@0.8,delay=3,die=3@500,leave=1@3,join=1@5,
    bitflip=40-60@0.5,nanstep=2@45,preempt=6@2,lag=50-90@3,slow=2@4

Multiple `flaky=` / `die=` / `leave=` / `join=` / `bitflip=` /
`nanstep=` / `preempt=` / `lag=` / `slow=` clauses accumulate.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple


@dataclasses.dataclass(frozen=True)
class FlakyWindow:
    """Drop probability raised to `drop_p` for passes in [start, end)."""

    start_pass: int
    end_pass: int
    drop_p: float = 1.0

    def __post_init__(self):
        if self.start_pass < 0 or self.end_pass < self.start_pass:
            raise ValueError(
                f"flaky window [{self.start_pass}, {self.end_pass}) invalid"
            )
        if not 0.0 <= self.drop_p <= 1.0:
            raise ValueError(f"flaky drop_p {self.drop_p} outside [0, 1]")


@dataclasses.dataclass(frozen=True)
class LagWindow:
    """Messages sent during passes [start, end) arrive `lag` passes after
    their send (a true queueing delay — the payload is preserved and
    committed on arrival, unlike `deliver_every`'s thinning)."""

    start_pass: int
    end_pass: int
    lag: int

    def __post_init__(self):
        if self.start_pass < 0 or self.end_pass < self.start_pass:
            raise ValueError(
                f"lag window [{self.start_pass}, {self.end_pass}) invalid"
            )
        if self.lag < 1:
            raise ValueError(
                f"lag {self.lag} invalid: delivery lag is >= 1 pass "
                "(lag 1 is the no-fault one-pass asynchrony baseline)"
            )


@dataclasses.dataclass(frozen=True)
class ChaosSchedule:
    """A replayable fault schedule. `death` is ((rank, pass), ...) pairs;
    `membership` holds epoch-keyed join/leave events (membership.py
    `MembershipEvent` tuples) that train() hands to the
    MembershipEngine — they never enter the traced step."""

    seed: int = 0
    drop_p: float = 0.0
    flaky: Tuple[FlakyWindow, ...] = ()
    deliver_every: int = 1
    death: Tuple[Tuple[int, int], ...] = ()
    membership: Tuple[Any, ...] = ()
    #: wire-corruption windows: FlakyWindow tuples whose drop_p is the
    #: per-edge per-pass BITFLIP probability (one flipped payload bit)
    bitflip: Tuple[FlakyWindow, ...] = ()
    #: gradient-poison events: ((rank, pass), ...) — rank's grads go NaN
    nanstep: Tuple[Tuple[int, int], ...] = ()
    #: graceful-preemption notices: ((epoch, step), ...) — host-side
    #: like membership; the loop drains at the enclosing block boundary
    preempt: Tuple[Tuple[int, int], ...] = ()
    #: queueing-delay windows (LagWindow tuples): messages sent in the
    #: window arrive `lag` passes late, payload preserved — serviced by
    #: the bounded-async engine (train(staleness=D >= 2))
    lag: Tuple[LagWindow, ...] = ()
    #: persistent stragglers: ((rank, lag), ...) — every message rank R
    #: SENDS arrives `lag` passes late for the whole run
    slow: Tuple[Tuple[int, int], ...] = ()

    def __post_init__(self):
        if not 0.0 <= self.drop_p <= 1.0:
            raise ValueError(f"drop_p {self.drop_p} outside [0, 1]")
        if self.deliver_every < 1:
            raise ValueError(f"deliver_every must be >= 1, got {self.deliver_every}")
        # normalize so equal schedules compare/serialize equal
        object.__setattr__(
            self, "flaky",
            tuple(sorted(self.flaky, key=lambda w: (w.start_pass, w.end_pass))),
        )
        object.__setattr__(self, "death", tuple(sorted(self.death)))
        for r, t in self.death:
            if r < 0 or t < 0:
                raise ValueError(f"death ({r}, {t}) invalid")
        object.__setattr__(
            self, "membership",
            tuple(sorted(self.membership, key=lambda e: e.epoch)),
        )
        object.__setattr__(
            self, "bitflip",
            tuple(sorted(self.bitflip, key=lambda w: (w.start_pass, w.end_pass))),
        )
        object.__setattr__(self, "nanstep", tuple(sorted(self.nanstep)))
        for r, t in self.nanstep:
            if r < 0 or t < 0:
                raise ValueError(f"nanstep ({r}, {t}) invalid")
        object.__setattr__(self, "preempt", tuple(sorted(self.preempt)))
        for e, s in self.preempt:
            if e < 1 or s < 1:
                raise ValueError(
                    f"preempt ({e}, {s}) invalid: epoch and step are "
                    "1-based"
                )
        object.__setattr__(
            self, "lag",
            tuple(sorted(self.lag, key=lambda w: (w.start_pass, w.end_pass))),
        )
        object.__setattr__(self, "slow", tuple(sorted(self.slow)))
        for r, f in self.slow:
            if r < 0 or f < 1:
                raise ValueError(
                    f"slow ({r}, {f}) invalid: rank >= 0 and lag >= 1 "
                    "(lag 1 is the no-fault asynchrony baseline)"
                )

    @property
    def is_noop(self) -> bool:
        """True when the schedule injects nothing (the drop-rate-0 regression
        point: the trajectory must be bitwise-identical to chaos=None).
        Membership events count: a transition changes the trajectory even
        with zero wire faults."""
        return (
            self.drop_p == 0.0
            and not self.flaky
            and self.deliver_every == 1
            and not self.death
            and not self.membership
            and not self.bitflip
            and not self.nanstep
            and not self.preempt
            and not self.lag
            and not self.slow
        )

    @property
    def has_lags(self) -> bool:
        """True when any clause can deliver a message late (the
        bounded-async engine then services per-edge delivery queues;
        lockstep runs see only the modeled wall-clock cost)."""
        return bool(self.lag or self.slow)

    def max_scheduled_lag(self) -> int:
        """The largest lag any clause can schedule (1 = the no-fault
        asynchrony baseline) — the straggler ablation's unclamped f."""
        m = 1
        for w in self.lag:
            m = max(m, w.lag)
        for _, f in self.slow:
            m = max(m, f)
        return m

    @property
    def has_bitflips(self) -> bool:
        """True when any pass could corrupt a payload (the step then
        threads the corruption transform into the exchange)."""
        return any(w.drop_p > 0.0 for w in self.bitflip)

    @property
    def has_nansteps(self) -> bool:
        return bool(self.nanstep)

    def membership_schedule(self):
        """The epoch-keyed join/leave events as a MembershipSchedule (for
        the MembershipEngine); empty events -> an is_noop schedule."""
        from eventgrad_tpu.chaos.membership import MembershipSchedule

        return MembershipSchedule(seed=self.seed, events=self.membership)

    def dead_ranks(self, up_to_pass: int) -> Tuple[int, ...]:
        """Ranks whose death pass is <= `up_to_pass` (host-side helper for
        heal decisions and survivor-consensus evaluation)."""
        return tuple(sorted({r for r, t in self.death if t <= up_to_pass}))

    # --- serialization (bench records / artifacts) ---------------------

    def to_dict(self) -> Dict[str, Any]:
        d = {
            "seed": self.seed,
            "drop_p": self.drop_p,
            "flaky": [
                [w.start_pass, w.end_pass, w.drop_p] for w in self.flaky
            ],
            "deliver_every": self.deliver_every,
            "death": [list(d) for d in self.death],
        }
        if self.membership:  # absent = legacy schedules round-trip unchanged
            d["membership"] = self.membership_schedule().to_dict()["events"]
        if self.bitflip:  # absent = legacy schedules round-trip unchanged
            d["bitflip"] = [
                [w.start_pass, w.end_pass, w.drop_p] for w in self.bitflip
            ]
        if self.nanstep:
            d["nanstep"] = [list(e) for e in self.nanstep]
        if self.preempt:
            d["preempt"] = [list(e) for e in self.preempt]
        if self.lag:  # absent = legacy schedules round-trip unchanged
            d["lag"] = [
                [w.start_pass, w.end_pass, w.lag] for w in self.lag
            ]
        if self.slow:
            d["slow"] = [list(e) for e in self.slow]
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ChaosSchedule":
        membership = ()
        if d.get("membership"):
            from eventgrad_tpu.chaos.membership import MembershipSchedule

            membership = MembershipSchedule.from_dict(
                {"events": d["membership"]}
            ).events
        return cls(
            seed=int(d.get("seed", 0)),
            drop_p=float(d.get("drop_p", 0.0)),
            flaky=tuple(
                FlakyWindow(int(s), int(e), float(p))
                for s, e, p in d.get("flaky", ())
            ),
            deliver_every=int(d.get("deliver_every", 1)),
            death=tuple(
                (int(r), int(t)) for r, t in d.get("death", ())
            ),
            membership=membership,
            bitflip=tuple(
                FlakyWindow(int(s), int(e), float(p))
                for s, e, p in d.get("bitflip", ())
            ),
            nanstep=tuple(
                (int(r), int(t)) for r, t in d.get("nanstep", ())
            ),
            preempt=tuple(
                (int(e), int(s)) for e, s in d.get("preempt", ())
            ),
            lag=tuple(
                LagWindow(int(s), int(e), int(f))
                for s, e, f in d.get("lag", ())
            ),
            slow=tuple(
                (int(r), int(f)) for r, f in d.get("slow", ())
            ),
        )

    # --- CLI spec round trip -------------------------------------------

    def to_spec(self) -> str:
        parts = [f"drop={self.drop_p:g}", f"seed={self.seed}"]
        for w in self.flaky:
            parts.append(f"flaky={w.start_pass}-{w.end_pass}@{w.drop_p:g}")
        if self.deliver_every != 1:
            parts.append(f"delay={self.deliver_every}")
        for r, t in self.death:
            parts.append(f"die={r}@{t}")
        for w in self.bitflip:
            parts.append(f"bitflip={w.start_pass}-{w.end_pass}@{w.drop_p:g}")
        for r, t in self.nanstep:
            parts.append(f"nanstep={r}@{t}")
        for e, s in self.preempt:
            parts.append(f"preempt={e}@{s}")
        for w in self.lag:
            parts.append(f"lag={w.start_pass}-{w.end_pass}@{w.lag}")
        for r, f in self.slow:
            parts.append(f"slow={r}@{f}")
        if self.membership:
            from eventgrad_tpu.chaos.membership import format_event_clause

            parts += [format_event_clause(e) for e in self.membership]
        return ",".join(parts)

    @classmethod
    def parse(cls, spec: str) -> "ChaosSchedule":
        """Parse the CLI grammar, e.g. `drop=0.2,seed=7,flaky=10-20@0.8`."""
        kw: Dict[str, Any] = {
            "flaky": [], "death": [], "membership": [], "bitflip": [],
            "nanstep": [], "preempt": [], "lag": [], "slow": [],
        }
        for clause in spec.split(","):
            clause = clause.strip()
            if not clause:
                continue
            key, sep, val = clause.partition("=")
            if not sep:
                raise ValueError(
                    f"bad chaos clause {clause!r} (expected key=value)"
                )
            try:
                if key == "drop":
                    kw["drop_p"] = float(val)
                elif key == "seed":
                    kw["seed"] = int(val)
                elif key == "delay":
                    kw["deliver_every"] = int(val)
                elif key == "flaky":
                    span, _, p = val.partition("@")
                    s, _, e = span.partition("-")
                    kw["flaky"].append(
                        FlakyWindow(int(s), int(e), float(p) if p else 1.0)
                    )
                elif key == "die":
                    r, _, t = val.partition("@")
                    kw["death"].append((int(r), int(t)))
                elif key == "bitflip":
                    # a bare probability corrupts the whole run — tried
                    # FIRST so scientific notation (`bitflip=1e-3`, the
                    # natural spell for realistic flip rates) is not
                    # misread as a `S-E` pass range by its '-'
                    try:
                        p_whole = float(val)
                    except ValueError:
                        p_whole = None
                    if p_whole is not None:
                        kw["bitflip"].append(
                            FlakyWindow(0, 2**31 - 1, p_whole)
                        )
                    else:  # windowed like flaky: `bitflip=S-E@p`
                        span, _, p = val.partition("@")
                        s, _, e = span.partition("-")
                        kw["bitflip"].append(
                            FlakyWindow(int(s), int(e), float(p) if p else 1.0)
                        )
                elif key == "nanstep":
                    r, _, t = val.partition("@")
                    kw["nanstep"].append((int(r), int(t)))
                elif key == "lag":
                    # queueing-delay window `lag=S-E@d` (bare `lag=d`
                    # delays the whole run)
                    span, sep_at, f = val.partition("@")
                    if sep_at:
                        s, _, e = span.partition("-")
                        kw["lag"].append(LagWindow(int(s), int(e), int(f)))
                    else:
                        kw["lag"].append(LagWindow(0, 2**31 - 1, int(val)))
                elif key == "slow":
                    r, _, f = val.partition("@")
                    kw["slow"].append((int(r), int(f)))
                elif key == "preempt":
                    # `preempt=E@S`; a bare `preempt=E` means step 1
                    # (the notice arrives as epoch E opens)
                    e, _, s = val.partition("@")
                    kw["preempt"].append((int(e), int(s) if s else 1))
                elif key in ("leave", "join"):
                    from eventgrad_tpu.chaos.membership import (
                        parse_event_clause,
                    )

                    kw["membership"].append(parse_event_clause(key, val))
                else:
                    raise ValueError(f"unknown chaos key {key!r}")
            except ValueError as err:
                raise ValueError(
                    f"bad chaos clause {clause!r}: {err}"
                ) from None
        kw["flaky"] = tuple(kw["flaky"])
        kw["death"] = tuple(kw["death"])
        kw["membership"] = tuple(kw["membership"])
        kw["bitflip"] = tuple(kw["bitflip"])
        kw["nanstep"] = tuple(kw["nanstep"])
        kw["preempt"] = tuple(kw["preempt"])
        kw["lag"] = tuple(kw["lag"])
        kw["slow"] = tuple(kw["slow"])
        return cls(**kw)


def resolve(chaos) -> "ChaosSchedule":
    """Accept a ChaosSchedule, a spec string, or a serialized dict — the one
    coercion used by train(), the CLI, and the sweep tool."""
    if isinstance(chaos, ChaosSchedule):
        return chaos
    if isinstance(chaos, str):
        return ChaosSchedule.parse(chaos)
    if isinstance(chaos, dict):
        return ChaosSchedule.from_dict(chaos)
    raise TypeError(
        f"chaos must be a ChaosSchedule, spec string, or dict; got {type(chaos)}"
    )
