"""Pallas TPU kernel: FlashAttention-2 style fused attention (fwd + bwd).

The Transformer/SP stack (beyond-reference capability; the reference trains
only image CNNs) spends its FLOPs in attention. The jnp paths in
`parallel/ring_attention.py` materialize [B,H,Tq,Tk] score tensors in HBM;
these kernels stream one (128, D) K/V tile through VMEM per grid step with
the online-softmax recurrence, so scores never leave the chip and VMEM
residency is O(block), not O(T):

    forward:  grid (B, H, nQ, nK) — TPU iterates the last grid dimension
              sequentially, so (m, l, acc) live in VMEM scratch across the
              nK sweep; the output block and logsumexp are written on the
              final K step. Causal Q/K block pairs above the diagonal are
              skipped with pl.when.
    backward: recomputation-style FlashAttention-2 — a dQ kernel sweeping
              KV blocks and a dK/dV kernel sweeping Q blocks, same
              scratch-accumulator pattern; the score matrix is rebuilt
              from (q, k, lse) one tile at a time.

Layout contract matches the models: q/k/v are [B, T, H, D] (self-attention:
all three share T). Internally heads move next to batch ([B, H, T, D]), T is
padded to a multiple of the 128-row block and D to a multiple of the
128-lane tile; padded K columns are masked, padded Q rows are sliced off
(their dK/dV contributions vanish because the padded dOut rows are zero).

`interpret=None` auto-selects the Pallas interpreter off-TPU, so the same
code path runs in the CPU-mesh test harness and compiled on real chips.
`flash_attention_reference` (= `parallel.ring_attention.full_attention`)
is the materialized-score twin used by tests.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from eventgrad_tpu.ops import flash_tuning
from eventgrad_tpu.parallel.ring_attention import full_attention

try:  # TPU memory spaces only exist on TPU builds; interpret mode elsewhere
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_LANES = 128
#: default Q/KV block rows; per-shape winners come from flash_tuning.plan
#: (Q and KV share one block size: the causal revisit/skip index maps
#: assume a square block diagonal)
_BLOCK = 128
_NEG_INF = -1e30  # finite mask value; exact zeros guaranteed by masking p

flash_attention_reference = full_attention


def _spec(block_shape, index_map, interpret):
    kw = {} if (interpret or _VMEM is None) else {"memory_space": _VMEM}
    return pl.BlockSpec(block_shape, index_map, **kw)


def _any_scratch(shape):
    return pltpu.VMEM(shape, jnp.float32)


def _compiler_params(interpret):
    """B/H/Q grid dims are independent (megacore-partitionable); only the
    innermost accumulation sweep is sequential."""
    if interpret:
        return {}
    return {
        "compiler_params": pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")
        )
    }


def _causal_kv_index(causal):
    """K/V block index for fwd/dq grid step (qi, kj). For causal steps above
    the diagonal (skipped by pl.when) revisit block qi instead: Pallas elides
    the DMA when the block index repeats, halving causal HBM traffic."""
    if causal:
        return lambda b, h, i, j: (b, h, jnp.minimum(j, i), 0)
    return lambda b, h, i, j: (b, h, j, 0)


def _causal_q_index(causal):
    """Q-side block index for the dkv grid step (kj, qi innermost): causal
    steps with qi < kj are skipped, so revisit block kj there."""
    if causal:
        return lambda b, h, j, i: (b, h, jnp.maximum(i, j), 0)
    return lambda b, h, j, i: (b, h, i, 0)


def _block_mask(qi, kj, t_real_k, causal, q_off=0, k_off=0, block=_BLOCK):
    """Validity of score block (qi, kj). The padding mask is in local
    coordinates; the causal comparison adds the global offsets (ring hops
    pass the rank origins of the resident Q and K shards)."""
    qpos = qi * block + lax.broadcasted_iota(jnp.int32, (block, block), 0)
    kpos = kj * block + lax.broadcasted_iota(jnp.int32, (block, block), 1)
    valid = kpos < t_real_k
    if causal:
        valid &= (q_off + qpos) >= (k_off + kpos)
    return valid


def _unpack(args, n_scratch, has_offsets):
    """Split pallas kernel args into (offs_ref|None, io_refs, scratch_refs)."""
    scratch = args[len(args) - n_scratch:]
    io = args[: len(args) - n_scratch]
    if has_offsets:
        return io[0], io[1:], scratch
    return None, io, scratch


def _dot(a, b, trans=False):
    dims = (((1,), (1,)), ((), ())) if trans else (((1,), (0,)), ((), ()))
    return jax.lax.dot_general(a, b, dims, preferred_element_type=jnp.float32)


def _fwd_kernel(*args, scale, causal, t_real, nk, has_offsets, block):
    offs_ref, (q_ref, k_ref, v_ref, o_ref, lse_ref), (m_s, l_s, a_s) = _unpack(
        args, 3, has_offsets
    )
    q_off = offs_ref[0, 0] if has_offsets else 0
    k_off = offs_ref[0, 1] if has_offsets else 0
    qi, kj = pl.program_id(2), pl.program_id(3)

    @pl.when(kj == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s[...], _NEG_INF)
        l_s[...] = jnp.zeros_like(l_s[...])
        a_s[...] = jnp.zeros_like(a_s[...])

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        s = _dot(q, k, trans=True)  # [bq, bk]
        valid = _block_mask(qi, kj, t_real, causal, q_off, k_off, block)
        s = jnp.where(valid, s, _NEG_INF)
        m_prev, l_prev = m_s[...], l_s[...]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new) * valid  # masked p is exactly 0
        corr = jnp.exp(m_prev - m_new)
        m_s[...] = m_new
        l_s[...] = l_prev * corr + p.sum(axis=-1, keepdims=True)
        a_s[...] = a_s[...] * corr + _dot(p, v)

    if causal and not has_offsets:  # skip KV blocks above the diagonal
        pl.when(kj <= qi)(_compute)  # square blocks: index compare suffices
    else:  # offset diagonals are dynamic: mask handles everything
        _compute()

    @pl.when(kj == nk - 1)
    def _finalize():
        l_safe = jnp.maximum(l_s[...], 1e-30)
        o_ref[0, 0] = (a_s[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = m_s[...] + jnp.log(l_safe)


def _dq_kernel(*args, scale, causal, t_real, nk, has_offsets, block):
    offs_ref, (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref), (dq_s,) = (
        _unpack(args, 1, has_offsets)
    )
    q_off = offs_ref[0, 0] if has_offsets else 0
    k_off = offs_ref[0, 1] if has_offsets else 0
    qi, kj = pl.program_id(2), pl.program_id(3)

    @pl.when(kj == 0)
    def _init():
        dq_s[...] = jnp.zeros_like(dq_s[...])

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse, delta = lse_ref[0, 0], delta_ref[0, 0]  # [bq, 1]
        s = _dot(q, k, trans=True)
        valid = _block_mask(qi, kj, t_real, causal, q_off, k_off, block)
        s = jnp.where(valid, s, _NEG_INF)
        p = jnp.exp(s - lse) * valid
        dp = _dot(do, v, trans=True)
        ds = p * (dp - delta) * scale
        dq_s[...] += _dot(ds, k)

    if causal and not has_offsets:
        pl.when(kj <= qi)(_compute)
    else:
        _compute()

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_s[...].astype(dq_ref.dtype)


def _dkv_kernel(*args, scale, causal, t_real, nq, has_offsets, block):
    (
        offs_ref,
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref),
        (dk_s, dv_s),
    ) = _unpack(args, 2, has_offsets)
    q_off = offs_ref[0, 0] if has_offsets else 0
    k_off = offs_ref[0, 1] if has_offsets else 0
    kj, qi = pl.program_id(2), pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_s[...] = jnp.zeros_like(dk_s[...])
        dv_s[...] = jnp.zeros_like(dv_s[...])

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse, delta = lse_ref[0, 0], delta_ref[0, 0]  # [bq, 1]
        s = scale * _dot(q, k, trans=True)  # [bq, bk]
        valid = _block_mask(qi, kj, t_real, causal, q_off, k_off, block)
        s = jnp.where(valid, s, _NEG_INF)
        p = jnp.exp(s - lse) * valid
        dv_s[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = _dot(do, v, trans=True)
        ds = p * (dp - delta) * scale
        dk_s[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal and not has_offsets:
        # Q blocks strictly before this KV block contribute nothing
        pl.when(qi >= kj)(_compute)
    else:
        _compute()

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_s[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_s[...].astype(dv_ref.dtype)


def _pad_to(x, t_pad, d_pad):
    b, h, t, d = x.shape
    return jnp.pad(x, ((0, 0), (0, 0), (0, t_pad - t), (0, d_pad - d)))


def _dims(t, d, block):
    t_pad = max(block, -(-t // block) * block)
    d_pad = max(_LANES, -(-d // _LANES) * _LANES)
    return t_pad, d_pad, t_pad // block


def _offs_spec(interpret):
    """(1, 2) int32 [q_offset, k_offset] — scalar memory on real TPU."""
    kw = {}
    if not interpret and pltpu is not None:
        kw["memory_space"] = pltpu.SMEM
    return pl.BlockSpec((1, 2), lambda b_, h_, i, j: (0, 0), **kw)


def _run_fwd(q, k, v, causal, interpret, offsets=None, block=_BLOCK):
    """q/k/v: [B, H, T, D] (already transposed). Returns (out, lse [B,H,T,1]).

    offsets: traced (1, 2) int32 [q_offset, k_offset] shifting the causal
    mask to global positions (ring attention hops), or None."""
    b, h, t, d = q.shape
    t_pad, d_pad, n = _dims(t, d, block)
    qp, kp, vp = (_pad_to(x, t_pad, d_pad) for x in (q, k, v))
    scale = 1.0 / float(d) ** 0.5
    has_offs = offsets is not None

    q_blk = _spec((1, 1, block, d_pad), lambda b_, h_, i, j: (b_, h_, i, 0), interpret)
    kv_blk = _spec(
        (1, 1, block, d_pad), _causal_kv_index(causal and not has_offs), interpret
    )
    row_blk = _spec((1, 1, block, 1), lambda b_, h_, i, j: (b_, h_, i, 0), interpret)
    in_specs = [q_blk, kv_blk, kv_blk]
    operands = [qp, kp, vp]
    if has_offs:
        in_specs.insert(0, _offs_spec(interpret))
        operands.insert(0, offsets)
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, scale=scale, causal=causal, t_real=t, nk=n,
            has_offsets=has_offs, block=block,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b, h, t_pad, d_pad), q.dtype),
            jax.ShapeDtypeStruct((b, h, t_pad, 1), jnp.float32),
        ),
        grid=(b, h, n, n),
        in_specs=in_specs,
        out_specs=(q_blk, row_blk),
        scratch_shapes=[
            _any_scratch((block, 1)),
            _any_scratch((block, 1)),
            _any_scratch((block, d_pad)),
        ],
        interpret=interpret,
        **_compiler_params(interpret),
    )(*operands)
    return out[:, :, :t, :d], lse[:, :, :t, :]


def _run_bwd(q, k, v, out, lse, do, causal, interpret, offsets=None, dlse=None,
             block=_BLOCK):
    """FA2 backward. dlse (cotangent of the logsumexp output, [B,H,T,1])
    folds into the delta term: ds = p * (dp - (delta - dlse))."""
    b, h, t, d = q.shape
    t_pad, d_pad, n = _dims(t, d, block)
    qp, kp, vp, op, dop = (_pad_to(x, t_pad, d_pad) for x in (q, k, v, out, do))
    lsep = jnp.pad(lse, ((0, 0), (0, 0), (0, t_pad - t), (0, 0)))
    scale = 1.0 / float(d) ** 0.5
    has_offs = offsets is not None
    delta = (dop.astype(jnp.float32) * op.astype(jnp.float32)).sum(-1, keepdims=True)
    if dlse is not None:
        delta = delta - jnp.pad(
            dlse.astype(jnp.float32), ((0, 0), (0, 0), (0, t_pad - t), (0, 0))
        )
    skip = causal and not has_offs

    q_blk = _spec((1, 1, block, d_pad), lambda b_, h_, i, j: (b_, h_, i, 0), interpret)
    kv_blk = _spec((1, 1, block, d_pad), _causal_kv_index(skip), interpret)
    row_q = _spec((1, 1, block, 1), lambda b_, h_, i, j: (b_, h_, i, 0), interpret)
    dq_specs = [q_blk, kv_blk, kv_blk, q_blk, row_q, row_q]
    dq_ops = [qp, kp, vp, dop, lsep, delta]
    if has_offs:
        dq_specs.insert(0, _offs_spec(interpret))
        dq_ops.insert(0, offsets)
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal, t_real=t, nk=n,
            has_offsets=has_offs, block=block,
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, t_pad, d_pad), q.dtype),
        grid=(b, h, n, n),
        in_specs=dq_specs,
        out_specs=q_blk,
        scratch_shapes=[_any_scratch((block, d_pad))],
        interpret=interpret,
        **_compiler_params(interpret),
    )(*dq_ops)

    # grid order (..., kv-block, q-block): the Q sweep is innermost
    kv_outer = _spec((1, 1, block, d_pad), lambda b_, h_, j, i: (b_, h_, j, 0), interpret)
    q_inner = _spec((1, 1, block, d_pad), _causal_q_index(skip), interpret)
    row_inner = _spec((1, 1, block, 1), _causal_q_index(skip), interpret)
    dkv_specs = [q_inner, kv_outer, kv_outer, q_inner, row_inner, row_inner]
    dkv_ops = [qp, kp, vp, dop, lsep, delta]
    if has_offs:
        dkv_specs.insert(0, _offs_spec(interpret))
        dkv_ops.insert(0, offsets)
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal, t_real=t, nq=n,
            has_offsets=has_offs, block=block,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b, h, t_pad, d_pad), k.dtype),
            jax.ShapeDtypeStruct((b, h, t_pad, d_pad), v.dtype),
        ),
        grid=(b, h, n, n),
        in_specs=dkv_specs,
        out_specs=(kv_outer, kv_outer),
        scratch_shapes=[_any_scratch((block, d_pad)), _any_scratch((block, d_pad))],
        interpret=interpret,
        **_compiler_params(interpret),
    )(*dkv_ops)
    cut = lambda x: x[:, :, :t, :d]
    return cut(dq), cut(dk), cut(dv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_bhtd(q, k, v, causal, interpret, block):
    out, _ = _run_fwd(q, k, v, causal, interpret, block=block)
    return out


def _flash_fwd(q, k, v, causal, interpret, block):
    out, lse = _run_fwd(q, k, v, causal, interpret, block=block)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, interpret, block, res, do):
    q, k, v, out, lse = res
    # do stays in its incoming (usually f32) dtype: kernels upcast anyway,
    # and truncating the cotangent to a bf16 q.dtype would lose precision
    dq, dk, dv = _run_bwd(q, k, v, out, lse, do, causal, interpret, block=block)
    return dq, dk, dv


_flash_bhtd.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_lse_bhtd(q, k, v, offs, causal, interpret, block):
    return _run_fwd(q, k, v, causal, interpret, offsets=offs, block=block)


def _flash_lse_fwd(q, k, v, offs, causal, interpret, block):
    out, lse = _run_fwd(q, k, v, causal, interpret, offsets=offs, block=block)
    return (out, lse), (q, k, v, offs, out, lse)


def _flash_lse_bwd(causal, interpret, block, res, cts):
    q, k, v, offs, out, lse = res
    do, dlse = cts
    dq, dk, dv = _run_bwd(
        q, k, v, out, lse, do, causal, interpret, offsets=offs, dlse=dlse,
        block=block,
    )
    d_offs = np.zeros(offs.shape, jax.dtypes.float0)  # int operand: no tangent
    return dq, dk, dv, d_offs


_flash_lse_bhtd.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def _resolve_block(t: int, block) -> int:
    """Static block-rows choice for sequence length t: explicit argument >
    EG_FLASH_BLOCK env override > flash_tuning table > default."""
    if block is not None:
        return int(block)
    env = flash_tuning.override()
    if env is not None:
        return env
    _, blk = flash_tuning.plan(t, "fwd_bwd")
    return blk


def flash_attention_lse(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    q_offset=0,
    k_offset=0,
    interpret: Optional[bool] = None,
    block: Optional[int] = None,
):
    """Fused attention returning (out [B,T,H,D], logsumexp [B,T,H]).

    The lse output makes partial results mergeable with the online-softmax
    combine rule — ring attention computes each KV hop through this kernel
    and folds the hops together (parallel/ring_attention.py). q_offset and
    k_offset (traced ints) shift the causal mask to global sequence
    positions: hop blocks are fully-visible, diagonal, or fully-masked
    depending on the ranks' relative positions. Differentiable in q/k/v,
    including through lse (the dlse cotangent folds into the delta term of
    the FA2 backward)."""
    if not (q.shape == k.shape == v.shape):
        raise ValueError(
            f"flash_attention_lse: q/k/v shapes must match, got "
            f"{q.shape}, {k.shape}, {v.shape}"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    offs = jnp.stack(
        [jnp.asarray(q_offset, jnp.int32), jnp.asarray(k_offset, jnp.int32)]
    )[None, :]
    to_bhtd = lambda x: jnp.swapaxes(x, 1, 2)
    out, lse = _flash_lse_bhtd(
        to_bhtd(q), to_bhtd(k), to_bhtd(v), offs, causal, bool(interpret),
        _resolve_block(q.shape[1], block),
    )
    return to_bhtd(out), jnp.swapaxes(lse[..., 0], 1, 2)  # lse -> [B,T,H]


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    interpret: Optional[bool] = None,
    block: Optional[int] = None,
) -> jnp.ndarray:
    """Fused self-attention on [B, T, H, D] tensors (model layout).

    Differentiable (custom FA2 backward). q, k, v must share one sequence
    length. interpret=None auto-selects the Pallas interpreter off-TPU so
    tests run on the CPU mesh; on TPU the kernels compile to Mosaic.

    block=None consults ops/flash_tuning.py: measured per-shape winners
    (block size, and whether Pallas beats XLA at all for this T — if not,
    the materialized-score XLA path runs instead, VERDICT r2 item 4).
    """
    if not (q.shape == k.shape == v.shape):
        raise ValueError(
            f"flash_attention is self-attention: q/k/v shapes must match, "
            f"got {q.shape}, {k.shape}, {v.shape}"
        )
    if pltpu is None:  # no pallas-tpu module: kernels (incl. their VMEM
        return full_attention(q, k, v, causal=causal)  # scratch) can't build
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block is None and flash_tuning.override() is None:
        use_pallas, _ = flash_tuning.plan(q.shape[1], "fwd_bwd")
        if not use_pallas and not interpret:
            # measured loss for this shape on this chip: demote to XLA
            return full_attention(q, k, v, causal=causal)
    to_bhtd = lambda x: jnp.swapaxes(x, 1, 2)
    out = _flash_bhtd(
        to_bhtd(q), to_bhtd(k), to_bhtd(v), causal, bool(interpret),
        _resolve_block(q.shape[1], block),
    )
    return to_bhtd(out)
