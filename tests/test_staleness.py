"""Delayed (staleness-1) gossip: the deterministic model of the reference's
one-sided RMA asynchrony — a rank may read its window before the neighbor's
Put arrives (event.cpp:348-360 vs :399-438), so mixing uses the previous
step's received values; pass 1 averages the zero-initialized window
(event.cpp:177-179,469-471)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.spmd import spmd
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.loop import train
from eventgrad_tpu.train.state import init_train_state
from eventgrad_tpu.train.steps import _xent, make_train_step

N, LR = 4, 0.05


def _setup(staleness):
    topo = Ring(N)
    model = MLP()
    tx = optax.sgd(LR)
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=10)  # all fire
    state = init_train_state(model, (28, 28, 1), tx, topo, "eventgrad", cfg)
    step = make_train_step(model, tx, topo, "eventgrad", event_cfg=cfg,
                           staleness=staleness)
    lifted = jax.jit(spmd(step, topo))
    x, y = synthetic_dataset(N * 8, (28, 28, 1), seed=9)
    xb = jnp.asarray(x.reshape(N, 8, 28, 28, 1))
    yb = jnp.asarray(y.reshape(N, 8))
    return topo, model, state, lifted, xb, yb


def _manual_grads(model, params_r, xb_r, yb_r):
    def loss_fn(p):
        out = model.apply({"params": p}, xb_r, train=True,
                          rngs={"dropout": jax.random.PRNGKey(0)})
        if isinstance(out, tuple):
            out = out[0]
        return _xent(out, yb_r)

    return jax.grad(loss_fn)(params_r)


def test_step1_mixes_zero_window():
    """With staleness=1 the first step averages the zero-init buffers
    (p/3 on a ring) before SGD — the exact event.cpp:177-179,469-471 case."""
    topo, model, state, lifted, xb, yb = _setup(staleness=1)
    p0 = jax.tree.map(lambda a: np.asarray(a[0]), state.params)  # replicated
    new_state, _ = lifted(state, (xb, yb))

    for r in range(N):
        g = _manual_grads(model, jax.tree.map(jnp.asarray, p0),
                          xb[r], yb[r])
        expect = jax.tree.map(
            lambda p, gg: p / 3.0 - LR * np.asarray(gg), p0, g
        )
        got = jax.tree.map(lambda a, _r=r: np.asarray(a[_r]), new_state.params)
        for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(expect)):
            np.testing.assert_allclose(a, b, atol=1e-5)


def test_step2_uses_step1_buffers():
    """Step 2 must mix with the values exchanged AT step 1 (one-step-stale),
    not with step 2's own exchange."""
    topo, model, state, lifted, xb, yb = _setup(staleness=1)
    s1, _ = lifted(state, (xb, yb))
    bufs1 = jax.tree.map(np.asarray, s1.event.bufs)  # landed during step 1
    s2, _ = lifted(s1, (xb, yb))

    for r in range(N):
        p1_r = jax.tree.map(lambda a, _r=r: np.asarray(a[_r]), s1.params)
        g = _manual_grads(model, jax.tree.map(jnp.asarray, p1_r), xb[r], yb[r])
        expect = jax.tree.map(
            lambda p, bl, br, gg: (p + bl[r] + br[r]) / 3.0 - LR * np.asarray(gg),
            p1_r, bufs1[0], bufs1[1], g,
        )
        got = jax.tree.map(lambda a, _r=r: np.asarray(a[_r]), s2.params)
        for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(expect)):
            np.testing.assert_allclose(a, b, atol=1e-5)


def test_delayed_eventgrad_converges():
    x, y = synthetic_dataset(256, (28, 28, 1), seed=3)
    _, hist = train(
        MLP(), Ring(4), x, y, algo="eventgrad", epochs=4, batch_size=8,
        learning_rate=0.05,
        event_cfg=EventConfig(adaptive=True, horizon=0.9, warmup_passes=3),
        seed=0, log_every_epoch=False, staleness=1,
    )
    assert hist[-1]["loss"] < hist[0]["loss"]
    assert hist[-1]["msgs_saved_pct"] > 0


def test_staleness_guards():
    topo = Ring(4)
    with pytest.raises(ValueError, match="event"):
        make_train_step(MLP(), optax.sgd(0.1), topo, "dpsgd", staleness=1)
    with pytest.raises(ValueError, match="trace"):
        make_train_step(MLP(), optax.sgd(0.1), topo, "eventgrad",
                        staleness=1, trace=True)
