"""Flat-arena event engine: bitwise equivalence, cached leaf metadata,
and the op-count regression gate.

The arena path's contract (docs/ARCHITECTURE.md): the whole train step —
trigger, wire, buffer commit, mix, SGD — run over one contiguous
per-rank buffer is BITWISE the tree path, across algorithms, wire
dtypes, gossip wires, staleness, telemetry, and chaos delivery masks.
Leaf metadata (`_leaf_meta` / ArenaSpec / `compact_capacity_floor`) is
lru-cached per structure so no caller can re-derive it inside a traced
step. The jaxpr op-count budget rides the shared nested-jaxpr walker
(eventgrad_tpu/analysis/walker.py) — the same traversal the trace
auditor (analysis/audit.py, tests/test_audit.py) uses for its ravel
and hygiene checks, so the two gates can never drift apart.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from _spmd import requires_shard_map
from jax.flatten_util import ravel_pytree

from eventgrad_tpu.analysis import walker
from eventgrad_tpu.chaos import monitor as chaos_monitor
from eventgrad_tpu.chaos.schedule import ChaosSchedule
from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP
from eventgrad_tpu.obs import device as obs_device
from eventgrad_tpu.ops import arena_update, event_engine
from eventgrad_tpu.parallel import arena, collectives
from eventgrad_tpu.parallel.events import (
    EventConfig, EventState, capacity_gate, decide_and_update, propose,
)
from eventgrad_tpu.parallel.spmd import build_mesh, spmd, stack_for_ranks
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.state import init_train_state
from eventgrad_tpu.train.steps import make_train_step
from eventgrad_tpu.utils import trees

N_RANKS = 4
IN_SHAPE = (8, 8, 1)
PER_RANK = 4
#: leaf sizes (1024, 16, 160, 10) — a dominant kernel plus ragged tails,
#: the geometry the compact gate and the arena slicing both care about
MODEL = dict(hidden=16)
CFG = EventConfig(adaptive=True, horizon=0.95, warmup_passes=2,
                  max_silence=4)
#: fits Dense_0's kernel+bias but defers the second layer when all fire
CAPACITY = 1100


def _batches(n_steps, seed=0):
    x, y = synthetic_dataset(
        N_RANKS * PER_RANK * n_steps, IN_SHAPE, seed=seed
    )
    xb = jnp.asarray(
        x.reshape((n_steps, N_RANKS, PER_RANK) + IN_SHAPE)
    )
    yb = jnp.asarray(y.reshape((n_steps, N_RANKS, PER_RANK)))
    return [(xb[i], yb[i]) for i in range(n_steps)]


def _build(algo, arena_on, *, wire=None, gossip_wire="dense",
           capacity=None, staleness=0, obs=False, chaos=None,
           momentum=0.0, fused=None, backend="vmap"):
    topo = Ring(N_RANKS)
    model = MLP(**MODEL)
    tx = optax.sgd(0.05, momentum=momentum if momentum else None)
    state = init_train_state(
        model, IN_SHAPE, tx, topo, algo, CFG, seed=0, arena=arena_on
    )
    if chaos is not None:
        state = state.replace(
            chaos=stack_for_ranks(chaos_monitor.PeerHealth.init(topo), topo)
        )
    if obs:
        state = state.replace(
            telemetry=stack_for_ranks(
                obs_device.TelemetryState.init(
                    len(jax.tree.leaves(state.params)), topo.n_neighbors
                ),
                topo,
            )
        )
    step = make_train_step(
        model, tx, topo, algo, event_cfg=CFG, wire=wire,
        gossip_wire=gossip_wire, compact_capacity=capacity,
        staleness=staleness, obs=obs, chaos=chaos,
        fused_sgd=fused, arena=arena_on,
    )
    mesh = build_mesh(topo) if backend == "shard_map" else None
    lifted = jax.jit(spmd(step, topo, mesh=mesh))
    return state, lifted


def _run(state, lifted, batches):
    for b in batches:
        state, m = lifted(state, b)
    # the last step's metrics depend on all prior state: enough to pin
    return state, [m]


def _assert_state_bitwise(s_tree, s_arena, algo):
    for name in ("params", "opt_state", "batch_stats"):
        a = jax.tree.leaves(getattr(s_tree, name))
        b = jax.tree.leaves(getattr(s_arena, name))
        assert len(a) == len(b)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(
                np.asarray(x), np.asarray(y), err_msg=name
            )
    if s_tree.event is not None:
        for f in ("thres", "last_sent_norm", "last_sent_iter", "slopes",
                  "num_events", "num_deferred"):
            np.testing.assert_array_equal(
                np.asarray(getattr(s_tree.event, f)),
                np.asarray(getattr(s_arena.event, f)), err_msg=f,
            )
        if algo == "eventgrad":
            # tree bufs are pytrees, arena bufs flat [n]: compare ravel
            for i, (bt, ba) in enumerate(
                zip(s_tree.event.bufs, s_arena.event.bufs)
            ):
                flat_t = jax.vmap(lambda t: ravel_pytree(t)[0])(bt)
                np.testing.assert_array_equal(
                    np.asarray(flat_t), np.asarray(ba),
                    err_msg=f"bufs[{i}]",
                )
    if s_tree.chaos is not None:
        for x, y in zip(jax.tree.leaves(s_tree.chaos),
                        jax.tree.leaves(s_arena.chaos)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                          err_msg="chaos")
    if s_tree.telemetry is not None:
        for x, y in zip(jax.tree.leaves(s_tree.telemetry),
                        jax.tree.leaves(s_arena.telemetry)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                          err_msg="telemetry")


def _assert_metrics_bitwise(m_tree, m_arena):
    for k in m_tree:
        np.testing.assert_array_equal(
            np.asarray(m_tree[k]), np.asarray(m_arena[k]), err_msg=k
        )


#: the required equivalence matrix: algos x wires x gossip wires x
#: staleness x obs x chaos (representative crossings, not the full
#: product — each dimension is exercised against at least one other)
CASES = {
    "dpsgd_f32": dict(algo="dpsgd"),
    "dpsgd_bf16": dict(algo="dpsgd", wire="bf16"),
    "dpsgd_int8_mom": dict(algo="dpsgd", wire="int8", momentum=0.9),
    "dpsgd_chaos": dict(algo="dpsgd", chaos=ChaosSchedule(seed=7, drop_p=0.4)),
    "event_masked_f32": dict(algo="eventgrad"),
    "event_masked_f32_obs": dict(algo="eventgrad", obs=True),
    "event_masked_bf16_stale": dict(algo="eventgrad", wire="bf16",
                                    staleness=1),
    "event_masked_int8": dict(algo="eventgrad", wire="int8"),
    "event_masked_chaos": dict(algo="eventgrad",
                               chaos=ChaosSchedule(seed=3, drop_p=0.4)),
    "event_compact_f32": dict(algo="eventgrad", gossip_wire="compact",
                              capacity=CAPACITY),
    "event_compact_int8_obs": dict(algo="eventgrad", gossip_wire="compact",
                                   capacity=CAPACITY, wire="int8", obs=True),
    "event_compact_bf16_stale": dict(algo="eventgrad",
                                     gossip_wire="compact",
                                     capacity=CAPACITY, wire="bf16",
                                     staleness=1),
    "event_masked_mom": dict(algo="eventgrad", momentum=0.9),
    "sp_f32": dict(algo="sp_eventgrad"),
    "sp_int8_stale": dict(algo="sp_eventgrad", wire="int8", staleness=1),
}


@pytest.mark.parametrize("name", sorted(CASES))
def test_arena_bitwise_matches_tree(name):
    """The arena lift of the full train step is bitwise the tree lift:
    final state AND step metrics, after several steps (warmup crossing,
    real fire patterns, deferrals on the compact cases)."""
    kw = dict(CASES[name])
    batches = _batches(5)
    s_t, lift_t = _build(arena_on=False, **kw)
    s_a, lift_a = _build(arena_on=True, **kw)
    s_t, m_t = _run(s_t, lift_t, batches)
    s_a, m_a = _run(s_a, lift_a, batches)
    _assert_state_bitwise(s_t, s_a, kw["algo"])
    for mt, ma in zip(m_t, m_a):
        _assert_metrics_bitwise(mt, ma)


@requires_shard_map
def test_arena_bitwise_matches_tree_shard_map():
    """Same contract under the real-mesh lift (one device per rank)."""
    if len(jax.devices()) < N_RANKS:
        pytest.skip(f"needs {N_RANKS} devices")
    batches = _batches(3)
    s_t, lift_t = _build("eventgrad", False, backend="shard_map")
    s_a, lift_a = _build("eventgrad", True, backend="shard_map")
    s_t, m_t = _run(s_t, lift_t, batches)
    s_a, m_a = _run(s_a, lift_a, batches)
    _assert_state_bitwise(s_t, s_a, "eventgrad")


def test_arena_fused_tail_matches_tree_fused():
    """fused_sgd + arena routes through fused_mix_commit (buffer commit
    fused into the mix+SGD pass). Values match the tree fused tail to
    float tolerance — NOT bitwise, by design: the tree tail pre-sums the
    buffers ((p + (b_l + b_r)) vs the arena's ((p + b_l) + b_r))."""
    batches = _batches(4)
    kw = dict(algo="eventgrad", momentum=0.9, fused=(0.05, 0.9))
    s_t, lift_t = _build(arena_on=False, **kw)
    s_a, lift_a = _build(arena_on=True, **kw)
    s_t, _ = _run(s_t, lift_t, batches)
    s_a, _ = _run(s_a, lift_a, batches)
    for x, y in zip(jax.tree.leaves(s_t.params),
                    jax.tree.leaves(s_a.params)):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), atol=1e-5, rtol=1e-5
        )
    # buffers are selections of neighbor params, which carry the same
    # tolerance-level divergence forward
    for bt, ba in zip(s_t.event.bufs, s_a.event.bufs):
        flat_t = jax.vmap(lambda t: ravel_pytree(t)[0])(bt)
        np.testing.assert_allclose(
            np.asarray(flat_t), np.asarray(ba), atol=1e-5, rtol=1e-5
        )


# ---------------------------------------------------------------------------
# carrier-resident gossip state (ISSUE 17): ON-vs-OFF bitwise parity


def _build_resident(carrier, *, wire, bucketed=1, gossip_wire="dense",
                    capacity=None, staleness=0, fused=None, momentum=0.0,
                    algo="eventgrad", backend="vmap"):
    topo = Ring(N_RANKS)
    model = MLP(**MODEL)
    tx = optax.sgd(0.05, momentum=momentum if momentum else None)
    state = init_train_state(
        model, IN_SHAPE, tx, topo, algo, CFG, seed=0, arena=True,
        bucketed=bucketed,
        resident_wire=(wire if carrier and algo == "eventgrad" else None),
    )
    step = make_train_step(
        model, tx, topo, algo, event_cfg=CFG, wire=wire,
        gossip_wire=gossip_wire, compact_capacity=capacity,
        staleness=staleness, fused_sgd=fused, arena=True,
        bucketed=bucketed, carrier_resident=carrier,
    )
    mesh = build_mesh(topo) if backend == "shard_map" else None
    return state, jax.jit(spmd(step, topo, mesh=mesh))


def _carrier_bufs_f32_view(state, buckets=1):
    """Dequant a carrier-resident state's receive buffers back to f32
    through the production helper (vmapped over the stacked rank axis).
    f32-resident states pass through untouched."""
    ev = state.event
    leaves = jax.tree.leaves(ev.bufs)
    if not leaves or leaves[0].dtype == jnp.float32:
        return ev.bufs
    spec = arena.arena_spec(jax.tree.map(lambda l: l[0], state.params))
    if ev.buf_scales is not None:
        return jax.vmap(lambda b, s: collectives.dequant_carrier_bufs(
            b, s, spec, buckets=buckets
        ))(ev.bufs, ev.buf_scales)
    return jax.vmap(lambda b: collectives.dequant_carrier_bufs(
        b, None, spec, buckets=buckets
    ))(ev.bufs)


def _assert_resident_bitwise(s_f, s_c, m_f, m_c, buckets=1):
    for field in ("params", "opt_state", "batch_stats"):
        for x, y in zip(jax.tree.leaves(getattr(s_f, field)),
                        jax.tree.leaves(getattr(s_c, field))):
            np.testing.assert_array_equal(
                np.asarray(x), np.asarray(y), err_msg=field
            )
    for f in ("thres", "last_sent_norm", "last_sent_iter", "slopes",
              "num_events", "num_deferred"):
        np.testing.assert_array_equal(
            np.asarray(getattr(s_f.event, f)),
            np.asarray(getattr(s_c.event, f)), err_msg=f,
        )
    for x, y in zip(
        jax.tree.leaves(_carrier_bufs_f32_view(s_f, buckets)),
        jax.tree.leaves(_carrier_bufs_f32_view(s_c, buckets)),
    ):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg="bufs"
        )
    for k in m_f:
        np.testing.assert_array_equal(
            np.asarray(m_f[k]), np.asarray(m_c[k]), err_msg=f"metric {k}"
        )


#: carrier ON-vs-OFF matrix: gossip wires x carrier dtypes x staleness x
#: momentum x fused tails x bucketed K (representative crossings; the
#: bucketed compact capacity sits above that layout's per-bucket floor)
RESIDENT_CASES = {
    "masked_int8": dict(wire="int8"),
    "masked_bf16": dict(wire="bf16"),
    "masked_int8_stale": dict(wire="int8", staleness=1),
    "masked_int8_mom": dict(wire="int8", momentum=0.9),
    "compact_int8": dict(wire="int8", gossip_wire="compact",
                         capacity=CAPACITY),
    "compact_bf16_stale": dict(wire="bf16", gossip_wire="compact",
                               capacity=CAPACITY, staleness=1),
    "masked_int8_fused": dict(wire="int8", fused=(0.05, 0.0)),
    "masked_bf16_fused_mom": dict(wire="bf16", momentum=0.9,
                                  fused=(0.05, 0.9)),
    "bucketed4_int8": dict(wire="int8", bucketed=4),
    "bucketed4_compact_int8": dict(wire="int8", bucketed=4,
                                   gossip_wire="compact", capacity=1300),
    "bucketed4_bf16_stale": dict(wire="bf16", bucketed=4, staleness=1),
    "sp_int8_noop": dict(wire="int8", algo="sp_eventgrad"),
}


@pytest.mark.parametrize("name", sorted(RESIDENT_CASES))
def test_carrier_resident_bitwise_matches_f32_resident(name):
    """train(carrier_resident=True) — EventState.bufs stored in the wire
    dtype with dequant fused into the commit/mix reads — is BITWISE the
    f32-resident step: full TrainState (buffers compared in their f32
    view) and step metrics, after several steps of real fire patterns.
    sp_eventgrad accepts the flag as a documented no-op."""
    kw = dict(RESIDENT_CASES[name])
    batches = _batches(5)
    s_f, lift_f = _build_resident(False, **kw)
    s_c, lift_c = _build_resident(True, **kw)
    s_f, m_f = _run(s_f, lift_f, batches)
    s_c, m_c = _run(s_c, lift_c, batches)
    if kw.get("algo", "eventgrad") == "eventgrad":
        wdt = {"int8": jnp.int8, "bf16": jnp.bfloat16}[kw["wire"]]
        assert all(
            b.dtype == wdt for b in jax.tree.leaves(s_c.event.bufs)
        ), "carrier leg must actually store wire-dtype buffers"
    _assert_resident_bitwise(s_f, s_c, m_f[-1], m_c[-1],
                             buckets=kw.get("bucketed", 1))


def test_carrier_resident_guards():
    """Explicit carrier_resident=True fails loudly off the supported
    envelope (the silent degradations it replaces were the hazard)."""
    topo = Ring(N_RANKS)
    model = MLP(**MODEL)
    tx = optax.sgd(0.05)
    with pytest.raises(ValueError, match="algo='eventgrad'"):
        make_train_step(model, tx, topo, "dpsgd", wire="int8",
                        carrier_resident=True)
    with pytest.raises(ValueError, match="arena=True"):
        make_train_step(model, tx, topo, "eventgrad", event_cfg=CFG,
                        wire="int8", carrier_resident=True)
    with pytest.raises(ValueError, match="wire="):
        make_train_step(model, tx, topo, "eventgrad", event_cfg=CFG,
                        arena=True, carrier_resident=True)
    # ISSUE 20 lifted carrier x bounded-async: the D-slot delivery
    # queues ride the wire carrier too (per-slot dequant scales), so
    # staleness >= 2 now BUILDS instead of refusing — both the step and
    # the state, with every queue candidate slot in the carrier dtype
    make_train_step(model, tx, topo, "eventgrad", event_cfg=CFG,
                    arena=True, wire="int8", staleness=2,
                    carrier_resident=True)
    st = init_train_state(model, IN_SHAPE, tx, topo, "eventgrad", CFG,
                          seed=0, arena=True, staleness=2,
                          resident_wire="int8")
    assert st.event.pending is not None
    for queue in st.event.pending:
        assert len(queue) == 2
        for slot in queue:
            assert slot[0].dtype == jnp.int8
    # carrier buffers only exist on the flat arena layout
    with pytest.raises(ValueError, match="arena"):
        init_train_state(model, IN_SHAPE, tx, topo, "eventgrad", CFG,
                         seed=0, resident_wire="int8")


# ---------------------------------------------------------------------------
# fused-op units


def _rand_tree(seed=0):
    k = jax.random.PRNGKey(seed)
    return {
        "a": jax.random.normal(jax.random.fold_in(k, 0), (16, 13)),
        "b": jax.random.normal(jax.random.fold_in(k, 1), (7,)),
        "c": jax.random.normal(jax.random.fold_in(k, 2), (3, 5, 2)),
    }


def test_event_propose_pack_matches_legacy_chain():
    """One fused arena pass == the tree chain flatten -> propose ->
    capacity_gate -> _compact_pack, bit for bit (proposal fields, gated
    fire bits, packed buffer)."""
    tree = _rand_tree()
    spec = arena.arena_spec(tree)
    topo = Ring(N_RANKS)
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=1,
                      max_silence=3)
    state = EventState.init(tree, topo, cfg)
    # advance once so thresholds/slopes are non-trivial
    fire0, state = decide_and_update(
        tree, state, jnp.int32(1), cfg, topo.n_neighbors
    )
    pass_num = jnp.int32(5)
    capacity = 220  # admits "a" (208) and defers the rest when all fire

    # legacy chain
    prop_t = propose(tree, state, pass_num, cfg)
    pri = prop_t.iter_diff >= cfg.max_silence
    sizes, starts, n = collectives._leaf_meta(tree)
    fire_t = capacity_gate(prop_t.fire_vec, sizes, capacity, priority=pri)
    flat_t, _ = ravel_pytree(tree)
    packed_t, leaf_id_t = collectives._compact_pack(
        flat_t, fire_t, sizes, starts, capacity
    )

    # fused arena pass
    prop_a, fire_a, packed_a, leaf_id_a = event_engine.event_propose_pack(
        tree, state, pass_num, cfg, spec, capacity=capacity
    )
    for f in ("fire_vec", "curr_norm", "new_slopes", "thres", "iter_diff",
              "value_diff"):
        np.testing.assert_array_equal(
            np.asarray(getattr(prop_t, f)), np.asarray(getattr(prop_a, f)),
            err_msg=f,
        )
    np.testing.assert_array_equal(np.asarray(fire_t), np.asarray(fire_a))
    np.testing.assert_array_equal(np.asarray(packed_t), np.asarray(packed_a))
    np.testing.assert_array_equal(
        np.asarray(leaf_id_t), np.asarray(leaf_id_a)
    )


def test_fused_mix_commit_matches_reference():
    """Pallas (interpret) == jitted jnp twin, bitwise, both staleness
    modes and a ragged (non-lane-multiple) length."""
    for n, stale in ((512, False), (300, True)):
        k = jax.random.PRNGKey(n)
        p, g, t, c0, c1, l0, l1 = (
            jax.random.normal(jax.random.fold_in(k, i), (n,))
            for i in range(7)
        )
        k0 = jax.random.uniform(jax.random.fold_in(k, 8), (n,)) > 0.5
        k1 = jax.random.uniform(jax.random.fold_in(k, 9), (n,)) > 0.3
        out_k = arena_update.fused_mix_commit(
            p, (c0, c1), (k0, k1), (l0, l1), g, t, 0.01, 0.9, 1 / 3,
            mix_stale=stale, interpret=True,
        )
        ref = jax.jit(
            lambda *a: arena_update.mix_commit_reference(
                *a, 0.01, 0.9, 1 / 3, mix_stale=stale
            )
        )
        out_r = ref(p, (c0, c1), (k0, k1), (l0, l1), g, t)
        for x, y in zip(jax.tree.leaves(out_k), jax.tree.leaves(out_r)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_masked_wire_kernel_matches_reference():
    """Pallas masked-wire builder (interpret) == the jnp mask/quantize
    the flat exchanges inline, bitwise, plain and int8 variants."""
    tree = _rand_tree(3)
    spec = arena.arena_spec(tree)
    flat = spec.ravel(tree)
    seg = spec.seg_expand()
    fire_vec = jnp.asarray([True, False, True])
    fire_exp = fire_vec[seg]
    out = event_engine.masked_wire(flat, fire_exp, interpret=True)
    ref = jax.jit(event_engine.masked_wire_reference)(flat, fire_exp)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    scale_vec = collectives._masked_scales(
        collectives._leaf_absmax(jax.tree.leaves(tree)), fire_vec
    )
    out_q = event_engine.masked_wire(
        flat, fire_exp, scale_vec[seg], interpret=True
    )
    ref_q = jax.jit(event_engine.masked_wire_reference)(
        flat, fire_exp, scale_vec[seg]
    )
    np.testing.assert_array_equal(np.asarray(out_q), np.asarray(ref_q))
    # and the quantize matches the shared int8 wire codec
    masked = jnp.where(fire_exp, flat, jnp.zeros_like(flat))
    codec = collectives._int8_encode_flat(masked, scale_vec, seg)
    np.testing.assert_array_equal(
        np.asarray(out_q.astype(jnp.int8)), np.asarray(codec)
    )


def test_legacy_checkpoint_resume_falls_back():
    """A tree-layout (pre-arena) eventgrad checkpoint must keep resuming
    under the auto-arena default: the loop falls back to arena=False
    with a warning; an EXPLICIT arena=True gets an actionable error."""
    import tempfile
    import warnings as _w

    from eventgrad_tpu.train.loop import train

    x, y = synthetic_dataset(64, IN_SHAPE, seed=3)
    d = tempfile.mkdtemp()
    common = dict(
        algo="eventgrad", epochs=1, batch_size=4, event_cfg=CFG, seed=0,
        log_every_epoch=False, checkpoint_dir=d, save_every=1,
    )
    train(MLP(**MODEL), Ring(N_RANKS), x, y, arena=False, **common)
    common["epochs"] = 2
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        _s, hist = train(MLP(**MODEL), Ring(N_RANKS), x, y, resume=True,
                         **common)
    assert any("flat-arena" in str(r.message) for r in rec)
    assert hist[-1]["arena"] is False and hist[-1]["epoch"] == 2
    with pytest.raises(RuntimeError, match="arena=False"):
        train(MLP(**MODEL), Ring(N_RANKS), x, y, resume=True, arena=True,
              **common)


def test_arena_scope_validation():
    """Explicit arena=True on an algo whose step does not consume the
    arena must fail loudly (silently flattening sp_eventgrad's unused
    receive buffers would break its existing checkpoints for nothing);
    auto mode simply resolves to the tree path there."""
    from eventgrad_tpu.train.loop import train

    x, y = synthetic_dataset(32, IN_SHAPE, seed=0)
    with pytest.raises(ValueError, match="no-op"):
        train(
            MLP(**MODEL), Ring(N_RANKS), x, y, algo="sp_eventgrad",
            arena=True, epochs=1, batch_size=4, event_cfg=CFG,
            log_every_epoch=False,
        )
    _, hist = train(
        MLP(**MODEL), Ring(N_RANKS), x, y, algo="sp_eventgrad",
        epochs=1, batch_size=4, event_cfg=CFG, log_every_epoch=False,
    )
    assert hist[-1]["arena"] is False


# ---------------------------------------------------------------------------
# cached leaf metadata


def test_leaf_meta_cache_hits():
    """Re-deriving leaf metadata for a known structure must be a cache
    HIT — the traced step can call these freely without rebuilding."""
    tree = _rand_tree(11)
    spec1 = arena.arena_spec(tree)
    before = arena.cache_info()
    spec2 = arena.arena_spec(jax.tree.map(lambda x: x * 2, tree))
    after = arena.cache_info()
    assert spec2 is spec1, "same structure must return the cached spec"
    assert after.hits == before.hits + 1
    assert after.misses == before.misses
    # _leaf_meta and the capacity floor ride the same caches
    sizes, starts, n = collectives._leaf_meta(tree)
    assert (sizes, starts, n) == (spec1.sizes, spec1.starts, spec1.n_total)
    assert arena.cache_info().misses == after.misses
    f1 = collectives.compact_capacity_floor(sizes)
    before_f = collectives._capacity_floor_cached.cache_info()
    f2 = collectives.compact_capacity_floor(list(sizes))
    after_f = collectives._capacity_floor_cached.cache_info()
    assert f1 == f2 == max(sizes)
    assert after_f.hits == before_f.hits + 1


# ---------------------------------------------------------------------------
# op-count regression gate (no timing — CI-stable jaxpr accounting),
# on the shared nested-jaxpr walker the trace auditor also uses


def _step_jaxpr(arena_on):
    topo = Ring(N_RANKS)
    model = MLP(**MODEL)
    tx = optax.sgd(0.05)
    state = init_train_state(
        model, IN_SHAPE, tx, topo, "eventgrad", CFG, seed=0, arena=arena_on
    )
    step = make_train_step(
        model, tx, topo, "eventgrad", event_cfg=CFG, arena=arena_on
    )
    batch = _batches(1)[0]
    return jax.make_jaxpr(spmd(step, topo))(state, batch)


def test_arena_step_op_budget():
    """The fused step's jaxpr stays inside an op budget. Full-model
    ravels (concatenates producing an [n_params] buffer) are the
    footprint of a pytree flatten: the arena step gets exactly TWO —
    params once, grads once for the flat SGD tail — where the tree path
    re-flattens per consumer. Total eqn count must also stay below the
    tree program's. No timing anywhere: CI-stable jaxpr accounting."""
    arena_jaxpr = _step_jaxpr(True)
    tree_jaxpr = _step_jaxpr(False)
    n_total = arena.arena_spec(
        jax.tree.map(
            lambda x: x[0],
            init_train_state(
                MLP(**MODEL), IN_SHAPE, optax.sgd(0.05), Ring(N_RANKS),
                "dpsgd", seed=0,
            ).params,
        )
    ).n_total
    # a full-model CONCATENATE is the footprint of materializing a
    # flattened model copy: the arena step gets exactly ONE — the wire
    # build, with the event mask fused into its pieces. A second one
    # means a per-step flatten crept back in.
    rav_arena = walker.count_full_ravels(arena_jaxpr.jaxpr, n_total)
    assert rav_arena <= 1, (
        f"arena step materializes {rav_arena} full-model concatenates — "
        "a per-step flatten crept back in (budget: the wire build only)"
    )
    # concatenate total: the wire plus the [L]-vector stacks of the
    # trigger (norms, slope ring); a per-leaf traversal would add L
    # entries and blow this
    cat_arena = walker.count_primitives(arena_jaxpr.jaxpr, "concatenate")
    assert cat_arena <= 5, f"arena concatenate count grew to {cat_arena}"
    # whole-graph budget: the arena program stays strictly leaner than
    # the tree program it replaced (no separate mask pass, no
    # per-neighbor unravels, no duplicate flatten), with an absolute
    # ceiling for drift (measured 323 + slack)
    n_arena = walker.count_primitives(arena_jaxpr.jaxpr)
    n_tree = walker.count_primitives(tree_jaxpr.jaxpr)
    assert n_arena < n_tree, (n_arena, n_tree)
    assert n_arena <= 380, f"arena step grew to {n_arena} eqns"
