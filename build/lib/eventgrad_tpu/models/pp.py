"""Pipeline parallelism: GPipe microbatch schedule over a named mesh axis.

Not present in the reference (SURVEY §2.5 marks PP "absent" — its models are
four-tensor CNNs), but part of the framework's scale story alongside TP
(models/tp.py) and EP (models/moe.py): Transformer blocks are split into
`pp_size` stages, each pp rank owns one stage's parameters, and microbatches
flow through the stages as a `lax.scan` over ticks with one
`lax.ppermute` activation shift per tick riding the ICI ring.

TPU-first design decisions:

  * The schedule is a static scan of `n_micro + pp_size - 1` ticks — no
    data-dependent control flow; XLA sees one compiled loop body whose
    matmuls stay MXU-shaped ([micro_batch, T, D] per tick).
  * Backward is free: AD through scan+ppermute yields exactly the reverse
    GPipe schedule (cotangents ppermute backward through the stages).
  * Stage parameters use the framework's `tp_` sharded-leaf convention
    (train/steps.py): each pp rank owns distinct values of the same-named
    leaves, their gradients divide by the axis size (the masked-psum loss
    broadcast scales cotangents by pp_size under the psum-transpose rule),
    and gossip/grad-pmean skip the pp axis entirely.
  * Embeddings and the LM head stay replicated across pp (they gossip
    normally across dp): every rank embeds the batch, only stage 0's copy
    enters the pipeline (a `where` on the stage index), and the last
    stage's output is broadcast back with one masked `psum` so every rank
    computes the same loss — which keeps the generic train step unchanged.

A pure-pp topology is `Topology(axes=("pp",), shape=(S,), sharded_axes=("pp",))`;
hybrid gossip×pp meshes work like gossip×TP.
"""

from __future__ import annotations

from typing import Any, Dict, List

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from eventgrad_tpu.models.tp import sharded_lecun_init
from eventgrad_tpu.parallel.ring_attention import full_attention


def gpipe(stage_fn, x_micro: jnp.ndarray, axis: str, pp_size: int) -> jnp.ndarray:
    """Run the GPipe schedule for one forward pass.

    `x_micro`: [n_micro, micro_batch, ...] microbatches, replicated across
    the pp axis (only stage 0's copy is consumed). `stage_fn` is this rank's
    stage, a pure function on one microbatch. Returns [n_micro, ...] stage
    outputs — valid on the LAST stage only (other ranks hold garbage;
    callers broadcast with a masked psum).

    Tick t: stage 0 feeds microbatch t, every stage applies its fn to its
    current activation, the last stage banks its result, and activations
    shift one stage rightward (one ppermute per tick).
    """
    n_micro = x_micro.shape[0]
    stage = lax.axis_index(axis)
    perm = [(r, (r + 1) % pp_size) for r in range(pp_size)]
    acts0 = jnp.zeros_like(x_micro[0])
    out0 = jnp.zeros_like(x_micro)

    def tick(carry, t):
        acts, outs = carry
        feed = lax.dynamic_index_in_dim(
            x_micro, jnp.clip(t, 0, n_micro - 1), axis=0, keepdims=False
        )
        inp = jnp.where(stage == 0, feed, acts)
        out = stage_fn(inp)
        o_idx = jnp.clip(t - (pp_size - 1), 0, n_micro - 1)
        bank = (stage == pp_size - 1) & (t >= pp_size - 1)
        prev = lax.dynamic_index_in_dim(outs, o_idx, axis=0, keepdims=False)
        outs = lax.dynamic_update_index_in_dim(
            outs, jnp.where(bank, out, prev), o_idx, axis=0
        )
        acts = lax.ppermute(out, axis, perm)
        return (acts, outs), None

    (_, outs), _ = lax.scan(
        tick, (acts0, out0), jnp.arange(n_micro + pp_size - 1)
    )
    return outs


def _layernorm(x, scale, bias):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + 1e-6) * scale + bias


def _block_apply(p: Dict[str, jnp.ndarray], x, n_heads: int, dtype) -> jnp.ndarray:
    """One pre-LN Transformer block as a pure function of its param dict —
    kept functional (not a flax submodule) so it can run inside the gpipe
    scan body without flax lifted-transform machinery."""
    b, t, dim = x.shape
    d = dim // n_heads
    y = _layernorm(x, p["ln1_scale"], p["ln1_bias"]).astype(dtype)
    qkv = y @ p["wqkv"].astype(dtype)
    q, k, v = jnp.split(qkv.reshape(b, t, 3 * n_heads, d), 3, axis=2)
    o = full_attention(q, k, v, causal=True)
    x = x + o.reshape(b, t, dim) @ p["wo"].astype(dtype)
    y = _layernorm(x, p["ln2_scale"], p["ln2_bias"]).astype(dtype)
    y = nn.gelu(y @ p["wi"].astype(dtype)) @ p["wo2"].astype(dtype)
    return x + y


class PPTransformerLM(nn.Module):
    """Decoder-only LM whose blocks are pipeline-sharded over `axis`.

    `n_layers` is the GLOBAL layer count; each of the `pp_size` stages owns
    `n_layers // pp_size` consecutive blocks (stage-major ownership: pp rank
    r holds global layers [r*L, (r+1)*L)). Every stage parameter is a
    `tp_l{i}_*` leaf — same names on every rank, distinct values. With
    pp_size == 1 all layers are local and no collective runs (the
    sequential twin used by tests)."""

    vocab: int = 256
    dim: int = 128
    n_heads: int = 8
    n_layers: int = 4
    max_len: int = 1024
    axis: str = "pp"
    pp_size: int = 1
    n_micro: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        if self.n_layers % self.pp_size:
            raise ValueError(
                f"n_layers {self.n_layers} not divisible by pp_size {self.pp_size}"
            )
        if self.n_micro < 1:
            raise ValueError(f"n_micro must be >= 1, got {self.n_micro}")
        b, t = tokens.shape
        # GPipe output is microbatch-count invariant, so the batch-1 init
        # trace may run unsplit; any other indivisible batch is a config
        # error (silently unsplitting would defeat the memory schedule)
        if b == 1:
            n_micro = 1
        elif b % self.n_micro == 0:
            n_micro = self.n_micro
        else:
            raise ValueError(
                f"batch {b} not divisible by n_micro {self.n_micro}"
            )
        layers_local = self.n_layers // self.pp_size
        sharded = self.pp_size > 1
        kinit = sharded_lecun_init(self.axis) if sharded else nn.initializers.lecun_normal()

        def ones_init(key, shape, dtype=jnp.float32):
            return jnp.ones(shape, dtype)

        def zeros_init(key, shape, dtype=jnp.float32):
            return jnp.zeros(shape, dtype)

        stage_params: List[Dict[str, jnp.ndarray]] = []
        for i in range(layers_local):
            stage_params.append(
                {
                    "ln1_scale": self.param(f"tp_l{i}_ln1_scale", ones_init, (self.dim,)),
                    "ln1_bias": self.param(f"tp_l{i}_ln1_bias", zeros_init, (self.dim,)),
                    "wqkv": self.param(f"tp_l{i}_wqkv", kinit, (self.dim, 3 * self.dim), jnp.float32),
                    "wo": self.param(f"tp_l{i}_wo", kinit, (self.dim, self.dim), jnp.float32),
                    "ln2_scale": self.param(f"tp_l{i}_ln2_scale", ones_init, (self.dim,)),
                    "ln2_bias": self.param(f"tp_l{i}_ln2_bias", zeros_init, (self.dim,)),
                    "wi": self.param(f"tp_l{i}_wi", kinit, (self.dim, 4 * self.dim), jnp.float32),
                    "wo2": self.param(f"tp_l{i}_wo2", kinit, (4 * self.dim, self.dim), jnp.float32),
                }
            )

        def stage_fn(h):
            for p in stage_params:
                h = _block_apply(p, h, self.n_heads, self.dtype)
            return h

        x = nn.Embed(self.vocab, self.dim, dtype=self.dtype)(tokens)
        x = x + nn.Embed(self.max_len, self.dim, dtype=self.dtype)(jnp.arange(t))

        if sharded:
            xm = x.reshape((n_micro, b // n_micro) + x.shape[1:])
            ym = gpipe(stage_fn, xm, self.axis, self.pp_size)
            y = ym.reshape(x.shape)
            last = lax.axis_index(self.axis) == self.pp_size - 1
            y = lax.psum(jnp.where(last, y, jnp.zeros_like(y)), self.axis)
        else:
            y = stage_fn(x)

        y = nn.LayerNorm(dtype=self.dtype)(y)
        return nn.Dense(self.vocab, dtype=self.dtype)(y).astype(jnp.float32)
