import pytest

from eventgrad_tpu.parallel.topology import Ring, Torus


def test_ring_neighbors():
    topo = Ring(4)
    assert topo.n_ranks == 4
    offsets = [(nb.axis, nb.offset) for nb in topo.neighbors]
    assert offsets == [("ring", -1), ("ring", 1)]
    assert topo.mix_weight == pytest.approx(1 / 3)


def test_torus_neighbors():
    topo = Torus(4, 2)
    assert topo.n_ranks == 8
    assert topo.n_neighbors == 4
    assert topo.mix_weight == pytest.approx(1 / 5)


def test_degenerate_axis_has_no_neighbors():
    topo = Ring(1)
    assert topo.n_neighbors == 0
    topo = Torus(4, 1)
    assert topo.n_neighbors == 2  # only the size-4 axis gossips
