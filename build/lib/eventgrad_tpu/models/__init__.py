from eventgrad_tpu.models.mlp import MLP
from eventgrad_tpu.models.moe import MoETransformerLM
from eventgrad_tpu.models.pp import PPTransformerLM
from eventgrad_tpu.models.tp import TPTransformerLM
from eventgrad_tpu.models.transformer import TransformerLM
from eventgrad_tpu.models.cnn import CNN1, CNN2, LeNetCifar
from eventgrad_tpu.models.resnet import (
    ResNet,
    BasicBlock,
    Bottleneck,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)

MODEL_REGISTRY = {
    "mlp": MLP,
    "cnn1": CNN1,
    "cnn2": CNN2,
    "lenet_cifar": LeNetCifar,
    "resnet18": ResNet18,
    "resnet34": ResNet34,
    "resnet50": ResNet50,
    "resnet101": ResNet101,
    "resnet152": ResNet152,
}
