"""Message-lifecycle conservation audit: every message's fate, accounted.

The proof instrument of the message-lifecycle ledger (obs/ledger.py,
obs/schema.py DISPOSITIONS): run ONE composed worst-case configuration —
chaos delivery drops x wire bitflips x bounded-async staleness D=2 with
a lag window x compact-wire capacity deferrals x integrity
checksum+quarantine with seeded nansteps — and check the integer
conservation laws on every flush window:

    proposed = suppressed + deferred + fired          (per rank, edge)
    fired    = delivered + dropped + rejected + in_flight
                                          (per edge, summed over ranks)
    sender.fired(e) = receiver.(delivered+dropped+rejected+
                      in_flight)(e)                   (per rank, edge)

Three legs, one JSON artifact (artifacts/ledger_conservation_cpu.json,
schema-gated by LEDGER_CONSERVATION_SCHEMA in validate_artifacts.py):

  * composed  — the configuration above, with EVERY disposition of the
                taxonomy exercised (suppressed by quarantined passes,
                deferred by the capacity gate, dropped by chaos,
                rejected by checksums, late_committed/in_flight by the
                delivery queue). Acceptance: every window's audit holds
                with INTEGER equality — zero violations — and no
                disposition row is accidentally dead (all > 0).
  * oracles   — the same run with each seeded leak enabled
                (EG_LEDGER_LEAK=uncounted_drop | double_reject): the
                classic counter bugs — a message fate nobody counts, a
                fate counted twice. Acceptance: the auditor CATCHES
                both (at least one window audit fails, naming the
                broken law) — the negative control that proves the
                auditor's teeth are real, not vacuous.
  * off       — obs="off" vs the ledgered obs run: final parameters
                bitwise identical (the ledger is observation, never
                physics).

Runs on CPU in ~1 min (--fast: one-epoch smoke for tier-1). Usage:
    python tools/ledger_audit.py [--fast] [--epochs 3] [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import subprocess
import sys
import time
from typing import Any, Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

from eventgrad_tpu.chaos.integrity import IntegrityConfig
from eventgrad_tpu.chaos.schedule import ChaosSchedule
from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP
from eventgrad_tpu.obs import ledger as obs_ledger
from eventgrad_tpu.obs.schema import LEDGER_COUNTER_ROWS
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.loop import train
from eventgrad_tpu.utils import compile_cache

LEDGER_SCHEMA_VERSION = 1

N_RANKS = 4
BATCH = 8

#: the composed worst case: drops and bitflips throughout, a lag window
#: covering the first half (so late commits are a strict SUB-count of
#: delivered, not all of it), and two nansteps early enough that the
#: quarantined rank's trigger still proposes densely (suppressed > 0)
CHAOS_SPEC = ("seed=7,drop=0.2,bitflip=4-20@0.2,lag=0-12@2,"
              "nanstep=1@3,nanstep=2@5")

EVENT_CFG = EventConfig(adaptive=True, horizon=0.95, warmup_passes=2,
                        max_silence=4)


def _run(x, y, epochs, seed, obs="epoch"):
    return train(
        MLP(hidden=16), Ring(N_RANKS), x, y,
        algo="eventgrad", epochs=epochs, batch_size=BATCH,
        learning_rate=0.05, event_cfg=EVENT_CFG, seed=seed,
        staleness=2, gossip_wire="compact", compact_frac=0.5,
        chaos=ChaosSchedule.parse(CHAOS_SPEC),
        integrity=IntegrityConfig(checksum=True, quarantine=True),
        obs=obs, log_every_epoch=True,
    )


def _fold_windows(history) -> Dict[str, Any]:
    """Per-window ledger blocks + audits -> (windows, totals, audit sum)."""
    windows: List[Dict[str, Any]] = []
    totals = {name: 0 for name in LEDGER_COUNTER_ROWS}
    checks = 0
    violations: List[Dict[str, Any]] = []
    in_flight_final = 0
    for rec in history:
        obs = rec.get("obs")
        if not obs or "message_ledger" not in obs:
            continue
        blk, aud = obs["message_ledger"], obs["ledger_audit"]
        for name in LEDGER_COUNTER_ROWS:
            totals[name] += sum(blk[name])
        in_flight_final = sum(blk["in_flight"])
        checks += int(aud["checks"])
        violations.extend(aud["violations"])
        windows.append({
            "epoch": rec["epoch"],
            "ledger": {k: sum(v) for k, v in blk.items()},
            "audit_ok": bool(aud["ok"]),
        })
    return {
        "windows": windows,
        "totals": totals,
        "in_flight_final": in_flight_final,
        "checks": checks,
        "violations": violations,
    }


def _oracle_leg(leak: str, epochs: int, seed: int) -> Dict[str, Any]:
    """Re-run the composed configuration in a SUBPROCESS with the seeded
    leak armed (the leak is read at trace time; a child process keeps
    this interpreter's traced steps honest) and report whether the
    auditor caught it."""
    code = (
        "import json, sys; sys.path.insert(0, {root!r})\n"
        "from tools.ledger_audit import _run, _fold_windows\n"
        "from eventgrad_tpu.data.datasets import synthetic_dataset\n"
        "x, y = synthetic_dataset({n}, (8, 8, 1), seed=1)\n"
        "_, h = _run(x, y, {epochs}, {seed})\n"
        "f = _fold_windows(h)\n"
        "print(json.dumps({{'violations': f['violations'],"
        " 'checks': f['checks']}}))\n"
    ).format(
        root=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        n=64 * N_RANKS, epochs=epochs, seed=seed,
    )
    env = dict(os.environ)
    env[obs_ledger.LEAK_ENV] = leak
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run(
        [sys.executable, "-c", code], env=env,
        capture_output=True, text=True, timeout=600,
    )
    if out.returncode != 0:
        raise RuntimeError(
            f"oracle leg {leak} failed:\n{out.stderr[-2000:]}"
        )
    res = json.loads(out.stdout.strip().splitlines()[-1])
    laws = sorted({v["law"] for v in res["violations"]})
    return {
        "leak": leak,
        "caught": bool(res["violations"]),
        "checks": res["checks"],
        "violated_laws": laws,
        "first_violation": (
            res["violations"][0] if res["violations"] else None
        ),
    }


def _params_equal_bitwise(a, b) -> bool:
    return all(
        np.array_equal(np.asarray(la), np.asarray(lb))
        for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "artifacts", f"ledger_conservation_{jax.default_backend()}.json",
    ))
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 smoke: 1 epoch, oracle legs in-process")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    compile_cache.honor_cpu_pin()
    compile_cache.enable()

    epochs = 2 if args.fast else args.epochs
    if args.fast:
        # the compact autotune's dense warmup (EG_COMPACT_MIN_SAMPLES,
        # default 16) applies capacity at a block boundary — shrink the
        # sample floor so the gate engages inside the 2-epoch smoke and
        # the `deferred` row is exercised like every other disposition
        os.environ.setdefault("EG_COMPACT_MIN_SAMPLES", "4")
    # fast: 4 passes/epoch — the chaos spec's pass-indexed windows
    # (nansteps @3/@5, bitflip 4-20, lag 0-12) all land inside the
    # 8-pass smoke, and the tier-1 budget pays half the run time
    n_per_rank = 32 if args.fast else 64
    x, y = synthetic_dataset(n_per_rank * N_RANKS, (8, 8, 1), seed=1)

    t0 = time.time()
    if os.environ.get(obs_ledger.LEAK_ENV):
        raise SystemExit(
            f"{obs_ledger.LEAK_ENV} is set — the composed leg must run "
            "leak-free (the oracle legs arm it themselves)"
        )

    # composed leg
    state, hist = _run(x, y, epochs, args.seed)
    fold = _fold_windows(hist)
    totals = fold["totals"]
    exercised = {
        name: totals[name] > 0 for name in LEDGER_COUNTER_ROWS
    }
    exercised["in_flight"] = any(
        w["ledger"]["in_flight"] > 0 for w in fold["windows"]
    )
    sender_identity = (
        totals["proposed"]
        == totals["suppressed"] + totals["deferred"] + totals["fired"]
    )
    # run-total receiver identity: what is still queued at the end is
    # the in-flight gauge of the last window
    receiver_identity = (
        totals["fired"]
        == totals["delivered"] + totals["dropped"] + totals["rejected"]
        + fold["in_flight_final"]
    )

    # oracle legs: the auditor must CATCH both seeded leaks
    if args.fast:
        # in-process (subprocesses would re-trace from a cold jit cache;
        # tier-1 budget says no): arm the env, re-run, disarm. The env
        # is read at trace time and train() builds fresh jitted
        # callables per call, so the leaky trace is really dispatched.
        oracles = []
        for leak in obs_ledger.LEAKS:
            os.environ[obs_ledger.LEAK_ENV] = leak
            try:
                _, lh = _run(x, y, epochs, args.seed)
            finally:
                del os.environ[obs_ledger.LEAK_ENV]
            lf = _fold_windows(lh)
            oracles.append({
                "leak": leak,
                "caught": bool(lf["violations"]),
                "checks": lf["checks"],
                "violated_laws": sorted({
                    v["law"] for v in lf["violations"]
                }),
                "first_violation": (
                    lf["violations"][0] if lf["violations"] else None
                ),
            })
    else:
        oracles = [
            _oracle_leg(leak, epochs, args.seed)
            for leak in obs_ledger.LEAKS
        ]

    # off leg: the ledger observes, it must not touch the physics
    state_off, _ = _run(x, y, epochs, args.seed, obs="off")
    state_off2, _ = _run(x, y, epochs, args.seed, obs="off")
    obs_off_deterministic = _params_equal_bitwise(
        state_off.params, state_off2.params
    )
    obs_off_matches_obs_run = _params_equal_bitwise(
        state.params, state_off.params
    )

    rec = {
        "bench": "ledger_conservation",
        "schema_version": LEDGER_SCHEMA_VERSION,
        "platform": f"{platform.system()}-{jax.default_backend()}",
        "topo": f"ring:{N_RANKS}",
        "algo": "eventgrad",
        "op_point": {
            "epochs": epochs, "batch_size": BATCH,
            "n_synth": int(len(x)), "model": "mlp16",
            "seed": args.seed, "staleness": 2,
            "gossip_wire": "compact", "compact_frac": 0.5,
        },
        "chaos": CHAOS_SPEC,
        "integrity": {"checksum": True, "quarantine": True},
        "windows": fold["windows"],
        "totals": totals,
        "in_flight_final": fold["in_flight_final"],
        "conservation": {
            "checks": fold["checks"],
            "violations": len(fold["violations"]),
            "all_windows_ok": all(
                w["audit_ok"] for w in fold["windows"]
            ),
            "sender_identity_run_total": bool(sender_identity),
            "receiver_identity_run_total": bool(receiver_identity),
        },
        "dispositions_exercised": exercised,
        "all_dispositions_exercised": all(exercised.values()),
        "leak_oracles": oracles,
        "all_leaks_caught": all(o["caught"] for o in oracles),
        "obs_off_deterministic": bool(obs_off_deterministic),
        "obs_off_matches_obs_run": bool(obs_off_matches_obs_run),
        "wall_s": round(time.time() - t0, 1),
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    print(json.dumps(
        {k: v for k, v in rec.items() if k != "windows"}, indent=1,
    ))
    print(f"wrote {args.out}", file=sys.stderr)

    ok = (
        rec["conservation"]["all_windows_ok"]
        and rec["conservation"]["violations"] == 0
        and rec["conservation"]["sender_identity_run_total"]
        and rec["conservation"]["receiver_identity_run_total"]
        and rec["all_dispositions_exercised"]
        and rec["all_leaks_caught"]
        and rec["obs_off_deterministic"]
        and rec["obs_off_matches_obs_run"]
    )
    if not ok:
        print("ledger audit: GATES FAILING", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
