"""Elastic supervisor: failure detection + automatic restart-from-snapshot.

The reference has no failure story at all — a dead rank leaves its ring
neighbors blocked in MPI_Recv forever (/root/reference/dmnist/decent/
decent.cpp:200-205) and an MPI RMA window silently freezes. Here the
training job runs under a supervisor that detects both failure modes:

  * **crash** — the child exits nonzero;
  * **hang** — the child stays alive but its heartbeat (the metrics
    log / checkpoint dir) stops advancing for `--timeout` seconds, the
    moral equivalent of a wedged collective.

Either way the child is killed and relaunched with `--resume`, restoring
the full gossip TrainState (params, optimizer moments, event thresholds,
stale neighbor buffers) from the latest orbax snapshot — so recovery costs
at most one `--save-every` interval of recomputation. Pair with the train
loop's `fault_inject` ("crash:N" / "hang:N") for end-to-end drills.

Usage:
    python -m eventgrad_tpu.supervise --timeout 120 --max-restarts 3 -- \
        --algo eventgrad --mesh ring:8 --dataset cifar10 --model resnet18 \
        --checkpoint-dir /ckpt --save-every 1 --log-file /logs/run.jsonl
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time
from typing import List, Optional, Sequence


def _latest_mtime(path: str) -> float:
    """Newest mtime under `path` (file, or dir scanned recursively)."""
    if not os.path.exists(path):
        return 0.0
    newest = os.path.getmtime(path)
    if os.path.isdir(path):
        for root, _, files in os.walk(path):
            for f in files:
                try:
                    newest = max(newest, os.path.getmtime(os.path.join(root, f)))
                except OSError:
                    pass  # snapshot promotion may race the walk
    return newest


def _flag_value(args: Sequence[str], flag: str) -> Optional[str]:
    for i, a in enumerate(args):
        if a == flag and i + 1 < len(args):
            return args[i + 1]
        if a.startswith(flag + "="):
            return a.split("=", 1)[1]
    return None


def _terminate(proc: subprocess.Popen, grace: float = 10.0) -> None:
    proc.terminate()
    try:
        proc.wait(timeout=grace)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()


def supervise(
    child_args: List[str],
    timeout: float = 0.0,
    max_restarts: int = 3,
    heartbeat: Optional[str] = None,
    poll_s: float = 0.5,
) -> int:
    """Run the CLI under supervision; returns the final exit code (0 on
    eventual success). `child_args` are eventgrad_tpu.cli flags and must
    include --checkpoint-dir (restarts would lose all progress otherwise)."""
    ckpt_dir = _flag_value(child_args, "--checkpoint-dir")
    if not ckpt_dir:
        raise SystemExit("supervise: child args must include --checkpoint-dir")
    heartbeat = heartbeat or _flag_value(child_args, "--log-file") or ckpt_dir

    attempt = 0
    while True:
        argv = list(child_args)
        if attempt > 0 and "--resume" not in argv:
            argv.append("--resume")
        cmd = [sys.executable, "-m", "eventgrad_tpu.cli", *argv]
        started = time.time()
        proc = subprocess.Popen(cmd)
        reason = None
        # stat the heartbeat at a fraction of the timeout, not every poll —
        # a checkpoint-dir heartbeat on shared storage shouldn't see a
        # metadata storm from its own supervisor
        hb_every = max(poll_s, timeout / 4.0) if timeout else poll_s
        last_hb_check, last_hb = 0.0, 0.0
        while proc.poll() is None:
            time.sleep(poll_s)
            if not timeout:
                continue
            now = time.time()
            if now - last_hb_check >= hb_every:
                last_hb_check = now
                last_hb = _latest_mtime(heartbeat)
            if now - max(started, last_hb) > timeout:
                # the cached mtime may be up to hb_every stale — re-stat
                # before declaring a live child hung
                last_hb_check = now
                last_hb = _latest_mtime(heartbeat)
                if now - max(started, last_hb) <= timeout:
                    continue
                reason = f"no heartbeat on {heartbeat} for {timeout:.0f}s"
                _terminate(proc)
                break
        rc = proc.returncode
        if rc == 0:
            return 0
        attempt += 1
        desc = reason or f"exit code {rc}"
        print(
            f"supervise: attempt {attempt} failed ({desc}); "
            + ("restarting from latest snapshot" if attempt <= max_restarts
               else "giving up"),
            file=sys.stderr, flush=True,
        )
        if attempt > max_restarts:
            if rc is None:
                return 1
            # signal deaths (rc < 0) would wrap around in sys.exit; report
            # them the shell way
            return 128 + abs(rc) if rc < 0 else rc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="eventgrad-tpu-supervise", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--timeout", type=float, default=0.0,
                   help="seconds without heartbeat progress before the child "
                        "is declared hung and killed (0 = crash detection only)")
    p.add_argument("--max-restarts", type=int, default=3)
    p.add_argument("--heartbeat", default=None,
                   help="file/dir whose mtime is the liveness signal "
                        "(default: the child's --log-file, else its "
                        "--checkpoint-dir)")
    p.add_argument("child", nargs=argparse.REMAINDER,
                   help="-- followed by eventgrad_tpu.cli flags")
    args = p.parse_args(argv)
    child = args.child
    if child and child[0] == "--":
        child = child[1:]
    if not child:
        raise SystemExit("supervise: pass CLI flags after --")
    return supervise(
        child, timeout=args.timeout, max_restarts=args.max_restarts,
        heartbeat=args.heartbeat,
    )


if __name__ == "__main__":
    sys.exit(main())
