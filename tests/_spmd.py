"""Shared SPMD test helpers: the one shard_map skip definition.

The mesh lift needs the shard_map transform. Since PR 14 the package
resolves it under either spelling — `jax.shard_map` (new) or
`jax.experimental.shard_map.shard_map` (the 0.4.x line) — via
`parallel.spmd.shard_map_available`, and tests/conftest.py forces an
8-device CPU host platform, so on every supported environment the
shard_map tests RUN (and must pass; the vmap/shard_map bitwise-parity
matrix lives in tests/test_mesh_parity.py). The skip below fires only
when shard_map is GENUINELY unavailable — a jax with neither spelling —
not merely renamed, which is what the pre-shim `hasattr(jax,
"shard_map")` condition mis-read as "mesh-less" on 0.4.x (the seed's
10 pre-existing tier-1 failures).

A tier-1 lint (tests/test_lint_spmd.py) enforces that every test
touching shard_map imports `requires_shard_map` from here rather than
re-spelling the skipif — one marker, one reason string.

Usage:

    from _spmd import requires_shard_map

    @requires_shard_map
    def test_something_shard_map(): ...

    BACKENDS = ["vmap", pytest.param("shard_map", marks=requires_shard_map)]
"""

import pytest

from eventgrad_tpu.parallel.spmd import shard_map_available

#: single source of truth for "this test needs the shard_map mesh lift"
requires_shard_map = pytest.mark.skipif(
    not shard_map_available(),
    reason=(
        "shard_map genuinely unavailable in this jax (neither "
        "jax.shard_map nor jax.experimental.shard_map.shard_map "
        "resolves — see parallel/spmd.py)"
    ),
)
