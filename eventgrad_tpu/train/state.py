"""Train state: everything a rank mutates, as one explicit pytree.

The reference's mutable per-rank state is scattered across the model, the
torch optimizer, loop counters, and raw C arrays
(/root/reference/dmnist/event/event.cpp:181-264). Here it is a single
`TrainState` pytree threaded through a jit-compiled step, created directly
in the *stacked* layout ([n_ranks, ...] leading axis): parameters replicate
the same initialization across ranks (the reference seeds every rank with
torch::manual_seed(0), event.cpp:150), while PRNG keys differ per rank so
dropout/augmentation decorrelate like the reference's per-rank data order.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax
from flax import struct

from eventgrad_tpu.parallel.events import EventConfig, EventState
from eventgrad_tpu.parallel.sparsify import SparseConfig, SparseState
from eventgrad_tpu.parallel.topology import Topology
from eventgrad_tpu.parallel.spmd import stack_for_ranks


def _maybe_jit(fn):
    """jit on accelerator backends (one dispatch instead of one tunnel
    round-trip per op); eager on CPU (dispatch is ~free and the closure
    is fresh per call, so a jit would pay a full retrace every time)."""
    return fn if jax.default_backend() == "cpu" else jax.jit(fn)


class TrainState(struct.PyTreeNode):
    params: Any
    opt_state: Any
    batch_stats: Any  # rank-local BatchNorm stats; never gossiped (see resnet.py)
    pass_num: jnp.ndarray  # int32, pre-incremented each batch (event.cpp:273)
    rng: jax.Array
    event: Optional[EventState] = None
    sparse: Optional[SparseState] = None
    #: chaos.monitor.PeerHealth when fault injection / recovery is on
    chaos: Optional[Any] = None
    #: obs.device.TelemetryState when train(obs=...) telemetry is on —
    #: cumulative on-device counters, flushed to host once per dispatch
    #: block (docs/OBSERVABILITY.md)
    telemetry: Optional[Any] = None


def init_train_state(
    model,
    input_shape,
    tx: optax.GradientTransformation,
    topo: Topology,
    algo: str,
    event_cfg: Optional[EventConfig] = None,
    seed: int = 0,
    input_dtype=jnp.float32,
    arena: bool = False,
    bucketed: int = 1,
    staleness: int = 0,
    resident_wire=None,
    sparse_cfg: Optional[SparseConfig] = None,
) -> TrainState:
    """Build a stacked TrainState for `topo.n_ranks` ranks.

    `bucketed=K` (arena event runs only) carries the EventState receive
    buffers in the K-bucket layout of the bucketed gossip schedule
    (parallel/arena.py ArenaSpec.buckets) — the layout the bucketed
    train step consumes; see EventState.init.

    `resident_wire` ('bf16' | 'int8'; arena event runs only) carries
    the receive buffers CARRIER-RESIDENT — stored in the wire dtype
    with per-leaf int8 dequant scales in EventState.buf_scales — the
    layout the carrier_resident train step consumes; see
    EventState.init.

    On accelerator backends the whole build — flax init (hundreds of
    small ops for a ResNet), optimizer/event/sparse state, stacking, PRNG
    split — runs as ONE jit call: eagerly it is one device round-trip per
    op, which over the axon TPU tunnel measured ~0.4 s each (216 s for a
    bare `ResNet18.init`, round-4 stage probe). On CPU the build stays
    eager: dispatch is ~free there, and a jit here would retrace per call
    (the closure over model/tx is fresh each time — train() constructs
    its optax transform per call, so no cache key survives).
    """

    def _build(root):
        variables = model.init(
            root, jnp.zeros((1,) + tuple(input_shape), input_dtype)
        )
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        opt_state = tx.init(params)

        event = None
        sparse = None
        if algo in ("eventgrad", "sp_eventgrad"):
            # arena=True stores the neighbor receive buffers flat (the
            # flat-arena step's layout; see EventState.init). Under
            # bounded-async, eventgrad's delivery queues live in the
            # EventState; sp's live in the SparseState payload queues —
            # its (arena-free) trigger EventState stays depth 0.
            event = EventState.init(
                params, topo, event_cfg or EventConfig(), arena=arena,
                buckets=bucketed,
                staleness=staleness if algo == "eventgrad" else 0,
                resident_wire=resident_wire,
            )
        if algo == "sp_eventgrad":
            sparse = SparseState.init(
                params, topo, cfg=sparse_cfg or SparseConfig(),
                staleness=staleness,
            )

        per_rank = TrainState(
            params=params,
            opt_state=opt_state,
            batch_stats=batch_stats,
            pass_num=jnp.zeros((), jnp.int32),
            rng=root,
            event=event,
            sparse=sparse,
        )
        stacked = stack_for_ranks(per_rank, topo)
        # decorrelate per-rank PRNG streams
        keys = jax.random.split(jax.random.fold_in(root, 1), topo.n_ranks)
        return stacked.replace(rng=keys)

    return _maybe_jit(_build)(jax.random.PRNGKey(seed))


def init_train_state_spmd(
    model,
    input_shape,
    tx: optax.GradientTransformation,
    topo: Topology,
    algo: str,
    event_cfg: Optional[EventConfig] = None,
    seed: int = 0,
    input_dtype=jnp.float32,
    arena: bool = False,
    bucketed: int = 1,
    staleness: int = 0,
    resident_wire=None,
    sparse_cfg: Optional[SparseConfig] = None,
) -> TrainState:
    """Per-rank initialization inside the SPMD context — required when the
    topology has `sharded_axes` (tensor/expert parallelism): sharded layers
    fold the axis index into their own initializers (models/tp.py
    `sharded_lecun_init`), so they need `lax.axis_index` available at init
    time. Every rank receives the same root key; replicated parameters come
    out identical mesh-wide, sharded kernels distinct per TP rank. Runs on
    the vmap simulator (init is cheap); the resulting stacked state works
    under either backend."""
    from eventgrad_tpu.parallel.spmd import spmd

    def per_rank_init(key):
        variables = model.init(key, jnp.zeros((1,) + tuple(input_shape), input_dtype))
        params = variables["params"]
        event = None
        sparse = None
        if algo in ("eventgrad", "sp_eventgrad"):
            event = EventState.init(
                params, topo, event_cfg or EventConfig(), arena=arena,
                buckets=bucketed,
                staleness=staleness if algo == "eventgrad" else 0,
                resident_wire=resident_wire,
            )
        if algo == "sp_eventgrad":
            sparse = SparseState.init(
                params, topo, cfg=sparse_cfg or SparseConfig(),
                staleness=staleness,
            )
        return TrainState(
            params=params,
            opt_state=tx.init(params),
            batch_stats=variables.get("batch_stats", {}),
            pass_num=jnp.zeros((), jnp.int32),
            rng=key,
            event=event,
            sparse=sparse,
        )

    def _build(root):
        keys = jnp.broadcast_to(root, (topo.n_ranks,) + root.shape)
        state = spmd(per_rank_init, topo)(keys)
        rngs = jax.random.split(jax.random.fold_in(root, 1), topo.n_ranks)
        return state.replace(rng=rngs)

    # one compiled dispatch instead of per-op tunnel round-trips (see
    # init_train_state) — vmap without jit still dispatches eagerly
    return _maybe_jit(_build)(jax.random.PRNGKey(seed))
