"""The trace auditor (eventgrad_tpu/analysis/): walker units, the
rank-isolation dataflow, the clean full config matrix, and the seeded
oracle violations — every check proven able to fire.

Acceptance (ISSUE 9): zero violations across the full configuration
matrix, the jaxpr-derived wire-byte count equal to the accounting
formula AND to the executed step's `sent_bytes_wire_real` metric
EXACTLY (masked and compact wires), and each seeded violation class
(rank coupling, byte-formula drift, host sync, dtype promotion, extra
ravel) detected.  tools/audit.py commits the same story as the
schema-gated artifacts/audit_cpu.json.
"""

import jax
import jax.numpy as jnp
import pytest

from _spmd import requires_shard_map
from jax import lax

from eventgrad_tpu.analysis import audit, rankflow, walker
from eventgrad_tpu.parallel.spmd import spmd
from eventgrad_tpu.parallel.topology import Ring


# --- walker units -----------------------------------------------------------


def test_walker_counts_through_nesting():
    """iter_eqns/count_primitives see inside pjit, scan, AND cond —
    an op one nesting level down counts exactly once."""

    def inner(x):
        return jnp.concatenate([x, x])

    def f(x):
        y = jax.jit(inner)(x)  # pjit sub-jaxpr

        def body(c, t):
            return c + jnp.sum(jnp.concatenate([t, t])), c

        c, _ = lax.scan(body, 0.0, jnp.zeros((2, 3)))  # scan sub-jaxpr
        z = lax.cond(
            c > 0,
            lambda v: jnp.concatenate([v, v]),
            lambda v: jnp.concatenate([v, -v]),
            x,
        )  # two cond branches
        return y, z

    jx = jax.make_jaxpr(f)(jnp.ones((3,)))
    assert walker.count_primitives(jx.jaxpr, "concatenate") == 4
    paths = {
        p for eqn, p in walker.iter_eqns(jx.jaxpr)
        if eqn.primitive.name == "concatenate"
    }
    assert any("scan" in p for p in paths)
    assert any("cond" in p for p in paths)
    census = walker.primitive_census(jx.jaxpr)
    assert census["concatenate"] == 4


def test_walker_full_ravel_counts_trailing_dim():
    def f(a, b):
        return jnp.concatenate([a, b], axis=-1), jnp.concatenate([a, a], -1)

    jx = jax.make_jaxpr(f)(jnp.ones((4, 6)), jnp.ones((4, 4)))
    assert walker.count_full_ravels(jx.jaxpr, 10) == 1
    assert walker.count_full_ravels(jx.jaxpr, 12) == 1
    assert walker.count_full_ravels(jx.jaxpr, 7) == 0


# --- rankflow units ---------------------------------------------------------


def _lift_jaxpr(fn, *args):
    topo = Ring(audit.N_RANKS)
    return jax.make_jaxpr(spmd(fn, topo))(*args), topo


def test_rankflow_clean_pointwise_program():
    x = jnp.ones((audit.N_RANKS, 8))
    jx, _ = _lift_jaxpr(lambda v: jnp.tanh(v) * 2 + jnp.sum(v), x)
    rep = rankflow.analyze(jx, audit.N_RANKS)
    assert rep.violations == [] and rep.exchanges == [] and rep.psums == []


def test_rankflow_detects_ppermute_and_offset():
    def f(v):
        return lax.ppermute(
            v, "ring",
            [((r + 1) % audit.N_RANKS, r) for r in range(audit.N_RANKS)],
        )

    x = jnp.ones((audit.N_RANKS, 8))
    jx, _ = _lift_jaxpr(f, x)
    rep = rankflow.analyze(jx, audit.N_RANKS)
    assert rep.violations == []
    assert rep.exchange_offsets() == [1]
    assert rep.exchanges[0].lane_shape == (8,)
    assert rep.exchanges[0].dtype == "float32"


def test_rankflow_flags_psum_and_cross_rank_reduce():
    x = jnp.ones((audit.N_RANKS, 8))
    jx, _ = _lift_jaxpr(lambda v: lax.pmean(v, "ring"), x)
    rep = rankflow.analyze(jx, audit.N_RANKS)
    assert rep.psums and rep.violations == []

    # a positional reduction over the stacked rank axis OUTSIDE the
    # per-rank fn is a violation, not a psum
    def leak(state):
        return state + jnp.sum(state, axis=0, keepdims=True)

    jx2 = jax.make_jaxpr(leak)(x)
    rep2 = rankflow.analyze(jx2, audit.N_RANKS)
    assert rep2.violations
    assert "reduces over the rank axis" in rep2.violations[0].reason


def test_rankflow_tracks_through_scan_over_time():
    """A step scanned over TIME (rank axis in the carry, time leading
    the xs) audits clean — the dispatch-block shape of the train loop."""

    def step(v):
        got = lax.ppermute(
            v, "ring",
            [((r + 1) % audit.N_RANKS, r) for r in range(audit.N_RANKS)],
        )
        return (v + got) * 0.5

    topo = Ring(audit.N_RANKS)
    lifted = spmd(step, topo)

    def scanned(v0, ts):
        def body(c, _):
            return lifted(c), jnp.sum(c, axis=tuple(range(1, c.ndim)))

        return lax.scan(body, v0, ts)

    x = jnp.ones((audit.N_RANKS, 8))
    jx = jax.make_jaxpr(scanned)(x, jnp.arange(3.0))
    rep = rankflow.analyze(jx, audit.N_RANKS)
    assert rep.violations == []
    assert rep.exchange_offsets() == [1]


def test_rankflow_counts_cond_and_scan_exchanges_once():
    """One runtime exchange is ONE recorded exchange: a ppermute inside
    both branches of a cond, or inside a scan whose carry needs a second
    fixpoint pass, must not double the derived wire bytes — and cond
    branches shipping DIFFERENT wires is itself a violation."""
    perm = [((r + 1) % audit.N_RANKS, r) for r in range(audit.N_RANKS)]
    topo = Ring(audit.N_RANKS)

    def shift(v):
        return lax.ppermute(v, "ring", perm)

    lifted = spmd(shift, topo)
    x = jnp.ones((audit.N_RANKS, 8))

    # a rank-invariant predicate keeps lax.cond a real cond primitive
    # (a rank-dependent one is batched into run-both+select by vmap, in
    # which case both exchanges genuinely execute and both count)
    def cond_prog(v, flag):
        return lax.cond(flag > 0, lifted, lifted, v)

    rep = rankflow.analyze(
        jax.make_jaxpr(cond_prog)(x, jnp.float32(1.0)), audit.N_RANKS
    )
    assert rep.violations == []
    assert len(rep.exchanges) == 1  # both branches agree: counted once

    # a scan whose carry starts rank-invariant (zeros built inline)
    # takes a second fixpoint pass; the body's exchange still counts once
    def scanned(v, ts):
        def body(c, _):
            return lifted(c + v), jnp.sum(c, axis=1)

        return lax.scan(body, jnp.zeros((audit.N_RANKS, 8)), ts)

    rep2 = rankflow.analyze(
        jax.make_jaxpr(scanned)(x, jnp.arange(2.0)), audit.N_RANKS
    )
    assert rep2.violations == []
    assert len(rep2.exchanges) == 1

    def asym_prog(v, flag):
        return lax.cond(flag > 0, lifted, lambda u: u * 1.0, v)

    rep3 = rankflow.analyze(
        jax.make_jaxpr(asym_prog)(x, jnp.float32(1.0)), audit.N_RANKS
    )
    assert any("different exchange lanes" in v.reason
               for v in rep3.violations)


def test_rankflow_flags_scan_over_ranks():
    def over_ranks(state):
        def body(c, row):
            return c + jnp.sum(row), c

        return lax.scan(body, 0.0, state)  # leading axis IS the rank axis

    jx = jax.make_jaxpr(over_ranks)(jnp.ones((audit.N_RANKS, 8)))
    rep = rankflow.analyze(jx, audit.N_RANKS)
    assert any("scan iterates OVER the rank axis" in v.reason
               for v in rep.violations)


# --- the clean matrix -------------------------------------------------------


@pytest.mark.parametrize("name", [c.name for c in audit.CONFIGS])
def test_audit_matrix_config_clean(name):
    """Every cell: zero rank-isolation violations, declared offsets
    only, wire bytes derived == formula == executed metric EXACTLY,
    ravel budget, no callbacks, donation aliasing where checked."""
    r = audit.audit_config(audit.config_by_name(name), run_metric=True)
    assert r["violations"] == 0, r["violation_details"]
    assert r["undeclared_offsets"] == [] and r["missing_offsets"] == []
    assert r["wire_problems"] == []
    assert (
        r["wire_bytes_per_neighbor_derived"]
        == r["wire_bytes_per_neighbor_formula"]
    )
    assert r["metric_match"] is True, (
        r["wire_metric_total"], r["wire_bytes_per_neighbor_derived"]
    )
    assert r["ravel_ok"], (r["ravel_count"], r["ravel_budget"])
    assert r["callbacks"] == 0
    assert r["donation_ok"] in (None, True), r["donation_note"]
    assert audit.clean(r)


def test_integrity_checksum_is_a_declared_rider():
    """The integrity checksum ships one int32 per neighbor OUTSIDE the
    wire-byte formula — visible to the auditor, excluded by contract,
    and absent with integrity off."""
    on = audit.audit_config(
        audit.config_by_name("event_masked_f32_arena_integrity"),
        run_metric=False,
    )
    off = audit.audit_config(
        audit.config_by_name("event_masked_f32_arena_obs"),
        run_metric=False,
    )
    assert on["wire_rider_bytes_per_neighbor"] == 4.0
    assert off["wire_rider_bytes_per_neighbor"] == 0.0
    assert (
        on["wire_bytes_per_neighbor_derived"]
        == off["wire_bytes_per_neighbor_derived"]
    )


# --- the oracle legs --------------------------------------------------------


@pytest.mark.parametrize("name", sorted(audit.ORACLES))
def test_oracle_violation_detected(name):
    """Each seeded violation class is flagged — a check that cannot
    fire proves nothing."""
    detected, reason = audit.ORACLES[name]()
    assert detected, f"oracle {name} NOT detected: {reason}"


def test_oracles_leave_no_monkeypatch_behind():
    """The dtype/formula oracles sabotage collectives functions under
    try/finally; a clean config audited afterwards is still clean."""
    audit.ORACLES["wire_dtype_upcast"]()
    audit.ORACLES["byte_formula_drift"]()
    r = audit.audit_config(
        audit.config_by_name("event_masked_bf16_arena"), run_metric=True
    )
    assert audit.clean(r)


# --- the real-mesh lift -----------------------------------------------------


@requires_shard_map
def test_audit_shard_lift_clean():
    """Under the shard_map lift the per-rank collectives stay explicit:
    only ppermutes at the declared offsets (plus axis_index) appear in
    the traced program, and the hygiene checks hold."""
    if len(jax.devices()) < audit.N_RANKS:
        pytest.skip(f"needs {audit.N_RANKS} devices")
    r = audit.audit_shard_lift(audit.config_by_name("event_masked_f32_tree"))
    assert r["offsets_ok"], (r["exchange_offsets"], r["declared_offsets"])
    assert r["undeclared_collectives"] == []
    assert r["callbacks"] == 0
