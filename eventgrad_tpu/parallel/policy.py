"""Trigger policies: WHO fires and WHAT ships, as a pluggable layer.

The repo's communication decisions used to be hard-wired: the norm-delta
trigger lived inline in the event branches of train/steps.py and
sp_eventgrad's top-k selection rode a bespoke side path in
parallel/sparsify.py. This module factors the decision out of the
engine as a `TriggerPolicy` — the same propose/commit split the engine
already has (parallel/events.py), plus a static `WireSpec` that names
which gossip wires the policy's payload can ride. The step builders
(train/steps.py), the train loop's wire autotune (train/loop.py), and
the CLI/bench guards all consult the registry instead of matching on
algo names, so a new selection rule lands as one registered class.

Registered policies:

  norm_delta  The EventGraD trigger exactly as before (event.cpp
              :320-390 via events.propose/commit). The base class
              delegates to the SAME function objects the engine always
              called and adds no masks, so the built step's jaxpr is
              identical to the pre-refactor path — bitwise, not just
              numerically (tests/test_policy.py pins full TrainState +
              metrics across the masked|compact x dtype x staleness x
              bucketed matrix).

  topk        sp_eventgrad's magnitude top-k, migrated off its bespoke
              SparseState gate: the norm-delta proposal still drives
              the per-leaf fire bits (same trigger state machine), and
              the payload helpers (`topk_payload`/`scatter_into`) now
              live here — sparsify.sparse_exchange is a thin wire
              adapter over them. Its top-k wire is already physically
              sparse and statically sized, so `--gossip-wire compact`
              is a no-op alias (accepted, needs no capacity) rather
              than the error the old CLI guard raised.

  micro       Partitioned index-free sparsification after "MiCRO:
              Near-Zero Cost Gradient Sparsification" (arXiv:2310.00967,
              PAPERS.md): the parameter space is cut into static
              element-balanced leaf-aligned partitions (ArenaSpec
              .buckets — the same geometry as the bucketed gossip
              schedule), each rank ships ONLY the partition it owns,
              and ownership is implicit in the (rank, pass) pair — so
              the wire carries no index lanes at all. Offsets are
              static like the compact wire's fire-bit offsets, and the
              payload rides the existing compact static-capacity format
              (with per-bucket splits from collectives.split_capacity
              under bucketed=K) at capacity >= the largest partition.
              DEVIATION from MiCRO's allreduce setting, by design:
              ownership ROTATES — rank r owns partition
              (r + pass_num) mod K. MiCRO's server sees every
              partition every round; a gossip neighbor only sees what
              its peers ship, so static ownership would freeze the
              non-owned (K-1)/K of every receive buffer at its zero
              init forever. Rotation bounds per-coordinate buffer
              staleness by K passes instead (docs/compaction.md).
              Second deviation, measured not assumed: suppression
              engages only after the trigger's warmup full-fire
              (`cfg.warmup_passes`) — see Micro's class doc for the
              collapse it prevents.

  hybrid      norm-delta gate x partitioned payload: a leaf ships only
              when the EventGraD trigger fires AND it lies in the
              owned partition of the pass. Suppressed leaves are never
              committed (the propose/commit rollback), so their
              thresholds keep decaying and they re-contend when their
              partition rotates back in — the gate semantics are the
              trigger's, the wire cost micro's.

Partition masks are plain static tuples (`partition_masks`), validated
by `validate_partitions` and audited per micro/hybrid matrix cell with
a seeded `partition_overlap` oracle (analysis/audit.py): a partition
geometry that double-claims or drops a leaf cannot land silently.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from eventgrad_tpu.parallel import events
from eventgrad_tpu.parallel.arena import ArenaSpec
from eventgrad_tpu.parallel.topology import Topology


# ---------------------------------------------------------------------------
# wire capabilities


@dataclasses.dataclass(frozen=True)
class WireSpec:
    """Static wire capabilities of a policy — what the guards consult.

    algos: the train-step branches the policy can drive ("eventgrad"
        rides the masked/compact event exchange, "sp_eventgrad" the
        physically-sparse top-k wire).
    gossip_wires: the --gossip-wire modes the payload can ride.
    indexed: the wire carries int32 index lanes per shipped value
        (top-k). Partitioned policies are index-free by construction.
    partitioned: payload restricted to the rotating owned partition.
    compact_needs_capacity: "compact" needs a static element budget
        (the autotune/--compact-capacity machinery). False when the
        wire is already statically sized without one — sp_eventgrad's
        top-k lanes — so compact is accepted as a no-op alias and the
        loop skips the dense warmup/rebuild entirely.
    """

    algos: Tuple[str, ...]
    gossip_wires: Tuple[str, ...]
    indexed: bool = False
    partitioned: bool = False
    compact_needs_capacity: bool = True


# ---------------------------------------------------------------------------
# partition geometry (micro / hybrid)


def partition_masks(
    spec: ArenaSpec, n_parts: int
) -> Tuple[Tuple[bool, ...], ...]:
    """Static per-partition leaf masks, [K][L] bools.

    Partition geometry IS the bucketed gossip geometry —
    ArenaSpec.buckets(n_parts): contiguous, leaf-aligned, element-
    balanced cuts, K clamped to the leaf count. Returned as plain
    tuples so the audit can validate the exact object the traced step
    consumes (ownership_vec stacks these), and so the seeded
    `partition_overlap` oracle can sabotage it in one place.
    """
    parts = spec.buckets(int(n_parts))
    return tuple(
        tuple(b.lo <= leaf < b.hi for leaf in range(spec.n_leaves))
        for b in parts
    )


def partition_table(spec: ArenaSpec, n_parts: int) -> Tuple[Dict[str, int], ...]:
    """Declared partition offsets — start/size element ranges per
    partition, published in the audit report exactly like the compact
    wire's fire-bit offsets (analysis/audit.py `partitions`)."""
    return tuple(
        {"index": b.index, "lo": b.lo, "hi": b.hi,
         "start": b.start, "size": b.size}
        for b in spec.buckets(int(n_parts))
    )


def validate_partitions(spec: ArenaSpec, n_parts: int) -> Dict[str, Any]:
    """Check the partition geometry's three invariants on the mask
    object itself (not the bucket metadata it was derived from — the
    oracle sabotages the masks, and this must catch it):

      disjoint     no leaf claimed by two partitions
      exact_cover  every leaf claimed by exactly one
      balanced     max partition size <= ceil(n_total/K) + largest
                   leaf (the best any leaf-aligned cut can guarantee)
    """
    masks = partition_masks(spec, n_parts)
    k = len(masks)
    claims = [sum(m[leaf] for m in masks) for leaf in range(spec.n_leaves)]
    disjoint = all(c <= 1 for c in claims)
    exact_cover = all(c == 1 for c in claims)
    sizes = [
        sum(sz for sz, on in zip(spec.sizes, m) if on) for m in masks
    ]
    bound = -(-spec.n_total // max(1, k)) + max(spec.sizes)
    balanced = bool(sizes) and max(sizes) <= bound
    return {
        "n_partitions": k,
        "sizes": sizes,
        "max_partition_elems": max(sizes) if sizes else 0,
        "disjoint": bool(disjoint),
        "exact_cover": bool(exact_cover),
        "balanced": bool(balanced),
        "ok": bool(disjoint and exact_cover and balanced),
    }


def max_partition_elems(spec: ArenaSpec, n_parts: int) -> int:
    """The compact capacity floor of a partitioned policy: the largest
    partition must ship whole (tools/frontier_sweep.py pins the sweep's
    shared element budget to this)."""
    return max(b.size for b in spec.buckets(int(n_parts)))


def ownership_vec(
    spec: ArenaSpec, topo: Topology, pass_num: jnp.ndarray
) -> jnp.ndarray:
    """bool [L]: the leaves of the partition THIS rank owns THIS pass.

    Rank identity is the row-major ravel of the per-axis lax.axis_index
    coordinates (the traced twin of Topology's rank numbering, same
    construction as chaos.inject.rank_and_sources — inlined here so
    parallel/ does not import chaos/). Ownership rotates:
    partition (rank + pass_num) mod K — see the module doc for why
    static MiCRO ownership is unsound under gossip.

    The masks are a replicated [K, L] constant; the dynamic index is
    the per-rank scalar `mine`, a gather over the constant's leading
    axis — no cross-rank data movement (the rankflow auditor sees a
    plain batched gather on a broadcast operand).
    """
    masks = jnp.asarray(partition_masks(spec, topo.n_ranks), bool)
    r = jnp.int32(0)
    for axis, size in zip(topo.axes, topo.shape):
        r = r * jnp.int32(size) + lax.axis_index(axis).astype(jnp.int32)
    k = masks.shape[0]
    mine = (r + jnp.asarray(pass_num, jnp.int32)) % jnp.int32(k)
    return masks[mine]


# ---------------------------------------------------------------------------
# top-k payload helpers (moved from parallel/sparsify.py — the policy owns
# selection; sparsify.sparse_exchange stays as the wire adapter over these)


def topk_payload(params: Any, prev_sent: Any, cfg) -> Tuple[Any, Any]:
    """Per-leaf (values, indices) of the k largest |p - prev_sent|
    entries (spevent.cpp:344-363): selection metric is the drift from
    the sender shadow, values sent are the CURRENT parameter at those
    indices, k = cfg.k_for(numel) is static under jit. Moved verbatim
    from parallel/sparsify.py — the topk policy owns selection;
    sparsify.sparse_exchange is the wire adapter over it.
    """

    def leaf(p, prev):
        flat = p.reshape(-1)
        diff = jnp.abs(flat - prev.reshape(-1))
        k = cfg.k_for(flat.size)
        _, idx = lax.top_k(diff, k)
        return flat[idx], idx.astype(jnp.int32)

    out = jax.tree.map(lambda p, q: leaf(p, q), params, prev_sent)
    vals = jax.tree.map(
        lambda t: t[0], out, is_leaf=lambda t: isinstance(t, tuple)
    )
    idxs = jax.tree.map(
        lambda t: t[1], out, is_leaf=lambda t: isinstance(t, tuple)
    )
    return vals, idxs


def scatter_into(full: Any, vals: Any, idxs: Any, gate: Any) -> Any:
    """Write `vals` at flat positions `idxs` of each leaf of `full`, but
    only where the per-leaf `gate` bit is set (receiver path
    spevent.cpp:438-448; sender prev_sent update :406-413 uses
    gate=fire). Moved verbatim from parallel/sparsify.py."""

    def leaf(f, v, i, g):
        scattered = f.reshape(-1).at[i].set(v).reshape(f.shape)
        return jnp.where(g, scattered, f)

    return jax.tree.map(leaf, full, vals, idxs, gate)


# ---------------------------------------------------------------------------
# policies


class TriggerPolicy:
    """Base policy = the EventGraD norm-delta trigger, whole.

    init_state/propose/commit delegate to the SAME events.* function
    objects the pre-refactor step branches called inline — no wrapper
    logic, no extra ops — so a policy that overrides nothing builds a
    trace-identical step. Subclasses specialize by:

      * `masks(spec, topo, pass_num, cfg)` -> (force_fire,
        suppress_fire), each None or bool [L], merged into the step's
        existing chaos force/quarantine-suppress seams (suppression is
        applied AFTER force ORs in — suppression wins, the quarantine
        precedent). Suppressed proposals are counted into num_deferred
        by commit, like any wire-budget deferral. `cfg` is the
        EventConfig: partitioned policies gate their suppression on
        `pass_num >= cfg.warmup_passes` so the trigger's warmup
        full-fire still synchronizes the ranks (see Micro).
      * `wire_spec()` -> WireSpec, the static capabilities the loop
        and CLI guards consult.
    """

    name = "base"

    def init_state(self, params, topo, cfg, *, arena=False, buckets=1,
                   staleness=0):
        return events.EventState.init(
            params, topo, cfg, arena=arena, buckets=buckets,
            staleness=staleness,
        )

    def propose(self, params, state, pass_num, cfg, force_fire=None):
        return events.propose(
            params, state, pass_num, cfg, force_fire=force_fire
        )

    def commit(self, state, prop, fire_vec, cfg, n_neighbors):
        return events.commit(state, prop, fire_vec, cfg, n_neighbors)

    def masks(
        self, spec: Optional[ArenaSpec], topo: Topology, pass_num, cfg
    ) -> Tuple[Optional[jnp.ndarray], Optional[jnp.ndarray]]:
        return None, None

    def wire_spec(self) -> WireSpec:
        raise NotImplementedError


class NormDelta(TriggerPolicy):
    """The current EventGraD trigger, extracted. Bitwise-identical to
    the legacy inline path by construction (no masks, same delegates)."""

    name = "norm_delta"

    def wire_spec(self) -> WireSpec:
        return WireSpec(
            algos=("eventgrad",),
            gossip_wires=("masked", "compact"),
        )


class TopK(TriggerPolicy):
    """sp_eventgrad's magnitude top-k selection (docs/ARCHITECTURE.md
    "Sparsified gossip"), driven by the shared norm-delta trigger state.
    Its wire is physically sparse and statically sized already, so
    "compact" is a capacity-free no-op alias of its native wire."""

    name = "topk"

    def wire_spec(self) -> WireSpec:
        return WireSpec(
            algos=("sp_eventgrad",),
            gossip_wires=("masked", "compact"),
            indexed=True,
            compact_needs_capacity=False,
        )


class Micro(TriggerPolicy):
    """MiCRO-style partitioned sends, rotated for gossip (module doc).

    force = owned (the owned partition ships every pass — selection is
    positional, near-zero cost, no trigger arithmetic on the payload),
    suppress = ~owned (nothing outside the partition ever ships, so
    the wire needs no index lanes). The compact capacity floor is the
    largest partition (`max_partition_elems`).

    Suppression engages only at `pass_num >= cfg.warmup_passes`: the
    trigger's warmup full-fire must still synchronize the ranks.
    Measured (LeNetCifar/Ring(8), the frontier op point): suppressing
    the warmup leaves early training unsynchronized under the violent
    first SGD steps and the run collapses to a dead uniform-output
    equilibrium it never leaves — loss pinned at ln(10) for 960 passes
    at every learning rate tried — while the warmup-synced run reaches
    99.6% in 10 epochs. Warmup passes full-fire exactly like
    norm_delta's, so the wire cost of the exception is the warmup the
    trigger already pays."""

    name = "micro"

    def masks(self, spec, topo, pass_num, cfg):
        owned = ownership_vec(spec, topo, pass_num)
        not_warm = (
            jnp.asarray(pass_num, jnp.int32)
            >= jnp.int32(cfg.warmup_passes)
        )
        return owned, (~owned) & not_warm

    def wire_spec(self) -> WireSpec:
        return WireSpec(
            algos=("eventgrad",),
            gossip_wires=("masked", "compact"),
            partitioned=True,
        )


class Hybrid(TriggerPolicy):
    """norm-delta gate x micro payload: fire = trigger AND owned. The
    gate stays adaptive (thresholds decay while suppressed, deferred
    leaves re-contend when their partition rotates back in); the wire
    stays index-free. Suppression engages post-warmup only, same
    rationale as Micro."""

    name = "hybrid"

    def masks(self, spec, topo, pass_num, cfg):
        owned = ownership_vec(spec, topo, pass_num)
        not_warm = (
            jnp.asarray(pass_num, jnp.int32)
            >= jnp.int32(cfg.warmup_passes)
        )
        return None, (~owned) & not_warm

    def wire_spec(self) -> WireSpec:
        return WireSpec(
            algos=("eventgrad",),
            gossip_wires=("masked", "compact"),
            partitioned=True,
        )


# ---------------------------------------------------------------------------
# registry


POLICIES: Dict[str, TriggerPolicy] = {
    p.name: p for p in (NormDelta(), TopK(), Micro(), Hybrid())
}

#: the policy an algo runs when train(trigger_policy=None) — the exact
#: pre-refactor behavior of each branch
DEFAULT_FOR_ALGO: Dict[str, str] = {
    "eventgrad": "norm_delta",
    "sp_eventgrad": "topk",
}


def resolve(name: Optional[str], algo: str) -> TriggerPolicy:
    """The policy instance `algo` runs: the registered `name`, or the
    algo's default when None. Raises ValueError for unknown names,
    algos with no event trigger (dpsgd), and policy/algo mismatches —
    the single guard train/loop.py and cli.py both call."""
    if name is None:
        default = DEFAULT_FOR_ALGO.get(algo)
        if default is None:
            raise ValueError(
                f"--algo {algo} has no event trigger; trigger policies "
                f"apply to {sorted(DEFAULT_FOR_ALGO)}"
            )
        return POLICIES[default]
    pol = POLICIES.get(name)
    if pol is None:
        raise ValueError(
            f"unknown trigger policy {name!r}; registered: "
            f"{sorted(POLICIES)} (parallel/policy.py)"
        )
    if algo not in pol.wire_spec().algos:
        raise ValueError(
            f"trigger policy {name!r} drives "
            f"{'/'.join(pol.wire_spec().algos)}, not --algo {algo}"
        )
    return pol
