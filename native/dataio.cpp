// eventgrad-tpu native data pipeline.
//
// TPU-native replacement for the reference's C++ data layer: the OpenCV JPEG
// walker + label map of /root/reference/dcifar10/common/custom.hpp:26-122 and
// libtorch's MNIST reader (used at dmnist/cent/cent.cpp:53-56). On TPU the
// only host-side jobs are bulk IO, deterministic shard/shuffle planning, and
// contiguous batch assembly (pixels are augmented on-device); those are
// exactly what this library does, exposed as a C ABI consumed from Python via
// ctypes (no pybind11 in this image).
//
// Everything is deterministic: shuffling uses splitmix64 seeded by
// (seed, epoch), mirroring the reference's per-epoch reshuffle of its path
// list (custom.hpp:119-120) without the hidden global RNG.
//
// Build: `make -C native` (plain g++ -O3 -shared; no external deps).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

#ifdef EG_HAVE_LIBJPEG
#include <setjmp.h>
#include <jpeglib.h>
#endif

extern "C" {

// ---------------------------------------------------------------------------
// deterministic RNG (splitmix64) — stable across platforms, unlike std::mt19937
// usage patterns that depend on distribution implementations.
// ---------------------------------------------------------------------------
static inline uint64_t splitmix64(uint64_t &state) {
  uint64_t z = (state += 0x9E3779B97F4A7C15ULL);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

// ---------------------------------------------------------------------------
// CIFAR-10 binary batches: each record is 1 label byte + 3072 CHW bytes.
// Returns number of samples written, or -1 on IO error.
// Output images are NHWC float32 in [0,1]; labels int32.
// ---------------------------------------------------------------------------
int64_t eg_load_cifar10_file(const char *path, float *images, int32_t *labels,
                             int64_t max_samples) {
  FILE *f = fopen(path, "rb");
  if (!f) return -1;
  const int64_t rec = 1 + 3 * 32 * 32;
  unsigned char buf[1 + 3 * 32 * 32];
  int64_t n = 0;
  const float inv = 1.0f / 255.0f;
  while (n < max_samples && fread(buf, 1, rec, f) == (size_t)rec) {
    labels[n] = (int32_t)buf[0];
    float *out = images + n * 32 * 32 * 3;
    // CHW uint8 -> HWC float
    for (int c = 0; c < 3; ++c) {
      const unsigned char *plane = buf + 1 + c * 32 * 32;
      for (int hw = 0; hw < 32 * 32; ++hw) {
        out[hw * 3 + c] = (float)plane[hw] * inv;
      }
    }
    ++n;
  }
  fclose(f);
  return n;
}

// ---------------------------------------------------------------------------
// MNIST idx files (big-endian headers).
// images path + labels path -> NHWC float32 (normalized if mean/std given).
// Returns sample count or -1.
// ---------------------------------------------------------------------------
static uint32_t be32(const unsigned char *p) {
  return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
         ((uint32_t)p[2] << 8) | (uint32_t)p[3];
}

int64_t eg_load_mnist(const char *images_path, const char *labels_path,
                      float *images, int32_t *labels, int64_t max_samples,
                      float mean, float std) {
  FILE *fi = fopen(images_path, "rb");
  if (!fi) return -1;
  unsigned char hdr[16];
  if (fread(hdr, 1, 16, fi) != 16) { fclose(fi); return -1; }
  int64_t n = be32(hdr + 4), rows = be32(hdr + 8), cols = be32(hdr + 12);
  if (n > max_samples) n = max_samples;
  const int64_t px = rows * cols;
  unsigned char *row = new unsigned char[px];
  const float inv = 1.0f / 255.0f;
  const float s = (std > 0.0f) ? (1.0f / std) : 1.0f;
  for (int64_t i = 0; i < n; ++i) {
    if (fread(row, 1, px, fi) != (size_t)px) { n = i; break; }
    float *out = images + i * px;
    for (int64_t j = 0; j < px; ++j)
      out[j] = ((float)row[j] * inv - mean) * s;
  }
  delete[] row;
  fclose(fi);

  FILE *fl = fopen(labels_path, "rb");
  if (!fl) return -1;
  unsigned char lhdr[8];
  if (fread(lhdr, 1, 8, fl) != 8) { fclose(fl); return -1; }
  unsigned char *lab = new unsigned char[n];
  int64_t got = (int64_t)fread(lab, 1, n, fl);
  for (int64_t i = 0; i < got; ++i) labels[i] = (int32_t)lab[i];
  delete[] lab;
  fclose(fl);
  return (got < n) ? got : n;
}

// ---------------------------------------------------------------------------
// Distributed shard plan — the reference's samplers as one call
// (DistributedRandomSampler / DistributedSequentialSampler,
//  cent.cpp:59-60, decent.cpp:81-82): disjoint 1/N shards, optionally a
// global Fisher-Yates permutation reseeded per (seed, epoch).
// out_idx has space for n_ranks * (n / n_ranks) int64s.
// ---------------------------------------------------------------------------
void eg_shard_plan(int64_t n, int64_t n_ranks, uint64_t seed, uint64_t epoch,
                   int shuffle, int64_t *out_idx) {
  const int64_t per = n / n_ranks;
  const int64_t total = per * n_ranks;
  if (!shuffle) {
    for (int64_t i = 0; i < total; ++i) out_idx[i] = i;
    return;
  }
  int64_t *perm = new int64_t[n];
  for (int64_t i = 0; i < n; ++i) perm[i] = i;
  uint64_t st = seed * 0x9E3779B97F4A7C15ULL + epoch + 1;
  for (int64_t i = n - 1; i > 0; --i) {
    int64_t j = (int64_t)(splitmix64(st) % (uint64_t)(i + 1));
    int64_t t = perm[i]; perm[i] = perm[j]; perm[j] = t;
  }
  memcpy(out_idx, perm, total * sizeof(int64_t));
  delete[] perm;
}

// ---------------------------------------------------------------------------
// Batch assembly: gather rows of a contiguous [n, elem] float array into
// [count, elem] following idx — the contiguous-marshalling role the reference
// performs per-tensor with flatten+memcpy (dcifar10/event/event.cpp:292-297),
// applied host-side to sample batches before one device_put.
// ---------------------------------------------------------------------------
void eg_gather(const float *src, int64_t elem, const int64_t *idx,
               int64_t count, float *dst) {
  const size_t bytes = (size_t)elem * sizeof(float);
  for (int64_t i = 0; i < count; ++i)
    memcpy(dst + i * elem, src + idx[i] * elem, bytes);
}

void eg_gather_i32(const int32_t *src, const int64_t *idx, int64_t count,
                   int32_t *dst) {
  for (int64_t i = 0; i < count; ++i) dst[i] = src[idx[i]];
}

// ---------------------------------------------------------------------------
// JPEG pipeline — the role OpenCV plays in the reference (cv::imread +
// cv::resize to image_size, custom.hpp:33-41), on libjpeg with a bilinear
// resampler (half-pixel centers, cv::INTER_LINEAR's mapping). Output is RGB
// interleaved; the reference reads BGR and reorders to RGB itself
// (custom.hpp:45-59) — same end state. The encoder exists for fixture
// generation and dataset export (no network egress in dev environments).
//
// Return codes: 0 ok; -1 io error; -2 image larger than caller capacity;
// -3 malformed stream; -9 built without libjpeg.
// ---------------------------------------------------------------------------
#ifdef EG_HAVE_LIBJPEG

struct EgJpegErr {
  struct jpeg_error_mgr mgr;
  jmp_buf jb;
};

static void eg_jpeg_error_exit(j_common_ptr cinfo) {
  longjmp(((EgJpegErr *)cinfo->err)->jb, 1);  // default handler exit()s
}

int eg_jpeg_supported(void) { return 1; }

// header-only parse: dimensions without decoding (cheap — a few KB of IO)
int eg_jpeg_header(const char *path, int32_t *w, int32_t *h) {
  FILE *f = fopen(path, "rb");
  if (!f) return -1;
  struct jpeg_decompress_struct cinfo;
  EgJpegErr err;
  cinfo.err = jpeg_std_error(&err.mgr);
  err.mgr.error_exit = eg_jpeg_error_exit;
  if (setjmp(err.jb)) {
    jpeg_destroy_decompress(&cinfo);
    fclose(f);
    return -3;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_stdio_src(&cinfo, f);
  jpeg_read_header(&cinfo, TRUE);
  *w = (int32_t)cinfo.image_width;
  *h = (int32_t)cinfo.image_height;
  jpeg_destroy_decompress(&cinfo);
  fclose(f);
  return 0;
}

int eg_jpeg_decode_file(const char *path, uint8_t *out, int32_t cap_w,
                        int32_t cap_h, int32_t *w, int32_t *h) {
  FILE *f = fopen(path, "rb");
  if (!f) return -1;
  struct jpeg_decompress_struct cinfo;
  EgJpegErr err;
  cinfo.err = jpeg_std_error(&err.mgr);
  err.mgr.error_exit = eg_jpeg_error_exit;
  if (setjmp(err.jb)) {
    jpeg_destroy_decompress(&cinfo);
    fclose(f);
    return -3;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_stdio_src(&cinfo, f);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;  // grayscale/YCbCr all land as RGB
  jpeg_start_decompress(&cinfo);
  *w = (int32_t)cinfo.output_width;
  *h = (int32_t)cinfo.output_height;
  if (*w > cap_w || *h > cap_h) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    fclose(f);
    return -2;
  }
  const int stride = *w * 3;
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = out + (size_t)cinfo.output_scanline * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  fclose(f);
  return 0;
}

int eg_jpeg_encode_file(const char *path, const uint8_t *rgb, int32_t w,
                        int32_t h, int32_t quality) {
  FILE *f = fopen(path, "wb");
  if (!f) return -1;
  struct jpeg_compress_struct cinfo;
  EgJpegErr err;
  cinfo.err = jpeg_std_error(&err.mgr);
  err.mgr.error_exit = eg_jpeg_error_exit;
  if (setjmp(err.jb)) {
    jpeg_destroy_compress(&cinfo);
    fclose(f);
    return -3;
  }
  jpeg_create_compress(&cinfo);
  jpeg_stdio_dest(&cinfo, f);
  cinfo.image_width = (JDIMENSION)w;
  cinfo.image_height = (JDIMENSION)h;
  cinfo.input_components = 3;
  cinfo.in_color_space = JCS_RGB;
  jpeg_set_defaults(&cinfo);
  jpeg_set_quality(&cinfo, quality, TRUE);
  jpeg_start_compress(&cinfo, TRUE);
  const int stride = w * 3;
  while (cinfo.next_scanline < cinfo.image_height) {
    JSAMPROW row = (JSAMPROW)(rgb + (size_t)cinfo.next_scanline * stride);
    jpeg_write_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_compress(&cinfo);
  jpeg_destroy_compress(&cinfo);
  fclose(f);
  return 0;
}

#else  // !EG_HAVE_LIBJPEG

int eg_jpeg_supported(void) { return 0; }
int eg_jpeg_header(const char *, int32_t *, int32_t *) { return -9; }
int eg_jpeg_decode_file(const char *, uint8_t *, int32_t, int32_t, int32_t *,
                        int32_t *) { return -9; }
int eg_jpeg_encode_file(const char *, const uint8_t *, int32_t, int32_t,
                        int32_t) { return -9; }

#endif  // EG_HAVE_LIBJPEG

// Bilinear resample with half-pixel centers (cv::INTER_LINEAR's mapping),
// RGB interleaved. Identity sizes short-circuit to a memcpy.
void eg_resize_bilinear_rgb(const uint8_t *src, int32_t w, int32_t h,
                            uint8_t *dst, int32_t ow, int32_t oh) {
  if (w == ow && h == oh) {
    memcpy(dst, src, (size_t)w * h * 3);
    return;
  }
  const float sx = (float)w / (float)ow, sy = (float)h / (float)oh;
  for (int32_t y = 0; y < oh; ++y) {
    float fy = ((float)y + 0.5f) * sy - 0.5f;
    if (fy < 0) fy = 0;
    if (fy > (float)(h - 1)) fy = (float)(h - 1);
    const int32_t y0 = (int32_t)fy;
    const int32_t y1 = (y0 + 1 < h) ? y0 + 1 : y0;
    const float ty = fy - (float)y0;
    for (int32_t x = 0; x < ow; ++x) {
      float fx = ((float)x + 0.5f) * sx - 0.5f;
      if (fx < 0) fx = 0;
      if (fx > (float)(w - 1)) fx = (float)(w - 1);
      const int32_t x0 = (int32_t)fx;
      const int32_t x1 = (x0 + 1 < w) ? x0 + 1 : x0;
      const float tx = fx - (float)x0;
      for (int c = 0; c < 3; ++c) {
        const float v00 = src[((size_t)y0 * w + x0) * 3 + c];
        const float v01 = src[((size_t)y0 * w + x1) * 3 + c];
        const float v10 = src[((size_t)y1 * w + x0) * 3 + c];
        const float v11 = src[((size_t)y1 * w + x1) * 3 + c];
        const float top = v00 + (v01 - v00) * tx;
        const float bot = v10 + (v11 - v10) * tx;
        const float v = top + (bot - top) * ty;
        dst[((size_t)y * ow + x) * 3 + c] = (uint8_t)(v + 0.5f);
      }
    }
  }
}

// One-shot loader: JPEG file -> image_size x image_size RGB float32 NHWC in
// [0,1] (the framework's input convention; the reference keeps raw 0..255
// CHW floats, custom.hpp:46-59 — a constant input scale, noted in PARITY).
// Returns 0 or the decoder's error code.
int eg_load_jpeg_image(const char *path, float *out, int32_t image_size) {
#ifdef EG_HAVE_LIBJPEG
  // single pass: one fopen + header parse, buffer sized from the header
  FILE *f = fopen(path, "rb");
  if (!f) return -1;
  struct jpeg_decompress_struct cinfo;
  EgJpegErr err;
  // volatile: assigned between setjmp and a potential longjmp, read after
  uint8_t *volatile raw = nullptr;
  cinfo.err = jpeg_std_error(&err.mgr);
  err.mgr.error_exit = eg_jpeg_error_exit;
  if (setjmp(err.jb)) {
    jpeg_destroy_decompress(&cinfo);
    free(raw);
    fclose(f);
    return -3;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_stdio_src(&cinfo, f);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  const int32_t w = (int32_t)cinfo.output_width;
  const int32_t h = (int32_t)cinfo.output_height;
  raw = (uint8_t *)malloc((size_t)w * h * 3);
  if (!raw) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    fclose(f);
    return -1;
  }
  const int stride = w * 3;
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = raw + (size_t)cinfo.output_scanline * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  fclose(f);

  uint8_t *small = (uint8_t *)malloc((size_t)image_size * image_size * 3);
  if (!small) {
    free(raw);
    return -1;
  }
  eg_resize_bilinear_rgb(raw, w, h, small, image_size, image_size);
  const int64_t px = (int64_t)image_size * image_size * 3;
  const float inv = 1.0f / 255.0f;
  for (int64_t i = 0; i < px; ++i) out[i] = (float)small[i] * inv;
  free(small);
  free(raw);
  return 0;
#else
  (void)path;
  (void)out;
  (void)image_size;
  return -9;
#endif
}

int eg_version(void) { return 2; }

}  // extern "C"
