"""Sequence-parallel attention == single-device full attention, exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from _spmd import requires_shard_map
from eventgrad_tpu.parallel.ring_attention import (
    full_attention,
    ring_attention,
    ulysses_attention,
)
from eventgrad_tpu.parallel.spmd import build_mesh, spmd
from eventgrad_tpu.parallel.topology import Ring

N = 4
B, T, H, D = 2, 32, 8, 16  # global sequence T, shard T//N per rank


def _shards(key):
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, H, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, H, D), jnp.float32)

    def shard(x):
        # [B, T, H, D] -> stacked [N, B, T/N, H, D]
        return jnp.stack(jnp.split(x, N, axis=1))

    return (q, k, v), (shard(q), shard(k), shard(v))


def _unshard(out):
    # [N, B, T/N, H, D] -> [B, T, H, D]
    return jnp.concatenate(list(out), axis=1)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize(
    "backend",
    ["vmap", pytest.param("shard_map", marks=requires_shard_map)],
)
def test_ring_attention_matches_full(causal, backend):
    topo = Ring(N)
    (q, k, v), (qs, ks, vs) = _shards(jax.random.PRNGKey(0))

    def fn(q, k, v):
        return ring_attention(q, k, v, topo, causal=causal)

    mesh = build_mesh(topo) if backend == "shard_map" else None
    out = _unshard(spmd(fn, topo, mesh=mesh)(qs, ks, vs))
    expect = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_full(causal):
    topo = Ring(N)
    (q, k, v), (qs, ks, vs) = _shards(jax.random.PRNGKey(1))

    def fn(q, k, v):
        return ulysses_attention(q, k, v, topo, causal=causal)

    out = _unshard(spmd(fn, topo)(qs, ks, vs))
    expect = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect), atol=2e-5)


def test_ulysses_rejects_bad_head_count():
    topo = Ring(N)
    q = jnp.zeros((1, 4, 6, 8))  # 6 heads not divisible by 4 ranks
    with pytest.raises(ValueError, match="not divisible"):
        spmd(lambda a, b, c: ulysses_attention(a, b, c, topo), topo)(
            jnp.stack([q] * N), jnp.stack([q] * N), jnp.stack([q] * N)
        )


def test_ring_attention_bf16_stable():
    topo = Ring(N)
    (q, k, v), (qs, ks, vs) = _shards(jax.random.PRNGKey(2))
    cast = lambda t: t.astype(jnp.bfloat16)

    def fn(q, k, v):
        return ring_attention(q, k, v, topo, causal=True)

    out = _unshard(spmd(fn, topo)(cast(qs), cast(ks), cast(vs)))
    assert out.dtype == jnp.bfloat16
    expect = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(expect), atol=0.05, rtol=0.05
    )
