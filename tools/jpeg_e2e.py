"""Real-format CIFAR-10 JPEG end-to-end run (VERDICT round-1 item 7).

Generates a raw-JPEG class-folder fixture in the reference's own on-disk
format (`<dir>/{train,test}/<class>/NNNN.jpg` — the "CIFAR-10-images"
mirror, /root/reference/dcifar10/common/custom.hpp:66-122) with the native
libjpeg encoder, then trains eventgrad vs dpsgd through the full CLI path
— JPEG ingestion (native decode + bilinear resize) and on-device pad4 +
flip + crop augmentation (transform.hpp:19-102 semantics) — writing
acc-vs-epoch JSONL metrics for both algorithms.

The synthetic images are built to SURVIVE the reference augmentation: class
prototypes are low-frequency (so ±4px crops keep them recognizable) and
horizontally symmetric (so flips are label-preserving) — unlike the bench's
white-noise prototypes, which augmentation would destroy.

Usage: python tools/jpeg_e2e.py [out_dir] [n_train] [epochs] [horizon]
       [max_silence]
Defaults reproduce the committed stabilized artifacts (horizon 1.05,
max-silence 50 — 67.8% saved at gap 0.0). For the reference-pure trigger
(55.95% saved): python tools/jpeg_e2e.py /tmp/eg_jpeg_fixture 2048 12 1.0 0
Artifacts (committed): artifacts/jpeg_e2e_{eventgrad,dpsgd}.jsonl
"""

from __future__ import annotations

import os
import subprocess
import sys

import numpy as np

# script invocation puts tools/ (not the repo root) on sys.path
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def smooth_symmetric_protos(num_classes: int, size: int, seed: int) -> np.ndarray:
    """[C, size, size, 3] float32 prototypes: low-pass filtered noise,
    symmetrized under horizontal flip, unit-ish variance."""
    rng = np.random.default_rng(seed)
    noise = rng.standard_normal((num_classes, size, size, 3))
    f = np.fft.fft2(noise, axes=(1, 2))
    keep = 4  # lowest spatial frequencies only
    mask = np.zeros((size, size), bool)
    mask[:keep, :keep] = mask[:keep, -keep:] = True
    mask[-keep:, :keep] = mask[-keep:, -keep:] = True
    protos = np.real(np.fft.ifft2(f * mask[None, :, :, None], axes=(1, 2)))
    protos = protos + protos[:, :, ::-1]  # horizontal-flip symmetry
    protos /= protos.std(axis=(1, 2, 3), keepdims=True)
    return protos.astype(np.float32)


def write_fixture(out_dir: str, n_train: int, n_test: int, seed: int = 0) -> None:
    from eventgrad_tpu.data import native
    from eventgrad_tpu.data.datasets import CIFAR10_CLASSES

    if not native.jpeg_supported():
        raise SystemExit("native libeg_dataio.so with libjpeg required")
    size = 32
    protos = smooth_symmetric_protos(len(CIFAR10_CLASSES), size, seed)
    rng = np.random.default_rng(seed + 1)
    for split, n in (("train", n_train), ("test", n_test)):
        counts = [0] * len(CIFAR10_CLASSES)
        y = rng.integers(0, len(CIFAR10_CLASSES), n)
        for i in range(n):
            img = protos[y[i]] + 0.35 * rng.standard_normal((size, size, 3))
            u8 = np.clip(127.5 + 55.0 * img, 0, 255).astype(np.uint8)
            cls = CIFAR10_CLASSES[y[i]]
            d = os.path.join(out_dir, split, cls)
            os.makedirs(d, exist_ok=True)
            native.save_jpeg(
                os.path.join(d, f"{counts[y[i]]:04d}.jpg"), u8, quality=92
            )
            counts[y[i]] += 1
    # manifest written LAST: its presence marks a complete fixture of this
    # exact size (an interrupted or differently-sized one regenerates)
    import json

    with open(os.path.join(out_dir, "fixture.json"), "w") as f:
        json.dump({"n_train": n_train, "n_test": n_test, "seed": seed}, f)


def _fixture_matches(out_dir: str, n_train: int, n_test: int) -> bool:
    import json

    try:
        with open(os.path.join(out_dir, "fixture.json")) as f:
            m = json.load(f)
        return m.get("n_train") == n_train and m.get("n_test") == n_test
    except (OSError, ValueError):
        return False


def main() -> None:
    out_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/eg_jpeg_fixture"
    n_train = int(sys.argv[2]) if len(sys.argv) > 2 else 2048
    epochs = int(sys.argv[3]) if len(sys.argv) > 3 else 12
    horizon = float(sys.argv[4]) if len(sys.argv) > 4 else 1.05
    max_silence = int(sys.argv[5]) if len(sys.argv) > 5 else 50
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    art = os.path.join(repo, "artifacts")
    os.makedirs(art, exist_ok=True)

    n_test = max(256, n_train // 8)
    if not _fixture_matches(out_dir, n_train, n_test):
        if os.path.isdir(os.path.join(out_dir, "train")) and not os.path.exists(
            os.path.join(out_dir, "fixture.json")
        ):
            # a class-folder tree WITHOUT our manifest is not ours to
            # delete — it may be a real CIFAR-10-images dataset
            raise SystemExit(
                f"{out_dir} holds a dataset this script did not generate "
                "(no fixture.json); refusing to overwrite it — point "
                "out_dir somewhere else or delete it yourself"
            )
        import shutil

        shutil.rmtree(out_dir, ignore_errors=True)
        print(f"writing JPEG fixture to {out_dir} ...", flush=True)
        write_fixture(out_dir, n_train, n_test)

    for algo in ("eventgrad", "dpsgd"):
        log = os.path.join(art, f"jpeg_e2e_{algo}.jsonl")
        if os.path.exists(log):
            os.unlink(log)
        cmd = [
            sys.executable, "-m", "eventgrad_tpu.cli",
            "--algo", algo, "--mesh", "ring:8",
            "--dataset", "cifar10", "--data-dir", out_dir,
            "--model", "resnet18", "--num-filters", "8", "--augment",
            "--epochs", str(epochs), "--global-batch", "64",
            "--lr", "1e-2", "--momentum", "0.9", "--random-sampler",
            "--log-file", log,
        ]
        if algo == "eventgrad":
            cmd += ["--thres-mode", "adaptive", "--horizon", str(horizon)]
            if max_silence:
                cmd += ["--max-silence", str(max_silence)]
        print("::", " ".join(cmd), flush=True)
        subprocess.run(cmd, cwd=repo, check=True)
    print(f"done; metrics in {art}/jpeg_e2e_*.jsonl", flush=True)


if __name__ == "__main__":
    main()
