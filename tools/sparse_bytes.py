"""Sparsified EventGraD: measured wire bytes vs the dense variants (E5's
story — real on-the-wire savings, not just skipped messages).

Three legs at the reduced CIFAR op-point (tools/tune_horizon.py's
`run_point` — one definition across artifact families), 512 passes,
horizon 1.0, warmup 30: dense eventgrad, sp_eventgrad at top-k 10%, and
sp_eventgrad at top-k 1%. Reports per-step per-chip sent bytes (the
BASELINE "grad-sync bytes/step/chip" metric; spevent.cpp:342-381
semantics: (value,index) pairs only for fired parameters) and consensus
accuracy.

Output: JSON lines appended per leg (a cut run keeps its finished legs);
a fresh invocation truncates the file first. Committed as
artifacts/sparse_bytes_r2_cpu.jsonl.
Usage: JAX_PLATFORMS=cpu python tools/sparse_bytes.py [epochs]
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from tune_horizon import run_point  # noqa: E402


def main() -> None:
    epochs = int(sys.argv[1]) if len(sys.argv) > 1 else 32  # 512 passes
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    os.makedirs(os.path.join(repo, "artifacts"), exist_ok=True)
    path = os.path.join(repo, "artifacts", "sparse_bytes_r2_cpu.jsonl")
    if os.path.exists(path):  # fresh run replaces stale rows
        os.unlink(path)
    for algo, topk in (("eventgrad", None), ("sp_eventgrad", 10.0),
                       ("sp_eventgrad", 1.0)):
        r = run_point("cifar", 1.0, warmup=30, epochs=epochs,
                      dpsgd_leg=False, algo=algo, topk_percent=topk)
        with open(path, "a") as f:  # per leg: survives a cut run
            f.write(json.dumps(r) + "\n")


if __name__ == "__main__":
    main()
