"""End-to-end epoch driver: convergence + savings on the emulated mesh."""

import numpy as np

from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP, CNN2
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.topology import Ring, Torus
from eventgrad_tpu.train.loop import consensus_params, evaluate, train


def test_mlp_eventgrad_end_to_end():
    topo = Ring(4)
    x, y = synthetic_dataset(2048, (8, 8, 1), seed=1)
    xt, yt = synthetic_dataset(256, (8, 8, 1), seed=1, split="test")
    state, hist = train(
        MLP(hidden=32),
        topo,
        x,
        y,
        algo="eventgrad",
        epochs=10,
        batch_size=16,
        learning_rate=0.1,
        event_cfg=EventConfig(adaptive=True, horizon=0.95, warmup_passes=5),
        x_test=xt,
        y_test=yt,
    )
    assert hist[-1]["loss"] < hist[0]["loss"]
    assert 0.0 < hist[-1]["msgs_saved_pct"] < 100.0
    assert hist[-1]["test_accuracy"] > 50.0  # prototype task: well above chance


def test_torus_dpsgd_runs():
    topo = Torus(4, 2)
    x, y = synthetic_dataset(512, (28, 28, 1), seed=2)
    state, hist = train(
        MLP(hidden=16), topo, x, y, algo="dpsgd", epochs=1, batch_size=8
    )
    assert np.isfinite(hist[0]["loss"])


def test_cnn2_with_dropout_trains():
    topo = Ring(4)
    x, y = synthetic_dataset(256, (28, 28, 1), seed=4)
    state, hist = train(
        CNN2(), topo, x, y, algo="dpsgd", epochs=2, batch_size=8, learning_rate=0.05
    )
    assert hist[-1]["loss"] < hist[0]["loss"]


def test_sp_axis_rejects_image_data():
    """Regression for the advisor's round-1 finding: an sp axis chunks the
    TRAILING input dimension as a token sequence; for image data that
    dimension is channels, which must never be silently sliced."""
    import pytest

    from eventgrad_tpu.parallel.topology import Topology

    topo = Topology(axes=("dp", "sp"), shape=(2, 2), gossip_axes=("dp",))
    x, y = synthetic_dataset(128, (8, 8, 2), seed=3)  # float images, C=2=sp
    with pytest.raises(ValueError, match="channels"):
        train(MLP(hidden=16), topo, x, y, algo="dpsgd", epochs=1, batch_size=8)


def test_expand_to_mesh_rejects_float_batches_on_sp():
    import pytest

    from eventgrad_tpu.data.sharding import expand_to_mesh
    from eventgrad_tpu.parallel.topology import Topology

    topo = Topology(axes=("dp", "sp"), shape=(2, 2), gossip_axes=("dp",))
    xb = np.zeros((2, 3, 4, 8, 8, 2), np.float32)  # [n_data, steps, B, H, W, C]
    yb = np.zeros((2, 3, 4), np.int64)
    with pytest.raises(ValueError, match="channels"):
        expand_to_mesh(xb, yb, topo)
