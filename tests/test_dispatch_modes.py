"""Dispatch-mode equivalences: device-resident data and K-epoch blocks.

Round-5 host-dispatch-tax work (train/loop.py device_data /
epochs_per_dispatch) must not change trajectories: the device gather uses
the SAME epoch_index_plan as the host prefetcher, and a K-epoch block is
the same scan run K*steps steps — so final parameters and per-epoch
metrics must match the host / per-epoch path exactly.
"""

import numpy as np
import pytest

from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.loop import train

N_RANKS = 4
BATCH = 8
EPOCHS = 5


def _train(algo="eventgrad", **kw):
    topo = Ring(N_RANKS)
    x, y = synthetic_dataset(N_RANKS * BATCH * 3, (28, 28, 1), seed=7)
    cfg = EventConfig(adaptive=True, horizon=0.95, warmup_passes=2)
    return train(
        MLP(hidden=16), topo, x, y, algo=algo, epochs=EPOCHS,
        batch_size=BATCH, learning_rate=0.05,
        event_cfg=cfg if algo in ("eventgrad", "sp_eventgrad") else None,
        **kw,
    )


def _leaves(state):
    import jax

    return [np.asarray(l) for l in jax.tree.leaves(state.params)]


def _assert_same(state_a, hist_a, state_b, hist_b):
    for la, lb in zip(_leaves(state_a), _leaves(state_b)):
        np.testing.assert_array_equal(la, lb)
    assert len(hist_a) == len(hist_b)
    for ra, rb in zip(hist_a, hist_b):
        assert ra["epoch"] == rb["epoch"]
        np.testing.assert_allclose(ra["loss"], rb["loss"], rtol=0, atol=0)
        if "msgs_saved_pct" in ra:
            assert ra["msgs_saved_pct"] == rb["msgs_saved_pct"]
        assert ra["sent_bytes_per_step_per_chip"] == (
            rb["sent_bytes_per_step_per_chip"]
        )
        assert ra["train_acc"] == rb["train_acc"]


@pytest.mark.parametrize("sampler", [False, True])
def test_device_data_matches_host_path(sampler):
    """device_data gathers on device from the identical index plan — the
    whole trajectory is bitwise the host path's."""
    s0, h0 = _train(random_sampler=sampler, device_data=False)
    s1, h1 = _train(random_sampler=sampler, device_data=True)
    _assert_same(s0, h0, s1, h1)


@pytest.mark.parametrize("algo", ["dpsgd", "eventgrad"])
def test_k_epoch_blocks_match_per_epoch(algo):
    """A K-epoch block is the same scan with K*steps steps: 5 epochs as
    3+2 blocks reproduce the per-epoch dispatch exactly, including the
    per-epoch history split."""
    s0, h0 = _train(algo=algo, device_data=False, epochs_per_dispatch=1)
    s1, h1 = _train(algo=algo, device_data=False, epochs_per_dispatch=3)
    _assert_same(s0, h0, s1, h1)


def test_k_blocks_with_device_data_and_random_sampler():
    s0, h0 = _train(random_sampler=True, device_data=False,
                    epochs_per_dispatch=1)
    s1, h1 = _train(random_sampler=True, device_data=True,
                    epochs_per_dispatch=4)
    _assert_same(s0, h0, s1, h1)


def test_blocks_split_on_save_every(tmp_path):
    """Checkpoint cadence survives K-epoch blocks: save_every=2 with K=3
    still snapshots at epochs 2 and 4 (blocks split at save points)."""
    topo = Ring(N_RANKS)
    x, y = synthetic_dataset(N_RANKS * BATCH * 2, (28, 28, 1), seed=7)
    ck = str(tmp_path / "ck")
    s0, h0 = train(
        MLP(hidden=16), topo, x, y, algo="dpsgd", epochs=EPOCHS,
        batch_size=BATCH, learning_rate=0.05,
        checkpoint_dir=ck, save_every=2, epochs_per_dispatch=3,
    )
    from eventgrad_tpu.utils import checkpoint
    import os

    found = checkpoint.latest(os.path.join(ck, "ckpt"))
    assert found is not None
    # resume from the last snapshot reproduces the non-checkpointed run
    s1, h1 = train(
        MLP(hidden=16), topo, x, y, algo="dpsgd", epochs=EPOCHS,
        batch_size=BATCH, learning_rate=0.05,
        checkpoint_dir=ck, save_every=2, resume=True,
        epochs_per_dispatch=3,
    )
    s2, h2 = train(
        MLP(hidden=16), topo, x, y, algo="dpsgd", epochs=EPOCHS,
        batch_size=BATCH, learning_rate=0.05,
    )
    for la, lb in zip(_leaves(s1), _leaves(s2)):
        np.testing.assert_array_equal(la, lb)


def test_eval_at_block_ends():
    """x_test + K>1: consensus eval runs at block ends only (every-K
    cadence), and always on the final epoch."""
    topo = Ring(N_RANKS)
    x, y = synthetic_dataset(N_RANKS * BATCH * 2, (28, 28, 1), seed=7)
    xt, yt = synthetic_dataset(64, (28, 28, 1), seed=8)
    _, hist = train(
        MLP(hidden=16), topo, x, y, algo="dpsgd", epochs=EPOCHS,
        batch_size=BATCH, learning_rate=0.05,
        x_test=xt, y_test=yt, epochs_per_dispatch=2,
    )
    evaled = [r["epoch"] for r in hist if "test_accuracy" in r]
    assert evaled == [2, 4, 5]
    assert hist[-1]["epoch"] == EPOCHS
