"""Native data pipeline (C++ via ctypes) vs the numpy fallbacks."""

import os
import tempfile

import numpy as np
import pytest

from eventgrad_tpu.data import native


@pytest.fixture(scope="module")
def lib():
    lib = native.load_library()
    if lib is None:
        pytest.skip("native library unavailable (no compiler?)")
    return lib


def test_version(lib):
    assert lib.eg_version() == 2


def test_shard_plan_matches_shapes(lib):
    plan = native.shard_plan(103, 4, seed=1, epoch=2, shuffle=True)
    assert plan.shape == (4, 25)
    flat = plan.reshape(-1)
    assert len(np.unique(flat)) == flat.size  # disjoint shards
    assert flat.min() >= 0 and flat.max() < 103
    # deterministic across calls
    plan2 = native.shard_plan(103, 4, seed=1, epoch=2, shuffle=True)
    np.testing.assert_array_equal(plan, plan2)
    # different epoch reshuffles
    plan3 = native.shard_plan(103, 4, seed=1, epoch=3, shuffle=True)
    assert not np.array_equal(plan, plan3)


def test_sequential_plan(lib):
    plan = native.shard_plan(16, 4, shuffle=False)
    np.testing.assert_array_equal(plan, np.arange(16).reshape(4, 4))


def test_gather_matches_numpy(lib):
    x = np.random.default_rng(0).standard_normal((20, 4, 4, 3)).astype(np.float32)
    y = np.arange(20, dtype=np.int32)
    idx = np.array([[3, 1], [7, 19]], np.int64)
    xg, yg = native.gather_batches(x, y, idx)
    np.testing.assert_array_equal(xg, x[idx.reshape(-1)].reshape(2, 2, 4, 4, 3))
    np.testing.assert_array_equal(yg, idx.astype(np.int32))


def test_cifar10_binary_roundtrip(lib):
    """Write a synthetic CIFAR binary batch, read it natively, compare with
    the pure-python reader."""
    rng = np.random.default_rng(7)
    n = 5
    labels = rng.integers(0, 10, n).astype(np.uint8)
    chw = rng.integers(0, 256, (n, 3, 32, 32)).astype(np.uint8)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "data_batch_1.bin")
        with open(path, "wb") as f:
            for i in range(n):
                f.write(bytes([labels[i]]))
                f.write(chw[i].tobytes())
        out = native.load_cifar10_bin([path])
        assert out is not None
        x, y = out
    assert x.shape == (n, 32, 32, 3)
    np.testing.assert_array_equal(y, labels.astype(np.int32))
    expect = chw.transpose(0, 2, 3, 1).astype(np.float32) / 255.0
    np.testing.assert_allclose(x, expect)


def test_mnist_idx_native(lib):
    rng = np.random.default_rng(9)
    n = 7
    imgs = rng.integers(0, 256, (n, 28, 28)).astype(np.uint8)
    labs = rng.integers(0, 10, n).astype(np.uint8)
    with tempfile.TemporaryDirectory() as d:
        ip = os.path.join(d, "train-images-idx3-ubyte")
        lp = os.path.join(d, "train-labels-idx1-ubyte")
        with open(ip, "wb") as f:
            f.write((2051).to_bytes(4, "big") + n.to_bytes(4, "big")
                    + (28).to_bytes(4, "big") + (28).to_bytes(4, "big"))
            f.write(imgs.tobytes())
        with open(lp, "wb") as f:
            f.write((2049).to_bytes(4, "big") + n.to_bytes(4, "big"))
            f.write(labs.tobytes())
        out = native.load_mnist_idx(ip, lp, 0.1307, 0.3081)
        assert out is not None
        x, y = out
    assert x.shape == (n, 28, 28, 1)
    np.testing.assert_array_equal(y, labs.astype(np.int32))
    expect = (imgs.astype(np.float32) / 255.0 - 0.1307) / 0.3081
    np.testing.assert_allclose(x.squeeze(-1), expect, rtol=1e-5)


# ---------------------------------------------------------------------------
# JPEG pipeline (libjpeg decode/encode + bilinear resize; D2 in SURVEY §2.4)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def jpeg(lib):
    if not native.jpeg_supported():
        pytest.skip("libeg_dataio built without libjpeg")
    return lib


def test_jpeg_roundtrip_high_quality(jpeg, tmp_path):
    # smooth image: JPEG at q=95 should reproduce it closely
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float32)
    img = np.stack(
        [127 + 120 * np.sin(xx / 7), 127 + 120 * np.cos(yy / 9),
         127 * np.ones_like(xx)], -1
    ).astype(np.uint8)
    p = str(tmp_path / "a.jpg")
    native.save_jpeg(p, img, quality=95)
    out = native.load_jpeg_image(p, 32)
    assert out.shape == (32, 32, 3) and out.dtype == np.float32
    assert 0.0 <= out.min() and out.max() <= 1.0
    err = np.abs(out * 255.0 - img.astype(np.float32))
    assert err.mean() < 3.0, err.mean()  # near-lossless at q=95


def test_jpeg_resize_to_dataset_size(jpeg, tmp_path):
    # constant-color 64x64 must resize to the exact same color at 32x32
    img = np.full((64, 64, 3), (10, 200, 90), np.uint8)
    p = str(tmp_path / "big.jpg")
    native.save_jpeg(p, img, quality=98)
    out = native.load_jpeg_image(p, 32)
    np.testing.assert_allclose(
        out.reshape(-1, 3).mean(0) * 255.0, (10, 200, 90), atol=3.0
    )


def test_jpeg_decode_rejects_garbage(jpeg, tmp_path):
    p = str(tmp_path / "bad.jpg")
    with open(p, "wb") as f:
        f.write(b"this is not a jpeg at all")
    with pytest.raises(ValueError):
        native.load_jpeg_image(p, 32)


def test_cifar10_jpeg_dir_loader(jpeg, tmp_path):
    from eventgrad_tpu.data.datasets import (
        CIFAR10_CLASSES, load_cifar10, load_cifar10_jpeg_dir,
    )

    rng = np.random.default_rng(3)
    for split in ("train", "test"):
        for cls in CIFAR10_CLASSES[:3]:  # 3 classes suffice
            d = tmp_path / split / cls
            d.mkdir(parents=True)
            n = 4 if split == "train" else 2
            for i in range(n):
                img = rng.integers(0, 256, (32, 32, 3)).astype(np.uint8)
                native.save_jpeg(str(d / f"{i:04d}.jpg"), img, quality=92)

    x, y = load_cifar10_jpeg_dir(str(tmp_path), "train")
    assert x.shape == (12, 32, 32, 3)
    assert [int((y == l).sum()) for l in range(3)] == [4, 4, 4]
    assert 0.0 <= x.min() and x.max() <= 1.0

    # load_cifar10 auto-detects the directory layout
    x2, y2 = load_cifar10(str(tmp_path), "test")
    assert x2.shape == (6, 32, 32, 3)
    np.testing.assert_array_equal(np.unique(y2), [0, 1, 2])


def test_gather_sequence_targets_and_int_inputs(lib):
    """Token datasets: int32 x rows gather bit-exactly through the float
    memcpy kernel, and [T]-shaped int targets keep their trailing dim."""
    rng = np.random.default_rng(5)
    x = rng.integers(0, 1000, (20, 16)).astype(np.int32)
    y = rng.integers(0, 1000, (20, 16)).astype(np.int32)
    idx = np.array([[4, 9], [0, 19]], np.int64)
    xg, yg = native.gather_batches(x, y, idx)
    assert xg.dtype == np.int32 and yg.shape == (2, 2, 16)
    np.testing.assert_array_equal(xg, x[idx.reshape(-1)].reshape(2, 2, 16))
    np.testing.assert_array_equal(yg, y[idx.reshape(-1)].reshape(2, 2, 16))
