"""JIT-compatible fault injection inside the gossip mixing step.

A dropped message is implemented as "the receiver keeps its stale buffer":
`collectives.masked_neighbor_vals` already selects
`where(neighbor_fired, payload, stale)` per edge, so injection just ANDs a
per-edge `delivered` bit into that select — one fused program handles both
event-triggered silence and injected loss, and an injected drop is
*bitwise-identical* to an event that did not fire (tests/test_chaos.py).

Determinism: the delivered bit for (pass, receiver rank, edge index) is a
pure function of the schedule seed via counter-style `fold_in` chains —
no carried RNG state, so the scan body stays shape-stable and the whole
schedule replays from its serialized form. `delivery_table` computes the
same bits on the host (same ops, same seeds) for replay analysis and
tests.

Everything here runs under `jax.vmap(axis_name=...)` and `jax.shard_map`
alike: rank identity comes from `lax.axis_index` on the topology's named
axes, exactly like the collectives.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from eventgrad_tpu.chaos.schedule import ChaosSchedule
from eventgrad_tpu.parallel.topology import Topology

#: fold_in tags separating the independent per-schedule random streams
#: (drop draws vs. delivery-thinning phases vs. bitflip draws); arbitrary
#: but frozen — changing them changes every serialized schedule's replay.
_TAG_DROP = 0x5EED
_TAG_PHASE = 0x9A5E
_TAG_FLIP = 0xB17F
_TAG_FLIP_POS = 0xB170


def rank_and_sources(topo: Topology) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(my flat rank, per-edge source flat rank [n_neighbors]) from inside
    the SPMD context — the traced twin of `Topology.neighbor_source`'s
    row-major arithmetic."""
    coords = [lax.axis_index(a) for a in topo.axes]

    def ravel(cs) -> jnp.ndarray:
        r = jnp.int32(0)
        for c, size in zip(cs, topo.shape):
            r = r * size + c.astype(jnp.int32)
        return r

    srcs = []
    for nb in topo.neighbors:
        ax = topo.axes.index(nb.axis)
        shifted = list(coords)
        shifted[ax] = (coords[ax] + nb.offset) % topo.shape[ax]
        srcs.append(ravel(shifted))
    me = ravel(coords)
    if not srcs:  # neighborless topology: keep a well-formed empty vector
        return me, jnp.zeros((0,), jnp.int32)
    return me, jnp.stack(srcs)


def host_source_table(topo: Topology):
    """Host twin of `rank_and_sources`: np.int64 [n_ranks, n_neighbors],
    entry (r, e) = the flat rank whose payload rank r receives on edge
    e (`Topology.neighbor_source`). The ledger auditor's cross-rank
    map (obs/ledger.py audit_window)."""
    import numpy as np

    return np.asarray(
        [
            [topo.neighbor_source(r, nb) for nb in topo.neighbors]
            for r in range(topo.n_ranks)
        ],
        np.int64,
    ).reshape(topo.n_ranks, topo.n_neighbors)


def reverse_edge_index(topo: Topology):
    """Per edge index e, the index of the reverse edge (same axis,
    negated offset), or None when any edge lacks its reverse — the
    repo's Ring/Torus topologies are symmetric, so the ledger auditor's
    cross-rank law always has a well-defined sender edge."""
    rev = []
    for nb in topo.neighbors:
        match = [
            j for j, other in enumerate(topo.neighbors)
            if other.axis == nb.axis and other.offset == -nb.offset
        ]
        if not match:
            return None
        rev.append(match[0])
    return rev


def delivery_mask(
    sched: ChaosSchedule,
    topo: Topology,
    pass_num: jnp.ndarray,
    rank: Optional[jnp.ndarray] = None,
    srcs: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Per-edge delivered bits (bool [n_neighbors]) for the current pass.

    Inside the SPMD step leave `rank`/`srcs` None (derived from
    `lax.axis_index`); the host-side `delivery_table` passes them
    explicitly so both paths run the identical fold_in chain. A True bit
    means "a message sent on this edge this pass arrives"; the event
    fire bit still decides whether anything WAS sent.
    """
    n_nb = topo.n_neighbors
    if rank is None or srcs is None:
        rank, srcs = rank_and_sources(topo)
    rank = jnp.asarray(rank, jnp.int32)
    srcs = jnp.asarray(srcs, jnp.int32)
    pass_i = jnp.asarray(pass_num, jnp.int32)
    key = jax.random.PRNGKey(sched.seed)

    # iid drop draw, one uniform per (pass, receiver, edge)
    u = jax.random.uniform(
        jax.random.fold_in(
            jax.random.fold_in(jax.random.fold_in(key, _TAG_DROP), pass_i),
            rank,
        ),
        (n_nb,),
    )
    p = jnp.full((n_nb,), sched.drop_p, jnp.float32)
    for w in sched.flaky:
        in_window = (pass_i >= w.start_pass) & (pass_i < w.end_pass)
        p = jnp.where(in_window, jnp.maximum(p, jnp.float32(w.drop_p)), p)
    deliver = u >= p  # u in [0, 1): drop_p == 0 can never drop

    if sched.deliver_every > 1:
        # k-pass thinning: each edge refreshes only when the pass hits its
        # seed-derived phase — staleness up to k-1 extra passes
        phase = jax.random.randint(
            jax.random.fold_in(
                jax.random.fold_in(key, _TAG_PHASE), rank
            ),
            (n_nb,), 0, sched.deliver_every,
        )
        deliver = deliver & ((pass_i % sched.deliver_every) == phase)

    for dead_rank, t in sched.death:
        dead_now = pass_i >= t
        # a dead peer neither sends (its outgoing edges drop) nor receives
        # (every edge INTO it drops too); its rows are excluded at
        # heal/consensus time (policy.heal_ring, survivor evaluation)
        deliver = deliver & ~(dead_now & (srcs == dead_rank))
        deliver = deliver & ~(dead_now & (rank == dead_rank))
    return deliver


def lag_vector(
    sched: Optional[ChaosSchedule],
    topo: Topology,
    pass_num: jnp.ndarray,
    bound: int,
    srcs: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Per-edge EFFECTIVE delivery lag (int32 [n_neighbors]) of the
    messages exchanged on this pass, for the bounded-async engine
    (train(staleness=D >= 2)).

    Scheduled lag = max(1, lag= windows covering this pass, slow=
    clauses naming the edge's SOURCE rank); the effective lag clamps it
    to [1, bound] — the bound is the whole point: a message can never
    land more than D passes late because the fast rank waits instead of
    running further ahead (tools/straggler_ablation.py charges that
    wait to the wall clock; the traced step only ever sees the clamped
    value). Pure data — no random draws, and deterministic in the edge
    SOURCES alone (no receiver-rank dependence) — so the host-side
    `lag_table` twin replays it exactly. `sched=None` is the
    all-baseline (lag 1) schedule."""
    n_nb = topo.n_neighbors
    if srcs is None:
        _, srcs = rank_and_sources(topo)
    srcs = jnp.asarray(srcs, jnp.int32)
    pass_i = jnp.asarray(pass_num, jnp.int32)
    lag = jnp.ones((n_nb,), jnp.int32)
    if sched is not None:
        for w in sched.lag:
            in_window = (pass_i >= w.start_pass) & (pass_i < w.end_pass)
            lag = jnp.where(
                in_window, jnp.maximum(lag, jnp.int32(w.lag)), lag
            )
        for r, f in sched.slow:
            lag = jnp.where(
                srcs == r, jnp.maximum(lag, jnp.int32(f)), lag
            )
    return jnp.clip(lag, 1, max(1, int(bound)))


def lag_table(
    sched: Optional[ChaosSchedule],
    topo: Topology,
    n_passes: int,
    start_pass: int = 1,
    bound: Optional[int] = None,
) -> np.ndarray:
    """Host-side replay of the lag schedule: int32 [n_passes, n_ranks,
    n_neighbors]. With `bound` it runs the exact clamp of `lag_vector`
    (the in-step ground truth); with bound=None it returns the RAW
    scheduled lag — what the network would do unconstrained, which is
    what the straggler ablation's wall-clock model charges a lockstep
    run for."""
    srcs = np.array(
        [
            [topo.neighbor_source(r, nb) for nb in topo.neighbors]
            for r in range(topo.n_ranks)
        ],
        np.int32,
    ).reshape(topo.n_ranks, topo.n_neighbors)
    out = np.ones((n_passes, topo.n_ranks, topo.n_neighbors), np.int32)
    for pi in range(n_passes):
        p = start_pass + pi
        for r in range(topo.n_ranks):
            lag = out[pi, r]
            if sched is not None:
                for w in sched.lag:
                    if w.start_pass <= p < w.end_pass:
                        lag[:] = np.maximum(lag, w.lag)
                for sr, f in sched.slow:
                    lag[srcs[r] == sr] = np.maximum(
                        lag[srcs[r] == sr], f
                    )
            if bound is not None:
                np.clip(lag, 1, max(1, int(bound)), out=lag)
    return out


def corrupt_mask(
    sched: ChaosSchedule,
    topo: Topology,
    pass_num: jnp.ndarray,
    rank: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-edge wire-corruption decisions for the current pass:
    (corrupt bool [n_neighbors], flip_salt int32 [n_neighbors]).

    A True bit means "the payload received on this edge this pass has one
    bit flipped in transit"; `flip_salt` seeds which element flips
    (`flip_one_bit` takes it modulo the buffer size). Deterministic in
    (seed, pass, receiver rank, edge index) via the same counter-style
    fold_in chains as `delivery_mask`, on an independent tag — adding
    bitflips to a schedule never perturbs its drop draws."""
    n_nb = topo.n_neighbors
    if rank is None:
        rank, _ = rank_and_sources(topo)
    rank = jnp.asarray(rank, jnp.int32)
    pass_i = jnp.asarray(pass_num, jnp.int32)
    key = jax.random.PRNGKey(sched.seed)

    u = jax.random.uniform(
        jax.random.fold_in(
            jax.random.fold_in(jax.random.fold_in(key, _TAG_FLIP), pass_i),
            rank,
        ),
        (n_nb,),
    )
    p = jnp.zeros((n_nb,), jnp.float32)
    for w in sched.bitflip:
        in_window = (pass_i >= w.start_pass) & (pass_i < w.end_pass)
        p = jnp.where(in_window, jnp.maximum(p, jnp.float32(w.drop_p)), p)
    corrupt = u < p  # u in [0, 1): p == 0 can never corrupt
    salt = jax.random.randint(
        jax.random.fold_in(
            jax.random.fold_in(
                jax.random.fold_in(key, _TAG_FLIP_POS), pass_i
            ),
            rank,
        ),
        (n_nb,), 0, 2**31 - 1,
    )
    return corrupt, salt


def flip_one_bit(
    buf: jnp.ndarray, do_flip: jnp.ndarray, salt: jnp.ndarray,
) -> jnp.ndarray:
    """Flip one bit of a wire buffer in transit (when `do_flip`).

    The flipped element is `salt % buf.size`; the flipped bit is the
    second-most-significant of the element's storage word — for a float
    payload that is the exponent MSB, the worst case a real bit error
    can do (a ~1e38-scale excursion), and exactly what the integrity
    checksum must catch. Works on any wire dtype (f32/bf16 bitcast to
    ints; int8 flips bit 6). Shapes are static; the flip is one
    dynamic-index XOR under `where`, so the traced program is identical
    whether or not the bit fires this pass."""
    flat = buf.reshape(-1)
    if jnp.issubdtype(flat.dtype, jnp.floating):
        nbits = jnp.finfo(flat.dtype).bits
        int_dt = {16: jnp.int16, 32: jnp.int32, 64: jnp.int64}[nbits]
        bits = lax.bitcast_convert_type(flat, int_dt)
    else:
        nbits = jnp.iinfo(flat.dtype).bits
        int_dt = flat.dtype
        bits = flat
    mask = jnp.asarray(1 << (nbits - 2), int_dt)
    idx = jnp.asarray(salt, jnp.int32) % flat.size
    flipped = bits.at[idx].set(
        jnp.where(do_flip, bits[idx] ^ mask, bits[idx])
    )
    if jnp.issubdtype(flat.dtype, jnp.floating):
        flipped = lax.bitcast_convert_type(flipped, flat.dtype)
    return flipped.reshape(buf.shape)


def nanstep_mask(
    sched: ChaosSchedule,
    topo: Topology,
    pass_num: jnp.ndarray,
    rank: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """bool []: is this rank's gradient poisoned (NaN) on this pass?
    Pure data — the schedule's `nanstep=R@P` events, no randomness."""
    if rank is None:
        rank, _ = rank_and_sources(topo)
    rank = jnp.asarray(rank, jnp.int32)
    pass_i = jnp.asarray(pass_num, jnp.int32)
    hit = jnp.zeros((), bool)
    for r, t in sched.nanstep:
        hit = hit | ((rank == r) & (pass_i == t))
    return hit


def corruption_table(
    sched: ChaosSchedule, topo: Topology, n_passes: int, start_pass: int = 1
) -> np.ndarray:
    """Host-side replay of the bitflip schedule: bool [n_passes, n_ranks,
    n_neighbors] of injected corruptions — the ground truth the integrity
    artifact's zero-silent-acceptance accounting compares against (same
    fold_in chain as `corrupt_mask`, like `delivery_table`)."""
    out = np.zeros((n_passes, topo.n_ranks, topo.n_neighbors), bool)
    fn = jax.jit(lambda p, r: corrupt_mask(sched, topo, p, rank=r)[0])
    for pi in range(n_passes):
        for r in range(topo.n_ranks):
            out[pi, r] = np.asarray(fn(jnp.int32(start_pass + pi), jnp.int32(r)))
    return out


def nansteps_in_range(
    sched: ChaosSchedule, n_ranks: int, n_passes: int, start_pass: int = 1
) -> int:
    """How many scheduled nanstep events land within the run (the
    integrity artifact's quarantine accounting denominator)."""
    return sum(
        1 for r, t in sched.nanstep
        if 0 <= r < n_ranks and start_pass <= t < start_pass + n_passes
    )


def delivery_table(
    sched: ChaosSchedule, topo: Topology, n_passes: int, start_pass: int = 1
) -> np.ndarray:
    """Host-side replay of the full schedule: bool [n_passes, n_ranks,
    n_neighbors], pass axis starting at `start_pass` (passes are 1-based
    in the step, event.cpp:273). Runs the exact fold_in chain of
    `delivery_mask`, so it IS the ground truth of what a run saw."""
    srcs = np.array(
        [
            [topo.neighbor_source(r, nb) for nb in topo.neighbors]
            for r in range(topo.n_ranks)
        ],
        np.int32,
    ).reshape(topo.n_ranks, topo.n_neighbors)
    out = np.zeros((n_passes, topo.n_ranks, topo.n_neighbors), bool)
    fn = jax.jit(
        lambda p, r, s: delivery_mask(sched, topo, p, rank=r, srcs=s),
        static_argnums=(),
    )
    for pi in range(n_passes):
        for r in range(topo.n_ranks):
            out[pi, r] = np.asarray(
                fn(jnp.int32(start_pass + pi), jnp.int32(r), srcs[r])
            )
    return out
