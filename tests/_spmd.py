"""Shared SPMD test helpers: the one shard_map skip definition.

The mesh lift needs `jax.shard_map`; some CPU-only environments run a
jax without it, where the SEED's shard_map tests fail outright (the
known pre-existing tier-1 failures). Tests added since skip instead —
via this ONE marker, so the reason string and the condition live in a
single place. A tier-1 lint test (tests/test_lint_spmd.py) enforces
that every new test touching shard_map imports `requires_shard_map`
from here rather than re-spelling the skipif — the debt stops
spreading while ROADMAP Open item 1 (real-mesh SPMD: retire the
single-chip vmap lift) is pending.

Usage:

    from _spmd import requires_shard_map

    @requires_shard_map
    def test_something_shard_map(): ...

    BACKENDS = ["vmap", pytest.param("shard_map", marks=requires_shard_map)]
"""

import jax
import pytest

#: single source of truth for "this test needs the shard_map mesh lift"
requires_shard_map = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason=(
        "jax.shard_map unavailable in this environment (the vmap lift "
        "covers the semantics until ROADMAP Open item 1 — real-mesh "
        "SPMD — retires the single-chip vmap path)"
    ),
)
