from eventgrad_tpu.parallel.topology import Ring, Torus, Topology, NeighborSpec
from eventgrad_tpu.parallel.spmd import spmd, build_mesh, stack_for_ranks, rank_index
from eventgrad_tpu.parallel import collectives
from eventgrad_tpu.parallel.events import (
    EventConfig,
    EventState,
    capacity_gate,
    commit,
    decide_and_update,
    propose,
)
from eventgrad_tpu.parallel.sparsify import (
    SparseConfig,
    SparseState,
    topk_payload,
    scatter_into,
    sparse_exchange,
)
