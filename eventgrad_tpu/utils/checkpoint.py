"""Checkpoint/resume — absent from the reference (no torch::save anywhere;
the consensus model is evaluated then dropped, event.cpp:517-586). Cheap win
on TPU: orbax snapshots of the full stacked TrainState (params, optimizer
moments, event thresholds/slopes/buffers, sparsifier replicas, PRNG keys),
so an interrupted decentralized run resumes with its exact gossip state.
"""

from __future__ import annotations

import os
import shutil
import threading
from typing import Any, Callable, ContextManager, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp


def save(path: str, state: Any) -> None:
    """Crash-safe snapshot: write to `<path>.tmp`, swap the old snapshot to
    `<path>.prev`, promote tmp, drop prev. A kill at any point leaves either
    `<path>` or `<path>.prev` complete — `latest()` finds whichever survived.

    Multi-process: EVERY process must call this (orbax coordinates the write
    internally and only the primary touches disk); `path` must be on a
    filesystem all processes can read for a later resume. Leaves must be
    host-replicated (numpy) — `multihost.to_host` the state first."""
    from eventgrad_tpu.parallel import multihost

    path = os.path.abspath(path)
    tmp, prev = path + ".tmp", path + ".prev"
    # force=True clears a stale tmp itself, primary-only with internal syncs
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(tmp, state, force=True)
    if multihost.is_primary():
        if os.path.exists(path):
            # make room for the demotion; the current snapshot covers the gap
            if os.path.exists(prev):
                shutil.rmtree(prev)
            os.rename(path, prev)
        # the promoted snapshot may be absent (first save, or resumed from
        # .prev); never touch a surviving .prev until the new one is in place
        os.rename(tmp, path)
        if os.path.exists(prev):
            shutil.rmtree(prev)
    multihost.barrier("eg-ckpt-promote")


def host_snapshot(tree: Any) -> Any:
    """Blocking device->host COPY of a pytree — the eager half of an async
    save. Every leaf becomes an owned numpy array (np.array copies even
    host-resident leaves), so the caller may keep mutating the originals
    (trace carries, counters) while `AsyncWriter` serializes the frozen
    snapshot on its thread."""
    return jax.tree.map(lambda x: np.array(x), tree)


class AsyncWriter:
    """One background writer thread for checkpoint serialization.

    The dispatch pipeline (train/loop.py, docs/ARCHITECTURE.md "The
    dispatch pipeline") snapshots device state to host eagerly
    (`host_snapshot`) and hands the frozen copy here; `save()` runs
    `checkpoint.save`'s write-tmp/atomic-swap on the thread, so the
    orbax serialization overlaps the next dispatch block's compute.
    Crash safety is unchanged: the swap in `save` is the same atomic
    promote, so a kill mid-serialization still leaves `<path>` or
    `<path>.prev` complete for `latest()`.

    Join barriers: `save()` joins any in-flight write first (two writers
    must never race the tmp/prev swap), and `wait()`/`close()` join on
    exit. A failed background save re-raises at the next barrier —
    never silently."""

    def __init__(self) -> None:
        self._thread: Optional[threading.Thread] = None
        self._exc: Optional[BaseException] = None

    def save(
        self,
        path: str,
        payload: Any,
        span: Optional[Callable[[], ContextManager]] = None,
    ) -> None:
        """Serialize `payload` (host numpy — see `host_snapshot`) to
        `path` on the writer thread; joins the previous save first.
        `span` (zero-arg context-manager factory) wraps the write for
        observability (obs.Registry spans are thread-safe)."""
        self.wait()

        def work() -> None:
            try:
                import contextlib

                with (span() if span is not None else contextlib.nullcontext()):
                    save(path, payload)
            except BaseException as e:  # re-raised at the next barrier
                self._exc = e

        self._thread = threading.Thread(
            target=work, daemon=True, name="eg-ckpt-writer"
        )
        self._thread.start()

    def wait(self) -> None:
        """Join the in-flight save (if any) and re-raise its error."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._exc is not None:
            exc, self._exc = self._exc, None
            raise RuntimeError("async checkpoint save failed") from exc

    def close(self, raise_errors: bool = True) -> None:
        """Exit barrier. `raise_errors=False` is for exception-unwind
        paths: join without masking the primary exception — but a
        discarded save failure is still LOGGED (the snapshot on disk is
        the stale previous one; a resume would replay extra epochs)."""
        if raise_errors:
            self.wait()
            return
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._exc is not None:
            import logging

            logging.getLogger(__name__).warning(
                "async checkpoint save failed during unwind (snapshot on "
                "disk is the previous one): %r", self._exc,
            )
        self._exc = None


def latest(path: str) -> Optional[str]:
    """The newest complete snapshot for `path` (the primary, or the .prev
    left by a save interrupted mid-swap); None if neither exists."""
    path = os.path.abspath(path)
    for cand in (path, path + ".prev"):
        if os.path.exists(cand):
            return cand
    return None


def peek(path: str) -> Any:
    """Template-free raw restore -> host numpy pytree. Restores the WHOLE
    snapshot (orbax has no partial read here), so use it only where the
    shape of the snapshot is itself unknown — e.g. a membership-elastic
    resume must read the saved epoch before it can size the state
    template (the rank count at that epoch follows from the membership
    schedule; train/loop.py)."""
    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        return ckptr.restore(path)


def restore(path: str, template: Any, raw: Any = None) -> Any:
    """Restore into the structure of `template` (an abstract or concrete
    TrainState with the same shapes/dtypes). `raw` (a `peek` of the same
    snapshot) grafts from the already-deserialized pytree instead of
    re-reading disk — exact-structure like the orbax item restore: a
    template leaf the snapshot lacks raises."""
    if raw is not None:
        restored, missing = _graft(raw, template)
        if missing:
            raise ValueError(f"snapshot lacks leaves {missing}")
        return restored
    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        target = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
        return ckptr.restore(path, item=target)


def _path_name(keypath) -> str:
    """'/'-joined leaf path that is stable across container kinds: flax
    struct fields (GetAttrKey), dicts (DictKey), and tuples vs the lists
    orbax restores them as (SequenceKey) all reduce to their name/index."""
    return "/".join(
        str(getattr(k, "name", getattr(k, "key", getattr(k, "idx", k))))
        for k in keypath
    )


def restore_with_fill(path: str, template: Any, raw: Any = None):
    """Forward-compatible restore: snapshot leaves graft onto `template`
    BY PATH, and any leaf the snapshot lacks keeps its template (init)
    value — so a state field added after the snapshot was taken (e.g. a
    new counter) resumes from its initial value instead of failing the
    exact-structure match `restore` enforces. Returns (restored,
    missing_path_names); the caller decides how loud to be about the
    fills. A snapshot leaf with no template counterpart is ignored.
    `raw` (a `peek` of the same snapshot) skips the disk read."""
    if raw is None:
        path = os.path.abspath(path)
        with ocp.PyTreeCheckpointer() as ckptr:
            raw = ckptr.restore(path)
    return _graft(raw, template)


def _graft(raw: Any, template: Any):
    """Path-keyed graft of a template-free restore onto `template`:
    (leaves filled in template order, missing template path names)."""
    raw_map = {
        _path_name(kp): v
        for kp, v in jax.tree_util.tree_flatten_with_path(raw)[0]
    }
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    filled, missing = [], []
    for kp, tmpl_leaf in flat:
        name = _path_name(kp)
        if name in raw_map:
            # host numpy, like the exact-structure restore returns (the
            # trace carry is MUTATED by the trace writer; device arrays
            # would break it)
            raw_leaf = np.asarray(raw_map[name])
            tmpl_np = np.asarray(tmpl_leaf)
            if raw_leaf.shape != tmpl_np.shape:
                # a path that still exists but changed shape (different
                # rank count, history depth, ...) is NOT an added-field
                # migration — grafting it would corrupt state silently
                raise ValueError(
                    f"snapshot leaf {name} has shape {raw_leaf.shape}, "
                    f"template wants {tmpl_np.shape}"
                )
            filled.append(raw_leaf.astype(tmpl_np.dtype))
        else:
            missing.append(name)
            filled.append(tmpl_leaf)
    return jax.tree_util.tree_unflatten(treedef, filled), missing
