"""Gate logic of the opportunistic TPU capture watchdog.

tools/tpu_watch.py is import-safe (main() is __main__-guarded); these
pin the artifact latches that decide whether a rare live window is
spent re-earning an artifact or advancing the ladder.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from tools import tpu_watch


def _write(tmp_path, name, obj):
    p = os.path.join(tmp_path, name)
    with open(p, "w") as f:
        json.dump(obj, f)
    return p


def test_is_tpu_artifact_latch(tmp_path):
    tmp = str(tmp_path)
    # only chip-captured artifacts count
    assert tpu_watch._is_tpu_artifact(_write(tmp, "a.json", {"platform": "tpu"}))
    assert not tpu_watch._is_tpu_artifact(
        _write(tmp, "b.json", {"platform": "cpu"})
    )
    # the flagship rungs latch only on a COMPLETE artifact: a partial
    # (pre-MNIST-leg) publish keeps the rung open
    partial = _write(tmp, "c.json", {"platform": "tpu", "step_ms_eventgrad": 1})
    assert not tpu_watch._is_tpu_artifact(partial, required=tpu_watch._FULL_KEYS)
    full = _write(tmp, "d.json", {
        "platform": "tpu", "mnist_msgs_saved": 70.0, "mnist_vs_baseline": 1.0,
    })
    assert tpu_watch._is_tpu_artifact(full, required=tpu_watch._FULL_KEYS)
    # missing / malformed files never gate a rung shut
    assert not tpu_watch._is_tpu_artifact(os.path.join(tmp, "missing.json"))
    bad = os.path.join(tmp, "bad.json")
    with open(bad, "w") as f:
        f.write("{not json")
    assert not tpu_watch._is_tpu_artifact(bad)


def test_swept_table_and_grid_gates(tmp_path):
    tmp = str(tmp_path)
    # the tune rung is satisfied only by an on-chip swept table
    assert tpu_watch._is_swept_table(_write(tmp, "t.json", {"swept": True}))
    assert not tpu_watch._is_swept_table(_write(tmp, "u.json", {"swept": False}))
    assert not tpu_watch._is_swept_table(os.path.join(tmp, "nope.json"))
    # only a grid whose header says platform tpu may replace the artifact
    g = os.path.join(tmp, "g.jsonl")
    with open(g, "w") as f:
        f.write(json.dumps({"platform": "tpu"}) + "\n")
        f.write(json.dumps({"row": 1}) + "\n")
    assert tpu_watch._is_tpu_grid(g)
    g2 = os.path.join(tmp, "g2.jsonl")
    with open(g2, "w") as f:
        f.write(json.dumps({"platform": "cpu"}) + "\n")
    assert not tpu_watch._is_tpu_grid(g2)


def test_relay_tcp_returns_verdict_string():
    # in any environment this returns a short verdict string; in the
    # build container the relay port is famously refused
    v = tpu_watch._relay_tcp()
    assert isinstance(v, str) and v
