"""Bytes-vs-accuracy frontier across trigger policies and wire dtypes.

The TriggerPolicy subsystem (parallel/policy.py) claims micro's
partitioned wire is strictly cheaper than top-k AT EQUAL CAPACITY —
ownership is implicit in the (rank, pass) pair, so the wire carries no
int32 index lanes — while the norm-delta trigger and the hybrid gate
trade bytes against accuracy differently. This tool MEASURES that
frontier instead of asserting it: one leg per (policy, wire dtype) on
LeNetCifar over Ring(8), every leg a real train() run on the synthetic
CIFAR-shaped task, bytes taken from the executed step's
`sent_bytes_wire_real_per_step_per_chip` metric (what the wire
actually moves, not a formula re-derivation).

Equal capacity, by construction: C = the largest static partition
(`policy.max_partition_elems(spec, n_ranks)`), the micro/hybrid compact
wire's floor. The norm_delta/micro/hybrid legs pin the compact budget
to C via `compact_frac = C / n_params`; the topk leg's
`topk_percent = 100 * C / n_params` makes its per-leaf k sum >= C.
At f32 the comparison is then micro ~ 4*C + L fire bytes vs
topk ~ (4+4)*C + L: the 4-bytes-per-value index lane is the entire
difference, and the gate `micro_below_topk_bytes` requires it strictly,
per wire dtype.

Gates (encoded in tools/validate_artifacts.py FRONTIER_SCHEMA, pinned
by tests/test_artifacts.py):
  * micro_below_topk_bytes — micro's measured bytes/step strictly below
    topk's at every swept wire dtype.
  * acc_gap_pt <= 0.5 — per-policy accuracy spread ACROSS wire dtypes
    (a wire dtype must be a bytes knob, not an accuracy knob; gaps
    between policies are the frontier itself and are reported, not
    gated).
  * replay_bitwise — every f32 leg re-run from its seed reproduces
    final params bitwise and the same accuracy.

Usage:
  python tools/frontier_sweep.py [--out artifacts/frontier_cpu.json]
                                 [--fast]
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import sys
import time
from typing import Any, Dict, List

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FRONTIER_SCHEMA_VERSION = 1

WIRES = {"f32": None, "bf16": "bf16", "int8": "int8"}


def _leg_kwargs(pol: str, frac: float, pct: float) -> Dict[str, Any]:
    """train() kwargs for one policy at the shared capacity point."""
    from eventgrad_tpu.parallel.sparsify import SparseConfig

    if pol == "topk":
        # sp's compact is capacity-free (WireSpec.compact_needs_capacity
        # False): no compact_frac; the capacity pin rides topk_percent
        return dict(
            algo="sp_eventgrad", trigger_policy="topk",
            gossip_wire="compact",
            sparse_cfg=SparseConfig(topk_percent=pct),
        )
    return dict(
        algo="eventgrad", trigger_policy=pol,
        gossip_wire="compact", compact_frac=frac,
    )


def _run_leg(model_fn, topo, data, pol, wire, frac, pct, args, event_cfg):
    from eventgrad_tpu.train.loop import train

    x, y, x_test, y_test = data
    state, hist = train(
        model_fn(), topo, x, y, epochs=args.epochs,
        batch_size=args.batch_size, learning_rate=args.learning_rate,
        momentum=args.momentum, event_cfg=event_cfg, seed=args.seed,
        wire=wire, x_test=x_test, y_test=y_test, log_every_epoch=True,
        **_leg_kwargs(pol, frac, pct),
    )
    return state, hist


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "artifacts", "frontier_cpu.json",
    ))
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 smoke leg: MLP/Ring(4), f32 only")
    ap.add_argument("--ranks", type=int, default=8)
    # 14 epochs x 32 passes: every policy x dtype leg SATURATES
    # (>= 99.8% measured; at 10 epochs micro's bf16 leg was still
    # mid-descent at 99.0, a 0.59 pt dtype gap that tripped the
    # 0.5 pt gate) — the dtype legs must compare plateaus
    ap.add_argument("--epochs", type=int, default=14)
    ap.add_argument("--n-synth", type=int, default=2048)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--learning-rate", type=float, default=1e-2)
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--policies",
                    default="norm_delta,topk,micro,hybrid")
    ap.add_argument("--wires", default="f32,bf16,int8")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    import jax  # noqa: F401  (import after argparse: --help stays fast)
    import jax.numpy as jnp

    from eventgrad_tpu.data.datasets import synthetic_dataset
    from eventgrad_tpu.models import MLP, LeNetCifar
    from eventgrad_tpu.parallel import arena as arena_lib
    from eventgrad_tpu.parallel import policy as policy_lib
    from eventgrad_tpu.parallel.events import EventConfig
    from eventgrad_tpu.parallel.topology import Ring

    if args.fast:
        # 1 epoch = 32 passes: past warmup, rotation live; the gates
        # the smoke checks (bytes ordering, replay) don't need depth
        args.ranks, args.epochs, args.n_synth = 4, 1, 256
        args.wires = "f32"
        model_fn, model_name, in_shape = (
            lambda: MLP(hidden=16), "mlp16", (8, 8, 1),
        )
    else:
        model_fn, model_name, in_shape = (
            LeNetCifar, "lenet_cifar", (32, 32, 3),
        )
    policies = [p for p in args.policies.split(",") if p]
    wires = [w for w in args.wires.split(",") if w]
    bad = [w for w in wires if w not in WIRES]
    if bad:
        raise SystemExit(f"unknown wire dtypes {bad}; known: "
                         f"{sorted(WIRES)}")
    for p in policies:
        policy_lib.resolve(
            p, "sp_eventgrad" if p == "topk" else "eventgrad"
        )

    topo = Ring(args.ranks)
    x, y = synthetic_dataset(args.n_synth, in_shape, seed=3)
    x_test, y_test = synthetic_dataset(
        max(256, args.n_synth // 4), in_shape, seed=3, split="test",
    )
    data = (x, y, x_test, y_test)
    event_cfg = EventConfig(adaptive=True, horizon=0.95,
                            warmup_passes=5, max_silence=20)

    params0 = model_fn().init(
        jax.random.PRNGKey(0), jnp.zeros((1,) + in_shape)
    )["params"]
    spec = arena_lib.arena_spec(params0)
    n_params = int(spec.n_total)
    cap = policy_lib.max_partition_elems(spec, topo.n_ranks)
    frac = cap / n_params
    pct = 100.0 * cap / n_params
    parts = policy_lib.validate_partitions(spec, topo.n_ranks)
    if not parts["ok"]:
        raise SystemExit(f"partition geometry invalid: {parts}")

    t0 = time.time()
    legs: List[Dict[str, Any]] = []
    for pol in policies:
        for wname in wires:
            wire = WIRES[wname]
            state, hist = _run_leg(model_fn, topo, data, pol, wire,
                                   frac, pct, args, event_cfg)
            h = hist[-1]
            leg = {
                "policy": pol,
                "wire": wname,
                "algo": h["algo"],
                "gossip_wire": h.get("gossip_wire") or "masked",
                "bytes_per_step_per_chip": float(
                    h["sent_bytes_wire_real_per_step_per_chip"]
                ),
                "test_accuracy": float(h["test_accuracy"]),
                "loss": float(h["loss"]),
                "msgs_saved_pct": float(h.get("msgs_saved_pct", 0.0)),
                "fired_frac": float(h.get("fired_frac", 1.0)),
            }
            assert h.get("policy") == pol, (
                f"history stamped policy {h.get('policy')!r}, ran {pol!r}"
            )
            if wire is None:
                # replay: same seed, same leg — params must reproduce
                state2, hist2 = _run_leg(model_fn, topo, data, pol,
                                         wire, frac, pct, args,
                                         event_cfg)
                leg["replay_bitwise"] = bool(all(
                    np.array_equal(np.asarray(a), np.asarray(b))
                    for a, b in zip(jax.tree.leaves(state.params),
                                    jax.tree.leaves(state2.params))
                ) and hist2[-1]["test_accuracy"] == h["test_accuracy"])
            legs.append(leg)
            print(f"  {pol}/{wname}: bytes/step="
                  f"{leg['bytes_per_step_per_chip']:.0f} "
                  f"acc={leg['test_accuracy']:.2f}"
                  + (" replay=" + str(leg.get("replay_bitwise"))
                     if "replay_bitwise" in leg else ""))

    by_pol: Dict[str, List[Dict[str, Any]]] = {}
    for leg in legs:
        by_pol.setdefault(leg["policy"], []).append(leg)
    policy_acc_gaps = {
        p: round(max(l["test_accuracy"] for l in ls)
                 - min(l["test_accuracy"] for l in ls), 3)
        for p, ls in by_pol.items()
    }
    acc_gap = max(policy_acc_gaps.values())
    micro_below = True
    if "micro" in by_pol and "topk" in by_pol:
        for wname in wires:
            mb = [l for l in by_pol["micro"] if l["wire"] == wname]
            tb = [l for l in by_pol["topk"] if l["wire"] == wname]
            if mb and tb:
                micro_below = micro_below and (
                    mb[0]["bytes_per_step_per_chip"]
                    < tb[0]["bytes_per_step_per_chip"]
                )

    rec = {
        "bench": "frontier",
        "schema_version": FRONTIER_SCHEMA_VERSION,
        "platform": f"{platform.system()}-{jax.default_backend()}",
        "topo": f"ring:{args.ranks}",
        "model": model_name,
        "op_point": {
            "epochs": args.epochs, "batch_size": args.batch_size,
            "n_synth": args.n_synth, "seed": args.seed,
            "learning_rate": args.learning_rate,
            "momentum": args.momentum,
        },
        "n_params": n_params,
        "capacity": int(cap),
        "capacity_frac": round(frac, 4),
        "topk_percent": round(pct, 4),
        "partition_sizes": parts["sizes"],
        "legs": legs,
        "n_policies": len(by_pol),
        "n_wire_dtypes": len(wires),
        "policy_acc_gaps": policy_acc_gaps,
        "acc_gap_pt": round(acc_gap, 3),
        "micro_below_topk_bytes": bool(micro_below),
        "replay_bitwise": bool(all(
            l.get("replay_bitwise", True) for l in legs
        )),
        "wall_s": round(time.time() - t0, 1),
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps({k: v for k, v in rec.items() if k != "legs"},
                     indent=1))
    ok = (rec["micro_below_topk_bytes"] and rec["acc_gap_pt"] <= 0.5
          and rec["replay_bitwise"])
    print(f"frontier sweep: {'OK' if ok else 'FAILED'} -> {args.out}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
