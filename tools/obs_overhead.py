"""Telemetry overhead micro-bench: obs on vs off, same step, same data.

The acceptance bar for the telemetry subsystem (docs/OBSERVABILITY.md):
steady-state per-step overhead of the on-device accumulators < 3% on the
CPU micro-bench. This tool measures it the same way
tools/overhead_ablation.py measures the event-trigger overhead — the
`utils.profiling.timed_steps` harness over the jitted lifted step, CNN-2
on a 4-rank ring (the reference MNIST op-point's model) — and writes the
paired numbers as one JSON artifact (committed:
artifacts/obs_overhead_cpu.json).

Usage: python tools/obs_overhead.py [--steps 40] [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from eventgrad_tpu.utils import compile_cache  # noqa: E402

compile_cache.honor_cpu_pin()
# persistent XLA cache: repeated overhead runs must not re-pay the jit
# compile per process (no-op on the CPU backend)
compile_cache.enable()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from eventgrad_tpu.data.datasets import synthetic_dataset  # noqa: E402
from eventgrad_tpu.data.sharding import batched_epoch  # noqa: E402
from eventgrad_tpu.models import CNN2  # noqa: E402
from eventgrad_tpu.obs import Registry, TelemetryState  # noqa: E402
from eventgrad_tpu.parallel.events import EventConfig  # noqa: E402
from eventgrad_tpu.parallel.spmd import spmd, stack_for_ranks  # noqa: E402
from eventgrad_tpu.parallel.topology import Ring  # noqa: E402
from eventgrad_tpu.train.state import init_train_state  # noqa: E402
from eventgrad_tpu.train.steps import make_train_step  # noqa: E402
from eventgrad_tpu.utils import trees  # noqa: E402
from eventgrad_tpu.utils.profiling import timed_steps  # noqa: E402


def measure(obs: bool, n_steps: int, batch: int = 16) -> dict:
    topo = Ring(4)
    model = CNN2()
    tx = optax.sgd(0.05)
    cfg = EventConfig(adaptive=True, horizon=0.95, warmup_passes=5)
    state = init_train_state(model, (28, 28, 1), tx, topo, "eventgrad", cfg)
    if obs:
        state = state.replace(telemetry=stack_for_ranks(
            TelemetryState.init(
                trees.tree_num_leaves(state.params), topo.n_neighbors
            ),
            topo,
        ))
    step = jax.jit(spmd(
        make_train_step(model, tx, topo, "eventgrad", event_cfg=cfg, obs=obs),
        topo,
    ))
    x, y = synthetic_dataset(4 * batch * n_steps, (28, 28, 1), seed=3)
    xb, yb = batched_epoch(x, y, 4, batch)
    batches = [
        (jnp.asarray(xb[:, s]), jnp.asarray(yb[:, s])) for s in range(n_steps)
    ]
    out = timed_steps(step, state, batches, warmup=5)
    out.pop("state")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--reps", type=int, default=3,
                    help="interleaved off/on repetitions; per-config "
                         "result is the min-p50 rep (the least "
                         "noise-contaminated estimate — single-ordered "
                         "pairs measured NEGATIVE overhead from process "
                         "warmup alone)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    reg = Registry()
    results = {}
    # interleave the configs so allocator/cache warmup splits evenly
    # across both instead of gifting the second config a warm process
    for rep in range(args.reps):
        for name, obs in (("obs_off", False), ("obs_on", True)):
            r = measure(obs, args.steps)
            if (
                name not in results
                or r["step_ms_p50"] < results[name]["step_ms_p50"]
            ):
                results[name] = r
    for name in results:
        reg.observe_latency(results[name], prefix=name)
    # p50-of-best-rep is the honest center for a CPU micro-bench (means
    # absorb scheduler hiccups); the mean rides along
    p50_off = results["obs_off"]["step_ms_p50"]
    p50_on = results["obs_on"]["step_ms_p50"]
    rec = {
        "bench": "obs_overhead",
        "model": "CNN2",
        "mesh": "ring:4 (vmap)",
        "n_timed_steps": args.steps,
        "reps": args.reps,
        "platform": jax.devices()[0].platform,
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "results": results,
        "overhead_pct_p50": round(100.0 * (p50_on / p50_off - 1.0), 2),
        "overhead_pct_mean": round(
            100.0
            * (results["obs_on"]["step_ms_mean"]
               / results["obs_off"]["step_ms_mean"] - 1.0),
            2,
        ),
        "prometheus": reg.prometheus_text(),
    }
    print(json.dumps(rec, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rec, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
