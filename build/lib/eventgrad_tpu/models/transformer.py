"""Decoder-only Transformer LM with pluggable sequence parallelism.

Beyond reference parity (the reference trains only image CNNs): the
framework's long-context story. Attention runs in one of four modes:

  * "full"    — single-rank exact attention (materialized scores).
  * "flash"   — single-rank fused Pallas FlashAttention kernel (VMEM-
                streamed scores, custom fwd+bwd; ops/attention.py).
  * "ring"    — ring attention over a named SP mesh axis: KV blocks rotate
                around the ICI ring, O(T/N) memory per chip.
  * "ulysses" — all-to-all head-sharded attention over the SP axis.

Under a hybrid mesh (e.g. axes ("dp","sp"), gossip_axes=("dp",)) the same
model trains with EventGraD/D-PSGD gossip across `dp` while each replica's
sequence is sharded across `sp` — the two ring structures (parameter gossip
and KV rotation) ride the same torus. Position embeddings are global: each
SP rank offsets by its axis index.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from eventgrad_tpu.parallel.ring_attention import (
    full_attention,
    ring_attention,
    ulysses_attention,
)
from eventgrad_tpu.parallel.topology import Topology


class Block(nn.Module):
    dim: int
    n_heads: int
    attn: str
    topo: Optional[Topology]
    sp_axis: Optional[str]
    dtype: Any = jnp.float32
    use_flash: bool = False

    @nn.compact
    def __call__(self, x):
        b, t, _ = x.shape
        h = self.n_heads
        d = self.dim // h

        y = nn.LayerNorm(dtype=self.dtype)(x)
        qkv = nn.Dense(3 * self.dim, use_bias=False, dtype=self.dtype)(y)
        q, k, v = jnp.split(qkv.reshape(b, t, 3 * h, d), 3, axis=2)
        if self.attn == "ring":
            o = ring_attention(q, k, v, self.topo, axis=self.sp_axis,
                               causal=True, use_flash=self.use_flash)
        elif self.attn == "ulysses":
            o = ulysses_attention(q, k, v, self.topo, axis=self.sp_axis,
                                  causal=True, use_flash=self.use_flash)
        elif self.attn == "flash" or (self.attn == "full" and self.use_flash):
            from eventgrad_tpu.ops.attention import flash_attention

            o = flash_attention(q, k, v, causal=True)
        elif self.attn == "full":
            o = full_attention(q, k, v, causal=True)
        else:
            raise ValueError(f"unknown attn mode {self.attn!r}")
        x = x + nn.Dense(self.dim, use_bias=False, dtype=self.dtype)(
            o.reshape(b, t, self.dim)
        )

        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = nn.Dense(4 * self.dim, dtype=self.dtype)(y)
        y = nn.gelu(y)
        return x + nn.Dense(self.dim, dtype=self.dtype)(y)


class TransformerLM(nn.Module):
    vocab: int = 256
    dim: int = 128
    n_heads: int = 8
    n_layers: int = 2
    max_len: int = 1024  # GLOBAL sequence length budget
    attn: str = "full"  # "full" | "flash" | "ring" | "ulysses"
    topo: Optional[Topology] = None
    sp_axis: Optional[str] = None
    dtype: Any = jnp.float32
    use_flash: bool = False  # run ring/ulysses/full local attention through
    #                          the Pallas kernel (attn="flash" implies it)

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        b, t_local = tokens.shape
        x = nn.Embed(self.vocab, self.dim, dtype=self.dtype)(tokens)

        # global positions: offset by this rank's index on the SP axis
        offset = 0
        if self.attn in ("ring", "ulysses") and self.topo is not None:
            axis = self.sp_axis or self.topo.axes[0]
            offset = lax.axis_index(axis) * t_local
        pos = offset + jnp.arange(t_local)
        x = x + nn.Embed(self.max_len, self.dim, dtype=self.dtype)(pos)

        for _ in range(self.n_layers):
            x = Block(
                self.dim, self.n_heads, self.attn, self.topo, self.sp_axis,
                self.dtype, self.use_flash,
            )(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        return nn.Dense(self.vocab, dtype=self.dtype)(x).astype(jnp.float32)
