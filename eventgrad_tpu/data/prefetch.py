"""Epoch/block prefetcher: overlap host batch assembly with device compute.

The reference's data layer is synchronous C++ inside the train loop
(custom.hpp get() per sample, assembled by the libtorch dataloader between
steps). On TPU the equivalent host-side cost is assembling the stacked
[n_ranks, steps, batch, ...] epoch arrays that the scan-compiled epoch
consumes. `EpochPrefetcher` hides that cost: while the device runs epoch E,
a background thread assembles epoch E+1: the shard plan comes from
`sharding.shard_random/shard_sequential` (numpy-PCG, so the data order is
identical whether or not the native library built — resume bit-parity
holds across machines) and the batch gather uses the native memcpy kernels
(native/dataio.cpp) when available — ctypes calls drop the GIL, so the
overlap is real.

Block granularity (the dispatch pipeline, train/loop.py): `get_block`
serves the K-epoch stacked arrays of one jit-dispatch block and
speculatively assembles the NEXT block on the worker — including the
optional `transfer` callable (the loop passes the device_put), so block
B+1's host->device upload also overlaps block B's compute instead of
sitting on the dispatch critical path. Speculation misses (an access
order the speculation didn't predict) fall back to synchronous assembly,
are counted in `.misses`, and logged — a silently cold prefetcher is a
perf bug, not a correctness one.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional, Tuple

import numpy as np

from eventgrad_tpu.data import native
from eventgrad_tpu.data.sharding import epoch_index_plan, epoch_steps

_log = logging.getLogger(__name__)


class EpochPrefetcher:
    """Double-buffered epoch/block batch assembly.

    get(epoch) returns (xb, yb) shaped [n_ranks, steps, batch, ...] /
    [n_ranks, steps, batch] — identical layout and shard semantics to
    `sharding.batched_epoch` — and immediately starts assembling
    epoch+1 in the background. get_block(first, last, next_span=...)
    returns the epochs first..last concatenated along the steps axis
    (what a K-epoch dispatch block consumes) and speculates `next_span`
    instead. With `transfer` set (e.g. `jnp.asarray` per array), the
    background thread also runs the device transfer, so the returned
    block is already on device.
    """

    def __init__(
        self,
        x: np.ndarray,
        y: np.ndarray,
        n_ranks: int,
        batch_size: int,
        *,
        random: bool = False,
        seed: int = 0,
        last_epoch: Optional[int] = None,
        transfer: Optional[Callable[[np.ndarray], object]] = None,
    ):
        # preserve integer inputs (token sequences); images go to float32
        # (one rule with the device-resident path: sharding.input_cast_dtype)
        from eventgrad_tpu.data.sharding import input_cast_dtype

        self.x = np.ascontiguousarray(x, input_cast_dtype(x))
        self.y = np.ascontiguousarray(y, np.int32)
        self.n_ranks = n_ranks
        self.batch = batch_size
        self.random = random
        self.seed = seed
        self.last_epoch = last_epoch  # no speculative assembly past this
        self.transfer = transfer
        #: speculation misses: a get()/get_block() the pending background
        #: assembly did not predict (fell back to synchronous assembly)
        self.misses = 0
        # validates batch/shard sizes too (single source of truth)
        self.steps = epoch_steps(len(x), n_ranks, batch_size)
        #: ((first, last), thread, box) of the in-flight speculation
        self._pending: Optional[Tuple[Tuple[int, int], threading.Thread, dict]] = None

    def _assemble(self, epoch: int) -> Tuple[np.ndarray, np.ndarray]:
        idx = epoch_index_plan(
            len(self.x), self.n_ranks, self.batch,
            random=self.random, seed=self.seed, epoch=epoch,
        )
        return native.gather_batches(self.x, self.y, idx)

    def _assemble_span(self, first: int, last: int):
        """Assemble epochs first..last stacked [n_ranks, K*steps, B, ...]
        and apply the transfer (device_put) when configured — the worker
        thread runs this whole chain, so the H2D upload overlaps too."""
        parts = [self._assemble(e) for e in range(first, last + 1)]
        if len(parts) == 1:
            xb, yb = parts[0]
        else:
            xb = np.concatenate([p[0] for p in parts], axis=1)
            yb = np.concatenate([p[1] for p in parts], axis=1)
        del parts
        if self.transfer is not None:
            return self.transfer(xb), self.transfer(yb)
        return xb, yb

    def _start(self, span: Tuple[int, int]):
        box: dict = {}

        def work():
            try:
                box["out"] = self._assemble_span(*span)
            except BaseException as e:  # surfaced by the consuming get()
                box["err"] = e

        th = threading.Thread(
            target=work, daemon=True, name=f"eg-prefetch-{span[0]}-{span[1]}"
        )
        th.start()
        return (span, th, box)

    def _take(self, span: Tuple[int, int]):
        """Consume the pending speculation if it matches `span`; None on a
        miss (counted and logged — the caller assembles synchronously)."""
        if self._pending is None:
            return None
        pspan, th, box = self._pending
        th.join()  # either our span, or stale speculation to retire
        self._pending = None
        if pspan != span:
            self.misses += 1
            _log.warning(
                "prefetch speculation miss #%d: assembled epochs %s, "
                "requested %s — falling back to synchronous assembly",
                self.misses, pspan, span,
            )
            if "err" in box:
                # the stale speculation ALSO failed: surface the root
                # cause next to the miss (the synchronous retry below
                # will usually re-raise it, but not necessarily — e.g.
                # a transient I/O fault)
                _log.warning(
                    "stale prefetch speculation %s had failed: %r",
                    pspan, box["err"],
                )
            return None
        if "err" in box:
            raise box["err"]
        return box["out"]

    def _clamp_span(self, span: Optional[Tuple[int, int]]):
        if span is None:
            return None
        first, last = span
        if self.last_epoch is not None:
            if first > self.last_epoch:
                return None
            last = min(last, self.last_epoch)
        return (first, last)

    def get_block(
        self,
        first: int,
        last: int,
        next_span: Optional[Tuple[int, int]] = None,
    ):
        """One dispatch block's stacked arrays; speculate `next_span`
        (the loop's next block bounds) in the background."""
        out = self._take((first, last))
        if out is None:  # miss (first call or unpredicted access order)
            out = self._assemble_span(first, last)
        nxt = self._clamp_span(next_span)
        if nxt is not None:
            self._pending = self._start(nxt)
        return out

    def get(self, epoch: int) -> Tuple[np.ndarray, np.ndarray]:
        """Single-epoch access (the pre-block API): speculates epoch+1."""
        nxt = (epoch + 1, epoch + 1)
        if self.last_epoch is not None and epoch >= self.last_epoch:
            nxt = None
        return self.get_block(epoch, epoch, next_span=nxt)

    def close(self) -> None:
        """Idempotent teardown: retire any in-flight speculation WITHOUT
        raising — a worker error in unconsumed speculative work must not
        mask the loop's real exception (the loop calls this in its
        `finally`). Safe to call repeatedly."""
        if self._pending is not None:
            _, th, box = self._pending
            self._pending = None
            try:
                th.join()
            except Exception:  # pragma: no cover - join never raises
                pass
            if "err" in box:
                _log.warning(
                    "prefetch worker error discarded at close: %r",
                    box["err"],
                )
