"""Subprocess worker for the 64-rank shard_map scale smoke.

Runs OUTSIDE pytest (tests/test_mesh_parity.py spawns it) because the
XLA host-platform device count is fixed at client startup: the tier-1
process pins 8 CPU devices (tests/conftest.py), so the 64-rank leg
needs its own interpreter with `--xla_force_host_platform_device_count
=64` set before jax initializes. Emits ONE JSON line on stdout:

  per-edge telemetry wire bytes, the step's sent_bytes_wire_real
  metric, the analytic per-neighbor formula
  (collectives.wire_real_bytes_per_neighbor), and the ppermute offsets
  collected from the traced mesh program vs the topology's declared
  neighbor offsets (analysis/audit.collect_collectives).

The parent asserts the three wire numbers agree EXACTLY and the mesh
program exchanges on the declared ring offsets only.
"""

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
_flags = " ".join(
    t for t in _flags.split()
    if "xla_force_host_platform_device_count" not in t
)
os.environ["XLA_FLAGS"] = (
    _flags + " --xla_force_host_platform_device_count=64"
).strip()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from eventgrad_tpu.analysis import audit  # noqa: E402
from eventgrad_tpu.data.datasets import synthetic_dataset  # noqa: E402
from eventgrad_tpu.data.sharding import batched_epoch  # noqa: E402
from eventgrad_tpu.models import MLP  # noqa: E402
from eventgrad_tpu.obs import device as obs_device  # noqa: E402
from eventgrad_tpu.parallel import collectives  # noqa: E402
from eventgrad_tpu.parallel.events import EventConfig  # noqa: E402
from eventgrad_tpu.parallel.spmd import (  # noqa: E402
    build_mesh, spmd, stack_for_ranks,
)
from eventgrad_tpu.parallel.topology import Ring  # noqa: E402
from eventgrad_tpu.train.state import init_train_state  # noqa: E402
from eventgrad_tpu.train.steps import make_train_step  # noqa: E402
from eventgrad_tpu.utils import trees  # noqa: E402

N_RANKS = 64
PER_RANK = 4
STEPS = 3


def main() -> int:
    topo = Ring(N_RANKS)
    model = MLP(hidden=8)
    tx = optax.sgd(0.05)
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=1)
    x, y = synthetic_dataset(
        N_RANKS * PER_RANK * STEPS, (8, 8, 1), seed=3
    )
    xb, yb = batched_epoch(x, y, N_RANKS, PER_RANK)

    state = init_train_state(
        model, (8, 8, 1), tx, topo, "eventgrad", cfg, arena=True
    )
    n_leaves = len(jax.tree.leaves(state.params))
    state = state.replace(
        telemetry=stack_for_ranks(
            obs_device.TelemetryState.init(n_leaves, topo.n_neighbors),
            topo,
        )
    )
    step = make_train_step(
        model, tx, topo, "eventgrad", event_cfg=cfg, arena=True, obs=True
    )
    mesh = build_mesh(topo)
    lifted = jax.jit(spmd(step, topo, mesh=mesh))

    batch0 = (jnp.asarray(xb[:, 0]), jnp.asarray(yb[:, 0]))
    closed = jax.make_jaxpr(lifted)(state, batch0)
    colls = audit.collect_collectives(closed.jaxpr, N_RANKS)
    offsets = sorted({
        o for rec in colls if rec["prim"] == "ppermute"
        for o in rec["offsets"]
    })
    bad = sorted({
        rec["prim"] for rec in colls
        if rec["prim"] not in ("ppermute", "axis_index")
    })

    m = None
    step_s = []
    for s in range(STEPS):
        t0 = time.perf_counter()
        state, m = lifted(
            state, (jnp.asarray(xb[:, s]), jnp.asarray(yb[:, s]))
        )
        jax.block_until_ready(jax.tree.leaves(state.params)[0])
        step_s.append(time.perf_counter() - t0)

    n_params = trees.tree_count_params(state.params) // N_RANKS
    per_nb = collectives.wire_real_bytes_per_neighbor(
        n_params, n_leaves, None, fire_bits=True
    )
    print(json.dumps({
        "n_devices": len(jax.devices()),
        "n_ranks": N_RANKS,
        "steps": STEPS,
        "per_neighbor_bytes_formula": float(per_nb),
        # [n_ranks, n_neighbors] cumulative per-edge wire bytes the
        # telemetry counted on device
        "edge_bytes": np.asarray(state.telemetry.edge_bytes).tolist(),
        # [n_ranks] per-step metric (constant per step per mode)
        "sent_bytes_wire_real": np.asarray(
            m["sent_bytes_wire_real"]
        ).tolist(),
        "n_neighbors": topo.n_neighbors,
        "exchange_offsets": offsets,
        "declared_offsets": sorted(nb.offset for nb in topo.neighbors),
        "undeclared_collectives": bad,
        "loss_finite": bool(np.isfinite(np.asarray(m["loss"])).all()),
        # steady step time: the first dispatch pays the 64-way compile,
        # so the committed number is the min of the post-compile steps
        "step_ms": round(min(step_s[1:]) * 1000, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
