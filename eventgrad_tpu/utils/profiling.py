"""Profiling & tracing — the reference's MPI_Wtime timers, upgraded.

The reference brackets the whole training loop with `MPI_Wtime`
(/root/reference/dmnist/cent/cent.cpp:98,158) and prints one number. Here:

  * `timed_steps` — a block_until_ready step-timing harness giving
    compile time and steady-state per-step latency percentiles.
  * `trace` — a context manager around `jax.profiler` emitting an XPlane
    trace viewable in TensorBoard/Perfetto (no-op with a `warnings`
    warning when the backend can't trace, e.g. over the axon tunnel).

`timed_steps` results fold into the unified telemetry surface via
`obs.Registry.observe_latency` (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Callable, Dict, Sequence

import jax
import numpy as np


def timed_steps(
    step_fn: Callable,
    state: Any,
    batches: Sequence[Any],
    warmup: int = 1,
) -> Dict[str, float]:
    """Run step_fn(state, batch) over batches; first call times compile.

    Returns {"compile_s", "step_ms_mean", "step_ms_p50", "step_ms_p95"} and
    leaves the final state in "state".
    """
    assert len(batches) > warmup, "need more batches than warmup steps"
    t0 = time.perf_counter()
    state, _ = step_fn(state, batches[0])
    jax.block_until_ready(state)
    compile_s = time.perf_counter() - t0

    times = []
    for batch in batches[1:]:
        t0 = time.perf_counter()
        state, _ = step_fn(state, batch)
        jax.block_until_ready(state)
        times.append(time.perf_counter() - t0)
    steady = times[max(0, warmup - 1):]
    ms = 1000 * np.asarray(steady)
    return {
        "compile_s": compile_s,
        "step_ms_mean": float(ms.mean()),
        "step_ms_p50": float(np.percentile(ms, 50)),
        "step_ms_p95": float(np.percentile(ms, 95)),
        "state": state,
    }


@contextlib.contextmanager
def trace(log_dir: str, python_tracer: bool = False):
    """jax.profiler trace scope; degrades to a no-op if tracing is
    unsupported on the active backend.

    python_tracer=False (default) keeps the host Python call tracer OFF:
    the round-4 flagship capture showed it flooding the export with ~1M
    host events, truncating the DEVICE timeline out of the trace JSON —
    the epoch scans' device events are the whole point of the capture."""
    started = False
    try:
        opts = jax.profiler.ProfileOptions()
        opts.python_tracer_level = 1 if python_tracer else 0
        jax.profiler.start_trace(log_dir, profiler_options=opts)
        started = True
    except Exception as e:
        import warnings

        # warnings, not a bare stderr print: capturable in tests/benches
        # (and still off stdout, which may carry a JSONL metrics stream)
        warnings.warn(
            f"[profiling] trace unavailable: {e}", RuntimeWarning,
            stacklevel=3,
        )
    try:
        yield
    finally:
        if started:
            jax.profiler.stop_trace()
