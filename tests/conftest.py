"""Test harness: emulate an 8-device mesh on CPU.

The environment pins JAX_PLATFORMS=axon (the real TPU tunnel) and pre-imports
jax via PYTHONPATH sitecustomize, so plain env vars are not enough; we must
also flip the config before any backend initializes. XLA_FLAGS still has to
be set before the CPU client spins up.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax
import pytest

jax.config.update("jax_platforms", "cpu")

assert len(jax.devices()) == 8, f"expected 8 CPU devices, got {jax.devices()}"

#: suites that dominate the wall clock (multi-epoch convergence runs,
#: Pallas-interpret flash sweeps, multi-process meshes, supervisor drills).
#: The default `pytest -m "not slow"` core tier must stay under ~5 min on
#: one CPU core (VERDICT r2 weak #6); the full suite is the nightly tier —
#: both commands + expected runtimes are in README.md.
SLOW_MODULES = {
    "test_convergence",
    "test_flash_attention",
    "test_flash_ring",
    "test_lm",
    "test_multihost",
    "test_supervise",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        mod = getattr(item, "module", None)
        if mod is not None and mod.__name__ in SLOW_MODULES:
            item.add_marker(pytest.mark.slow)
