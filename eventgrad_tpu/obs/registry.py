"""Host-side observability registry: one schema, three exporters.

Unifies the repo's three pre-existing fragments behind
`obs.schema.OBS_SCHEMA_VERSION`:

  * `utils.metrics.JsonlLogger` records  -> `record()` (the JSONL stream;
    a strict superset of the old records — every line gains `obs_schema`)
  * `chaos.monitor` per-edge health      -> `observe_health()` (gauges)
  * `utils.profiling.timed_steps` output -> `observe_latency()` (gauges)

plus host span traces (`span()` — dispatch blocks, eval, checkpoint,
telemetry flush) exported as Chrome-trace/Perfetto JSON so a training run
opens directly in `chrome://tracing` or https://ui.perfetto.dev.

Everything here is host Python — nothing touches the device. The loop
calls `span()` around operations it already performs; recording one span
is two `perf_counter` reads and a tuple append.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from eventgrad_tpu.obs.schema import OBS_SCHEMA_VERSION, PROM_PREFIX
from eventgrad_tpu.utils.metrics import JsonlLogger


class Span(tuple):
    """(name, cat, ts_us, dur_us, depth, args, tid) — depth is the
    nesting level at open time (0 = top-level) on the RECORDING thread,
    which Chrome trace infers from timestamps but tests assert directly;
    tid is a small per-registry thread index (0 = the first recording
    thread, i.e. the loop) so spans recorded from worker threads (the
    async checkpoint writer) land on their own trace track instead of
    fake-nesting under main-thread spans."""

    __slots__ = ()
    name = property(lambda s: s[0])
    cat = property(lambda s: s[1])
    ts_us = property(lambda s: s[2])
    dur_us = property(lambda s: s[3])
    depth = property(lambda s: s[4])
    args = property(lambda s: s[5])
    tid = property(lambda s: s[6] if len(s) > 6 else 0)


def _prom_escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


class Registry:
    """The one run-wide sink. Construct with an existing `JsonlLogger`
    (not owned; `close()` leaves it open for the caller) or a
    `jsonl_path` (owned; closed with the registry). Usable as a context
    manager — exceptions still flush exporter files the caller set up via
    `write_*` in its `finally`."""

    def __init__(
        self,
        logger: Optional[JsonlLogger] = None,
        jsonl_path: Optional[str] = None,
        echo: bool = False,
        fsync: bool = False,
        run_meta: Optional[Dict[str, Any]] = None,
    ):
        if logger is not None and jsonl_path is not None:
            raise ValueError("pass logger= or jsonl_path=, not both")
        self._own_logger = logger is None and jsonl_path is not None
        if self._own_logger:
            logger = JsonlLogger(jsonl_path, echo=echo, fsync=fsync)
        self._logger = logger
        self._t0 = time.perf_counter()
        self._spans: List[Span] = []
        self._spans_lock = threading.Lock()
        # per-thread open stack: spans are recorded from the loop AND from
        # background workers (the async checkpoint writer) — depth is the
        # nesting level within the RECORDING thread
        self._tls = threading.local()
        #: thread ident -> small stable tid (0 = first recording thread)
        self._tids: Dict[int, int] = {}
        #: (name, labels-frozenset-or-None) -> (value, labels-dict)
        self._gauges: Dict[Tuple[str, Any], Tuple[float, Optional[Dict]]] = {}
        self.run_meta = dict(run_meta or {})
        self.n_records = 0

    # --- JSONL stream ----------------------------------------------------
    def record(self, rec: Dict[str, Any]) -> None:
        """Stamp the schema version and forward to the JSONL stream (and
        echo, if the logger echoes). Safe without a logger: the record
        still counts, so spans/gauges-only registries work."""
        rec = {"obs_schema": OBS_SCHEMA_VERSION, **rec}
        self.n_records += 1
        if self._logger is not None:
            self._logger.log(rec)

    # --- spans -----------------------------------------------------------
    def _open_stack(self) -> List[Tuple[str, str, float, Dict[str, Any]]]:
        stack = getattr(self._tls, "open", None)
        if stack is None:
            stack = self._tls.open = []
        return stack

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "run", **args):
        """Record one host span; nests (depth = open spans at entry on the
        recording thread). Thread-safe: worker threads (e.g. the async
        checkpoint writer) record flat spans of their own."""
        stack = self._open_stack()
        depth = len(stack)
        t0 = time.perf_counter()
        stack.append((name, cat, t0, args))
        try:
            yield
        finally:
            stack.pop()
            t1 = time.perf_counter()
            ident = threading.get_ident()
            with self._spans_lock:
                tid = self._tids.setdefault(ident, len(self._tids))
                self._spans.append(Span((
                    name, cat,
                    (t0 - self._t0) * 1e6, (t1 - t0) * 1e6,
                    depth, dict(args), tid,
                )))

    @property
    def spans(self) -> List[Span]:
        with self._spans_lock:
            return list(self._spans)

    def chrome_trace(self) -> Dict[str, Any]:
        """Chrome Trace Event Format (complete "X" events) — loads in
        chrome://tracing and Perfetto. Spans sort by start time; nesting
        is recovered by the viewer from containment per tid (worker
        threads — the async checkpoint writer — get their own track, so
        their spans can overlap the loop's without fake nesting)."""
        events = [
            {
                "name": s.name,
                "cat": s.cat,
                "ph": "X",
                "ts": round(s.ts_us, 1),
                "dur": round(s.dur_us, 1),
                "pid": 0,
                "tid": s.tid,
                "args": {**s.args, "depth": s.depth},
            }
            for s in sorted(self.spans, key=lambda s: (s.ts_us, -s.dur_us))
        ]
        other: Dict[str, Any] = {
            "obs_schema": OBS_SCHEMA_VERSION,
            **{k: str(v) for k, v in self.run_meta.items()},
        }
        if self._gauges:
            # gauges ride along so a trace file is self-contained (the
            # Prometheus textfile is the scrapeable form of the same data)
            other["gauges"] = {
                name + (
                    "{%s}" % ",".join(
                        f"{k}={v}" for k, v in sorted(labels.items())
                    ) if labels else ""
                ): value
                for (name, _), (value, labels) in sorted(self._gauges.items())
            }
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": other,
        }

    def write_chrome_trace(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)

    # --- gauges (Prometheus textfile) ------------------------------------
    def gauge(
        self, name: str, value: float,
        labels: Optional[Dict[str, Any]] = None,
    ) -> None:
        key = (name, frozenset((labels or {}).items()))
        self._gauges[key] = (float(value), dict(labels) if labels else None)

    def observe_latency(self, timed: Dict[str, Any], prefix: str = "step") -> None:
        """Fold a `utils.profiling.timed_steps` result into gauges
        (`<prefix>_ms_mean/p50/p95`, `<prefix>_compile_s`)."""
        for k in ("step_ms_mean", "step_ms_p50", "step_ms_p95"):
            if k in timed:
                self.gauge(k.replace("step", prefix, 1), timed[k])
        if "compile_s" in timed:
            self.gauge(f"{prefix}_compile_s", timed["compile_s"])

    def observe_health(
        self, silence, drops, max_silence: int, edges=None,
    ) -> Dict[str, Any]:
        """Fold chaos.monitor PeerHealth counters into per-edge gauges;
        returns (and records nothing — caller attaches) the same summary
        dict `chaos.monitor.health_record` produces."""
        from eventgrad_tpu.chaos import monitor as chaos_monitor

        rec = chaos_monitor.health_record(
            silence, drops, max_silence, edges=edges
        )
        names = edges or [str(i) for i in range(len(rec["edge_silence_max"]))]
        for name, v in zip(names, rec["edge_silence_max"]):
            self.gauge("edge_silence_max", v, labels={"edge": name})
        self.gauge("chaos_drops_total", rec["chaos_drops"])
        return rec

    def prometheus_text(self) -> str:
        """Prometheus textfile-collector format (one gauge family per
        metric name, labels sorted) — point node_exporter's textfile
        collector at the written file."""
        by_name: Dict[str, List[Tuple[Optional[Dict], float]]] = {}
        for (name, _), (value, labels) in sorted(self._gauges.items()):
            by_name.setdefault(name, []).append((labels, value))
        lines = []
        for name, series in by_name.items():
            full = f"{PROM_PREFIX}_{name}"
            lines.append(f"# TYPE {full} gauge")
            for labels, value in series:
                if labels:
                    lab = ",".join(
                        f'{k}="{_prom_escape(v)}"'
                        for k, v in sorted(labels.items())
                    )
                    lines.append(f"{full}{{{lab}}} {value}")
                else:
                    lines.append(f"{full} {value}")
        return "\n".join(lines) + ("\n" if lines else "")

    def write_prometheus(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.prometheus_text())

    # --- lifecycle -------------------------------------------------------
    def close(self) -> None:
        if self._own_logger and self._logger is not None:
            self._logger.close()

    def __enter__(self) -> "Registry":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
