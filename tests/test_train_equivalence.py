"""Algorithm cross-equivalences — the reference's only built-in correctness
check (threshold 0 ≡ D-PSGD, dmnist/event/README.md) plus stronger ones the
reference could never run, on an emulated 8-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from _spmd import requires_shard_map
from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.data.sharding import batched_epoch
from eventgrad_tpu.models import MLP
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.sparsify import SparseConfig
from eventgrad_tpu.parallel.spmd import build_mesh, spmd
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.state import init_train_state
from eventgrad_tpu.train.steps import make_train_step

N_RANKS = 4
BATCH = 8
STEPS = 6


def _run(algo, backend="vmap", event_cfg=None, sparse_cfg=None, lr=0.05,
         topo=None):
    topo = topo or Ring(N_RANKS)
    n = topo.n_ranks
    model = MLP(hidden=16)
    tx = optax.sgd(lr)
    x, y = synthetic_dataset(n * BATCH * STEPS, (28, 28, 1), seed=3)
    xb, yb = batched_epoch(x, y, n, BATCH)

    state = init_train_state(model, (28, 28, 1), tx, topo, algo, event_cfg)
    step = make_train_step(
        model, tx, topo, algo, event_cfg=event_cfg, sparse_cfg=sparse_cfg
    )
    mesh = build_mesh(topo) if backend == "shard_map" else None
    lifted = jax.jit(spmd(step, topo, mesh=mesh))

    metrics = []
    for s in range(STEPS):
        state, m = lifted(state, (jnp.asarray(xb[:, s]), jnp.asarray(yb[:, s])))
    return state, m


def _params_np(state):
    return jax.tree.map(np.asarray, state.params)


def test_dpsgd_consensus_first_step_equals_allreduce():
    """With identical init, after one step: mean_r(dpsgd params) ==
    allreduce params (both are p0 - lr * mean(g))."""
    topo = Ring(N_RANKS)
    model = MLP(hidden=16)
    tx = optax.sgd(0.05)
    x, y = synthetic_dataset(N_RANKS * BATCH, (28, 28, 1), seed=3)
    xb, yb = batched_epoch(x, y, N_RANKS, BATCH)

    outs = {}
    for algo in ("dpsgd", "allreduce"):
        state = init_train_state(model, (28, 28, 1), tx, topo, algo)
        step = make_train_step(model, tx, topo, algo)
        lifted = jax.jit(spmd(step, topo))
        state, _ = lifted(state, (jnp.asarray(xb[:, 0]), jnp.asarray(yb[:, 0])))
        outs[algo] = state

    dpsgd_mean = jax.tree.map(lambda p: np.asarray(p).mean(0), outs["dpsgd"].params)
    allr = jax.tree.map(lambda p: np.asarray(p)[0], outs["allreduce"].params)
    for a, b in zip(jax.tree.leaves(dpsgd_mean), jax.tree.leaves(allr)):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_eventgrad_threshold0_equals_dpsgd():
    """constant=0 makes every parameter fire every pass -> exact D-PSGD."""
    cfg = EventConfig(adaptive=False, constant=0.0, warmup_passes=0)
    st_event, _ = _run("eventgrad", event_cfg=cfg)
    st_dpsgd, _ = _run("dpsgd")
    for a, b in zip(
        jax.tree.leaves(_params_np(st_event)), jax.tree.leaves(_params_np(st_dpsgd))
    ):
        np.testing.assert_allclose(a, b, atol=1e-7)


def test_eventgrad_threshold0_equals_dpsgd_on_torus():
    """The same equivalence must hold on the 2D torus (4 neighbors, /5
    mixing) — the BASELINE stress topology the reference never had. 2x4 so
    the four neighbor directions hit distinct ranks (a 2x2 torus aliases
    -1/+1 on every axis and would hide swapped-direction wiring bugs)."""
    from eventgrad_tpu.parallel.topology import Torus

    cfg = EventConfig(adaptive=False, constant=0.0, warmup_passes=0)
    st_event, _ = _run("eventgrad", event_cfg=cfg, topo=Torus(2, 4))
    st_dpsgd, _ = _run("dpsgd", topo=Torus(2, 4))
    for a, b in zip(
        jax.tree.leaves(_params_np(st_event)), jax.tree.leaves(_params_np(st_dpsgd))
    ):
        np.testing.assert_allclose(a, b, atol=1e-7)


def test_torus_differs_from_ring_after_divergence():
    """Sanity: the torus actually mixes differently than the ring once
    per-rank shards diverge (guards against axis wiring collapsing to a
    single neighborhood)."""
    from eventgrad_tpu.parallel.topology import Torus

    st_ring, _ = _run("dpsgd")
    st_torus, _ = _run("dpsgd", topo=Torus(2, 2))
    diffs = [
        float(np.abs(a - b).max())
        for a, b in zip(
            jax.tree.leaves(_params_np(st_ring)), jax.tree.leaves(_params_np(st_torus))
        )
    ]
    assert max(diffs) > 1e-6, diffs


def test_sparse_topk100_equals_dense_eventgrad():
    """k = 100% of elements makes the sparsified payload dense."""
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=3)
    st_dense, _ = _run("eventgrad", event_cfg=cfg)
    st_sparse, _ = _run(
        "sp_eventgrad", event_cfg=cfg, sparse_cfg=SparseConfig(topk_percent=100.0)
    )
    for a, b in zip(
        jax.tree.leaves(_params_np(st_dense)), jax.tree.leaves(_params_np(st_sparse))
    ):
        np.testing.assert_allclose(a, b, atol=1e-6)


@requires_shard_map
@pytest.mark.parametrize("algo", ["dpsgd", "eventgrad"])
def test_shard_map_matches_vmap(algo):
    """The same per-rank program must produce identical trajectories whether
    lifted onto a real device mesh or the single-chip simulator."""
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=2)
    st_v, _ = _run(algo, backend="vmap", event_cfg=cfg)
    st_s, _ = _run(algo, backend="shard_map", event_cfg=cfg)
    for a, b in zip(
        jax.tree.leaves(_params_np(st_v)), jax.tree.leaves(_params_np(st_s))
    ):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_eventgrad_saves_messages():
    """After warmup, a real threshold must suppress a nonzero share of sends
    while training still progresses (the headline EventGraD property)."""
    cfg = EventConfig(adaptive=True, horizon=0.95, warmup_passes=2)
    st, m = _run("eventgrad", event_cfg=cfg)
    topo = Ring(N_RANKS)
    sz = 4  # MLP tensors
    possible = topo.n_neighbors * STEPS * sz
    events = int(np.asarray(st.event.num_events).sum()) / N_RANKS
    assert events < possible, "no messages saved"
    assert events > 0, "no messages sent at all"


def test_allreduce_loss_decreases():
    topo = Ring(N_RANKS)
    model = MLP(hidden=32)
    tx = optax.sgd(0.05)
    x, y = synthetic_dataset(N_RANKS * BATCH * 20, (28, 28, 1), seed=5)
    xb, yb = batched_epoch(x, y, N_RANKS, BATCH)
    state = init_train_state(model, (28, 28, 1), tx, topo, "allreduce")
    lifted = jax.jit(spmd(make_train_step(model, tx, topo, "allreduce"), topo))
    losses = []
    for s in range(xb.shape[1]):
        state, m = lifted(state, (jnp.asarray(xb[:, s]), jnp.asarray(yb[:, s])))
        losses.append(float(np.asarray(m["loss"]).mean()))
    assert np.mean(losses[-5:]) < np.mean(losses[:5])
