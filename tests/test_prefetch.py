"""EpochPrefetcher: background assembly == inline assembly, any access order."""

import numpy as np
import pytest

from eventgrad_tpu.data import native
from eventgrad_tpu.data.prefetch import EpochPrefetcher


def _data(n=64, shape=(4, 4, 1), seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n,) + shape).astype(np.float32)
    y = rng.integers(0, 10, n).astype(np.int32)
    return x, y


@pytest.mark.parametrize("random", [False, True])
def test_prefetched_epochs_match_inline(random):
    x, y = _data()
    pre = EpochPrefetcher(x, y, n_ranks=4, batch_size=4, random=random, seed=3)
    try:
        for epoch in (1, 2, 3):
            xb, yb = pre.get(epoch)  # epochs 2,3 come from the background thread
            xe, ye = pre._assemble(epoch)
            np.testing.assert_array_equal(xb, xe)
            np.testing.assert_array_equal(yb, ye)
            assert xb.shape == (4, 4, 4, 4, 4, 1) and yb.shape == (4, 4, 4)
    finally:
        pre.close()


def test_out_of_order_epoch_still_correct():
    x, y = _data(seed=1)
    pre = EpochPrefetcher(x, y, n_ranks=2, batch_size=8, random=True, seed=0)
    try:
        pre.get(1)  # pending is now epoch 2
        xb, yb = pre.get(7)  # jump: miss path assembles inline
        xe, ye = pre._assemble(7)
        np.testing.assert_array_equal(xb, xe)
        np.testing.assert_array_equal(yb, ye)
    finally:
        pre.close()


def test_sequential_plan_is_disjoint_cover():
    x, y = _data(n=32)
    pre = EpochPrefetcher(x, y, n_ranks=4, batch_size=8, random=False)
    try:
        xb, yb = pre.get(1)
        # sequential sharding: rank r sees samples [r*8, (r+1)*8)
        np.testing.assert_array_equal(
            xb.reshape(4, 8, -1), x.reshape(32, -1).reshape(4, 8, -1)
        )
    finally:
        pre.close()


def test_no_speculation_past_last_epoch():
    x, y = _data()
    pre = EpochPrefetcher(x, y, 2, 8, random=True, last_epoch=3)
    try:
        pre.get(1)
        assert pre._pending is not None
        pre.get(2)
        pre.get(3)  # final epoch: nothing further to assemble
        assert pre._pending is None
    finally:
        pre.close()


def test_plan_identical_with_and_without_native(monkeypatch):
    """Shuffle order must not depend on whether libeg_dataio built."""
    from eventgrad_tpu.data import native as native_mod

    x, y = _data(n=96, seed=5)
    a = EpochPrefetcher(x, y, 2, 8, random=True, seed=9)
    xa, ya = a._assemble(4)
    monkeypatch.setattr(native_mod, "load_library", lambda: None)
    b = EpochPrefetcher(x, y, 2, 8, random=True, seed=9)
    xb, yb = b._assemble(4)
    np.testing.assert_array_equal(xa, xb)
    np.testing.assert_array_equal(ya, yb)


def test_batch_too_large_raises():
    x, y = _data(n=16)
    with pytest.raises(ValueError, match="larger than per-rank shard"):
        EpochPrefetcher(x, y, n_ranks=4, batch_size=8)


def test_shuffled_epochs_differ_and_are_deterministic():
    x, y = _data(n=128, seed=2)
    a = EpochPrefetcher(x, y, 2, 8, random=True, seed=5)
    b = EpochPrefetcher(x, y, 2, 8, random=True, seed=5)
    try:
        x1, _ = a.get(1)
        x2, _ = a.get(2)
        assert not np.array_equal(x1, x2)  # reshuffled per epoch
        x1b, _ = b.get(1)
        np.testing.assert_array_equal(x1, x1b)  # same (seed, epoch) -> same plan
    finally:
        a.close()
        b.close()
