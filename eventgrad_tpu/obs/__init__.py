"""Telemetry subsystem: on-device accumulators, host registry, exporters.

See docs/OBSERVABILITY.md. Device side: `TelemetryState` rides the
train-scan carry and flushes to host once per jit-dispatch block
(`train(obs="block"|"epoch")`). Host side: `Registry` unifies the JSONL
metrics stream, chaos peer-health, and profiling latencies behind one
versioned schema, with Prometheus-textfile and Chrome-trace/Perfetto
exporters. `obs.report.build_report` (tools/obs_report.py) renders a
self-contained run report from any history/JSONL. `obs.bubble`
decomposes a span trace into wall = steps + host bubble — the dispatch
pipeline's acceptance metric (tools/bubble_decomposition.py).
`obs.costmodel` + `obs.devicespec` are the performance ledger's
analytic side: phase-split FLOP/HBM-byte counts from the traced step's
jaxpr, MFU and roofline position against per-device peak specs
(tools/perf_ledger.py owns the cross-round trajectory + regression
gates; obs/schema.py PERF_FIELDS names every surface).
"""

from eventgrad_tpu.obs.device import TelemetryState, accumulate
from eventgrad_tpu.obs.registry import Registry
from eventgrad_tpu.obs.schema import OBS_SCHEMA_VERSION, SILENCE_BUCKETS

OBS_MODES = ("off", "block", "epoch")

__all__ = [
    "TelemetryState",
    "accumulate",
    "Registry",
    "OBS_SCHEMA_VERSION",
    "SILENCE_BUCKETS",
    "OBS_MODES",
]
