"""Command-line launcher for all four training algorithms.

Replaces the reference's five `mpirun -np N ./binary ARGS` entry points with
one flag-driven program. Reference argv semantics are preserved under new
names (event.cpp:88-100, spevent.cpp:47-60):

    argv[1] file_write   -> --log-file (JSONL instead of send{r}.txt)
    argv[2] thres_type   -> --thres-mode {adaptive,constant}
    argv[3] horizon|const-> --horizon / --constant
    argv[4] topk_percent -> --topk-percent

plus what MPI provided implicitly:

    mpirun -np N         -> --mesh ring:N | torus:XxY
                            (simulated on one chip with --backend sim,
                             or real devices with --backend mesh)

Examples:
    python -m eventgrad_tpu.cli --algo eventgrad --mesh ring:8 \
        --dataset mnist --model cnn2 --epochs 10 --batch-size 64 --lr 0.05 \
        --thres-mode adaptive --horizon 0.95
    python -m eventgrad_tpu.cli --algo sp_eventgrad --mesh ring:4 \
        --dataset cifar10 --model resnet18 --topk-percent 10
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import jax
import numpy as np

from eventgrad_tpu.chaos.crashpoint import GracefulPreemption
from eventgrad_tpu.chaos.integrity import (
    INTEGRITY_ABORT_EXIT, IntegrityEscalation,
)
from eventgrad_tpu.exitcodes import PREEMPTED_EXIT
from eventgrad_tpu.data.datasets import load_or_synthesize, synthetic_lm_dataset
from eventgrad_tpu.models import MODEL_REGISTRY
from eventgrad_tpu.parallel import multihost
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.sparsify import SparseConfig
from eventgrad_tpu.parallel.spmd import build_mesh
from eventgrad_tpu.parallel.topology import Ring, Topology, Torus
from eventgrad_tpu.train.loop import consensus_params, evaluate, rank0_slice, train
from eventgrad_tpu.train.steps import ALGOS
from eventgrad_tpu.utils.metrics import JsonlLogger


#: axes that shard parameters (tensor/pipeline/expert parallelism); any
#: other non-dp axis (e.g. "sp") replicates parameters and is aux
_SHARDED_AXES = ("tp", "pp", "ep")

#: transformer LM family — constructed from --dim/--heads/--layers/... and
#: the mesh (unlike MODEL_REGISTRY's zero-argument image models)
LM_MODELS = ("transformer", "transformer_tp", "transformer_pp", "transformer_moe")


def build_lm_model(args, topo: Topology):
    """Construct the requested transformer over the mesh's parallel axes."""
    from eventgrad_tpu.models.moe import MoETransformerLM
    from eventgrad_tpu.models.pp import PPTransformerLM
    from eventgrad_tpu.models.tp import TPTransformerLM
    from eventgrad_tpu.models.transformer import TransformerLM

    def need(axis: str):
        if axis not in topo.axes:
            raise SystemExit(
                f"--model {args.model} needs a {axis!r} axis in --mesh "
                f"(e.g. --mesh dp:2,{axis}:2); got {topo.axes}"
            )
        return topo.axis_size(axis)

    common = dict(vocab=args.vocab, dim=args.dim, n_heads=args.heads,
                  n_layers=args.layers, max_len=args.seq_len)
    if args.model == "transformer":
        if args.attn in ("ring", "ulysses"):
            need("sp")
            return TransformerLM(**common, attn=args.attn, topo=topo,
                                 sp_axis="sp")
        return TransformerLM(**common, attn=args.attn)
    if args.model == "transformer_tp":
        return TPTransformerLM(**common, axis="tp", tp_size=need("tp"))
    if args.model == "transformer_pp":
        return PPTransformerLM(**common, axis="pp", pp_size=need("pp"))
    return MoETransformerLM(**common, n_experts=args.n_experts, axis="ep",
                            ep_size=need("ep"))


def parse_mesh(spec: str):
    kind, _, dims = spec.partition(":")
    try:
        if kind == "ring":
            return Ring(int(dims))
        if kind == "torus":
            nx, ny = dims.lower().split("x")
            return Torus(int(nx), int(ny))
        if "," in spec or kind in ("dp", "ddp", "sp") + _SHARDED_AXES:
            # hybrid grammar: comma-separated axis:N pairs, e.g.
            # "dp:4,sp:2" or "dp:2,tp:2" — dp gossips, tp/pp/ep shard
            # parameters, ddp forms allreduce subgroups that shard data,
            # sp is a replicated aux axis sharing its group's batch
            axes, shape = [], []
            for part in spec.split(","):
                name, _, n = part.partition(":")
                name = name.strip()
                if name not in ("dp", "ddp", "sp") + _SHARDED_AXES:
                    raise ValueError(f"unknown axis {name!r}")
                axes.append(name)
                shape.append(int(n))
            if len(set(axes)) != len(axes):
                raise ValueError(f"duplicate axis in {spec!r}")
            return Topology(
                axes=tuple(axes),
                shape=tuple(shape),
                gossip_axes=tuple(a for a in axes if a == "dp"),
                sharded_axes=tuple(a for a in axes if a in _SHARDED_AXES),
                data_aux_axes=tuple(a for a in axes if a == "ddp"),
            )
    except (ValueError, TypeError) as e:
        raise argparse.ArgumentTypeError(f"bad mesh spec {spec!r}: {e}")
    raise argparse.ArgumentTypeError(
        f"bad mesh spec {spec!r} (ring:N, torus:XxY, or axis:N[,axis:N...] "
        f"with axes dp/ddp/sp/tp/pp/ep)"
    )


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="eventgrad-tpu", description=__doc__)
    p.add_argument("--algo", choices=ALGOS, default="eventgrad")
    p.add_argument("--mesh", type=parse_mesh, default="ring:4", help="ring:N or torus:XxY")
    p.add_argument("--backend", choices=["sim", "mesh", "auto"], default="sim",
                   help="sim = vmap all ranks onto one chip; mesh = one rank "
                        "per device (shard_map over a real device mesh — "
                        "collectives ride ICI/DCN); auto = mesh whenever "
                        "shard_map and enough devices exist, else sim")
    p.add_argument("--dataset",
                   choices=["mnist", "cifar10", "digits", "digits32",
                            "synthetic", "synthetic-lm",
                            "synthetic-imagenet"],
                   default=None,
                   help="default: mnist for image models, synthetic-lm for "
                        "transformers; digits = real handwritten scans "
                        "bundled with scikit-learn (no --data-dir or "
                        "network needed, MNIST geometry; digits32 = the "
                        "same real scans at the 32x32x3 CIFAR geometry); "
                        "synthetic-imagenet is the ImageNet-shaped "
                        "scale-stress stand-in "
                        "(--image-size/--num-classes)")
    p.add_argument("--image-size", type=int, default=64,
                   help="side length for --dataset synthetic-imagenet "
                        "(224 = true ImageNet shape)")
    p.add_argument("--num-classes", type=int, default=10,
                   help="label count for synthetic-imagenet (resnet models "
                        "only)")
    p.add_argument("--num-filters", type=int, default=64,
                   help="resnet stem width (64 = faithful; smaller for "
                        "smoke runs)")
    p.add_argument("--data-dir", default=None)
    p.add_argument("--model",
                   choices=sorted(MODEL_REGISTRY) + sorted(LM_MODELS),
                   default="cnn2")
    # LM / transformer knobs (--model transformer*)
    p.add_argument("--seq-len", type=int, default=128,
                   help="global sequence length (sp ranks hold seq-len/n_sp)")
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--dim", type=int, default=128)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--attn", choices=["full", "flash", "ring", "ulysses"],
                   default="full",
                   help="attention mode for --model transformer; ring/ulysses "
                        "need an sp axis in --mesh")
    p.add_argument("--n-experts", type=int, default=8,
                   help="experts for --model transformer_moe")
    p.add_argument("--epochs", type=int, default=10)          # event.cpp:255
    p.add_argument("--batch-size", type=int, default=64)      # event.cpp:145 (per rank)
    p.add_argument("--global-batch", type=int, default=None,
                   help="if set, per-rank batch = global/N (dcifar10 style, event.cpp:89-91)")
    p.add_argument("--lr", type=float, default=0.05)          # event.cpp:227
    p.add_argument("--momentum", type=float, default=0.0)     # 0.9 on CIFAR (:196-200)
    p.add_argument("--thres-mode", choices=["adaptive", "constant"], default="adaptive")
    p.add_argument("--horizon", type=float, default=0.95)
    p.add_argument("--constant", type=float, default=0.0)
    p.add_argument("--warmup-passes", type=int, default=30)   # event.cpp:262
    p.add_argument("--history", type=int, default=2)          # event.cpp:103
    p.add_argument("--max-silence", type=int, default=0,
                   help="bounded staleness (beyond reference): force a "
                   "parameter to fire after N silent passes; 0 = off, "
                   "1 = exact D-PSGD. Stabilizes aggressive horizons")
    p.add_argument("--topk-percent", type=float, default=10.0)
    p.add_argument("--trigger-policy",
                   choices=["norm_delta", "topk", "micro", "hybrid"],
                   default=None,
                   help="registered TriggerPolicy (parallel/policy.py) "
                        "driving the event trigger: norm_delta = the "
                        "EventGraD trigger (eventgrad default), topk = "
                        "sp_eventgrad's selection (its default), micro = "
                        "rotating owned-partition sends, index-free "
                        "(MiCRO, arXiv:2310.00967), hybrid = norm-delta "
                        "gate x owned partition. Default: the algo's "
                        "own policy")
    p.add_argument("--augment", action="store_true", help="CIFAR pad4+flip+crop32")
    p.add_argument("--staleness", type=int, default=0,
                   help="1 = mix with the previous step's received buffers "
                        "(deterministic model of the reference's one-sided "
                        "RMA asynchrony; lets XLA overlap the exchange with "
                        "compute; event algorithms only). D >= 2 = the "
                        "bounded-async gossip engine: per-edge delivery "
                        "queues, a rank runs up to D passes ahead of a "
                        "late neighbor (chaos lag=/slow= clauses schedule "
                        "the lag; eventgrad + arena only; see "
                        "docs/chaos.md 'Bounded-async gossip & "
                        "stragglers')")
    p.add_argument("--wire", choices=["bf16", "int8"], default=None,
                   help="compress gossip payloads on the wire: bf16 = half "
                        "the reference's f32 MPI wire bytes, int8 = a "
                        "quarter (per-leaf absmax quantization); local "
                        "params and event state stay full precision "
                        "(gossip algos only)")
    p.add_argument("--wire-bf16", action="store_true",
                   help="shorthand for --wire bf16")
    p.add_argument("--gossip-wire", choices=["dense", "compact"],
                   default="dense",
                   help="compact = budgeted compacted exchange (eventgrad "
                        "only): only fired leaves' elements ride the "
                        "interconnect, through a static buffer autotuned "
                        "from the post-warmup fire rate; fired leaves "
                        "beyond the budget defer to the next pass "
                        "(max_silence-overdue leaves get priority). Turns "
                        "msgs_saved_%% into real wire bytes — see "
                        "docs/compaction.md. dense = the masked full-"
                        "payload exchange (default)")
    p.add_argument("--compact-frac", type=float, default=None,
                   metavar="F",
                   help="explicit compact buffer capacity as a fraction "
                        "of the parameter count (0 < F <= 1); default: "
                        "autotune from the observed fire rate (requires "
                        "--gossip-wire compact)")
    p.add_argument("--arena", choices=["auto", "on", "off"], default="auto",
                   help="flat parameter arena for the gossip hot path "
                        "(parallel/arena.py): params, event wire buffers "
                        "and the mix/SGD tail run over one contiguous "
                        "per-rank buffer with cached leaf metadata — "
                        "bitwise-identical training, fewer per-step tree "
                        "traversals. auto (default) enables it for "
                        "dpsgd/eventgrad on plain data-parallel "
                        "topologies; off = legacy tree path (the A/B "
                        "knob of tools/overhead_ablation.py)")
    p.add_argument("--carrier-resident", action="store_true",
                   help="keep the event exchange's receive buffers "
                        "resident in the wire carrier dtype "
                        "(train/steps.py carrier_resident): under "
                        "--wire bf16|int8 EventState.bufs stores the "
                        "1-2 byte carrier (+ per-leaf scales for int8) "
                        "and the dequant fuses into the commit/mix "
                        "reads — bitwise-identical training at a "
                        "fraction of the buffer HBM traffic "
                        "(tools/overhead_ablation.py resident). Needs "
                        "eventgrad + the arena + --wire; composes with "
                        "--bucketed and --staleness >= 2 (the delivery "
                        "queues allocate carrier-resident slots too)")
    p.add_argument("--bucketed", type=int, default=0, metavar="K",
                   help="bucketed gossip schedule (train/steps.py): "
                        "segment the flat arena into K leaf-aligned "
                        "buckets and pipeline each bucket's gate/pack/"
                        "exchange/commit/mix so the scheduler can "
                        "overlap one bucket's transfer with another's "
                        "update work — bitwise-identical training "
                        "(tests/test_bucketed.py). eventgrad (needs "
                        "the arena) and sp_eventgrad; 0/1 = monolithic "
                        "(the default)")
    p.add_argument("--pipeline", choices=["auto", "on", "off"],
                   default="auto",
                   help="zero-bubble dispatch pipeline (train/loop.py): "
                        "dispatch block B+1 immediately and run block "
                        "B's host work (telemetry flush, history "
                        "records, eval readback, checkpoint "
                        "serialization) while the device computes — "
                        "training is bitwise-identical either way. auto "
                        "(default) enables it for single-process runs "
                        "without --fault-inject; off = the serial "
                        "block_until_ready chain (the A/B knob of "
                        "tools/bubble_decomposition.py)")
    p.add_argument("--fused", action="store_true",
                   help="Pallas fused gossip-mix+SGD update tail "
                        "(gossip algorithms; plain/momentum SGD only). "
                        "Off by default per measurement: the r2 v5e grid "
                        "timed the kernel at 0.79x the XLA fusion "
                        "(KERNELS_TPU.json); small leaves auto-route to "
                        "XLA either way (ops/fused_update.py). Flip the "
                        "default if a re-captured grid shows the "
                        "megacore-parallel kernel winning")
    p.add_argument("--random-sampler", action="store_true")
    p.add_argument("--sync-bn", action="store_true")
    p.add_argument("--seed", type=int, default=0)             # torch::manual_seed(0)
    p.add_argument("--log-file", default=None, help="JSONL metrics path")
    p.add_argument("--trace-file", default=None,
                   help="per-pass per-param send-trace JSONL (the reference's "
                        "file_write=1 send{r}.txt, event.cpp:337-391)")
    p.add_argument("--n-synth", type=int, default=4096)
    p.add_argument("--coordinator", default=None,
                   help="host:port of process 0 — joins a multi-host run "
                        "(mpirun's role; requires --backend mesh)")
    p.add_argument("--num-processes", type=int, default=1)
    p.add_argument("--process-id", type=int, default=0)
    p.add_argument("--checkpoint-dir", default=None,
                   help="snapshot the full gossip TrainState here")
    p.add_argument("--save-every", type=int, default=0,
                   help="checkpoint every N epochs (0 = final epoch only)")
    p.add_argument("--resume", action="store_true",
                   help="restore the latest snapshot from --checkpoint-dir")
    p.add_argument("--fault-inject", default=None, metavar="MODE:N",
                   help="elastic-recovery drill: crash:N exits 13 after "
                        "epoch N (post-snapshot), hang:N stops making "
                        "progress — pair with eventgrad_tpu.supervise")
    p.add_argument("--chaos", default=None, metavar="SPEC",
                   help="deterministic gossip fault injection (chaos/): "
                        "e.g. 'drop=0.2,seed=7,flaky=100-200@0.8,"
                        "delay=3,die=3@500,preempt=6@2' — per-edge drop "
                        "probability, flaky windows [start-end)@p, "
                        "k-pass delivery thinning, permanent peer "
                        "death, scheduled graceful preemption (drain + "
                        f"snapshot + exit {PREEMPTED_EXIT}); gossip "
                        "algos (dpsgd/eventgrad) only. Replayable: the "
                        "schedule is serialized into the first history "
                        "record")
    p.add_argument("--membership", default=None, metavar="SPEC",
                   help="elastic membership schedule (chaos/membership.py): "
                        "e.g. 'leave=1@3,join=1@5' — rank 1 leaves after "
                        "epoch 3, a newcomer joins at position 1 after "
                        "epoch 5, bootstrapping its full gossip state from "
                        "a neighbor's snapshot streamed through the async "
                        "checkpoint writer; every transition force-fires "
                        "the next exchange. Replayable: the schedule rides "
                        "the first history record. Single-process ring "
                        "gossip runs (dpsgd/eventgrad) only; join=/leave= "
                        "clauses inside --chaos are equivalent")
    p.add_argument("--integrity", default=None, metavar="SPEC",
                   help="integrity engine (chaos/integrity.py, docs/"
                        "chaos.md): 'on', 'off', or field=value clauses "
                        "(e.g. 'checksum=1,quarantine=1,sentinel=1,"
                        "rollback=1,max_rollbacks=1'). on = wire "
                        "checksums on every gossip payload (a failed "
                        "check is an event that did not fire), non-"
                        "finite quarantine (a NaN-producing rank skips "
                        "its update and suppresses its sends), and the "
                        "divergence sentinel with rollback-to-last-good "
                        "(restore every rank from the retained snapshot, "
                        "force-refresh all event buffers, harden, "
                        "replay). A trip beyond max_rollbacks exits "
                        f"{INTEGRITY_ABORT_EXIT} and the supervisor "
                        "gives up without a restart. off is bitwise-"
                        "identical to no flag")
    p.add_argument("--chaos-sync-after", type=int, default=0, metavar="N",
                   help="recovery: an edge silent N passes makes the "
                        "receiver request a forced full sync from that "
                        "peer (eventgrad + --chaos; use N > "
                        "--max-silence)")
    p.add_argument("--chaos-freeze-after", type=int, default=0, metavar="N",
                   help="recovery: an edge silent N passes leaves the "
                        "mix with renormalized weights until it speaks "
                        "again (requires --chaos)")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler (XPlane/TensorBoard) trace "
                        "of the training run into this directory")
    p.add_argument("--obs", choices=["off", "block", "epoch"],
                   default="off",
                   help="on-device telemetry (docs/OBSERVABILITY.md): "
                        "per-leaf fire/deferral counts, threshold and "
                        "drift trajectories, silence histograms, per-edge "
                        "wire bytes — accumulated in the train scan and "
                        "flushed to host once per jit-dispatch block "
                        "(zero per-step host syncs); block = summaries "
                        "ride block-end epoch records, epoch = every "
                        "epoch (pins --epochs-per-dispatch behavior to "
                        "1); off = bit-identical to a telemetry-free run")
    p.add_argument("--obs-dir", default=None, metavar="DIR",
                   help="export host observability artifacts into DIR at "
                        "exit: trace.json (Chrome-trace/Perfetto spans of "
                        "dispatch blocks, eval, checkpoint, telemetry "
                        "flushes — open in chrome://tracing) and "
                        "metrics.prom (Prometheus textfile gauges)")
    p.add_argument("--log-fsync", action="store_true",
                   help="fsync the --log-file after every record — "
                        "crash-safe JSONL artifacts")
    return p


def main(argv=None) -> int:
    from eventgrad_tpu.utils import compile_cache

    compile_cache.honor_cpu_pin()
    args = build_parser().parse_args(argv)
    topo = args.mesh  # argparse already applied parse_mesh (also to the default)

    if args.coordinator:
        if args.backend != "mesh":
            raise SystemExit("--coordinator requires --backend mesh")
        multihost.init(args.coordinator, args.num_processes, args.process_id)
    elif os.environ.get("EG_COORDINATOR"):
        # env-var twin of the flags (EG_COORDINATOR / EG_NUM_PROCESSES /
        # EG_PROCESS_ID) — lets launchers join a multi-process mesh
        # without threading argv through every wrapper (mpirun's
        # environment-propagation role). Same contract as the flag:
        # exactly --backend mesh (an "auto" that quietly fell back to
        # vmap would run N independent full-ring simulations), checked
        # BEFORE joining the distributed runtime.
        if args.backend != "mesh":
            raise SystemExit("EG_COORDINATOR requires --backend mesh")
        multihost.init_from_env()

    # enable() only after distributed init — resolving the backend would
    # otherwise initialize it and break jax.distributed.initialize's
    # ordering contract
    compile_cache.enable()

    primary = multihost.is_primary()
    logger = JsonlLogger(
        args.log_file if primary else None, echo=primary,
        fsync=args.log_fsync,
    )
    registry = None
    if args.obs != "off" or args.obs_dir:
        from eventgrad_tpu.obs import Registry

        # the registry wraps (not owns) the logger: every record gains
        # the obs_schema stamp; spans/gauges export at exit via --obs-dir
        registry = Registry(
            logger=logger,
            run_meta={"algo": args.algo, "model": args.model},
        )
    emit = registry.record if registry is not None else logger.log

    is_lm = args.model in LM_MODELS
    if args.dataset is None:
        args.dataset = "synthetic-lm" if is_lm else "mnist"
    if is_lm != (args.dataset == "synthetic-lm"):
        raise SystemExit(
            "--dataset synthetic-lm pairs with the transformer models "
            "(--model transformer*) and vice versa"
        )
    if is_lm and args.augment:
        raise SystemExit("--augment is an image transform; not for LM")
    if args.algo != "allreduce" and not topo.gossip_axes:
        raise SystemExit(
            f"--algo {args.algo} needs a gossip axis (dp) in --mesh; "
            f"{tuple(topo.axes)} has none (did you mean dp instead of ddp?)"
        )
    if args.wire_bf16:
        if args.wire and args.wire != "bf16":
            raise SystemExit(
                f"--wire-bf16 conflicts with --wire {args.wire}"
            )
        args.wire = "bf16"
    if args.wire and args.algo == "allreduce":
        raise SystemExit(
            "--wire applies to gossip exchanges; allreduce gradients "
            "keep full precision"
        )
    # registry-driven wire validation (parallel/policy.py): resolve the
    # trigger policy the run will use and consult its WireSpec —
    # sp_eventgrad's statically-sized top-k wire ACCEPTS compact as a
    # capacity-free no-op alias (the old algo-name guard wrongly
    # rejected it); dpsgd/allreduce have no trigger policy at all
    from eventgrad_tpu.parallel import policy as policy_lib

    cli_pol = None
    if args.algo in policy_lib.DEFAULT_FOR_ALGO or args.trigger_policy:
        try:
            cli_pol = policy_lib.resolve(args.trigger_policy, args.algo)
        except ValueError as e:
            raise SystemExit(str(e))
    if args.gossip_wire == "compact" and (
        cli_pol is None
        or "compact" not in cli_pol.wire_spec().gossip_wires
    ):
        raise SystemExit(
            "--gossip-wire compact rides the statically-sized wire of "
            "an event trigger policy (--algo eventgrad / sp_eventgrad); "
            f"--algo {args.algo} has no compactable payload"
        )
    if args.compact_frac is not None:
        if args.gossip_wire != "compact":
            raise SystemExit("--compact-frac requires --gossip-wire compact")
        if not (0.0 < args.compact_frac <= 1.0):
            raise SystemExit(
                f"--compact-frac must be in (0, 1], got {args.compact_frac}"
            )
        if (cli_pol is not None
                and not cli_pol.wire_spec().compact_needs_capacity):
            raise SystemExit(
                "--compact-frac sizes the capacity autotune; the "
                f"{cli_pol.name!r} policy's compact wire is capacity-"
                "free (its top-k lanes are already statically sized)"
            )
    if args.max_silence < 0:
        raise SystemExit(
            "--max-silence must be >= 0 (0 disables the bound; a "
            "negative value would be silently inert)"
        )
    if args.max_silence and args.algo not in ("eventgrad", "sp_eventgrad"):
        raise SystemExit("--max-silence applies to the event algorithms only")
    if args.staleness < 0:
        raise SystemExit(
            "--staleness must be >= 0 (0 = synchronous, 1 = one-pass-"
            "stale, D >= 2 = the bounded-async gossip engine)"
        )
    if args.staleness:
        if args.algo not in ("eventgrad", "sp_eventgrad"):
            raise SystemExit("--staleness applies to the event algorithms only")
        if args.trace_file:
            raise SystemExit(
                "--trace-file records the synchronous exchange; not "
                "available with --staleness"
            )
    membership = None
    if args.membership is not None:
        from eventgrad_tpu.chaos import MembershipSchedule

        try:
            membership = MembershipSchedule.parse(args.membership)
        except ValueError as e:
            raise SystemExit(f"--membership: {e}")

    def _membership_guards(flag: str):
        """The same guards whether the events arrived via --membership or
        a --chaos spec's join=/leave= clauses."""
        if args.algo not in ("dpsgd", "eventgrad"):
            raise SystemExit(
                f"{flag} rides the gossip exchange (dpsgd/eventgrad); "
                f"--algo {args.algo} has no ring to re-shape"
            )
        if args.trace_file:
            raise SystemExit(
                "--trace-file carries rank-shaped recv staleness; not "
                f"available with {flag}"
            )
        if args.pipeline == "on":
            raise SystemExit(
                f"--pipeline on cannot honor {flag} (transitions "
                "re-shape the state between blocks, which needs the "
                "serial schedule); use --pipeline auto or off"
            )

    if membership is not None:
        _membership_guards("--membership")
    chaos_sched = None
    chaos_policy = None
    if args.chaos is not None:
        from eventgrad_tpu.chaos import ChaosSchedule, RecoveryPolicy

        if args.algo not in ("dpsgd", "eventgrad"):
            raise SystemExit(
                "--chaos injects loss into the gossip exchange; "
                f"--algo {args.algo} has no maskable edges"
            )
        if args.fused:
            raise SystemExit(
                "--chaos is not combinable with --fused (the Pallas tail "
                "bakes in the uniform mix weight)"
            )
        try:
            chaos_sched = ChaosSchedule.parse(args.chaos)
        except ValueError as e:
            raise SystemExit(f"--chaos: {e}")
        if chaos_sched.membership:
            if membership is not None:
                raise SystemExit(
                    "membership events given both via --membership and "
                    "the --chaos spec's join=/leave= clauses; pass one"
                )
            _membership_guards("--chaos join=/leave=")
        if args.chaos_sync_after and args.algo != "eventgrad":
            raise SystemExit(
                "--chaos-sync-after rides the event fire decision; "
                "dpsgd already sends everything every pass — a dropped "
                "message there is final (use --chaos-freeze-after)"
            )
        if args.chaos_sync_after or args.chaos_freeze_after:
            try:
                chaos_policy = RecoveryPolicy(
                    sync_after=args.chaos_sync_after,
                    freeze_after=args.chaos_freeze_after,
                )
                chaos_policy.validate_against(args.max_silence)
            except ValueError as e:
                raise SystemExit(f"--chaos-sync-after/--chaos-freeze-after: {e}")
    elif args.chaos_sync_after or args.chaos_freeze_after:
        raise SystemExit(
            "--chaos-sync-after/--chaos-freeze-after need --chaos (use "
            "--chaos 'drop=0' for recovery monitoring without injected "
            "faults)"
        )
    if args.pipeline == "on" and args.fault_inject:
        raise SystemExit(
            "--pipeline on cannot honor --fault-inject (the fault must "
            "land at an exact post-snapshot epoch boundary, which needs "
            "the serial schedule); use --pipeline auto or off"
        )
    integrity_cfg = None
    if args.integrity is not None:
        from eventgrad_tpu.chaos import integrity as chaos_integrity

        try:
            integrity_cfg = chaos_integrity.resolve(args.integrity)
        except ValueError as e:
            raise SystemExit(f"--integrity: {e}")
    if integrity_cfg is not None:
        if (
            (integrity_cfg.checksum or integrity_cfg.quarantine)
            and args.algo != "eventgrad"
        ):
            raise SystemExit(
                "--integrity checksums/quarantine ride the event "
                f"exchange; --algo {args.algo} has none (pass "
                "'checksum=0,quarantine=0,...' for the sentinel alone)"
            )
        if args.fused:
            raise SystemExit(
                "--integrity is not combinable with --fused (the Pallas "
                "update tail bypasses the guarded update path)"
            )
        if args.pipeline == "on" and integrity_cfg.sentinel:
            raise SystemExit(
                "--pipeline on cannot honor the --integrity sentinel/"
                "rollback engine (the verdict on block B gates what "
                "block B+1 may dispatch); use --pipeline auto or off, "
                "or pass 'sentinel=0,rollback=0,...'"
            )
    if not is_lm and not args.model.startswith("resnet") and (
        args.num_classes != 10 or args.num_filters != 64
    ):
        raise SystemExit(
            "--num-classes/--num-filters apply to resnet models only "
            "(the reference's small CNNs have fixed heads)"
        )

    n_test = max(512, args.n_synth // 8)
    if is_lm:
        x, y = synthetic_lm_dataset(
            args.n_synth, args.seq_len, args.vocab, args.seed
        )
        xt, yt = synthetic_lm_dataset(
            n_test, args.seq_len, args.vocab, args.seed, split="test"
        )
    elif args.dataset == "synthetic-imagenet":
        # ImageNet-shaped scale stress (BASELINE's "ResNet-50 ImageNet on a
        # v4-256 2D torus" config): hermetic class-prototype images at
        # --image-size, --num-classes labels
        from eventgrad_tpu.data.datasets import synthetic_dataset

        shape = (args.image_size, args.image_size, 3)
        x, y = synthetic_dataset(
            args.n_synth, shape, num_classes=args.num_classes, seed=args.seed
        )
        xt, yt = synthetic_dataset(
            n_test, shape, num_classes=args.num_classes,
            seed=args.seed, split="test",
        )
    else:
        # --dataset synthetic means "hermetic stand-in even if real data
        # exists": drop data_dir so load_or_synthesize can't pick up on-disk
        # files.
        dataset = "mnist" if args.dataset == "synthetic" else args.dataset
        data_dir = None if args.dataset == "synthetic" else args.data_dir
        x, y = load_or_synthesize(dataset, data_dir, "train", args.n_synth, args.seed)
        xt, yt = load_or_synthesize(dataset, data_dir, "test", n_test, args.seed)

    # data parallelism degree = the data axes' extent: gossip ranks plus
    # any ddp allreduce subgroups split the batch; sp/tp/pp/ep ranks
    # replicate or chunk it instead
    n_data = topo.n_data_ranks
    hybrid = topo.is_hybrid
    batch = args.batch_size
    if args.global_batch:
        batch = max(1, args.global_batch // n_data)

    if is_lm:
        model = build_lm_model(args, topo)
    elif args.num_classes != 10 or args.num_filters != 64:
        model = MODEL_REGISTRY[args.model](  # resnet-only, validated above
            num_classes=args.num_classes, num_filters=args.num_filters
        )
    else:
        model = MODEL_REGISTRY[args.model]()
    if args.backend == "mesh":
        mesh = build_mesh(topo)
    elif args.backend == "auto":
        from eventgrad_tpu.parallel.spmd import resolve_backend

        mesh = resolve_backend("auto", topo)
    else:
        mesh = None

    event_cfg = EventConfig(
        adaptive=args.thres_mode == "adaptive",
        horizon=args.horizon,
        constant=args.constant,
        warmup_passes=args.warmup_passes,
        history=args.history,
        max_silence=args.max_silence,
    )
    import contextlib

    from eventgrad_tpu.utils import profiling

    scope = (
        profiling.trace(args.profile_dir) if args.profile_dir
        else contextlib.nullcontext()
    )
    hist = []
    try:
        try:
            with scope:
                state, hist = train(
                    model, topo, x, y,
                    algo=args.algo, epochs=args.epochs, batch_size=batch,
                    learning_rate=args.lr, momentum=args.momentum,
                    event_cfg=event_cfg, sparse_cfg=SparseConfig(args.topk_percent),
                    augment=args.augment, random_sampler=args.random_sampler,
                    sync_bn=args.sync_bn, mesh=mesh, seed=args.seed, x_test=xt, y_test=yt,
                    checkpoint_dir=args.checkpoint_dir, save_every=args.save_every,
                    resume=args.resume, trace_file=args.trace_file,
                    wire=args.wire, staleness=args.staleness,
                    gossip_wire=args.gossip_wire, compact_frac=args.compact_frac,
                    trigger_policy=args.trigger_policy,
                    fused_update=args.fused, fault_inject=args.fault_inject,
                    chaos=chaos_sched, chaos_policy=chaos_policy,
                    membership=membership, integrity=integrity_cfg,
                    obs=args.obs, registry=registry,
                    arena={"auto": None, "on": True, "off": False}[args.arena],
                    bucketed=args.bucketed or None,
                    carrier_resident=args.carrier_resident or None,
                    pipeline={
                        "auto": None, "on": True, "off": False
                    }[args.pipeline],
                    on_epoch=emit,  # records stream as epochs finish: live
                    # metrics for the user, a liveness signal for supervise.py
                )
        except GracefulPreemption as e:
            # the loop already drained the pipeline, joined the writer,
            # force-snapshotted at the block boundary, and left the
            # PREEMPTED marker: exit the reserved code so the supervisor
            # relaunches immediately without charging its restart budget
            if primary:
                emit({"preempted": True, **e.info})
            print(f"preempted: {e}", file=sys.stderr, flush=True)
            return PREEMPTED_EXIT
        except IntegrityEscalation as e:
            # the retained last-known-good state cannot outrun this
            # fault: exit the reserved code so the supervisor gives up
            # instead of replaying the same divergence
            if primary:
                emit({"integrity_abort": True, "reason": str(e)})
            print(f"integrity abort: {e}", file=sys.stderr, flush=True)
            return INTEGRITY_ABORT_EXIT

        if hybrid:
            # consensus averaging across sp/tp/pp/ep ranks would mix
            # differently-sharded parameters; report final train metrics
            # instead (hist can be empty when resuming from a
            # final-epoch snapshot)
            if primary:
                rec = {"final": True, "consensus_eval": False}
                if hist:
                    rec.update(
                        loss=hist[-1]["loss"], train_acc=hist[-1]["train_acc"]
                    )
                emit(rec)
        else:
            # allgathers are collective: every process participates...
            params_host = multihost.to_host(state.params)
            stats_host = multihost.to_host(state.batch_stats)
            if primary:  # ...but only the primary spends the eval + log
                cons = consensus_params(params_host)
                stats0 = rank0_slice(stats_host)
                final = evaluate(model, cons, stats0, xt, yt)
                emit({"final": True, **final})
    finally:
        # exporters land even on an exception path — a crashed run's
        # spans are exactly the ones worth reading — but they are
        # best-effort: an unwritable --obs-dir must neither mask the
        # real exception nor skip logger.close()
        try:
            if registry is not None and args.obs_dir and primary:
                # final-state gauges for the textfile collector: the
                # scrape answers "where did the run end up" without
                # parsing JSONL
                if hist:
                    last = hist[-1]
                    registry.gauge("epochs_completed", last["epoch"])
                    for k in (
                        "loss", "msgs_saved_pct", "test_accuracy",
                        "sent_bytes_per_step_per_chip",
                        "sent_bytes_wire_real_per_step_per_chip",
                    ):
                        if isinstance(last.get(k), (int, float)):
                            registry.gauge(f"last_{k}", last[k])
                os.makedirs(args.obs_dir, exist_ok=True)
                registry.write_chrome_trace(
                    os.path.join(args.obs_dir, "trace.json")
                )
                registry.write_prometheus(
                    os.path.join(args.obs_dir, "metrics.prom")
                )
        except OSError as e:
            import warnings

            warnings.warn(f"--obs-dir export failed: {e}", RuntimeWarning)
        finally:
            logger.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
