"""Kernel microbenchmarks (not part of the driver contract — run by hand).

Times the Pallas kernels against their XLA/jnp twins on the active device:

  * flash attention fwd and fwd+bwd vs materialized-score attention, over
    a sweep of sequence lengths;
  * the fused gossip-mix + momentum-SGD update vs the unfused tree-map
    chain, at the flagship ResNet parameter count.

Prints one JSON line per measurement: {"kernel", "config", "pallas_ms",
"xla_ms", "speedup"}.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp


def _time(fn, *args, iters=20):
    out = fn(*args)  # compile
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return 1000 * (time.perf_counter() - t0) / iters


def bench_attention():
    from eventgrad_tpu.ops import flash_attention, flash_attention_reference

    b, h, d = 4, 8, 64
    for t in (512, 1024, 2048, 4096):
        key = jax.random.PRNGKey(0)
        q, k, v = (
            jax.random.normal(jax.random.fold_in(key, i), (b, t, h, d), jnp.bfloat16)
            for i in range(3)
        )
        flash = jax.jit(lambda q, k, v: flash_attention(q, k, v, True))
        ref = jax.jit(lambda q, k, v: flash_attention_reference(q, k, v, True))
        ms_f, ms_r = _time(flash, q, k, v), _time(ref, q, k, v)
        print(json.dumps({
            "kernel": "flash_attention_fwd", "config": f"B{b}xT{t}xH{h}xD{d}",
            "pallas_ms": round(ms_f, 3), "xla_ms": round(ms_r, 3),
            "speedup": round(ms_r / ms_f, 2),
        }))

        lossf = jax.jit(jax.grad(lambda q: jnp.sum(flash_attention(q, k, v, True).astype(jnp.float32) ** 2)))
        lossr = jax.jit(jax.grad(lambda q: jnp.sum(flash_attention_reference(q, k, v, True).astype(jnp.float32) ** 2)))
        ms_f, ms_r = _time(lossf, q), _time(lossr, q)
        print(json.dumps({
            "kernel": "flash_attention_fwd_bwd", "config": f"B{b}xT{t}xH{h}xD{d}",
            "pallas_ms": round(ms_f, 3), "xla_ms": round(ms_r, 3),
            "speedup": round(ms_r / ms_f, 2),
        }))


def bench_fused_update():
    from eventgrad_tpu.ops import fused_mix_sgd, mix_sgd_reference

    n = 17_400_000  # flagship ResNet parameter count
    key = jax.random.PRNGKey(1)
    p, b_, g, t = (
        {"w": jax.random.normal(jax.random.fold_in(key, i), (n,))} for i in range(4)
    )
    fused = jax.jit(lambda p, b, g, t: fused_mix_sgd(p, b, g, t, 0.01, 0.9, 1 / 3))
    ref = jax.jit(lambda p, b, g, t: mix_sgd_reference(p, b, g, t, 0.01, 0.9, 1 / 3))
    ms_f, ms_r = _time(fused, p, b_, g, t), _time(ref, p, b_, g, t)
    print(json.dumps({
        "kernel": "fused_mix_sgd", "config": f"{n/1e6:.1f}M params",
        "pallas_ms": round(ms_f, 3), "xla_ms": round(ms_r, 3),
        "speedup": round(ms_r / ms_f, 2),
    }))


if __name__ == "__main__":
    print(json.dumps({"platform": jax.devices()[0].platform}))
    bench_attention()
    bench_fused_update()
