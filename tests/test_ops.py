"""Pallas fused mix+SGD kernel == unfused reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np

from eventgrad_tpu.ops import fused_mix_sgd, mix_sgd_reference


def _trees(key):
    ks = jax.random.split(key, 4)
    shapes = {"w": (33, 47), "b": (129,), "conv": (3, 3, 8, 16)}
    mk = lambda k: {
        name: jax.random.normal(jax.random.fold_in(k, i), s)
        for i, (name, s) in enumerate(shapes.items())
    }
    return mk(ks[0]), mk(ks[1]), mk(ks[2]), mk(ks[3])


def test_fused_matches_reference():
    p, b, g, t = _trees(jax.random.PRNGKey(0))
    lr, mom, w = 0.05, 0.9, 1 / 3
    fp, ft = fused_mix_sgd(p, b, g, t, lr, mom, w, interpret=True)
    rp, rt = mix_sgd_reference(p, b, g, t, lr, mom, w)
    for a, c in zip(jax.tree.leaves(fp), jax.tree.leaves(rp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c), atol=1e-6)
    for a, c in zip(jax.tree.leaves(ft), jax.tree.leaves(rt)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c), atol=1e-6)


def test_fused_zero_momentum_plain_sgd():
    p, b, g, t = _trees(jax.random.PRNGKey(1))
    t = jax.tree.map(jnp.zeros_like, t)
    fp, ft = fused_mix_sgd(p, b, g, t, 0.1, 0.0, 1.0, interpret=True)
    for name in p:
        expect = p[name] + b[name] - 0.1 * g[name]
        np.testing.assert_allclose(np.asarray(fp[name]), np.asarray(expect), atol=1e-6)
        np.testing.assert_allclose(np.asarray(ft[name]), np.asarray(g[name]))


def test_fused_handles_tiny_and_odd_sizes():
    p = {"s": jnp.array([1.0, 2.0, 3.0])}  # far below one tile
    z = {"s": jnp.zeros(3)}
    fp, _ = fused_mix_sgd(p, z, z, z, 0.0, 0.0, 1.0, interpret=True)
    np.testing.assert_allclose(np.asarray(fp["s"]), [1.0, 2.0, 3.0])


def test_fused_partial_trailing_block():
    """A lane-divisible leaf whose row count does NOT divide the block:
    the pad-free path must mask the out-of-bounds stores of the partial
    trailing block (fused_update.py layout contract)."""
    n = 128 * (512 + 100)  # rows=612 -> blocks (512, partial 100)
    key = jax.random.PRNGKey(7)
    p, b, g, t = (
        {"w": jax.random.normal(jax.random.fold_in(key, i), (n,))}
        for i in range(4)
    )
    fp, ft = fused_mix_sgd(p, b, g, t, 0.01, 0.9, 1 / 3, interpret=True)
    rp, rt = mix_sgd_reference(p, b, g, t, 0.01, 0.9, 1 / 3)
    np.testing.assert_allclose(np.asarray(fp["w"]), np.asarray(rp["w"]),
                               rtol=0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ft["w"]), np.asarray(rt["w"]),
                               rtol=0, atol=1e-6)


def test_fused_train_loop_matches_unfused():
    """train(fused_update=True) follows the optax trajectory exactly."""
    from eventgrad_tpu.data.datasets import synthetic_dataset
    from eventgrad_tpu.models import MLP
    from eventgrad_tpu.parallel.events import EventConfig
    from eventgrad_tpu.parallel.topology import Ring
    from eventgrad_tpu.train.loop import train

    x, y = synthetic_dataset(256, (28, 28, 1), seed=6)
    kwargs = dict(
        algo="eventgrad", epochs=2, batch_size=8, learning_rate=0.05,
        momentum=0.9, event_cfg=EventConfig(adaptive=True, warmup_passes=3),
        seed=1, log_every_epoch=False,
    )
    s_fused, _ = train(MLP(), Ring(4), x, y, fused_update=True, **kwargs)
    s_plain, _ = train(MLP(), Ring(4), x, y, **kwargs)
    for a, b in zip(jax.tree.leaves(s_fused.params), jax.tree.leaves(s_plain.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_fused_step_matches_unfused_trajectory():
    """A full EventGraD step with fused_sgd must equal the optax path."""
    import optax

    from eventgrad_tpu.data.datasets import synthetic_dataset
    from eventgrad_tpu.data.sharding import batched_epoch
    from eventgrad_tpu.models import MLP
    from eventgrad_tpu.parallel.events import EventConfig
    from eventgrad_tpu.parallel.spmd import spmd
    from eventgrad_tpu.parallel.topology import Ring
    from eventgrad_tpu.train.state import init_train_state
    from eventgrad_tpu.train.steps import make_train_step

    topo = Ring(4)
    model = MLP(hidden=16)
    lr, mom = 0.05, 0.9
    tx = optax.sgd(lr, momentum=mom)
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=2)
    x, y = synthetic_dataset(4 * 8 * 4, (8, 8, 1), seed=11)
    xb, yb = batched_epoch(x, y, 4, 8)

    results = []
    for fused in (None, (lr, mom)):
        state = init_train_state(model, (8, 8, 1), tx, topo, "eventgrad", cfg)
        step = make_train_step(model, tx, topo, "eventgrad", event_cfg=cfg,
                               fused_sgd=fused)
        lifted = jax.jit(spmd(step, topo))
        for s in range(xb.shape[1]):
            state, _ = lifted(state, (jnp.asarray(xb[:, s]), jnp.asarray(yb[:, s])))
        results.append(state)

    for a, b in zip(jax.tree.leaves(results[0].params),
                    jax.tree.leaves(results[1].params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    for a, b in zip(jax.tree.leaves(results[0].opt_state),
                    jax.tree.leaves(results[1].opt_state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
