"""Collective primitives for decentralized training, on named axes.

The reference uses three MPI paradigms; each maps to one function here:

  * `MPI_Allreduce` of gradients (/root/reference/dmnist/cent/cent.cpp:135-142)
     -> `allreduce_mean`  (jax.lax.pmean, XLA all-reduce over ICI)
  * two-sided ring sends `MPI_Issend`/`MPI_Recv`
    (/root/reference/dmnist/decent/decent.cpp:192-208)
     -> `neighbor_vals` (jax.lax.ppermute ring shift)
  * one-sided event-triggered `MPI_Put` into an RMA window
    (/root/reference/dmnist/event/event.cpp:346-360)
     -> `masked_neighbor_vals`: ppermute of (fire-bit, zero-masked payload);
        the receiver keeps its previous buffer when the bit is off. This is
        the SPMD-legal form of "maybe send": the collective always runs, the
        *bytes that matter* are counted by the metrics layer, and true wire
        savings materialize via sparsification (sparsify.py) or DCN paths.

All functions operate on pytrees and work identically under `jax.shard_map`
(real mesh) and `jax.vmap(axis_name=...)` (single-chip simulation).
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.flatten_util import ravel_pytree

from eventgrad_tpu.parallel.topology import NeighborSpec, Topology


def allreduce_mean(tree: Any, topo: Topology) -> Any:
    """Mean over every rank in the topology (all axes)."""
    for axis in topo.axes:
        tree = lax.pmean(tree, axis)
    return tree


def allreduce_sum(tree: Any, topo: Topology) -> Any:
    for axis in topo.axes:
        tree = lax.psum(tree, axis)
    return tree


def recv_from(tree: Any, topo: Topology, nb: NeighborSpec) -> Any:
    """Each rank receives the pytree held by the rank `nb.offset` away along
    `nb.axis` (offset -1 == "from my left neighbor"). One ppermute per leaf."""
    n = topo.axis_size(nb.axis)
    perm = [((r + nb.offset) % n, r) for r in range(n)]
    return jax.tree.map(lambda x: lax.ppermute(x, nb.axis, perm), tree)


def _packable(tree: Any) -> bool:
    """One contiguous wire buffer needs a single dtype across leaves."""
    leaves = jax.tree.leaves(tree)
    return len(leaves) > 1 and all(l.dtype == leaves[0].dtype for l in leaves)


#: wire modes: None = native dtype; "bf16" = bfloat16 transfer (2 B/elem);
#: "int8" = per-leaf absmax-scaled int8 transfer (1 B/elem + one f32
#: scale per leaf). Local state always stays full precision.
WIRE_MODES = (None, "bf16", "int8")


def _wire_out(x: Any, wire) -> Any:
    """Downcast a wire payload (array or pytree of floats) for transfer
    (bf16 mode; int8 has its own quantize/dequantize pair below)."""
    dt = jnp.bfloat16 if wire == "bf16" else None
    cast = lambda a: (
        a.astype(dt)
        if dt is not None and jnp.issubdtype(a.dtype, jnp.floating)
        and a.dtype != dt
        else a
    )
    return jax.tree.map(cast, x)


def _wire_in(x: Any, like: Any) -> Any:
    """Upcast received payload back to the local dtypes."""
    return jax.tree.map(lambda a, ref: a.astype(ref.dtype), x, like)


def _int8_scales(tree: Any) -> Any:
    """Per-leaf absmax/127 quantization scales (zero-safe)."""
    return jax.tree.map(
        lambda a: jnp.maximum(jnp.max(jnp.abs(a)), 1e-30) / 127.0, tree
    )


def _int8_quant(tree: Any, scales: Any) -> Any:
    return jax.tree.map(
        lambda a, s: jnp.clip(jnp.round(a / s), -127, 127).astype(jnp.int8),
        tree, scales,
    )


def _int8_dequant(q: Any, scales: Any, like: Any) -> Any:
    return jax.tree.map(
        lambda v, s, ref: (v.astype(ref.dtype) * s.astype(ref.dtype)),
        q, scales, like,
    )


def _int8_encode(tree: Any):
    """Quantize a float pytree for the wire: (int8 tree, stacked per-leaf
    scale vector, the scales' treedef for decode). One codec shared by the
    dense, masked, and sparse exchange paths."""
    scales = _int8_scales(tree)
    q = _int8_quant(tree, scales)
    return q, jnp.stack(jax.tree.leaves(scales)), jax.tree.structure(scales)


def _int8_decode(got_q: Any, got_s: Any, scale_def, like: Any) -> Any:
    got_scales = jax.tree.unflatten(
        scale_def, [got_s[i] for i in range(got_s.shape[0])]
    )
    return _int8_dequant(got_q, got_scales, like)


def _recv_packed(tree: Any, topo: Topology, nb: NeighborSpec, wire=None) -> Any:
    """recv_from through one contiguous buffer: a model is one ICI transfer
    per neighbor, not one per parameter tensor. The reference pays the
    per-tensor cost (86 x 2 MPI_Puts per step on its ResNet,
    dcifar10/event/event.cpp:282,320-332); packing amortizes every
    per-message overhead and gives the ICI DMA one large contiguous op.
    `wire` ("bf16"/"int8") compresses the buffer for the transfer and
    restores full precision on receipt — 2x/4x fewer ICI/DCN bytes for
    float32 models."""
    if wire == "int8":
        q, scale_vec, scale_def = _int8_encode(tree)
        if _packable(q):
            flatq, unravel_q = ravel_pytree(q)
            got_q, got_s = recv_from((flatq, scale_vec), topo, nb)
            got_tree = unravel_q(got_q)
        else:
            got_tree, got_s = recv_from((q, scale_vec), topo, nb)
        return _int8_decode(got_tree, got_s, scale_def, tree)
    if not _packable(tree):
        got = recv_from(_wire_out(tree, wire), topo, nb)
        return _wire_in(got, tree)
    flat, unravel = ravel_pytree(tree)
    got = recv_from(_wire_out(flat, wire), topo, nb)
    return unravel(got.astype(flat.dtype))


def neighbor_vals(tree: Any, topo: Topology, wire=None) -> Tuple[Any, ...]:
    """D-PSGD exchange: the full pytree from every gossip neighbor.

    Ring: returns (from_left, from_right) — the payloads of
    decent.cpp:200-205's two blocking receives, with no lockstep deadlock
    risk because ppermute is a collective. Packed: one wire buffer per
    neighbor regardless of how many parameter tensors the model has.
    """
    return tuple(
        _recv_packed(tree, topo, nb, wire) for nb in topo.neighbors
    )


def masked_neighbor_vals(
    payload: Any,
    fire: Any,
    last_bufs: Tuple[Any, ...],
    topo: Topology,
    wire=None,
    deliver: "Optional[Any]" = None,
) -> Tuple[Tuple[Any, ...], Tuple[Any, ...]]:
    """Event-triggered exchange (EventGraD's RMA window, deterministic form).

    `payload` — pytree of parameters; `fire` — matching pytree of boolean
    scalars (per-parameter event bits, event.cpp:343); `last_bufs` — one
    pytree per neighbor holding the last received values (the local RMA
    window halves, event.cpp:169-179).

    Returns (new_bufs, recv_fires). For every neighbor:
      new_buf_i = where(neighbor_fired_i, neighbor_payload_i, last_buf_i)
    Non-fired payloads are zero-masked before the shift so the wire content
    is well-defined (and compressible); receivers never read torn data,
    unlike the reference's MPI_LOCK_SHARED races (event.cpp:348-360 vs
    :399-438) — staleness is explicit carried state instead.

    `deliver` (chaos.inject): optional bool [n_neighbors] of per-edge
    delivered bits — a False edge keeps its stale buffer even when the
    sender fired, making an injected message drop bitwise-identical to an
    event that did not fire. `recv_fires` stays the RAW sender bits
    (what was on the wire), so callers can count injected drops as
    `sent & ~delivered`.
    """
    masked = jax.tree.map(
        lambda p, f: jnp.where(f, p, jnp.zeros_like(p)), payload, fire
    )
    fire_leaves, fire_def = jax.tree.flatten(fire)
    fire_vec = jnp.stack(fire_leaves)

    def _unflat_fire(got_vec):
        return jax.tree.unflatten(
            fire_def, [got_vec[i] for i in range(len(fire_leaves))]
        )

    if wire == "int8":
        # quantized wire: int8 payload + one f32 scale per leaf (non-fired
        # leaves are all-zero, so their scale bottoms out and decodes to 0)
        q, scale_vec, scale_def = _int8_encode(masked)
        flatq, unravel_q = ravel_pytree(q) if _packable(q) else (None, None)

        def receive(nb):
            if flatq is not None:
                got_q, got_s, got_vec = recv_from(
                    (flatq, scale_vec, fire_vec), topo, nb
                )
                got_tree = unravel_q(got_q)
            else:
                got_tree, got_s, got_vec = recv_from(
                    (q, scale_vec, fire_vec), topo, nb
                )
            return _int8_decode(got_tree, got_s, scale_def, masked), (
                _unflat_fire(got_vec)
            )
    elif _packable(masked):
        # one wire buffer (+ one fire-bit vector) per neighbor: the whole
        # model rides a single ICI transfer instead of one per tensor
        packed, unravel = ravel_pytree(masked)
        wire_buf = _wire_out(packed, wire)

        def receive(nb):
            got_flat, got_vec = recv_from((wire_buf, fire_vec), topo, nb)
            return unravel(got_flat.astype(packed.dtype)), _unflat_fire(got_vec)
    else:

        def receive(nb):
            got_p, got_f = recv_from(
                (_wire_out(masked, wire), fire), topo, nb
            )
            return _wire_in(got_p, masked), got_f

    new_bufs, recv_fires = [], []
    for i, (nb, last) in enumerate(zip(topo.neighbors, last_bufs)):
        got_p, got_f = receive(nb)
        eff_f = got_f
        if deliver is not None:
            eff_f = jax.tree.map(
                lambda f, _d=deliver[i]: jnp.logical_and(f, _d), got_f
            )
        buf = jax.tree.map(
            lambda f, new, old: jnp.where(f, new, old), eff_f, got_p, last
        )
        new_bufs.append(buf)
        recv_fires.append(got_f)
    return tuple(new_bufs), tuple(recv_fires)


def mix(params: Any, bufs: Tuple[Any, ...], topo: Topology) -> Any:
    """Uniform gossip averaging with neighbor buffers:
    p <- (p + sum(bufs)) / (1 + n_neighbors)   (event.cpp:469-471: /3 on a
    ring; /5 on a 2D torus). Stale or zero-initialized buffers participate
    exactly as in the reference (event.cpp:177-179)."""
    w = topo.mix_weight
    acc = params
    for buf in bufs:
        acc = jax.tree.map(jnp.add, acc, buf)
    return jax.tree.map(lambda x: x * w, acc)


def mix_weighted(params: Any, bufs: Tuple[Any, ...], gate: Any) -> Any:
    """Gossip averaging over a data-dependent subset of edges:
    p <- (p + sum(gate_i * buf_i)) / (1 + sum(gate_i)).

    `gate` is bool [n_neighbors] (chaos.policy.alive_mask and the lossy
    D-PSGD path): a gated-off edge leaves the mix entirely and the weight
    renormalizes over the survivors, instead of averaging in a frozen
    buffer forever. With every gate on this reproduces `mix` bitwise:
    where(True, b, 0) == b, the adds run in the same order, and the f32
    reciprocal of a small integer equals the cast Python double (both
    correctly rounded to the same float32)."""
    acc = params
    for i, buf in enumerate(bufs):
        acc = jax.tree.map(
            lambda x, b, _g=gate[i]: x + jnp.where(_g, b, jnp.zeros_like(b)),
            acc, buf,
        )
    n_alive = jnp.sum(gate.astype(jnp.float32))
    w = 1.0 / (1.0 + n_alive)
    return jax.tree.map(lambda x: x * w, acc)
