"""Checkpoint/resume: an interrupted run continues to the exact same state.

The reference has no persistence at all (SURVEY §5); here the whole gossip
TrainState (params, SGD momenta, event thresholds/slopes, stale neighbor
buffers, PRNG keys, pass counter) round-trips through orbax, so a run
killed mid-training and resumed is bit-identical to one that never stopped.
"""

import jax
import jax.numpy as jnp
import numpy as np

from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.loop import train


def _run(tmp, *, epochs, resume, save_every=2):
    x, y = synthetic_dataset(256, (28, 28, 1), seed=4)
    model = MLP()
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=3)
    return train(
        model, Ring(4), x, y,
        algo="eventgrad", epochs=epochs, batch_size=16, learning_rate=0.05,
        event_cfg=cfg, random_sampler=True, seed=7,
        checkpoint_dir=str(tmp) if tmp else None,
        save_every=save_every, resume=resume,
    )


def test_interrupt_and_resume_matches_uninterrupted(tmp_path):
    # uninterrupted 4-epoch run
    state_full, hist_full = _run(None, epochs=4, resume=False)

    # "crash" after epoch 2 (checkpoint lands there), then resume to 4
    ck = tmp_path / "ck"
    _run(ck, epochs=2, resume=False)
    state_res, hist_res = _run(ck, epochs=4, resume=True)

    assert [h["epoch"] for h in hist_res] == [3, 4]
    for a, b in zip(jax.tree.leaves(state_full.params), jax.tree.leaves(state_res.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    # event state resumed too, not reset
    np.testing.assert_array_equal(
        np.asarray(state_res.event.num_events), np.asarray(state_full.event.num_events)
    )
    np.testing.assert_allclose(
        np.asarray(state_res.pass_num), np.asarray(state_full.pass_num)
    )


def test_resume_without_checkpoint_starts_fresh(tmp_path):
    state, hist = _run(tmp_path / "none", epochs=2, resume=True)
    assert [h["epoch"] for h in hist] == [1, 2]


def test_interrupted_save_falls_back_to_prev(tmp_path):
    """A kill mid-snapshot-swap leaves ckpt.prev; resume must find it."""
    import os
    import shutil

    from eventgrad_tpu.utils import checkpoint

    ck = tmp_path / "ck"
    _run(ck, epochs=2, resume=False)
    path = os.path.join(str(ck), "ckpt")
    # simulate dying after the old snapshot moved aside but before promotion
    os.rename(path, path + ".prev")
    assert checkpoint.latest(path) == os.path.abspath(path) + ".prev"

    state_res, hist_res = _run(ck, epochs=4, resume=True)
    assert [h["epoch"] for h in hist_res] == [3, 4]


def test_corrupt_primary_resume_recovers_from_prev_loudly(tmp_path):
    """peek/load .prev auto-fallback (ISSUE 8 satellite): a TRUNCATED
    primary snapshot with a complete demoted twin resumes from the twin
    with a loud RuntimeWarning instead of failing the service; with the
    twin also corrupt, the resume fails loudly naming both paths."""
    import os
    import shutil

    import pytest

    from eventgrad_tpu.utils import checkpoint

    def corrupt(tree):
        # the promoted name pointing at zero-length files (a torn write)
        for dirpath, _, files in os.walk(tree):
            for f in files:
                open(os.path.join(dirpath, f), "w").close()

    state_full, _ = _run(None, epochs=4, resume=False)
    ck = tmp_path / "ck"
    _run(ck, epochs=2, resume=False)
    path = os.path.join(str(ck), "ckpt")
    # a complete twin of the epoch-2 snapshot, then a torn primary
    shutil.copytree(path, path + ".prev")
    corrupt(path)

    # both-corrupt leg first (the successful recovery below overwrites
    # the scenario when its epoch-4 save prunes the .prev)
    ck2 = tmp_path / "ck2"
    shutil.copytree(str(ck), str(ck2))
    corrupt(os.path.join(str(ck2), "ckpt.prev"))
    with pytest.raises(RuntimeError, match="both unreadable"):
        _run(ck2, epochs=4, resume=True)

    with pytest.warns(RuntimeWarning, match="RECOVERED"):
        state_res, hist_res = _run(ck, epochs=4, resume=True)
    assert [h["epoch"] for h in hist_res] == [3, 4]
    for a, b in zip(
        jax.tree.leaves(state_full.params), jax.tree.leaves(state_res.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_hybrid_lm_resume_matches_uninterrupted(tmp_path):
    """Hybrid meshes persist too: an EventGraD dp x sp ring-attention LM run
    interrupted at epoch 2 and resumed matches the straight 4-epoch run."""
    from eventgrad_tpu.data.datasets import synthetic_lm_dataset
    from eventgrad_tpu.models.transformer import TransformerLM
    from eventgrad_tpu.parallel.topology import Topology

    topo = Topology(axes=("dp", "sp"), shape=(2, 2), gossip_axes=("dp",))
    x, y = synthetic_lm_dataset(64, 32, vocab=64, seed=2)
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=3)

    def go(ck, *, epochs, resume):
        model = TransformerLM(vocab=64, dim=32, n_heads=4, n_layers=1,
                              max_len=32, attn="ring", topo=topo, sp_axis="sp")
        return train(
            model, topo, x, y,
            algo="eventgrad", epochs=epochs, batch_size=4, learning_rate=0.1,
            event_cfg=cfg, random_sampler=True, seed=5,
            checkpoint_dir=str(ck) if ck else None, save_every=2,
            resume=resume, log_every_epoch=False,
        )

    state_full, _ = go(None, epochs=4, resume=False)
    ck = tmp_path / "ck"
    go(ck, epochs=2, resume=False)
    state_res, hist = go(ck, epochs=4, resume=True)

    assert [h["epoch"] for h in hist] == [3, 4]
    for a, b in zip(
        jax.tree.leaves(state_full.params), jax.tree.leaves(state_res.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    np.testing.assert_array_equal(
        np.asarray(state_full.event.num_events),
        np.asarray(state_res.event.num_events),
    )


def test_resume_across_carrier_residency_fails_loudly(tmp_path):
    """The resident dtype of the receive buffers is checkpoint layout,
    like the bounded-async depth: resuming across a different residency
    fails LOUDLY in BOTH directions. The bf16-carrier <-> f32 pair is
    the dangerous one — identical pytree structure and shapes, so
    without the guard the restore would silently CAST the buffers
    instead of failing."""
    import pytest

    x, y = synthetic_dataset(64, (8, 8, 1), seed=3)
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=2)
    common = dict(
        algo="eventgrad", epochs=1, batch_size=4, event_cfg=cfg, seed=0,
        log_every_epoch=False, save_every=1, arena=True,
    )

    def go(ck, **kw):
        return train(MLP(hidden=16), Ring(4), x, y, checkpoint_dir=ck,
                     **{**common, **kw})

    d1 = str(tmp_path / "car_int8")
    go(d1, wire="int8", carrier_resident=True)
    # carrier snapshot -> f32-resident resume (scales would be orphaned)
    with pytest.raises(RuntimeError, match="carrier"):
        go(d1, wire="int8", resume=True, epochs=2)
    # carrier-int8 snapshot -> carrier-bf16 resume (dtype mismatch)
    with pytest.raises(RuntimeError, match="carrier"):
        go(d1, wire="bf16", carrier_resident=True, resume=True, epochs=2)

    d2 = str(tmp_path / "f32_resident")
    go(d2, wire="int8")
    # f32-resident snapshot -> carrier resume (the grow direction)
    with pytest.raises(RuntimeError, match="carrier"):
        go(d2, wire="int8", carrier_resident=True, resume=True, epochs=2)

    d3 = str(tmp_path / "car_bf16")
    go(d3, wire="bf16", carrier_resident=True)
    # bf16-carrier snapshot -> f32 resume: structurally LEGAL (same
    # pytree/shapes), so this is exactly the silent-cast hazard
    with pytest.raises(RuntimeError, match="carrier"):
        go(d3, wire="bf16", resume=True, epochs=2)

    # same-layout resumes round-trip on both carrier dtypes
    _, h1 = go(d1, wire="int8", carrier_resident=True, resume=True,
               epochs=2)
    assert [r["epoch"] for r in h1] == [2]
    _, h3 = go(d3, wire="bf16", carrier_resident=True, resume=True,
               epochs=2)
    assert [r["epoch"] for r in h3] == [2]


def test_resume_across_composed_queue_layout_fails_loudly(tmp_path):
    """The COMPOSED overlap stack (bounded-async D=4 x bucketed K=4 x
    compact int8 x carrier-resident) carries its delivery queues
    per-bucket inside EventState.pending — BOTH the depth D and the
    bucket count K are checkpoint layout now. Resuming a composed
    snapshot into a lockstep or monolithic loop (or vice versa) fails
    LOUDLY with an actionable message in every direction; the
    same-layout resume round-trips."""
    import pytest

    x, y = synthetic_dataset(64, (8, 8, 1), seed=3)
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=2)
    composed = dict(
        staleness=4, bucketed=4, gossip_wire="compact", compact_frac=0.5,
        wire="int8", carrier_resident=True,
    )
    common = dict(
        algo="eventgrad", epochs=1, batch_size=4, event_cfg=cfg, seed=0,
        log_every_epoch=False, save_every=1, arena=True,
    )

    def go(ck, **kw):
        return train(MLP(hidden=16), Ring(4), x, y, checkpoint_dir=ck,
                     **{**common, **kw})

    d1 = str(tmp_path / "composed")
    go(d1, **composed)
    # composed snapshot -> lockstep loop (queues would be dropped)
    with pytest.raises(RuntimeError, match="staleness"):
        go(d1, **{**composed, "staleness": 1}, resume=True, epochs=2)
    # composed snapshot -> monolithic loop (per-bucket slots would be
    # misread as flat buffers)
    with pytest.raises(RuntimeError, match="bucketed"):
        go(d1, **{**composed, "bucketed": None}, resume=True, epochs=2)

    # ...and the grow direction: a lockstep/monolithic snapshot must
    # refuse the composed loop
    d2 = str(tmp_path / "mono")
    go(d2, **{**composed, "staleness": 0, "bucketed": None})
    with pytest.raises(RuntimeError, match="staleness"):
        go(d2, **composed, resume=True, epochs=2)
    d3 = str(tmp_path / "b_only")
    go(d3, **{**composed, "staleness": 0})
    with pytest.raises(RuntimeError, match="staleness"):
        go(d3, **composed, resume=True, epochs=2)

    # same composed layout round-trips
    _, h = go(d1, **composed, resume=True, epochs=2)
    assert [r["epoch"] for r in h] == [2]


def test_delayed_gossip_resume_matches_uninterrupted(tmp_path):
    """staleness=1 carries its pending exchange in EventState.bufs, which is
    part of the snapshot — an interrupted delayed-gossip run resumes onto
    the exact uninterrupted trajectory."""
    x, y = synthetic_dataset(256, (28, 28, 1), seed=4)
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=3)
    kw = dict(
        algo="eventgrad", batch_size=16, learning_rate=0.05, event_cfg=cfg,
        random_sampler=True, seed=7, staleness=1, save_every=2,
    )
    state_full, _ = train(MLP(), Ring(4), x, y, epochs=4, resume=False, **kw)
    ck = str(tmp_path / "ck")
    train(MLP(), Ring(4), x, y, epochs=2, resume=False, checkpoint_dir=ck, **kw)
    state_res, hist = train(MLP(), Ring(4), x, y, epochs=4, resume=True,
                            checkpoint_dir=ck, **kw)
    assert [h["epoch"] for h in hist] == [3, 4]
    for a, b in zip(
        jax.tree.leaves(state_full.params), jax.tree.leaves(state_res.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
