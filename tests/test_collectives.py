"""Collective semantics on both lifting paths (vmap sim and shard_map mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from _spmd import requires_shard_map
from eventgrad_tpu.parallel import collectives
from eventgrad_tpu.parallel.spmd import build_mesh, spmd
from eventgrad_tpu.parallel.topology import Ring, Torus


def _lift(fn, topo, backend):
    if backend == "vmap":
        return spmd(fn, topo)
    return spmd(fn, topo, mesh=build_mesh(topo))


BACKENDS = ["vmap", pytest.param("shard_map", marks=requires_shard_map)]


@pytest.mark.parametrize("backend", BACKENDS)
def test_recv_from_ring_shift(backend):
    topo = Ring(4)

    def fn(x):
        left = collectives.recv_from(x, topo, topo.neighbors[0])
        right = collectives.recv_from(x, topo, topo.neighbors[1])
        return left, right

    x = jnp.arange(4.0)
    left, right = _lift(fn, topo, backend)(x)
    # rank r receives rank r-1's value from the left, r+1's from the right
    np.testing.assert_allclose(left, [3.0, 0.0, 1.0, 2.0])
    np.testing.assert_allclose(right, [1.0, 2.0, 3.0, 0.0])


@pytest.mark.parametrize("backend", BACKENDS)
def test_allreduce_mean_matches_numpy(backend):
    topo = Ring(8)
    x = jnp.arange(8.0) * 2.0

    def fn(x):
        return collectives.allreduce_mean(x, topo)

    out = _lift(fn, topo, backend)(x)
    np.testing.assert_allclose(out, np.full(8, np.arange(8.0).mean() * 2.0))


@pytest.mark.parametrize("backend", BACKENDS)
def test_dpsgd_mixing_on_ring(backend):
    """p <- (p + left + right)/3, per decent.cpp:232-234."""
    topo = Ring(4)

    def fn(p):
        bufs = collectives.neighbor_vals(p, topo)
        return collectives.mix(p, bufs, topo)

    p = jnp.array([0.0, 3.0, 6.0, 9.0])
    out = _lift(fn, topo, backend)(p)
    expect = [(0 + 9 + 3) / 3, (3 + 0 + 6) / 3, (6 + 3 + 9) / 3, (9 + 6 + 0) / 3]
    np.testing.assert_allclose(out, expect)


@pytest.mark.parametrize("backend", BACKENDS)
def test_torus_four_neighbor_mix(backend):
    topo = Torus(4, 2)

    def fn(p):
        bufs = collectives.neighbor_vals(p, topo)
        return collectives.mix(p, bufs, topo)

    p = jnp.arange(8.0)
    out = _lift(fn, topo, backend)(p)

    grid = np.arange(8.0).reshape(4, 2)
    expect = np.zeros_like(grid)
    for i in range(4):
        for j in range(2):
            vals = [
                grid[i, j],
                grid[(i - 1) % 4, j],
                grid[(i + 1) % 4, j],
                grid[i, (j - 1) % 2],
                grid[i, (j + 1) % 2],
            ]
            expect[i, j] = sum(vals) / 5
    np.testing.assert_allclose(out, expect.reshape(-1), rtol=1e-6)


@pytest.mark.parametrize("backend", BACKENDS)
def test_masked_exchange_keeps_stale_buffer(backend):
    topo = Ring(4)

    def fn(p, fire, last):
        bufs, fires = collectives.masked_neighbor_vals(p, fire, (last, last), topo)
        return bufs

    p = jnp.array([1.0, 2.0, 3.0, 4.0])
    # only ranks 0 and 2 fire
    fire = jnp.array([True, False, True, False])
    last = jnp.full(4, -7.0)
    left_buf, right_buf = _lift(fn, topo, backend)(p, fire, last)
    # from the left: rank r sees rank r-1's payload iff r-1 fired, else stale
    np.testing.assert_allclose(left_buf, [-7.0, 1.0, -7.0, 3.0])
    # from the right: rank r sees rank r+1's payload iff r+1 fired, else stale
    np.testing.assert_allclose(right_buf, [-7.0, 3.0, -7.0, 1.0])


def test_pytree_exchange_vmap():
    topo = Ring(4)
    tree = {"a": jnp.arange(4.0), "b": jnp.arange(8.0).reshape(4, 2)}

    def fn(t):
        return collectives.neighbor_vals(t, topo)

    left, right = spmd(fn, topo)(tree)
    np.testing.assert_allclose(left["a"], [3.0, 0.0, 1.0, 2.0])
    np.testing.assert_allclose(right["b"][0], [2.0, 3.0])


@pytest.mark.parametrize("backend", BACKENDS)
def test_masked_exchange_packed_multileaf(backend):
    """Multi-leaf trees take the packed wire path (one buffer + one
    fire-bit vector per neighbor); fire bits and values must land on the
    right leaves in the right leaf order, stale values preserved per leaf."""
    topo = Ring(4)

    def fn(p, fire, last):
        bufs, fires = collectives.masked_neighbor_vals(
            p, fire, (last, last), topo
        )
        return bufs, fires

    # leaf "a" fires on even ranks, leaf "b" on odd ranks
    p = {"a": jnp.arange(4.0), "b": 10.0 + jnp.arange(8.0).reshape(4, 2)}
    fire = {
        "a": jnp.array([True, False, True, False]),
        "b": jnp.array([False, True, False, True]),
    }
    last = {"a": jnp.full(4, -7.0), "b": jnp.full((4, 2), -9.0)}
    (left, right), (lf, rf) = _lift(fn, topo, backend)(p, fire, last)

    # from the left (rank r-1): a fired iff r-1 even, b iff r-1 odd
    np.testing.assert_allclose(left["a"], [-7.0, 0.0, -7.0, 2.0])
    np.testing.assert_allclose(
        left["b"], [[16.0, 17.0], [-9.0, -9.0], [12.0, 13.0], [-9.0, -9.0]]
    )
    np.testing.assert_array_equal(lf["a"], [False, True, False, True])
    np.testing.assert_array_equal(lf["b"], [True, False, True, False])
    # from the right (rank r+1)
    np.testing.assert_allclose(right["a"], [-7.0, 2.0, -7.0, 0.0])
    np.testing.assert_allclose(
        right["b"], [[12.0, 13.0], [-9.0, -9.0], [16.0, 17.0], [-9.0, -9.0]]
    )
