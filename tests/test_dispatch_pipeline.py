"""Zero-bubble dispatch pipeline: pipeline on == pipeline off, bitwise.

The pipelined schedule (train/loop.py) only moves HOST work — the
training scans dispatch in the same order with the same inputs, the
eval is the same jitted device function, the checkpoint snapshot is the
same bytes. So final state AND history metrics must be bit-identical
with the pipeline on or off, across algorithms, telemetry modes, and a
checkpoint/resume that lands mid-run; and a crash during the async save
must leave a restorable snapshot (the atomic-swap invariant).
"""

import os

import jax
import numpy as np
import pytest

from _spmd import requires_shard_map

from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP
from eventgrad_tpu.obs import bubble
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.loop import train
from eventgrad_tpu.utils import checkpoint

#: host-timing fields — the only history keys allowed to differ by mode
_TIMING_KEYS = {"wall_s"}


def _run(algo="eventgrad", obs="off", pipeline=None, ck=None, resume=False,
         epochs=6, mesh=None, epochs_per_dispatch=2, **kw):
    x, y = synthetic_dataset(256, (8, 8, 1), seed=3)
    xt, yt = synthetic_dataset(64, (8, 8, 1), seed=3, split="test")
    cfg = EventConfig(adaptive=True, horizon=0.95, warmup_passes=3)
    return train(
        MLP(hidden=16), Ring(4), x, y,
        algo=algo, epochs=epochs, batch_size=8, learning_rate=0.05,
        event_cfg=cfg if algo != "dpsgd" else None,
        random_sampler=True, seed=5, x_test=xt, y_test=yt,
        epochs_per_dispatch=epochs_per_dispatch, obs=obs,
        pipeline=pipeline, mesh=mesh,
        checkpoint_dir=str(ck) if ck else None,
        save_every=2 if ck else 0, resume=resume, **kw,
    )


def _assert_value_equal(a, b, path=""):
    """Bitwise-recursive equality that tolerates numpy leaves inside
    history records (dict == would be ambiguous on arrays)."""
    assert type(a) is type(b) or (
        isinstance(a, (int, float)) and isinstance(b, (int, float))
    ), f"{path}: {type(a)} vs {type(b)}"
    if isinstance(a, dict):
        assert set(a) == set(b), f"{path}: keys {set(a) ^ set(b)}"
        for k in a:
            _assert_value_equal(a[k], b[k], f"{path}.{k}")
    elif isinstance(a, (list, tuple)):
        assert len(a) == len(b), path
        for i, (x, y) in enumerate(zip(a, b)):
            _assert_value_equal(x, y, f"{path}[{i}]")
    elif isinstance(a, np.ndarray):
        np.testing.assert_array_equal(a, b, err_msg=path)
    else:
        assert a == b, f"{path}: {a!r} != {b!r}"


def _assert_same_run(res0, res1):
    state0, hist0 = res0
    state1, hist1 = res1
    for a, b in zip(jax.tree.leaves(state0), jax.tree.leaves(state1)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert len(hist0) == len(hist1)
    for r0, r1 in zip(hist0, hist1):
        _assert_value_equal(
            {k: v for k, v in r0.items() if k not in _TIMING_KEYS},
            {k: v for k, v in r1.items() if k not in _TIMING_KEYS},
            path=f"epoch{r0.get('epoch')}",
        )


@pytest.mark.parametrize("algo,obs", [
    ("eventgrad", "off"),
    ("eventgrad", "block"),
    ("dpsgd", "off"),
    ("dpsgd", "block"),
])
def test_pipeline_bitwise_parity(algo, obs, tmp_path):
    """pipeline on vs off: final FULL state (params, momenta, event
    buffers, telemetry) and every history record identical — eval and
    checkpoint land at the same epochs with the same contents."""
    res0 = _run(algo, obs, pipeline=False, ck=tmp_path / "a")
    res1 = _run(algo, obs, pipeline=True, ck=tmp_path / "b")
    _assert_same_run(res0, res1)
    # the async save produced a restorable snapshot identical in reach
    for d in ("a", "b"):
        assert checkpoint.latest(str(tmp_path / d / "ckpt")) is not None
    # eval cadence preserved: block ends only, final epoch always
    evaled = [r["epoch"] for r in res1[1] if "test_accuracy" in r]
    assert evaled == [2, 4, 6]


def test_resume_mid_pipeline_matches_uninterrupted(tmp_path):
    """A pipelined run interrupted at a mid-run snapshot and resumed
    (still pipelined) lands on the serial uninterrupted trajectory."""
    full = _run(pipeline=False, epochs=6)
    ck = tmp_path / "ck"
    _run(pipeline=True, ck=ck, epochs=4)
    res = _run(pipeline=True, ck=ck, epochs=6, resume=True)
    assert [h["epoch"] for h in res[1]] == [5, 6]
    for a, b in zip(
        jax.tree.leaves(full[0].params), jax.tree.leaves(res[0].params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


#: per-block bookkeeping keys that legitimately differ between a
#: resumed run and its uninterrupted twin (block indices restart; the
#: resumed first block pays its own compile)
_RESUME_KEYS = _TIMING_KEYS | {"dispatch_block", "dispatch_cold"}


def _assert_resumed_records_match(full_hist, resumed_hist):
    by_epoch = {r["epoch"]: r for r in full_hist}
    for r in resumed_hist:
        ref = by_epoch[r["epoch"]]
        _assert_value_equal(
            {k: v for k, v in r.items() if k not in _RESUME_KEYS},
            {k: v for k, v in ref.items() if k not in _RESUME_KEYS},
            path=f"epoch{r['epoch']}",
        )


def test_resume_reproduces_pipelined_eval_history_bitwise(tmp_path):
    """Resume-under-pipeline edge (ISSUE 8 satellite): with one-epoch
    blocks, block N's eval readback drains one block late by design, so
    the epoch-4 snapshot is written while an eval future is pending.
    Resuming from it must reproduce the uninterrupted run's eval
    history — test_accuracy/test_loss and every other record value —
    bitwise, not approximately."""
    full = _run(pipeline=True, ck=tmp_path / "a", epochs=6,
                epochs_per_dispatch=1)
    ck = tmp_path / "b"
    _run(pipeline=True, ck=ck, epochs=4, epochs_per_dispatch=1)
    res = _run(pipeline=True, ck=ck, epochs=6, resume=True,
               epochs_per_dispatch=1)
    assert [h["epoch"] for h in res[1]] == [5, 6]
    # K=1 evaluates at every block end: both resumed records carry eval
    assert all("test_accuracy" in r for r in res[1])
    _assert_resumed_records_match(full[1], res[1])
    for a, b in zip(jax.tree.leaves(full[0]), jax.tree.leaves(res[0])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@requires_shard_map
def test_resume_pipelined_eval_history_shard_map(tmp_path):
    """The resume-under-pipeline eval edge is lift-agnostic: the
    shard_map-lifted run reproduces its uninterrupted eval history
    bitwise too."""
    from eventgrad_tpu.parallel.spmd import build_mesh

    mesh = build_mesh(Ring(4))
    full = _run(pipeline=True, ck=tmp_path / "a", epochs=4, mesh=mesh,
                epochs_per_dispatch=1)
    ck = tmp_path / "b"
    _run(pipeline=True, ck=ck, epochs=2, mesh=mesh, epochs_per_dispatch=1)
    res = _run(pipeline=True, ck=ck, epochs=4, resume=True, mesh=mesh,
               epochs_per_dispatch=1)
    assert [h["epoch"] for h in res[1]] == [3, 4]
    _assert_resumed_records_match(full[1], res[1])


def test_interrupt_mid_run_joins_writer_and_leaves_complete_snapshot(
    tmp_path,
):
    """AsyncWriter interrupt barrier (ISSUE 8 satellite): a
    KeyboardInterrupt raised inside the training loop (the user's ^C)
    unwinds through the join barrier, so a partially-serialized
    snapshot can never be the newest file — the latest snapshot loads
    completely and the run resumes from it."""
    ck = tmp_path / "ck"

    def interrupt(rec):
        if rec.get("epoch") == 4:
            raise KeyboardInterrupt

    with pytest.raises(KeyboardInterrupt):
        _run(pipeline=True, ck=ck, epochs=6, on_epoch=interrupt)
    found = checkpoint.latest(str(ck / "ckpt"))
    assert found is not None
    raw = checkpoint.peek(found)  # a torn write would fail this loudly
    assert int(np.asarray(raw["epoch"])) in (2, 4)
    res = _run(pipeline=True, ck=ck, epochs=6, resume=True)
    assert res[1][-1]["epoch"] == 6


def test_pipeline_rejects_fault_inject_and_auto_disables():
    with pytest.raises(ValueError, match="fault_inject"):
        _run(pipeline=True, fault_inject="crash:99")
    # auto mode silently falls back to the serial schedule (the fault
    # epoch is past the run, so training completes normally)
    state, hist = _run(pipeline=None, fault_inject="crash:99", epochs=2)
    assert [h["epoch"] for h in hist] == [1, 2]


def test_crash_during_async_save_leaves_restorable_snapshot(tmp_path):
    """The atomic-swap invariant survives the writer thread dying at the
    worst point: after the old snapshot moved aside, before the new one
    promoted. latest() finds the .prev and a pipelined resume works."""
    ck = tmp_path / "ck"
    _run(pipeline=True, ck=ck, epochs=4)
    path = os.path.join(str(ck), "ckpt")
    # simulate the mid-swap kill the async writer could suffer
    os.rename(path, path + ".prev")
    assert checkpoint.latest(path) == os.path.abspath(path) + ".prev"
    res = _run(pipeline=True, ck=ck, epochs=6, resume=True)
    assert [h["epoch"] for h in res[1]] == [5, 6]


def test_async_writer_error_surfaces_at_barrier(tmp_path, monkeypatch):
    """A failed background save re-raises at the next join barrier —
    never silently (a run that 'checkpointed' nothing must not exit 0)."""
    real_save = checkpoint.save
    boom = {"armed": True}

    def flaky_save(path, payload):
        if boom.pop("armed", False):
            raise OSError("disk full")
        real_save(path, payload)

    monkeypatch.setattr(checkpoint, "save", flaky_save)
    w = checkpoint.AsyncWriter()
    w.save(str(tmp_path / "ck"), {"a": np.zeros(2)})
    with pytest.raises(RuntimeError, match="async checkpoint save failed"):
        w.wait()
    # the barrier consumed the error; the writer is reusable
    w.save(str(tmp_path / "ck"), {"a": np.zeros(2)})
    w.close()
    assert checkpoint.latest(str(tmp_path / "ck"))


def test_async_writer_join_barrier_orders_saves(tmp_path):
    """save() joins the in-flight write first — two snapshots can never
    race the tmp/prev swap; the LAST payload wins on disk."""
    w = checkpoint.AsyncWriter()
    p = str(tmp_path / "ck")
    for i in range(3):
        w.save(p, {"epoch": np.int64(i)})
    w.close()
    got = checkpoint.restore(checkpoint.latest(p), {"epoch": np.int64(0)})
    assert int(got["epoch"]) == 2


def test_pipeline_spans_decompose(tmp_path):
    """The span trace carries the overlap phases: obs.bubble.decompose
    recovers blocks, components, and a finite bubble from both modes."""
    from eventgrad_tpu.obs import Registry

    for flag in (False, True):
        reg = Registry()
        _run(obs="block", pipeline=flag, ck=tmp_path / f"p{flag}",
             registry=reg)
        d = bubble.decompose(reg.spans)
        assert d["n_blocks"] == 3  # 6 epochs at K=2
        assert d["pipelined"] is flag
        assert 0.0 <= d["host_bubble_frac"] <= 1.0
        assert d["wall_s"] > 0 and d["steps_s"] > 0
        names = {s.name for s in reg.spans}
        assert {"train", "data", "dispatch_block", "block_ready",
                "obs_flush", "eval", "eval_readback"} <= names
        # checkpoint spans follow the schedule: snapshot+write when
        # pipelined, the inline span when serial
        if flag:
            assert {"ckpt_snapshot", "ckpt_write"} <= names
        else:
            assert "checkpoint" in names


@requires_shard_map
def test_pipeline_parity_shard_map():
    """The pipelined schedule is lift-agnostic: shard_map-lifted runs
    match their serial twins bitwise too."""
    from eventgrad_tpu.parallel.spmd import build_mesh

    mesh = build_mesh(Ring(4))
    res0 = _run(pipeline=False, mesh=mesh, epochs=4)
    res1 = _run(pipeline=True, mesh=mesh, epochs=4)
    _assert_same_run(res0, res1)
