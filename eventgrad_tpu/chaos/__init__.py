"""Chaos subsystem: deterministic fault injection and recovery for gossip.

EventGraD's core claim is stale-tolerance: a receiver that misses a send
keeps mixing with the last value it got (PAPER.md; the zero-initialized RMA
window of event.cpp:177-179 already exercises this on pass 1). That is the
exact failure semantics of a lossy network, so this package makes loss a
first-class, *measured* property instead of a hope:

  * `schedule` — seeded, fully reproducible fault schedules (per-edge drop
    probability, flaky windows, k-pass delivery thinning, permanent peer
    death), serializable into bench records so every run is replayable.
  * `inject`   — JIT-compatible injection that masks gossip edges inside
    the mixing step; a dropped message is "receiver keeps its stale
    buffer", composing with the fired/not-fired mask of
    `parallel.events.decide_and_update` in one fused program.
  * `monitor`  — peer-health tracking: per-edge silence counters, injected
    drop counters, and a consensus-error probe `||p_i - mean(p)||` that
    distinguishes "quiet because the threshold says so" from "quiet
    because the link is dead".
  * `policy`   — recovery: receiver-side forced full-sync (generalizing
    the sender-side `max_silence` knob), edge-freeze with renormalized mix
    weights, and ring heal on permanent death (survivors bridge the gap
    via a rewritten `Topology`).
  * `membership` — ELASTIC membership: a replayable stream of epoch-keyed
    join/leave events applied between jit dispatch blocks — leave
    generalizes the heal, join bootstraps a newcomer's full gossip state
    from a neighbor's snapshot streamed through the async checkpoint
    writer, and every transition force-fires the next exchange so
    buffers refresh in one cycle.
  * `crashpoint` — PROCESS death drills and graceful preemption: a
    registry of named, deterministically-armed kill sites at every
    state-mutating seam (checkpoint swap, async writer thread, block
    boundaries, bootstrap stream, rollback-restore) for the crash-
    consistency matrix (tools/crash_matrix.py), plus the SIGTERM/SIGINT
    drain + `preempt=EPOCH@STEP` clause that turns preemption into a
    clean ≤-one-block loss (exit `exitcodes.PREEMPTED_EXIT`; the
    supervisor relaunches without charging its budget).
  * `integrity` — LYING peers and SICK ranks (where the faults above are
    silent ones): wire checksums on every gossip payload (a failed check
    is an event that did not fire), non-finite quarantine inside the
    fused step, and the host-side divergence sentinel + rollback-to-
    last-good engine riding the block drain. Exercised by the
    `bitflip=` / `nanstep=` fault clauses of `schedule`.

Entry points: `train.loop.train(chaos=..., chaos_policy=...,
membership=...)`, the CLI's `--chaos/--chaos-sync-after/
--chaos-freeze-after/--membership` flags, `bench.py`'s EG_BENCH_CHAOS
mode, `tools/chaos_sweep.py` (drop-rate vs accuracy and recovery-latency
curves), and `tools/soak.py` (the supervised long-running soak harness).
Fault model and formats: docs/chaos.md.
"""

from eventgrad_tpu.chaos.crashpoint import GracefulPreemption
from eventgrad_tpu.chaos.schedule import ChaosSchedule, FlakyWindow
from eventgrad_tpu.chaos.integrity import (
    INTEGRITY_ABORT_EXIT, DivergenceSentinel, IntegrityConfig,
    IntegrityEscalation,
)
from eventgrad_tpu.chaos.membership import (
    MembershipEngine, MembershipEvent, MembershipSchedule,
)
from eventgrad_tpu.chaos.monitor import PeerHealth, consensus_error
from eventgrad_tpu.chaos.policy import RecoveryPolicy, heal_ring, apply_ring_heal

__all__ = [
    "ChaosSchedule",
    "FlakyWindow",
    "INTEGRITY_ABORT_EXIT",
    "DivergenceSentinel",
    "GracefulPreemption",
    "IntegrityConfig",
    "IntegrityEscalation",
    "MembershipEngine",
    "MembershipEvent",
    "MembershipSchedule",
    "PeerHealth",
    "RecoveryPolicy",
    "consensus_error",
    "heal_ring",
    "apply_ring_heal",
]
