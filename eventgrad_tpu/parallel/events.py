"""EventGraD: per-parameter event-triggered communication state machine.

Pure-functional rebuild of the sender-side state of
/root/reference/dmnist/event/event.cpp:

  * event condition  (event.cpp:343):
        fire_i  <-  |‖p_i‖₂ − last_sent_norm_i| >= thres_i
                    OR pass_num < warmup_passes          (warmup, :262)
  * threshold decay BEFORE the check (adaptive: thres *= horizon, :330-332;
    constant mode: thres = constant, :332-334)
  * on fire (adaptive): slope history ring-buffer shifts in
    value_diff/iter_diff and thres becomes the history mean (:363-378);
    last_sent_norm/iter update (:380-382)
  * num_events += n_neighbors per fired parameter (:344 counts 2 on a ring)

The reference keeps this state in C scalar arrays indexed by parameter
(:181-225); here it is a pytree-of-scalars mirroring the param pytree, so the
whole update is a fused elementwise program under jit — no per-parameter
Python loop survives tracing.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from eventgrad_tpu.parallel.topology import Topology
from eventgrad_tpu.utils import trees


@dataclasses.dataclass(frozen=True)
class EventConfig:
    """Static event-trigger configuration (the reference's argv[2]/argv[3],
    event.cpp:88-100).

    adaptive=True  -> thres decays by `horizon` each pass and resets to the
                      mean send slope on fire.
    adaptive=False -> thres is the fixed `constant` every pass.
    constant=0 (or horizon=0) makes every pass fire: exact D-PSGD
    (dmnist/event/README.md's baseline-equivalence knob).

    max_silence (beyond the reference): bounded staleness — a parameter
    that has not fired for `max_silence` passes fires regardless of its
    norm drift. 0 disables (reference behavior). The reference's adaptive
    threshold has an instability: with horizon > 1 a growing threshold can
    silence a parameter indefinitely, ranks drift apart unnoticed, and
    training collapses on some seeds (observed at horizon 1.05 on the
    LeNet/CIFAR op-point: one seed −76pp, another +0.4pp). A silence bound
    turns that cliff into a controlled trade: aggressive horizons keep
    their savings while consensus error stays bounded. max_silence=1 is
    exact D-PSGD.
    """

    adaptive: bool = True
    horizon: float = 0.95
    constant: float = 0.0
    warmup_passes: int = 30
    history: int = 2
    max_silence: int = 0


def resolve_bench_trigger(environ) -> tuple:
    """(horizon, max_silence) for the benchmark op-point, resolved from the
    EG_BENCH_HORIZON / EG_BENCH_MAX_SILENCE env knobs — the ONE definition
    shared by bench.py and tools/tpu_flagship.py so the two artifacts
    always measure the same trigger config.

    Default is the stabilized aggressive op-point (horizon 1.05 + silence
    guard 50). A reference-pure request (guard off) drops the horizon to
    the neutral 1.0 unless one was explicitly pinned: 1.05 UNGUARDED is
    the seed-collapsing combination documented above (up to −76pp,
    artifacts/horizon_stability_r2_cpu.jsonl).
    """
    horizon = float(environ.get("EG_BENCH_HORIZON", "1.05"))
    max_silence = int(environ.get("EG_BENCH_MAX_SILENCE", "50"))
    if max_silence == 0 and "EG_BENCH_HORIZON" not in environ:
        horizon = 1.0
    return horizon, max_silence


#: the full-scale MNIST claim op-point (n_train, epochs, batch/rank):
#: 1168 passes of CNN-2 at batch 64, lr 0.05, sequential sampler — the
#: reference's ~70% headline geometry (dmnist/event/event.cpp:103,145,
#: 227,255). ONE definition shared by bench.py's full tier and
#: tools/tpu_flagship.py so the two artifacts measure the same leg.
MNIST_FULLSCALE_OP_POINT = (8192, 73, 64)


def pick_mnist_rung(remaining_s: float, refpure: bool) -> tuple:
    """Reduced-tier MNIST ladder (round-4): pick the best measured rung
    the remaining attempt budget affords. Returns (n_train, epochs,
    horizon, max_silence) or None to keep the tier's 160-pass floor.

    Rungs (artifacts/mnist_knee_r4_cpu.jsonl, warmup 10, one core):
      544 passes, 1.025+guard50, 4096 samples: 71.09% saved at 97.7%
        test acc, ~341 s — the >= 1.0 vs-baseline rung
      380 passes, 1.025+guard50, 2048 samples: 69.71% at 94.8%, ~237 s
    With `refpure` (an explicit EG_BENCH_MAX_SILENCE=0 request) only the
    pass budget upgrades — the trigger stays the paper's
    (544 passes reference-pure measured 66.08%, mnist_knee_r3_cpu.jsonl).

    There is deliberately NO rung below 380 passes: the 1.025+guard
    trigger cliff-collapses at shorter scale (measured: 71.07% "saved"
    at 55.8% accuracy at 240 passes, 70.6% at 75.1% at 280 — same
    artifact), so tighter budgets keep the reference-pure 160-pass
    floor.
    """
    if remaining_s >= 390:
        return (4096, 68) + ((1.0, 0) if refpure else (1.025, 50))
    if remaining_s >= 285:
        return (2048, 95) + ((1.0, 0) if refpure else (1.025, 50))
    return None


def pick_full_epochs(attempt_s) -> int:
    """Full (TPU) tier CIFAR epoch count by attempt budget. None (no
    deadline, direct run) = the 61-epoch reference scale (3904 passes,
    dcifar10/event/event.cpp:31-36).

    Ladder recalibrated from the round-4 live capture
    (artifacts/tpu_flagship_quick.json, TPU v5 lite): steady epochs
    ~7.6 s (eventgrad) + ~11.7 s (dpsgd) = ~19.3 s per epoch pair;
    fixed costs ~230 s warm-cache (two consensus+evals ~45 s each,
    MNIST claim leg 109 s, startup/dispatch) and up to ~320 s with cold
    compiles. The >= 640 s rungs keep ~15% headroom over (fixed_cold +
    epochs * 19.3) — safe even with cold compiles; the pre-capture
    guesses (61 epochs at >= 420 s!) would have blown any driver-window
    attempt and lost the tier to the CPU fallback. The rungs BELOW
    640 s are sized for the warm-compile-cache case (~230 s fixed):
    cold they cannot fit at all (fixed costs alone approach the
    budget), and the realistic short-window path IS warm — either this
    session's captures populated the persistent cache, or a killed
    cold first attempt populated it for the upgrade-phase re-run; a
    cold miss falls back to the guaranteed CPU line. The MNIST claim
    leg keeps its full 1168 passes in every case — it is the ~70%
    headline's exact op-point and the cheapest leg on-chip."""
    if attempt_s is None:
        return 61
    a = float(attempt_s)
    if a >= 1720:
        return 61   # full reference scale: ~320 + 61*19.3 ~= 1500 s
    if a >= 1030:
        return 30   # 1920 passes, past the savings knee: ~900 s
    if a >= 640:
        return 12   # 768 passes: ~550 s cold
    if a >= 460:
        return 8    # warm ~385 s (measured cold end-to-end: 545 s)
    return 5        # minimum chip evidence: warm ~330 s


def pick_cifar_epochs(remaining_s: float) -> int:
    """Reduced-tier CIFAR pass-count ladder (round-4): 40 epochs (640
    passes — stabilized 64.6% saved at gap 0.0, the floor) upgrades to
    60 epochs (960 passes — 67.31% at 99.6% acc, cifar_knee_r3_cpu.jsonl)
    only when the remaining budget still guarantees the MNIST ladder's
    top rung behind it: the CIFAR upgrade buys +2.7pp of headline, the
    MNIST top rung is the metric that was below bar — it keeps priority.
    Budget check: 960-pass pair ~175 s + evals ~25 s + MNIST top rung
    ~355 s + startup/misc ~35 s ≈ 590 s; 640 gives ~50 s of variance
    headroom so the CIFAR upgrade can never demote the MNIST pick
    (measured pair walls: ~120 s at 640 passes, ~175 s at 960)."""
    return 60 if remaining_s >= 640 else 40


def resolve_bench_trigger_mnist(environ, max_silence: int) -> float:
    """Full-tier MNIST-leg horizon — the same one-definition rule as
    resolve_bench_trigger. Stabilized 1.05 (proven 75.5% saved at
    -1.17pp over 1168 passes) requires the silence guard; a
    reference-pure request (guard off) drops to the neutral 1.0 unless
    EG_BENCH_HORIZON_MNIST explicitly pins one."""
    return float(environ.get(
        "EG_BENCH_HORIZON_MNIST", "1.05" if max_silence > 0 else "1.0"
    ))


class EventState(struct.PyTreeNode):
    """Sender-side per-parameter state + per-neighbor receive buffers.

    The reference keeps one C scalar array per quantity indexed by
    parameter id (event.cpp:181-225); the TPU-native form is the same
    thing as VECTORS over the leaf axis — one fused state-machine update
    of shape [L] per pass instead of ~L pytree ops on 0-d scalars (which
    bloat the HLO graph and dominate step overhead for small models).
    Leaf order is the params pytree's canonical flatten order.

    thres / last_sent_norm / last_sent_iter: f32[L] (L = number of
        parameter leaves).
    slopes: f32[L, history] (sent_slopes_norm, :187).
    bufs:   one pytree-like-params per topology neighbor — the RMA window
            halves (:169-179), zero-initialized exactly like the reference
            (:177-179; the /3 mixing still divides by 3 before any message
            arrives, which warmup makes moot after pass 1).
    num_events: local int32 event counter (:264).
    """

    thres: jnp.ndarray
    last_sent_norm: jnp.ndarray
    last_sent_iter: jnp.ndarray
    slopes: jnp.ndarray
    bufs: Tuple[Any, ...]
    num_events: jnp.ndarray
    #: leaf-fires proposed by the trigger but deferred by the compact wire
    #: budget (capacity_gate) — rolled back to re-contend next pass;
    #: int32 scalar, cumulative like num_events
    num_deferred: jnp.ndarray = None  # type: ignore[assignment]
    #: bounded-async delivery queues (train(staleness=D) for D >= 2;
    #: None otherwise — D <= 1 states keep the legacy structure so old
    #: checkpoints restore unchanged): per neighbor, D slots of
    #: (candidate buffer, effective fire bits, sent-pass int32 scalar,
    #: late-message count int32 scalar[, dequant scales — int8 carrier
    #: only]), slot r holding the in-flight message that commits r+1
    #: passes from now (the late count survives same-arrival-pass
    #: merges, where the merged sent-pass keeps only the newest). The
    #: candidate/eff/scale entries carry the buffers' own layout — flat
    #: [n] monolithic or per-bucket tuples under bucketed=K, in the
    #: wire dtype under carrier residency (arena.alloc_event_queue).
    #: Zero slots are no-op commits (eff all False), so the zero init
    #: needs no special casing — exactly the reference's zero RMA
    #: window (event.cpp:177-179).
    pending: Any = None
    #: int32 [n_neighbors] per-edge staleness clock: the send pass of
    #: the newest DELIVERED exchange committed on each edge (0 = none
    #: yet). `pass_num - edge_clock` is the per-edge staleness gauge
    #: (obs/schema.py `edge_staleness`), bounded by D + the drop streak.
    edge_clock: jnp.ndarray = None  # type: ignore[assignment]
    #: cumulative int32: commits that arrived >= 2 passes after their
    #: send — the genuinely-late deliveries the bound admitted
    late_commits: jnp.ndarray = None  # type: ignore[assignment]
    #: carrier-resident gossip (train(carrier_resident=...)): per-leaf
    #: f32 dequant scales for int8-resident receive buffers — one [L]
    #: vector per neighbor ([L_b] per bucket under the bucketed
    #: layout); None for f32/bf16 residency, so legacy states keep the
    #: exact pytree structure and old checkpoints restore unchanged.
    buf_scales: Any = None

    @classmethod
    def init(
        cls, params: Any, topo: Topology, cfg: EventConfig,
        arena: bool = False, buckets: int = 1, staleness: int = 0,
        resident_wire=None,
    ) -> "EventState":
        """`arena=True` stores the per-neighbor receive buffers as flat
        [n_params] arenas (parallel/arena.py) instead of pytrees — the
        layout the flat-arena train step carries so no per-step
        ravel/unravel of stale buffers survives. `buckets=K` (arena
        only) further segments each neighbor's buffer into the K
        leaf-aligned bucket arrays of the bucketed gossip schedule
        (ArenaSpec.buckets — the step commits and mixes each bucket
        independently, so the state carries the per-bucket layout
        directly). Zero-initialized either way (event.cpp:177-179);
        checkpoints restore into whichever layout the run was built
        with (a cross-layout restore fails loudly, by design).

        `staleness=D` (D >= 2, arena only) additionally carries the
        bounded-async per-edge delivery queues: D in-flight slots per
        neighbor plus the per-edge staleness clocks and the late-commit
        counter. The queue depth is part of the checkpoint layout like
        the bucket count — resuming across a different D fails loudly
        (train/loop.py names the cause).

        `resident_wire` ('bf16' | 'int8', arena only) stores the
        receive buffers CARRIER-RESIDENT: in the wire dtype, plus the
        per-leaf f32 dequant scales (`buf_scales`, int8 only) — the
        dequant then happens inside the commit/mix reads
        (parallel/arena.py alloc_event_bufs). The resident dtype is
        part of the checkpoint layout; cross-layout restores fail
        loudly, both directions."""
        n = trees.tree_num_leaves(params)
        zeros = jnp.zeros((n,), jnp.float32)
        depth = int(staleness) if staleness and int(staleness) >= 2 else 0
        if depth and not arena:
            raise ValueError(
                "EventState.init(staleness>=2) carries flat per-edge "
                "delivery queues and needs arena=True (the bounded-"
                "async engine is an arena hot path) — drop staleness "
                "to <= 1 or pass arena=True"
            )
        buf_scales = None
        if arena:
            from eventgrad_tpu.parallel import arena as arena_mod

            spec = arena_mod.arena_spec(params)
            if not spec.homogeneous:
                # the flat buffers pack ONE dtype; a mismatched layout
                # here would meet the step's tree-path demotion and die
                # with an unrelated structure error — name the cause
                raise ValueError(
                    "EventState.init(arena=True) needs a single "
                    f"parameter dtype; got {sorted(set(spec.dtypes))} — "
                    "use arena=False for heterogeneous models"
                )
            bufs, buf_scales = arena_mod.alloc_event_bufs(
                spec, topo.n_neighbors, wire=resident_wire,
                buckets=int(buckets) if buckets else 1,
            )
            buf0 = bufs[0]
        else:
            if resident_wire is not None:
                raise ValueError(
                    "EventState.init(resident_wire=...) rides the flat "
                    "arena buffer layout; got arena=False"
                )
            buf0 = trees.tree_zeros_like(params)
            bufs = tuple(buf0 for _ in topo.neighbors)
        pending = None
        edge_clock = None
        late_commits = None
        if depth:
            # queue slots share the buffers' exact layout — per-bucket
            # tuples under bucketed=K, the wire carrier dtype (+ per-slot
            # dequant scales) under carrier residency — allocated through
            # the one arena helper that declares the resident dtype
            pending = arena_mod.alloc_event_queue(
                spec, topo.n_neighbors, depth, wire=resident_wire,
                buckets=int(buckets) if buckets else 1,
            )
            edge_clock = jnp.zeros((topo.n_neighbors,), jnp.int32)
            late_commits = jnp.zeros((), jnp.int32)
        return cls(
            thres=zeros,
            last_sent_norm=zeros,
            last_sent_iter=zeros,
            slopes=jnp.zeros((n, cfg.history), jnp.float32),
            # the same (immutable) zero leaves may back every neighbor
            bufs=bufs,
            num_events=jnp.zeros((), jnp.int32),
            num_deferred=jnp.zeros((), jnp.int32),
            pending=pending,
            edge_clock=edge_clock,
            late_commits=late_commits,
            buf_scales=buf_scales,
        )


class EventProposal(struct.PyTreeNode):
    """Sender state-machine decision for one pass, BEFORE any wire-budget
    gating: everything `commit` needs to finalize the EventState once the
    effective fire bits are known. Splitting decide from commit is what
    makes compact-wire deferral a rollback-free operation — a deferred
    leaf's state is simply never committed (thres keeps decaying, silence
    keeps accruing, slopes don't shift), exactly as if the trigger had not
    fired, so it re-contends next pass and the max_silence bound still
    sees its true silence."""

    fire_vec: jnp.ndarray    # bool [L] — the un-gated trigger decision
    curr_norm: jnp.ndarray   # f32 [L]
    new_slopes: jnp.ndarray  # f32 [L, history]
    thres: jnp.ndarray       # f32 [L] post-decay, pre-fire threshold
    iter_diff: jnp.ndarray   # f32 [L] passes since last send
    pass_f: jnp.ndarray      # f32 [] — this pass, as float
    #: f32 [L] |‖p‖₂ − last_sent_norm| — the trigger's drive signal,
    #: surfaced for the telemetry drift-norm accumulator (obs.device)
    value_diff: jnp.ndarray = None  # type: ignore[assignment]


def propose(
    params: Any,
    state: EventState,
    pass_num: jnp.ndarray,
    cfg: EventConfig,
    force_fire: "Any" = None,
) -> EventProposal:
    """One pass of the sender trigger for every parameter at once.

    `pass_num` is 1-based and already incremented for this pass, matching
    `pass_num++` at the top of the batch loop (event.cpp:273).

    `force_fire` (optional bool scalar or [L]) ORs into the fire decision —
    the receiver-side forced-full-sync channel of chaos.policy (a neighbor
    whose silence bound tripped asked for fresh values last pass). Forced
    fires update the sender state and event counters like any fire: the
    wire cost of recovery is accounted, not hidden.
    """
    # per-leaf L2 norms stacked into the [L] state-vector order; every
    # subsequent state-machine op is one fused vector op, not L scalar ops
    leaves, _ = jax.tree.flatten(params)
    curr_norm = jnp.stack(
        [jnp.linalg.norm(l.reshape(-1)) for l in leaves]
    ).astype(jnp.float32)
    return propose_from_norms(
        curr_norm, state, pass_num, cfg, force_fire=force_fire
    )


def propose_from_norms(
    curr_norm: jnp.ndarray,
    state: EventState,
    pass_num: jnp.ndarray,
    cfg: EventConfig,
    force_fire: "Any" = None,
) -> EventProposal:
    """`propose` with the [L] parameter norms precomputed — the shared
    body of `propose` above, split out as the injection seam for any
    caller that already holds the norms (e.g. a future fused norm
    kernel); today both engines reach it through `propose`."""
    pass_f = pass_num.astype(jnp.float32)
    value_diff = jnp.abs(curr_norm - state.last_sent_norm)
    iter_diff = pass_f - state.last_sent_iter

    # threshold decay/assignment happens before the check (:330-334)
    if cfg.adaptive:
        thres = state.thres * cfg.horizon
    else:
        thres = jnp.full_like(state.thres, cfg.constant)

    warm = pass_num < cfg.warmup_passes
    fire_vec = (value_diff >= thres) | warm
    if cfg.max_silence > 0:  # bounded staleness (beyond-reference)
        fire_vec = fire_vec | (iter_diff >= cfg.max_silence)
    if force_fire is not None:  # receiver-requested full sync (chaos.policy)
        fire_vec = fire_vec | force_fire

    # slope ring buffer: drop oldest, append value_diff/iter_diff (:363-373)
    new_slopes = jnp.concatenate(
        [state.slopes[:, 1:], (value_diff / iter_diff)[:, None]], axis=1
    )
    return EventProposal(
        fire_vec=fire_vec,
        curr_norm=curr_norm,
        new_slopes=new_slopes,
        thres=thres,
        iter_diff=iter_diff,
        pass_f=pass_f,
        value_diff=value_diff,
    )


def commit(
    state: EventState,
    prop: EventProposal,
    fire_vec: jnp.ndarray,
    cfg: EventConfig,
    n_neighbors: int,
) -> EventState:
    """Apply one pass's state update for the leaves that actually fired.

    `fire_vec` is the EFFECTIVE fire decision — `prop.fire_vec` itself on
    the dense/masked paths, or its `capacity_gate`d subset on the compact
    wire. Leaves proposed but not committed count into `num_deferred`;
    their thres/norm/iter/slopes stay untouched (the rollback), and
    `num_events` counts effective sends only, so msgs-saved-% keeps
    matching what the wire really carried.

    `num_deferred` conflates capacity deferrals with quarantine/policy
    suppressions (both look like "proposed but not on the wire" here);
    the message-lifecycle ledger (obs/ledger.py, schema.DISPOSITIONS)
    splits them into `deferred` vs `suppressed` — use the ledger when
    the distinction matters.
    """
    slope_avg = jnp.mean(prop.new_slopes, axis=1)
    if cfg.adaptive:
        thres_on_fire = slope_avg  # (:376-378)
    else:
        thres_on_fire = prop.thres
    deferred = jnp.sum((prop.fire_vec & ~fire_vec).astype(jnp.int32))
    return state.replace(
        thres=jnp.where(fire_vec, thres_on_fire, prop.thres),
        last_sent_norm=jnp.where(fire_vec, prop.curr_norm, state.last_sent_norm),
        last_sent_iter=jnp.where(fire_vec, prop.pass_f, state.last_sent_iter),
        slopes=jnp.where(fire_vec[:, None], prop.new_slopes, state.slopes),
        num_events=state.num_events
        + n_neighbors * jnp.sum(fire_vec.astype(jnp.int32)),
        num_deferred=state.num_deferred + deferred,
    )


def async_delivery_plan(
    state: EventState,
    delivered: "Any",
    lag_vec: jnp.ndarray,
    pass_num: jnp.ndarray,
    bound: int,
):
    """The scalar half of one bounded-async pass, shared by every bucket
    of the buffer layout: arrival clocks from slot 0's sent stamps, the
    late-commit drain, and the shift+merge of the per-slot (sent, late)
    scalars — none of which depend on the candidate arrays, so the
    bucketed schedule computes them ONCE and threads the enqueue masks
    into each per-bucket commit tail (`async_bucket_commit`).

    Returns `(here, sent_slots, late_slots, new_clock, late_now)`:
    `here[i][r]` the bool enqueue mask of edge i's slot r (this pass's
    message lands where its lag says), `sent_slots`/`late_slots` the
    post-shift-and-merge per-edge per-slot i32 stamps, `new_clock` the
    advanced per-edge staleness clock, `late_now` the late commits
    drained this pass (slot 0's counts)."""
    D = int(bound)
    pass_i = jnp.asarray(pass_num, jnp.int32)
    n_nb = len(state.pending)
    if delivered is None:
        delivered = jnp.ones((n_nb,), bool)
    sent_new = jnp.where(delivered, pass_i, jnp.int32(0))  # [n_nb]
    # a delivered message enqueued at lag >= 2 WILL commit late; the
    # count rides its slot so same-arrival-pass merges (whose sent-pass
    # keeps only the newest message) still account every late one
    late_new = (delivered & (lag_vec >= 2)).astype(jnp.int32)  # [n_nb]
    here_all, sent_all, late_all, clock_out = [], [], [], []
    late = jnp.zeros((), jnp.int32)
    for i in range(n_nb):
        slots = state.pending[i]
        s0, l0 = slots[0][2], slots[0][3]
        arrived = s0 > 0
        clock_out.append(jnp.where(
            arrived, jnp.maximum(state.edge_clock[i], s0),
            state.edge_clock[i],
        ))
        late = late + l0
        d = lag_vec[i]
        hs, ss_out, ls_out = [], [], []
        for r in range(D):
            if r + 1 < D:
                ss, sl = slots[r + 1][2], slots[r + 1][3]
            else:
                ss = sl = jnp.zeros((), jnp.int32)
            h = (d - 1) == r
            hs.append(h)
            ss_out.append(jnp.where(h, jnp.maximum(ss, sent_new[i]), ss))
            ls_out.append(jnp.where(h, sl + late_new[i], sl))
        here_all.append(tuple(hs))
        sent_all.append(tuple(ss_out))
        late_all.append(tuple(ls_out))
    clock = jnp.stack(clock_out) if n_nb else state.edge_clock
    return here_all, sent_all, late_all, clock, late


def async_bucket_commit(
    slots,
    here,
    cand: jnp.ndarray,
    eff: jnp.ndarray,
    last: jnp.ndarray,
    seg: jnp.ndarray,
    bucket=None,
    cand_scale=None,
    last_scale=None,
):
    """The array half of one edge's bounded-async update, restricted to
    one bucket of the buffer layout (`bucket=None` = the monolithic
    whole-wire slice): slot 0's arrival commits into the persistent
    buffer with the same `where(eff, cand, stale)` select every
    synchronous path uses, the queue shifts, and this pass's shipped
    (cand, eff[, scale]) merge-inserts at the slots `here` flags (from
    `async_delivery_plan`) — later-sent-wins, elementwise per bucket.
    Under an int8 carrier the per-slot dequant scales ride the same
    discipline: arrivals land their scales next to their payload, so a
    committed leaf always dequantizes through the scale it crossed the
    wire with.

    Returns `(buf, new_cands, new_effs, new_scales, buf_scale)` — the
    post-arrival buffer, the D per-slot candidate/eff (and scale)
    entries for this bucket, and the post-arrival dequant scales
    (scale returns are None without an int8 carrier)."""
    D = len(slots)

    def pick(slot, idx):
        v = slot[idx]
        return v if bucket is None else v[bucket]

    c0, e0 = pick(slots[0], 0), pick(slots[0], 1)
    buf = jnp.where(e0[seg], c0, last)
    buf_scale = None
    if last_scale is not None:
        buf_scale = jnp.where(e0, pick(slots[0], 4), last_scale)
    eff_exp = eff[seg]
    new_cands, new_effs, new_scales = [], [], []
    for r in range(D):
        if r + 1 < D:
            sc, se = pick(slots[r + 1], 0), pick(slots[r + 1], 1)
            ssc = pick(slots[r + 1], 4) if last_scale is not None else None
        else:
            sc, se = jnp.zeros_like(c0), jnp.zeros_like(e0)
            ssc = (
                jnp.zeros_like(last_scale)
                if last_scale is not None else None
            )
        h = here[r]
        new_cands.append(jnp.where(h & eff_exp, cand, sc))
        new_effs.append(jnp.where(h, se | eff, se))
        if last_scale is not None:
            new_scales.append(jnp.where(h & eff, cand_scale, ssc))
    return (
        buf, tuple(new_cands), tuple(new_effs),
        tuple(new_scales) if last_scale is not None else None,
        buf_scale,
    )


def async_delivery_commit(
    state: EventState,
    cands: Tuple[jnp.ndarray, ...],
    effs: Tuple[jnp.ndarray, ...],
    delivered: "Any",
    lag_vec: jnp.ndarray,
    pass_num: jnp.ndarray,
    spec,
    bound: int,
    cand_scales=None,
) -> Tuple[EventState, Tuple[jnp.ndarray, ...], jnp.ndarray, jnp.ndarray]:
    """One pass of the bounded-async delivery engine (staleness=D >= 2).

    Semantics: the exchange still physically runs every pass (ppermute
    is a collective), but the received candidate COMMITS only when its
    scheduled lag elapses — the deterministic model of a message that
    left on time and arrived late. Three phases, per edge:

      1. ARRIVALS: the queue's slot 0 (in-flight messages whose lag
         elapses this pass) commits into the persistent receive buffer
         with the same `where(eff, cand, stale)` select every other
         path uses — so a late delivery is BITWISE a fire deferred to
         its arrival pass with the sender's original payload (the
         contract tests/test_bounded_async.py pins, the way chaos
         pinned drop ≡ not-fired). The per-edge staleness clock
         advances to the committed message's send pass, and commits
         with lag >= 2 count into `late_commits`.
      2. SHIFT: every slot's remaining delay decreases by one.
      3. ENQUEUE: this pass's (candidate, eff) enters at slot lag-1
         (`lag_vec` is pre-clamped to [1, D] — chaos.inject.lag_vector;
         the clamp IS the bound: the fast rank waits rather than run
         further ahead). Two messages landing on the same arrival pass
         merge later-sent-wins: merged candidate
         `where(eff_new, cand_new, cand_old)`, merged eff `old | new` —
         committing the merge is bitwise committing old then new.

    `cands`/`effs` are the flat arena exchange's per-neighbor outputs
    (deliver/integrity verdicts already folded into `effs`);
    `delivered` (bool [n_nb] or None = all True) is the physical
    delivery bit that gates the clock — a chaos-dropped or
    integrity-rejected exchange is not a delivery, so its silence keeps
    the gauge growing. Returns (new_state, visible bufs — post-arrival,
    what this pass mixes with, edge staleness int32 [n_nb], late
    commits this pass int32 []).

    The message-lifecycle ledger (obs.ledger.MessageLedger.queue) keeps
    an int32 COUNT twin of this queue with the same drain/shift/enqueue
    discipline, so the auditor's in-flight balancing term matches this
    engine slot for slot; its `late_committed` row counts leaf-messages
    where the `late_commits` return counts edge-exchanges — same events,
    different units.

    Under an int8 carrier (`state.buf_scales` set) the caller passes
    `cand_scales` — the exchange's per-neighbor [L] dequant scales —
    and both the queue slots and the post-arrival `buf_scales` carry
    them alongside their payloads. The bucketed schedule does not call
    this wrapper: it runs `async_delivery_plan` once and
    `async_bucket_commit` inside each per-bucket commit tail."""
    D = int(bound)
    pass_i = jnp.asarray(pass_num, jnp.int32)
    seg = spec.seg_expand()
    n_nb = len(cands)
    scaled = state.buf_scales is not None
    if scaled and cand_scales is None:
        raise ValueError(
            "async_delivery_commit on an int8-carrier state needs the "
            "exchange's cand_scales (the per-slot dequant scales ride "
            "the queue)"
        )
    here, sent_slots, late_slots, clock, late = async_delivery_plan(
        state, delivered, lag_vec, pass_num, bound
    )
    new_bufs, new_pending, new_bscales = [], [], []
    for i in range(n_nb):
        buf, ncs, nes, nss, bscale = async_bucket_commit(
            state.pending[i], here[i], cands[i], effs[i],
            state.bufs[i], seg,
            cand_scale=cand_scales[i] if scaled else None,
            last_scale=state.buf_scales[i] if scaled else None,
        )
        slots_next = []
        for r in range(D):
            slot = (ncs[r], nes[r], sent_slots[i][r], late_slots[i][r])
            if scaled:
                slot = slot + (nss[r],)
            slots_next.append(slot)
        new_bufs.append(buf)
        new_pending.append(tuple(slots_next))
        if scaled:
            new_bscales.append(bscale)
    new_state = state.replace(
        bufs=tuple(new_bufs),
        pending=tuple(new_pending),
        edge_clock=clock,
        late_commits=state.late_commits + late,
        buf_scales=tuple(new_bscales) if scaled else state.buf_scales,
    )
    return new_state, tuple(new_bufs), pass_i - clock, late


def capacity_gate(
    fire_vec: jnp.ndarray,
    sizes,
    capacity: int,
    priority: "Any" = None,
) -> jnp.ndarray:
    """Admit fired leaves into a static wire budget; defer the overflow.

    Greedy prefix admission over the cumulative fired sizes (one cumsum +
    compare — static shapes) in a stable priority order: leaves flagged in
    `priority` (overdue per max_silence, chaos forced syncs) claim budget
    first, then everything else in leaf order. Returns the effective fire
    bits, always a subset of `fire_vec`; the caller commits the event
    state with them (see `commit`) so a deferred leaf re-contends next
    pass. Greedy means a mid-list overflow can also defer later fired
    leaves that would still have fit — the slack is deliberate: offsets
    must be a pure function of the admitted bits (the receiver recomputes
    them from the wire's fire_vec), and one pass keeps the gate cheap.

    Liveness: with `capacity >= max leaf size` (enforced by
    compact_neighbor_vals) a priority leaf is admitted no later than its
    position in the priority queue drains, so max_silence-overdue leaves
    cannot be starved by ordinary traffic.
    """
    sizes_arr = jnp.asarray(sizes, jnp.int32)
    if priority is None:
        order = jnp.arange(fire_vec.shape[0])
    else:
        pri = jnp.broadcast_to(priority, fire_vec.shape)
        # argsort of the NOT-priority bit is stable: priority-fired leaves
        # first (in leaf order), then the rest (in leaf order)
        order = jnp.argsort(~(pri & fire_vec))
    fire_p = fire_vec[order]
    ends_p = jnp.cumsum(jnp.where(fire_p, sizes_arr[order], 0))
    keep_p = fire_p & (ends_p <= capacity)
    return jnp.zeros_like(fire_vec).at[order].set(keep_p)


def decide_and_update(
    params: Any,
    state: EventState,
    pass_num: jnp.ndarray,
    cfg: EventConfig,
    n_neighbors: int,
    force_fire: "Any" = None,
) -> Tuple[Any, EventState]:
    """One pass of the sender state machine for every parameter at once:
    `propose` + `commit` with the un-gated fire bits (the dense/masked
    exchange paths — no wire budget). Returns (fire, new_state) where
    `fire` is a pytree of bools per param. Compact-wire callers use the
    split form directly so `capacity_gate` can sit between the two."""
    prop = propose(params, state, pass_num, cfg, force_fire=force_fire)
    new_state = commit(state, prop, prop.fire_vec, cfg, n_neighbors)
    leaves, treedef = jax.tree.flatten(params)
    fire = jax.tree.unflatten(
        treedef, [prop.fire_vec[i] for i in range(len(leaves))]
    )
    return fire, new_state
