"""Crash-consistency matrix: kill at every seam, resume, prove parity.

The preemption & crash-consistency acceptance run (ISSUE 8): every
registered crashpoint (chaos/crashpoint.py SITES — the checkpoint
swap's three instants, the async writer thread, both dispatch-block
boundaries, the membership bootstrap stream, the integrity
rollback-restore) is armed under every configuration whose durability
machinery differs (flat arena on/off x dispatch pipeline on/off x
elastic membership x integrity rollback), the child is KILLED there
(`os._exit`, no unwind — the honest model of SIGKILL/power loss),
relaunched with `--resume`, and the recovered run must reproduce the
uninterrupted run's final snapshot BITWISE and its per-epoch history
value-for-value. Three verdicts per cell, measured not assumed
(arXiv:1711.00705's discipline):

  * crashed   — the child died at the armed site with CRASHPOINT_EXIT
                (an unfired site would read as "survived" vacuously);
  * resumed   — the relaunch found a loadable snapshot and completed;
  * parity    — final state bitwise vs the uninterrupted twin, history
                records value-equal epoch-for-epoch, and the recomputed
                epochs bounded by one --save-every interval.

Plus the GRACEFUL preemption legs: a scheduled `preempt=E@S` notice and
a real SIGTERM, each expected to exit PREEMPTED_EXIT, leave a PREEMPTED
marker next to a boundary snapshot, and lose at most ONE dispatch block
(measured as re-computed epochs in the resumed log — the ISSUE 8 bound;
with the boundary force-snapshot it is zero).

Output: artifacts/crash_matrix_<platform>.json, validated against
`tools/validate_artifacts.CRASH_MATRIX_SCHEMA` (tier-1 gated by
tests/test_artifacts.py: zero unresumable cells, zero silent data loss,
preemption within the one-block bound).

Usage:
    python tools/crash_matrix.py [--smoke] [--out artifacts/...]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

# CPU proxy by design (the artifact is crash_matrix_cpu.json): pin THIS
# process and every child to the CPU backend, and make the package
# importable from the children regardless of install state
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PYTHONPATH"] = (
    _ROOT + os.pathsep + os.environ.get("PYTHONPATH", "")
).rstrip(os.pathsep)

from eventgrad_tpu.utils import compile_cache  # noqa: E402

compile_cache.honor_cpu_pin()
compile_cache.enable()

import jax  # noqa: E402
import numpy as np  # noqa: E402

from eventgrad_tpu.exitcodes import (  # noqa: E402
    CRASHPOINT_EXIT, PREEMPTED_EXIT,
)

#: one shared op point: 4-rank ring MLP, 6 epochs x 6 steps, snapshots
#: every 2 epochs — small enough that ~60 child runs stay in minutes,
#: structured enough that every seam (async writer, bootstrap stream,
#: retention, rollback) actually executes
_OP = dict(
    ranks=4, epochs=6, n_synth=192, batch=8, save_every=2, seed=0,
)

#: history keys compared value-for-value between the recovered and the
#: uninterrupted log (host-timing and block-bookkeeping keys differ by
#: construction: wall_s, dispatch_block/cold, riders)
_VALUE_KEYS = (
    "loss", "train_acc", "num_events", "num_deferred", "msgs_saved_pct",
    "fired_frac", "sent_bytes_per_step_per_chip",
    "sent_bytes_wire_real_per_step_per_chip", "active_ranks",
    "wire_rejects", "quarantined_steps", "integrity_rollbacks",
)

#: the ckpt.*/loop.* sites fire in every configuration; the other three
#: only where their subsystem runs
_COMMON_SITES = {
    # hit 2 = the epoch-4 save / the second block: mid-run progress
    # exists on both sides of the kill
    "ckpt.tmp_written": 2,
    "ckpt.mid_swap": 1,     # first demotion = save #2 (epoch 4)
    "ckpt.post_promote": 2,
    "loop.block_dispatched": 2,
    "loop.block_end": 2,
}

#: config name -> (extra CLI flags, {site: hit_n})
_CONFIGS: Dict[str, Tuple[List[str], Dict[str, int]]] = {
    "arena_pipe": (
        ["--arena", "on", "--pipeline", "on"],
        {**_COMMON_SITES, "writer.bg_save": 2},
    ),
    "tree_pipe": (
        ["--arena", "off", "--pipeline", "on"],
        {**_COMMON_SITES, "writer.bg_save": 2},
    ),
    "arena_serial": (
        ["--arena", "on", "--pipeline", "off"],
        dict(_COMMON_SITES),
    ),
    "membership": (
        # leave at 2, join at 4: the join streams a neighbor snapshot
        # through the bootstrap path mid-matrix
        ["--membership", "leave=1@2,join=1@4"],
        {**_COMMON_SITES, "membership.bootstrap": 1},
    ),
    "integrity": (
        # quarantine OFF so the seeded nanstep LANDS (epoch 3, pass 14),
        # trips the sentinel, and exercises the rollback-restore;
        # escalate hardens the replay so it converges
        ["--integrity",
         "checksum=0,quarantine=0,sentinel=1,rollback=1,escalate=1,"
         "max_rollbacks=1",
         "--chaos", "drop=0,seed=3,nanstep=1@14"],
        {**_COMMON_SITES, "integrity.rollback": 1},
    ),
}

_SMOKE_CONFIGS = ("arena_pipe", "membership")


def _cli(tmp: str, tag: str, extra: List[str]) -> List[str]:
    return [
        sys.executable, "-m", "eventgrad_tpu.cli",
        "--algo", "eventgrad", "--mesh", f"ring:{_OP['ranks']}",
        "--dataset", "synthetic", "--model", "mlp",
        "--epochs", str(_OP["epochs"]), "--batch-size", str(_OP["batch"]),
        "--n-synth", str(_OP["n_synth"]), "--warmup-passes", "2",
        "--max-silence", "8", "--lr", "0.1", "--seed", str(_OP["seed"]),
        "--save-every", str(_OP["save_every"]),
        "--log-file", os.path.join(tmp, f"{tag}.jsonl"),
    ] + extra


def _run_child(
    tmp: str, tag: str, extra: List[str],
    crashpoint: Optional[str] = None, timeout: float = 300.0,
) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env.pop("EG_CRASHPOINT", None)
    if crashpoint:
        env["EG_CRASHPOINT"] = crashpoint
    return subprocess.run(
        _cli(tmp, tag, extra), env=env, capture_output=True, text=True,
        timeout=timeout,
    )


def _records(tmp: str, *tags: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    for tag in tags:
        path = os.path.join(tmp, f"{tag}.jsonl")
        if os.path.exists(path):
            with open(path) as f:
                out += [json.loads(line) for line in f if line.strip()]
    return out


def _epoch_recs(recs: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Training epoch records only: the terminal `preempted` record
    carries an epoch too (the drained boundary) but no metrics."""
    return [r for r in recs if "epoch" in r and "loss" in r]


def _by_epoch(recs: List[Dict[str, Any]]) -> Dict[int, Dict[str, Any]]:
    """Last record per epoch — an integrity replay (and a resumed
    attempt) legitimately re-emits an epoch; the final word must match."""
    out: Dict[int, Dict[str, Any]] = {}
    for r in _epoch_recs(recs):
        out[int(r["epoch"])] = r
    return out


def _history_equal(
    ref: List[Dict[str, Any]], got: List[Dict[str, Any]]
) -> Tuple[bool, str]:
    a, b = _by_epoch(ref), _by_epoch(got)
    if set(a) != set(b):
        return False, f"epoch sets differ: {sorted(set(a) ^ set(b))}"
    for e in sorted(a):
        for k in _VALUE_KEYS:
            if (k in a[e]) != (k in b[e]):
                return False, f"epoch {e}: key {k} presence differs"
            if k in a[e] and a[e][k] != b[e][k]:
                return False, f"epoch {e}: {k} {a[e][k]!r} != {b[e][k]!r}"
    return True, ""


def _final_state_equal(ck_ref: str, ck_got: str) -> bool:
    from eventgrad_tpu.utils import checkpoint

    ref = checkpoint.peek(checkpoint.latest(os.path.join(ck_ref, "ckpt")))
    got = checkpoint.peek(checkpoint.latest(os.path.join(ck_got, "ckpt")))
    if int(np.asarray(ref["epoch"])) != int(np.asarray(got["epoch"])):
        return False
    ra, rb = jax.tree.leaves(ref["state"]), jax.tree.leaves(got["state"])
    return len(ra) == len(rb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(ra, rb)
    )


def _lost_epochs(
    first_recs: List[Dict[str, Any]], resume_recs: List[Dict[str, Any]]
) -> int:
    """Epochs the recovery RECOMPUTED: logged by the killed attempt and
    logged again by the resume (zero when the kill landed at/behind the
    newest snapshot)."""
    a = {int(r["epoch"]) for r in _epoch_recs(first_recs)}
    b = {int(r["epoch"]) for r in _epoch_recs(resume_recs)}
    return len(a & b)


def _crash_cell(
    workdir: str, config: str, extra: List[str], site: str, hit: int,
    baseline_recs: List[Dict[str, Any]], ck_base: str,
) -> Dict[str, Any]:
    tmp = os.path.join(workdir, f"{config}--{site.replace('.', '_')}")
    os.makedirs(tmp, exist_ok=True)
    ck = os.path.join(tmp, "ck")
    flags = extra + ["--checkpoint-dir", ck]
    cell: Dict[str, Any] = {
        "config": config, "site": site, "hit": hit,
        "crashed": False, "resumed": False,
        "final_state_bitwise": False, "history_bitwise": False,
        "lost_epochs": -1,
    }
    killed = _run_child(tmp, "crash", flags, crashpoint=f"{site}:{hit}")
    cell["crash_exit"] = killed.returncode
    if killed.returncode != CRASHPOINT_EXIT or (
        f"crashpoint {site} hit" not in killed.stderr
    ):
        cell["error"] = (
            f"kill did not land: rc={killed.returncode} "
            f"stderr={killed.stderr[-500:]}"
        )
        return cell
    cell["crashed"] = True
    resumed = _run_child(tmp, "resume", flags + ["--resume"])
    if resumed.returncode != 0:
        cell["error"] = (
            f"resume failed: rc={resumed.returncode} "
            f"stderr={resumed.stderr[-500:]}"
        )
        return cell
    cell["resumed"] = True
    crash_recs = _records(tmp, "crash")
    resume_recs = _records(tmp, "resume")
    cell["lost_epochs"] = _lost_epochs(crash_recs, resume_recs)
    ok, why = _history_equal(baseline_recs, crash_recs + resume_recs)
    cell["history_bitwise"] = ok
    if not ok:
        cell["error"] = f"history: {why}"
    cell["final_state_bitwise"] = _final_state_equal(ck_base, ck)
    if not cell["final_state_bitwise"]:
        cell.setdefault("error", "final snapshot differs")
    return cell


def _preempt_cell(
    workdir: str, kind: str, extra: List[str],
    baseline_recs: List[Dict[str, Any]], ck_base: str,
) -> Dict[str, Any]:
    """One graceful-preemption leg: scheduled notice or a real SIGTERM.
    Expected: exit PREEMPTED_EXIT, PREEMPTED marker next to a boundary
    snapshot, resume bitwise, recomputed work <= one dispatch block
    (with one-epoch blocks: <= 1 epoch; the boundary snapshot makes it
    0)."""
    tmp = os.path.join(workdir, f"preempt--{kind}")
    os.makedirs(tmp, exist_ok=True)
    ck = os.path.join(tmp, "ck")
    flags = extra + ["--checkpoint-dir", ck]
    cell: Dict[str, Any] = {
        "kind": kind, "exit": None, "marker": False,
        "final_state_bitwise": False, "history_bitwise": False,
        "lost_blocks": -1,
    }
    env = dict(os.environ)
    env.pop("EG_CRASHPOINT", None)
    if kind == "schedule":
        proc = subprocess.run(
            _cli(tmp, "preempt", flags), env=env, capture_output=True,
            text=True, timeout=300,
        )
        rc = proc.returncode
    else:  # kind == "signal": SIGTERM once training visibly progresses
        log = os.path.join(tmp, "preempt.jsonl")
        # stderr to a FILE, not a pipe: nobody drains a pipe while the
        # child runs, and a chatty child blocking on a full pipe buffer
        # would never reach the block boundary the SIGTERM drains at
        stderr_f = open(os.path.join(tmp, "preempt.stderr"), "w")
        child = subprocess.Popen(
            _cli(tmp, "preempt", flags), env=env,
            stdout=subprocess.DEVNULL, stderr=stderr_f, text=True,
        )
        deadline = time.time() + 240
        while time.time() < deadline:
            if os.path.exists(log) and any(
                "epoch" in r for r in _records(tmp, "preempt")
            ):
                break
            if child.poll() is not None:
                break
            time.sleep(0.2)
        if child.poll() is None:
            child.send_signal(signal.SIGTERM)
        rc = child.wait(timeout=120)
        stderr_f.close()
    cell["exit"] = rc
    if rc != PREEMPTED_EXIT:
        cell["error"] = f"expected exit {PREEMPTED_EXIT}, got {rc}"
        return cell
    cell["marker"] = os.path.exists(os.path.join(ck, "PREEMPTED"))
    first_recs = _records(tmp, "preempt")
    pre = next((r for r in first_recs if r.get("preempted")), None)
    if pre is not None:
        cell["reason"] = pre.get("reason")
        cell["drain_epoch"] = pre.get("epoch")
        cell["drain_s"] = pre.get("drain_s")
    resumed = _run_child(tmp, "resume", flags + ["--resume"])
    if resumed.returncode != 0:
        cell["error"] = f"resume failed: rc={resumed.returncode}"
        return cell
    resume_recs = _records(tmp, "resume")
    # one-epoch dispatch blocks at this op point: recomputed epochs ARE
    # recomputed blocks
    cell["lost_blocks"] = _lost_epochs(first_recs, resume_recs)
    ok, why = _history_equal(baseline_recs, first_recs + resume_recs)
    cell["history_bitwise"] = ok
    cell["final_state_bitwise"] = _final_state_equal(ck_base, ck)
    if not ok:
        cell["error"] = f"history: {why}"
    elif not cell["final_state_bitwise"]:
        cell["error"] = "final snapshot differs"
    return cell


def run_matrix(
    out_path: str, smoke: bool = False, workdir: Optional[str] = None,
) -> Dict[str, Any]:
    import tempfile

    t_start = time.perf_counter()
    configs = {
        k: v for k, v in _CONFIGS.items()
        if not smoke or k in _SMOKE_CONFIGS
    }
    ctx = tempfile.TemporaryDirectory() if workdir is None else None
    root = workdir if workdir is not None else ctx.name
    os.makedirs(root, exist_ok=True)
    cells: List[Dict[str, Any]] = []
    preempt_cells: List[Dict[str, Any]] = []
    try:
        baselines: Dict[str, Tuple[List[Dict[str, Any]], str]] = {}
        for config, (extra, _sites) in configs.items():
            tmp = os.path.join(root, f"{config}--base")
            os.makedirs(tmp, exist_ok=True)
            ck = os.path.join(tmp, "ck")
            base = _run_child(
                tmp, "base", extra + ["--checkpoint-dir", ck]
            )
            if base.returncode != 0:
                raise RuntimeError(
                    f"uninterrupted {config} baseline failed: "
                    f"{base.stderr[-1000:]}"
                )
            baselines[config] = (_records(tmp, "base"), ck)
            print(f"[baseline] {config}: ok", flush=True)

        for config, (extra, sites) in configs.items():
            base_recs, ck_base = baselines[config]
            for site, hit in sites.items():
                cell = _crash_cell(
                    root, config, extra, site, hit, base_recs, ck_base
                )
                cells.append(cell)
                verdict = "OK" if (
                    cell["crashed"] and cell["resumed"]
                    and cell["final_state_bitwise"]
                    and cell["history_bitwise"]
                ) else f"FAIL ({cell.get('error')})"
                print(
                    f"[cell] {config} x {site}:{hit} -> {verdict} "
                    f"(lost {cell['lost_epochs']} epochs)", flush=True,
                )

        # graceful preemption legs ride the pipeline-on arena config;
        # the scheduled leg needs a chaos rider in BOTH legs (the chaos
        # state is part of the traced step), so it gets its own baseline
        if "arena_pipe" in configs:
            extra = configs["arena_pipe"][0]
            sched_extra = extra + ["--chaos", "drop=0,seed=7,preempt=3@2"]
            sched_base_extra = extra + ["--chaos", "drop=0,seed=7"]
            tmpb = os.path.join(root, "preempt--base")
            os.makedirs(tmpb, exist_ok=True)
            ckb = os.path.join(tmpb, "ck")
            base = _run_child(
                tmpb, "base", sched_base_extra + ["--checkpoint-dir", ckb]
            )
            if base.returncode != 0:
                raise RuntimeError(
                    f"preempt baseline failed: {base.stderr[-1000:]}"
                )
            preempt_cells.append(_preempt_cell(
                root, "schedule", sched_extra, _records(tmpb, "base"), ckb,
            ))
            preempt_cells.append(_preempt_cell(
                root, "signal", extra, *baselines["arena_pipe"],
            ))
            for c in preempt_cells:
                verdict = "OK" if (
                    c["exit"] == PREEMPTED_EXIT and c["marker"]
                    and c["final_state_bitwise"] and c["history_bitwise"]
                    and 0 <= c["lost_blocks"] <= 1
                ) else f"FAIL ({c.get('error')})"
                print(f"[preempt] {c['kind']} -> {verdict}", flush=True)
    finally:
        if ctx is not None:
            ctx.cleanup()

    unresumable = sum(
        1 for c in cells if not (c["crashed"] and c["resumed"])
    )
    silent_loss = sum(
        1 for c in cells
        if c["resumed"] and not (
            c["final_state_bitwise"] and c["history_bitwise"]
        )
    )
    out = {
        "bench": "crash_matrix",
        "platform": jax.default_backend(),
        "mode": "smoke" if smoke else "full",
        "op_point": dict(_OP, model="mlp", algo="eventgrad"),
        "configs": {k: " ".join(v[0]) for k, v in configs.items()},
        "exit_codes": {
            "crashpoint": CRASHPOINT_EXIT, "preempted": PREEMPTED_EXIT,
        },
        "n_cells": len(cells),
        "cells": cells,
        "unresumable": unresumable,
        "silent_data_loss": silent_loss,
        # recomputation bound per cell: one save interval of snapshot
        # age, PLUS one more under the dispatch pipeline — a kill
        # inside the ASYNC epoch-E save (ckpt.mid_swap et al.) falls
        # back to the epoch E-save_every snapshot while the main loop
        # legitimately ran ahead to the next join barrier (the E+
        # save_every save). Measured worst case: 2 * save_every.
        "recovery_bound_epochs": 2 * _OP["save_every"],
        "recovery_ok": bool(cells) and all(
            0 <= c["lost_epochs"] <= 2 * _OP["save_every"] for c in cells
        ),
        "preemption": {"cells": preempt_cells},
        "wall_s": round(time.perf_counter() - t_start, 1),
    }
    if out_path:
        os.makedirs(
            os.path.dirname(os.path.abspath(out_path)), exist_ok=True
        )
        with open(out_path, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="two configs instead of five (same schema; the "
                         "committed artifact uses the full matrix)")
    ap.add_argument("--workdir", default=None,
                    help="keep per-cell checkpoints/logs here instead of "
                         "a temp dir (debugging)")
    ap.add_argument("--out", default=os.path.join(
        _ROOT, "artifacts", f"crash_matrix_{jax.default_backend()}.json"
    ))
    args = ap.parse_args(argv)
    out = run_matrix(args.out, smoke=args.smoke, workdir=args.workdir)
    print(json.dumps(
        {k: v for k, v in out.items() if k not in ("cells", "preemption")},
        indent=1, sort_keys=True,
    ))

    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "validate_artifacts",
        os.path.join(_ROOT, "tools", "validate_artifacts.py"),
    )
    va = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(va)
    errs = va.validate(out, va.CRASH_MATRIX_SCHEMA)
    for e in errs:
        print(f"CRASH_MATRIX_SCHEMA violation: {e}", file=sys.stderr)
    return 1 if errs else 0


if __name__ == "__main__":
    sys.exit(main())
