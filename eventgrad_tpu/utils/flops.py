"""FLOPs accounting and MFU — the perf yardstick the reference never had.

The reference reports only whole-run wall-clock (`MPI_Wtime`,
/root/reference/dmnist/cent/cent.cpp:98,158-161). A TPU framework is judged
on model-FLOPs utilization: analytic FLOPs of the compiled step program
(XLA's own cost model, so convs/matmuls/fusions are counted as compiled,
not hand-estimated) divided by measured step time and the chip's peak.

`compiled_flops` works on any backend (the CPU test mesh included);
`chip_peak_flops` knows the public bf16 peaks of recent TPU generations and
returns 0.0 for unknown/non-TPU devices, making `mfu()` return None there —
an MFU against an unknown peak would be noise, not a metric.
"""

from __future__ import annotations

from typing import Any, Optional

import jax

from eventgrad_tpu.obs.devicespec import TPU_SPECS

#: public peak dense-matmul throughput (bf16 FLOP/s) by device-kind
#: substring, most-specific first — read from the one spec table
#: (obs/devicespec.py) so MFU here and the roofline in obs.costmodel can
#: never disagree about the peak.
PEAK_FLOPS_BY_KIND = tuple(
    (sub, spec.peak_flops) for sub, spec in TPU_SPECS
)


def chip_peak_flops(device: Optional[Any] = None) -> float:
    """Peak bf16 FLOP/s of one chip; 0.0 when unknown (non-TPU backends).

    Contract kept from before the devicespec table: non-TPU backends get
    0.0 here (so `mfu()` stays None off-chip); callers that WANT the
    nominal generic-cpu tracking spec use obs.devicespec.device_spec."""
    device = device or jax.devices()[0]
    if device.platform != "tpu":
        return 0.0
    kind = device.device_kind.lower()
    for sub, peak in PEAK_FLOPS_BY_KIND:
        if sub in kind:
            return peak
    return 0.0


def compiled_flops(fn, *args, **kwargs) -> float:
    """Analytic FLOPs of one call of jit-able `fn` at these args, from the
    compiled executable's cost analysis. 0.0 if the backend reports none."""
    try:
        lowered = jax.jit(fn).lower(*args, **kwargs)
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, (list, tuple)):  # old jax returns [dict]
            cost = cost[0] if cost else {}
        return float(cost.get("flops", 0.0))
    except Exception:
        return 0.0


def step_layout_kwargs(state) -> dict:
    """make_train_step kwargs matching the LAYOUT of `state`'s event
    buffers. train() may have auto-enabled the flat arena (bufs carried
    as flat arrays) or the bucketed schedule (per-bucket tuples of
    arrays); tracing a tree-layout step against such a state fails with
    a pytree-structure error — which compiled_flops' guard used to
    swallow into a silent 0.0 FLOPs / None MFU. One detector shared by
    train_step_flops and obs.costmodel.analyze_step."""
    ev = getattr(state, "event", None)
    bufs = getattr(ev, "bufs", None) or ()
    if not bufs:
        return {}
    first = bufs[0]
    out = None
    if isinstance(first, tuple):  # per-neighbor tuple of per-bucket bufs
        out = {"arena": True, "bucketed": len(first)}
        first = first[0]
    elif getattr(first, "ndim", None) is not None:  # flat [.., n] array
        out = {"arena": True}
    if out is None:
        return {}  # per-neighbor pytrees: the tree layout
    # carrier-resident arena: the buffers live in the WIRE dtype (f32-
    # resident states always carry f32 buffers, whatever the wire), so
    # the dtype alone names the layout — a carrier step must be traced
    # with the matching wire or the commit select's dtypes disagree
    dt = str(getattr(first, "dtype", ""))
    wire = {"int8": "int8", "bfloat16": "bf16"}.get(dt)
    if wire is not None:
        out.update(carrier_resident=True, wire=wire)
    return out


def train_step_flops(model, tx, topo, algo, event_cfg, x, y,
                     per_rank: int, state) -> float:
    """Analytic FLOPs of one full train step (all vmap-ranks) of the given
    algo/model at per-rank batch size — the bench/flagship MFU numerator.
    One definition shared by bench.py and tools/tpu_flagship.py so the two
    MFU figures can never diverge."""
    import jax.numpy as jnp

    from eventgrad_tpu.parallel.spmd import spmd
    from eventgrad_tpu.train.steps import make_train_step

    step = make_train_step(
        model, tx, topo, algo, event_cfg=event_cfg,
        **step_layout_kwargs(state),
    )
    xb = jnp.asarray(x[: topo.n_ranks * per_rank]).reshape(
        (topo.n_ranks, per_rank) + x.shape[1:]
    )
    yb = jnp.asarray(y[: topo.n_ranks * per_rank]).reshape(
        (topo.n_ranks, per_rank)
    )
    return compiled_flops(spmd(step, topo), state, (xb, yb))


def mfu(flops_per_step: float, step_seconds: float,
        device: Optional[Any] = None) -> Optional[float]:
    """Model-FLOPs utilization of ONE device running `flops_per_step` every
    `step_seconds`. None when either input or the chip peak is unknown.

    For the single-chip rank simulator (vmap over 8 ranks on one chip) pass
    the TOTAL step FLOPs: all ranks' work runs on the one chip, so the
    quotient is that chip's true utilization."""
    peak = chip_peak_flops(device)
    if not (peak and flops_per_step and step_seconds):
        return None
    return flops_per_step / (step_seconds * peak)
