"""Persistent XLA compilation cache for the launcher/bench entry points.

First compilation of the flagship ResNet train step costs tens of seconds
on TPU; the reference pays nothing comparable (its "compile" is cmake,
once). Caching compiled executables on disk makes every run after the
first start in milliseconds — including separate processes, so the bench
harness and repeated CLI invocations don't re-pay XLA.

Off by default for library use; entry points opt in via `enable()`.
`EG_COMPILE_CACHE=off` disables, `EG_COMPILE_CACHE=<dir>` relocates
(default: `<repo>/.jax_cache`, git-ignored).
"""

from __future__ import annotations

import os

import jax

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def honor_cpu_pin() -> None:
    """Honor an explicit JAX_PLATFORMS=cpu env pin over accelerator plugins
    that registered themselves ahead of it (jax config may read
    "plugin,cpu"). Must run before the first backend use; shared by the
    CLI and bench entry points."""
    if os.environ.get("JAX_PLATFORMS") == "cpu" and jax.config.jax_platforms != "cpu":
        jax.config.update("jax_platforms", "cpu")


def enable(path: str | None = None) -> str | None:
    """Turn on the persistent compilation cache; returns the dir (or None
    when disabled via EG_COMPILE_CACHE=off/0)."""
    path = path or os.environ.get("EG_COMPILE_CACHE") or os.path.join(
        _REPO_ROOT, ".jax_cache"
    )
    if path.lower() in ("0", "off", "none"):
        return None
    # XLA:CPU AOT reload is brittle across host-feature detection (loader
    # warns about possible SIGILL); the compile-time win is a TPU concern,
    # so skip caching when the process resolves to the CPU backend. Prefer
    # the config pin — resolving the backend initializes it, which callers
    # may not be ready for (jax.distributed.initialize must come first).
    plats = (jax.config.jax_platforms or "").split(",")
    backend = plats[0] if plats and plats[0] else jax.default_backend()
    if backend == "cpu":
        return None
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # cache every executable, not just the slowest ones
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    return path
