"""Measure the MNIST savings knee vs pass count (VERDICT round-2 item 3).

The driver-captured reduced tier reported 61.6% saved at 180 passes
(reference-pure trigger) — below the reference's ~70% headline
(/root/reference/README.md:4) — while the stabilized full-scale op-point
measures 75.5% at 1168 passes. This sweep maps msgs-saved-% (and test
accuracy, so savings at collapsed accuracy can't masquerade as wins)
against pass count for the candidate reduced-tier MNIST op-points, with
per-leg wall cost, to pick the cheapest config whose savings cross ~70%
inside the reduced tier's budget.

Writes artifacts/mnist_knee_r3_cpu.jsonl (one JSON line per config).

Usage: python tools/mnist_knee.py [quick]
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax


def main() -> None:
    jax.config.update("jax_platforms", "cpu")

    from eventgrad_tpu.data.datasets import load_or_synthesize
    from eventgrad_tpu.models import CNN2
    from eventgrad_tpu.parallel.events import EventConfig
    from eventgrad_tpu.parallel.topology import Ring
    from eventgrad_tpu.train.loop import consensus_params, evaluate, rank0_slice, train

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out_path = os.path.join(repo, "artifacts", "mnist_knee_r3_cpu.jsonl")
    topo = Ring(8)
    quick = len(sys.argv) > 1 and sys.argv[1] == "quick"

    # (n_train, epochs, horizon, max_silence) candidates. batch 64/rank,
    # lr 0.05, sequential sampler = the reference MNIST op-point
    # (dmnist/event/event.cpp:103,145,227,255). Reference-pure rows map
    # the pure knee; stabilized rows test whether the guard keeps the
    # accuracy-fragile miniature honest at higher pass counts.
    # round-3 findings so far (artifacts/mnist_knee_r3_cpu.jsonl):
    # reference-pure plateaus (61.6@180, 62.3@360, 64.2@540, 66.1@544x2data)
    # and stabilized 1.05+guard50 collapses at miniature scale (81.7% saved
    # but 36.5% acc at 360 passes). Phase 2: intermediate horizons.
    grid = [
        (2048, 45, 1.0, 0),      # wall-calibration rerun (vectorized events)
        (2048, 90, 1.01, 50),    # gentle growth + guard, 360 passes
        (2048, 90, 1.02, 50),
        (2048, 90, 1.03, 50),
        (2048, 90, 1.02, 25),    # tighter guard
        (4096, 68, 1.02, 50),    # 544 passes, 2x data
    ]
    if quick:
        grid = grid[:2]
    warmup = 10
    if len(sys.argv) > 1 and sys.argv[1] == "r4":
        # round-4 phase: candidate op-points for the budget-adaptive
        # reduced-tier MNIST leg (verdict r3 item 3). Targets: >= 70%
        # saved at accuracy within ~2pp of the ref-pure plateau (97-98),
        # at pass counts a ~300-500 s attempt can afford. Known anchors:
        # 1.02+50 @544p/4096 = 69.96 at 97.4 (350 s); 1.02+50 @360p =
        # 68.69 at 94.2; 1.03+50 @360p = 74.96 at 83.4 (too lossy).
        out_path = os.path.join(repo, "artifacts", "mnist_knee_r4_cpu.jsonl")
        # 5th element (optional) overrides the warmup: the full-scale
        # trail suggests the reference's 30-pass warmup bootstraps the
        # adaptive thresholds better than the short tiers' 10
        grid = [
            (4096, 68, 1.025, 50),      # 544p, between the 1.02 near-miss
            (4096, 68, 1.03, 50),       # 544p, does more data tame 1.03?
            (2048, 95, 1.025, 50),      # 380p, the mid-budget candidate
            (4096, 68, 1.02, 25),       # 544p, tighter guard
            (4096, 70, 1.02, 50),       # 560p, ride the 1.02 trend over 70
            (4096, 68, 1.02, 50, 30),   # 544p near-miss with ref warmup 30
        ]
    elif len(sys.argv) > 1 and sys.argv[1] == "fullscale":
        # r3 confirmation of the claim-level op-point mnist_proven cites
        # (r2: 75.5% at -1.17pp over 1168 passes, warmup 30)
        grid = [(8192, 73, 1.05, 50), (8192, 73, 1.0, 0)]
        warmup = 30

    xt, yt = load_or_synthesize("mnist", None, "test", n_synth=1024)
    for row in grid:
        n_train, epochs, horizon, silence = row[:4]
        row_warmup = row[4] if len(row) > 4 else warmup
        x, y = load_or_synthesize("mnist", None, "train", n_synth=n_train)
        cfg = EventConfig(adaptive=True, horizon=horizon,
                          warmup_passes=row_warmup, max_silence=silence)
        t0 = time.perf_counter()
        state, hist = train(
            CNN2(), topo, x, y, algo="eventgrad", event_cfg=cfg,
            epochs=epochs, batch_size=64, learning_rate=0.05,
            random_sampler=False, log_every_epoch=False,
        )
        wall = time.perf_counter() - t0
        cons = consensus_params(state.params)
        stats0 = rank0_slice(state.batch_stats)
        acc = evaluate(CNN2(), cons, stats0, xt, yt)["accuracy"]
        rec = {
            "n_train": n_train, "epochs": epochs,
            "passes": epochs * (n_train // (64 * topo.n_ranks)),
            "horizon": horizon, "max_silence": silence, "warmup": row_warmup,
            "msgs_saved_pct": round(hist[-1]["msgs_saved_pct"], 2),
            "test_acc": round(acc, 2),
            "wall_s": round(wall, 1),
        }
        with open(out_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
