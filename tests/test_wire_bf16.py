"""bfloat16 wire format: gossip payloads downcast for the transfer (half
the ICI/DCN bytes of the reference's float32 MPI wire), upcast on receipt;
local parameters, event norms, and thresholds stay full precision."""

import jax
import jax.numpy as jnp
import numpy as np

from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.loop import train


def _go(algo, wire_bf16, **kw):
    x, y = synthetic_dataset(128, (28, 28, 1), seed=6)
    kw.setdefault(
        "event_cfg", EventConfig(adaptive=True, horizon=0.9, warmup_passes=2)
    )
    return train(
        MLP(), Ring(4), x, y,
        algo=algo, epochs=2, batch_size=8, learning_rate=0.05,
        seed=1, log_every_epoch=False, wire_bf16=wire_bf16, **kw,
    )


def test_bytes_halve_and_training_stays_close():
    state32, hist32 = _go("eventgrad", False)
    state16, hist16 = _go("eventgrad", True)
    # accounting: same fired pattern costs half the bytes on the wire
    assert hist16[0]["num_events"] == hist32[0]["num_events"]
    np.testing.assert_allclose(
        hist16[0]["sent_bytes_per_step_per_chip"],
        hist32[0]["sent_bytes_per_step_per_chip"] / 2,
    )
    # training dynamics stay in the same regime (bf16 has ~3 decimal digits)
    assert abs(hist16[-1]["loss"] - hist32[-1]["loss"]) < 0.1
    for a, b in zip(
        jax.tree.leaves(state16.params), jax.tree.leaves(state32.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-2)


def test_threshold0_equivalence_holds_on_bf16_wire():
    """eventgrad with threshold 0 must remain bitwise D-PSGD when both ride
    the bf16 wire (identical rounding on both paths)."""
    cfg0 = EventConfig(adaptive=False, constant=0.0, warmup_passes=0)
    x, y = synthetic_dataset(128, (28, 28, 1), seed=6)
    kw = dict(epochs=2, batch_size=8, learning_rate=0.05, seed=1,
              log_every_epoch=False, wire_bf16=True)
    s_ev, _ = train(MLP(), Ring(4), x, y, algo="eventgrad",
                    event_cfg=cfg0, **kw)
    s_dp, _ = train(MLP(), Ring(4), x, y, algo="dpsgd", **kw)
    for a, b in zip(jax.tree.leaves(s_ev.params), jax.tree.leaves(s_dp.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sparse_wire_bf16_runs_and_counts_6_bytes():
    _, h32 = _go("sp_eventgrad", False)
    _, h16 = _go("sp_eventgrad", True)
    assert h16[0]["num_events"] == h32[0]["num_events"]
    np.testing.assert_allclose(
        h16[0]["sent_bytes_per_step_per_chip"] / h32[0]["sent_bytes_per_step_per_chip"],
        6.0 / 8.0,  # bf16 value + int32 index vs f32 value + int32 index
    )
    assert np.isfinite(h16[-1]["loss"])


def test_cli_wire_bf16_rejects_allreduce():
    import pytest as _pytest

    from eventgrad_tpu.cli import main

    with _pytest.raises(SystemExit, match="--wire"):
        main(["--algo", "allreduce", "--wire-bf16"])
    with _pytest.raises(SystemExit, match="--wire"):
        main(["--algo", "allreduce", "--wire", "int8"])


def test_int8_wire_bytes_quarter_and_training_stays_close():
    # dpsgd always sends dense, so the byte accounting is exact: quarter
    # the values plus one f32 scale per leaf per neighbor (the advisor's
    # round-1 finding — scales ride the wire and must be counted)
    _, d32 = _go("dpsgd", False)
    _, d8 = _go("dpsgd", False, wire="int8")
    n_leaves, n_nb = 4, 2  # MLP tensors; ring neighbors
    np.testing.assert_allclose(
        d8[0]["sent_bytes_per_step_per_chip"],
        d32[0]["sent_bytes_per_step_per_chip"] / 4 + n_nb * 4 * n_leaves,
    )
    # eventgrad dynamics stay in the same regime despite 8-bit rounding
    state32, hist32 = _go("eventgrad", False)
    state8, hist8 = _go("eventgrad", False, wire="int8")
    assert abs(hist8[-1]["loss"] - hist32[-1]["loss"]) < 0.15
    for a, b in zip(
        jax.tree.leaves(state8.params), jax.tree.leaves(state32.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=8e-2)


def test_threshold0_equivalence_holds_on_int8_wire():
    """Both paths quantize the identical payload with identical per-leaf
    scales, so threshold-0 EventGraD equals D-PSGD on the int8 wire up to
    XLA fusion reassociation of the dequant multiply (~1 ulp/step; when
    that ulp lands on a rounding boundary an isolated element shifts one
    quantization grain, so rare outliers reach ~1e-3 over 32 steps —
    bf16's plain cast stays bitwise, see test above)."""
    cfg0 = EventConfig(adaptive=False, constant=0.0, warmup_passes=0)
    x, y = synthetic_dataset(128, (28, 28, 1), seed=6)
    kw = dict(epochs=2, batch_size=8, learning_rate=0.05, seed=1,
              log_every_epoch=False, wire="int8")
    s_ev, _ = train(MLP(), Ring(4), x, y, algo="eventgrad",
                    event_cfg=cfg0, **kw)
    s_dp, _ = train(MLP(), Ring(4), x, y, algo="dpsgd", **kw)
    for a, b in zip(jax.tree.leaves(s_ev.params), jax.tree.leaves(s_dp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-3)


def test_sparse_int8_wire_runs_and_counts_5_bytes():
    # threshold-0 fires every pass, making the byte ratio deterministic
    cfg0 = EventConfig(adaptive=False, constant=0.0, warmup_passes=0)
    kw = dict(event_cfg=cfg0)
    _, h32 = _go("sp_eventgrad", False, **kw)
    _, h8 = _go("sp_eventgrad", False, wire="int8", **kw)
    assert h8[0]["num_events"] == h32[0]["num_events"]
    n_leaves, n_nb = 4, 2  # MLP tensors; ring neighbors
    np.testing.assert_allclose(
        h8[0]["sent_bytes_per_step_per_chip"],
        # int8 value + int32 index vs f32 value + int32 index, plus one
        # f32 quantization scale per leaf per neighbor
        h32[0]["sent_bytes_per_step_per_chip"] * 5.0 / 8.0
        + n_nb * 4 * n_leaves,
    )
    assert np.isfinite(h8[-1]["loss"])


def test_cli_wire_flag_conflict_rejected():
    import pytest as _pytest

    from eventgrad_tpu.cli import main

    with _pytest.raises(SystemExit, match="conflicts"):
        main(["--wire-bf16", "--wire", "int8"])


def test_int8_codec_roundtrip_error_bound():
    """Quantize/dequantize error is bounded by half a grain (scale/2 =
    absmax/254) per element, across magnitudes and signs, and zero maps
    to exactly zero (the masked path's non-fired leaves)."""
    from eventgrad_tpu.parallel.collectives import _int8_decode, _int8_encode

    rng = np.random.default_rng(11)
    for mag in (1e-6, 1.0, 1e4):
        tree = {
            "a": jnp.asarray(mag * rng.standard_normal((17, 5)), jnp.float32),
            "b": jnp.asarray(-mag * rng.random(33), jnp.float32),
            "z": jnp.zeros(9, jnp.float32),
        }
        q, scale_vec, scale_def = _int8_encode(tree)
        back = _int8_decode(q, scale_vec, scale_def, tree)
        for k in ("a", "b"):
            grain = float(np.abs(np.asarray(tree[k])).max()) / 127.0
            err = np.abs(np.asarray(back[k]) - np.asarray(tree[k])).max()
            assert err <= grain / 2 + 1e-12, (k, mag, err, grain)
        np.testing.assert_array_equal(np.asarray(back["z"]), 0.0)
