"""Nested-jaxpr traversal + the op-accounting the regression gates use.

One walker for every consumer (the arena op-count gate in
tests/test_arena.py, the hygiene checks in analysis/audit.py, ad-hoc
prints in tools/): `iter_eqns` yields every equation of a jaxpr
INCLUDING those inside nested call/scan/cond/while/pjit/custom-deriv
sub-jaxprs, so a count or a search can never silently miss ops that
jit/scan wrapping moved one level down.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import jax


def sub_jaxprs(eqn) -> Iterator["jax.core.Jaxpr"]:
    """Every jaxpr nested in an equation's params (pjit's `jaxpr`,
    scan/while/cond bodies, custom_jvp/vjp call jaxprs, ...), as bare
    `jax.core.Jaxpr` objects."""
    for v in eqn.params.values():
        for sub in jax.tree.leaves(
            v,
            is_leaf=lambda x: isinstance(
                x, (jax.core.Jaxpr, jax.core.ClosedJaxpr)
            ),
        ):
            if isinstance(sub, jax.core.ClosedJaxpr):
                yield sub.jaxpr
            elif isinstance(sub, jax.core.Jaxpr):
                yield sub


def iter_eqns(
    jaxpr: "jax.core.Jaxpr", path: Tuple[str, ...] = ()
) -> Iterator[Tuple["jax.core.JaxprEqn", Tuple[str, ...]]]:
    """(eqn, path) for every equation, depth-first through every nested
    sub-jaxpr. `path` names the enclosing primitives (e.g.
    ('scan', 'pjit')) so findings can say WHERE they sit."""
    for eqn in jaxpr.eqns:
        yield eqn, path
        sub_path = path + (eqn.primitive.name,)
        for sub in sub_jaxprs(eqn):
            yield from iter_eqns(sub, sub_path)


def count_primitives(jaxpr, name: Optional[str] = None) -> int:
    """Total equation count (or occurrences of primitive `name`)
    including every nested sub-jaxpr."""
    return sum(
        1 for eqn, _ in iter_eqns(jaxpr) if name is None or eqn.primitive.name == name
    )


def count_full_ravels(jaxpr, n_total: int) -> int:
    """Concatenates materializing a full [n_total] model buffer — the
    per-step footprint of a pytree flatten (the arena op budget's unit;
    under the vmap lift the buffer is [n_ranks, n_total], so the check
    reads the TRAILING dim)."""
    total = 0
    for eqn, _ in iter_eqns(jaxpr):
        if (
            eqn.primitive.name == "concatenate"
            and eqn.outvars[0].aval.shape
            and eqn.outvars[0].aval.shape[-1] == n_total
        ):
            total += 1
    return total


def primitive_census(jaxpr) -> dict:
    """{primitive name: count} over every nested equation — the
    inventory view `tools/audit.py --census` prints."""
    out: dict = {}
    for eqn, _ in iter_eqns(jaxpr):
        out[eqn.primitive.name] = out.get(eqn.primitive.name, 0) + 1
    return out
