"""Messages-saved trajectory at reference-scale pass counts (VERDICT item 4
evidence).

One eventgrad leg per headline config at horizon 1.0 / warmup 30
(the reference's sample adaptive run, dmnist/event/README.md): MNIST CNN-2
at the full 1168-pass op-point (event.cpp:255: 10 epochs x ~117 steps) and
CIFAR tiny-ResNet at 256 passes. Prints a JSON line per config with the
final msgs-saved-% and its trajectory (`trail`) — savings climb as training
converges because parameter-norm drift shrinks, so they must be judged at
the reference pass counts, not short smoke tiers.

The op-points are tools/tune_horizon.py's `run_point` — one definition, so
the sweep artifacts and these curves measure the same config (this script
just runs longer, single-leg, with a trajectory).

Round-2 CPU results committed as artifacts/savings_curve_r2_cpu.jsonl
(four rows, each reproducible by one invocation of this script):
  MNIST 66.2% @1168 passes   -> savings_curve.py 292
  MNIST 70.1% @2336 passes   -> savings_curve.py 584   (the ~70% claim,
    crossed outright; acc saturates the 256-image curve test set — the
    apples-to-apples D-PSGD parity numbers live in
    artifacts/mnist_parity_r2_cpu.json, 512-image set, gap -0.58pp)
  CIFAR 47.4% @256 passes    -> savings_curve.py 292 16   (early point)
  CIFAR 59.3% @1024 passes   -> savings_curve.py 292 64   (rising
    ~0.4pp/128 passes; crosses the ~60% target within the 3904-pass
    flagship scale)

Usage: JAX_PLATFORMS=cpu python tools/savings_curve.py \
           [mnist_epochs=292] [cifar_epochs=64]"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from tune_horizon import run_point  # noqa: E402  (shares the op-points)

if __name__ == "__main__":
    mnist_epochs = int(sys.argv[1]) if len(sys.argv) > 1 else 292
    cifar_epochs = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    # MNIST: 4 steps/epoch (292 -> the 1168-pass reference scale)
    run_point("mnist", 1.0, warmup=30, epochs=mnist_epochs,
              dpsgd_leg=False, trail_every=40)
    # CIFAR: 16 steps/epoch (64 -> 1024 passes)
    run_point("cifar", 1.0, warmup=30, epochs=cifar_epochs,
              dpsgd_leg=False, trail_every=4)
