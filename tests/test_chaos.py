"""Chaos subsystem: deterministic schedules, injection ≡ silence, recovery.

The load-bearing guarantees (ISSUE 1 acceptance):
  * schedules replay bit-for-bit from (seed, pass, rank, edge);
  * an injected drop is BITWISE the same mixing as an event that did not
    fire (chaos composes with EventGraD's stale-buffer semantics, it does
    not approximate them);
  * a drop-rate-0 chaos run is BITWISE the unmodified training loop;
  * ring heal rewires survivors exactly like the (n-1)-ring;
  * the receiver-side forced-sync bound keeps consensus error bounded
    where the unguarded aggressive trigger diverges.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from eventgrad_tpu.chaos import inject, monitor
from eventgrad_tpu.chaos.policy import (
    RecoveryPolicy, apply_ring_heal, heal_ring,
)
from eventgrad_tpu.chaos.schedule import ChaosSchedule, FlakyWindow
from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP
from eventgrad_tpu.parallel import collectives
from eventgrad_tpu.parallel.events import EventConfig, decide_and_update
from eventgrad_tpu.parallel.spmd import spmd, stack_for_ranks
from eventgrad_tpu.parallel.topology import Ring, Topology
from eventgrad_tpu.train.loop import train
from eventgrad_tpu.train.state import init_train_state
from eventgrad_tpu.train.steps import make_train_step
from tools import chaos_sweep

#: one bitwise comparator and one step-at-a-time chaos harness, shared
#: with the sweep tool instead of duplicated here
_leaves_equal_bitwise = chaos_sweep._params_equal_bitwise


# --- (a) schedule determinism + serialization --------------------------


def test_schedule_spec_and_dict_round_trip():
    s = ChaosSchedule(
        seed=7, drop_p=0.2, flaky=(FlakyWindow(10, 20, 0.8),),
        deliver_every=3, death=((3, 500),),
    )
    assert ChaosSchedule.parse(s.to_spec()) == s
    assert ChaosSchedule.from_dict(s.to_dict()) == s
    assert ChaosSchedule.parse("drop=0").is_noop
    assert not s.is_noop
    with pytest.raises(ValueError):
        ChaosSchedule.parse("drop=1.5")
    with pytest.raises(ValueError):
        ChaosSchedule.parse("bogus")


def test_delay_and_lag_round_trip_side_by_side():
    """The two delay vocabularies round-trip independently: `delay=k`
    (deliver_every — k-pass THINNING, skipped payloads lost) and the
    true queueing-delay clauses `lag=S-E@d` / `slow=R@f` (payload
    preserved, committed on arrival by the bounded-async engine) —
    documented side by side in chaos/schedule.py."""
    from eventgrad_tpu.chaos.schedule import LagWindow

    # thinning alone
    thin = ChaosSchedule(seed=1, deliver_every=4)
    assert ChaosSchedule.parse(thin.to_spec()) == thin
    assert ChaosSchedule.from_dict(thin.to_dict()) == thin
    assert "delay=4" in thin.to_spec()
    # queueing delay alone
    lagged = ChaosSchedule(
        seed=1, lag=(LagWindow(50, 90, 3),), slow=((2, 6),),
    )
    assert ChaosSchedule.parse(lagged.to_spec()) == lagged
    assert ChaosSchedule.from_dict(lagged.to_dict()) == lagged
    assert "lag=50-90@3" in lagged.to_spec()
    assert "slow=2@6" in lagged.to_spec()
    assert lagged.has_lags and not lagged.is_noop
    assert lagged.max_scheduled_lag() == 6
    # both at once (they model different faults and compose)
    both = ChaosSchedule.parse("seed=1,delay=4,lag=50-90@3,slow=2@6")
    assert both.deliver_every == 4 and both.has_lags
    assert ChaosSchedule.parse(both.to_spec()) == both
    # bare lag=d covers the whole run; legacy dicts (no lag keys)
    # round-trip unchanged
    assert ChaosSchedule.parse("lag=2").max_scheduled_lag() == 2
    assert "lag" not in thin.to_dict() and "slow" not in thin.to_dict()
    with pytest.raises(ValueError):
        ChaosSchedule.parse("lag=10-20@0")  # lag >= 1
    with pytest.raises(ValueError):
        ChaosSchedule.parse("slow=2@0")


def test_schedule_deterministic_under_fixed_seed():
    topo = Ring(4)
    s = ChaosSchedule(seed=7, drop_p=0.3, flaky=(FlakyWindow(5, 9, 1.0),))
    t1 = inject.delivery_table(s, topo, 20)
    t2 = inject.delivery_table(s, topo, 20)
    np.testing.assert_array_equal(t1, t2)
    t3 = inject.delivery_table(
        ChaosSchedule(seed=8, drop_p=0.3, flaky=s.flaky), topo, 20
    )
    assert not np.array_equal(t1, t3), "seed must matter"
    # blackout window drops everything; noop schedule drops nothing
    assert not t1[4:8].any()  # passes 5..8 (table starts at pass 1)
    assert inject.delivery_table(ChaosSchedule(seed=7), topo, 8).all()


def test_in_step_mask_matches_host_table():
    """The SPMD-context mask (lax.axis_index identity) must be the same
    bits as the host replay table — it IS the ground truth artifact."""
    topo = Ring(4)
    s = ChaosSchedule(seed=11, drop_p=0.5, death=((2, 4),))
    table = inject.delivery_table(s, topo, 8)
    for pass_num in (1, 4, 7):
        def fn(_x, _p=pass_num):
            return inject.delivery_mask(s, topo, jnp.int32(_p))

        got = np.asarray(spmd(fn, topo)(jnp.zeros(4)))
        np.testing.assert_array_equal(got, table[pass_num - 1])


def test_death_silences_both_directions():
    topo = Ring(4)
    s = ChaosSchedule(seed=0, death=((1, 3),))
    t = inject.delivery_table(s, topo, 10)
    srcs = np.array(
        [[topo.neighbor_source(r, nb) for nb in topo.neighbors]
         for r in range(4)]
    )
    for p in range(10):
        for r in range(4):
            for e in range(2):
                dead = (p + 1) >= 3 and (r == 1 or srcs[r, e] == 1)
                assert t[p, r, e] == (not dead), (p, r, e)


# --- (b) injected drop ≡ event that did not fire (bitwise) -------------


def test_drop_bitwise_equals_not_fired():
    topo = Ring(4)
    p = {"w": jnp.arange(4.0), "b": 10.0 + jnp.arange(8.0).reshape(4, 2)}
    fire_on = {
        "w": jnp.ones(4, bool), "b": jnp.ones(4, bool)
    }
    fire_off = {
        "w": jnp.zeros(4, bool), "b": jnp.zeros(4, bool)
    }
    last = {"w": jnp.full(4, -7.0), "b": jnp.full((4, 2), -9.0)}

    def dropped(pp, ff, ll):
        bufs, _ = collectives.masked_neighbor_vals(
            pp, ff, (ll, ll), topo,
            deliver=jnp.zeros((2,), bool),  # sent, but the wire ate it
        )
        return bufs

    def unfired(pp, ff, ll):
        bufs, _ = collectives.masked_neighbor_vals(pp, ff, (ll, ll), topo)
        return bufs

    got_drop = spmd(dropped, topo)(p, fire_on, last)
    got_quiet = spmd(unfired, topo)(p, fire_off, last)
    assert _leaves_equal_bitwise(got_drop, got_quiet)
    # and both are exactly the stale buffers
    assert _leaves_equal_bitwise(got_drop, (last, last))


def test_partial_delivery_masks_per_edge():
    topo = Ring(4)
    p = jnp.array([1.0, 2.0, 3.0, 4.0])
    fire = jnp.ones(4, bool)
    last = jnp.full(4, -7.0)

    def fn(pp, ff, ll):
        bufs, fires = collectives.masked_neighbor_vals(
            pp, ff, (ll, ll), topo,
            deliver=jnp.array([False, True]),
        )
        return bufs, fires

    (left, right), (lf, rf) = spmd(fn, topo)(p, fire, last)
    np.testing.assert_allclose(left, [-7.0] * 4)  # dropped edge: stale
    np.testing.assert_allclose(right, [2.0, 3.0, 4.0, 1.0])  # delivered
    # recv_fires stay RAW (what was sent) so drops are observable
    np.testing.assert_array_equal(np.asarray(lf), [True] * 4)


def test_mix_weighted_all_alive_is_bitwise_mix():
    topo = Ring(4)
    key = jax.random.PRNGKey(0)
    params = {"w": jax.random.normal(key, (4, 3, 3))}
    bufs = tuple(
        {"w": jax.random.normal(jax.random.fold_in(key, i), (4, 3, 3))}
        for i in range(2)
    )

    def plain(pp, b0, b1):
        return collectives.mix(pp, (b0, b1), topo)

    def weighted(pp, b0, b1):
        return collectives.mix_weighted(
            pp, (b0, b1), jnp.ones((2,), bool)
        )

    a = spmd(plain, topo)(params, *bufs)
    b = spmd(weighted, topo)(params, *bufs)
    assert _leaves_equal_bitwise(a, b)


def test_mix_weighted_renormalizes_dead_edge():
    topo = Ring(4)
    p = jnp.array([0.0, 3.0, 6.0, 9.0])

    def fn(pp):
        bufs = collectives.neighbor_vals(pp, topo)
        return collectives.mix_weighted(
            pp, bufs, jnp.array([False, True])  # left edge frozen
        )

    out = spmd(fn, topo)(p)
    # (self + right)/2, the /3 weight renormalized over survivors
    np.testing.assert_allclose(out, [(0 + 3) / 2, (3 + 6) / 2,
                                     (6 + 9) / 2, (9 + 0) / 2])


# --- (c) ring heal -----------------------------------------------------


def test_ring_heal_matches_smaller_ring():
    topo = Ring(8)
    healed, survivors = heal_ring(topo, {2, 5})
    assert healed.n_ranks == 6 and survivors == (0, 1, 3, 4, 6, 7)
    assert healed.axes == topo.axes
    # healed neighbor_source IS Ring(6)'s; in old-rank terms each survivor
    # bridges to the cyclically-next survivor (6->7, 7->0, 1->3, 4->6)
    ref = Ring(6)
    for j in range(6):
        for k, nb in enumerate(healed.neighbors):
            assert healed.neighbor_source(j, nb) == ref.neighbor_source(
                j, ref.neighbors[k]
            )
        right_src = healed.neighbor_source(j, healed.neighbors[1])
        assert survivors[right_src] == survivors[(j + 1) % 6]
    with pytest.raises(ValueError):
        heal_ring(topo, set(range(7)))  # < 2 survivors
    with pytest.raises(ValueError):
        heal_ring(topo, {99})
    with pytest.raises(ValueError):
        heal_ring(Topology(axes=("x", "y"), shape=(2, 2)), {0})


def test_apply_ring_heal_slices_state_rows():
    topo = Ring(4)
    tx = optax.sgd(0.1)
    state = init_train_state(
        MLP(hidden=8), (8, 8, 1), tx, topo, "eventgrad", EventConfig()
    )
    state = state.replace(
        chaos=stack_for_ranks(monitor.PeerHealth.init(topo), topo)
    )
    # make rows distinguishable, and silence nonzero to check the reset
    state = state.replace(
        pass_num=jnp.arange(4, dtype=jnp.int32),
        chaos=state.chaos.replace(
            silence=jnp.full((4, 2), 9, jnp.int32)
        ),
    )
    healed, healed_topo, survivors = apply_ring_heal(state, topo, {1})
    assert survivors == (0, 2, 3)
    assert healed_topo.n_ranks == 3
    np.testing.assert_array_equal(np.asarray(healed.pass_num), [0, 2, 3])
    for a, b in zip(
        jax.tree.leaves(healed.params), jax.tree.leaves(state.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b)[[0, 2, 3]])
    assert not np.asarray(healed.chaos.silence).any(), "silence must reset"


# --- (d) forced sync bounds consensus error ----------------------------


_BLACKOUT = (5, 25)


def _drift_run(policy, passes=45):
    """A trigger that goes permanently quiet after warmup (the limit of
    the documented collapse mode, where an over-aggressive threshold
    silences every parameter indefinitely), a total-blackout flaky window,
    and decorrelated shards: with the stale buffers frozen out of the mix
    the ranks genuinely diverge, so recovery is observable. Returns the
    per-pass max consensus error (via the sweep tool's shared harness)."""
    cfg = EventConfig(adaptive=False, constant=1e9, warmup_passes=2,
                      max_silence=0)
    sched = ChaosSchedule(seed=0, flaky=(FlakyWindow(*_BLACKOUT, 1.0),))
    _, _, errs, _ = chaos_sweep._manual_leg(
        sched, policy, passes, seed=0, event_cfg=cfg,
        hidden=8, lr=0.2, data_seed=6, batch=8,
    )
    return errs


def test_forced_sync_bound_restores_consensus():
    """Twin runs differing ONLY in the sync bound: freeze-only never
    recovers (the silent trigger means no edge ever speaks again after
    the blackout — silence keeps every edge frozen and ranks run pure
    local SGD), while the receiver-side sync bound forces fresh full
    syncs as soon as the wire heals, pulling consensus error back down."""
    w_end = _BLACKOUT[1]
    freeze_only = _drift_run(RecoveryPolicy(freeze_after=8))
    with_sync = _drift_run(RecoveryPolicy(sync_after=6, freeze_after=8))
    # deterministic twins through the blackout...
    np.testing.assert_allclose(
        freeze_only[: w_end - 2], with_sync[: w_end - 2]
    )
    peak = with_sync[:w_end + 2].max()
    assert peak > 2.0 * with_sync[2], "blackout must cause real drift"
    # ...then forced sync restores consensus below the divergence peak
    assert with_sync[w_end:w_end + 10].min() < 0.5 * peak
    # while the syncless twin keeps drifting apart
    assert freeze_only[-1] > peak
    assert freeze_only[-1] > 1.1 * freeze_only[w_end]
    assert with_sync[-1] < 0.6 * freeze_only[-1], (
        with_sync[-1], freeze_only[-1]
    )


def test_policy_validation():
    with pytest.raises(ValueError, match="max_silence"):
        RecoveryPolicy(sync_after=3).validate_against(5)
    RecoveryPolicy(sync_after=6).validate_against(5)
    with pytest.raises(ValueError):
        RecoveryPolicy(sync_after=-1)
    with pytest.raises(ValueError, match="chaos_policy requires chaos"):
        make_train_step(
            MLP(hidden=8), optax.sgd(0.1), Ring(4), "eventgrad",
            chaos_policy=RecoveryPolicy(sync_after=6),
        )
    with pytest.raises(ValueError, match="gossip"):
        make_train_step(
            MLP(hidden=8), optax.sgd(0.1), Ring(4), "allreduce",
            chaos=ChaosSchedule(),
        )
    with pytest.raises(ValueError, match="force_fire"):
        make_train_step(
            MLP(hidden=8), optax.sgd(0.1), Ring(4), "dpsgd",
            chaos=ChaosSchedule(),
            chaos_policy=RecoveryPolicy(sync_after=6),
        )


def test_force_fire_overrides_threshold():
    topo = Ring(2)
    params = {"w": jnp.ones((3,))}
    from eventgrad_tpu.parallel.events import EventState

    cfg = EventConfig(adaptive=False, constant=1e9, warmup_passes=0)
    state = EventState.init(params, topo, cfg)
    fire, _ = decide_and_update(
        params, state, jnp.int32(5), cfg, 2
    )
    assert not bool(jax.tree.leaves(fire)[0])  # huge threshold: quiet
    fire_f, st_f = decide_and_update(
        params, state, jnp.int32(5), cfg, 2, force_fire=jnp.bool_(True)
    )
    assert bool(jax.tree.leaves(fire_f)[0])
    assert int(st_f.num_events) > 0  # forced sends are accounted


# --- drop-rate-0 regression guard (acceptance criterion) ---------------


def test_drop0_bitwise_identical_to_unmodified_loop():
    topo = Ring(4)
    x, y = synthetic_dataset(512, (8, 8, 1), seed=1)
    cfg = EventConfig(adaptive=True, horizon=0.95, warmup_passes=3,
                      max_silence=5)
    kw = dict(algo="eventgrad", epochs=2, batch_size=16,
              learning_rate=0.1, event_cfg=cfg)
    st_plain, _ = train(MLP(hidden=16), topo, x, y, **kw)
    st_chaos, hist = train(
        MLP(hidden=16), topo, x, y,
        chaos=ChaosSchedule(seed=3, drop_p=0.0),
        chaos_policy=RecoveryPolicy(sync_after=12, freeze_after=24),
        **kw,
    )
    assert _leaves_equal_bitwise(st_plain.params, st_chaos.params)
    assert hist[0]["chaos"]["drop_p"] == 0.0  # schedule rides the record
    assert hist[-1]["chaos_drops"] == 0


def test_sweep_artifact_structure(tmp_path):
    out = chaos_sweep.run_sweep(
        drops=(0.0, 0.3, 0.7), epochs=2, seed=0,
        out_path=str(tmp_path / "sweep.json"), legs=("drop",),
    )
    assert len(out["points"]) >= 3
    assert out["points"][0]["bitwise_identical_to_baseline"] is True
    for pt in out["points"]:
        assert {"drop_p", "test_acc", "schedule", "edge_silence_max",
                "chaos_drops", "consensus_err_max"} <= set(pt)
    assert (tmp_path / "sweep.json").exists()
