"""EventGraD step overhead at the FLAGSHIP op-point, on chip (round-5
verdict item 2: eventgrad must be <= 1.0x dpsgd step time, or the trigger
machinery is costing wall time instead of buying it).

Times the steady-state step of the flagship ResNet op-point (8-rank vmap
ring, global batch 256, bf16 compute — the same config bench.py's full
tier and tools/tpu_flagship.py measure) for a variant matrix:

  dpsgd                  the dense baseline
  eventgrad              the bench trigger (synchronous exchange)
  eventgrad_stale        staleness=1 — mixes with the PREVIOUS step's
                         buffers, the deterministic model of the
                         reference's RMA asynchrony (event.cpp:348-360 vs
                         :399-438); frees XLA to overlap the exchange
  eventgrad_bf16         wire="bf16" — half-width exchange payloads
  eventgrad_stale_bf16   both
  spevent                sparsified top-k 10% (E5) — the top_k+scatter
                         path's chip cost (round-4 verdict missing #2)

Each variant runs a short multi-epoch train() with the round-5 dispatch
modes (device-resident data, K-epoch blocks); step_ms comes from the warm
(non-cold) dispatch blocks only, so compiles never contaminate it.

Writes artifacts/flagship_overhead_r5_<platform>.json.
Usage: python tools/flagship_overhead.py [epochs_per_variant]
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

from eventgrad_tpu.utils import compile_cache  # noqa: E402

compile_cache.honor_cpu_pin()
compile_cache.enable()

import numpy as np  # noqa: E402


def main() -> None:
    import jax.numpy as jnp

    from eventgrad_tpu.data.datasets import load_or_synthesize
    from eventgrad_tpu.models import ResNet18
    from eventgrad_tpu.parallel.events import (
        EventConfig, resolve_bench_trigger,
    )
    from eventgrad_tpu.parallel.sparsify import SparseConfig
    from eventgrad_tpu.parallel.topology import Ring
    from eventgrad_tpu.train.loop import train
    from eventgrad_tpu.utils.metrics import steady_records

    epochs = int(sys.argv[1]) if len(sys.argv) > 1 else 6
    topo = Ring(8)
    global_batch, n_train = 256, 16384
    if os.environ.get("EG_OVERHEAD_SMOKE") == "1":
        # script-path validation off-chip (never a measurement)
        from eventgrad_tpu.models import LeNetCifar

        model_fn = LeNetCifar
        global_batch, n_train = 64, 512
    else:
        model_fn = lambda: ResNet18(dtype=jnp.bfloat16)  # noqa: E731
    per_rank = global_batch // topo.n_ranks
    horizon, max_silence = resolve_bench_trigger(os.environ)
    cfg = EventConfig(adaptive=True, horizon=horizon, warmup_passes=30,
                      max_silence=max_silence)
    x, y = load_or_synthesize("cifar10", None, "train", n_synth=n_train)
    common = dict(
        epochs=epochs, batch_size=per_rank, learning_rate=1e-2,
        momentum=0.9, random_sampler=True, log_every_epoch=False,
        epochs_per_dispatch=8,
    )

    variants = [
        ("dpsgd", dict(algo="dpsgd")),
        ("eventgrad", dict(algo="eventgrad", event_cfg=cfg)),
        ("eventgrad_stale", dict(algo="eventgrad", event_cfg=cfg,
                                 staleness=1)),
        ("eventgrad_bf16", dict(algo="eventgrad", event_cfg=cfg,
                                wire="bf16")),
        ("eventgrad_stale_bf16", dict(algo="eventgrad", event_cfg=cfg,
                                      staleness=1, wire="bf16")),
        ("spevent", dict(algo="sp_eventgrad", event_cfg=cfg,
                         sparse_cfg=SparseConfig(10.0))),
    ]

    d = jax.devices()[0]
    out = {
        "op_point": {
            "model": type(model_fn()).__name__, "topology": "ring8",
            "global_batch": global_batch, "n_train": n_train,
            "epochs_per_variant": epochs,
            "trigger": {"horizon": horizon, "max_silence": max_silence,
                        "warmup": 30},
        },
        "platform": d.platform,
        "device_kind": d.device_kind,
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "variants": {},
    }
    path = os.path.join(
        REPO, "artifacts", f"flagship_overhead_r5_{d.platform}.json"
    )
    for name, kw in variants:
        t0 = time.perf_counter()
        _, hist = train(model_fn(), topo, x, y, **common, **kw)
        wall = time.perf_counter() - t0
        steady = steady_records(hist)
        rec = {
            "step_ms": round(1000 * float(np.mean(
                [h["wall_s"] / h["steps"] for h in steady]
            )), 3),
            "wall_s": round(wall, 1),
            "final_loss": round(hist[-1]["loss"], 4),
        }
        if "msgs_saved_pct" in hist[-1]:
            rec["msgs_saved_pct"] = round(hist[-1]["msgs_saved_pct"], 2)
        out["variants"][name] = rec
        print(json.dumps({name: rec}), flush=True)
        # publish incrementally: a tunnel wedge mid-matrix keeps the
        # completed variants
        base = out["variants"].get("dpsgd", {}).get("step_ms")
        for vn, vr in out["variants"].items():
            if base:
                vr["vs_dpsgd"] = round(vr["step_ms"] / base, 4)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(out, f, indent=1)
            f.write("\n")
        os.replace(tmp, path)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
