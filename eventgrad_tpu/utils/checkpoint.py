"""Checkpoint/resume — absent from the reference (no torch::save anywhere;
the consensus model is evaluated then dropped, event.cpp:517-586). Cheap win
on TPU: orbax snapshots of the full stacked TrainState (params, optimizer
moments, event thresholds/slopes/buffers, sparsifier replicas, PRNG keys),
so an interrupted decentralized run resumes with its exact gossip state.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import orbax.checkpoint as ocp


def save(path: str, state: Any) -> None:
    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, state, force=True)


def restore(path: str, template: Any) -> Any:
    """Restore into the structure of `template` (an abstract or concrete
    TrainState with the same shapes/dtypes)."""
    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        target = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
        return ckptr.restore(path, item=target)
