"""Structured metrics — the reference's flat-file logs, upgraded to JSONL.

The reference writes per-rank `send{r}.txt`/`recv{r}.txt`/`train{r}.txt`
plus stdout accuracy (/root/reference/dmnist/event/event.cpp:232-252,
337-339, 385-391; dcifar10/event/event.cpp:271-273). Here every record is a
JSON line with the BASELINE metrics first-class: msgs-saved-%,
grad-sync bytes/step/chip, test-acc vs epoch. The obs.Registry wraps
this stream behind the versioned telemetry schema (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Any, Dict, List, Optional


def median(vals) -> float:
    """Plain middle-of-sorted median (even length: mean of the two
    middles) — ONE definition for the ablation tools' paired-ratio
    protocol (overhead_ablation / integrity_sweep / mesh_ablation),
    which previously each carried their own copy."""
    s = sorted(vals)
    mid = len(s) // 2
    return s[mid] if len(s) % 2 else 0.5 * (s[mid - 1] + s[mid])


def _scrub_nonfinite(obj: Any, path: str, bad: List[str]) -> Any:
    """Copy `obj` with NaN/Inf number leaves replaced by None, recording
    each replaced leaf's dotted path in `bad`. Python and numpy scalars
    both; containers recurse; everything else passes through untouched
    (json's `default=` hook still sees it)."""
    if isinstance(obj, dict):
        return {
            k: _scrub_nonfinite(v, f"{path}.{k}" if path else str(k), bad)
            for k, v in obj.items()
        }
    if isinstance(obj, (list, tuple)):
        return [
            _scrub_nonfinite(v, f"{path}[{i}]", bad)
            for i, v in enumerate(obj)
        ]
    v = obj
    if not isinstance(v, (bool, int, float, str, type(None))):
        try:
            v = float(v)  # numpy floating scalars and friends
        except (TypeError, ValueError):
            return obj
    if isinstance(v, float) and not math.isfinite(v):
        bad.append(path)
        return None
    return obj


class JsonlLogger:
    """Append-only JSONL sink; every record is timestamped and flushed.

    Context-manager friendly (`with JsonlLogger(path) as log:`) so the
    stream closes on exception paths too. `fsync=True` additionally
    fsyncs after every record — crash-safe artifacts at the cost of one
    syscall per line (records are per-epoch, so the cost is noise)."""

    def __init__(
        self, path: Optional[str] = None, echo: bool = True,
        fsync: bool = False,
    ):
        self.path = path
        self.echo = echo
        self.fsync = fsync
        self._fh = open(path, "a") if path else None

    def log(self, record: Dict[str, Any]) -> None:
        record = {"ts": round(time.time(), 3), **record}
        try:
            line = json.dumps(record, default=float, allow_nan=False)
        except ValueError:
            # a NaN/Inf metric (a diverging loss — exactly the record an
            # operator most needs) must neither crash the run mid-stream
            # nor emit the bare `NaN` token json.loads rejects: serialize
            # the offenders as null and name them in a rider, so the
            # line stays valid JSON and the divergence stays visible
            bad: List[str] = []
            record = _scrub_nonfinite(record, "", bad)
            record["nonfinite_fields"] = bad
            line = json.dumps(record, default=float, allow_nan=False)
        if self._fh:
            self._fh.write(line + "\n")
            self._fh.flush()
            if self.fsync:
                os.fsync(self._fh.fileno())
        if self.echo:
            print(line)

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None  # idempotent: with-block + explicit close

    def __enter__(self) -> "JsonlLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def msgs_saved_pct(num_events: int, passes: int, n_tensors: int, n_neighbors: int, n_ranks: int) -> float:
    """1 - events/possible, the reference's headline metric
    (events counted per neighbor per tensor per pass, event.cpp:344,527-532)."""
    possible = n_neighbors * passes * n_tensors * n_ranks
    return 100.0 * (1.0 - num_events / possible) if possible else 0.0


def msgs_saved_pct_per_leaf(
    fire_counts, passes: int, n_neighbors: int, n_ranks: int,
) -> list:
    """Per-leaf msgs-saved-%: `fire_counts` is per-leaf EFFECTIVE fire
    counts summed over ranks (obs telemetry `fire_count`); each fire is
    `n_neighbors` messages, out of `n_neighbors * passes * n_ranks`
    possible per leaf — so the neighbor factor cancels and the mean over
    leaves equals the aggregate `msgs_saved_pct` exactly (the oracle
    cross-check in tests/test_obs.py). Division-guarded like the
    aggregate: zero possible messages reports 0.0 saved."""
    possible = passes * n_ranks
    if not possible or not n_neighbors:
        return [0.0 for _ in fire_counts]
    return [100.0 * (1.0 - float(f) / possible) for f in fire_counts]


def steady_records(history) -> list:
    """The steady-state slice of a train() history: every record outside a
    COLD jit-dispatch block (a block that paid a trace+compile — block 0,
    plus the first block of any other size, e.g. the tail remainder when
    epochs % K != 0). With K-epoch blocks (loop.py epochs_per_dispatch)
    dropping only epoch 1 would smear 1/K of the compile into the
    'steady' mean — the cold-block tag is the honest cut.

    When EVERY block was cold (e.g. 2-3 distinct block sizes over few
    epochs) no honest steady slice exists: fall back to dropping the
    first record unconditionally (the legacy hist[1:] rule — it sheds the
    worst of the compile even in legacy/resumed histories without
    dispatch_cold tags), and to the full history only when that leaves
    nothing. Every fallback record is a COPY carrying
    `steady_contaminated: True` so benches report compile contamination
    instead of silently absorbing it (ADVICE r5 #2)."""
    out = [
        h for h in history
        if not h.get("dispatch_cold", h.get("dispatch_block", h["epoch"] - 1) == 0)
    ]
    if out:
        return out
    fallback = list(history)[1:] or list(history)
    return [dict(h, steady_contaminated=True) for h in fallback]


def collapse_verdict(
    losses,
    twin_loss: Optional[float] = None,
    *,
    factor: float = 2.0,
    abs_floor: float = 0.5,
    bounce: float = 1.25,
    random_loss: float = 2.35,
) -> bool:
    """True when an event-triggered run has DIVERGED rather than trained —
    the guard that keeps a collapsed run from ever presenting as a
    messages-saved win (an aggressive horizon can trade accuracy for
    silence: the measured cliff is horizon 1.05 + max-silence 50 at 360
    passes -> 81.66% "saved" at 36.5% test accuracy,
    artifacts/mnist_knee_r3_cpu.jsonl).

    `losses` is the per-epoch train-loss history (a scalar is accepted
    as a 1-entry history). Collapse is distinct from UNDERtraining: a
    short smoke tier legitimately ends with high loss while still
    descending, and must not be flagged. Three signals, any of which
    flags:

    - twin divergence: final loss > `factor`x the dense D-PSGD twin's
      AND above `abs_floor` (the floor keeps both-converged pairs like
      0.06-vs-0.02 from false-flagging; an undertrained pair shares its
      high loss with its twin, so the ratio stays ~1)
    - bounce: final loss > `bounce`x the history's minimum AND above
      `abs_floor` — the cliff's signature (the run trains through
      warmup, then climbs once the trigger silences the exchange); a
      monotone still-descending run has min ~= final
    - never trained: final loss at or above `random_loss` (10-class
      random guessing is ln 10 ~= 2.303), or non-finite (NaN/inf — the
      hardest divergence mode must not slip through NaN's
      compare-False semantics).

    When a twin exists and the run TRACKS it (final within `factor`x),
    the bounce signal is vetoed: a late-epoch noise bounce that the
    dense twin shares is SGD noise, not collapse."""
    import math

    if hasattr(losses, "__iter__"):
        hist = [float(x) for x in losses]  # list, array, or generator
        if not hist:
            raise ValueError("collapse_verdict: empty loss history")
    else:
        hist = [float(losses)]
    final = hist[-1]
    if not math.isfinite(final) or final >= random_loss:
        return True
    if twin_loss is not None:
        return final > max(factor * float(twin_loss), abs_floor)
    return final > max(bounce * min(hist), abs_floor)
