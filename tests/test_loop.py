"""End-to-end epoch driver: convergence + savings on the emulated mesh."""

import numpy as np

from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP, CNN2
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.topology import Ring, Torus
from eventgrad_tpu.train.loop import consensus_params, evaluate, train


def test_mlp_eventgrad_end_to_end():
    topo = Ring(4)
    x, y = synthetic_dataset(2048, (8, 8, 1), seed=1)
    xt, yt = synthetic_dataset(256, (8, 8, 1), seed=1, split="test")
    state, hist = train(
        MLP(hidden=32),
        topo,
        x,
        y,
        algo="eventgrad",
        epochs=10,
        batch_size=16,
        learning_rate=0.1,
        event_cfg=EventConfig(adaptive=True, horizon=0.95, warmup_passes=5),
        x_test=xt,
        y_test=yt,
    )
    assert hist[-1]["loss"] < hist[0]["loss"]
    assert 0.0 < hist[-1]["msgs_saved_pct"] < 100.0
    assert hist[-1]["test_accuracy"] > 50.0  # prototype task: well above chance


def test_torus_dpsgd_runs():
    topo = Torus(4, 2)
    x, y = synthetic_dataset(512, (28, 28, 1), seed=2)
    state, hist = train(
        MLP(hidden=16), topo, x, y, algo="dpsgd", epochs=1, batch_size=8
    )
    assert np.isfinite(hist[0]["loss"])


def test_cnn2_with_dropout_trains():
    topo = Ring(4)
    x, y = synthetic_dataset(256, (28, 28, 1), seed=4)
    state, hist = train(
        CNN2(), topo, x, y, algo="dpsgd", epochs=2, batch_size=8, learning_rate=0.05
    )
    assert hist[-1]["loss"] < hist[0]["loss"]
