"""Epoch driver: scan-compiled training, consensus, and evaluation.

Mirrors the reference's shared skeleton (epoch loop -> batch loop -> comm ->
step -> accuracy, e.g. /root/reference/dmnist/event/event.cpp:269-500) but
compiles the *entire epoch* as one `lax.scan` over steps, so the TPU runs
back-to-back fused steps with no host round-trips; per-epoch metrics come
back as stacked arrays.

End-of-training consensus: the reference allreduce-averages parameters and
lets rank 0 evaluate (event.cpp:517-525). Here `consensus_params` means over
the stacked rank axis — numerically the same reduction.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from eventgrad_tpu.data.sharding import batched_epoch
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.sparsify import SparseConfig
from eventgrad_tpu.parallel.spmd import spmd
from eventgrad_tpu.parallel.topology import Topology
from eventgrad_tpu.train.state import init_train_state
from eventgrad_tpu.train.steps import make_train_step
from eventgrad_tpu.utils import trees
from eventgrad_tpu.utils.metrics import msgs_saved_pct


def consensus_params(stacked_params: Any) -> Any:
    """Average the per-rank models into the final consensus model."""
    return jax.tree.map(lambda x: jnp.mean(x, axis=0), stacked_params)


def evaluate(model, params, batch_stats, x, y, batch_size: int = 1000) -> Dict[str, float]:
    """Rank-0-style test pass (event.cpp:535-586) on a single device."""
    variables = {"params": params}
    if batch_stats is not None and jax.tree.leaves(batch_stats):
        variables["batch_stats"] = batch_stats

    @jax.jit
    def fwd(xb):
        return model.apply(variables, xb, train=False)

    n = (len(x) // batch_size) * batch_size or len(x)
    correct, total, loss_sum = 0, 0, 0.0
    for i in range(0, n, batch_size):
        xb = jnp.asarray(x[i : i + batch_size])
        yb = np.asarray(y[i : i + batch_size])
        out = np.asarray(fwd(xb))
        logp = out - np.log(np.sum(np.exp(out - out.max(-1, keepdims=True)), -1, keepdims=True)) - out.max(-1, keepdims=True)
        loss_sum += float(-logp[np.arange(len(yb)), yb].sum())
        correct += int((out.argmax(-1) == yb).sum())
        total += len(yb)
    return {"accuracy": 100.0 * correct / total, "loss": loss_sum / total}


def train(
    model,
    topo: Topology,
    x_train: np.ndarray,
    y_train: np.ndarray,
    *,
    algo: str = "dpsgd",
    epochs: int = 2,
    batch_size: int = 64,
    learning_rate: float = 0.05,
    momentum: float = 0.0,
    event_cfg: Optional[EventConfig] = None,
    sparse_cfg: Optional[SparseConfig] = None,
    augment: bool = False,
    random_sampler: bool = False,
    sync_bn: bool = False,
    mesh=None,
    seed: int = 0,
    x_test: Optional[np.ndarray] = None,
    y_test: Optional[np.ndarray] = None,
    log_every_epoch: bool = True,
) -> Tuple[Any, List[Dict[str, Any]]]:
    """Run the full training job; returns (final_state, per-epoch history)."""
    tx = optax.sgd(learning_rate, momentum=momentum if momentum else None)
    state = init_train_state(
        model, x_train.shape[1:], tx, topo, algo, event_cfg, seed=seed
    )
    step = make_train_step(
        model, tx, topo, algo,
        event_cfg=event_cfg, sparse_cfg=sparse_cfg, augment=augment,
        sync_bn=sync_bn,
    )
    lifted = spmd(step, topo, mesh=mesh)

    @jax.jit
    def run_epoch(st, xb, yb):
        def body(s, batch):
            return lifted(s, batch)

        # [n_ranks, steps, ...] -> scan over steps
        xs = (jnp.swapaxes(xb, 0, 1), jnp.swapaxes(yb, 0, 1))
        return jax.lax.scan(body, st, xs)

    n_params = trees.tree_count_params(
        jax.tree.map(lambda p: p[0], state.params)
    )
    sz = trees.tree_num_leaves(state.params)
    history: List[Dict[str, Any]] = []

    for epoch in range(1, epochs + 1):
        xb, yb = batched_epoch(
            x_train, y_train, topo.n_ranks, batch_size,
            random=random_sampler, seed=seed, epoch=epoch,
        )
        steps = xb.shape[1]
        t0 = time.perf_counter()
        state, m = run_epoch(state, jnp.asarray(xb), jnp.asarray(yb))
        jax.block_until_ready(state.params)
        dt = time.perf_counter() - t0

        # metrics are [steps, n_ranks]
        m = jax.tree.map(np.asarray, m)
        total_passes = int(state.pass_num.reshape(-1)[0])
        rec = {
            "epoch": epoch,
            "algo": algo,
            "steps": steps,
            "wall_s": dt,
            "loss": float(m["loss"].mean()),
            "train_acc": 100.0 * float(m["correct"].sum()) / (topo.n_ranks * steps * batch_size),
            "sent_bytes_per_step_per_chip": float(m["sent_bytes"][..., 0].mean()),
            "n_params": n_params,
        }
        if algo in ("eventgrad", "sp_eventgrad"):
            # msgs-saved vs D-PSGD: events/(n_neighbors * passes * sz) fired
            events_total = int(m["num_events"][-1].sum())
            rec["num_events"] = events_total
            rec["msgs_saved_pct"] = msgs_saved_pct(
                events_total, total_passes, sz, topo.n_neighbors, topo.n_ranks
            )
            rec["fired_frac"] = float(m["fired_frac"].mean())
        if x_test is not None and log_every_epoch:
            cons = consensus_params(state.params)
            stats0 = jax.tree.map(lambda s: s[0], state.batch_stats)
            rec.update(
                {"test_" + k: v for k, v in evaluate(model, cons, stats0, x_test, y_test).items()}
            )
        history.append(rec)

    return state, history
