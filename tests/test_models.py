"""Model parity: parameter/tensor counts match the reference exactly.

Reference counts: MLP 101,770 params / 4 tensors (cent.cpp:16-35); CNN-2
27,480 / 8 tensors (event.cpp printout :162-165); ResNet-as-coded ~17.4M /
86 named tensors from the 3-blocks-per-stage make_layer quirk
(resnet.hpp:172-178, SURVEY §2.2 M4).
"""

import jax
import jax.numpy as jnp

from eventgrad_tpu.models import MLP, CNN1, CNN2, LeNetCifar, ResNet18
from eventgrad_tpu.utils import trees


def _init(model, shape):
    return model.init(jax.random.PRNGKey(0), jnp.zeros((1,) + shape))


def test_mlp_matches_reference():
    variables = _init(MLP(), (28, 28, 1))
    assert trees.tree_count_params(variables["params"]) == 101_770
    assert trees.tree_num_leaves(variables["params"]) == 4


def test_cnn2_matches_reference():
    variables = _init(CNN2(), (28, 28, 1))
    assert trees.tree_count_params(variables["params"]) == 27_480
    assert trees.tree_num_leaves(variables["params"]) == 8


def test_cnn1_matches_reference():
    variables = _init(CNN1(), (28, 28, 1))
    assert trees.tree_count_params(variables["params"]) == 38_390


def test_lenet_cifar_matches_reference():
    variables = _init(LeNetCifar(), (32, 32, 3))
    assert trees.tree_count_params(variables["params"]) == 62_006


def test_resnet18_faithful_has_3_blocks_per_stage():
    model = ResNet18()
    variables = _init(model, (32, 32, 3))
    n_tensors = trees.tree_num_leaves(variables["params"])
    n_params = trees.tree_count_params(variables["params"])
    assert n_tensors == 86, f"expected the reference's 86 named tensors, got {n_tensors}"
    assert 17_000_000 < n_params < 18_000_000, n_params


def test_resnet18_canonical_block_count():
    model = ResNet18(extra_block=False)
    variables = _init(model, (32, 32, 3))
    # canonical ResNet-18 for CIFAR: ~11.2M params
    n = trees.tree_count_params(variables["params"])
    assert 11_000_000 < n < 11_400_000, n


def test_forward_shapes_and_logprobs():
    x = jnp.zeros((2, 28, 28, 1))
    for model in (MLP(), CNN1(), CNN2()):
        variables = model.init(jax.random.PRNGKey(0), x)
        out = model.apply(variables, x, train=False)
        assert out.shape == (2, 10)

    xc = jnp.zeros((2, 32, 32, 3))
    model = ResNet18()
    variables = model.init(jax.random.PRNGKey(0), xc)
    out = model.apply(variables, xc, train=False)
    assert out.shape == (2, 10)
    assert "batch_stats" in variables  # BN buffers exist and stay rank-local


def test_cnn2_log_softmax_output():
    x = jnp.ones((3, 28, 28, 1))
    model = CNN2()
    variables = model.init(jax.random.PRNGKey(0), x)
    out = model.apply(variables, x, train=False)
    # outputs are log-probabilities: logsumexp == 0
    assert jnp.allclose(jax.nn.logsumexp(out, axis=-1), 0.0, atol=1e-5)
